"""Device-side x86-64 transition function: one instruction, one lane, vmapped.

This is the TPU-native replacement for the reference's emulator hot loop
(bochscpu's fetch-decode-execute + hook chain, reference
src/wtf/bochscpu_backend.cc:352-548): instead of one guest stepping through
branchy C++ per instruction, every lane of the batch advances one
*pre-decoded* uop per call, fully vectorized, with lane masking for
divergence.  The host decodes (cpu/decoder.py), publishes uops to the device
table (interp/uoptable.py), and this module consumes them.

Structure of `step_lane` (single lane; `jax.vmap` adds the lane axis):
  1. hash-probe the uop table with rip          -> NEED_DECODE on miss
  2. breakpoint check (honoring bp_skip)        -> BREAKPOINT (pre-execution,
     like the reference's BeforeExecutionHook dispatch, bochscpu:545-547)
  3. self-modifying-code check: current code bytes (through the lane's dirty
     overlay) vs the decode-time raw bytes      -> SMC
  4. effective address, at most two generic loads (src-like / dst-like),
     ALU/flag select over op classes mirroring cpu/emu.py semantics exactly,
     one store, register writebacks
  5. rip / rflags / status / icount update; coverage bit (per uop-table
     entry) + edge-hash bit (reference RecordEdge, bochscpu:699-728) set in
     the per-lane bitmaps

Anything the device path does not implement surfaces as per-lane UNSUPPORTED
and is single-stepped on the host by the EmuCpu oracle (interp/runner.py) —
the same "precise slow path backs a fast path" split the reference gets from
bochscpu vs KVM, collapsed into one machine.

Representation: the hot machine state is u32 limb pairs (interp/limbs.py;
TPU has no native 64-bit integers, and the future Pallas kernel cannot hold
them at all).  The ported paths — decode-cache hash probe, integer ALU and
unary ops, flag images, effective addressing, condition evaluation, and the
fallthrough/Jcc rip updates — run entirely on u32 limbs (`alu_limb`,
`unary_limb`, `ea_limb` below are compiled standalone by tests/test_limbs.py
to pin the absence of 64-bit ops).  Cold classes (shifts, mul/div, strings,
SSE/x87, syscalls, the memory/paging subsystem) read u64 bitcast views and
convert back at the pack_u64/unpack_u64 seam, which XLA lowers for free.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

import numpy as np

from wtf_tpu.core.results import StatusCode
from wtf_tpu.cpu import uops as U
from wtf_tpu.cpu.emu import MSR_ATTR
from wtf_tpu.cpu.cpuid import CPUID_TABLE, MAX_BASIC_LEAF
from wtf_tpu.interp import limbs as L
from wtf_tpu.interp.machine import Machine
from wtf_tpu.interp.uoptable import (
    F_A32,
    F_BASE_REG, F_COND, F_DST_KIND, F_DST_REG, F_IDX_REG, F_LENGTH, F_LOCK,
    F_OPC, F_OPSIZE, F_REP, F_SCALE, F_SEG, F_SEXT, F_SRCSIZE, F_SRC_KIND,
    F_SRC_REG, F_SUB, M_BP, M_PFN0, M_PFN1, MU_DISP, MU_IMM, MU_RAW_HI,
    MU_RAW_LO, PROBES, UopTable,
)
from wtf_tpu.mem.overlay import (
    extract_pair, load_windows3_vec, store_window3,
)
from wtf_tpu.mem.paging import Translation, translate_vec_l
from wtf_tpu.mem.physmem import IMAGE_IN_AXES, MemImage, lane_image

MASK64 = (1 << 64) - 1

# rflags bits
_CF, _PF, _AF, _ZF, _SF, _OF = 0x1, 0x4, 0x10, 0x40, 0x80, 0x800
_TF, _IF, _DF = 0x100, 0x200, 0x400
FLAGS_ARITH = _CF | _PF | _AF | _ZF | _SF | _OF  # 0x8D5


def _u(x: int) -> jnp.ndarray:
    return jnp.uint64(x & MASK64)


# Device copy of the oracle's CPUID model (cpu/cpuid.py): plain numpy at
# module scope (must not touch the jax backend at import time); becomes a
# compile-time constant of the traced step.
_CPUID_KEYS = np.array([[l, s] for (l, s) in CPUID_TABLE], dtype=np.uint32)
_CPUID_VALS = np.array([CPUID_TABLE[k] for k in CPUID_TABLE], dtype=np.uint32)
_CPUID_BASIC_ROW = list(CPUID_TABLE).index((MAX_BASIC_LEAF, 0))


def _mix64(z):
    """splitmix64 mixing steps only — bit-for-bit the reference's RecordEdge
    RIP hash (bochscpu_backend.cc:705-715); must match utils.hashing.mix64."""
    z = (z ^ (z >> _u(30))) * _u(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> _u(27))) * _u(0x94D049BB133111EB)
    return z ^ (z >> _u(31))


def _splitmix64(x):
    return _mix64(x + _u(0x9E3779B97F4A7C15))


def _size_mask(nbytes):
    """nbytes (int32 scalar) -> u64 value mask; >=8 bytes = full mask."""
    sh = (jnp.minimum(nbytes, 8).astype(jnp.uint64)) * _u(8)
    partial = (_u(1) << jnp.minimum(sh, _u(63))) - _u(1)
    return jnp.where(sh >= _u(64), _u(MASK64), partial)


def _shl(x, s):
    """x << s with s (u64) >= 64 yielding 0 (XLA leaves it undefined)."""
    return jnp.where(s >= _u(64), _u(0), x << jnp.minimum(s, _u(63)))


def _shr(x, s):
    return jnp.where(s >= _u(64), _u(0), x >> jnp.minimum(s, _u(63)))


def _sext(val, nbytes):
    """Sign-extend the low nbytes of val to 64 bits."""
    sh = ((8 - jnp.minimum(nbytes, 8)).astype(jnp.uint64)) * _u(8)
    widened = (val << sh).astype(jnp.int64) >> sh.astype(jnp.int64)
    return widened.astype(jnp.uint64)


def _canon(gva):
    """Canonical 48-bit address predicate (bits 63:47 all equal)."""
    top = gva >> _u(47)
    return (top == _u(0)) | (top == _u(0x1FFFF))


def _parity_even(r):
    v = r & _u(0xFF)
    v = v ^ (v >> _u(4))
    v = v ^ (v >> _u(2))
    v = v ^ (v >> _u(1))
    return (v & _u(1)) == _u(0)


def _popcnt(x):
    x = x - ((x >> _u(1)) & _u(0x5555555555555555))
    x = (x & _u(0x3333333333333333)) + ((x >> _u(2)) & _u(0x3333333333333333))
    x = (x + (x >> _u(4))) & _u(0x0F0F0F0F0F0F0F0F)
    return (x * _u(0x0101010101010101)) >> _u(56)


def _bitlen(x):
    """Position of highest set bit + 1 (0 for x == 0)."""
    x = x | (x >> _u(1))
    x = x | (x >> _u(2))
    x = x | (x >> _u(4))
    x = x | (x >> _u(8))
    x = x | (x >> _u(16))
    x = x | (x >> _u(32))
    return _popcnt(x)


def _mkflags(cf, pf, af, zf, sf, of):
    def bit(c, v):
        return jnp.where(c, _u(v), _u(0))

    return (bit(cf, _CF) | bit(pf, _PF) | bit(af, _AF) | bit(zf, _ZF)
            | bit(sf, _SF) | bit(of, _OF))


def _msb(r, opsize):
    return (r >> ((opsize.astype(jnp.uint64) * _u(8)) - _u(1))) & _u(1)


def _flags_add(a, b, r, opsize, carry):
    m = _size_mask(opsize)
    am, bm, rm = a & m, b & m, r & m
    c = jnp.where(carry, _u(1), _u(0))
    cf = jnp.where(opsize >= 8,
                   (rm < am) | ((c == _u(1)) & (rm == am)),
                   (am + bm + c) > m)
    return _mkflags(
        cf=cf,
        pf=_parity_even(rm),
        af=((a ^ b ^ r) & _u(0x10)) != _u(0),
        zf=rm == _u(0),
        sf=_msb(rm, opsize) != _u(0),
        of=(((a ^ r) & (b ^ r)) >> ((opsize.astype(jnp.uint64) * _u(8)) - _u(1))) & _u(1) != _u(0),
    )


def _flags_sub(a, b, r, opsize, borrow):
    m = _size_mask(opsize)
    am, bm, rm = a & m, b & m, r & m
    cf = jnp.where(borrow, am <= bm, am < bm)
    return _mkflags(
        cf=cf,
        pf=_parity_even(rm),
        af=((a ^ b ^ r) & _u(0x10)) != _u(0),
        zf=rm == _u(0),
        sf=_msb(rm, opsize) != _u(0),
        of=(((a ^ b) & (a ^ r)) >> ((opsize.astype(jnp.uint64) * _u(8)) - _u(1))) & _u(1) != _u(0),
    )


def _flags_logic(r, opsize):
    m = _size_mask(opsize)
    rm = r & m
    return _mkflags(
        cf=jnp.bool_(False),
        pf=_parity_even(rm),
        af=jnp.bool_(False),
        zf=rm == _u(0),
        sf=_msb(rm, opsize) != _u(0),
        of=jnp.bool_(False),
    )


# (condition evaluation lives in limbs.eval_cond — the arithmetic flags all
# sit in the low rflags limb, so the ported path is u32-only by nature)


# ---------------------------------------------------------------------------
# register file helpers
# ---------------------------------------------------------------------------

def _read_reg(gpr, idx, nbytes):
    high = idx >= U.REG_AH_BASE
    base = jnp.clip(jnp.where(high, idx - U.REG_AH_BASE, idx), 0, 15)
    v = gpr[base]
    return jnp.where(high, (v >> _u(8)) & _u(0xFF), v & _size_mask(nbytes))


def _read64(gpr, idx):
    """Full qword read; REG_NONE (or any out-of-file index) reads 0."""
    ok = (idx >= 0) & (idx < 16)
    return jnp.where(ok, gpr[jnp.clip(idx, 0, 15)], _u(0))


def _gpr_write(gpr, cond, idx, val, nbytes):
    """Partial-register merge semantics of cpu/emu.py write_reg: 32-bit
    writes zero-extend, 8/16-bit merge, AH-view writes hit bits 15:8."""
    high = idx >= U.REG_AH_BASE
    base = jnp.clip(jnp.where(high, idx - U.REG_AH_BASE, idx), 0, 15)
    old = gpr[base]
    m = _size_mask(nbytes)
    merged = jnp.where(
        high, (old & ~_u(0xFF00)) | ((val & _u(0xFF)) << _u(8)),
        jnp.where(nbytes >= 8, val,
                  jnp.where(nbytes == 4, val & _u(0xFFFFFFFF),
                            (old & ~m) | (val & m))))
    return gpr.at[base].set(jnp.where(cond, merged, old))


# ---------------------------------------------------------------------------
# limb register file helpers (the u32-packed mirror of the three above;
# `gl` is the uint32[16, 2] per-lane file)
# ---------------------------------------------------------------------------

def _z32():
    return jnp.uint32(0)


def _read64_l(gl, idx):
    """Full qword read as a limb pair; REG_NONE (out-of-file) reads 0."""
    ok = (idx >= 0) & (idx < 16)
    row = gl[jnp.clip(idx, 0, 15)]
    z = _z32()
    return jnp.where(ok, row[0], z), jnp.where(ok, row[1], z)


def _read_reg_l(gl, idx, nbytes):
    high = idx >= U.REG_AH_BASE
    base = jnp.clip(jnp.where(high, idx - U.REG_AH_BASE, idx), 0, 15)
    row = gl[base]
    lo, hi = L.zext((row[0], row[1]), nbytes)
    ah = (row[0] >> 8) & jnp.uint32(0xFF)
    return jnp.where(high, ah, lo), jnp.where(high, _z32(), hi)


def _gpr_write_l(gl, cond, idx, val, nbytes):
    """_gpr_write on the limb file: 32-bit writes zero the high limb,
    8/16-bit writes merge into the low limb, AH-views hit bits 15:8.

    Not called by step_lane (the one shared u64 scatter is cheaper while
    the file lives behind a free bitcast) — this is the register-file
    writer for the Pallas fused-step kernel, where no u64 file can exist;
    tests/test_limbs.py pins it against _gpr_write."""
    high = idx >= U.REG_AH_BASE
    base = jnp.clip(jnp.where(high, idx - U.REG_AH_BASE, idx), 0, 15)
    old_lo, old_hi = gl[base, 0], gl[base, 1]
    mlo, _mhi = L.size_mask(nbytes)
    ah_merged = ((old_lo & jnp.uint32(0xFFFF00FF))
                 | ((val[0] & jnp.uint32(0xFF)) << 8))
    lo = jnp.where(high, ah_merged,
                   jnp.where(nbytes >= 4, val[0],
                             (old_lo & ~mlo) | (val[0] & mlo)))
    hi = jnp.where(high, old_hi,
                   jnp.where(nbytes >= 8, val[1],
                             jnp.where(nbytes == 4, _z32(), old_hi)))
    lo = jnp.where(cond, lo, old_lo)
    hi = jnp.where(cond, hi, old_hi)
    return gl.at[base].set(jnp.stack([lo, hi]))


# ---------------------------------------------------------------------------
# ported hot paths (pure u32 limb arithmetic — tests/test_limbs.py compiles
# these standalone and fails if a 64-bit integer op appears in the HLO)
# ---------------------------------------------------------------------------

def alu_limb(sub, a, b, cf_in, opsize, rf_lo):
    """Integer ALU class on u32 limbs: add/adc/sub/sbb/cmp/and/or/xor/test
    plus the CF/PF/AF/ZF/SF/OF image — semantics mirror cpu/emu.py exactly
    (the same contract the deleted u64 block carried).

    Returns (masked result pair, new low-rflags limb, writes-result)."""
    r_add = L.zext(L.add64(a, b), opsize)
    r_adc = L.zext(L.adc64(a, b, cf_in)[0], opsize)
    r_sub = L.zext(L.sub64(a, b), opsize)
    r_sbb = L.zext(L.sbb64(a, b, cf_in)[0], opsize)
    r_and, r_or, r_xor = L.and64(a, b), L.or64(a, b), L.xor64(a, b)
    zero = (_z32(), _z32())
    r = L.select64(
        [sub == U.ALU_ADD, sub == U.ALU_ADC, sub == U.ALU_SUB,
         sub == U.ALU_SBB, sub == U.ALU_CMP, sub == U.ALU_AND,
         sub == U.ALU_OR, sub == U.ALU_XOR, sub == U.ALU_TEST],
        [r_add, r_adc, r_sub, r_sbb, r_sub, r_and, r_or, r_xor, r_and],
        zero)
    fl_add = L.flags_add(a, b, r, opsize, (sub == U.ALU_ADC) & cf_in)
    fl_sub = L.flags_sub(a, b, r, opsize, (sub == U.ALU_SBB) & cf_in)
    fl_logic = L.flags_logic(r, opsize)
    is_add = (sub == U.ALU_ADD) | (sub == U.ALU_ADC)
    is_sub = (sub == U.ALU_SUB) | (sub == U.ALU_SBB) | (sub == U.ALU_CMP)
    fl = jnp.where(is_add, fl_add, jnp.where(is_sub, fl_sub, fl_logic))
    new_rf_lo = (rf_lo & jnp.uint32(~L.FLAGS_ARITH & 0xFFFFFFFF)) | fl
    writes = ~((sub == U.ALU_CMP) | (sub == U.ALU_TEST))
    return r, new_rf_lo, writes


def unary_limb(sub, a, cf_in, opsize, rf_lo):
    """inc/dec/not/neg on u32 limbs (inc/dec preserve CF; neg CF = a != 0;
    not leaves rflags alone) — mirrors the deleted u64 UNARY block."""
    one = (jnp.uint32(1), _z32())
    zero = (_z32(), _z32())
    r_inc = L.zext(L.add64(a, one), opsize)
    r_dec = L.zext(L.sub64(a, one), opsize)
    r_neg = L.zext(L.neg64(a), opsize)
    r_not = L.zext(L.not64(a), opsize)
    r = L.select64(
        [sub == U.UN_INC, sub == U.UN_DEC, sub == U.UN_NOT, sub == U.UN_NEG],
        [r_inc, r_dec, r_not, r_neg], zero)
    false = jnp.bool_(False)
    fl = jnp.where(
        sub == U.UN_INC, L.flags_add(a, one, r_inc, opsize, false),
        jnp.where(sub == U.UN_DEC, L.flags_sub(a, one, r_dec, opsize, false),
                  L.flags_sub(zero, a, r_neg, opsize, false)))
    cf = jnp.where((sub == U.UN_INC) | (sub == U.UN_DEC), cf_in,
                   ~L.is_zero64(L.zext(a, opsize)))
    new_rf_lo = jnp.where(
        sub == U.UN_NOT, rf_lo,
        (rf_lo & jnp.uint32(~L.FLAGS_ARITH & 0xFFFFFFFF))
        | (fl & jnp.uint32(~L.CF & 0xFFFFFFFF))
        | jnp.where(cf, jnp.uint32(L.CF), _z32()))
    return r, new_rf_lo


def _scale_idx_l(v, scale):
    """index * scale for SIB scales {0,1,2,4,8} as a limb shift (where-
    chain, not jnp.select — select's case index would reintroduce s64).
    The shift is at most 3, so the cross-limb carry needs no >=32 cases
    (and lg==0 makes the 32-lg carry shift a harmless full shift-out)."""
    lg = jnp.where(scale == 2, jnp.uint32(1),
                   jnp.where(scale == 4, jnp.uint32(2),
                             jnp.where(scale == 8, jnp.uint32(3), _z32())))
    carry = jnp.where(lg == 0, _z32(), v[0] >> (jnp.uint32(32) - lg))
    lo, hi = v[0] << lg, (v[1] << lg) | carry
    keep = scale != 0
    return jnp.where(keep, lo, _z32()), jnp.where(keep, hi, _z32())


def ea_limb(disp, base, idx_scaled, seg, a32):
    """Effective address on u32 limbs: disp + base + scaled index, 67h
    truncation to 32 bits BEFORE the segment base (SDM address-size
    override in 64-bit mode — the truncation is literally zeroing the
    high limb, the representation's home turf)."""
    flat_lo, flat_hi = L.add64(L.add64(disp, base), idx_scaled)
    flat_hi = jnp.where(a32 != 0, _z32(), flat_hi)
    return L.add64((flat_lo, flat_hi), seg)


def shift_limb(sub, sext_f, a, filler, cl_lo, src_lo, imm_lo, cf_in,
               opsize, rf_lo):
    """SHIFT/ROT class on u32 limbs: shl/shr/sar/rol/ror/rcl/rcr/shld/shrd
    plus the partial CF/OF(/ZF/SF/PF) flag image — semantics mirror the
    deleted u64 SHIFT block bit-for-bit (which mirrored cpu/emu.py).

    `a` is the dst value pair, `filler` the shld/shrd fill register (read
    at opsize), `cl_lo`/`src_lo`/`imm_lo` the low limbs of rcx / the src
    operand / the immediate (every count fits 6 bits after masking, so
    the high limbs never participate).

    Returns (masked result pair, new low-rflags limb, writes-result)."""
    z = _z32()
    one = jnp.uint32(1)
    false = jnp.bool_(False)
    bits = opsize.astype(jnp.uint32) * jnp.uint32(8)
    is_shxd = (sub == U.SH_SHLD) | (sub == U.SH_SHRD)
    cl = cl_lo & jnp.uint32(0xFF)
    cnt_src = jnp.where(is_shxd, jnp.where(sext_f == 3, imm_lo, cl), src_lo)
    cnt_mask = jnp.where(opsize >= 8, jnp.uint32(0x3F), jnp.uint32(0x1F))
    count0 = cnt_src & cnt_mask
    # rcl/rcr rotate through CF over bits+1 positions
    is_rc = (sub == U.SH_RCL) | (sub == U.SH_RCR)
    count = jnp.where(is_rc, count0 % (bits + one), count0)
    # shld/shrd 16-bit with count > bits: arch-undefined; emu reduces mod bits
    count = jnp.where(is_shxd & (count > bits), count % bits, count)
    cnz = count != z
    am = L.zext(a, opsize)
    sa = L.sext(a, opsize)
    cf01 = (jnp.where(cf_in, one, z), z)
    c1m = count - one            # count==0 wraps >= 64: shifts yield 0

    def bit0(p):
        return (p[0] & one) != z

    sh_shl_r = L.zext(L.shl64(am, count), opsize)
    sh_shl_cf = (count <= bits) & bit0(L.shr64(am, bits - count))
    sh_shr_r = L.shr64(am, count)
    sh_shr_cf = (count <= bits) & bit0(L.shr64(am, c1m))
    sh_sar_r = L.zext(L.sar64(sa, count), opsize)
    sh_sar_cf = bit0(L.sar64(sa, c1m))        # sar64 clamps counts at 63
    rot_c = count % bits
    rot_cz = rot_c == z
    sh_rol_r = L.where64(
        rot_cz, am,
        L.zext(L.or64(L.shl64(am, rot_c), L.shr64(am, bits - rot_c)), opsize))
    sh_rol_cf = bit0(sh_rol_r)
    sh_ror_r = L.where64(
        rot_cz, am,
        L.zext(L.or64(L.shr64(am, rot_c), L.shl64(am, bits - rot_c)), opsize))
    sh_ror_cf = L.msb(sh_ror_r, opsize)
    # rcl/rcr: (bits+1)-bit rotate through carry, expressed without u128
    zero2 = (z, z)
    sh_rcl_r = L.zext(
        L.or64(L.or64(L.shl64(am, count), L.shl64(cf01, c1m)),
               L.where64(count > one,
                         L.shr64(am, bits + one - count), zero2)),
        opsize)
    sh_rcl_cf = jnp.where(cnz, bit0(L.shr64(am, bits - count)), cf_in)
    sh_rcr_r = L.zext(
        L.or64(L.or64(L.shr64(am, count), L.shl64(cf01, bits - count)),
               L.where64(count > one,
                         L.shl64(am, bits + one - count), zero2)),
        opsize)
    sh_rcr_cf = jnp.where(cnz, bit0(L.shr64(am, c1m)), cf_in)
    sh_shld_r = L.zext(
        L.or64(L.shl64(am, count), L.shr64(filler, bits - count)), opsize)
    sh_shld_cf = bit0(L.shr64(am, bits - count))
    sh_shrd_r = L.zext(
        L.or64(L.shr64(am, count), L.shl64(filler, bits - count)), opsize)
    sh_shrd_cf = bit0(L.shr64(am, c1m))

    conds = [(sub == U.SH_SHL) | (sub == U.SH_SAL), sub == U.SH_SHR,
             sub == U.SH_SAR, sub == U.SH_ROL, sub == U.SH_ROR,
             sub == U.SH_RCL, sub == U.SH_RCR, sub == U.SH_SHLD,
             sub == U.SH_SHRD]
    r = L.select64(conds,
                   [sh_shl_r, sh_shr_r, sh_sar_r, sh_rol_r, sh_ror_r,
                    sh_rcl_r, sh_rcr_r, sh_shld_r, sh_shrd_r], zero2)
    cf = L.sel(conds,
               [sh_shl_cf, sh_shr_cf, sh_sar_cf, sh_rol_cf, sh_ror_cf,
                sh_rcl_cf, sh_rcr_cf, sh_shld_cf, sh_shrd_cf], false)
    count1 = count == one
    of_keep = (rf_lo & jnp.uint32(L.OF)) != z
    r_msb = L.msb(r, opsize)
    am_msb = L.msb(am, opsize)
    ror_b2 = bit0(L.shr64(sh_ror_r, bits - jnp.uint32(2)))
    of = L.sel(conds, [
        jnp.where(count1, r_msb != cf, of_keep),
        jnp.where(count1, am_msb, of_keep),
        jnp.where(count1, false, of_keep),
        jnp.where(count1, r_msb != cf, of_keep),
        jnp.where(count1, r_msb != ror_b2, of_keep),
        jnp.where(count1, r_msb != cf, of_keep),
        jnp.where(count1, am_msb != cf_in, of_keep),
        jnp.where(count1, L.msb(L.xor64(sh_shld_r, am), opsize), false),
        jnp.where(count1, L.msb(L.xor64(sh_shrd_r, am), opsize), false),
    ], of_keep)
    full = L.mkflags(cf, L.parity_even(r[0]), false,
                     L.is_zero64(r), r_msb, of)
    # rcl/rcr update only CF|OF; others CF|OF|ZF|SF|PF (AF untouched,
    # mirroring the oracle's partial set_flags in emu._exec_shift)
    mask = jnp.where(is_rc, jnp.uint32(L.CF | L.OF),
                     jnp.uint32(L.CF | L.OF | L.ZF | L.SF | L.PF))
    new_rf_lo = jnp.where(cnz, (rf_lo & ~mask) | (full & mask), rf_lo)
    return r, new_rf_lo, cnz


def mul_limb(sub, sext_f, a, b, rax, imm, opsize, rf_lo):
    """MUL class on u32 limbs: 2/3-operand imul plus the widening
    mul/imul forms (lo to rAX/dst, hi to rDX, 8-bit widening writes the
    full product to AX) with the CF/OF image — mirrors the deleted u64
    MUL block bit-for-bit.

    For opsize < 8 every signed/unsigned product fits 64 bits exactly, so
    the wide product is one mul64_lo; opsize 8 takes the high half from
    limbs.umulhi64/smulhi64.  Returns (w1 pair — the primary write —,
    w2 pair — the widening high half —, new low-rflags limb)."""
    z = _z32()
    zero2 = (z, z)
    ones2 = (jnp.uint32(0xFFFFFFFF), jnp.uint32(0xFFFFFFFF))
    false = jnp.bool_(False)
    bits = opsize.astype(jnp.uint32) * jnp.uint32(8)
    sb = L.sext(b, opsize)
    is_mul2 = sub == U.MUL_2OP
    mul2_a = L.where64(sext_f == 2, b, a)          # 3-op: r/m * imm
    mul2_b = L.where64(sext_f == 2, L.zext(imm, opsize), b)
    m2sa = L.sext(mul2_a, opsize)
    m2sb = L.sext(mul2_b, opsize)
    m2_full = L.mul64_lo(m2sa, m2sb)
    mul2_lo = L.zext(m2_full, opsize)
    mul2_of_small = ~L.eq64(m2_full, L.sext(mul2_lo, opsize))
    m2_hi = L.smulhi64(m2sa, m2sb)
    m2_fill = L.where64((mul2_lo[1] >> 31) != 0, ones2, zero2)
    mul2_of = jnp.where(opsize >= 8, ~L.eq64(m2_hi, m2_fill), mul2_of_small)

    # unsigned widening
    muw_full_u = L.mul64_lo(rax, b)    # exact for opsize < 8; low64 at 8
    muw_u_lo = L.where64(opsize >= 8, muw_full_u,
                         L.zext(muw_full_u, opsize))
    muw_u_hi = L.where64(opsize >= 8, L.umulhi64(rax, b),
                         L.zext(L.shr64(muw_full_u, bits), opsize))
    muw_u_of = ~L.is_zero64(muw_u_hi)
    # signed widening
    sax = L.sext(rax, opsize)
    muw_full_s = L.mul64_lo(sax, sb)   # exact two's complement for < 8
    muw_s_lo_small = L.zext(muw_full_s, opsize)
    muw_s_hi64 = L.smulhi64(sax, sb)
    muw_s_lo = L.where64(opsize >= 8, muw_full_s, muw_s_lo_small)
    muw_s_hi = L.where64(opsize >= 8, muw_s_hi64,
                         L.zext(L.shr64(muw_full_s, bits), opsize))
    s_fill = L.where64((muw_full_s[1] >> 31) != 0, ones2, zero2)
    muw_s_of = jnp.where(
        opsize >= 8, ~L.eq64(muw_s_hi64, s_fill),
        ~L.eq64(muw_full_s, L.sext(muw_s_lo_small, opsize)))
    mul_wide_s = sub == U.MUL_WIDE_S
    muw_lo = L.where64(mul_wide_s, muw_s_lo, muw_u_lo)
    muw_hi = L.where64(mul_wide_s, muw_s_hi, muw_u_hi)
    muw_of = jnp.where(mul_wide_s, muw_s_of, muw_u_of)
    mul_of = jnp.where(is_mul2, mul2_of, muw_of)
    # 8-bit widening mul writes the full product to AX (emu _exec_mul)
    prod16 = L.zext(L.where64(mul_wide_s, muw_full_s, muw_full_u),
                    jnp.int32(2))
    w1 = L.where64(is_mul2, mul2_lo,
                   L.where64(opsize == 1, prod16, muw_lo))
    rf2 = ((rf_lo & jnp.uint32(~L.FLAGS_ARITH & 0xFFFFFFFF))
           | L.mkflags(mul_of, L.parity_even(mul2_lo[0]), false, false,
                       L.msb(mul2_lo, opsize), mul_of))
    rfw = ((rf_lo & jnp.uint32(~(L.CF | L.OF) & 0xFFFFFFFF))
           | jnp.where(mul_of, jnp.uint32(L.CF | L.OF), z))
    new_rf_lo = jnp.where(is_mul2, rf2, rfw)
    return w1, muw_hi, new_rf_lo


# ---------------------------------------------------------------------------
# memory spans (dynamic size <= 16 bytes, overlay-aware, two pages max)
#
# Word-window design: any <=16-byte span is covered by 3 aligned u64 words
# (the page boundary is word-aligned, so each window word maps wholly to
# one of the two translated pages).  Loads are 3 word gathers + shifts;
# stores are a 3-word masked read-modify-write (mem/overlay.py).
# ---------------------------------------------------------------------------

def _bytes_of(lo, hi):
    sh = jnp.arange(8, dtype=jnp.uint64) * _u(8)
    b_lo = ((lo >> sh) & _u(0xFF)).astype(jnp.uint8)
    b_hi = ((hi >> sh) & _u(0xFF)).astype(jnp.uint8)
    return jnp.concatenate([b_lo, b_hi])


def _unpack_bytes(lo, hi):
    """(lo, hi) u64 pair -> u8[16] vector (for SSE byte ops)."""
    return _bytes_of(lo, hi)


def _pack_pair(b16):
    """u8[16] -> (lo, hi) u64 pair."""
    sh = jnp.arange(8, dtype=jnp.uint64) * _u(8)
    lo = jnp.sum(b16[:8].astype(jnp.uint64) << sh)
    hi = jnp.sum(b16[8:].astype(jnp.uint64) << sh)
    return lo, hi

# ---------------------------------------------------------------------------
# the transition function
# ---------------------------------------------------------------------------

def uop_lookup(tab: UopTable, rip_l):
    """Open-addressed probe (host inserter bounds chains to PROBES) ->
    entry index or -1 (NEED_DECODE).  All PROBES slots are fetched in ONE
    gather — the hash rows carry the probe key's limbs next to the entry
    index ([hash_size, 3]), so the verification compare reads the same
    [PROBES, 3] block instead of chasing entry indices through a second
    dependent gather of rip_l (probe count is a latency, not a work,
    concern on TPU; dependent gathers are both).

    Ported path: `rip_l` is a u32 limb pair and the whole probe — the
    splitmix64 hash, the slot indices, the key verification compare — is
    u32-only (the table mask always fits 32 bits, so slot = (hash + k) &
    mask needs only the low hash limb)."""
    hmask = jnp.uint32(tab.hash_tab.shape[0] - 1)
    h_lo, _h_hi = L.splitmix64(rip_l)
    slots = ((h_lo + jnp.arange(PROBES, dtype=jnp.uint32))
             & hmask).astype(jnp.int32)
    rows = tab.hash_tab[slots]
    e = rows[:, 0]
    match = ((e >= 0)
             & (rows[:, 1].astype(jnp.uint32) == rip_l[0])
             & (rows[:, 2].astype(jnp.uint32) == rip_l[1]))
    # first-match via i32 min-rank (argmax's reduce runs an s64 iota under
    # x64, which would be the probe's only 64-bit op)
    rank = jnp.where(match, jnp.arange(PROBES, dtype=jnp.int32),
                     jnp.int32(PROBES))
    first = jnp.min(rank)
    return jnp.where(first < PROBES,
                     e[jnp.minimum(first, PROBES - 1)], jnp.int32(-1))


# Export hook for the static analyzer (wtf_tpu/analysis): every ported
# u32-limb hot path, compiled standalone under the zero-u64 dtype rule.
# Adding a newly ported path here (and an argument recipe in
# analysis/rules.py — the lint fails on an export without one) is how it
# comes under the pin; tests/test_limbs.py runs the same rule family.
PORTED_LIMB_PATHS = {
    "step.alu_limb": alu_limb,
    "step.unary_limb": unary_limb,
    "step.shift_limb": shift_limb,
    "step.mul_limb": mul_limb,
    "step.ea_limb": ea_limb,
    "step.scale_idx_l": _scale_idx_l,
    "step.uop_lookup": uop_lookup,
    "step.gpr_write_l": _gpr_write_l,
}


def step_lane(tab: UopTable, image: MemImage, st: Machine, limit) -> Machine:
    """Advance one lane by one instruction (vmapped over the batch).

    Lanes whose status != RUNNING are a no-op.  `limit` is the instruction
    budget (u64; 0 = unlimited) -> TIMEDOUT, the deterministic equivalent of
    the reference's after_execution counter (bochscpu_backend.cc:458-469)."""
    enabled = st.status == jnp.int32(int(StatusCode.RUNNING))
    # limb-packed hot state (ported paths) + free u64 bitcast views (cold
    # paths and the memory subsystem convert at this seam)
    glimb = st.gpr_l                                  # uint32[16, 2]
    rip_l = (st.rip_l[0], st.rip_l[1])
    rf_lo, rf_hi = st.rflags_l[0], st.rflags_l[1]
    gpr, rip, rf = st.gpr, st.rip, st.rflags
    overlay = st.overlay

    # -- 1. decode-cache lookup (u32-only hash probe) -------------------
    # Heterogeneous batches (wtf_tpu/tenancy) probe a TENANT-TAGGED key:
    # rip ^ (tenant << 48).  Canonical rips keep bits 62:48 as sign bits,
    # so the tag never collides with a real address and two base images
    # sharing a virtual address resolve to distinct cache entries (each
    # with its own raw bytes / code pfns — no cross-tenant SMC thrash).
    # Single-image dispatch (tenant=None) probes the bare rip: key == rip.
    if image.tenant is None:
        key_l = rip_l
    else:
        ttag = (image.tenant.astype(jnp.uint32) << 16)  # bit 48 = hi bit 16
        key_l = (rip_l[0], rip_l[1] ^ ttag)
    idx = uop_lookup(tab, key_l)
    miss = enabled & (idx < 0)
    idxc = jnp.maximum(idx, 0)

    f = tab.meta_i32[idxc]          # one row gather: fields + pfn0/pfn1/bp
    mu = tab.meta_u64[idxc]         # one row gather: disp/imm/raw_lo/raw_hi
    opc = f[F_OPC]
    sub = f[F_SUB]
    cond = f[F_COND]
    length = f[F_LENGTH]
    opsize = f[F_OPSIZE]
    srcsize0 = f[F_SRCSIZE]
    sext_f = f[F_SEXT]
    dk = f[F_DST_KIND]
    dr = f[F_DST_REG]
    sk = f[F_SRC_KIND]
    sr = f[F_SRC_REG]
    breg = f[F_BASE_REG]
    ireg = f[F_IDX_REG]
    scale = f[F_SCALE]
    seg = f[F_SEG]
    rep = f[F_REP]
    disp = mu[MU_DISP]
    imm = mu[MU_IMM]
    disp_l = L.pair(disp)
    imm_l = L.pair(imm)

    opmask = _size_mask(opsize)
    bits_u = opsize.astype(jnp.uint64) * _u(8)
    next_rip_l = L.add64_u32(rip_l, length.astype(jnp.uint32))
    next_rip = L.to_u64(next_rip_l)

    # -- 2. breakpoint (pre-execution, like BeforeExecutionHook dispatch) --
    at_bp = enabled & ~miss & (f[M_BP] == 1) & (st.bp_skip == 0)

    # -- 3. SMC check addresses: live code bytes vs decode-time raw -------
    # Code physical frames come from the decode-time translation (pfn0/pfn1
    # table columns) so no page walk is needed for fetch; a *mapping* change
    # without a byte change is not detected (documented divergence — the
    # oracle flushes uops from dirtied pages the same way).  The window
    # itself loads below, batched with the operand loads.
    code_off = (rip & _u(0xFFF)).astype(jnp.int32)
    code_crosses = (code_off + 16) > 4096
    gpa_c0 = (f[M_PFN0].astype(jnp.uint64) << _u(12)) \
        + code_off.astype(jnp.uint64)
    gpa_c15 = jnp.where(
        code_crosses,
        (f[M_PFN1].astype(jnp.uint64) << _u(12))
        + (code_off + 15 - 4096).astype(jnp.uint64),
        gpa_c0 + _u(15))

    # `live`'s final value needs the SMC verdict, which needs the batched
    # window load; the predicates feeding address computation only need
    # enabled/miss/bp (an SMC or about-to-fault lane computes garbage
    # addresses whose loads are simply not `need`ed — same as before).
    pre_live = enabled & ~miss & ~at_bp

    # -- class predicates (opc/fields only — stale for an SMC lane, but an
    # SMC lane never commits: `live` below excludes it) -------------------
    def is_(o):
        return opc == o

    opc_list = lambda pairs, default: jnp.select(  # noqa: E731
        [p[0] for p in pairs], [p[1] for p in pairs], default=default)

    is_string = is_(U.OPC_STRING)
    s_movs = is_string & (sub == U.STR_MOVS)
    s_stos = is_string & (sub == U.STR_STOS)
    s_lods = is_string & (sub == U.STR_LODS)
    s_scas = is_string & (sub == U.STR_SCAS)
    s_cmps = is_string & (sub == U.STR_CMPS)
    rep_on = is_string & (rep != U.REP_NONE)
    rcx = gpr[1]
    rep_skip = rep_on & (rcx == _u(0))  # rep w/ rcx=0: architectural no-op

    is_push, is_pop = is_(U.OPC_PUSH), is_(U.OPC_POP)
    is_pushf, is_popf = is_(U.OPC_PUSHF), is_(U.OPC_POPF)
    is_call, is_ret = is_(U.OPC_CALL), is_(U.OPC_RET)
    is_leave = is_(U.OPC_LEAVE) & (sub == 0)
    is_enter = is_(U.OPC_LEAVE) & (sub == 1)
    is_sse = is_(U.OPC_SSEMOV) | is_(U.OPC_SSEALU)
    is_ssefp = is_(U.OPC_SSEFP)
    is_x87 = is_(U.OPC_X87)
    # x87 state save/restore (512+ byte images) stays oracle-serviced;
    # everything else in the decoded x87 subset executes below
    x87_oracle = is_x87 & (
        (sub == U.X87_FXSAVE) | (sub == U.X87_FXRSTOR)
        | (sub == U.X87_XSAVE) | (sub == U.X87_XRSTOR))
    # store-shaped x87 subs must not issue the l1 read (their fault is a
    # WRITE fault, like the MOV/SETCC/POP store_only set)
    x87_store = is_x87 & (
        (sub == U.X87_FST_M) | (sub == U.X87_FIST) | (sub == U.X87_FIST_T)
        | (sub == U.X87_FNSTCW) | (sub == U.X87_FNSTSW_M)
        | (sub == U.X87_STMXCSR))
    # SSE-FP memory-operand byte counts mirror the oracle's virt_read sizes
    # exactly (emu._exec_ssefp) so page-boundary fault behavior matches:
    # elementwise forms read 16 (packed) / elem; converts have their own
    # shapes (the DQ/PS/PD block reads a full 16 even for cvtdq2pd, an
    # oracle-internal convention both engines share).
    fp_is_ew = (sub <= U.FP_SQRT) | (sub == U.FP_CMP)  # arith/minmax/sqrt/cmp
    fp_ldsize = jnp.select(
        [sub == U.FP_CVT_I2F,
         (sub == U.FP_CVT_F2I) | (sub == U.FP_CVT_F2I_T)
         | (sub == U.FP_UCOMI) | (sub == U.FP_COMI),
         sub == U.FP_CVT_F2F,
         fp_is_ew],
        [opsize, srcsize0,
         jnp.where(sext_f == 1, srcsize0 * 2, srcsize0),
         jnp.where(sext_f == 1, jnp.int32(16), srcsize0)],
        default=jnp.int32(16))

    # -- unsupported classes -> host oracle fallback ----------------------
    rax, rdx = gpr[0], gpr[2]
    # MSRs the machine carries — derived from the oracle's MSR_ATTR map
    # (single source of truth; attr names are Machine field names);
    # unknown ids stay oracle-serviced
    msr_id = gpr[1] & _u(0xFFFFFFFF)
    msr_known = jnp.zeros((), bool)
    for _mid in MSR_ATTR:
        msr_known = msr_known | (msr_id == _u(_mid))
    div64_hard = is_(U.OPC_DIV) & (opsize >= 8) & ~jnp.where(
        sub == U.DIV_U, rdx == _u(0),
        rdx == jnp.where((rax >> _u(63)) != 0, _u(MASK64), _u(0)))
    movcr_bad = is_(U.OPC_MOVCR) & ~(
        (sub == 0) | (sub == 3) | (sub == 4) | (sub == 8)
        | ((sext_f == 0) & (sub == 2)))
    unsupported = pre_live & (
        is_(U.OPC_INVALID) | is_(U.OPC_IRET)
        | (is_(U.OPC_MSR) & ~msr_known)
        | is_(U.OPC_SSECVT) | is_(U.OPC_PCLMUL)
        | is_(U.OPC_STACKSTR)
        | x87_oracle
        # pinsrw m16: a 2-byte load outside the 16-byte operand window
        | (is_(U.OPC_SSEALU) & (sub == U.SSE_PINSRW) & (sk == U.K_MEM))
        # non-canonical wr{fs,gs}base #GPs on hardware: divert so the
        # oracle raises it through the non-canonical -> #GP seam
        | (is_(U.OPC_RDGSBASE) & ((sub == 2) | (sub == 3))
           & ~_canon(_read_reg(gpr, dr, opsize)))
        # 67h string forms use 32-bit rsi/rdi/rcx; neither engine models
        # that — surface loudly instead of executing with 64-bit regs
        | (is_string & (f[F_A32] != 0))
        | movcr_bad | div64_hard)

    # -- 4a. effective address (ported: u32 limbs end to end) -------------
    base_val_l = L.where64(breg == U.REG_RIP, next_rip_l,
                           _read64_l(glimb, breg))
    idx_val_l = _scale_idx_l(_read64_l(glimb, ireg), scale)
    seg_base_l = L.select64(
        [seg == U.SEG_FS, seg == U.SEG_GS],
        [(st.fs_base_l[0], st.fs_base_l[1]),
         (st.gs_base_l[0], st.gs_base_l[1])],
        (jnp.uint32(0), jnp.uint32(0)))
    ea_l = ea_limb(disp_l, base_val_l, idx_val_l, seg_base_l, f[F_A32])
    ea = L.to_u64(ea_l)

    # BT bit-string addressing: register bit index moves the EA by opsize
    # for every `bits` of signed offset (emu _exec_bt).
    bt_sel = _read_reg(gpr, sr, opsize)
    bt_signed = _sext(bt_sel, opsize)
    log2bits = jnp.where(opsize >= 8, 6, jnp.where(opsize == 4, 5, 4)).astype(jnp.int64)
    bt_adjust = ((bt_signed.astype(jnp.int64) >> log2bits)
                 * opsize.astype(jnp.int64)).astype(jnp.uint64)
    bt_mem_reg = is_(U.OPC_BT) & (dk == U.K_MEM) & (sk == U.K_REG)
    ea = jnp.where(bt_mem_reg, ea + bt_adjust, ea)
    bt_off = bt_signed & (bits_u - _u(1))

    # -- 4b. memory roles (ported: span addresses assemble in u32 limbs;
    # the page walk itself converts at the translate_vec_l seam) ----------
    rsp, rbp, rsi, rdi = gpr[4], gpr[5], gpr[6], gpr[7]
    rsp_l = (glimb[4, 0], glimb[4, 1])
    rbp_l = (glimb[5, 0], glimb[5, 1])
    rsi_l = (glimb[6, 0], glimb[6, 1])
    rdi_l = (glimb[7, 0], glimb[7, 1])
    # post-BT-adjust EA (the BT bit-string displacement stays u64-cold)
    ea_mem_l = L.pair(ea)
    srcsize = jnp.where(srcsize0 == 0, opsize, srcsize0)

    l1_need = pre_live & ~unsupported & ~rep_skip & (
        ((sk == U.K_MEM) & ~x87_store) | is_pop | is_popf | is_ret
        | is_leave | s_movs | s_lods | s_cmps | s_scas)
    l1_addr_l = L.select64(
        [s_movs | s_lods | s_cmps, s_scas, is_pop | is_popf | is_ret,
         is_leave],
        [rsi_l, rdi_l, rsp_l, rbp_l], ea_mem_l)
    l1_addr = L.to_u64(l1_addr_l)
    l1_size = jnp.where(is_popf | is_ret | is_leave, 8,
               jnp.where(is_pop | is_string | is_sse, opsize,
                jnp.where(is_ssefp, fp_ldsize, srcsize)))

    # store-only destinations (MOV/SETCC/POP write [mem] without reading it)
    # must NOT issue a dst-read load: their fault is the *store* fault, so
    # crash names report write access like the oracle's translate(write=True)
    store_only = is_(U.OPC_MOV) | is_(U.OPC_SETCC) | is_pop
    l2_need = pre_live & ~unsupported & ~rep_skip & (
        ((dk == U.K_MEM) & ~is_sse & ~store_only) | s_cmps)
    l2_addr_l = L.where64(s_cmps, rdi_l, ea_mem_l)
    l2_addr = L.to_u64(l2_addr_l)
    l2_size = opsize

    # store address/size (the store itself commits at the end of the step;
    # computing its span here lets its translation batch with the loads')
    push_size = jnp.where(is_pushf | is_call, jnp.int32(8), opsize)
    push_size_l = (push_size.astype(jnp.uint32), jnp.uint32(0))
    st_addr_l = L.select64(
        [is_push | is_pushf | is_call, is_enter, s_movs | s_stos],
        [L.sub64(rsp_l, push_size_l),
         L.sub64(rsp_l, (jnp.uint32(8), jnp.uint32(0))),
         rdi_l],
        ea_mem_l)
    st_addr = L.to_u64(st_addr_l)
    # stores and pushes span the same byte count; x87 stores their
    # operand width (fst m32/m64, fist m16/32/64, fnstcw/fnstsw m16,
    # stmxcsr m32)
    st_size = jnp.where(x87_store, srcsize, push_size)

    # -- 4b'. ONE vectorized page walk for all six translations, ONE
    # batched gather for all three 16-byte windows (code/SMC, l1, l2).
    # On TPU the step's cost is the count of unfusable gather kernels,
    # so the walks and window reads are batched, not sequential.
    def _span_last(addr_l, size):
        return L.add64_u32(addr_l, (size - 1).astype(jnp.uint32))

    gva6_l = jnp.stack([
        jnp.stack(p, axis=-1) for p in (
            l1_addr_l, _span_last(l1_addr_l, l1_size),
            l2_addr_l, _span_last(l2_addr_l, l2_size),
            st_addr_l, _span_last(st_addr_l, st_size))])
    t6 = translate_vec_l(image, overlay, st.cr3, gva6_l)

    def _tr(i):
        return Translation(gpa=t6.gpa[i], ok=t6.ok[i],
                           writable=t6.writable[i], user=t6.user[i])

    l1t0, l1t1, l2t0, l2t1, ts0, ts1 = (_tr(i) for i in range(6))
    fault1 = l1_need & ~(l1t0.ok & l1t1.ok)
    fault2 = l2_need & ~(l2t0.ok & l2t1.ok)

    wf = jnp.stack([gpa_c0, l1t0.gpa, l2t0.gpa])
    wl = jnp.stack([gpa_c15, l1t1.gpa, l2t1.gpa])
    w3_0, w3_1, w3_2 = load_windows3_vec(image, overlay, wf, wl)
    lo3, hi3 = extract_pair(w3_0, w3_1, w3_2, wf)
    code_lo, code_hi = lo3[0], hi3[0]
    l1_lo, l1_hi = lo3[1], hi3[1]
    l2_lo = lo3[2]

    # -- SMC verdict + the final live mask --------------------------------
    lmask_lo = _size_mask(jnp.minimum(length, 8))
    lmask_hi = jnp.where(length > 8, _size_mask(length - 8), _u(0))
    smc = pre_live & (
        (((code_lo ^ mu[MU_RAW_LO]) & lmask_lo) != _u(0))
        | (((code_hi ^ mu[MU_RAW_HI]) & lmask_hi) != _u(0)))
    live = pre_live & ~smc
    is_crash = live & (is_(U.OPC_INT) | is_(U.OPC_HLT) | is_(U.OPC_INT1))

    # -- 4c. operand values (ported: read/extend/mask in u32 limbs; the
    # u64 views below are free bitcasts for the cold classes) -------------
    l1_lo_l = L.pair(l1_lo)
    l2_lo_l = L.pair(l2_lo)
    zero_l = (jnp.uint32(0), jnp.uint32(0))
    src_raw_l = L.where64(
        sk == U.K_REG, _read_reg_l(glimb, sr, srcsize),
        L.where64(sk == U.K_MEM, L.zext(l1_lo_l, srcsize), zero_l))
    src_ext_l = L.where64(
        sext_f == 1, L.zext(L.sext(src_raw_l, srcsize), opsize),
        L.zext(src_raw_l, opsize))
    src_val_l = L.where64(sk == U.K_IMM, L.zext(imm_l, opsize), src_ext_l)
    dst_val_l = L.where64(
        dk == U.K_REG, _read_reg_l(glimb, dr, opsize),
        L.where64(dk == U.K_MEM, L.zext(l2_lo_l, opsize), zero_l))
    src_val = L.to_u64(src_val_l)
    dst_val = L.to_u64(dst_val_l)

    # -- 4d. integer ALU classes (ported; mirrors cpu/emu.py exactly) -----
    a, b = dst_val, src_val
    cf_in = (rf_lo & jnp.uint32(_CF)) != jnp.uint32(0)

    # ALU (u32 limb path; the u64 image is a bitcast for mem-dst stores)
    alu_r_l, alu_rf_lo, alu_writes = alu_limb(
        sub, dst_val_l, src_val_l, cf_in, opsize, rf_lo)
    alu_r = L.to_u64(alu_r_l)

    # SHIFT (ported u32 limb path; shift_limb is compiled standalone by
    # tests/test_limbs.py to pin the absence of 64-bit ops) -----------
    filler_l = _read_reg_l(glimb, sr, opsize)
    sh_r_l, sh_rf_lo, sh_writes = shift_limb(
        sub, sext_f, dst_val_l, filler_l, glimb[1, 0], src_val_l[0],
        imm_l[0], cf_in, opsize, rf_lo)
    sh_r = L.to_u64(sh_r_l)

    # UNARY (ported u32 limb path) ------------------------------------
    un_r_l, un_rf_lo = unary_limb(sub, dst_val_l, cf_in, opsize, rf_lo)
    un_r = L.to_u64(un_r_l)

    # MUL (ported u32 limb path; mul_limb is compiled standalone by
    # tests/test_limbs.py to pin the absence of 64-bit ops) ----------
    rax_op = _read_reg(gpr, jnp.int32(0), opsize)
    is_mul2 = sub == U.MUL_2OP
    mul_r1_l, mul_r2_l, mul_rf_lo = mul_limb(
        sub, sext_f, dst_val_l, src_val_l,
        _read_reg_l(glimb, jnp.int32(0), opsize), imm_l, opsize, rf_lo)

    # DIV (device path: dividend fits in 64 bits; else host fallback) --
    d_lo = rax_op
    d_hi = _read_reg(gpr, jnp.int32(2), opsize)
    dividend_u = jnp.where(opsize == 1, _read_reg(gpr, jnp.int32(0), jnp.int32(2)),
                           jnp.where(opsize >= 8, d_lo,
                                     _shl(d_hi, bits_u) | d_lo))
    div_b = b & opmask
    div_bz = div_b == _u(0)
    safe_b = jnp.where(div_bz, _u(1), div_b)
    q_u = dividend_u // safe_b
    rem_u = dividend_u % safe_b
    # signed: sign-extend the (bits*2 <=64 or rdx:rax w/ rdx=sign) dividend
    sdividend = jnp.where(
        opsize == 1, _sext(dividend_u, jnp.int32(2)),
        jnp.where(opsize == 2, _sext(dividend_u, jnp.int32(4)),
                  jnp.where(opsize == 4, _sext(dividend_u, jnp.int32(8)),
                            d_lo))).astype(jnp.int64)
    sb_div = _sext(div_b, opsize).astype(jnp.int64)
    safe_sb = jnp.where(div_bz, jnp.int64(1), sb_div)
    # guard INT64_MIN / -1 (hardware #DE; lax.div would trap-free wrap)
    int_min_edge = (sdividend == jnp.int64(-2**63)) & (sb_div == jnp.int64(-1))
    q_s = lax.div(sdividend, jnp.where(int_min_edge, jnp.int64(1), safe_sb))
    rem_s = lax.rem(sdividend, jnp.where(int_min_edge, jnp.int64(1), safe_sb))
    is_sdiv = sub == U.DIV_S
    half_mask = _shr(opmask, _u(1))  # max positive quotient
    q_over = jnp.where(
        is_sdiv,
        (q_s > half_mask.astype(jnp.int64))
        | (q_s < (-(half_mask.astype(jnp.int64)) - 1)) | int_min_edge,
        q_u > opmask)
    de = live & is_(U.OPC_DIV) & ~div64_hard & (div_bz | q_over)
    div_q = jnp.where(is_sdiv, q_s.astype(jnp.uint64), q_u) & opmask
    div_rem = jnp.where(is_sdiv, rem_s.astype(jnp.uint64), rem_u) & opmask

    # CONVERT ---------------------------------------------------------
    half_bytes = jnp.maximum(opsize // 2, 1)
    cvt_widen = _sext(rax_op & _size_mask(half_bytes), half_bytes) & opmask
    cvt_sign = jnp.where(_msb(rax_op, opsize) != 0, opmask, _u(0))

    # BT --------------------------------------------------------------
    bt_imm_off = imm & (bits_u - _u(1))
    bt_offset = jnp.where(sk == U.K_IMM, bt_imm_off, bt_off)
    bt_val = dst_val
    bt_bit = (_shr(bt_val, bt_offset) & _u(1)) != 0
    bt_one = _shl(_u(1), bt_offset)
    bt_r = jnp.select(
        [sub == U.BT_BT, sub == U.BT_BTS, sub == U.BT_BTR, sub == U.BT_BTC],
        [bt_val, bt_val | bt_one, bt_val & ~bt_one, bt_val ^ bt_one],
        default=bt_val)
    bt_rf = (rf & ~_u(_CF)) | jnp.where(bt_bit, _u(_CF), _u(0))
    bt_writes = sub != U.BT_BT

    # BITSCAN ---------------------------------------------------------
    bs_src = src_val & opmask
    bs_zero = bs_src == _u(0)
    bs_pop = _popcnt(bs_src)
    bs_tz = _popcnt((~bs_src) & (bs_src - _u(1)))
    bs_len = _bitlen(bs_src)
    bs_lz = bits_u - bs_len
    bs_r = jnp.select(
        [sub == U.BS_POPCNT, sub == U.BS_TZCNT, sub == U.BS_LZCNT,
         sub == U.BS_BSF, sub == U.BS_BSR],
        [bs_pop,
         jnp.where(bs_zero, bits_u, bs_tz),
         jnp.where(bs_zero, bits_u, bs_lz),
         bs_tz, bs_len - _u(1)], default=_u(0))
    bs_writes = ~(((sub == U.BS_BSF) | (sub == U.BS_BSR)) & bs_zero)
    bs_rf = jnp.select(
        [sub == U.BS_POPCNT,
         (sub == U.BS_TZCNT) | (sub == U.BS_LZCNT)],
        [(rf & ~_u(FLAGS_ARITH)) | jnp.where(bs_zero, _u(_ZF), _u(0)),
         (rf & ~_u(_CF | _ZF))
         | jnp.where(bs_zero, _u(_CF), _u(0))
         | jnp.where(bs_r == _u(0), _u(_ZF), _u(0))],
        default=(rf & ~_u(_ZF)) | jnp.where(bs_zero, _u(_ZF), _u(0)))

    # BMI1/BMI2 (OPC_PEXT): VEX scalar bit ops; the third operand
    # (VEX.vvvv) rides in `cond` per the decoder's convention ----------
    bmi_third = _read_reg(gpr, cond, opsize)
    bmi_src = src_val & opmask
    bmi_n8 = bmi_third & _u(0xFF)
    bzhi_keep = bmi_n8 >= bits_u
    bmi_bzhi = jnp.where(bzhi_keep, bmi_src,
                         bmi_src & (_shl(_u(1), bmi_n8) - _u(1)))
    bx_start = bmi_third & _u(0xFF)
    bx_len = (bmi_third >> _u(8)) & _u(0xFF)
    bx_mask = _shl(_u(1), bx_len) - _u(1)   # len >= 64 wraps to all-ones
    bmi_bextr = jnp.where(bx_start < bits_u,
                          _shr(bmi_src, bx_start) & bx_mask, _u(0)) & opmask
    bmi_cnt = bmi_third & jnp.where(opsize >= 8, _u(63), _u(31))
    bmi_shlx = _shl(bmi_src, bmi_cnt) & opmask
    bmi_shrx = _shr(bmi_src, bmi_cnt)
    bmi_sarx = (_sext(bmi_src, opsize).astype(jnp.int64)
                >> jnp.minimum(bmi_cnt, _u(63)).astype(jnp.int64)
                ).astype(jnp.uint64) & opmask
    # pdep/pext: rank-based bit scatter/gather over 64 lanes
    bit_i = jnp.arange(64, dtype=jnp.uint64)
    src_bit = (bmi_src >> bit_i) & _u(1)
    bit_rank = jnp.cumsum(src_bit) - src_bit    # exclusive prefix count
    bmi_pext = jnp.sum(jnp.where(src_bit != 0,
                                 ((bmi_third >> bit_i) & _u(1)) << bit_rank,
                                 _u(0)))
    bmi_pdep = jnp.sum(jnp.where(src_bit != 0,
                                 ((bmi_third >> bit_rank) & _u(1)) << bit_i,
                                 _u(0)))
    bmi_blsr = bmi_src & (bmi_src - _u(1)) & opmask
    bmi_blsmsk = (bmi_src ^ (bmi_src - _u(1))) & opmask
    bmi_blsi = bmi_src & ((_u(0) - bmi_src) & opmask) & opmask
    rorx_n = imm & jnp.where(opsize >= 8, _u(63), _u(31))
    bmi_rorx = jnp.where(
        rorx_n == _u(0), bmi_src,
        (_shr(bmi_src, rorx_n) | _shl(bmi_src, bits_u - rorx_n)) & opmask)
    bmi_andn = (~bmi_third & bmi_src) & opmask
    bmi_res = jnp.select(
        [sub == U.BMI_ANDN, sub == U.BMI_BZHI, sub == U.BMI_BEXTR,
         sub == U.BMI_SHLX, sub == U.BMI_SHRX, sub == U.BMI_SARX,
         sub == U.BMI_PDEP, sub == U.BMI_PEXT_, sub == U.BMI_BLSR,
         sub == U.BMI_BLSMSK, sub == U.BMI_BLSI],
        [bmi_andn, bmi_bzhi, bmi_bextr, bmi_shlx, bmi_shrx, bmi_sarx,
         bmi_pdep, bmi_pext, bmi_blsr, bmi_blsmsk, bmi_blsi],
        default=bmi_rorx)
    # flag images: andn/bzhi/bls* touch SF/ZF/CF/OF, bextr ZF/CF/OF
    # (SF untouched), shifts/pdep/pext/rorx none — oracle set_flags kwargs
    bmi_sf = _msb(bmi_res, opsize) != 0
    bmi_zf = bmi_res == _u(0)
    bmi_cf = jnp.select(
        [sub == U.BMI_BZHI, sub == U.BMI_BLSR, sub == U.BMI_BLSMSK,
         sub == U.BMI_BLSI],
        [bmi_n8 > (bits_u - _u(1)), bmi_src == _u(0), bmi_src == _u(0),
         bmi_src != _u(0)],
        default=jnp.bool_(False))
    bmi_szco = _u(_SF | _ZF | _CF | _OF)
    bmi_flag_bits = _mkflags(bmi_cf, jnp.bool_(False), jnp.bool_(False),
                             bmi_zf, bmi_sf, jnp.bool_(False))
    bmi_rf = jnp.select(
        [(sub == U.BMI_ANDN) | (sub == U.BMI_BZHI) | (sub == U.BMI_BLSR)
         | (sub == U.BMI_BLSMSK) | (sub == U.BMI_BLSI),
         sub == U.BMI_BEXTR],
        [(rf & ~bmi_szco) | (bmi_flag_bits & bmi_szco),
         (rf & ~_u(_ZF | _CF | _OF)) | (bmi_flag_bits & _u(_ZF | _CF | _OF))],
        default=rf)

    # MSR (rdmsr/wrmsr) over the MSR-backed machine fields (msr_known
    # computed with the unsupported gate above; same MSR_ATTR source)
    msr_rval = jnp.select(
        [msr_id == _u(mid) for mid in MSR_ATTR],
        [st.tsc + st.icount if attr == "tsc" else getattr(st, attr)
         for attr in MSR_ATTR.values()],
        default=_u(0))
    msr_wval = ((gpr[2] & _u(0xFFFFFFFF)) << _u(32)) | (gpr[0] & _u(0xFFFFFFFF))

    # CMPXCHG / XADD --------------------------------------------------
    cx_acc = rax_op
    cx_eq = cx_acc == dst_val
    cx_store = jnp.where(cx_eq, _read_reg(gpr, sr, opsize), dst_val)
    cx_rf = (rf & ~_u(FLAGS_ARITH)) | _flags_sub(
        cx_acc, dst_val, (cx_acc - dst_val) & opmask, opsize, jnp.bool_(False))
    xadd_r = (dst_val + _read_reg(gpr, sr, opsize)) & opmask
    xadd_rf = (rf & ~_u(FLAGS_ARITH)) | _flags_add(
        dst_val, _read_reg(gpr, sr, opsize), xadd_r, opsize, jnp.bool_(False))

    # BSWAP -----------------------------------------------------------
    bsw_in = dst_val & opmask
    sh8 = jnp.arange(8, dtype=jnp.uint64) * _u(8)
    bsw_bytes = (bsw_in >> sh8) & _u(0xFF)
    nb_u = opsize.astype(jnp.uint64)
    rev_sh = jnp.where(jnp.arange(8, dtype=jnp.uint64) < nb_u,
                       (nb_u - _u(1) - jnp.arange(8, dtype=jnp.uint64)) * _u(8),
                       _u(0))
    bsw_r = jnp.sum(jnp.where(jnp.arange(8, dtype=jnp.uint64) < nb_u,
                              bsw_bytes << rev_sh, _u(0)))

    # STRING (one element per step; REP iterates by re-executing) ------
    df_set = (rf & _u(_DF)) != 0
    str_delta = jnp.where(df_set, _u(0) - opsize.astype(jnp.uint64),
                          opsize.astype(jnp.uint64))
    str_a = jnp.where(s_cmps, l1_lo & opmask,
                      rax_op)                       # scas: rax, cmps: [rsi]
    str_b = jnp.where(s_cmps, l2_lo & opmask, l1_lo & opmask)  # [rdi]
    str_cmp_r = (str_a - str_b) & opmask
    str_rf = (rf & ~_u(FLAGS_ARITH)) | _flags_sub(
        str_a, str_b, str_cmp_r, opsize, jnp.bool_(False))
    str_zf_new = (str_cmp_r == _u(0))
    rcx_dec = rcx - _u(1)
    str_cc_done = (s_scas | s_cmps) & jnp.where(
        rep == U.REP_REP, ~str_zf_new, str_zf_new)
    str_done = jnp.where(rep_on,
                         rep_skip | (rcx_dec == _u(0)) | str_cc_done,
                         jnp.bool_(True))
    str_upd = live & is_string & ~unsupported & ~rep_skip

    # control flow (ported: condition eval + relative targets in limbs;
    # indirect targets come from registers/memory through the u64 seam) --
    rcx_l = (glimb[1, 0], glimb[1, 1])
    cc_true = L.eval_cond(rf_lo, rcx_l, cond)
    jcc_target_l = L.add64(next_rip_l, imm_l)
    jmp_target = jnp.where(sk == U.K_IMM, L.to_u64(jcc_target_l), src_val)
    ret_target = l1_lo
    syscall_entry = sub == 0

    # PUSHF / POPF / FLAGOP -------------------------------------------
    popf_rf = (l1_lo & _u(0x40FD5)) | _u(0x2)
    flagop_rf = jnp.select(
        [sub == U.FL_CLC, sub == U.FL_STC, sub == U.FL_CMC,
         sub == U.FL_CLD, sub == U.FL_STD, sub == U.FL_CLI,
         sub == U.FL_STI, sub == U.FL_SAHF],
        [rf & ~_u(_CF), rf | _u(_CF), rf ^ _u(_CF),
         rf & ~_u(_DF), rf | _u(_DF), rf & ~_u(_IF),
         rf | _u(_IF),
         (rf & ~_u(0xD5)) | (_read_reg(gpr, jnp.int32(U.REG_AH_BASE), jnp.int32(1)) & _u(0xD5)) | _u(0x2)],
        default=rf)  # LAHF leaves rflags alone (writes AH instead)
    lahf_val = (rf & _u(0xD7)) | _u(0x2)

    # CPUID: same table + fallback chain as the oracle (cpu/cpuid.py
    # `cpuid()`): exact (leaf, subleaf), then (leaf, 0), then the highest
    # basic leaf for out-of-range basic leaves, else zeros ---------------
    cpuid_keys = jnp.asarray(_CPUID_KEYS)
    cpuid_vals = jnp.asarray(_CPUID_VALS)
    cp_eax = (gpr[0] & _u(0xFFFFFFFF)).astype(jnp.uint32)
    cp_ecx = (gpr[1] & _u(0xFFFFFFFF)).astype(jnp.uint32)
    cp_exact = (cpuid_keys[:, 0] == cp_eax) & (cpuid_keys[:, 1] == cp_ecx)
    cp_leaf0 = (cpuid_keys[:, 0] == cp_eax) & (cpuid_keys[:, 1] == 0)
    cp_in_basic_fb = ((cp_eax < jnp.uint32(0x80000000))
                      & (cp_eax > jnp.uint32(MAX_BASIC_LEAF)))
    # Masked-sum row selection instead of a dynamic-slice gather of the
    # matching row: CPUID_TABLE keys are unique so at most one row
    # matches each mask and the sum IS that row; the basic-leaf fallback
    # row is a static index, so it constant-folds.  One fewer
    # data-dependent kernel in the compiled ladder (budgets.json).
    cp_exact_row = jnp.sum(
        jnp.where(cp_exact[:, None], cpuid_vals, jnp.uint32(0)), axis=0,
        dtype=jnp.uint32)
    cp_leaf0_row = jnp.sum(
        jnp.where(cp_leaf0[:, None], cpuid_vals, jnp.uint32(0)), axis=0,
        dtype=jnp.uint32)
    cp_basic_row = jnp.asarray(_CPUID_VALS[_CPUID_BASIC_ROW])
    cpuid_out = jnp.where(
        jnp.any(cp_exact), cp_exact_row,
        jnp.where(jnp.any(cp_leaf0), cp_leaf0_row,
                  jnp.where(cp_in_basic_fb, cp_basic_row,
                            jnp.zeros(4, jnp.uint32)))).astype(jnp.uint64)

    # RDTSC / RDRAND / XGETBV / SYSCALL / SWAPGS / MOVCR ---------------
    tsc_now = st.tsc + st.icount
    rdrand_next = _splitmix64(st.rdrand)
    rdrand_rf = (rf & ~_u(FLAGS_ARITH)) | _u(_CF)
    syscall_rf = (rf & ~(st.sfmask | _u(_TF))) | _u(0x2)
    sysret_rf = (gpr[11] & _u(U.RF_WRITABLE)) | _u(0x2)
    cr_read = jnp.select(
        [sub == 0, sub == 2, sub == 3, sub == 4, sub == 8],
        [st.cr0, st.cr2, st.cr3, st.cr4, st.cr8], default=_u(0))
    movcr_is_write = is_(U.OPC_MOVCR) & (sext_f != 0)
    cr_wval = _read_reg(gpr, sr, jnp.int32(8))

    # SSE --------------------------------------------------------------
    xmm = st.xmm
    x_dst_lo, x_dst_hi = xmm[jnp.clip(dr, 0, 15), 0], xmm[jnp.clip(dr, 0, 15), 1]
    x_src_lo = jnp.where(sk == U.K_XMM, xmm[jnp.clip(sr, 0, 15), 0], l1_lo)
    x_src_hi = jnp.where(sk == U.K_XMM, xmm[jnp.clip(sr, 0, 15), 1], l1_hi)
    is_ssemov = is_(U.OPC_SSEMOV)
    is_ssealu = is_(U.OPC_SSEALU)
    # movd/movq gpr->xmm (sub 1): value zero-extended into the register
    gpr_to_x = _read_reg(gpr, sr, opsize)
    ssm_in_lo = jnp.where(sub == 1, gpr_to_x, x_src_lo)
    ssm_in_hi = jnp.where(sub == 1, _u(0),
                          jnp.where(opsize >= 16, x_src_hi, _u(0)))
    # movss/movsd xmm,xmm merge low lanes; loads and movq (sub 3) zero upper
    ssm_merge = (sk == U.K_XMM) & (opsize < 16) & (sub != 3) & (sub != 1)
    sz_mask_x = _size_mask(opsize)  # opsize 4/8/16
    ssm_lo = jnp.where(opsize >= 8, ssm_in_lo,
                       jnp.where(ssm_merge,
                                 (x_dst_lo & ~sz_mask_x) | (ssm_in_lo & sz_mask_x),
                                 ssm_in_lo & sz_mask_x))
    ssm_hi = jnp.where(opsize >= 16, ssm_in_hi,
                       jnp.where(ssm_merge, x_dst_hi, _u(0)))
    ssm_hi = jnp.where(sub == 1, _u(0), ssm_hi)
    # movlps/movhps family (sub 4 = low half, 5 = high half): memory loads
    # take l1; reg forms cross halves (movhlps: src HIGH, movlhps: src LOW)
    half4 = jnp.where(sk == U.K_XMM, x_src_hi, l1_lo)
    half5 = jnp.where(sk == U.K_XMM, x_src_lo, l1_lo)
    ssm_lo = jnp.where(sub == 4, half4, jnp.where(sub == 5, x_dst_lo, ssm_lo))
    ssm_hi = jnp.where(sub == 4, x_dst_hi, jnp.where(sub == 5, half5, ssm_hi))

    # byte-level SSE ALU on unpacked u8[16] vectors
    ba = _unpack_bytes(x_dst_lo, x_dst_hi)
    bb = jnp.where(sk == U.K_XMM,
                   _unpack_bytes(xmm[jnp.clip(sr, 0, 15), 0],
                                 xmm[jnp.clip(sr, 0, 15), 1]),
                   _unpack_bytes(l1_lo, l1_hi))
    i16u = jnp.arange(16, dtype=jnp.int32)
    eq_b = (ba == bb)
    # word/dword equality via group-reduction of byte equality
    eq_w16 = eq_b[(i16u // 2) * 2] & eq_b[(i16u // 2) * 2 + 1]
    eq_d16 = (eq_b[(i16u // 4) * 4] & eq_b[(i16u // 4) * 4 + 1]
              & eq_b[(i16u // 4) * 4 + 2] & eq_b[(i16u // 4) * 4 + 3])
    pshufd_sel = ((imm >> ((i16u // 4).astype(jnp.uint64) * _u(2))) & _u(3)
                  ).astype(jnp.int32)
    pshufd_idx = pshufd_sel * 4 + (i16u % 4)
    pslldq_n = jnp.minimum(imm, _u(16)).astype(jnp.int32)
    psll_idx = jnp.clip(i16u - pslldq_n, 0, 15)
    psrl_idx = jnp.clip(i16u + pslldq_n, 0, 15)
    # punpckldq: interleave the low dwords -> [a0 b0 a1 b1] (dword units)
    punp_src_b = (i16u // 4) & 1  # odd dword slots come from src
    punp_idx = ((i16u // 8) * 4) + (i16u % 4)
    # pinsrw: word `cond` replaced by the gpr's low word (mem form is
    # oracle-serviced: its 2-byte load doesn't fit the 16-byte window)
    pinsrw_word = _read_reg(gpr, sr, jnp.int32(2))
    pinsrw_byte = jnp.where(i16u % 2 == 0, pinsrw_word & _u(0xFF),
                            (pinsrw_word >> _u(8)) & _u(0xFF)).astype(jnp.uint8)
    sse_bytes = jnp.select(
        [sub == U.SSE_PXOR, sub == U.SSE_XORPS, sub == U.SSE_POR,
         sub == U.SSE_PAND, sub == U.SSE_PANDN,
         sub == U.SSE_PCMPEQB, sub == U.SSE_PCMPEQW, sub == U.SSE_PCMPEQD,
         sub == U.SSE_PSUBB, sub == U.SSE_PADDB, sub == U.SSE_PMINUB,
         sub == U.SSE_PUNPCKLQDQ, sub == U.SSE_PSHUFD,
         sub == U.SSE_PSLLDQ, sub == U.SSE_PSRLDQ,
         sub == U.SSE_PUNPCKLDQ, sub == U.SSE_PINSRW],
        [ba ^ bb, ba ^ bb, ba | bb, ba & bb, (~ba) & bb,
         jnp.where(eq_b, jnp.uint8(0xFF), jnp.uint8(0)),
         jnp.where(eq_w16, jnp.uint8(0xFF), jnp.uint8(0)),
         jnp.where(eq_d16, jnp.uint8(0xFF), jnp.uint8(0)),
         ba - bb, ba + bb, jnp.minimum(ba, bb),
         jnp.where(i16u < 8, ba, bb[jnp.clip(i16u - 8, 0, 15)]),
         bb[pshufd_idx],
         jnp.where(i16u >= pslldq_n, ba[psll_idx], jnp.uint8(0)),
         jnp.where(i16u + pslldq_n < 16, ba[psrl_idx], jnp.uint8(0)),
         jnp.where(punp_src_b == 0, ba[punp_idx], bb[punp_idx]),
         jnp.where(i16u // 2 == cond, pinsrw_byte, ba)],
        default=ba)
    sse_out_lo, sse_out_hi = _pack_pair(sse_bytes)
    # paddq works on the u64 limbs directly (byte-wise adds lose carries)
    is_paddq = is_ssealu & (sub == U.SSE_PADDQ)
    sse_out_lo = jnp.where(is_paddq, x_dst_lo + x_src_lo, sse_out_lo)
    sse_out_hi = jnp.where(is_paddq, x_dst_hi + x_src_hi, sse_out_hi)
    # psllq/psrlq imm: per-qword bit shifts on the limbs (count > 63
    # architecturally zeroes the register)
    shq = jnp.minimum(imm, _u(63))
    shq_zero = imm > _u(63)
    is_psllq = is_ssealu & (sub == U.SSE_PSLLQ_I)
    is_psrlq = is_ssealu & (sub == U.SSE_PSRLQ_I)
    sse_out_lo = jnp.where(
        is_psllq, jnp.where(shq_zero, _u(0), x_dst_lo << shq), sse_out_lo)
    sse_out_hi = jnp.where(
        is_psllq, jnp.where(shq_zero, _u(0), x_dst_hi << shq), sse_out_hi)
    sse_out_lo = jnp.where(
        is_psrlq, jnp.where(shq_zero, _u(0), x_dst_lo >> shq), sse_out_lo)
    sse_out_hi = jnp.where(
        is_psrlq, jnp.where(shq_zero, _u(0), x_dst_hi >> shq), sse_out_hi)
    # pmovmskb: sign bit of each src byte -> gpr bit i
    bsrc_msk = _unpack_bytes(xmm[jnp.clip(sr, 0, 15), 0],
                             xmm[jnp.clip(sr, 0, 15), 1])
    pmov_mask = jnp.sum(
        jnp.where((bsrc_msk & jnp.uint8(0x80)) != 0,
                  _u(1) << i16u.astype(jnp.uint64), _u(0)))
    # pextrw: word `cond` of the src register, zero-extended into the gpr
    pextrw_val = (jnp.where(cond < 4,
                            xmm[jnp.clip(sr, 0, 15), 0],
                            xmm[jnp.clip(sr, 0, 15), 1])
                  >> ((cond & 3).astype(jnp.uint64) * _u(16))) & _u(0xFFFF)
    # ptest
    ptest_zf = ((x_dst_lo & x_src_lo) == _u(0)) & ((x_dst_hi & x_src_hi) == _u(0))
    ptest_cf = (((~x_dst_lo) & x_src_lo) == _u(0)) & (((~x_dst_hi) & x_src_hi) == _u(0))
    ptest_rf = (rf & ~_u(FLAGS_ARITH)) | _mkflags(
        ptest_cf, jnp.bool_(False), jnp.bool_(False), ptest_zf,
        jnp.bool_(False), jnp.bool_(False))

    # -- SSE/SSE2 floating point (OPC_SSEFP), device execution ------------
    # Same semantics as the oracle's _SseFp (emu.py), element-level: NaN
    # payloads preserved and SNaNs quieted at the BIT level (never relying
    # on what NaN the platform's FP unit produces), the dst NaN wins for
    # arithmetic, min/max/cmp forward the second operand on NaN/equality,
    # out-of-range converts produce the integer indefinite.  Normal-range
    # arithmetic rides the platform's f32/f64 units (IEEE bit-exact on the
    # CPU backend — tests/test_step_fp.py pins device == oracle == host
    # CPU); denormal-touching lanes detect themselves and divert to the
    # oracle (see below).  Residual TPU-only caveat: div/sqrt rounding is
    # the platform's — a documented fidelity delta of the fast path,
    # mirroring the bochs-vs-KVM precision split in the reference design.
    fp_elem8 = srcsize0 == 8       # 4 = float32 lanes, 8 = float64 lanes
    _m32 = _u(0xFFFFFFFF)
    fpa32 = jnp.stack([x_dst_lo & _m32, x_dst_lo >> _u(32),
                       x_dst_hi & _m32, x_dst_hi >> _u(32)]).astype(jnp.uint32)
    fpb32 = jnp.stack([x_src_lo & _m32, x_src_lo >> _u(32),
                       x_src_hi & _m32, x_src_hi >> _u(32)]).astype(jnp.uint32)
    fpa64 = jnp.stack([x_dst_lo, x_dst_hi])
    fpb64 = jnp.stack([x_src_lo, x_src_hi])
    fa32 = lax.bitcast_convert_type(fpa32, jnp.float32)
    fb32 = lax.bitcast_convert_type(fpb32, jnp.float32)
    fa64 = lax.bitcast_convert_type(fpa64, jnp.float64)
    fb64 = lax.bitcast_convert_type(fpb64, jnp.float64)

    _QBIT32, _QBIT64 = jnp.uint32(0x00400000), _u(0x0008000000000000)
    _INDEF32, _INDEF64 = jnp.uint32(0xFFC00000), _u(0xFFF8000000000000)

    def _nan32(u):
        return (u & jnp.uint32(0x7FFFFFFF)) > jnp.uint32(0x7F800000)

    def _nan64(u):
        return (u & _u(0x7FFFFFFFFFFFFFFF)) > _u(0x7FF0000000000000)

    def _b32(f):
        return lax.bitcast_convert_type(f, jnp.uint32)

    def _b64(f):
        return lax.bitcast_convert_type(f, jnp.uint64)

    nan_a32, nan_b32 = _nan32(fpa32), _nan32(fpb32)
    nan_a64, nan_b64 = _nan64(fpa64), _nan64(fpb64)

    # Denormals: XLA flushes them (FTZ/DAZ) on both the CPU and TPU
    # backends, where the oracle (numpy on the host thread) honors them.
    # Any lane whose FP op *touches* the denormal range — denormal input,
    # or a result the hardware would flush — is routed to the oracle
    # through the same UNSUPPORTED servicing seam, so the fast path keeps
    # the overwhelming normal-range majority and the rare denormal op
    # stays bit-exact.  Detection is conservative (over-flagging is only
    # a performance event, never a correctness one).
    def _den32(u):
        return ((u & jnp.uint32(0x7F800000)) == 0) \
            & ((u & jnp.uint32(0x7FFFFFFF)) != 0)

    def _den64(u):
        return ((u & _u(0x7FF0000000000000)) == _u(0)) \
            & ((u & _u(0x7FFFFFFFFFFFFFFF)) != _u(0))

    def _fp_elementwise(fa, fb, ua, ub, nan_a, nan_b, bits, qbit, indef,
                        nanf, denf, magmask, expmask):
        """arith/minmax/sqrt/cmp over one lane-width's vector (f32[4]/f64[2]).

        Returns (result_bits, denormal_risk) per lane."""
        r_arith = jnp.select(
            [sub == U.FP_ADD, sub == U.FP_SUB, sub == U.FP_MUL,
             sub == U.FP_DIV],
            [fa + fb, fa - fb, fa * fb, fa / fb], default=fa)
        r_bits = bits(r_arith)
        arith_out = jnp.where(
            nan_a, ua | qbit,
            jnp.where(nan_b, ub | qbit,
                      jnp.where(nanf(r_bits), indef, r_bits)))
        take_a = jnp.where(sub == U.FP_MIN, fa < fb, fa > fb)
        mm_out = jnp.where(nan_a | nan_b | (fa == fb), ub,
                           jnp.where(take_a, ua, ub))
        sq_out = jnp.where(
            nan_b, ub | qbit,
            jnp.where(fb < 0, indef, bits(jnp.sqrt(fb))))
        unord = nan_a | nan_b
        pred = (imm & _u(7)).astype(jnp.int32)
        eq, lt, le = fa == fb, fa < fb, fa <= fb
        cmp_res = jnp.select(
            [pred == 0, pred == 1, pred == 2, pred == 3,
             pred == 4, pred == 5, pred == 6],
            [~unord & eq, ~unord & lt, ~unord & le, unord,
             unord | ~eq, unord | ~lt, unord | ~le],
            default=~unord)
        ones = ~jnp.zeros_like(ua)
        cmp_out = jnp.where(cmp_res, ones, jnp.zeros_like(ua))
        out = jnp.select(
            [(sub >= U.FP_ADD) & (sub <= U.FP_DIV),
             (sub == U.FP_MIN) | (sub == U.FP_MAX),
             sub == U.FP_SQRT],
            [arith_out, mm_out, sq_out], default=cmp_out)
        # FTZ risk: a flushed result reads as +/-0 where the true result
        # was a nonzero denormal; true zeros are exactly the listed cases
        r_zero = (r_bits & magmask) == 0
        true_zero = jnp.select(
            [sub == U.FP_ADD, sub == U.FP_SUB, sub == U.FP_MUL],
            [fa == -fb, fa == fb,
             ((ua & magmask) == 0) | ((ub & magmask) == 0)],
            default=((ua & magmask) == 0) | ((ub & magmask) == expmask))
        ftz = ((sub >= U.FP_ADD) & (sub <= U.FP_DIV)) \
            & r_zero & ~true_zero & ~nan_a & ~nan_b
        den_in = jnp.where(sub == U.FP_SQRT, denf(ub), denf(ua) | denf(ub))
        return out, ftz | den_in

    ew32, ewrisk32 = _fp_elementwise(
        fa32, fb32, fpa32, fpb32, nan_a32, nan_b32, _b32, _QBIT32,
        _INDEF32, _nan32, _den32, jnp.uint32(0x7FFFFFFF),
        jnp.uint32(0x7F800000))
    ew64, ewrisk64 = _fp_elementwise(
        fa64, fb64, fpa64, fpb64, nan_a64, nan_b64, _b64, _QBIT64,
        _INDEF64, _nan64, _den64, _u(0x7FFFFFFFFFFFFFFF),
        _u(0x7FF0000000000000))

    def _limbs32(v32):
        v = v32.astype(jnp.uint64)
        return v[0] | (v[1] << _u(32)), v[2] | (v[3] << _u(32))

    ew_lo32, ew_hi32 = _limbs32(ew32)
    ew_lo = jnp.where(fp_elem8, ew64[0], ew_lo32)
    ew_hi = jnp.where(fp_elem8, ew64[1], ew_hi32)

    fp_is_f2i = (sub == U.FP_CVT_F2I) | (sub == U.FP_CVT_F2I_T)
    fp_is_comi = (sub == U.FP_UCOMI) | (sub == U.FP_COMI)

    # lanes an op actually reads (scalar forms must not flag junk in the
    # upper lanes of the destination register)
    used32 = jnp.where(sext_f == 1, jnp.ones(4, bool),
                       jnp.arange(4) == 0)
    used64 = jnp.where(sext_f == 1, jnp.ones(2, bool),
                       jnp.arange(2) == 0)
    ew_risk = jnp.where(fp_elem8, jnp.any(ewrisk64 & used64),
                        jnp.any(ewrisk32 & used32))
    comi_risk = jnp.where(fp_elem8,
                          _den64(fpa64[0]) | _den64(fpb64[0]),
                          _den32(fpa32[0]) | _den32(fpb32[0]))
    # f2f: s2d flags denormal f32 inputs (DAZ); d2s flags any f64 input
    # small enough that the f32 result lands at/under the normal minimum
    d2s_small = (((fpb64 >> _u(52)) & _u(0x7FF)) <= _u(897)) \
        & ((fpb64 & _u(0x7FFFFFFFFFFFFFFF)) != _u(0))
    f2f_risk = jnp.where(fp_elem8, jnp.any(d2s_small & used64),
                         jnp.any(_den32(fpb32)
                                 & jnp.where(sext_f == 1,
                                             jnp.arange(4) < 2,
                                             jnp.arange(4) == 0)))
    fp_denorm = is_ssefp & jnp.select(
        [fp_is_ew, fp_is_comi, sub == U.FP_CVT_F2F],
        [ew_risk, comi_risk, f2f_risk], default=jnp.bool_(False))

    # ucomis/comis flag image: unordered -> ZF=PF=CF=1; else ZF=(a==b),
    # CF=(a<b), PF=0; OF/AF/SF cleared (oracle set_flags call)
    uc_unord = jnp.where(fp_elem8, nan_a64[0] | nan_b64[0],
                         nan_a32[0] | nan_b32[0])
    uc_eq = jnp.where(fp_elem8, fa64[0] == fb64[0], (fa32[0] == fb32[0]))
    uc_lt = jnp.where(fp_elem8, fa64[0] < fb64[0], (fa32[0] < fb32[0]))
    ucomi_rf = (rf & ~_u(FLAGS_ARITH)) | _mkflags(
        uc_unord | (~uc_unord & uc_lt), uc_unord, jnp.bool_(False),
        uc_unord | (~uc_unord & uc_eq), jnp.bool_(False), jnp.bool_(False))

    # int -> fp scalar (cvtsi2ss/sd): integer comes from a GPR or an
    # opsize-wide memory load, sign-extended, rounded ONCE by the convert
    i2f_raw = jnp.where(sk == U.K_REG, _read_reg(gpr, sr, opsize),
                        l1_lo & _size_mask(opsize))
    i2f_int = _sext(i2f_raw, opsize).astype(jnp.int64)
    i2f_b32 = _b32(i2f_int.astype(jnp.float32)).astype(jnp.uint64)
    i2f_b64 = _b64(i2f_int.astype(jnp.float64))
    i2f_lo = jnp.where(fp_elem8, i2f_b64, i2f_b32)

    # fp -> int (cvt/cvtt to gpr, and the packed PS2DQ/PD2DQ families):
    # rounding/range logic runs in f64 exactly like the oracle's to_int
    # (f32 widens losslessly first), indefinite = 1 << (bits-1)
    def _fp_to_int(v64, int_bits, truncate, src_nan):
        limit = jnp.float64(2.0) ** (int_bits - 1)
        r = jnp.where(truncate, jnp.trunc(v64),
                      lax.round(v64, lax.RoundingMethod.TO_NEAREST_EVEN))
        bad = src_nan | jnp.isnan(v64) | (r >= limit) | (r < -limit)
        indef = _u(1) << jnp.uint64(int_bits - 1)
        safe = jnp.clip(r, -limit, limit - 1)
        return jnp.where(bad, indef,
                         safe.astype(jnp.int64).astype(jnp.uint64)
                         & _size_mask(jnp.int32(int_bits // 8)))

    f2i_src64 = jnp.where(fp_elem8, fb64[0], fb32[0].astype(jnp.float64))
    f2i_nan = jnp.where(fp_elem8, nan_b64[0], nan_b32[0])
    f2i_trunc = sub == U.FP_CVT_F2I_T
    f2i_val = jnp.where(
        opsize >= 8,
        _fp_to_int(f2i_src64, 64, f2i_trunc, f2i_nan),
        _fp_to_int(f2i_src64, 32, f2i_trunc, f2i_nan))

    # f32 <-> f64 converts: NaNs rebuilt at the bit level (payload shifted,
    # quiet bit forced) so the device never depends on platform NaN rules
    def _cvt_s2d(u32v, f32v, isnan):
        sign = (u32v.astype(jnp.uint64) >> _u(31)) << _u(63)
        frac = (u32v.astype(jnp.uint64) & _u(0x7FFFFF)) << _u(29)
        nan_bits = sign | _u(0x7FF0000000000000) | _QBIT64 | frac
        return jnp.where(isnan, nan_bits, _b64(f32v.astype(jnp.float64)))

    def _cvt_d2s(u64v, f64v, isnan):
        sign = (u64v >> _u(63)).astype(jnp.uint32) << jnp.uint32(31)
        frac = ((u64v >> _u(29)) & _u(0x3FFFFF)).astype(jnp.uint32)
        nan_bits = sign | jnp.uint32(0x7F800000) | _QBIT32 | frac
        return jnp.where(isnan, nan_bits, _b32(f64v.astype(jnp.float32)))

    s2d = _cvt_s2d(fpb32, fb32, nan_b32)          # u64[4], lanes 0/1 used
    d2s = _cvt_d2s(fpb64, fb64, nan_b64)          # u32[2]
    f2f_packed_lo4 = s2d[0]                        # cvtps2pd
    f2f_packed_hi4 = s2d[1]
    f2f_packed_lo8 = (d2s[0].astype(jnp.uint64)
                      | (d2s[1].astype(jnp.uint64) << _u(32)))  # cvtpd2ps
    f2f_lo = jnp.where(fp_elem8, f2f_packed_lo8, f2f_packed_lo4)
    f2f_hi = jnp.where(fp_elem8, _u(0), f2f_packed_hi4)

    # packed int <-> fp families (each writes the full register)
    dq2ps = _b32(fpb32.astype(jnp.int32).astype(jnp.float32))
    ps2dq_t = sub == U.FP_CVT_PS2DQ_T
    ps2dq = jnp.stack([
        _fp_to_int(fb32[i].astype(jnp.float64), 32, ps2dq_t, nan_b32[i])
        for i in range(4)]).astype(jnp.uint32)
    dq2pd_lo = _b64(fpb32[0].astype(jnp.int32).astype(jnp.float64))
    dq2pd_hi = _b64(fpb32[1].astype(jnp.int32).astype(jnp.float64))
    pd2dq_t = sub == U.FP_CVT_PD2DQ_T
    pd2dq = jnp.stack([
        _fp_to_int(fb64[i], 32, pd2dq_t, nan_b64[i]) for i in range(2)])
    pd2dq_lo = (pd2dq[0] & _m32) | ((pd2dq[1] & _m32) << _u(32))

    # shufps/shufpd, unpckl/h ps/pd: pure lane shuffles
    shuf_sel = imm
    sh32_src = jnp.concatenate([fpa32, fpb32])    # picks: dst,dst,src,src
    shufps = jnp.stack([
        sh32_src[jnp.where(jnp.int32(i) < 2, jnp.int32(0), jnp.int32(4))
                 + ((shuf_sel >> _u(2 * i)) & _u(3)).astype(jnp.int32)]
        for i in range(4)])
    shufpd_lo = jnp.where((shuf_sel & _u(1)) != 0, x_dst_hi, x_dst_lo)
    shufpd_hi = jnp.where((shuf_sel & _u(2)) != 0, x_src_hi, x_src_lo)
    shufps_lo, shufps_hi = _limbs32(shufps)
    unp_h = sub == U.FP_UNPCKH
    unpck32 = jnp.stack([
        jnp.where(unp_h, fpa32[2], fpa32[0]), jnp.where(unp_h, fpb32[2], fpb32[0]),
        jnp.where(unp_h, fpa32[3], fpa32[1]), jnp.where(unp_h, fpb32[3], fpb32[1])])
    unpck32_lo, unpck32_hi = _limbs32(unpck32)
    unpck64_lo = jnp.where(unp_h, x_dst_hi, x_dst_lo)
    unpck64_hi = jnp.where(unp_h, x_src_hi, x_src_lo)

    fp_sub_sel = [
        sub == U.FP_CVT_I2F,
        sub == U.FP_CVT_F2F,
        sub == U.FP_CVT_DQ2PS,
        (sub == U.FP_CVT_PS2DQ) | (sub == U.FP_CVT_PS2DQ_T),
        sub == U.FP_CVT_DQ2PD,
        (sub == U.FP_CVT_PD2DQ) | (sub == U.FP_CVT_PD2DQ_T),
        sub == U.FP_SHUF,
        (sub == U.FP_UNPCKL) | (sub == U.FP_UNPCKH),
    ]
    dq2ps_lo, dq2ps_hi = _limbs32(dq2ps)
    ps2dq_lo, ps2dq_hi = _limbs32(ps2dq)
    fp_res_lo = jnp.select(fp_sub_sel, [
        i2f_lo, f2f_lo, dq2ps_lo, ps2dq_lo, dq2pd_lo, pd2dq_lo,
        jnp.where(fp_elem8, shufpd_lo, shufps_lo),
        jnp.where(fp_elem8, unpck64_lo, unpck32_lo),
    ], default=ew_lo)
    fp_res_hi = jnp.select(fp_sub_sel, [
        _u(0), f2f_hi, dq2ps_hi, ps2dq_hi, dq2pd_hi, _u(0),
        jnp.where(fp_elem8, shufpd_hi, shufps_hi),
        jnp.where(fp_elem8, unpck64_hi, unpck32_hi),
    ], default=ew_hi)
    # destination write width: 16 = whole register, else low bytes merge
    fp_wsz = jnp.select(
        [sub == U.FP_CVT_I2F,
         sub == U.FP_CVT_F2F,
         fp_is_ew],
        [srcsize0,
         jnp.where(sext_f == 1, jnp.int32(16), 12 - srcsize0),
         jnp.where(sext_f == 1, jnp.int32(16), srcsize0)],
        default=jnp.int32(16))
    fp_wlo_mask = _size_mask(jnp.minimum(fp_wsz, 8))
    fp_out_lo = (x_dst_lo & ~fp_wlo_mask) | (fp_res_lo & fp_wlo_mask)
    fp_out_hi = jnp.where(fp_wsz >= 16, fp_res_hi, x_dst_hi)
    fp_writes_xmm = is_ssefp & ~fp_is_f2i & ~fp_is_comi

    # -- x87 (OPC_X87) device execution -----------------------------------
    # The same f64-value model as the oracle (emu._exec_x87; bit-exact vs
    # hardware under Windows' PC=53 control word): the register stack is
    # fpst[8] physical slots with TOP in fpsw bits 11-13, values are f64
    # bits, and arithmetic rides the same NaN-routing helpers as the SSE
    # block (dst-NaN-wins, quieting, real-indefinite).  FXSAVE-class
    # state movers stay oracle-serviced (x87_oracle above); denormal-
    # touching lanes divert like SSE-FP lanes do.
    fpst_v, fpcw_v, fpsw_v, fptw_v = st.fpst, st.fpcw, st.fpsw, st.fptw
    x_top = (fpsw_v >> _u(11)) & _u(7)
    x_i = imm & _u(7)

    def _xphys(k):
        return ((x_top + k) & _u(7)).astype(jnp.int32)

    x_ph0 = _xphys(_u(0))
    x_phi = _xphys(x_i)
    # one two-row gather instead of two scalar gathers (kernel-count
    # currency: the step wall tracks gather-class kernels, not bytes)
    x_st_pair = fpst_v[jnp.stack([x_ph0, x_phi])]
    st0_b = x_st_pair[0]
    sti_b = x_st_pair[1]
    st0_f = lax.bitcast_convert_type(st0_b, jnp.float64)

    # memory operand -> f64 bits: m64 is a raw bit move, m32 converts
    # with the NaN-safe widening, integers convert exactly like the
    # oracle's int64 -> float64
    xm32_u = (l1_lo & _m32).astype(jnp.uint32)
    xm32_f = lax.bitcast_convert_type(xm32_u, jnp.float32)
    x_mem_b = jnp.where(srcsize0 >= 8, l1_lo,
                        _cvt_s2d(xm32_u, xm32_f, _nan32(xm32_u)))
    x_fild_b = _b64(_sext(l1_lo, srcsize0).astype(jnp.int64)
                    .astype(jnp.float64))

    # arithmetic (ADD/MUL/SUB/SUBR/DIV/DIVR by the encoded digit; COM/
    # COMP digits compare instead)
    x_arith_m = sub == U.X87_ARITH_M
    x_arith_st = sub == U.X87_ARITH_ST
    x_dsti = x_arith_st & (dr == 1)       # DC/DE: st(i) is the destination
    xa_b = jnp.where(x_dsti, sti_b, st0_b)
    xb_b = jnp.where(x_arith_m, x_mem_b,
                     jnp.where(x_dsti, st0_b, sti_b))
    xa_f = lax.bitcast_convert_type(xa_b, jnp.float64)
    xb_f = lax.bitcast_convert_type(xb_b, jnp.float64)
    x_r = jnp.select(
        [cond == U.X87_OP_ADD, cond == U.X87_OP_MUL,
         cond == U.X87_OP_SUB, cond == U.X87_OP_SUBR,
         cond == U.X87_OP_DIV],
        [xa_f + xb_f, xa_f * xb_f, xa_f - xb_f, xb_f - xa_f, xa_f / xb_f],
        default=xb_f / xa_f)  # X87_OP_DIVR
    x_r_b = _b64(x_r)
    nan_xa, nan_xb = _nan64(xa_b), _nan64(xb_b)
    # NaN routing follows the OPERATION's operand order: hardware
    # propagates the first source operand's NaN, and for the reversed
    # forms (fsubr/fdivr: b OP a) that is xb — matching the oracle's
    # `bn - an` / `bn / an`
    x_rev = (cond == U.X87_OP_SUBR) | (cond == U.X87_OP_DIVR)
    x_n1 = jnp.where(x_rev, nan_xb, nan_xa)
    x_n1_b = jnp.where(x_rev, xb_b, xa_b)
    x_n2 = jnp.where(x_rev, nan_xa, nan_xb)
    x_n2_b = jnp.where(x_rev, xa_b, xb_b)
    x_arith_out = jnp.where(
        x_n1, x_n1_b | _QBIT64,
        jnp.where(x_n2, x_n2_b | _QBIT64,
                  jnp.where(_nan64(x_r_b), _INDEF64, x_r_b)))
    x_is_com_digit = (cond == U.X87_OP_COM) | (cond == U.X87_OP_COMP)
    x_arith_writes = (x_arith_m | x_arith_st) & ~x_is_com_digit

    # compares: fcom/fucom (C3/C2/C0 in the status word), fcomi/fucomi
    # (ZF/PF/CF in rflags) — same unordered rules as ucomis
    x_cmp_b = jnp.where(x_arith_m, x_mem_b, sti_b)
    x_cmp_bf = lax.bitcast_convert_type(x_cmp_b, jnp.float64)
    x_unord = _nan64(st0_b) | _nan64(x_cmp_b)
    x_eq = st0_f == x_cmp_bf
    x_lt = st0_f < x_cmp_bf
    x87_comi_rf = (rf & ~_u(FLAGS_ARITH)) | _mkflags(
        x_unord | (~x_unord & x_lt), x_unord, jnp.bool_(False),
        x_unord | (~x_unord & x_eq), jnp.bool_(False), jnp.bool_(False))
    x_com_bits = (jnp.where(x_unord | (~x_unord & x_eq), _u(0x4000), _u(0))
                  | jnp.where(x_unord, _u(0x400), _u(0))
                  | jnp.where(x_unord | (~x_unord & x_lt), _u(0x100), _u(0)))
    x_is_com = is_x87 & ((sub == U.X87_COM)
                         | ((x_arith_m | x_arith_st) & x_is_com_digit))

    # fist(p)/fisttp: fpcw.RC rounding (fisttp always chops), integer
    # indefinite on NaN/overflow — the oracle's _exec_x87 FIST logic
    x_rc = jnp.where(sub == U.X87_FIST_T, _u(3), (fpcw_v >> _u(10)) & _u(3))
    x_bits_n = srcsize0 * 8
    x_limit = jnp.exp2((x_bits_n - 1).astype(jnp.float64))
    x_round = jnp.select(
        [x_rc == _u(0), x_rc == _u(1), x_rc == _u(2)],
        [lax.round(st0_f, lax.RoundingMethod.TO_NEAREST_EVEN),
         jnp.floor(st0_f), jnp.ceil(st0_f)],
        default=jnp.trunc(st0_f))
    x_fist_bad = _nan64(st0_b) | (x_round >= x_limit) | (x_round < -x_limit)
    x_fist_safe = jnp.clip(x_round, -x_limit, x_limit - 1)
    x_fist_val = jnp.where(
        x_fist_bad,
        _shl(_u(1), (x_bits_n - 1).astype(jnp.uint64)),
        x_fist_safe.astype(jnp.int64).astype(jnp.uint64)
        ) & _size_mask(srcsize0)

    # fst m32: NaN-safe narrowing of st0
    x_fst32 = _cvt_d2s(st0_b, st0_f, _nan64(st0_b)).astype(jnp.uint64)
    x87_store_val = jnp.select(
        [sub == U.X87_FST_M,
         (sub == U.X87_FIST) | (sub == U.X87_FIST_T),
         sub == U.X87_FNSTCW,
         sub == U.X87_FNSTSW_M],
        [jnp.where(srcsize0 >= 8, st0_b, x_fst32),
         x_fist_val, fpcw_v & _u(0xFFFF), fpsw_v & _u(0xFFFF)],
        default=st.mxcsr & _u(0xFFFFFFFF))  # STMXCSR

    # pushes
    x_is_push = is_x87 & (
        (sub == U.X87_FLD_M) | (sub == U.X87_FILD)
        | (sub == U.X87_FLD_STI) | (sub == U.X87_FLD_CONST))
    x_push_b = jnp.select(
        [sub == U.X87_FLD_M, sub == U.X87_FILD, sub == U.X87_FLD_STI],
        [x_mem_b, x_fild_b, sti_b],
        default=jnp.where(imm == _u(0), _u(0x3FF0000000000000), _u(0)))
    x_push_slot = ((x_top - _u(1)) & _u(7)).astype(jnp.int32)

    # register-stack writes: one generic write + the FXCH partner write
    x_fxch = sub == U.X87_FXCH
    x_w1_en = is_x87 & (
        x_is_push | x_arith_writes | (sub == U.X87_FST_STI)
        | (sub == U.X87_FCHS) | (sub == U.X87_FABS) | x_fxch)
    x_w1_idx = jnp.select(
        [x_is_push, x_arith_writes & x_dsti, sub == U.X87_FST_STI],
        [x_push_slot, x_phi, x_phi], default=x_ph0)
    x_w1_val = jnp.select(
        [x_is_push, x_arith_writes, sub == U.X87_FST_STI,
         sub == U.X87_FCHS, sub == U.X87_FABS],
        [x_push_b, x_arith_out, st0_b,
         st0_b ^ _u(1 << 63), st0_b & _u((1 << 63) - 1)],
        default=sti_b)  # FXCH: st0 <- st(i)

    # stack top / tag word / control+status words
    x_pops = jnp.where(is_x87, sext_f, jnp.int32(0))
    x_fninit = sub == U.X87_FNINIT
    x_new_top = jnp.where(
        x_fninit, _u(0),
        jnp.where(x_is_push, (x_top - _u(1)) & _u(7),
                  (x_top + x_pops.astype(jnp.uint64)) & _u(7)))

    def _tag_set(tw, phys_i32, val):
        sh = phys_i32.astype(jnp.uint64) * _u(2)
        return (tw & ~(_u(3) << sh)) | (_u(val) << sh)

    x_tw = fptw_v
    x_tw = jnp.where(x_is_push, _tag_set(x_tw, x_push_slot, 0), x_tw)
    x_tw = jnp.where(is_x87 & (sub == U.X87_FST_STI),
                     _tag_set(x_tw, x_phi, 0), x_tw)
    x_tw = jnp.where(is_x87 & (x_pops >= 1), _tag_set(x_tw, x_ph0, 3), x_tw)
    x_tw = jnp.where(is_x87 & (x_pops >= 2),
                     _tag_set(x_tw, _xphys(_u(1)), 3), x_tw)
    x_tw = jnp.where(is_x87 & (sub == U.X87_FFREE),
                     _tag_set(x_tw, x_phi, 3), x_tw)
    x_tw = jnp.where(is_x87 & (x_fninit | (sub == U.X87_EMMS)),
                     _u(0xFFFF), x_tw)

    x_cw = jnp.where(is_x87 & (sub == U.X87_FLDCW), l1_lo & _u(0xFFFF),
                     jnp.where(is_x87 & x_fninit, _u(0x37F), fpcw_v))
    x_sw = fpsw_v
    x_sw = jnp.where(x_is_com, (x_sw & ~_u(0x4500)) | x_com_bits, x_sw)
    x_sw = jnp.where(is_x87 & (sub == U.X87_FNCLEX), x_sw & ~_u(0x80FF), x_sw)
    x_sw = jnp.where(is_x87 & x_fninit, _u(0), x_sw)
    x_sw = jnp.where(is_x87,
                     (x_sw & ~_u(0x3800)) | (x_new_top << _u(11)), x_sw)

    # denormal / FTZ risk -> oracle divert, same policy as the SSE block
    x_r_zero = (x_r_b & _u(0x7FFFFFFFFFFFFFFF)) == _u(0)
    x_true_zero = jnp.select(
        [cond == U.X87_OP_ADD,
         (cond == U.X87_OP_SUB) | (cond == U.X87_OP_SUBR),
         cond == U.X87_OP_MUL],
        [xa_f == -xb_f, xa_f == xb_f,
         ((xa_b & _u(0x7FFFFFFFFFFFFFFF)) == _u(0))
         | ((xb_b & _u(0x7FFFFFFFFFFFFFFF)) == _u(0))],
        default=((jnp.where(cond == U.X87_OP_DIV, xa_b, xb_b)
                  & _u(0x7FFFFFFFFFFFFFFF)) == _u(0))
        | ((jnp.where(cond == U.X87_OP_DIV, xb_b, xa_b)
            & _u(0x7FFFFFFFFFFFFFFF)) == _u(0x7FF0000000000000)))
    x_ftz = (x_arith_m | x_arith_st) & ~x_is_com_digit \
        & x_r_zero & ~x_true_zero & ~nan_xa & ~nan_xb
    # an m32 arith operand needs the f32-level denormal check: DAZ in
    # the widening flushes it before _den64 could ever see it (a
    # converted f32 denormal is a NORMAL f64)
    x_den_arith = (x_arith_m | x_arith_st) & (
        _den64(xa_b) | _den64(xb_b)
        | (x_arith_m & (srcsize0 < 8) & _den32(xm32_u)))
    x_fst32_small = (((st0_b >> _u(52)) & _u(0x7FF)) <= _u(897)) \
        & ((st0_b & _u(0x7FFFFFFFFFFFFFFF)) != _u(0))
    x87_denorm = is_x87 & ~x87_oracle & jnp.select(
        [x_arith_m | x_arith_st,
         (sub == U.X87_FLD_M) & (srcsize0 < 8),
         (sub == U.X87_FST_M) & (srcsize0 < 8),
         (sub == U.X87_FIST) | (sub == U.X87_FIST_T),
         (sub == U.X87_COM) | (sub == U.X87_COMI)],
        [x_ftz | x_den_arith,
         _den32(xm32_u),
         x_fst32_small,
         _den64(st0_b),
         _den64(st0_b) | _den64(x_cmp_b)],
        default=jnp.bool_(False))

    # -- 5. result routing -------------------------------------------------
    cc01 = jnp.where(cc_true, _u(1), _u(0))
    is_mul = is_(U.OPC_MUL)
    is_swapgs = is_(U.OPC_RDGSBASE) & (sub == 4)
    i0, i1_, i2_, i4_, i5_, i11_ = (jnp.int32(0), jnp.int32(1), jnp.int32(2),
                                    jnp.int32(4), jnp.int32(5), jnp.int32(11))

    # primary register write (the generic `store_dst` reg case of emu.py).
    # Ported-class values (MOV/LEA/ALU/UNARY/SETCC/CMOVCC) were computed
    # on u32 limbs above and enter this chain as free bitcasts — one
    # shared register-file scatter for hot and cold classes alike.
    w1_cond = opc_list([
        (is_(U.OPC_MOV), dk == U.K_REG),
        (is_(U.OPC_LEA), jnp.bool_(True)),
        (is_(U.OPC_ALU), alu_writes & (dk == U.K_REG)),
        (is_(U.OPC_SHIFT), sh_writes & (dk == U.K_REG)),
        (is_(U.OPC_UNARY), dk == U.K_REG),
        (is_mul, jnp.bool_(True)),
        (is_(U.OPC_DIV), jnp.bool_(True)),
        (is_pop, dk == U.K_REG),
        (is_(U.OPC_SETCC), dk == U.K_REG),
        (is_(U.OPC_CMOVCC), jnp.bool_(True)),
        (is_(U.OPC_BT), bt_writes & (dk == U.K_REG)),
        (is_(U.OPC_BITSCAN), bs_writes),
        (is_(U.OPC_CONVERT), jnp.bool_(True)),
        (is_(U.OPC_FLAGOP), sub == U.FL_LAHF),
        (is_(U.OPC_BSWAP), jnp.bool_(True)),
        (is_(U.OPC_CMPXCHG), dk == U.K_REG),
        (is_(U.OPC_XADD), dk == U.K_REG),
        (is_leave | is_enter, jnp.bool_(True)),
        (is_(U.OPC_RDTSC), jnp.bool_(True)),
        (is_(U.OPC_RDRAND), jnp.bool_(True)),
        (is_(U.OPC_XGETBV), jnp.bool_(True)),
        (is_string, s_lods & ~rep_skip),
        (is_(U.OPC_SYSCALL), syscall_entry),
        (is_(U.OPC_MOVCR), ~movcr_is_write),
        (is_(U.OPC_XCHG), dk == U.K_REG),
        (is_ssemov, (sub == 2) & (dk == U.K_REG)),
        (is_ssealu, (sub == U.SSE_PMOVMSKB) | (sub == U.SSE_PEXTRW)),
        (is_ssefp, fp_is_f2i),
        (is_x87, sub == U.X87_FNSTSW_AX),
        (is_(U.OPC_PEXT), jnp.bool_(True)),
        (is_(U.OPC_MSR), sub == 0),   # rdmsr -> eax
        (is_(U.OPC_RDGSBASE), (sub == 0) | (sub == 1)),  # rd{fs,gs}base
    ], jnp.bool_(False))
    w1_idx = opc_list([
        (is_mul, jnp.where(is_mul2, dr, i0)),
        (is_(U.OPC_DIV) | is_(U.OPC_MSR), i0),
        (is_(U.OPC_CONVERT), jnp.where(sub == 0, i0, i2_)),
        (is_(U.OPC_FLAGOP), jnp.int32(U.REG_AH_BASE)),
        (is_leave | is_enter, i5_),
        (is_(U.OPC_RDTSC) | is_(U.OPC_XGETBV), i0),
        (is_string, i0),
        (is_(U.OPC_SYSCALL), i11_),
    ], dr)
    w1_val = opc_list([
        (is_(U.OPC_MOV), src_val),
        (is_(U.OPC_LEA), ea),
        (is_(U.OPC_ALU), alu_r),
        (is_(U.OPC_SHIFT), sh_r),
        (is_(U.OPC_UNARY), un_r),
        (is_mul, L.to_u64(mul_r1_l)),
        (is_(U.OPC_DIV), div_q),
        (is_pop, l1_lo & opmask),
        (is_(U.OPC_SETCC), cc01),
        (is_(U.OPC_CMOVCC), jnp.where(cc_true, src_val, dst_val)),
        (is_(U.OPC_BT), bt_r),
        (is_(U.OPC_BITSCAN), bs_r),
        (is_(U.OPC_CONVERT), jnp.where(sub == 0, cvt_widen, cvt_sign)),
        (is_(U.OPC_FLAGOP), lahf_val),
        (is_(U.OPC_BSWAP), bsw_r),
        (is_(U.OPC_CMPXCHG), cx_store),
        (is_(U.OPC_XADD), xadd_r),
        (is_leave, l1_lo),
        (is_enter, rsp - _u(8)),   # rbp = frame pointer
        (is_(U.OPC_RDTSC), tsc_now & _u(0xFFFFFFFF)),
        (is_(U.OPC_RDRAND), rdrand_next & opmask),
        (is_(U.OPC_XGETBV), _u(7)),
        (is_string, l1_lo & opmask),
        (is_(U.OPC_SYSCALL), rf & ~_u(0x10000)),
        (is_(U.OPC_MOVCR), cr_read),
        (is_(U.OPC_XCHG), src_val),
        (is_ssemov, xmm[jnp.clip(sr, 0, 15), 0]),
        (is_ssealu, jnp.where(sub == U.SSE_PEXTRW, pextrw_val, pmov_mask)),
        (is_ssefp, f2i_val),
        (is_x87, fpsw_v & _u(0xFFFF)),
        (is_(U.OPC_PEXT), bmi_res),
        (is_(U.OPC_MSR), msr_rval & _u(0xFFFFFFFF)),
        (is_(U.OPC_RDGSBASE),
         jnp.where(sub == 0, st.fs_base, st.gs_base)),
    ], _u(0))
    w1_size = opc_list([
        (is_mul, jnp.where(is_mul2, opsize,
                           jnp.where(opsize == 1, jnp.int32(2), opsize))),
        (is_(U.OPC_FLAGOP), jnp.int32(1)),
        (is_leave | is_enter | is_(U.OPC_RDTSC) | is_(U.OPC_SYSCALL)
         | is_(U.OPC_MOVCR) | is_(U.OPC_MSR), jnp.int32(8)),
        (is_(U.OPC_XGETBV) | is_ssealu, jnp.int32(4)),
        (is_x87, jnp.int32(2)),  # fnstsw ax
    ], opsize)

    # secondary register write
    w2_cond = opc_list([
        (is_(U.OPC_XCHG), sk == U.K_REG),
        (is_mul, ~is_mul2 & (opsize > 1)),
        (is_(U.OPC_DIV), jnp.bool_(True)),
        (is_(U.OPC_CMPXCHG), ~cx_eq),
        (is_(U.OPC_XADD), jnp.bool_(True)),
        (is_(U.OPC_RDTSC) | is_(U.OPC_XGETBV), jnp.bool_(True)),
        (is_(U.OPC_SYSCALL), syscall_entry),
        (is_(U.OPC_MSR), sub == 0),   # rdmsr -> edx
    ], jnp.bool_(False))
    w2_idx = opc_list([
        (is_(U.OPC_XCHG) | is_(U.OPC_XADD), sr),
        (is_(U.OPC_DIV), jnp.where(opsize == 1,
                                   jnp.int32(U.REG_AH_BASE), i2_)),
        (is_(U.OPC_CMPXCHG), i0),
        (is_(U.OPC_SYSCALL), i1_),
    ], i2_)
    w2_val = opc_list([
        (is_(U.OPC_XCHG) | is_(U.OPC_XADD) | is_(U.OPC_CMPXCHG), dst_val),
        (is_mul, L.to_u64(mul_r2_l)),
        (is_(U.OPC_DIV), div_rem),
        (is_(U.OPC_RDTSC), tsc_now >> _u(32)),
        (is_(U.OPC_XGETBV), _u(0)),
        (is_(U.OPC_SYSCALL), next_rip),
        (is_(U.OPC_MSR), msr_rval >> _u(32)),
    ], _u(0))
    w2_size = opc_list([
        (is_(U.OPC_DIV), jnp.where(opsize == 1, jnp.int32(1), opsize)),
        (is_(U.OPC_RDTSC) | is_(U.OPC_SYSCALL) | is_(U.OPC_MSR),
         jnp.int32(8)),
        (is_(U.OPC_XGETBV), jnp.int32(4)),
    ], opsize)

    # rsp adjustment (push_size computed with the store span, section 4b)
    w3_cond = (is_push | is_pushf | is_call | is_pop | is_popf | is_ret
               | is_leave | is_enter)
    w3_val = opc_list([
        (is_push | is_pushf | is_call, rsp - push_size.astype(jnp.uint64)),
        (is_pop, rsp + opsize.astype(jnp.uint64)),
        (is_popf, rsp + _u(8)),
        (is_ret, rsp + _u(8) + imm),
        (is_leave, rbp + _u(8)),
        (is_enter, rsp - _u(8) - imm),  # push rbp then alloc imm bytes
    ], rsp)

    # string pointer/count updates
    w4_cond = (s_movs | s_lods | s_cmps) & ~rep_skip   # rsi
    w5_cond = (s_movs | s_stos | s_scas | s_cmps) & ~rep_skip  # rdi
    w6_cond = rep_on & ~rep_skip                        # rcx

    # -- memory store ------------------------------------------------------
    mem_class_writes = opc_list([
        (is_(U.OPC_MOV), jnp.bool_(True)),
        (is_(U.OPC_ALU), alu_writes),
        (is_(U.OPC_SHIFT), sh_writes),
        (is_(U.OPC_UNARY) | is_(U.OPC_SETCC) | is_(U.OPC_CMPXCHG)
         | is_(U.OPC_XADD) | is_pop | is_(U.OPC_XCHG) | is_ssemov,
         jnp.bool_(True)),
        (is_(U.OPC_BT), bt_writes),
    ], jnp.bool_(False))
    st_need = live & ~unsupported & ~rep_skip & (
        ((dk == U.K_MEM) & mem_class_writes)
        | is_push | is_pushf | is_call | is_enter
        | s_movs | s_stos | x87_store)
    st_lo = opc_list([
        (is_(U.OPC_MOV) | is_push, src_val),
        (is_(U.OPC_ALU), alu_r),
        (is_(U.OPC_SHIFT), sh_r),
        (is_(U.OPC_UNARY), un_r),
        (is_(U.OPC_SETCC), cc01),
        (is_(U.OPC_BT), bt_r),
        (is_(U.OPC_CMPXCHG), cx_store),
        (is_(U.OPC_XADD), xadd_r),
        (is_pop, l1_lo & opmask),
        (is_(U.OPC_XCHG), src_val),
        (is_call, next_rip),
        (is_pushf, rf | _u(0x2)),
        (is_enter, rbp),
        (s_stos, rax_op),
        (s_movs, l1_lo),
        # movhps-store (sub 5) writes the HIGH xmm limb; everything else
        # in the class stores from the low limb
        (is_ssemov, jnp.where(sub == 5, xmm[jnp.clip(sr, 0, 15), 1],
                              xmm[jnp.clip(sr, 0, 15), 0])),
        (is_x87, x87_store_val),
    ], _u(0))
    st_hi = jnp.where(is_ssemov, xmm[jnp.clip(sr, 0, 15), 1],
                      jnp.where(s_movs, l1_hi, _u(0)))

    # store translations (ts0/ts1) come from the step's single batched walk
    store_fault = st_need & ~(ts0.ok & ts1.ok & ts0.writable & ts1.writable)

    page_fault = live & ~unsupported & ~is_crash & (fault1 | fault2 | store_fault)
    fp_oracle = live & ~unsupported & ~page_fault & (fp_denorm | x87_denorm)
    commit_pre = live & ~unsupported & ~is_crash & ~de & ~page_fault \
        & ~fp_oracle

    overlay, store_ok = store_window3(image, overlay, ts0, ts1, st_size,
                                      st_lo, st_hi, st_need & commit_pre)
    ovf = st_need & commit_pre & ~store_ok
    commit = commit_pre & ~ovf

    # -- register file application (order: rsp/rsi/rdi/rcx, aux, primary) --
    new_gpr = gpr
    new_gpr = new_gpr.at[4].set(jnp.where(commit & w3_cond, w3_val, new_gpr[4]))
    new_gpr = new_gpr.at[6].set(jnp.where(commit & w4_cond,
                                          rsi + str_delta, new_gpr[6]))
    new_gpr = new_gpr.at[7].set(jnp.where(commit & w5_cond,
                                          rdi + str_delta, new_gpr[7]))
    new_gpr = new_gpr.at[1].set(jnp.where(commit & w6_cond,
                                          rcx_dec, new_gpr[1]))
    new_gpr = _gpr_write(new_gpr, commit & w2_cond, w2_idx, w2_val, w2_size)
    new_gpr = _gpr_write(new_gpr, commit & w1_cond, w1_idx, w1_val, w1_size)
    # CPUID writes all four GPRs (32-bit, zero-extending), one more than
    # the generic two-write router carries (oracle: emu.py OPC_CPUID)
    cpw = commit & is_(U.OPC_CPUID)
    for ridx, col in ((0, 0), (3, 1), (1, 2), (2, 3)):  # eax, ebx, ecx, edx
        new_gpr = new_gpr.at[ridx].set(
            jnp.where(cpw, cpuid_out[col], new_gpr[ridx]))

    # all writes (hot values entered the chains as bitcasts) land through
    # the one shared u64 scatter; the limb file is a free bitcast back
    glimb_out = L.unpack_u64(new_gpr)

    # -- rflags ------------------------------------------------------------
    # Ported classes (ALU/UNARY) produce a u32 low-limb image; everything
    # else rides the u64 chain and splits at the seam below.
    rf_exec = opc_list([
        (is_(U.OPC_BT), bt_rf),
        (is_(U.OPC_BITSCAN), bs_rf),
        (is_string, jnp.where((s_scas | s_cmps) & ~rep_skip, str_rf, rf)),
        (is_(U.OPC_CMPXCHG), cx_rf),
        (is_(U.OPC_XADD), xadd_rf),
        (is_(U.OPC_RDRAND), rdrand_rf),
        (is_(U.OPC_FLAGOP), flagop_rf),
        (is_popf, popf_rf),
        (is_(U.OPC_SYSCALL), jnp.where(syscall_entry, syscall_rf, sysret_rf)),
        (is_ssealu & (sub == U.SSE_PTEST), ptest_rf),
        (is_ssefp & fp_is_comi, ucomi_rf),
        (is_x87 & (sub == U.X87_COMI), x87_comi_rf),
        (is_(U.OPC_PEXT), bmi_rf),
    ], rf)
    hot_rf = (is_(U.OPC_ALU) | is_(U.OPC_UNARY) | is_(U.OPC_SHIFT)
              | is_mul)
    rf_cold_lo, rf_cold_hi = L.pair(rf_exec)
    rf_exec_lo = jnp.where(
        hot_rf,
        L.sel([is_(U.OPC_ALU), is_(U.OPC_UNARY), is_(U.OPC_SHIFT)],
              [alu_rf_lo, un_rf_lo, sh_rf_lo], mul_rf_lo),
        rf_cold_lo)
    new_rf_lo = jnp.where(commit, rf_exec_lo | jnp.uint32(0x2), rf_lo)
    # hot classes never touch bits 32+ (arith flags live in the low limb)
    new_rf_hi = jnp.where(commit & ~hot_rf, rf_cold_hi, rf_hi)

    # -- rip ---------------------------------------------------------------
    # ported: fallthrough and Jcc targets come from the limb adder
    jcc_rip_l = L.where64(cc_true, jcc_target_l, next_rip_l)
    rip_exec = opc_list([
        (is_(U.OPC_JMP) | is_call, jmp_target),
        (is_(U.OPC_JCC), L.to_u64(jcc_rip_l)),
        (is_ret, ret_target),
        (is_(U.OPC_SYSCALL), jnp.where(syscall_entry, st.lstar, gpr[1])),
        (is_string, jnp.where(str_done, next_rip, rip)),
    ], next_rip)
    new_rip = jnp.where(commit, rip_exec, rip)

    # -- control registers / gs bases -------------------------------------
    cr_w = commit & movcr_is_write
    new_cr0 = jnp.where(cr_w & (sub == 0), cr_wval, st.cr0)
    new_cr3 = jnp.where(cr_w & (sub == 3), cr_wval, st.cr3)
    new_cr4 = jnp.where(cr_w & (sub == 4), cr_wval, st.cr4)
    new_cr8 = jnp.where(cr_w & (sub == 8), cr_wval, st.cr8)
    cr3_changed = cr_w & (sub == 3) & (cr_wval != st.cr3_base)
    sw = commit & is_swapgs
    new_gs = jnp.where(sw, st.kernel_gs_base, st.gs_base)
    new_kgs = jnp.where(sw, st.gs_base, st.kernel_gs_base)

    # wrfsbase/wrgsbase (r32 forms zero-extend via the masked reg read)
    fsgs_val = _read_reg(gpr, dr, opsize)
    fsgsw = commit & is_(U.OPC_RDGSBASE)
    new_gs = jnp.where(fsgsw & (sub == 3), fsgs_val, new_gs)
    fs_pre = jnp.where(fsgsw & (sub == 2), fsgs_val, st.fs_base)

    # wrmsr state writes, driven by the same MSR_ATTR map (tsc keeps
    # rdtsc = tsc_base + icount coherent, same adjustment as the oracle);
    # gs/fs bases chain after the swapgs/wrfsbase values
    msrw = commit & is_(U.OPC_MSR) & (sub == 1)
    _msr_state = {"gs_base": new_gs, "kernel_gs_base": new_kgs,
                  "fs_base": fs_pre}
    for _mid, _attr in MSR_ATTR.items():
        base = _msr_state.get(_attr, getattr(st, _attr))
        val = msr_wval - st.icount if _attr == "tsc" else msr_wval
        _msr_state[_attr] = jnp.where(msrw & (msr_id == _u(_mid)), val, base)
    new_lstar = _msr_state["lstar"]
    new_star = _msr_state["star"]
    new_sfmask = _msr_state["sfmask"]
    new_efer = _msr_state["efer"]
    new_tsc = _msr_state["tsc"]
    new_fs = _msr_state["fs_base"]
    new_gs = _msr_state["gs_base"]
    new_kgs = _msr_state["kernel_gs_base"]

    # -- CS/SS selectors (CPL tracking for host exception delivery) -------
    # SYSCALL loads CPL-0 selectors from IA32_STAR[47:32]; SYSRET the CPL-3
    # pair from IA32_STAR[63:48] (SDM).  iretq restores them on the oracle.
    sysc = commit & is_(U.OPC_SYSCALL)
    star_k = (st.star >> _u(32)) & _u(0xFFFC)
    star_u = (st.star >> _u(48)) & _u(0xFFFF)
    new_cs = jnp.where(
        sysc, jnp.where(syscall_entry, star_k, (star_u + _u(16)) | _u(3)),
        st.cs)
    new_ss = jnp.where(
        sysc, jnp.where(syscall_entry, star_k + _u(8), (star_u + _u(8)) | _u(3)),
        st.ss)

    # -- xmm ---------------------------------------------------------------
    wx_cond = commit & (
        (is_ssemov & (sub != 2) & (dk == U.K_XMM))
        | (is_ssealu & (sub != U.SSE_PMOVMSKB) & (sub != U.SSE_PTEST)
           & (sub != U.SSE_PEXTRW))
        | fp_writes_xmm)
    wx_lo = jnp.where(is_ssefp, fp_out_lo,
                      jnp.where(is_ssealu, sse_out_lo, ssm_lo))
    wx_hi = jnp.where(is_ssefp, fp_out_hi,
                      jnp.where(is_ssealu, sse_out_hi, ssm_hi))
    xr = jnp.clip(dr, 0, 15)
    # limbs 0-1 only: upper YMM halves (limbs 2-3) are carried state the
    # legacy-SSE subset never computes on (AVX snapshots round-trip;
    # reference CpuState_t holds 32xZMM, globals.h:1020-1159)
    new_xmm = xmm.at[xr, 0].set(jnp.where(wx_cond, wx_lo, xmm[xr, 0]))
    new_xmm = new_xmm.at[xr, 1].set(jnp.where(wx_cond, wx_hi, new_xmm[xr, 1]))
    # vzeroall (sub 0) zeroes the whole file; vzeroupper (sub 1) the
    # upper halves only — whole-file writes, no dst register
    vz = commit & is_(U.OPC_VZEROALL)
    vz_limb = jnp.where(vz & (sub == 0), jnp.arange(4) >= 0,
                        jnp.where(vz, jnp.arange(4) >= 2,
                                  jnp.zeros(4, bool)))
    new_xmm = jnp.where(vz_limb[None, :], _u(0), new_xmm)

    # -- x87 state application --------------------------------------------
    x87c = commit & is_x87
    new_fpst = fpst_v.at[x_w1_idx].set(
        jnp.where(x87c & x_w1_en, x_w1_val, fpst_v[x_w1_idx]))
    new_fpst = new_fpst.at[x_phi].set(
        jnp.where(x87c & x_fxch, st0_b, new_fpst[x_phi]))
    new_fpcw = jnp.where(x87c, x_cw, fpcw_v)
    new_fpsw = jnp.where(x87c, x_sw, fpsw_v)
    new_fptw = jnp.where(x87c, x_tw, fptw_v)
    new_mxcsr = jnp.where(x87c & (sub == U.X87_LDMXCSR),
                          l1_lo & _u(0xFFFFFFFF), st.mxcsr)

    # -- bookkeeping -------------------------------------------------------
    new_icount = st.icount + jnp.where(commit, _u(1), _u(0))
    # device-side telemetry block (machine.CTR_* order): accumulated
    # in-graph every step, folded into host metrics once per burst — the
    # per-step host sync this exists to avoid.  page_fault/miss already
    # imply `enabled`, commit implies `live`.  CTR_FUSED stays untouched
    # here: only the fused Pallas kernel (interp/pstep.py) retires into it.
    _f = jnp.bool_(False)
    new_ctr = st.ctr + jnp.stack(
        [commit, page_fault, miss, _f, _f, _f]).astype(jnp.uint32)
    timed = commit & (limit > _u(0)) & (new_icount >= limit)
    new_rdrand = jnp.where(commit & is_(U.OPC_RDRAND), rdrand_next, st.rdrand)
    new_bp_skip = jnp.where(commit, jnp.int32(0), st.bp_skip)

    # coverage: the instruction was reached (reference records RIP in
    # before_execution even when the insn then faults, bochscpu:479-505)
    cov_set = live
    wi = idxc >> 5
    cov_bit = jnp.where(cov_set,
                        jnp.uint32(1) << (idxc & 31).astype(jnp.uint32),
                        jnp.uint32(0))
    new_cov = st.cov.at[wi].set(st.cov[wi] | cov_bit)

    # edges: taken AND not-taken control transfers (reference registers
    # cnear_branch_taken/not_taken + ucnear hooks, bochscpu:235-257)
    is_branch = is_(U.OPC_JMP) | is_(U.OPC_JCC) | is_call | is_ret
    eh = _mix64(rip) ^ rip_exec
    ebits = st.edge.shape[0] * 32
    ei = (eh & _u(ebits - 1)).astype(jnp.int32)
    edge_bit = jnp.where(commit & is_branch,
                         jnp.uint32(1) << (ei & 31).astype(jnp.uint32),
                         jnp.uint32(0))
    new_edge = st.edge.at[ei >> 5].set(st.edge[ei >> 5] | edge_bit)

    # -- status ------------------------------------------------------------
    S = StatusCode
    status_chain = jnp.select(
        [miss, at_bp, smc, unsupported, page_fault, fp_oracle, de, is_crash,
         ovf, cr3_changed, timed],
        [jnp.int32(int(S.NEED_DECODE)), jnp.int32(int(S.BREAKPOINT)),
         jnp.int32(int(S.SMC)), jnp.int32(int(S.UNSUPPORTED)),
         jnp.int32(int(S.PAGE_FAULT)), jnp.int32(int(S.UNSUPPORTED)),
         jnp.int32(int(S.DIVIDE_ERROR)),
         jnp.int32(int(S.CRASH)), jnp.int32(int(S.OVERLAY_FULL)),
         jnp.int32(int(S.CR3_CHANGE)), jnp.int32(int(S.TIMEDOUT))],
        default=jnp.int32(int(S.RUNNING)))
    new_status = jnp.where(enabled, status_chain, st.status)

    # faulting address: when the access's first page translates but the
    # access straddles into a bad page, the faulting byte is at the next
    # page boundary (the oracle's per-page walk reports it there)
    def _fault_at(addr, first_ok):
        return jnp.where(first_ok, (addr & ~_u(0xFFF)) + _u(0x1000), addr)

    st_first_ok = ts0.ok & ts0.writable
    new_fault_gva = jnp.where(
        enabled & page_fault,
        jnp.where(fault1, _fault_at(l1_addr, l1t0.ok),
                  jnp.where(fault2, _fault_at(l2_addr, l2t0.ok),
                            _fault_at(st_addr, st_first_ok))),
        jnp.where(enabled & is_crash, rip, st.fault_gva))
    new_fault_write = jnp.where(
        enabled & page_fault & ~fault1 & ~fault2, jnp.int32(1),
        jnp.where(enabled & page_fault, jnp.int32(0), st.fault_write))

    return st._replace(
        gpr_l=glimb_out,
        rip_l=L.unpack_u64(new_rip),
        rflags_l=jnp.stack([new_rf_lo, new_rf_hi]),
        xmm_l=L.unpack_u64(new_xmm).reshape(16, 8),
        fpst=new_fpst, fpcw=new_fpcw, fpsw=new_fpsw, fptw=new_fptw,
        mxcsr=new_mxcsr,
        fs_base_l=L.unpack_u64(new_fs),
        gs_base_l=L.unpack_u64(new_gs),
        kernel_gs_base=new_kgs,
        lstar=new_lstar, star=new_star, sfmask=new_sfmask,
        efer=new_efer, tsc=new_tsc,
        cr0=new_cr0, cr3=new_cr3, cr4=new_cr4, cr8=new_cr8,
        cs=new_cs, ss=new_ss,
        status=new_status, icount=new_icount, rdrand=new_rdrand,
        bp_skip=new_bp_skip, fault_gva=new_fault_gva,
        fault_write=new_fault_write, ctr=new_ctr, cov=new_cov,
        edge=new_edge, overlay=overlay)


# ---------------------------------------------------------------------------
# chunked batch run
# ---------------------------------------------------------------------------

_CHUNK_CACHE: dict = {}


def make_run_chunk(n_steps: int, donate: bool = None, jit: bool = True):
    """Build (or fetch) the jitted chunk executor: up to n_steps vmapped
    transitions with early exit when no lane is RUNNING.  The host runner
    (interp/runner.py) calls this in a loop, servicing lane statuses between
    chunks — the batched analog of the reference's vmexit servicing
    (kvm_backend.cc:1371-1566).

    Memoized per (n_steps, donate) so every Runner with the same chunk size
    shares one jit cache entry (XLA recompiles only on new array *shapes*,
    not per Runner instance).

    donate=True (the runner's hot path): the machine argument is donated so
    the dominant buffers (overlay data, cov/edge bitmaps) update in place
    instead of being copied every chunk call — safe because machine_restore
    copies template leaves rather than aliasing them, and the runner
    reassigns its machine from the result.  Callers that reuse an argument
    tuple across calls (the driver's entry() compile check) need
    donate=False.

    CAVEAT — CPU backend: donation is demonstrably unsound there with this
    graph (XLA CPU's buffer reuse around donated while_loop carries plus
    the u32<->u64 bitcast views corrupts live machine leaves — observed as
    garbage status/fpsw/xmm reads, reproducible and gone with donation
    off).  The Runner therefore requests donation only off-CPU, where it
    actually matters (HBM); pass donate explicitly if you know better.
    donate=None (the default) resolves to that policy lazily.

    jit=False returns the UNDECORATED body (a fresh closure every call,
    never memoized): the static analyzer's retrace-stability probe needs
    a genuinely fresh trace per lowering — jax's trace cache keys on
    function identity, so re-lowering the memoized jitted executor would
    never re-trace and the probe would be vacuous."""
    if donate is None:
        donate = jax.default_backend() != "cpu"
    key = (n_steps, donate)
    if jit:
        cached = _CHUNK_CACHE.get(key)
        if cached is not None:
            return cached

    step_v = jax.vmap(step_lane, in_axes=(None, IMAGE_IN_AXES, 0, None))

    def run_chunk(tab: UopTable, image: MemImage, machine: Machine, limit):
        # normalize in-body: the per-lane tenant selector is always
        # populated past this point (zeros for single-image callers), so
        # one vmap structure serves both dispatch shapes
        image = lane_image(image, machine.status.shape[0])

        def cond(carry):
            i, m = carry
            return (i < n_steps) & jnp.any(
                m.status == jnp.int32(int(StatusCode.RUNNING)))

        def body(carry):
            i, m = carry
            return i + 1, step_v(tab, image, m, limit)

        _, out = lax.while_loop(cond, body, (jnp.int32(0), machine))
        return out

    if not jit:
        return run_chunk
    run_chunk = partial(jax.jit, donate_argnums=(2,) if donate else ())(
        run_chunk)
    _CHUNK_CACHE[key] = run_chunk
    return run_chunk
