"""Little-endian u32 limb arithmetic: the register width the TPU executes.

TPUs have no native 64-bit integers — XLA lowers every u64 op in the
transition function to a pair of u32 ops with full carry/borrow plumbing,
whether or not the semantics need it.  This module is the hand-packed
representation: a guest 64-bit value is an explicit pair of uint32 limbs
``(lo, hi)`` (limb 0 = least significant 32 bits, matching the memory
byte order of the snapshot image), and every helper here is built from
32-bit adds/shifts/multiplies ONLY.  The hot paths of the device step
(interp/step.py: ALU, flags, addressing, condition evaluation, the
decode-cache hash probe) run on these helpers; cold paths convert at the
``pack_u64``/``unpack_u64`` seam, which XLA lowers to a free bitcast.

This is also the prerequisite representation for the fused Pallas step
kernel (PERF.md open lever 3): Pallas TPU kernels cannot hold 64-bit
integers at all, so everything a future kernel needs must already exist
here in u32 form.

Conventions:
  * a "pair" is a tuple ``(lo, hi)`` of uint32 arrays (scalars under vmap)
  * byte-count operands (``nbytes``) are int32 like the uop table fields;
    they are cast to uint32 internally before any shift
  * nothing in this module may create a 64-bit value — the tier-1 HLO
    inspection test (tests/test_limbs.py) compiles the public helpers and
    fails if a u64/s64 op appears in the lowered code
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

U32_MASK = 0xFFFFFFFF

# rflags bits (duplicated from step.py's u64 constants; kept as plain ints
# so they weak-type-promote against u32 arrays)
CF, PF, AF, ZF, SF, OF = 0x1, 0x4, 0x10, 0x40, 0x80, 0x800
FLAGS_ARITH = CF | PF | AF | ZF | SF | OF


def _u32(x) -> jnp.ndarray:
    return jnp.uint32(x & U32_MASK)


# ---------------------------------------------------------------------------
# pack/unpack seam (device): XLA bitcasts, no arithmetic
# ---------------------------------------------------------------------------

def pack_u64(x32):
    """uint32[..., 2] -> uint64[...] (little-endian limbs; free bitcast)."""
    return lax.bitcast_convert_type(x32, jnp.uint64)


def unpack_u64(x64):
    """uint64[...] -> uint32[..., 2] (limb 0 = low; free bitcast)."""
    return lax.bitcast_convert_type(x64, jnp.uint32)


def pair(x64):
    """uint64[...] -> (lo, hi) tuple of uint32[...]."""
    y = unpack_u64(x64)
    return y[..., 0], y[..., 1]


def to_u64(p):
    """(lo, hi) tuple -> uint64[...]."""
    return pack_u64(jnp.stack([p[0], p[1]], axis=-1))


def const_pair(v: int):
    """Python int -> (lo, hi) uint32 constants."""
    return _u32(v), _u32(v >> 32)


# ---------------------------------------------------------------------------
# pack/unpack seam (host): numpy views for HostView mirrors
# ---------------------------------------------------------------------------

def pack_np(a: np.ndarray) -> np.ndarray:
    """uint32[..., 2] -> uint64[...] on the host (little-endian view)."""
    a = np.ascontiguousarray(a, dtype=np.uint32)
    return a.view(np.uint64).reshape(a.shape[:-1])


def unpack_np(a: np.ndarray) -> np.ndarray:
    """uint64[...] -> uint32[..., 2] on the host."""
    a = np.ascontiguousarray(a, dtype=np.uint64)
    return a.view(np.uint32).reshape(a.shape + (2,))


# ---------------------------------------------------------------------------
# logic
# ---------------------------------------------------------------------------

def and64(a, b):
    return a[0] & b[0], a[1] & b[1]


def or64(a, b):
    return a[0] | b[0], a[1] | b[1]


def xor64(a, b):
    return a[0] ^ b[0], a[1] ^ b[1]


def not64(a):
    return ~a[0], ~a[1]


def where64(c, a, b):
    return jnp.where(c, a[0], b[0]), jnp.where(c, a[1], b[1])


def select64(conds, pairs, default):
    """jnp.select semantics (first true cond wins) over limb pairs.

    Built as a where-fold rather than jnp.select: select's lowering runs
    its case index in 64-bit scalars under x64, which would put s64 ops
    back into every ported path this library exists to keep u32-only.
    """
    lo, hi = default
    for c, p in zip(reversed(conds), reversed(pairs)):
        lo = jnp.where(c, p[0], lo)
        hi = jnp.where(c, p[1], hi)
    return lo, hi


def sel(conds, vals, default):
    """jnp.select semantics over scalar (non-pair) values as a where-fold —
    same rationale as select64: jnp.select's lowering runs its case index
    in 64-bit scalars under x64, which the ported paths must not emit."""
    out = default
    for c, v in zip(reversed(conds), reversed(vals)):
        out = jnp.where(c, v, out)
    return out


# ---------------------------------------------------------------------------
# add/sub with carry/borrow
# ---------------------------------------------------------------------------

def add64(a, b):
    """(a + b) mod 2^64."""
    return adc64(a, b, jnp.bool_(False))[0]


def adc64(a, b, carry_in):
    """a + b + carry_in -> (sum_pair, carry_out bool)."""
    cin = jnp.where(carry_in, _u32(1), _u32(0))
    s0 = a[0] + b[0]
    c0 = s0 < a[0]
    lo = s0 + cin
    c0 = c0 | (lo < s0)
    cu = jnp.where(c0, _u32(1), _u32(0))
    s1 = a[1] + b[1]
    c1 = s1 < a[1]
    hi = s1 + cu
    c1 = c1 | (hi < s1)
    return (lo, hi), c1


def add64_u32(a, small):
    """a + small (u32, zero-extended) — the cheap adder for +length /
    +span-1 style increments: one compare instead of a full carry chain."""
    lo = a[0] + small
    return lo, a[1] + jnp.where(lo < small, _u32(1), _u32(0))


def sub64(a, b):
    """(a - b) mod 2^64."""
    return sbb64(a, b, jnp.bool_(False))[0]


def sbb64(a, b, borrow_in):
    """a - b - borrow_in -> (diff_pair, borrow_out bool)."""
    bin_ = jnp.where(borrow_in, _u32(1), _u32(0))
    d0 = a[0] - b[0]
    w0 = a[0] < b[0]
    lo = d0 - bin_
    w0 = w0 | (d0 < bin_)
    bu = jnp.where(w0, _u32(1), _u32(0))
    d1 = a[1] - b[1]
    w1 = a[1] < b[1]
    hi = d1 - bu
    w1 = w1 | (d1 < bu)
    return (lo, hi), w1


def neg64(a):
    return sub64(const_pair(0), a)


# ---------------------------------------------------------------------------
# comparisons
# ---------------------------------------------------------------------------

def eq64(a, b):
    return (a[0] == b[0]) & (a[1] == b[1])


def is_zero64(a):
    return (a[0] | a[1]) == _u32(0)


def ltu64(a, b):
    return (a[1] < b[1]) | ((a[1] == b[1]) & (a[0] < b[0]))


def leu64(a, b):
    return (a[1] < b[1]) | ((a[1] == b[1]) & (a[0] <= b[0]))


# ---------------------------------------------------------------------------
# shifts / rotates (dynamic count, crossing the limb boundary)
# ---------------------------------------------------------------------------

def _ucount(s):
    return s.astype(jnp.uint32) if hasattr(s, "astype") else _u32(s)


def shl64(a, s):
    """a << s; s >= 64 yields 0 (the XLA-undefined region is defined here)."""
    s = _ucount(s)
    z = _u32(0)
    sh = jnp.minimum(s, _u32(31))           # in-limb shift (valid < 32)
    shb = jnp.minimum(s - _u32(32), _u32(31))  # cross-limb shift for s>=32
    carry = jnp.where(s == z, z, a[0] >> (_u32(32) - jnp.minimum(s, _u32(31))))
    # s in [1,31]: carry = lo >> (32-s); s==0 handled; s>=32 selected away
    lo = jnp.where(s >= 64, z, jnp.where(s >= 32, z, a[0] << sh))
    hi = jnp.where(
        s >= 64, z,
        jnp.where(s >= 32, a[0] << shb, (a[1] << sh) | carry))
    return lo, hi


def shr64(a, s):
    """Logical a >> s; s >= 64 yields 0."""
    s = _ucount(s)
    z = _u32(0)
    sh = jnp.minimum(s, _u32(31))
    shb = jnp.minimum(s - _u32(32), _u32(31))
    carry = jnp.where(s == z, z, a[1] << (_u32(32) - jnp.minimum(s, _u32(31))))
    lo = jnp.where(
        s >= 64, z,
        jnp.where(s >= 32, a[1] >> shb, (a[0] >> sh) | carry))
    hi = jnp.where(s >= 64, z, jnp.where(s >= 32, z, a[1] >> sh))
    return lo, hi


def shl64_const(a, k: int):
    """a << k for a trace-time-constant k — no dynamic-count selects."""
    assert 0 <= k < 64
    if k == 0:
        return a
    if k >= 32:
        return jnp.zeros_like(a[0]), a[0] << (k - 32)
    return a[0] << k, (a[1] << k) | (a[0] >> (32 - k))


def shr64_const(a, k: int):
    """Logical a >> k for a trace-time-constant k."""
    assert 0 <= k < 64
    if k == 0:
        return a
    if k >= 32:
        return a[1] >> (k - 32), jnp.zeros_like(a[1])
    return (a[0] >> k) | (a[1] << (32 - k)), a[1] >> k


def sar64(a, s):
    """Arithmetic a >> s; s >= 64 fills with the sign like s == 63."""
    s = jnp.minimum(_ucount(s), _u32(63))
    sign = jnp.where((a[1] >> 31) != 0, _u32(U32_MASK), _u32(0))
    z = _u32(0)
    sh = jnp.minimum(s, _u32(31))
    shb = jnp.minimum(s - _u32(32), _u32(31))
    hi_s = (a[1].astype(jnp.int32) >> sh.astype(jnp.int32)).astype(jnp.uint32)
    hi_b = (a[1].astype(jnp.int32) >> shb.astype(jnp.int32)).astype(jnp.uint32)
    carry = jnp.where(s == z, z, a[1] << (_u32(32) - jnp.minimum(s, _u32(31))))
    lo = jnp.where(s >= 32, hi_b, (a[0] >> sh) | carry)
    hi = jnp.where(s >= 32, sign, hi_s)
    return lo, hi


def rol64(a, s):
    """Rotate left by s (mod 64)."""
    s = _ucount(s) & _u32(63)
    return where64(s == _u32(0), a,
                   or64(shl64(a, s), shr64(a, _u32(64) - s)))


def ror64(a, s):
    """Rotate right by s (mod 64)."""
    s = _ucount(s) & _u32(63)
    return where64(s == _u32(0), a,
                   or64(shr64(a, s), shl64(a, _u32(64) - s)))


# ---------------------------------------------------------------------------
# multiply
# ---------------------------------------------------------------------------

def mul32_wide(a32, b32):
    """Widening 32x32 -> 64 multiply from 16-bit partial products.

    Every operand of every multiply stays < 2^32, so XLA never sees a
    64-bit multiplier — this is the primitive the Pallas kernel will use.
    """
    m16 = _u32(0xFFFF)
    a0, a1 = a32 & m16, a32 >> 16
    b0, b1 = b32 & m16, b32 >> 16
    ll = a0 * b0
    lh = a0 * b1
    hl = a1 * b0
    hh = a1 * b1
    mid = (ll >> 16) + (lh & m16) + (hl & m16)       # <= 3*(2^16-1): no wrap
    lo = (ll & m16) | (mid << 16)
    hi = hh + (lh >> 16) + (hl >> 16) + (mid >> 16)
    return lo, hi


def mul64_lo(a, b):
    """Low 64 bits of a 64x64 multiply (the splitmix64/hash workhorse)."""
    lo, hi = mul32_wide(a[0], b[0])
    hi = hi + a[0] * b[1] + a[1] * b[0]
    return lo, hi


def umulhi64(a, b):
    """High 64 bits of the unsigned 128-bit product a * b, from four
    mul32_wide partial products (the step's widening-MUL port and the
    only place the full 128-bit product shape exists in limb form)."""
    p00l, p00h = mul32_wide(a[0], b[0])
    p01l, p01h = mul32_wide(a[0], b[1])
    p10l, p10h = mul32_wide(a[1], b[0])
    p11 = mul32_wide(a[1], b[1])
    # bits 32..63 of the product: p00h + p01l + p10l, carry count 0..2
    s1 = p00h + p01l
    c1 = s1 < p01l
    s2 = s1 + p10l
    c2 = s2 < p10l
    midcarry = jnp.where(c1, _u32(1), _u32(0)) + jnp.where(c2, _u32(1), _u32(0))
    hi = add64(p11, (p01h, _u32(0)))
    hi = add64(hi, (p10h, _u32(0)))
    return add64(hi, (midcarry, _u32(0)))


def smulhi64(a, b):
    """High 64 bits of the signed 128-bit product (two's-complement
    correction of umulhi64, mirroring step.py's deleted _smulhi)."""
    hi = umulhi64(a, b)
    zero = (_u32(0), _u32(0))
    hi = sub64(hi, where64((a[1] >> 31) != 0, b, zero))
    return sub64(hi, where64((b[1] >> 31) != 0, a, zero))


# ---------------------------------------------------------------------------
# splitmix64 (decode-cache hash probe; must match utils.hashing bit-for-bit)
# ---------------------------------------------------------------------------

# plain-int limb pairs, NOT jnp arrays: a device array created at import
# time would be a captured constant inside a Pallas kernel trace
# (interp/pstep.py), which pallas_call rejects; python ints weak-type
# against the u32 operands and lower to u32 literals either way
_GOLDEN = (0x9E3779B97F4A7C15 & U32_MASK, 0x9E3779B97F4A7C15 >> 32)
_MIX1 = (0xBF58476D1CE4E5B9 & U32_MASK, 0xBF58476D1CE4E5B9 >> 32)
_MIX2 = (0x94D049BB133111EB & U32_MASK, 0x94D049BB133111EB >> 32)


def mix64(z):
    z = mul64_lo(xor64(z, shr64_const(z, 30)), _MIX1)
    z = mul64_lo(xor64(z, shr64_const(z, 27)), _MIX2)
    return xor64(z, shr64_const(z, 31))


def splitmix64(x):
    return mix64(add64(x, _GOLDEN))


# ---------------------------------------------------------------------------
# size masks / extensions
# ---------------------------------------------------------------------------

def mask32(nbits):
    """(1 << nbits) - 1 for nbits in [0, 32] (32 -> all ones)."""
    nbits = _ucount(nbits)
    partial = (_u32(1) << jnp.minimum(nbits, _u32(31))) - _u32(1)
    return jnp.where(nbits >= 32, _u32(U32_MASK), partial)


def size_mask(nbytes):
    """nbytes (int32) -> (lo, hi) value mask; >= 8 bytes = full mask."""
    bits = jnp.minimum(nbytes, 8).astype(jnp.uint32) * _u32(8)
    return mask32(bits), mask32(jnp.maximum(bits, _u32(32)) - _u32(32))


def zext(a, nbytes):
    """Zero-extend the low nbytes of a to 64 bits (i.e. mask)."""
    mlo, mhi = size_mask(nbytes)
    return a[0] & mlo, a[1] & mhi


def sext(a, nbytes):
    """Sign-extend the low nbytes (1/2/4/8+) of a to 64 bits."""
    bits32 = jnp.minimum(nbytes, 4).astype(jnp.uint32) * _u32(8)
    sh = (_u32(32) - bits32).astype(jnp.int32)
    lo_se = ((a[0] << sh.astype(jnp.uint32)).astype(jnp.int32)
             >> sh).astype(jnp.uint32)
    hi_se = (lo_se.astype(jnp.int32) >> 31).astype(jnp.uint32)
    wide = nbytes >= 8
    return (jnp.where(wide, a[0], lo_se), jnp.where(wide, a[1], hi_se))


def msb(a, nbytes):
    """Sign bit of the low-nbytes value (nbytes in {1,2,4,8+}) as bool."""
    hi_bit = (a[1] >> 31) & _u32(1)
    sh = (jnp.minimum(nbytes, 4).astype(jnp.uint32) * _u32(8)) - _u32(1)
    lo_bit = (a[0] >> sh) & _u32(1)
    return jnp.where(nbytes >= 8, hi_bit, lo_bit) != _u32(0)


# ---------------------------------------------------------------------------
# x86 flag images (CF/PF/AF/ZF/SF/OF live in rflags bits 0-11: u32-only)
# ---------------------------------------------------------------------------

def parity_even(lo):
    v = lo & _u32(0xFF)
    v = v ^ (v >> 4)
    v = v ^ (v >> 2)
    v = v ^ (v >> 1)
    return (v & _u32(1)) == _u32(0)


def mkflags(cf, pf, af, zf, sf, of):
    def bit(c, v):
        return jnp.where(c, _u32(v), _u32(0))

    return (bit(cf, CF) | bit(pf, PF) | bit(af, AF) | bit(zf, ZF)
            | bit(sf, SF) | bit(of, OF))


def _of_bit(x, y, nbytes):
    """msb of (x & y) at the operand width — the overflow predicates."""
    return msb(and64(x, y), nbytes)


def flags_add(a, b, r, nbytes, carry):
    """Flag image of a + b (+carry) = r at nbytes width (r pre-masked ok).

    Mirrors step.py's u64 ``_flags_add`` bit-for-bit: the masked-result
    carry formula (rm < am) | (carry & (rm == am)) holds at every width.
    """
    am, rm = zext(a, nbytes), zext(r, nbytes)
    cf = ltu64(rm, am) | (carry & eq64(rm, am))
    return mkflags(
        cf=cf,
        pf=parity_even(rm[0]),
        af=((a[0] ^ b[0] ^ r[0]) & _u32(0x10)) != _u32(0),
        zf=is_zero64(rm),
        sf=msb(rm, nbytes),
        of=_of_bit(xor64(a, r), xor64(b, r), nbytes),
    )


def flags_sub(a, b, r, nbytes, borrow):
    """Flag image of a - b (-borrow) = r at nbytes width."""
    am, bm, rm = zext(a, nbytes), zext(b, nbytes), zext(r, nbytes)
    cf = jnp.where(borrow, leu64(am, bm), ltu64(am, bm))
    return mkflags(
        cf=cf,
        pf=parity_even(rm[0]),
        af=((a[0] ^ b[0] ^ r[0]) & _u32(0x10)) != _u32(0),
        zf=is_zero64(rm),
        sf=msb(rm, nbytes),
        of=_of_bit(xor64(a, b), xor64(a, r), nbytes),
    )


def flags_logic(r, nbytes):
    """Flag image of a logic result (CF=OF=AF=0)."""
    rm = zext(r, nbytes)
    false = jnp.bool_(False)
    return mkflags(
        cf=false,
        pf=parity_even(rm[0]),
        af=false,
        zf=is_zero64(rm),
        sf=msb(rm, nbytes),
        of=false,
    )


# ---------------------------------------------------------------------------
# condition evaluation (Jcc/SETcc/CMOVcc; arith flags are all in the low limb)
# ---------------------------------------------------------------------------

def eval_cond(rf_lo, rcx, cc):
    """cc 0-15: the x86 condition table; 16: jrcxz; 17: jecxz."""
    cf = (rf_lo & _u32(CF)) != 0
    pf = (rf_lo & _u32(PF)) != 0
    zf = (rf_lo & _u32(ZF)) != 0
    sf = (rf_lo & _u32(SF)) != 0
    of = (rf_lo & _u32(OF)) != 0
    conds = jnp.stack([
        of, ~of, cf, ~cf, zf, ~zf, cf | zf, ~(cf | zf),
        sf, ~sf, pf, ~pf, sf != of, sf == of,
        zf | (sf != of), ~zf & (sf == of),
    ])
    base = conds[jnp.clip(cc, 0, 15)]
    base = jnp.where(cc == 16, is_zero64(rcx), base)
    return jnp.where(cc == 17, rcx[0] == _u32(0), base)
