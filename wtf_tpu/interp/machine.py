"""Batched machine state: every lane is one guest, stored SoA on device.

Equivalent of the reference's per-VM register/memory state (`CpuState_t`
loaded into bochs/KVM/WHV at `Initialize`/`Restore`, reference
src/wtf/bochscpu_backend.cc:1026-1122), redesigned for lockstep batch
execution: all architectural state lives in `[lanes, ...]` arrays so one
vmapped transition function advances every guest at once, and `Restore()` is
a functional rebuild from the snapshot broadcast — no per-page rollback loop.

Only the state the interpreter subset actually reads/writes is device
resident (GPRs, rip, rflags, XMM0-15, segment bases, control registers,
syscall MSRs).  The full `CpuState` (x87 stack, debug registers, the other
16 ZMM...) stays host-side in the snapshot and is restored by construction
since the device never mutates it.

Hot-state representation: the fields the transition function touches every
step (GPRs, rip, rflags, the XMM file, fs/gs bases) are stored as explicit
little-endian u32 limb arrays (`*_l` fields, trailing axis = limb) because
the TPU has no native 64-bit integers — XLA would otherwise lower every
u64 op into a u32 pair with carry plumbing the semantics rarely need, and
the future Pallas step kernel cannot hold u64 at all (interp/limbs.py).
The u64-named accessors (`machine.gpr`, `.rip`, ...) are free bitcast
views for host mirrors, tests, and cold device paths.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from wtf_tpu.core.cpustate import CpuState
from wtf_tpu.core.results import StatusCode
from wtf_tpu.interp.limbs import pack_u64, unpack_np
from wtf_tpu.mem.overlay import DirtyOverlay, overlay_init, overlay_reset

# Device-side telemetry counter block: one u32 per lane per counter,
# accumulated in-graph by step_lane and folded into host metrics once per
# burst (no per-step host sync).  u32 (not u64 — the TPU has no native
# 64-bit ints; a u64 counter would re-add the limb ops PR 2 removed)
# covers every budgeted run: counters reset at each restore and the
# BASELINE budget is 100M instructions/testcase.  Caveat: with limit=0
# (unlimited) a single testcase retiring > 2^32 instructions wraps the
# counter while the u64 icount keeps counting — the CTR_INSTR == icount
# invariant holds mod 2^32 there.
CTR_INSTR = 0        # instructions retired (commit) — the oracle fallback
                     # mirrors its host steps in (runner._fallback_step) so
                     # this matches icount exactly, fallback paths included
CTR_MEM_FAULT = 1    # translation faults observed (device page walks +
                     # oracle MemFaults), counted once per fault event
CTR_DECODE_MISS = 2  # decode-cache misses (NEED_DECODE transitions)
CTR_FUSED = 3        # instructions retired INSIDE the fused Pallas step
                     # kernel (interp/pstep.py); a subset of CTR_INSTR, so
                     # fused occupancy = CTR_FUSED / CTR_INSTR.  Stays 0
                     # on the plain XLA chunk path
CTR_PARK_SUBSET = 4  # fused-kernel park events for a SUBSET reason: a
                     # non-hot opclass/operand form, an armed breakpoint,
                     # or an SMC-risk code window (one count per park,
                     # not per held step).  Stays 0 on the XLA path
CTR_PARK_MEM = 5     # fused-kernel park events for a MEMORY reason: a
                     # non-present/non-writable walk, an out-of-range
                     # store frame, or overlay-slot exhaustion — the lane
                     # leaves the kernel so the XLA leg can raise the
                     # precise PAGE_FAULT/OVERLAY_FULL.  Distinct from
                     # CTR_PARK_SUBSET so occupancy loss is attributable
                     # (bench.py --fused-compare / telemetry_report)
N_CTRS = 6


class Machine(NamedTuple):
    """All fields carry a leading lane axis."""

    # Architectural hot state, as little-endian u32 limbs (limbs.py)
    gpr_l: jax.Array      # uint32[L, 16, 2] (x86 encoding order)
    rip_l: jax.Array      # uint32[L, 2]
    rflags_l: jax.Array   # uint32[L, 2]
    xmm_l: jax.Array      # uint32[L, 16, 8] YMM as 8 u32 limbs: device ops
                          # compute on limbs 0-3 (low XMM); limbs 4-7
                          # (upper YMM) are carried for AVX snapshot
                          # round-trip (reference globals.h:1020-1159)
    fs_base_l: jax.Array  # uint32[L, 2]
    gs_base_l: jax.Array  # uint32[L, 2]
    kernel_gs_base: jax.Array  # uint64[L]
    cr0: jax.Array        # uint64[L]
    cr2: jax.Array        # uint64[L] (set by host exception delivery)
    cr3: jax.Array        # uint64[L]
    cr4: jax.Array        # uint64[L]
    cr8: jax.Array        # uint64[L]
    cs: jax.Array         # uint64[L] CS selector (CPL tracking for delivery)
    ss: jax.Array         # uint64[L] SS selector
    lstar: jax.Array      # uint64[L]
    star: jax.Array       # uint64[L]
    sfmask: jax.Array     # uint64[L]
    efer: jax.Array       # uint64[L]
    tsc: jax.Array        # uint64[L]
    # x87/SSE control state: carried (never computed on device) so the
    # oracle's per-step fallback sees a persistent FPU across steps
    fpst: jax.Array       # uint64[L, 8] f64 bits per physical slot
    fpcw: jax.Array       # uint64[L]
    fpsw: jax.Array       # uint64[L] (incl. TOP bits 11-13)
    fptw: jax.Array       # uint64[L]
    mxcsr: jax.Array      # uint64[L]

    # Run bookkeeping
    status: jax.Array     # int32[L] (core.results.StatusCode)
    icount: jax.Array     # uint64[L] executed instructions this testcase
    rdrand: jax.Array     # uint64[L] deterministic rdrand chain state
    cr3_base: jax.Array   # uint64[L] snapshot cr3 (writes != this stop the lane)
    bp_skip: jax.Array    # int32[L] suppress bp check for one step post-resume
    fault_gva: jax.Array  # uint64[L] faulting address (PAGE_FAULT/SMC detail)
    fault_write: jax.Array  # int32[L] 1 when the faulting access was a write

    # Device-side telemetry (CTR_* indices above); folded into the host
    # metrics registry once per burst, reset on restore
    ctr: jax.Array        # uint32[L, N_CTRS]

    # Coverage (reference: robin_set<Gva_t> per run + edge hash inserts,
    # bochscpu_backend.cc:479-548,699-728 — here: per-lane bitmaps)
    cov: jax.Array        # uint32[L, cap/32] bit per uop-table entry executed
    edge: jax.Array       # uint32[L, EW] splitmix64 edge-hash bitmap

    # Guest memory writes (copy-on-write; reset = Restore)
    overlay: DirtyOverlay  # fields carry the lane axis

    @property
    def n_lanes(self) -> int:
        return self.rip_l.shape[0]

    # -- u64 bitcast views of the limb-packed hot state --------------------
    # Free reinterprets (no arithmetic); what host mirrors, tests, and the
    # device step's cold paths read.  Pytree structure is unaffected.
    @property
    def gpr(self) -> jax.Array:        # uint64[L, 16]
        return pack_u64(self.gpr_l)

    @property
    def rip(self) -> jax.Array:        # uint64[L]
        return pack_u64(self.rip_l)

    @property
    def rflags(self) -> jax.Array:     # uint64[L]
        return pack_u64(self.rflags_l)

    @property
    def xmm(self) -> jax.Array:        # uint64[L, 16, 4]
        x = self.xmm_l
        return pack_u64(x.reshape(x.shape[:-1] + (4, 2)))

    @property
    def fs_base(self) -> jax.Array:    # uint64[L]
        return pack_u64(self.fs_base_l)

    @property
    def gs_base(self) -> jax.Array:    # uint64[L]
        return pack_u64(self.gs_base_l)


def _fpst_f64_bits(v: int) -> int:
    """Snapshot fpst entry -> the f64-bits FPU model: 80-bit extended
    values (real dumps) reduce via the oracle's converter; already-64-bit
    values pass through."""
    if v >> 64:
        from wtf_tpu.cpu.emu import _f80_to_f64_bits

        return _f80_to_f64_bits(v)
    return v & (1 << 64) - 1


def machine_init(
    cpu: CpuState,
    n_lanes: int,
    uop_capacity: int,
    overlay_slots: int = 128,
    edge_bits: int = 17,
) -> Machine:
    """Build the batch with every lane at the snapshot state."""
    ones = np.ones(n_lanes, dtype=np.uint64)

    def bcast(value: int) -> jax.Array:
        return jnp.asarray(ones * np.uint64(value & (1 << 64) - 1))

    def bcast_l(value: int) -> jax.Array:
        return jnp.asarray(unpack_np(ones * np.uint64(value & (1 << 64) - 1)))

    gpr = np.tile(np.array(cpu.gpr_list(), dtype=np.uint64), (n_lanes, 1))
    xmm = np.zeros((n_lanes, 16, 4), dtype=np.uint64)
    for i in range(16):
        for limb in range(4):
            xmm[:, i, limb] = np.uint64(cpu.zmm[i][limb])

    return Machine(
        gpr_l=jnp.asarray(unpack_np(gpr)),
        rip_l=bcast_l(cpu.rip),
        rflags_l=bcast_l(cpu.rflags | 0x2),
        xmm_l=jnp.asarray(unpack_np(xmm).reshape(n_lanes, 16, 8)),
        fs_base_l=bcast_l(cpu.fs.base),
        gs_base_l=bcast_l(cpu.gs.base),
        kernel_gs_base=bcast(cpu.kernel_gs_base),
        cr0=bcast(cpu.cr0),
        cr2=bcast(cpu.cr2),
        cr3=bcast(cpu.cr3),
        cr4=bcast(cpu.cr4),
        cr8=bcast(cpu.cr8),
        cs=bcast(cpu.cs.selector),
        ss=bcast(cpu.ss.selector),
        lstar=bcast(cpu.lstar),
        star=bcast(cpu.star),
        sfmask=bcast(cpu.sfmask),
        efer=bcast(cpu.efer),
        tsc=bcast(cpu.tsc),
        fpst=jnp.asarray(np.tile(np.array(
            [_fpst_f64_bits(v) for v in cpu.fpst[:8]],
            dtype=np.uint64), (n_lanes, 1))),
        fpcw=bcast(cpu.fpcw),
        fpsw=bcast(cpu.fpsw),
        fptw=bcast(cpu.fptw),
        mxcsr=bcast(cpu.mxcsr),
        status=jnp.full((n_lanes,), int(StatusCode.RUNNING), dtype=jnp.int32),
        icount=jnp.zeros((n_lanes,), dtype=jnp.uint64),
        rdrand=jnp.zeros((n_lanes,), dtype=jnp.uint64),
        cr3_base=bcast(cpu.cr3),
        bp_skip=jnp.zeros((n_lanes,), dtype=jnp.int32),
        fault_gva=jnp.zeros((n_lanes,), dtype=jnp.uint64),
        fault_write=jnp.zeros((n_lanes,), dtype=jnp.int32),
        ctr=jnp.zeros((n_lanes, N_CTRS), dtype=jnp.uint32),
        cov=jnp.zeros((n_lanes, (uop_capacity + 31) // 32), dtype=jnp.uint32),
        edge=jnp.zeros((n_lanes, (1 << edge_bits) // 32), dtype=jnp.uint32),
        overlay=overlay_init(n_lanes, overlay_slots),
    )


def _machine_restore_impl(machine: Machine,
                          snapshot_template: Machine) -> Machine:
    return snapshot_template._replace(
        # Keep the overlay *storage* from the live machine so no new buffers
        # are allocated; overlay_reset rebuilds just the indexing state.
        overlay=overlay_reset(machine.overlay),
        ctr=jnp.zeros_like(machine.ctr),
        cov=jnp.zeros_like(machine.cov),
        edge=jnp.zeros_like(machine.edge),
    )


_machine_restore_donated = partial(
    jax.jit, donate_argnums=(0,))(_machine_restore_impl)
_machine_restore_plain = jax.jit(_machine_restore_impl)


def machine_restore(machine: Machine, snapshot_template: Machine,
                    donate: bool = None) -> Machine:
    """Restore(): every lane back to the snapshot.  O(1) in guest memory —
    replaces the reference's dirty-page rewrite loops (SURVEY.md §5.4).

    `snapshot_template` is the pristine machine from machine_init.  Only its
    small per-lane register/bookkeeping arrays are used; the overlay STORAGE
    always comes from the live machine and cov/edge are rebuilt as zeros, so
    build the template with `overlay_slots=0` to avoid holding a second
    multi-GiB overlay buffer alive.

    Donation (donate=True, the off-CPU hot path): `machine` is donated so
    the overlay storage is reset in place (no copy of the
    [lanes, slots, 4096] buffer).  The template is NOT donated — XLA
    copies its leaves into the output, so the result never aliases the
    template and later run_chunk calls may donate the machine freely.
    On the CPU backend donation must stay OFF: XLA CPU's buffer reuse
    for donated inputs corrupts live machine leaves on this graph
    (interp/step.py make_run_chunk documents the failure mode).  The
    default (donate=None) resolves to that policy lazily, exactly like
    make_run_chunk."""
    if donate is None:
        donate = jax.default_backend() != "cpu"
    fn = _machine_restore_donated if donate else _machine_restore_plain
    return fn(machine, snapshot_template)
