"""Host orchestration of the batched device interpreter.

The reference's run loop alternates guest execution with host servicing
(vmexits: kvm_backend.cc:1371-1566; emulator hooks: bochscpu_backend.cc:
352-548).  Here the device runs *chunks* of vmapped steps (interp/step.py)
and the host services whatever each lane reported in its status word:

  NEED_DECODE  - decode bytes at the lane's rip once, publish to the shared
                 uop table, resume (the JIT-translation-cache fill path)
  SMC          - lane's code bytes diverged from the cache: re-decode and
                 update the entry in place
  UNSUPPORTED  - single-step the lane on the host EmuCpu oracle (precise
                 slow path; mirrors the bochscpu-backs-KVM methodology)
  BREAKPOINT   - dispatch to the backend's registered handler
  terminal     - OK/CRASH/TIMEDOUT/... mapped to results by the backend

Host<->device traffic is batched: one pull of the small per-lane register
arrays per service round (`HostView`), page-granular reads on demand, and
all memory writes buffered host-side and applied in a single jitted scan
(`_apply_page_writes`) before the next chunk.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Callable, Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from wtf_tpu.core.cpustate import CpuState
from wtf_tpu.core.gxa import PAGE_SHIFT, PAGE_SIZE
from wtf_tpu.core.results import StatusCode
from wtf_tpu.cpu.decoder import decode
from wtf_tpu.cpu import uops as U
from wtf_tpu.cpu.emu import (
    DivideError, EmuCpu, GuestCrash, MemFault, UnsupportedInsn,
)
from wtf_tpu.cpu.interrupts import (
    VEC_DE, DeliveryFailed, deliver_exception, deliver_page_fault,
)
from wtf_tpu.interp import limbs
from wtf_tpu.interp.machine import (
    CTR_DECODE_MISS, CTR_FUSED, CTR_INSTR, CTR_MEM_FAULT, CTR_PARK_MEM,
    CTR_PARK_SUBSET, Machine, machine_init, machine_restore,
)
from wtf_tpu.interp.step import make_run_chunk
from wtf_tpu.interp.uoptable import DecodeCache
from wtf_tpu.snapshot.loader import Snapshot
from wtf_tpu.supervise import Supervisor
from wtf_tpu.telemetry import NULL, Registry, StatsDict

MASK64 = (1 << 64) - 1

# Executor shapes (chunk_steps, donate, n_lanes, operand shapes) dispatched
# at least once in this process — mirrors the process-global jit cache, so
# `compile` telemetry events fire exactly when XLA actually compiles
_DISPATCHED_EXECUTORS: Set[Tuple] = set()

# opc int -> lowercase class name ("alu", "ssefp", ...) for fallback stats
_OPC_NAMES = {
    value: name[len("OPC_"):].lower()
    for name, value in vars(U).items() if name.startswith("OPC_")
}

PTE_P = 1
PTE_W = 1 << 1
PTE_PS = 1 << 7
PHYS_MASK = 0x000F_FFFF_FFFF_F000

# Machine leaves mirrored into HostView (everything except overlay/cov/edge).
# The limb-packed hot fields (machine.py) are exposed to ALL host code as
# u64 views under their architectural names — HostView packs on pull and
# unpacks on push, so the seam lives in exactly two places.
_MIRROR_FIELDS = (
    "gpr", "rip", "rflags", "xmm", "fs_base", "gs_base", "kernel_gs_base",
    "cr0", "cr2", "cr3", "cr4", "cr8", "cs", "ss",
    "lstar", "star", "sfmask", "efer", "tsc",
    "fpst", "fpcw", "fpsw", "fptw", "mxcsr",
    "status", "icount", "rdrand", "bp_skip", "fault_gva", "fault_write",
    "ctr",
)

# host mirror name -> u32-limb Machine field
_LIMB_FIELDS = {
    "gpr": "gpr_l", "rip": "rip_l", "rflags": "rflags_l", "xmm": "xmm_l",
    "fs_base": "fs_base_l", "gs_base": "gs_base_l",
}


def _pack_mirror(name: str, arr: np.ndarray) -> np.ndarray:
    """Device limb array -> host u64 mirror (xmm pairs its 8 limbs to 4)."""
    if name == "xmm":
        arr = arr.reshape(arr.shape[:-1] + (4, 2))
    return np.array(limbs.pack_np(arr))


def _unpack_mirror(name: str, arr: np.ndarray) -> np.ndarray:
    """Host u64 mirror -> device limb array.

    Returns a fresh copy, never a view: the result is uploaded into the
    machine, whose buffers are DONATED to the next chunk — on the CPU
    backend jnp.asarray can zero-copy alias host numpy memory, and a
    donated alias of a still-mutable HostView array is silent corruption
    (observed as garbage status/fpsw reads under multi-test processes).
    """
    u = limbs.unpack_np(arr)
    if name == "xmm":
        u = u.reshape(u.shape[:-2] + (8,))
    return u.copy()


class HostFault(Exception):
    """Host-side page walk failed (non-present / non-canonical)."""

    def __init__(self, gva: int, write: bool):
        super().__init__(f"host #PF {'write' if write else 'read'} @ {gva:#x}")
        self.gva = gva
        self.write = write


class HostView:
    """Mutable host mirror of the batch: registers as numpy arrays, guest
    memory as a merged (pending-writes | device overlay | base image) view.

    This is what breakpoint handlers and the target harness operate on — the
    equivalent of the reference's `Backend_t` register/VirtRead/VirtWriteDirty
    surface (backend.cc:30-127), but for all lanes at once.  Mutations stay
    host-side until `Runner._push` applies them in one batch.
    """

    def __init__(self, runner: "Runner"):
        self.runner = runner
        m = runner.machine
        # ONE batched device->host transfer for all mirrored leaves (a
        # per-field pull costs a device round trip each — 22 RPCs per
        # servicing round over a remote-TPU tunnel)
        host = jax.device_get(
            {name: getattr(m, _LIMB_FIELDS.get(name, name))
             for name in _MIRROR_FIELDS}
            | {"__ov_pfn": m.overlay.pfn})
        # np.array: device_get may hand back read-only views; handlers
        # mutate.  Limb-packed fields convert to u64 views here (pack_np)
        # so every host consumer keeps architectural u64 semantics.
        self.r: Dict[str, np.ndarray] = {
            name: (_pack_mirror(name, np.asarray(host[name]))
                   if name in _LIMB_FIELDS else np.array(host[name]))
            for name in _MIRROR_FIELDS
        }
        # overlay index pulled once; data rows fetched lazily per (lane, pfn)
        self._ov_pfn = host["__ov_pfn"]
        self._page_cache: Dict[Tuple[int, int], bytes] = {}
        self.pending: Dict[Tuple[int, int], bytearray] = {}

    # -- registers -------------------------------------------------------
    def get_reg(self, lane: int, idx: int) -> int:
        return int(self.r["gpr"][lane, idx])

    def set_reg(self, lane: int, idx: int, value: int) -> None:
        self.r["gpr"][lane, idx] = np.uint64(value & MASK64)

    def get_rip(self, lane: int) -> int:
        return int(self.r["rip"][lane])

    def set_rip(self, lane: int, value: int) -> None:
        self.r["rip"][lane] = np.uint64(value & MASK64)

    def set_status(self, lane: int, status: StatusCode) -> None:
        self.r["status"][lane] = np.int32(int(status))

    def get_status(self, lane: int) -> StatusCode:
        return StatusCode(int(self.r["status"][lane]))

    # -- physical memory -------------------------------------------------
    def _base_page(self, lane: int, pfn: int) -> bytes:
        # routed per lane: heterogeneous batches read the LANE's base
        # image (wtf_tpu/tenancy); single-image runners route to the one
        # physmem as before
        return self.runner.lane_physmem(lane).host_read(
            pfn << PAGE_SHIFT, PAGE_SIZE)

    def _device_overlay_page(self, lane: int, pfn: int) -> Optional[bytes]:
        slots = np.nonzero(self._ov_pfn[lane] == pfn)[0]
        if len(slots) == 0:
            return None
        slot = int(slots[0])
        ov = self.runner.machine.overlay
        data = np.asarray(ov.data[lane, slot])
        valid = np.asarray(ov.valid[lane, slot])
        # delta row: only valid words come from the overlay, the rest
        # from the base image (little-endian words -> bytes on a LE host)
        base = np.frombuffer(self._base_page(lane, pfn), dtype=np.uint64)
        return np.where(valid != 0, data, base).tobytes()

    def page(self, lane: int, pfn: int) -> bytes:
        """Current contents of a guest-physical page as this lane sees it."""
        key = (lane, pfn)
        if key in self.pending:
            return bytes(self.pending[key])
        cached = self._page_cache.get(key)
        if cached is None:
            cached = self._device_overlay_page(lane, pfn)
            if cached is None:
                cached = self._base_page(lane, pfn)
            self._page_cache[key] = cached
        return cached

    def page_dirty(self, lane: int, pfn: int) -> bool:
        return ((lane, pfn) in self.pending
                or bool(np.any(self._ov_pfn[lane] == pfn)))

    def phys_read(self, lane: int, gpa: int, size: int) -> bytes:
        out = bytearray()
        pos = gpa
        while pos < gpa + size:
            pfn = pos >> PAGE_SHIFT
            off = pos & (PAGE_SIZE - 1)
            chunk = min(gpa + size - pos, PAGE_SIZE - off)
            out += self.page(lane, pfn)[off:off + chunk]
            pos += chunk
        return bytes(out)

    def phys_write(self, lane: int, gpa: int, data: bytes) -> None:
        pos = 0
        while pos < len(data):
            addr = gpa + pos
            pfn = addr >> PAGE_SHIFT
            off = addr & (PAGE_SIZE - 1)
            chunk = min(len(data) - pos, PAGE_SIZE - off)
            key = (lane, pfn)
            if key not in self.pending:
                self.pending[key] = bytearray(self.page(lane, pfn))
            self.pending[key][off:off + chunk] = data[pos:pos + chunk]
            pos += chunk

    # -- virtual memory --------------------------------------------------
    def translate(self, lane: int, gva: int, write: bool = False) -> int:
        """4-level long-mode walk through this lane's memory view
        (reference kvm_backend.cc:1937-1998)."""
        gva &= MASK64
        top = gva >> 47
        if top != 0 and top != 0x1FFFF:
            raise HostFault(gva, write)
        table = int(self.r["cr3"][lane]) & PHYS_MASK
        for shift, large_mask in ((39, None), (30, 0x000F_FFFF_C000_0000),
                                  (21, 0x000F_FFFF_FFE0_0000), (12, None)):
            index = (gva >> shift) & 0x1FF
            entry = int.from_bytes(
                self.phys_read(lane, table + index * 8, 8), "little")
            if not entry & PTE_P:
                raise HostFault(gva, write)
            if write and not entry & PTE_W:
                raise HostFault(gva, write)
            if large_mask is not None and entry & PTE_PS:
                return (entry & large_mask) | (gva & ((1 << shift) - 1))
            if shift == 12:
                return (entry & PHYS_MASK) | (gva & 0xFFF)
            table = entry & PHYS_MASK
        raise AssertionError("unreachable")

    def virt_read(self, lane: int, gva: int, size: int) -> bytes:
        out = bytearray()
        pos = gva
        while pos < gva + size:
            off = pos & (PAGE_SIZE - 1)
            chunk = min(gva + size - pos, PAGE_SIZE - off)
            gpa = self.translate(lane, pos)
            out += self.phys_read(lane, gpa, chunk)
            pos += chunk
        return bytes(out)

    def virt_write(self, lane: int, gva: int, data: bytes) -> None:
        """Host-initiated guest write.  Writes through page protection (the
        reference's VirtWrite is a raw memcpy, backend.cc:91-127) and is
        dirty by construction — it lands in the overlay and rolls back at
        Restore, preserving the VirtWriteDirty contract."""
        pos = 0
        while pos < len(data):
            addr = gva + pos
            off = addr & (PAGE_SIZE - 1)
            chunk = min(len(data) - pos, PAGE_SIZE - off)
            gpa = self.translate(lane, addr, write=False)
            self.phys_write(lane, gpa, data[pos:pos + chunk])
            pos += chunk


class _LaneCtx:
    """Exception-delivery ctx (cpu/interrupts.py duck type) over one lane of
    a HostView: register/memory mutations land in the view and reach the
    device on the next push.  The IDT/TSS anchors come from the snapshot
    CpuState (lidt/ltr are not emulated — same fixed-tables model as the
    oracle)."""

    def __init__(self, view: HostView, lane: int, snapshot_cpu: CpuState):
        self.view = view
        self.lane = lane
        self.idt_base = snapshot_cpu.idtr.base
        self.idt_limit = snapshot_cpu.idtr.limit
        self.tss_base = snapshot_cpu.tr.base

    # registers
    @property
    def rip(self) -> int:
        return self.view.get_rip(self.lane)

    @rip.setter
    def rip(self, value: int) -> None:
        self.view.set_rip(self.lane, value)

    @property
    def rsp(self) -> int:
        return self.view.get_reg(self.lane, 4)

    @rsp.setter
    def rsp(self, value: int) -> None:
        self.view.set_reg(self.lane, 4, value)

    @property
    def rflags(self) -> int:
        return int(self.view.r["rflags"][self.lane])

    @rflags.setter
    def rflags(self, value: int) -> None:
        self.view.r["rflags"][self.lane] = np.uint64(value & MASK64)

    @property
    def cs_sel(self) -> int:
        return int(self.view.r["cs"][self.lane])

    @cs_sel.setter
    def cs_sel(self, value: int) -> None:
        self.view.r["cs"][self.lane] = np.uint64(value & 0xFFFF)

    @property
    def ss_sel(self) -> int:
        return int(self.view.r["ss"][self.lane])

    @ss_sel.setter
    def ss_sel(self, value: int) -> None:
        self.view.r["ss"][self.lane] = np.uint64(value & 0xFFFF)

    def set_cr2(self, value: int) -> None:
        self.view.r["cr2"][self.lane] = np.uint64(value & MASK64)

    # memory (through the lane's page tables; raises HostFault)
    def read_virt(self, gva: int, size: int) -> bytes:
        return self.view.virt_read(self.lane, gva, size)

    def read_u64(self, gva: int) -> int:
        return int.from_bytes(self.read_virt(gva, 8), "little")

    def write_u64(self, gva: int, value: int) -> None:
        self.view.virt_write(
            self.lane, gva, (value & MASK64).to_bytes(8, "little"))


class _FallbackMem:
    """EmuMem-compatible adapter running the EmuCpu oracle against one
    lane's HostView (slow-path single-stepping for UNSUPPORTED uops)."""

    def __init__(self, view: HostView, lane: int):
        self.view = view
        self.lane = lane

    def phys_read(self, gpa: int, size: int) -> bytes:
        return self.view.phys_read(self.lane, gpa, size)

    def phys_write(self, gpa: int, data: bytes) -> None:
        self.view.phys_write(self.lane, gpa, data)

    def phys_read_u64(self, gpa: int) -> int:
        return int.from_bytes(self.phys_read(gpa, 8), "little")

    @property
    def overlay(self):
        # EmuCpu probes `pfn in mem.overlay` for its SMC check; expose the
        # lane's dirty-page predicate as a minimal container.
        view, lane = self.view, self.lane

        class _DirtySet:
            def __contains__(self, pfn):
                return view.page_dirty(lane, pfn)

        return _DirtySet()


def _lane_cpu_state(view: HostView, lane: int, snapshot_cpu: CpuState) -> CpuState:
    """Materialize a CpuState for the oracle from a lane's mirror (fields the
    device doesn't carry — segments, dr, x87 — come from the snapshot)."""
    cpu = snapshot_cpu.copy()
    cpu.set_gpr_list(list(view.r["gpr"][lane]))
    cpu.rip = int(view.r["rip"][lane])
    cpu.rflags = int(view.r["rflags"][lane])
    cpu.fs.base = int(view.r["fs_base"][lane])
    cpu.gs.base = int(view.r["gs_base"][lane])
    cpu.kernel_gs_base = int(view.r["kernel_gs_base"][lane])
    cpu.cr0 = int(view.r["cr0"][lane])
    cpu.cr2 = int(view.r["cr2"][lane])
    cpu.cr3 = int(view.r["cr3"][lane])
    cpu.cr4 = int(view.r["cr4"][lane])
    cpu.cr8 = int(view.r["cr8"][lane])
    cpu.cs.selector = int(view.r["cs"][lane])
    cpu.ss.selector = int(view.r["ss"][lane])
    cpu.lstar = int(view.r["lstar"][lane])
    cpu.star = int(view.r["star"][lane])
    cpu.sfmask = int(view.r["sfmask"][lane])
    cpu.efer = int(view.r["efer"][lane])
    cpu.tsc = int(view.r["tsc"][lane])
    cpu.fpst = [int(v) for v in view.r["fpst"][lane]]
    cpu.fpcw = int(view.r["fpcw"][lane])
    cpu.fpsw = int(view.r["fpsw"][lane])
    cpu.fptw = int(view.r["fptw"][lane])
    cpu.mxcsr = int(view.r["mxcsr"][lane])
    for i in range(16):
        for limb in range(4):
            cpu.zmm[i][limb] = int(view.r["xmm"][lane, i, limb])
    return cpu


def _writeback_lane(view: HostView, lane: int, cpu: EmuCpu) -> None:
    view.r["gpr"][lane] = np.array(cpu.gpr, dtype=np.uint64)
    view.r["rip"][lane] = np.uint64(cpu.rip & MASK64)
    view.r["rflags"][lane] = np.uint64(cpu.rflags & MASK64)
    view.r["fs_base"][lane] = np.uint64(cpu.fs_base & MASK64)
    view.r["gs_base"][lane] = np.uint64(cpu.gs_base & MASK64)
    view.r["kernel_gs_base"][lane] = np.uint64(cpu.kernel_gs_base & MASK64)
    view.r["cr0"][lane] = np.uint64(cpu.cr0 & MASK64)
    view.r["cr2"][lane] = np.uint64(cpu.cr2 & MASK64)
    view.r["cr3"][lane] = np.uint64(cpu.cr3 & MASK64)
    view.r["cr4"][lane] = np.uint64(cpu.cr4 & MASK64)
    view.r["cr8"][lane] = np.uint64(cpu.cr8 & MASK64)
    view.r["cs"][lane] = np.uint64(cpu.cs_sel & 0xFFFF)
    view.r["ss"][lane] = np.uint64(cpu.ss_sel & 0xFFFF)
    # MSR-backed fields a wrmsr fallback may have rewritten
    view.r["lstar"][lane] = np.uint64(cpu.lstar & MASK64)
    view.r["star"][lane] = np.uint64(cpu.star & MASK64)
    view.r["sfmask"][lane] = np.uint64(cpu.sfmask & MASK64)
    view.r["efer"][lane] = np.uint64(cpu.efer & MASK64)
    view.r["tsc"][lane] = np.uint64(cpu.tsc & MASK64)
    view.r["fpst"][lane] = np.array(
        [v & MASK64 for v in cpu.fp_state_list()], dtype=np.uint64)
    view.r["fpcw"][lane] = np.uint64(cpu.fpcw & 0xFFFF)
    view.r["fpsw"][lane] = np.uint64(cpu.fpsw_packed() & 0xFFFF)
    view.r["fptw"][lane] = np.uint64(cpu.fptw & 0xFFFF)
    view.r["mxcsr"][lane] = np.uint64(cpu.mxcsr & MASK64)
    for i in range(16):
        view.r["xmm"][lane, i, 0] = np.uint64(cpu.xmm[i][0] & MASK64)
        view.r["xmm"][lane, i, 1] = np.uint64(cpu.xmm[i][1] & MASK64)
        view.r["xmm"][lane, i, 2] = np.uint64(cpu.ymmh[i][0] & MASK64)
        view.r["xmm"][lane, i, 3] = np.uint64(cpu.ymmh[i][1] & MASK64)


def _apply_page_writes(machine: Machine, lanes, pfns, pages, ok_mask):
    """Apply K buffered (lane, pfn, page) writes into the batched overlay in
    one device call (lax.scan; K is padded to a bucket size host-side).

    Jitted below in a donated variant (overlay mutates in place; off-CPU
    hot path) and a plain one (CPU — donation is unsound there, see
    make_run_chunk); machine_restore copies template leaves so the live
    machine never aliases the pristine template."""
    capacity = machine.overlay.pfn.shape[1]

    def body(overlay, item):
        lane, pfn, page, ok = item
        row = overlay.pfn[lane]
        eq = row == pfn
        idx0 = jnp.argmax(eq).astype(jnp.int32)
        hit = eq[idx0]
        can = overlay.count[lane] < capacity
        slot = jnp.where(hit, idx0, overlay.count[lane] % capacity)
        do = ok & (hit | can)
        data = overlay.data.at[lane, slot].set(
            jnp.where(do, page, overlay.data[lane, slot]))
        # a whole-page host write makes every word of the delta row valid
        valid = overlay.valid.at[lane, slot].set(
            jnp.where(do, jnp.ones_like(overlay.valid[lane, slot]),
                      overlay.valid[lane, slot]))
        pfn_new = overlay.pfn.at[lane, slot].set(
            jnp.where(do, pfn, overlay.pfn[lane, slot]).astype(jnp.int32))
        count = overlay.count.at[lane].add(
            jnp.where(ok & ~hit & can, 1, 0).astype(jnp.int32))
        overflow = overlay.overflow.at[lane].set(
            overlay.overflow[lane] | (ok & ~hit & ~can))
        return overlay._replace(pfn=pfn_new, data=data, valid=valid,
                                count=count, overflow=overflow), None

    overlay, _ = lax.scan(body, machine.overlay, (lanes, pfns, pages, ok_mask))
    # A host write that exceeded the lane's slots was dropped — surface the
    # lane as OVERLAY_FULL instead of running on silently-truncated memory
    # (the guest-store path surfaces the same way via step.py's `ovf`).
    status = jnp.where(
        overlay.overflow
        & (machine.status == jnp.int32(int(StatusCode.RUNNING))),
        jnp.int32(int(StatusCode.OVERLAY_FULL)), machine.status)
    return machine._replace(overlay=overlay, status=status)


_apply_page_writes_donated = partial(
    jax.jit, donate_argnums=(0,))(_apply_page_writes)
_apply_page_writes_plain = jax.jit(_apply_page_writes)


def device_insert_impl(n_pages: int, len_gpr: int, ptr_gpr: int,
                       masked: bool = False):
    """The PURE insert transition (machine, words, lens, pfns, gva_l[,
    active]) -> machine' for a given insert-region geometry — shared by
    the jitted standalone seam below and the megachunk program
    (wtf_tpu/fuzz/megachunk.py), so the two dispatch paths cannot drift.
    See `_make_device_insert` for the slot-claim contract."""

    def impl(machine: Machine, words, lens, pfns, gva_l, *rest):
        n_words = words.shape[1]
        pad = n_pages * (PAGE_SIZE // 4) - n_words
        assert pad >= 0, "testcase words exceed the insert region"
        # `masked` variant (wtf_tpu/tenancy): `active` (bool[L]) limits
        # the insert to one tenant's lanes — inactive lanes keep their
        # overlay rows, counters, status and ABI registers untouched, so
        # per-tenant device batches land with one dispatch per tenant.
        active = rest[0] if masked else None
        n_lanes = machine.status.shape[0]
        w = jnp.pad(words, ((0, 0), (0, pad))) if pad else words
        rows = limbs.pack_u64(
            w.reshape(n_lanes, n_pages, PAGE_SIZE // 8, 2))
        ov = machine.overlay
        capacity = ov.pfn.shape[1]
        # retire rows already holding an insert-region pfn (a pushed
        # host write into the input region; slot leaks until restore)
        dead = (ov.pfn[:, :, None] == pfns[None, None, :]).any(-1)
        if active is not None:
            dead = dead & active[:, None]
        pfn0 = jnp.where(dead, jnp.int32(-1), ov.pfn)
        start = ov.count                                   # i32[L]
        can = start + jnp.int32(n_pages) <= jnp.int32(capacity)
        ok = can if active is None else (can & active)
        li = lax.broadcasted_iota(jnp.int32, (n_lanes, n_pages), 0)
        ridx = jnp.minimum(start[:, None]
                           + lax.broadcasted_iota(
                               jnp.int32, (n_lanes, n_pages), 1),
                           jnp.int32(capacity - 1))
        sel = ok[:, None]
        full = ~can if active is None else (active & ~can)
        overlay = ov._replace(
            data=ov.data.at[li, ridx].set(
                jnp.where(sel[..., None], rows, ov.data[li, ridx])),
            valid=ov.valid.at[li, ridx].set(
                jnp.where(sel[..., None], jnp.uint8(1),
                          ov.valid[li, ridx])),
            pfn=pfn0.at[li, ridx].set(
                jnp.where(sel, jnp.broadcast_to(pfns, (n_lanes, n_pages)),
                          pfn0[li, ridx])),
            count=jnp.where(ok, start + jnp.int32(n_pages), start),
            overflow=ov.overflow | full,
        )
        status = jnp.where(
            full & (machine.status == jnp.int32(int(StatusCode.RUNNING))),
            jnp.int32(int(StatusCode.OVERLAY_FULL)), machine.status)
        gpr = machine.gpr_l
        if active is None:
            gpr = gpr.at[:, len_gpr, 0].set(lens.astype(jnp.uint32))
            gpr = gpr.at[:, len_gpr, 1].set(jnp.uint32(0))
            gpr = gpr.at[:, ptr_gpr, 0].set(gva_l[0])
            gpr = gpr.at[:, ptr_gpr, 1].set(gva_l[1])
        else:
            gpr = gpr.at[:, len_gpr, 0].set(
                jnp.where(active, lens.astype(jnp.uint32),
                          gpr[:, len_gpr, 0]))
            gpr = gpr.at[:, len_gpr, 1].set(
                jnp.where(active, jnp.uint32(0), gpr[:, len_gpr, 1]))
            gpr = gpr.at[:, ptr_gpr, 0].set(
                jnp.where(active, gva_l[0], gpr[:, ptr_gpr, 0]))
            gpr = gpr.at[:, ptr_gpr, 1].set(
                jnp.where(active, gva_l[1], gpr[:, ptr_gpr, 1]))
        return machine._replace(overlay=overlay, gpr_l=gpr,
                                status=status)

    return impl


@lru_cache(maxsize=None)
def _make_device_insert(n_pages: int, n_words: int, len_gpr: int,
                        ptr_gpr: int, donate: bool, masked: bool = False):
    """The fused insert seam for device-generated testcases (wtf_tpu/
    devmut): one in-graph update that lands a whole batch's bytes in the
    per-lane overlay and sets the target ABI registers — the
    mutate-on-device replacement for per-lane target.insert_testcase.

    Claims n_pages FRESH overlay slots per lane starting at the lane's
    current count, so rows the preceding host push allocated (init-time
    target writes to pages OUTSIDE the insert region) survive.  Any
    existing row already holding an insert-region pfn is retired first
    (pfn -> -1): the testcase must win, and a duplicate-pfn row would
    shadow the new one (overlay lookup takes the FIRST match).  A lane
    without n_pages free slots surfaces as OVERLAY_FULL, exactly like
    the host page-write path.  The u32 word stream bitcasts to the
    overlay's u64 words at the pack seam; rows are fully valid (bytes
    past the testcase length are zero by the engine's padded-slab
    contract, so page contents are deterministic).

    `n_words` only keys the memoization (jit re-specializes on shapes);
    the transition itself comes from `device_insert_impl`, the same
    pure function the megachunk program inlines."""
    del n_words
    impl = device_insert_impl(n_pages, len_gpr, ptr_gpr, masked=masked)
    return jax.jit(impl, donate_argnums=(0,) if donate else ())


class Runner:
    """Owns the device batch + decode cache and drives the chunked run loop.

    One Runner == one snapshot loaded on device == N lanes of that snapshot
    (the reference equivalent is one Backend_t instance == one VM; here the
    VM is the whole batch)."""

    def __init__(
        self,
        snapshot: Snapshot,
        n_lanes: int,
        uop_capacity: int = 1 << 14,
        overlay_slots: int = 128,
        edge_bits: int = 17,
        chunk_steps: int = 256,
        deliver_exceptions: Optional[bool] = None,
        registry: Optional[Registry] = None,
        events=None,
        fused_step: str = "off",
        fused_k: int = 32,
        fused_rounds: int = 8,
        fused_resume_steps: int = 1,
        burst_any_tier: Optional[bool] = None,
        tenants=None,
        supervisor: Optional[Supervisor] = None,
        device_decode: bool = False,
    ):
        # Telemetry: metrics registry (private unless the backend/CLI hands
        # in a shared one) + JSONL event sink (NULL swallows when unwired)
        self.registry = registry if registry is not None else Registry()
        self.events = events if events is not None else NULL
        # Every device dispatch seam routes through the supervisor
        # (wtf_tpu/supervise): inert by default (one `is None` test per
        # dispatch), armed by the backend for watchdog/recovery/chaos.
        # A rebuilt Runner SHARES its backend's supervisor so dispatch
        # indices and telemetry survive recovery.
        self.supervisor = supervisor if supervisor is not None \
            else Supervisor(registry=self.registry, events=self.events)
        self.snapshot = snapshot
        self.physmem = snapshot.physmem
        # extra executor-identity tag mixed into compile-event keys
        # (mesh runners dispatch different programs at the same shapes)
        self.exec_sig: Tuple = ()
        self.n_lanes = n_lanes
        self.cache = DecodeCache(capacity=uop_capacity)
        if tenants is None:
            # the image operand executors dispatch against (a mesh runner
            # re-points this at a replicated placement; host-side page
            # reads keep going through self.physmem)
            self.image = snapshot.physmem.image
            self.machine = machine_init(
                snapshot.cpu, n_lanes, uop_capacity, overlay_slots,
                edge_bits)
            self.template = machine_init(
                snapshot.cpu, n_lanes, uop_capacity, overlay_slots=0,
                edge_bits=edge_bits)
            self.tenant_of_lane = np.zeros(n_lanes, dtype=np.int32)
            self._physmems = [snapshot.physmem]
            self._cpu0s = [snapshot.cpu]
        else:
            # heterogeneous batch (wtf_tpu/tenancy): per-lane base-image
            # ids over a stacked image table; per-lane machine state
            # initialized from each tenant's CpuState.  `snapshot` is the
            # table's primary (tenant 0) for the compat surfaces above.
            from wtf_tpu.tenancy.image import build_batch_state

            built = build_batch_state(tenants, n_lanes, uop_capacity,
                                      overlay_slots, edge_bits)
            self.image = built.image
            self.machine = built.machine
            self.template = built.template
            self.tenant_of_lane = built.tenant_of_lane
            self._physmems = built.physmems
            self._cpu0s = built.cpus
        self.limit = 0
        self.chunk_steps = chunk_steps
        # Guest exception delivery (reference: every fault is serviced by
        # the guest through bochs' IDT emulation / KVM event injection).
        # Auto mode turns it on exactly when the snapshot carries an IDT;
        # IDT-less synthetic guests keep the terminal-fault behavior.
        # Heterogeneous batches gate per lane: the servicing loop only
        # delivers through tenants that carry an IDT (cpu0_of), so an
        # IDT-less tenant's faults stay terminal exactly as they do solo.
        if deliver_exceptions is None:
            deliver_exceptions = any(
                cpu.idtr.limit > 0 for cpu in self._cpu0s)
        self.deliver_exceptions = deliver_exceptions
        # Donation only off-CPU: XLA CPU miscompiles donated machines on
        # this graph (see make_run_chunk's caveat) and donation buys
        # nothing on a host backend anyway.
        self._donate = jax.default_backend() != "cpu"
        # Fused Pallas fast path (interp/pstep.py): per chunk the runner
        # dispatches the fused kernel first, then a SHORT XLA chunk that
        # resumes lanes the kernel parked (NEEDS_XLA) — the park-and-
        # resume ladder.  "auto" enables it only where the per-kernel
        # dispatch win exists (a real TPU backend); the CPU stand-in runs
        # it when forced with "on" (kernel under pallas interpret mode).
        if fused_step not in ("off", "auto", "on"):
            raise ValueError(
                f"fused_step must be off|auto|on, got {fused_step!r}")
        self.fused_step = fused_step
        self.fused_enabled = fused_step == "on" or (
            fused_step == "auto" and jax.default_backend() == "tpu")
        self.fused_k = fused_k
        self.fused_rounds = fused_rounds
        self.fused_resume_steps = fused_resume_steps
        # Device-resident x86 decode (interp/devdec): megachunk windows
        # service decode misses in-graph and host servicing rounds pull
        # only the missing lanes' code windows instead of full page
        # views.  The host decoder stays the authoritative oracle: every
        # device-published entry is re-decoded and cross-checked at
        # harvest (uoptable.adopt_device_entries).
        self.device_decode = device_decode
        if self.fused_enabled:
            from wtf_tpu.interp.pstep import fused_available

            if not fused_available():
                if fused_step == "on":
                    raise RuntimeError(
                        "fused_step='on' but this jax build cannot run "
                        "pallas kernels (interp/pstep.py fused_available)")
                self.fused_enabled = False  # auto: degrade to the XLA path
        self.lane_errors: Dict[int, str] = {}
        self._smc_updates: Dict[int, int] = {}
        # Adaptive chunk growth for deep executions (BASELINE config 5 is
        # 100M instructions/testcase): once a chunk completes with nothing
        # to service and lanes still running — i.e. the decode cache is
        # warm and the guest is just executing — step up to a larger
        # chunk so host round trips stop dominating.  Any serviceable
        # status drops back to the base size for responsive servicing.
        # Sizes are sparse (x16) to bound the number of XLA compiles.
        self.adaptive_chunks = True
        # x16 growth rungs, min-capped so the TOP rung always reaches
        # 65536 (the plain x16 ladder stops short for most bases — e.g.
        # 512 -> 8192 — and a deep execution, BASELINE config 5's 100M
        # instructions, then pays 8x the host round trips)
        self._chunk_sizes = [chunk_steps]
        while self._chunk_sizes[-1] < (1 << 16):
            self._chunk_sizes.append(
                min(self._chunk_sizes[-1] * 16, 1 << 16))
        self._chunk_level = 0
        # consecutive service rounds per lane (oracle burst sizing)
        self._fallback_streak: Dict[int, int] = {}
        # The burst's any-instruction tier amortizes EXPENSIVE dispatch
        # round trips (a real chip, possibly behind a tunnel); on the CPU
        # platform a dispatch is ~free and the device executes glue
        # instructions faster than the Python oracle, so the platform
        # default is off there.  The explicit override (config/CLI
        # --burst-any-tier) exists so the tier can run — and be benched —
        # on the CPU platform too (VERDICT weak item 4).
        if burst_any_tier is None:
            burst_any_tier = jax.default_backend() != "cpu"
        self.burst_any_tier = burst_any_tier
        # (lane, uop-entry) coverage bits and (lane, edge-index) edge bits
        # owed by oracle burst steps; OR-ed into the device bitmaps at
        # the next push
        self._pending_cov: List[Tuple[int, int]] = []
        self._pending_edge: List[Tuple[int, int]] = []
        # run statistics (reference PrintRunStats role, backend.h:218) —
        # a dict facade over registry counters, so the same numbers feed
        # print_run_stats, the heartbeat line, and the JSONL stream.
        # fallbacks_by_opclass: oracle single-steps keyed by the uop's
        # opcode class name, so campaign output can attribute WHY lanes
        # left the device path (VERDICT r5 item 3).
        self.stats = StatsDict(
            self.registry, "runner",
            fields=("chunks", "decodes", "decodes_prefetched",
                    "decode_windows_gathered", "fallbacks",
                    "fallback_burst_steps", "smc_updates",
                    "bp_dispatches", "exceptions_delivered"),
            gauges=("max_chunk_steps",),
            labeled=("fallbacks_by_opclass",))
        self.stats["max_chunk_steps"] = chunk_steps
        self.supervisor.attach_runner(self)

    # -- per-lane tenant routing (wtf_tpu/tenancy; single-image batches
    # are tenant 0 everywhere) ----------------------------------------------
    def tenant_of(self, lane: int) -> int:
        return int(self.tenant_of_lane[lane])

    def cpu0_of(self, lane: int):
        """The lane's snapshot CpuState (oracle fallback segments/x87,
        IDT/TSS anchors for exception delivery)."""
        return self._cpu0s[self.tenant_of(lane)]

    def lane_physmem(self, lane: int):
        """The lane's base-image PhysMem (host-side page reads)."""
        return self._physmems[self.tenant_of(lane)]

    def _deliver_lane(self, lane: int) -> bool:
        return self._cpu0s[self.tenant_of(lane)].idtr.limit > 0

    # -- device dispatch surface (the seams MeshRunner re-points) ----------
    def device_tab(self):
        """The dispatch-ready uop table (mesh runners hand back a
        replicated placement of the same pytree)."""
        return self.cache.device()

    def _chunk_callable(self, n_steps: int):
        """The executor run() dispatches for one chunk of `n_steps`
        (memoized in step._CHUNK_CACHE; mesh runners swap in the
        shard_map executor, meshrun/executor.py)."""
        return make_run_chunk(n_steps, donate=self._donate)

    def _fused_callables(self):
        """(fused kernel, resume leg) pair for _fused_dispatch."""
        from wtf_tpu.interp.pstep import make_run_fused, make_run_resume

        return (make_run_fused(self.fused_k),
                make_run_resume(self.fused_resume_steps,
                                donate=self._donate))

    def megachunk_callable(self, max_batches: int, n_pages: int,
                           len_gpr: int, ptr_gpr: int, rounds: int):
        """The one-dispatch multi-batch window program (wtf_tpu/fuzz/
        megachunk.py) — the seam the megachunk driver dispatches, so
        mesh runners can swap in the shard_map variant with the same
        signature.  `fused_enabled` is read HERE, at call time, so the
        degradation ladder's no-fused rung (supervise.DegradationLadder
        toggling runner.fused_enabled) also swaps the window's step
        engine back to the XLA ladder."""
        from wtf_tpu.fuzz.megachunk import make_megachunk

        return make_megachunk(max_batches, n_pages, len_gpr, ptr_gpr,
                              rounds, deliver=self.deliver_exceptions,
                              devdec=self.device_decode,
                              fused=bool(self.fused_enabled),
                              fused_k=self.fused_k,
                              fused_resume_steps=self.fused_resume_steps,
                              donate=self._donate)

    def devdec_operands(self) -> Tuple:
        """Extra megachunk operands for the in-graph decoder: the live
        cache count plus the pending-breakpoint key vector, padded to a
        pow2 bucket (0 is a VALID key, so the live length rides along).
        Empty tuple when --device-decode is off, so dispatch sites can
        always splat it."""
        if not self.device_decode:
            return ()
        keys = sorted(self.cache.pending_bps)
        bucket = 8
        while bucket < len(keys):
            bucket *= 2
        padded = np.zeros(bucket, dtype=np.uint64)
        for j, k in enumerate(keys):
            padded[j] = np.uint64(k)
        return (jnp.int32(self.cache.count), jnp.asarray(padded),
                jnp.int32(len(keys)))

    def megachunk_place(self, slab_first, slab_rest, seeds):
        """Placement hook for one window's operands — identity on a
        single device; the mesh runner replicates the slabs and shards
        the seed stream."""
        return slab_first, slab_rest, seeds

    def devmut_generate(self, rounds: int, data, lens, cumw, seeds):
        """Dispatch one devmut batch generation (wtf_tpu/devmut) — the
        seam the device mutator drives, so mesh runners can run the
        generator per shard with the slab replicated and the seed stream
        lane-sharded."""
        from wtf_tpu.devmut.engine import make_generate

        return make_generate(rounds)(data, lens, cumw, jnp.asarray(seeds))

    # -- checkpoint/resume (wtf_tpu/resume) --------------------------------
    def checkpoint_state(self) -> dict:
        """The runner state a campaign checkpoint must carry: the decode
        cache in insertion order (coverage-bitmap bit i is cache entry i,
        so restored aggregate bitmaps are meaningless without identical
        indices) plus the SMC thrash counters that gate the per-rip
        fallback cutover.  Machine state needs nothing — checkpoints are
        taken at batch boundaries, where the machine is freshly
        restored to the snapshot."""
        return {
            "cache": self.cache.checkpoint_entries(),
            # (tenant, rip) keys flatten to JSON-able triples
            "smc_updates": [[t, r, n]
                            for (t, r), n in self._smc_updates.items()],
        }

    def restore_state(self, state: dict) -> None:
        """Restore checkpoint_state() output into a freshly-initialized
        runner (empty decode cache; breakpoints from target.init may
        already be pending — add() re-arms them)."""
        self.cache.restore_entries(state.get("cache", []))
        smc = state.get("smc_updates", [])
        if isinstance(smc, dict):
            # pre-tenancy checkpoints: {rip: n} means tenant 0
            self._smc_updates = {(0, int(k)): int(v)
                                 for k, v in smc.items()}
        else:
            self._smc_updates = {(int(t), int(r)): int(n)
                                 for t, r, n in smc}

    # -- trace-capture hooks (ablate.py / bench.py / wtf_tpu.analysis) -----
    def executor_operands(self) -> Tuple:
        """(tab, image, machine, limit) — the chunk executor's positional
        operands, exactly as run() dispatches them.  The export hook for
        benches and the static analyzer; no private-state reach-in."""
        return (self.device_tab(), self.image, self.machine,
                jnp.uint64(self.limit))

    def chunk_executor(self, n_steps: Optional[int] = None,
                       donate: Optional[bool] = None):
        """The jitted chunk executor this runner dispatches (memoized in
        step._CHUNK_CACHE).  Defaults follow the runner's own size and
        platform donation policy."""
        return make_run_chunk(
            self.chunk_steps if n_steps is None else n_steps,
            donate=self._donate if donate is None else donate)

    # -- host memory access ------------------------------------------------
    def view(self) -> HostView:
        return HostView(self)

    # -- mutate-on-device insert seam (wtf_tpu/devmut) ---------------------
    def device_insert(self, words, lens, pfns, gva: int,
                      len_gpr: int, ptr_gpr: int, active=None) -> None:
        """Insert a device-generated batch without a host round-trip:
        `words` (u32[L, W]) / `lens` (i32[L]) — typically straight from
        devmut's generate dispatch — land in overlay slots [0, n_pages)
        of every lane and the target's ABI registers are set in the same
        program.  Call on a freshly restored machine (the overlay must
        be empty; the fuzz loop's restore→insert ordering guarantees
        it)."""
        n_pages = len(pfns)
        capacity = self.machine.overlay.pfn.shape[1]
        if n_pages > capacity:
            raise ValueError(
                f"device-insert region spans {n_pages} pages but lanes "
                f"have only {capacity} overlay slots — raise "
                f"overlay_slots or shrink the mutator/spec max_len")
        masked = active is not None
        fn = _make_device_insert(n_pages, words.shape[1], len_gpr, ptr_gpr,
                                 self._donate, masked=masked)
        key = ("devins", n_pages, words.shape[1], len_gpr, ptr_gpr,
               self.n_lanes, self._donate, masked, self.exec_sig)
        if key not in _DISPATCHED_EXECUTORS:
            _DISPATCHED_EXECUTORS.add(key)
            self.events.emit("compile", kind="device-insert",
                             pages=n_pages, words=int(words.shape[1]))
        gva_l = np.array([gva & 0xFFFF_FFFF, (gva >> 32) & 0xFFFF_FFFF],
                         dtype=np.uint32)
        extra = (jnp.asarray(np.asarray(active, dtype=bool)),) if masked \
            else ()
        self.machine = self.supervisor.dispatch(
            "device-insert", fn, self.machine, words, lens,
            jnp.asarray(np.asarray(pfns, dtype=np.int32)),
            jnp.asarray(gva_l), *extra, sync=lambda m: m.status)

    def push(self, view: HostView) -> None:
        """Apply a HostView's mutations (registers + buffered page writes +
        burst coverage bits) back to the device batch."""
        updates = {
            _LIMB_FIELDS.get(name, name): jnp.asarray(
                _unpack_mirror(name, view.r[name])
                if name in _LIMB_FIELDS else view.r[name])
            for name in _MIRROR_FIELDS
        }
        self.machine = self.machine._replace(**updates)
        def _apply_bits(bitmap, pending):
            # combine host-side to unique (lane, word) pairs so the
            # device read-modify-write scatter is deterministic
            acc: Dict[Tuple[int, int], int] = {}
            for lane, bit in pending:
                key = (lane, bit >> 5)
                acc[key] = acc.get(key, 0) | (1 << (bit & 31))
            lanes = jnp.asarray([k[0] for k in acc], dtype=jnp.int32)
            words = jnp.asarray([k[1] for k in acc], dtype=jnp.int32)
            bits = jnp.asarray(list(acc.values()), dtype=jnp.uint32)
            return bitmap.at[lanes, words].set(bitmap[lanes, words] | bits)

        if self._pending_cov:
            self.machine = self.machine._replace(
                cov=_apply_bits(self.machine.cov, self._pending_cov))
            self._pending_cov.clear()
        if self._pending_edge:
            self.machine = self.machine._replace(
                edge=_apply_bits(self.machine.edge, self._pending_edge))
            self._pending_edge.clear()
        if view.pending:
            items = sorted(view.pending.items())
            k = len(items)
            bucket = 8
            while bucket < k:
                bucket *= 2
            lanes = np.zeros(bucket, dtype=np.int32)
            pfns = np.full(bucket, -2, dtype=np.int32)
            pages = np.zeros((bucket, PAGE_SIZE), dtype=np.uint8)
            valid = np.zeros(bucket, dtype=bool)
            for j, ((lane, pfn), page) in enumerate(items):
                lanes[j] = lane
                pfns[j] = pfn
                pages[j] = np.frombuffer(bytes(page), dtype=np.uint8)
                valid[j] = True
            apply_writes = (_apply_page_writes_donated if self._donate
                            else _apply_page_writes_plain)
            self.machine = apply_writes(
                self.machine, jnp.asarray(lanes), jnp.asarray(pfns),
                jnp.asarray(pages.view(np.uint64)), jnp.asarray(valid))
            view.pending.clear()

    # -- servicing ---------------------------------------------------------
    def _decode_at(self, view: HostView, lane: int, rip: int,
                   prefetched=None) -> bool:
        """Decode the instruction at `rip` through `lane`'s memory view and
        publish it.  Returns False on hard failure (lane made terminal).

        `prefetched` is an optional (window, fault, pfn0, pfn14) tuple
        from the device window gather (--device-decode): same bytes,
        same fault/pfn facts, no HostView page pulls for the fetch."""
        if prefetched is not None:
            window, faulted, pfn0, pfn14 = prefetched
        else:
            try:
                window = view.virt_read(lane, rip, 15)
                pfn0 = view.translate(lane, rip) >> PAGE_SHIFT
                faulted = False
            except HostFault:
                faulted = True
        if faulted:
            self.lane_errors[lane] = f"fetch fault @ {rip:#x}"
            # host-detected fault: mirror the device's CTR_MEM_FAULT
            # accounting (a device page walk would have counted it)
            view.r["ctr"][lane, CTR_MEM_FAULT] += np.uint32(1)
            view.set_status(lane, StatusCode.PAGE_FAULT)
            view.r["fault_gva"][lane] = np.uint64(rip & MASK64)
            view.r["fault_write"][lane] = np.int32(0)
            return False
        uop = decode(window, rip)
        if prefetched is not None:
            # a successful 15-byte window read guarantees the last
            # instruction byte translates; its frame is pfn0 unless the
            # instruction itself crosses into the window's second page
            crosses = (rip & (PAGE_SIZE - 1)) + max(uop.length - 1, 0) \
                >= PAGE_SIZE
            pfn1 = pfn14 if crosses else pfn0
        else:
            try:
                pfn1 = view.translate(
                    lane, rip + max(uop.length - 1, 0)) >> PAGE_SHIFT
            except HostFault:
                pfn1 = pfn0
        self.cache.add(rip, uop, pfn0, pfn1, tenant=self.tenant_of(lane))
        self.stats["decodes"] += 1
        self._prefetch_block(view, lane, uop, rip)
        return True

    # Decode-ahead bounds: block prefetch publishes up to this many extra
    # instructions per miss, and never within this margin of cache capacity
    PREFETCH_BUDGET = 48
    _PREFETCH_MARGIN = 64

    def _prefetch_block(self, view: HostView, lane: int, uop, rip: int) -> None:
        """Recursive-descent decode-ahead from a fresh miss: follow the
        fallthrough and direct branch targets so a basic block's worth of
        code publishes in ONE servicing round instead of one full
        pull/push/dispatch round trip per instruction — the dominant
        cold-start cost when the chip sits behind a tunnel (PERF.md's
        host<->device term).  Wrong-path prefetches are harmless: decode
        is deterministic on bytes, entries are only consulted at executed
        rips, and OPC_INVALID results are simply not published."""
        def succs(u, at):
            nxt = (at + u.length) & MASK64
            opc = u.opc
            if opc in (U.OPC_RET, U.OPC_IRET, U.OPC_HLT, U.OPC_INT,
                       U.OPC_INT1, U.OPC_INVALID, U.OPC_SYSCALL):
                return ()
            if opc == U.OPC_JMP:
                return ((nxt + u.imm) & MASK64,) if u.src_kind == U.K_IMM \
                    else ()
            if opc == U.OPC_JCC:
                return (nxt, (nxt + u.imm) & MASK64)
            if opc == U.OPC_CALL and u.src_kind == U.K_IMM:
                return (nxt, (nxt + u.imm) & MASK64)
            return (nxt,)

        budget = self.PREFETCH_BUDGET
        tenant = self.tenant_of(lane)
        work = list(succs(uop, rip))
        while work and budget > 0:
            if self.cache.count >= self.cache.capacity - self._PREFETCH_MARGIN:
                return
            at = work.pop()
            if self.cache.has(at, tenant):
                continue
            try:
                window = view.virt_read(lane, at, 15)
                pfn0 = view.translate(lane, at) >> PAGE_SHIFT
            except HostFault:
                continue
            u2 = decode(window, at)
            if u2.opc == U.OPC_INVALID:
                continue  # probably swept into data; let a real miss decide
            try:
                pfn1 = view.translate(
                    lane, at + max(u2.length - 1, 0)) >> PAGE_SHIFT
            except HostFault:
                pfn1 = pfn0
            self.cache.add(at, u2, pfn0, pfn1, tenant=tenant)
            self.stats["decodes_prefetched"] += 1
            budget -= 1
            work.extend(succs(u2, at))

    def _service_decode(self, view: HostView, lanes: List[int]) -> None:
        windows = (self._gather_code_windows(view, lanes)
                   if self.device_decode else {})
        done: Set[Tuple[int, int]] = set()
        for lane in lanes:
            rip = view.get_rip(lane)
            key = (self.tenant_of(lane), rip)
            if key not in done:
                if not self.cache.has(rip, key[0]):
                    if not self._decode_at(view, lane, rip,
                                           prefetched=windows.get(lane)):
                        continue
                done.add(key)
            view.set_status(lane, StatusCode.RUNNING)

    def _gather_code_windows(self, view: HostView, lanes: List[int]):
        """--device-decode satellite: ONE device dispatch gathers the
        missing lanes' 15-byte code windows (plus fault/pfn walk facts)
        through the in-kernel page walk + overlay probe, so servicing a
        decode miss transfers k x 15 bytes instead of riding the full
        HostView page-pull path.  Lanes whose rip is already cached (or
        duplicated within the round) are skipped on host before the
        gather."""
        from wtf_tpu.interp import devdec

        want: List[int] = []
        seen: Set[Tuple[int, int]] = set()
        for lane in lanes:
            rip = view.get_rip(lane)
            key = (self.tenant_of(lane), rip)
            if key in seen or self.cache.has(rip, key[0]):
                continue
            seen.add(key)
            want.append(lane)
        if not want:
            return {}
        # pow2 bucket bounds jit re-specialization like push()'s writes
        bucket = 8
        while bucket < len(want):
            bucket *= 2
        idx = np.zeros(bucket, dtype=np.int32)
        idx[:len(want)] = want
        m = self.machine
        out = self.supervisor.dispatch(
            "device-decode", devdec.gather_windows, self.image,
            m.overlay, m.cr3, jnp.asarray(view.r["rip"]),
            jnp.asarray(idx), sync=lambda o: o[1])
        wins, faults, pfn0s, pfn14s = jax.device_get(out)
        self.stats["decode_windows_gathered"] += len(want)
        return {lane: (wins[j].tobytes(), bool(faults[j]),
                       int(pfn0s[j]), int(pfn14s[j]))
                for j, lane in enumerate(want)}

    def _service_smc(self, view: HostView, lanes: List[int]) -> None:
        for lane in lanes:
            rip = view.get_rip(lane)
            skey = (self.tenant_of(lane), rip)
            n = self._smc_updates.get(skey, 0) + 1
            self._smc_updates[skey] = n
            if n > 16:
                # cache thrash: lanes disagree about the bytes at this rip;
                # fall back to the oracle for this lane instead of ping-
                # ponging the shared entry (documented batch-vs-VM tradeoff)
                self._fallback_step(view, lane)
                continue
            try:
                window = view.virt_read(lane, rip, 15)
                pfn0 = view.translate(lane, rip) >> PAGE_SHIFT
            except HostFault:
                view.r["ctr"][lane, CTR_MEM_FAULT] += np.uint32(1)
                view.set_status(lane, StatusCode.PAGE_FAULT)
                continue
            uop = decode(window, rip)
            try:
                pfn1 = view.translate(lane, rip + max(uop.length - 1, 0)) >> PAGE_SHIFT
            except HostFault:
                pfn1 = pfn0
            self.cache.update(rip, uop, pfn0, pfn1, tenant=skey[0])
            self.stats["smc_updates"] += 1
            view.set_status(lane, StatusCode.RUNNING)

    def _fallback_step(self, view: HostView, lane: int) -> None:
        """Single-step one lane on the EmuCpu oracle (the host slow path for
        instructions outside the device subset)."""
        self.stats["fallbacks"] += 1
        # per-opclass attribution (VERDICT r5 item 3: a campaign's fallback
        # total was a single opaque number — e.g. real_pe's 1321 — with no
        # way to tell WHICH instruction classes keep diverting)
        uop = self.cache.uop_at(view.get_rip(lane), self.tenant_of(lane))
        opclass = (_OPC_NAMES.get(uop.opc, f"opc{uop.opc}")
                   if uop is not None else "undecoded")
        by_class = self.stats["fallbacks_by_opclass"]
        by_class[opclass] = by_class.get(opclass, 0) + 1
        cpu_state = _lane_cpu_state(view, lane, self.cpu0_of(lane))
        emu = EmuCpu(_FallbackMem(view, lane), cpu_state)
        icount_before = int(view.r["icount"][lane])
        emu.icount = icount_before
        emu.rdrand_state = int(view.r["rdrand"][lane])
        try:
            emu.step()
        except GuestCrash:
            view.set_status(lane, StatusCode.CRASH)
            view.r["fault_gva"][lane] = np.uint64(emu.rip & MASK64)
            return
        except MemFault as e:
            # mirror the device's CTR_MEM_FAULT accounting: a device page
            # walk would have counted this fault in-graph
            view.r["ctr"][lane, CTR_MEM_FAULT] += np.uint32(1)
            view.set_status(lane, StatusCode.PAGE_FAULT)
            view.r["fault_gva"][lane] = np.uint64(e.gva & MASK64)
            view.r["fault_write"][lane] = np.int32(1 if e.write else 0)
            return
        except DivideError:
            view.set_status(lane, StatusCode.DIVIDE_ERROR)
            return
        except UnsupportedInsn as e:
            self.lane_errors[lane] = str(e)
            view.set_status(lane, StatusCode.HARD_ERROR)
            return
        _writeback_lane(view, lane, emu)
        view.r["icount"][lane] = np.uint64(emu.icount)
        # keep CTR_INSTR == icount exactly (the differential-test anchor):
        # every oracle-retired instruction lands in the device counter block
        view.r["ctr"][lane, CTR_INSTR] += np.uint32(emu.icount - icount_before)
        view.r["rdrand"][lane] = np.uint64(emu.rdrand_state)
        view.r["bp_skip"][lane] = np.int32(0)
        if emu.cr3_event is not None \
                and emu.cr3_event != self.cpu0_of(lane).cr3:
            view.set_status(lane, StatusCode.CR3_CHANGE)
        elif self.limit and emu.icount >= self.limit:
            view.set_status(lane, StatusCode.TIMEDOUT)
        else:
            view.set_status(lane, StatusCode.RUNNING)

    # Opcode classes only the oracle executes.  The burst below may run
    # AHEAD through these; every burst-stepped rip is published to the
    # decode cache and its coverage bit is OR-ed into the device bitmap
    # at the next push (`_pending_cov`), so burst lanes report exactly
    # the coverage a per-dispatch servicing loop would have.  A device-
    # executable instruction ends the burst so its coverage/edge bits
    # land through the normal device path.
    _ORACLE_OPCS = frozenset((
        U.OPC_SSECVT, U.OPC_PCLMUL, U.OPC_STACKSTR, U.OPC_IRET,
    ))
    # x87 executes on-device except the state movers
    _X87_ORACLE_SUBS = frozenset((
        U.X87_FXSAVE, U.X87_FXRSTOR, U.X87_XSAVE, U.X87_XRSTOR,
    ))

    _BRANCH_OPCS = frozenset((U.OPC_JMP, U.OPC_JCC, U.OPC_CALL, U.OPC_RET))

    # statuses whose oracle step COMMITTED (rip advanced): the edge-hash
    # bit is owed even when the run stops right after the branch
    _COMMITTED_STATUSES = frozenset((
        StatusCode.RUNNING, StatusCode.TIMEDOUT, StatusCode.CR3_CHANGE))

    def _entry_at(self, view: HostView, lane: int,
                  rip: int) -> Optional[Tuple[int, "U.Uop"]]:
        """(uop-table entry index, uop) at `rip`, publishing the decode on
        a miss; None when the bytes can't be fetched or don't decode."""
        tenant = self.tenant_of(lane)
        uop = self.cache.uop_at(rip, tenant)
        if uop is None:
            try:
                window = view.virt_read(lane, rip, 15)
                pfn0 = view.translate(lane, rip) >> PAGE_SHIFT
            except HostFault:
                return None
            uop = decode(window, rip)
            if uop.opc == U.OPC_INVALID:
                return None
            try:
                pfn1 = view.translate(
                    lane, rip + max(uop.length - 1, 0)) >> PAGE_SHIFT
            except HostFault:
                pfn1 = pfn0
            self.cache.add(rip, uop, pfn0, pfn1, tenant=tenant)
        return self.cache.entry_index(rip, tenant), uop

    def _is_oracle_uop(self, uop) -> bool:
        return (uop.opc in self._ORACLE_OPCS
                or (uop.opc == U.OPC_X87
                    and uop.sub in self._X87_ORACLE_SUBS))

    def _fallback_burst(self, view: HostView, lane: int) -> None:
        """Service an UNSUPPORTED lane; when the lane has needed the oracle
        for consecutive rounds, keep stepping it host-side so its progress
        per round grows instead of staying one-instruction-per-chunk.

        Two burst tiers: a short streak runs ahead through further
        oracle-class instructions only; a chronic streak (>= 4 rounds —
        e.g. a lane crunching denormal-range FP where every arith op
        diverts) runs ahead through ANY instruction.  Coverage parity is
        preserved both ways: every burst-stepped rip's coverage bit and
        every branch's edge-hash bit are recorded host-side
        (_pending_cov/_pending_edge) and OR-ed into the device bitmaps at
        the next push.  Stops at armed breakpoints (the device checks
        them pre-execution) and on any terminal/fault status."""
        self._fallback_step(view, lane)
        streak = self._fallback_streak.get(lane, 0) + 1
        self._fallback_streak[lane] = streak
        if streak < 2:
            return
        budget = min(32 << min(streak, 6), 1024)
        # The any-instruction tier is kept SHORT: it exists to carry a
        # chronic lane across the device-class glue between diverting
        # instructions (denormal FP every few ops), not to steal long
        # normal stretches from the device, which executes them faster.
        #
        # FP-reproducibility caveat: this tier runs device-class SSE/x87
        # FP on the host oracle (numpy).  On the CPU backend both engines
        # are IEEE bit-exact, but on a real TPU the device's div/sqrt
        # rounding is the platform's (the documented fast-path fidelity
        # delta, step.py SSE-FP block) — so WHERE an instruction executes
        # can change low FP bits there.  A crash found through a burst
        # therefore reproduces under `--backend=emu` (all-oracle) but a
        # TPU re-run of the same input may divert at different points.
        # The tier is off on CPU (burst_any_tier) and bounded here, so
        # the exposure is a 24-instruction window per chronic round.
        any_budget = 24 if (streak >= 4 and self.burst_any_tier) else 0
        ebits = self.machine.edge.shape[1] * 32
        from wtf_tpu.utils.hashing import mix64

        while budget > 0:
            if view.get_status(lane) != StatusCode.RUNNING:
                return
            rip = view.get_rip(lane)
            if self.cache.has_breakpoint(rip, self.tenant_of(lane)):
                return
            entry = self._entry_at(view, lane, rip)
            if entry is None:
                return
            idx, uop = entry
            if not self._is_oracle_uop(uop):
                if any_budget <= 0:
                    return
                any_budget -= 1
            self._fallback_step(view, lane)
            # the coverage/edge bits the device dispatch would have set.
            # TIMEDOUT/CR3_CHANGE are set AFTER the oracle committed the
            # step (the branch executed; only the run stops afterwards),
            # so those statuses still record the edge — the device path
            # likewise sets edge bits on a committing step that trips the
            # instruction budget (exact-parity claim in the docstring).
            self._pending_cov.append((lane, idx))
            if (uop.opc in self._BRANCH_OPCS
                    and view.get_status(lane) in self._COMMITTED_STATUSES):
                eh = mix64(rip) ^ view.get_rip(lane)
                self._pending_edge.append((lane, eh & (ebits - 1)))
            self.stats["fallback_burst_steps"] += 1
            budget -= 1

    def _service_exception(self, view: HostView, lane: int) -> bool:
        """Vector a faulted lane through the guest IDT (reference: bochs
        delivers internally, bochscpu_backend.cc:917-999; KVM injects,
        kvm_backend.cc:2019-2042).  On success the lane resumes RUNNING at
        the guest handler; an undeliverable fault (absent gate, unmapped
        IDT/TSS/kernel stack — the double-fault analog) keeps the lane's
        terminal status and the crash naming that comes with it.  Returns
        whether the exception was delivered."""
        status = view.get_status(lane)
        ctx = _LaneCtx(view, lane, self.cpu0_of(lane))
        try:
            if status == StatusCode.PAGE_FAULT:
                gva = int(view.r["fault_gva"][lane])
                write = bool(view.r["fault_write"][lane])

                def reads(g):
                    try:
                        view.translate(lane, g, write=False)
                        return True
                    except HostFault:
                        return False

                deliver_page_fault(ctx, gva, write, reads)
            elif status == StatusCode.DIVIDE_ERROR:
                deliver_exception(ctx, VEC_DE)
            else:
                return False
        except (DeliveryFailed, HostFault) as e:
            self.lane_errors.setdefault(lane, f"undelivered exception: {e}")
            return False
        self.stats["exceptions_delivered"] += 1
        view.set_status(lane, StatusCode.RUNNING)
        return True

    # -- fused Pallas fast path (interp/pstep.py) --------------------------
    def _fused_dispatch(self, tab, limit, shape_sig, spans) -> None:
        """One fused 'chunk': `fused_rounds` pairs of (Pallas kernel for up
        to fused_k hot steps) -> (unpark + fused_resume_steps XLA steps for
        parked lanes).  With resume_steps=1 every XLA-retired instruction
        is exactly one park event, so fused occupancy equals the hot
        fraction of the instruction stream.  Rounds stop early once no
        lane is RUNNING (everything needs host servicing or finished)."""
        run_fused, run_resume = self._fused_callables()
        fkey = ("fused", self.fused_k, self.n_lanes, shape_sig,
                self.exec_sig)
        if fkey not in _DISPATCHED_EXECUTORS:
            _DISPATCHED_EXECUTORS.add(fkey)
            self.events.emit("compile", kind="pallas-fused",
                             k_steps=self.fused_k)
        rkey = ("resume", self.fused_resume_steps, self._donate,
                self.n_lanes, shape_sig, self.exec_sig)
        if rkey not in _DISPATCHED_EXECUTORS:
            _DISPATCHED_EXECUTORS.add(rkey)
            self.events.emit("compile",
                             chunk_steps=self.fused_resume_steps,
                             donate=self._donate, kind="fused-resume")
        for _ in range(max(self.fused_rounds, 1)):
            with spans.span("pallas-step") as sp:
                self.machine = self.supervisor.dispatch(
                    "fused", run_fused, tab, self.image,
                    self.machine, limit,
                    steps=self.fused_k, sync=lambda m: m.status)
                sp.fence(self.machine.status)
            with spans.span("device-step") as sp:
                # resumes parked lanes; ends with NO lane in NEEDS_XLA
                self.machine = self.supervisor.dispatch(
                    "fused-resume", run_resume, tab, self.image,
                    self.machine, limit,
                    steps=self.fused_resume_steps,
                    sync=lambda m: m.status)
                sp.fence(self.machine.status)
            # copy, not a view (donation note in run())
            status = np.array(jax.device_get(self.machine.status))
            if not (status == int(StatusCode.RUNNING)).any():
                break

    # -- run loop ----------------------------------------------------------
    def run(
        self,
        bp_handler: Optional[Callable[["Runner", HostView, int], None]] = None,
        max_chunks: int = 1 << 20,
    ) -> np.ndarray:
        """Drive the batch until every lane reaches a terminal status.

        `bp_handler(runner, view, lane)` services BREAKPOINT lanes (the
        backend layer supplies it; reference breakpoint dispatch is
        backend.h:231 + kvm_backend.cc:1256-1369).  Returns the final status
        array."""
        tab = self.device_tab()
        # jit also keys on operand shapes: a second Runner with the same
        # (size, donate, lanes) but a different physmem image or uop-table
        # capacity still pays a real XLA compile and must report it
        shape_sig = tuple(
            a.shape for a in jax.tree_util.tree_leaves(
                (tab, self.image)))
        limit = jnp.uint64(self.limit)
        self._chunk_level = 0
        self._fallback_streak = {}
        spans = self.registry.spans
        undeliverable: Set[int] = set()  # lanes whose IDT delivery failed
        for _ in range(max_chunks):
            if self.fused_enabled:
                self._fused_dispatch(tab, limit, shape_sig, spans)
            else:
                size = (self._chunk_sizes[self._chunk_level]
                        if self.adaptive_chunks else self.chunk_steps)
                self.stats["max_chunk_steps"] = max(
                    self.stats["max_chunk_steps"], size)
                run_chunk = self._chunk_callable(size)
                compile_key = (size, self._donate, self.n_lanes, shape_sig,
                               self.exec_sig)
                if compile_key not in _DISPATCHED_EXECUTORS:
                    # the first dispatch of this executor shape pays the
                    # XLA compile (jit compiles on call, not on
                    # make_run_chunk); its wall shows up inside the next
                    # device-step span.  Process-global like the jit cache
                    # itself — a second Runner at the same (size, donate,
                    # lanes) dispatches warm and must not re-report a
                    # compile.
                    _DISPATCHED_EXECUTORS.add(compile_key)
                    # the image tag keeps scheduler placements with
                    # different stacked-image shapes (wtf_tpu/tenancy)
                    # from reading as shape-churn in telemetry_report
                    self.events.emit("compile", chunk_steps=size,
                                     donate=self._donate,
                                     lanes=self.n_lanes,
                                     image="x".join(
                                         str(d) for d in
                                         self.image.frame_table.shape))
                with spans.span("device-step") as sp:
                    self.machine = self.supervisor.dispatch(
                        "chunk", run_chunk,
                        tab, self.image, self.machine, limit,
                        steps=size, sync=lambda m: m.status)
                    # explicit fence: JAX dispatch is async; without it
                    # this span times Python dispatch and the device time
                    # leaks into whichever later span synchronizes first
                    sp.fence(self.machine.status)
            self.stats["chunks"] += 1
            # COPY, never a zero-copy view: the machine's buffers are
            # donated into the next chunk call, and a live numpy view of
            # a donated CPU buffer reads whatever XLA reuses the memory
            # for (seen as garbage status/fpsw under multi-test processes)
            status = np.array(jax.device_get(self.machine.status))
            running = status == int(StatusCode.RUNNING)
            need = {
                int(StatusCode.NEED_DECODE): [],
                int(StatusCode.SMC): [],
                int(StatusCode.UNSUPPORTED): [],
                int(StatusCode.BREAKPOINT): [],
            }
            if self.deliver_exceptions:
                need[int(StatusCode.PAGE_FAULT)] = []
                need[int(StatusCode.DIVIDE_ERROR)] = []
            fault_statuses = (int(StatusCode.PAGE_FAULT),
                              int(StatusCode.DIVIDE_ERROR))
            for lane in np.nonzero(np.isin(status, list(need)))[0]:
                if int(lane) in undeliverable:
                    continue  # delivery already failed: stays terminal
                if (int(status[lane]) in fault_statuses
                        and not self._deliver_lane(int(lane))):
                    # heterogeneous batch: this lane's tenant has no IDT
                    # — its faults are terminal, exactly as they are in
                    # a solo campaign of that tenant
                    undeliverable.add(int(lane))
                    continue
                need[int(status[lane])].append(int(lane))
            total = sum(len(v) for v in need.values())
            if total == 0:
                if not running.any():
                    return status
                # nothing to service, lanes still running: grow the chunk
                if (self.adaptive_chunks
                        and self._chunk_level < len(self._chunk_sizes) - 1):
                    self._chunk_level += 1
                continue
            # Chunk-size policy: broad servicing (decode misses, SMC churn,
            # breakpoint dispatch — events that gate the BATCH's forward
            # progress) drops back to fine-grained chunks; a few chronic
            # oracle-bound lanes must NOT stall everyone (VERDICT r4 item
            # 4), so UNSUPPORTED/fault-only rounds keep growing the ladder
            # and the chronic lanes ride the oracle burst instead.
            broad = bool(need[int(StatusCode.NEED_DECODE)]
                         or need[int(StatusCode.SMC)]
                         or need[int(StatusCode.BREAKPOINT)])
            if broad:
                self._chunk_level = 0
            elif (self.adaptive_chunks
                    and self._chunk_level < len(self._chunk_sizes) - 1):
                self._chunk_level += 1

            unsup_lanes = need[int(StatusCode.UNSUPPORTED)]
            self._fallback_streak = {
                lane: self._fallback_streak.get(lane, 0)
                for lane in unsup_lanes}

            with spans.span("service-pull"):
                view = self.view()
            if need[int(StatusCode.NEED_DECODE)] or need[int(StatusCode.SMC)]:
                with spans.span("service-decode"):
                    if need[int(StatusCode.NEED_DECODE)]:
                        self._service_decode(
                            view, need[int(StatusCode.NEED_DECODE)])
                    if need[int(StatusCode.SMC)]:
                        self._service_smc(view, need[int(StatusCode.SMC)])
            if unsup_lanes:
                with spans.span("oracle-fallback"):
                    for lane in unsup_lanes:
                        self._fallback_burst(view, lane)
            for lane in (need.get(int(StatusCode.PAGE_FAULT), [])
                         + need.get(int(StatusCode.DIVIDE_ERROR), [])):
                if not self._service_exception(view, lane):
                    undeliverable.add(lane)
            for lane in need[int(StatusCode.BREAKPOINT)]:
                self.stats["bp_dispatches"] += 1
                if bp_handler is None:
                    self.lane_errors[lane] = (
                        f"breakpoint @ {view.get_rip(lane):#x} with no handler")
                    view.set_status(lane, StatusCode.CRASH)
                    continue
                rip_before = view.get_rip(lane)
                bp_handler(self, view, lane)
                if view.get_status(lane) == StatusCode.BREAKPOINT:
                    # resume: suppress the bp for one step ONLY if the
                    # handler left rip in place; a redirected rip must hit
                    # any breakpoint armed at the new address (the emu
                    # backend's skip_rip-clearing semantics, emu.py:66-67)
                    if view.get_rip(lane) == rip_before:
                        view.r["bp_skip"][lane] = np.int32(1)
                    view.set_status(lane, StatusCode.RUNNING)
            with spans.span("service-push"):
                self.push(view)
                tab = self.device_tab()
        # max_chunks exhausted: revoke the lanes still making (or
        # awaiting) progress as TIMEDOUT — burst semantics, their chunk
        # budget ran out — instead of aborting the whole batch.  One
        # runaway lane must not kill a campaign; TIMEDOUT lanes are
        # already excluded from the coverage merge by the backend's
        # include mask, so no partial-execution edges are credited.
        status = np.array(jax.device_get(self.machine.status))
        fault_statuses = (int(StatusCode.PAGE_FAULT),
                          int(StatusCode.DIVIDE_ERROR))
        nonterminal = [int(StatusCode.RUNNING), int(StatusCode.NEED_DECODE),
                       int(StatusCode.SMC), int(StatusCode.UNSUPPORTED),
                       int(StatusCode.BREAKPOINT), int(StatusCode.NEEDS_XLA)]
        if self.deliver_exceptions:
            # deliverable faults would have gone back to the guest too
            nonterminal += list(fault_statuses)
        stuck = [int(lane)
                 for lane in np.nonzero(np.isin(status, nonterminal))[0]
                 if int(lane) not in undeliverable
                 and not (int(status[lane]) in fault_statuses
                          and not self._deliver_lane(int(lane)))]
        if stuck:
            view = self.view()
            for lane in stuck:
                self.lane_errors.setdefault(
                    lane, f"revoked: exceeded max_chunks={max_chunks}")
                view.set_status(lane, StatusCode.TIMEDOUT)
            self.push(view)
            self.registry.counter("runner.max_chunks_timeouts").inc(
                len(stuck))
            self.events.emit("timeout", kind="max-chunks", lanes=stuck,
                             chunks=max_chunks)
        return np.array(jax.device_get(self.machine.status))

    def restore(self) -> None:
        """Every lane back to the snapshot: O(1) overlay reset + register
        broadcast (replaces the reference's dirty-page rewrite loops,
        SURVEY.md §5.4)."""
        with self.registry.spans.span("overlay-restore") as sp:
            self.machine = machine_restore(self.machine, self.template,
                                           donate=self._donate)
            sp.fence(self.machine.status)
        self.lane_errors.clear()
        self._pending_cov.clear()
        self._pending_edge.clear()
        # per-testcase SMC thrash window: a rip legitimately rewritten many
        # times within ONE run falls back to the oracle, but the count must
        # not accumulate across the campaign (fresh-run behavior parity)
        self._smc_updates.clear()

    def statuses(self) -> np.ndarray:
        # copy, not a view — see the donation note in run()
        return np.array(jax.device_get(self.machine.status))

    # -- device-side telemetry counters ------------------------------------
    def device_counters(self) -> np.ndarray:
        """The per-lane counter block (uint32[L, N_CTRS], machine.CTR_*
        indices) accumulated in-graph since the last restore.  One pull;
        a copy, never a view (donation note in run())."""
        return np.array(jax.device_get(self.machine.ctr))

    def fold_counter_totals(self, totals) -> None:
        """Add one [N_CTRS] totals vector into the registry's `device.*`
        counters — shared by the per-burst fold below and the megachunk
        driver (whose in-graph restores zero the per-lane block between
        batches, so the program emits per-batch totals instead)."""
        reg = self.registry
        reg.counter("device.instructions").inc(int(totals[CTR_INSTR]))
        reg.counter("device.mem_faults").inc(int(totals[CTR_MEM_FAULT]))
        reg.counter("device.decode_misses").inc(int(totals[CTR_DECODE_MISS]))
        # instructions retired inside the fused Pallas kernel (a subset of
        # device.instructions; their ratio is the fused-step occupancy)
        reg.counter("device.fused_steps").inc(int(totals[CTR_FUSED]))
        # park-reason split (interp/pstep.py): SUBSET = non-hot opclass /
        # armed bp / SMC-risk code; MEM = a lane the kernel WOULD have
        # run that the memory path diverted (failing walk, unwritable
        # store, overlay exhaustion).  One number used to hide why lanes
        # leave the kernel; these two make occupancy loss attributable.
        reg.counter("device.fused_park_subset").inc(
            int(totals[CTR_PARK_SUBSET]))
        reg.counter("device.fused_park_mem").inc(int(totals[CTR_PARK_MEM]))

    def fold_device_counters(self) -> np.ndarray:
        """Pull the counter block ONCE per burst and add the batch totals
        into the registry (`device.*` counters) — the host-side fold that
        replaces any per-step sync.  Call between run() and restore();
        returns the per-lane block for callers that want lane detail."""
        ctr = self.device_counters()
        self.fold_counter_totals(ctr.sum(axis=0, dtype=np.uint64))
        return ctr


def warm_decode_cache(runner: Runner, target, payload: bytes,
                      limit: int = 100_000) -> int:
    """Populate the runner's uop table by running `payload` once on the
    host EmuCpu oracle and decoding every reached rip — pure host work, no
    device compile (used by entry points that must budget XLA compiles).
    Returns the number of rips decoded."""
    from wtf_tpu.backend.emu import EmuBackend

    eb = EmuBackend(runner.snapshot, limit=limit)
    eb.initialize()
    target.init(eb)
    target.insert_testcase(eb, payload)
    eb.run()
    view = runner.view()
    n = 0
    for rip in sorted(eb.last_new_coverage()):
        if not runner.cache.has(rip):
            runner._decode_at(view, 0, rip)
            n += 1
    return n
