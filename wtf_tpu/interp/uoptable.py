"""Device-resident decode cache: the batch interpreter's translation table.

The reference decodes instruction bytes inside the emulator on every
execution (bochscpu's fetch-decode-execute loop).  On TPU that per-byte,
branchy work would serialize the VPU, so the host decodes each unique RIP
exactly once (wtf_tpu/cpu/decoder.py) and publishes the result here as
fixed-width parallel arrays the device indexes with a hash probe — the same
role a JIT translation cache plays.

Contents per entry (capacity rows):
  rip       u64  - guest virtual address of the instruction
  fields    i32  - the Uop's integer fields (uops.INT_FIELDS order)
  disp/imm  u64  - displacement / immediate
  raw_lo/hi u64  - first 16 raw bytes (SMC verification; length-masked)
  pfn0/pfn1 i32  - decode-time code page frames (dirty-code check)
  bp        i32  - 1 when a breakpoint is armed at this rip (the batched
                   equivalent of the reference's 0xcc patching +
                   `SetBreakpoint`, reference src/wtf/backend.h:231)

Lookup is open-addressed linear probing over `hash_tab` (slot -> [entry
index or -1, probe-key limbs]), probe sequence splitmix64(rip) + k for
k < PROBES.  The key limbs ride IN the hash row so a probe is ONE gather
of an [8, 3] block — entry index and verification key land together,
instead of a second gather through rip_l (which stays for the
checkpoint/debug paths).  The host inserter enforces the same probe
bound, so a device miss <=> rip genuinely undecoded, surfacing as
per-lane NEED_DECODE status for the runner to service — and, under
--device-decode, serviced in-graph by interp/devdec.py, with
`adopt_device_entries` back-filling and cross-checking every
device-published row against this host decoder at harvest.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from wtf_tpu.cpu.uops import INT_FIELDS, Uop
from wtf_tpu.interp.limbs import unpack_np
from wtf_tpu.utils.hashing import splitmix64

NF = len(INT_FIELDS)
# Shared host/device probe bound.  The host re-hashes everything into a
# bigger table if an insert would exceed it, so device lookups stay O(PROBES).
PROBES = 8

_FIELD_INDEX = {name: i for i, name in enumerate(INT_FIELDS)}
F_OPC = _FIELD_INDEX["opc"]
F_SUB = _FIELD_INDEX["sub"]
F_COND = _FIELD_INDEX["cond"]
F_LENGTH = _FIELD_INDEX["length"]
F_OPSIZE = _FIELD_INDEX["opsize"]
F_SRCSIZE = _FIELD_INDEX["srcsize"]
F_SEXT = _FIELD_INDEX["sext"]
F_DST_KIND = _FIELD_INDEX["dst_kind"]
F_DST_REG = _FIELD_INDEX["dst_reg"]
F_SRC_KIND = _FIELD_INDEX["src_kind"]
F_SRC_REG = _FIELD_INDEX["src_reg"]
F_BASE_REG = _FIELD_INDEX["base_reg"]
F_IDX_REG = _FIELD_INDEX["idx_reg"]
F_SCALE = _FIELD_INDEX["scale"]
F_SEG = _FIELD_INDEX["seg"]
F_REP = _FIELD_INDEX["rep"]
F_LOCK = _FIELD_INDEX["lock"]
F_A32 = _FIELD_INDEX["a32"]


class UopTable(NamedTuple):
    """Device arrays; broadcast (unmapped) under vmap over lanes.

    Entry metadata is packed into TWO row-gatherable arrays (one int32, one
    uint64) so fetching an instruction costs two gathers instead of nine —
    on TPU the per-step cost is dominated by the count of unfusable gather
    kernels, not their width.

    The probe-verification rip column is stored as u32 limb pairs so the
    hash-probe path of the device step (interp/step.py `uop_lookup`) runs
    entirely in u32 — TPUs have no native u64, and the probe compare is
    per-step hot (interp/limbs.py has the representation contract)."""

    rip_l: jax.Array     # uint32[capacity, 2] (probe verification, LE limbs)
    meta_i32: jax.Array  # int32[capacity, NF + 3]: Uop fields, pfn0, pfn1, bp
    meta_u64: jax.Array  # uint64[capacity, 4]: disp, imm, raw_lo, raw_hi
    hash_tab: jax.Array  # int32[hash_size, 3]: entry index or -1, key limbs


# meta_i32 column layout (first NF columns are uops.INT_FIELDS)
M_PFN0 = NF
M_PFN1 = NF + 1
M_BP = NF + 2
# meta_u64 column layout
MU_DISP, MU_IMM, MU_RAW_LO, MU_RAW_HI = 0, 1, 2, 3

_MASK64 = (1 << 64) - 1


def tag_key(rip: int, tenant: int = 0) -> int:
    """The probe key a (tenant, rip) pair hashes and verifies under
    (wtf_tpu/tenancy): rip ^ (tenant << 48).  Canonical x86-64 addresses
    carry bits 62:48 as copies of bit 47, so the tag occupies dead bits
    and two base images sharing a virtual address get distinct cache
    entries.  tenant 0 (every single-image campaign) leaves the rip
    untouched — the pre-tenancy key space, bit for bit."""
    return (rip ^ (tenant << 48)) & _MASK64


def _pack_raw(raw: bytes) -> Tuple[int, int]:
    padded = raw[:16].ljust(16, b"\x00")
    lo = int.from_bytes(padded[:8], "little")
    hi = int.from_bytes(padded[8:16], "little")
    return lo, hi


class DecodeCache:
    """Host mirror of the device table; owns insertion and breakpoint state."""

    def __init__(self, capacity: int = 1 << 15, hash_factor: int = 4):
        self.capacity = capacity
        self.hash_size = 1
        while self.hash_size < capacity * hash_factor:
            self.hash_size *= 2
        self.count = 0
        # self.rip holds the PROBE KEY per entry (tag_key(rip, tenant));
        # tenant_of untags it back to the real rip for reporting
        self.rip = np.zeros(capacity, dtype=np.uint64)
        self.tenant_of = np.zeros(capacity, dtype=np.int32)
        self.fields = np.zeros((capacity, NF), dtype=np.int32)
        self.disp = np.zeros(capacity, dtype=np.uint64)
        self.imm = np.zeros(capacity, dtype=np.uint64)
        self.raw_lo = np.zeros(capacity, dtype=np.uint64)
        self.raw_hi = np.zeros(capacity, dtype=np.uint64)
        self.pfn0 = np.zeros(capacity, dtype=np.int32)
        self.pfn1 = np.zeros(capacity, dtype=np.int32)
        self.bp = np.zeros(capacity, dtype=np.int32)
        self.hash_tab = np.full(self.hash_size, -1, dtype=np.int32)
        self.index: Dict[int, int] = {}      # probe key -> entry idx
        self.uops: Dict[int, Uop] = {}       # probe key -> host Uop
        # Breakpoints may be registered before their rip is ever decoded
        # (symbol breakpoints at Init time, reference backend.cc:214-239).
        # Keyed like entries: tag_key(gva, tenant).
        self.pending_bps: Set[int] = set()
        self._device: Optional[UopTable] = None

    # -- keyed lookups (tenant 0 == the pre-tenancy rip key space) -------
    def entry_index(self, rip: int, tenant: int = 0) -> Optional[int]:
        return self.index.get(tag_key(rip, tenant))

    def has(self, rip: int, tenant: int = 0) -> bool:
        return tag_key(rip, tenant) in self.index

    def uop_at(self, rip: int, tenant: int = 0) -> Optional[Uop]:
        return self.uops.get(tag_key(rip, tenant))

    # -- insertion -------------------------------------------------------
    def _hash_insert(self, rip: int, idx: int) -> bool:
        h = splitmix64(rip)
        mask = self.hash_size - 1
        for k in range(PROBES):
            slot = (h + k) & mask
            if self.hash_tab[slot] < 0:
                self.hash_tab[slot] = idx
                return True
        return False

    def _rehash(self) -> None:
        self.hash_size *= 2
        while True:
            self.hash_tab = np.full(self.hash_size, -1, dtype=np.int32)
            ok = all(
                self._hash_insert(int(self.rip[i]), i) for i in range(self.count)
            )
            if ok:
                return
            self.hash_size *= 2

    def add(self, rip: int, uop: Uop, pfn0: int, pfn1: int,
            tenant: int = 0) -> int:
        """Insert a decoded instruction; returns its entry index."""
        key = tag_key(rip, tenant)
        existing = self.index.get(key)
        if existing is not None:
            return existing
        if self.count >= self.capacity:
            raise RuntimeError(
                f"uop table full ({self.capacity}); raise capacity"
            )
        idx = self.count
        self.count += 1
        self.rip[idx] = np.uint64(key)
        self.tenant_of[idx] = tenant
        for f, name in enumerate(INT_FIELDS):
            self.fields[idx, f] = getattr(uop, name)
        self.disp[idx] = np.uint64(uop.disp & ((1 << 64) - 1))
        self.imm[idx] = np.uint64(uop.imm & ((1 << 64) - 1))
        lo, hi = _pack_raw(uop.raw)
        self.raw_lo[idx] = np.uint64(lo)
        self.raw_hi[idx] = np.uint64(hi)
        self.pfn0[idx] = pfn0
        self.pfn1[idx] = pfn1
        self.bp[idx] = 1 if key in self.pending_bps else 0
        if not self._hash_insert(key, idx):
            self._rehash()
        self.index[key] = idx
        self.uops[key] = uop
        self._device = None
        return idx

    def update(self, rip: int, uop: Uop, pfn0: int, pfn1: int,
               tenant: int = 0) -> int:
        """Re-publish a rip whose bytes changed (self-modifying code / SMC
        servicing).  Overwrites the existing entry in place — the entry index
        is stable, so coverage-bitmap indices stay valid — or inserts when
        the rip was never decoded."""
        key = tag_key(rip, tenant)
        idx = self.index.get(key)
        if idx is None:
            return self.add(rip, uop, pfn0, pfn1, tenant=tenant)
        for f, name in enumerate(INT_FIELDS):
            self.fields[idx, f] = getattr(uop, name)
        self.disp[idx] = np.uint64(uop.disp & ((1 << 64) - 1))
        self.imm[idx] = np.uint64(uop.imm & ((1 << 64) - 1))
        lo, hi = _pack_raw(uop.raw)
        self.raw_lo[idx] = np.uint64(lo)
        self.raw_hi[idx] = np.uint64(hi)
        self.pfn0[idx] = pfn0
        self.pfn1[idx] = pfn1
        self.uops[key] = uop
        self._device = None
        return idx

    # -- checkpoint/resume (wtf_tpu/resume) ------------------------------
    def checkpoint_entries(self) -> list:
        """Insertion-ordered entry snapshot: (rip, raw bytes, pfn0, pfn1)
        per entry.  Coverage-bitmap bit i IS entry index i (insertion
        order), so a resumed campaign must rebuild the cache with
        identical indices before a restored aggregate bitmap means
        anything.  Only the raw bytes are persisted — decode is
        deterministic on bytes, so the restore re-decodes; SMC-updated
        entries round-trip with their *current* bytes (update() keeps
        uops/raw in sync), exactly the state the killed run held."""
        out = []
        for idx in range(self.count):
            key = int(self.rip[idx])
            tenant = int(self.tenant_of[idx])
            uop = self.uops[key]
            entry = (tag_key(key, tenant), uop.raw, int(self.pfn0[idx]),
                     int(self.pfn1[idx]))
            # tenant rides as a 5th element only when nonzero, so
            # pre-tenancy checkpoints round-trip byte-identically
            out.append(entry if tenant == 0 else entry + (tenant,))
        return out

    def restore_entries(self, entries) -> None:
        """Rebuild from checkpoint_entries() output (4-tuples, or
        5-tuples carrying a tenant tag).  Requires an empty cache —
        replaying into a partially-filled one would shift every entry
        index and silently scramble restored coverage bitmaps."""
        if self.count:
            raise RuntimeError(
                "decode-cache restore needs an empty cache "
                f"(has {self.count} entries)")
        from wtf_tpu.cpu.decoder import decode

        for entry in entries:
            rip, raw, pfn0, pfn1 = entry[:4]
            tenant = int(entry[4]) if len(entry) > 4 else 0
            self.add(rip, decode(raw, rip), pfn0, pfn1, tenant=tenant)

    # -- device-published entry adoption (interp/devdec.py harvest) ------
    def adopt_device_entries(self, rip_l, meta_i32, meta_u64,
                             start: int, end: int) -> int:
        """Back-fill rows [start, end) that the device decoder published
        during a megachunk window, in publish order, so host and device
        tables keep identical entry indices (coverage bit i IS entry
        index i).  The arrays are the [start, end) SLICE of the device
        table (row 0 == entry `start`) so the harvest transfers only the
        published rows, not the whole capacity.  The host decoder stays
        the authoritative oracle: every row is re-decoded from its raw
        bytes and cross-checked field for field; the HOST result is what
        gets stored.  Returns the number of rows whose device decode
        disagreed (must be 0 — any nonzero count is a devdec bug,
        surfaced by the caller's counter).
        """
        from wtf_tpu.cpu.decoder import decode

        if start != self.count:
            raise RuntimeError(
                f"device-entry adoption out of order: device rows start "
                f"at {start}, host cache has {self.count}")
        rip_l = np.asarray(rip_l)
        meta_i32 = np.asarray(meta_i32)
        meta_u64 = np.asarray(meta_u64)
        mismatches = 0
        for idx in range(end - start):
            key = (int(rip_l[idx, 0]) & 0xFFFFFFFF) | (
                (int(rip_l[idx, 1]) & 0xFFFFFFFF) << 32)
            # untag: canonical rips carry bits 63:48 as copies of bit 47
            # (bit 47 is below the tag, so it survives tagging intact)
            tenant = (key >> 48) ^ (0xFFFF if (key >> 47) & 1 else 0)
            rip = tag_key(key, tenant)
            length = max(int(meta_i32[idx, F_LENGTH]), 0)
            raw = (int(meta_u64[idx, MU_RAW_LO]).to_bytes(8, "little")
                   + int(meta_u64[idx, MU_RAW_HI]).to_bytes(8, "little")
                   )[:length]
            uop = decode(raw, rip)
            bad = any(
                int(meta_i32[idx, f]) != int(getattr(uop, name))
                for f, name in enumerate(INT_FIELDS))
            bad = bad or int(meta_u64[idx, MU_DISP]) != (uop.disp & _MASK64)
            bad = bad or int(meta_u64[idx, MU_IMM]) != (uop.imm & _MASK64)
            bad = bad or int(meta_i32[idx, M_BP]) != (
                1 if key in self.pending_bps else 0)
            if bad:
                mismatches += 1
            self.add(rip, uop, int(meta_i32[idx, M_PFN0]),
                     int(meta_i32[idx, M_PFN1]), tenant=tenant)
        return mismatches

    # -- breakpoints -----------------------------------------------------
    def set_breakpoint(self, gva: int, tenant: int = 0) -> None:
        key = tag_key(gva, tenant)
        self.pending_bps.add(key)
        idx = self.index.get(key)
        if idx is not None and self.bp[idx] != 1:
            self.bp[idx] = 1
            self._device = None

    def clear_breakpoint(self, gva: int, tenant: int = 0) -> None:
        key = tag_key(gva, tenant)
        self.pending_bps.discard(key)
        idx = self.index.get(key)
        if idx is not None and self.bp[idx] != 0:
            self.bp[idx] = 0
            self._device = None

    def has_breakpoint(self, gva: int, tenant: int = 0) -> bool:
        return tag_key(gva, tenant) in self.pending_bps

    # -- device view -----------------------------------------------------
    def device(self) -> UopTable:
        """Upload (or return cached) device arrays."""
        if self._device is None:
            meta_i32 = np.concatenate(
                [self.fields, self.pfn0[:, None], self.pfn1[:, None],
                 self.bp[:, None]], axis=1)
            meta_u64 = np.stack(
                [self.disp, self.imm, self.raw_lo, self.raw_hi], axis=1)
            # hash rows carry the probe key's u32 limbs alongside the
            # entry index (one [PROBES, 3] gather per lookup)
            occ = self.hash_tab >= 0
            keys = self.rip[np.maximum(self.hash_tab, 0)]
            klo = np.where(occ, keys & np.uint64(0xFFFFFFFF), 0)
            khi = np.where(occ, keys >> np.uint64(32), 0)
            rows = np.stack(
                [self.hash_tab,
                 klo.astype(np.uint32).view(np.int32),
                 khi.astype(np.uint32).view(np.int32)], axis=1)
            self._device = UopTable(
                rip_l=jnp.asarray(unpack_np(self.rip)),
                meta_i32=jnp.asarray(meta_i32),
                meta_u64=jnp.asarray(meta_u64),
                hash_tab=jnp.asarray(rows),
            )
        return self._device

    def rip_of(self, idx: int) -> int:
        """Real (untagged) rip of an entry."""
        return tag_key(int(self.rip[idx]), int(self.tenant_of[idx]))

    def tenant_entries(self, tenant: int) -> list:
        """This tenant's entries in insertion order, as (global entry
        index, real rip, raw bytes, pfn0, pfn1) — the per-tenant slice a
        tenancy checkpoint persists (wtf_tpu/tenancy/state.py); the
        global indices are the tenant's coverage-bitmap remap."""
        out = []
        for idx in range(self.count):
            if int(self.tenant_of[idx]) != tenant:
                continue
            key = int(self.rip[idx])
            rip = tag_key(key, tenant)
            uop = self.uops[key]
            out.append((idx, rip, uop.raw, int(self.pfn0[idx]),
                        int(self.pfn1[idx])))
        return out

    def rips_of_bits(self, words: np.ndarray) -> list:
        """Decode a coverage bitmap (u32 words over entry indices) to
        real (untagged) RIPs."""
        out = []
        bits = np.nonzero(words)[0]
        for word_idx in bits:
            word = int(words[word_idx])
            base = word_idx * 32
            while word:
                low = word & -word
                idx = base + low.bit_length() - 1
                out.append(tag_key(int(self.rip[idx]),
                                   int(self.tenant_of[idx])))
                word ^= low
        return out
