"""Device-resident x86-64 decode: the in-graph half of the decode seam.

PR 12's megachunk made a whole window of batches ONE dispatch, but every
decode-cache miss still early-returns the window for a host round trip
through `cpu.decoder.decode`.  This module closes that seam for the hot
subset: a lane that parks NEED_DECODE inside a megachunk window decodes
its own bytes *on device* (LUT-driven prefix/REX scan, ModRM/SIB/disp/imm
extraction, length decode, uop synthesis), claims a uop-table slot — and
with it the entry's coverage bit — through an atomic-free sequential
reservation replay, and keeps running.  Only encodings outside the device
subset park to the host as before; the host decoder stays the
authoritative oracle that back-fills and cross-checks every
device-published entry at harvest (`DecodeCache.adopt_device_entries`).

Bit-identity contract (what makes the published entries indistinguishable
from host-serviced ones):

  * the byte->uop mapping replicates `cpu/decoder.py` rule for rule — the
    descriptor LUT below is a transcription of `_decode_primary` /
    `_decode_0f`, and anything the transcription does not cover with
    certainty decodes as UNKNOWN, which parks the lane to the host
    (conservative: a park costs a round trip, a wrong publish would
    corrupt the cache);
  * code fetch goes through `mem.paging.virt_read` — the same
    overlay-aware walk the host's `HostView.virt_read` mirrors — so the
    window bytes, the fetch-fault surface and the pfn0/pfn1 SMC tags are
    the host's exactly;
  * the service order replicates `runner._service_decode`: lanes in lane
    order, one `_decode_at` + `_prefetch_block` per missing rip (publish
    even OPC_INVALID at the miss rip; LIFO successor walk with budget
    PREFETCH_BUDGET, capacity margin MARGIN, prefetched INVALIDs
    skipped), with hash-probe slots computed by the same splitmix64 + 8
    linear probes as `DecodeCache._hash_insert` so host adoption at
    harvest reproduces identical slots and entry indices.

Mesh form: block computation is lane-local (each shard fetches/decodes
with its own overlay), then the per-lane publish records are
all-gathered and EVERY shard replays the identical global commit over
its replica of the table — the replicated-table analogue of the host's
single sequential service loop.  Commit-time key dedup drops records an
earlier lane already published; a lane whose *miss* rip was published by
an earlier lane resumes without contributing records, exactly like the
host's `cache.has` gate.  (The one documented divergence from a pure
host replay: a lane's prefetch WALK is computed against the table as of
the round start plus its own records, so when two lanes' prefetch
regions overlap at differing miss rips the walk shape may differ from
the host's strictly-sequential walk.  Identical-miss lanes — the cold
start case — dedup at the lane level and match the host bit for bit.)
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from wtf_tpu.cpu import uops as U
from wtf_tpu.cpu.uops import INT_FIELDS
from wtf_tpu.mem import paging
from wtf_tpu.mem.physmem import MemImage

NF = len(INT_FIELDS)
_IX = {name: i for i, name in enumerate(INT_FIELDS)}
MAX_LEN = 15          # cpu.decoder.MAX_INSN_LEN
PROBES = 8            # uoptable.PROBES
PREFETCH_BUDGET = 48  # runner.PREFETCH_BUDGET
MARGIN = 64           # runner._PREFETCH_MARGIN
RECS = 50             # 1 miss + PREFETCH_BUDGET prefetched + slack
STACK = 64            # LIFO worklist depth bound (net +1 per publish)
WALK_ITERS = 112      # >= initial(2) + 2*budget pops + skipped pops slack

_M64 = jnp.uint64(0xFFFFFFFFFFFFFFFF)

# ---------------------------------------------------------------------------
# Descriptor LUT: [2 maps, 256 opcodes, 8 modrm digits, N_COL] int32, built
# once at import (numpy) and folded into the graph as a constant.  One row
# fully describes an opcode's decode rule; rows without a ModRM byte are
# replicated across the 8 digit slots.
# ---------------------------------------------------------------------------
(C_KIND, C_OPC, C_SUB, C_COND, C_MODRM, C_FORM, C_SIZE8, C_RM8, C_OSZ,
 C_IMM, C_KIMM, C_SRCSIZE, C_SEXT, C_REP, C_SPECIAL) = range(15)
N_COL = 15

# C_KIND
K_UNKNOWN, K_KNOWN = 0, 1
# C_FORM (operand wiring)
(F_NONE, F_RM_REG, F_REG_RM, F_RM_DST, F_RM_SRC, F_OPREG_SRC, F_OPREG_DST,
 F_OPREG_DST8, F_ACC, F_LEA, F_XCHG_ACC, F_RM_CL) = range(12)
# C_OSZ (operation size rule; C_SIZE8 overrides to 1)
OSZ_STD, OSZ_PP, OSZ_8, OSZ_W84 = range(4)
# C_IMM (immediate rule)
(IMM_NONE, IMM_8SX, IMM_8ZX, IMM_16ZX, IMM_STD, IMM_32SX, IMM_MOVABS,
 IMM_ONE) = range(8)
# C_SPECIAL
(SP_NONE, SP_VEX, SP_E3, SP_CD, SP_C8, SP_AE, SP_C7RD, SP_BSCAN,
 SP_POPCNT) = range(9)


def _build_lut() -> np.ndarray:
    lut = np.zeros((2, 256, 8, N_COL), dtype=np.int32)
    # default: everything UNKNOWN (parks to host) until a rule claims it
    lut[:, :, :, C_KIND] = K_UNKNOWN

    def row(m, op, digit=None, kind=K_KNOWN, opc=U.OPC_INVALID, sub=0,
            cond=0, modrm=0, form=F_NONE, size8=0, rm8=0, osz=OSZ_STD,
            imm=IMM_NONE, kimm=0, srcsize=0, sext=0, rep=0, special=SP_NONE):
        r = np.array([kind, opc, sub, cond, modrm, form, size8, rm8, osz,
                      imm, kimm, srcsize, sext, rep, special],
                     dtype=np.int32)
        digits = range(8) if digit is None else [digit]
        for d in digits:
            lut[m, op, d] = r

    def invalid(m, op, digit=None, modrm=0):
        # host `_decode_primary`/`_decode_0f` fall-through: OPC_INVALID
        # keeping a32/lock and the bytes consumed so far
        row(m, op, digit=digit, opc=U.OPC_INVALID, modrm=modrm)

    # ---- primary map ------------------------------------------------------
    # every primary opcode host-decodes deterministically; rows not claimed
    # below are the decoder's unmatched `else` -> INVALID after the opcode
    for op in range(256):
        invalid(0, op)

    # 00-3D ALU block: op>>3 = sub, op&7 = form (skip the x6/x7/xE/xF gaps)
    for hi in range(8):
        base = hi << 3
        for lo, (f, s8, im) in enumerate((
                (F_RM_REG, 1, IMM_NONE), (F_RM_REG, 0, IMM_NONE),
                (F_REG_RM, 1, IMM_NONE), (F_REG_RM, 0, IMM_NONE),
                (F_ACC, 1, IMM_8SX), (F_ACC, 0, IMM_STD))):
            row(0, base + lo, opc=U.OPC_ALU, sub=hi, modrm=(lo < 4),
                form=f, size8=s8, imm=im, kimm=(im != IMM_NONE))
    for op in range(0x50, 0x58):  # push r
        row(0, op, opc=U.OPC_PUSH, form=F_OPREG_SRC, osz=OSZ_PP)
    for op in range(0x58, 0x60):  # pop r
        row(0, op, opc=U.OPC_POP, form=F_OPREG_DST, osz=OSZ_PP)
    row(0, 0x63, opc=U.OPC_MOV, modrm=1, form=F_REG_RM, srcsize=4, sext=1)
    row(0, 0x68, opc=U.OPC_PUSH, osz=OSZ_8, imm=IMM_32SX, kimm=1)
    row(0, 0x69, opc=U.OPC_MUL, sub=U.MUL_2OP, modrm=1, form=F_REG_RM,
        imm=IMM_STD, sext=2)
    row(0, 0x6A, opc=U.OPC_PUSH, osz=OSZ_8, imm=IMM_8SX, kimm=1)
    row(0, 0x6B, opc=U.OPC_MUL, sub=U.MUL_2OP, modrm=1, form=F_REG_RM,
        imm=IMM_8SX, sext=2)
    for op in range(0x70, 0x80):  # jcc rel8
        row(0, op, opc=U.OPC_JCC, cond=op & 0xF, osz=OSZ_8, imm=IMM_8SX)
    for d in range(8):  # group 1
        row(0, 0x80, digit=d, opc=U.OPC_ALU, sub=d, modrm=1, form=F_RM_DST,
            size8=1, imm=IMM_8SX, kimm=1)
        row(0, 0x81, digit=d, opc=U.OPC_ALU, sub=d, modrm=1, form=F_RM_DST,
            imm=IMM_STD, kimm=1)
        row(0, 0x83, digit=d, opc=U.OPC_ALU, sub=d, modrm=1, form=F_RM_DST,
            imm=IMM_8SX, kimm=1)
    row(0, 0x84, opc=U.OPC_ALU, sub=U.ALU_TEST, modrm=1, form=F_RM_REG,
        size8=1)
    row(0, 0x85, opc=U.OPC_ALU, sub=U.ALU_TEST, modrm=1, form=F_RM_REG)
    row(0, 0x86, opc=U.OPC_XCHG, modrm=1, form=F_RM_REG, size8=1)
    row(0, 0x87, opc=U.OPC_XCHG, modrm=1, form=F_RM_REG)
    row(0, 0x88, opc=U.OPC_MOV, modrm=1, form=F_RM_REG, size8=1)
    row(0, 0x89, opc=U.OPC_MOV, modrm=1, form=F_RM_REG)
    row(0, 0x8A, opc=U.OPC_MOV, modrm=1, form=F_REG_RM, size8=1)
    row(0, 0x8B, opc=U.OPC_MOV, modrm=1, form=F_REG_RM)
    row(0, 0x8D, opc=U.OPC_LEA, modrm=1, form=F_LEA)
    row(0, 0x8F, opc=U.OPC_POP, modrm=1, form=F_RM_DST, osz=OSZ_PP)
    row(0, 0x90, opc=U.OPC_NOP, osz=OSZ_8)
    for op in range(0x91, 0x98):
        row(0, op, opc=U.OPC_XCHG, form=F_XCHG_ACC)
    row(0, 0x98, opc=U.OPC_CONVERT, sub=0)
    row(0, 0x99, opc=U.OPC_CONVERT, sub=1)
    row(0, 0x9B, opc=U.OPC_NOP, osz=OSZ_8)  # fwait
    row(0, 0x9C, opc=U.OPC_PUSHF, osz=OSZ_8)
    row(0, 0x9D, opc=U.OPC_POPF, osz=OSZ_8)
    row(0, 0x9E, opc=U.OPC_FLAGOP, sub=U.FL_SAHF, osz=OSZ_8)
    row(0, 0x9F, opc=U.OPC_FLAGOP, sub=U.FL_LAHF, osz=OSZ_8)
    for op, sub in ((0xA4, U.STR_MOVS), (0xA6, U.STR_CMPS),
                    (0xAA, U.STR_STOS), (0xAC, U.STR_LODS),
                    (0xAE, U.STR_SCAS)):
        row(0, op, opc=U.OPC_STRING, sub=sub, size8=1, rep=1)
        row(0, op + 1, opc=U.OPC_STRING, sub=sub, rep=1)
    row(0, 0xA8, opc=U.OPC_ALU, sub=U.ALU_TEST, form=F_ACC, size8=1,
        imm=IMM_8SX, kimm=1)
    row(0, 0xA9, opc=U.OPC_ALU, sub=U.ALU_TEST, form=F_ACC, imm=IMM_STD,
        kimm=1)
    for op in range(0xB0, 0xB8):  # mov r8, imm8 (unsigned)
        row(0, op, opc=U.OPC_MOV, form=F_OPREG_DST8, size8=1, imm=IMM_8ZX,
            kimm=1)
    for op in range(0xB8, 0xC0):  # mov r, imm (movabs family, unsigned)
        row(0, op, opc=U.OPC_MOV, form=F_OPREG_DST, imm=IMM_MOVABS, kimm=1)
    for d in range(8):  # group 2
        row(0, 0xC0, digit=d, opc=U.OPC_SHIFT, sub=d, modrm=1,
            form=F_RM_DST, size8=1, imm=IMM_8ZX, kimm=1)
        row(0, 0xC1, digit=d, opc=U.OPC_SHIFT, sub=d, modrm=1,
            form=F_RM_DST, imm=IMM_8ZX, kimm=1)
        row(0, 0xD0, digit=d, opc=U.OPC_SHIFT, sub=d, modrm=1,
            form=F_RM_DST, size8=1, imm=IMM_ONE, kimm=1)
        row(0, 0xD1, digit=d, opc=U.OPC_SHIFT, sub=d, modrm=1,
            form=F_RM_DST, imm=IMM_ONE, kimm=1)
        row(0, 0xD2, digit=d, opc=U.OPC_SHIFT, sub=d, modrm=1,
            form=F_RM_CL, size8=1, srcsize=1)
        row(0, 0xD3, digit=d, opc=U.OPC_SHIFT, sub=d, modrm=1,
            form=F_RM_CL, srcsize=1)
    row(0, 0xC2, opc=U.OPC_RET, osz=OSZ_8, imm=IMM_16ZX)
    row(0, 0xC3, opc=U.OPC_RET, osz=OSZ_8)
    row(0, 0xC6, digit=0, opc=U.OPC_MOV, modrm=1, form=F_RM_DST, size8=1,
        imm=IMM_8ZX, kimm=1)
    for d in range(1, 8):
        invalid(0, 0xC6, digit=d, modrm=1)
    row(0, 0xC7, digit=0, opc=U.OPC_MOV, modrm=1, form=F_RM_DST,
        imm=IMM_STD, kimm=1)
    for d in range(1, 8):
        invalid(0, 0xC7, digit=d, modrm=1)
    row(0, 0xC8, kind=K_UNKNOWN)  # enter: rare; host-serviced
    row(0, 0xC9, opc=U.OPC_LEAVE, osz=OSZ_8)
    row(0, 0xCA, opc=U.OPC_IRET, sub=1, osz=OSZ_8, imm=IMM_16ZX)
    row(0, 0xCB, opc=U.OPC_IRET, sub=1, osz=OSZ_8)
    row(0, 0xCC, opc=U.OPC_INT, sub=3, osz=OSZ_8)
    row(0, 0xCD, opc=U.OPC_INT, osz=OSZ_8, special=SP_CD)
    row(0, 0xCF, opc=U.OPC_IRET, osz=OSZ_W84)
    for op in range(0xD8, 0xE0):  # x87 -> host
        row(0, op, kind=K_UNKNOWN)
    row(0, 0xE3, opc=U.OPC_JCC, osz=OSZ_8, imm=IMM_8SX, special=SP_E3)
    row(0, 0xE8, opc=U.OPC_CALL, osz=OSZ_8, imm=IMM_32SX, kimm=1)
    row(0, 0xE9, opc=U.OPC_JMP, osz=OSZ_8, imm=IMM_32SX, kimm=1)
    row(0, 0xEB, opc=U.OPC_JMP, osz=OSZ_8, imm=IMM_8SX, kimm=1)
    # 0xF1 (icebp): the oracle decoder leaves it unmatched -> INVALID
    row(0, 0xF4, opc=U.OPC_HLT, osz=OSZ_8)
    row(0, 0xF5, opc=U.OPC_FLAGOP, sub=U.FL_CMC, osz=OSZ_8)
    for op, sub in ((0xF8, U.FL_CLC), (0xF9, U.FL_STC), (0xFA, U.FL_CLI),
                    (0xFB, U.FL_STI), (0xFC, U.FL_CLD), (0xFD, U.FL_STD)):
        row(0, op, opc=U.OPC_FLAGOP, sub=sub, osz=OSZ_8)
    for op, s8, im in ((0xF6, 1, IMM_8SX), (0xF7, 0, IMM_STD)):  # group 3
        for d in (0, 1):
            row(0, op, digit=d, opc=U.OPC_ALU, sub=U.ALU_TEST, modrm=1,
                form=F_RM_DST, size8=s8, imm=im, kimm=1)
        row(0, op, digit=2, opc=U.OPC_UNARY, sub=U.UN_NOT, modrm=1,
            form=F_RM_DST, size8=s8)
        row(0, op, digit=3, opc=U.OPC_UNARY, sub=U.UN_NEG, modrm=1,
            form=F_RM_DST, size8=s8)
        row(0, op, digit=4, opc=U.OPC_MUL, sub=U.MUL_WIDE_U, modrm=1,
            form=F_RM_SRC, size8=s8)
        row(0, op, digit=5, opc=U.OPC_MUL, sub=U.MUL_WIDE_S, modrm=1,
            form=F_RM_SRC, size8=s8)
        row(0, op, digit=6, opc=U.OPC_DIV, sub=U.DIV_U, modrm=1,
            form=F_RM_SRC, size8=s8)
        row(0, op, digit=7, opc=U.OPC_DIV, sub=U.DIV_S, modrm=1,
            form=F_RM_SRC, size8=s8)
    row(0, 0xFE, digit=0, opc=U.OPC_UNARY, sub=U.UN_INC, modrm=1,
        form=F_RM_DST, size8=1)
    row(0, 0xFE, digit=1, opc=U.OPC_UNARY, sub=U.UN_DEC, modrm=1,
        form=F_RM_DST, size8=1)
    for d in range(2, 8):
        invalid(0, 0xFE, digit=d, modrm=1)
    row(0, 0xFF, digit=0, opc=U.OPC_UNARY, sub=U.UN_INC, modrm=1,
        form=F_RM_DST)
    row(0, 0xFF, digit=1, opc=U.OPC_UNARY, sub=U.UN_DEC, modrm=1,
        form=F_RM_DST)
    row(0, 0xFF, digit=2, opc=U.OPC_CALL, modrm=1, form=F_RM_SRC, osz=OSZ_8)
    row(0, 0xFF, digit=4, opc=U.OPC_JMP, modrm=1, form=F_RM_SRC, osz=OSZ_8)
    row(0, 0xFF, digit=6, opc=U.OPC_PUSH, modrm=1, form=F_RM_SRC,
        osz=OSZ_PP)
    for d in (3, 5, 7):
        invalid(0, 0xFF, digit=d, modrm=1)
    # C4/C5: VEX when no legacy/REX prefix (device -> host), else the
    # primary map's unmatched INVALID
    row(0, 0xC4, special=SP_VEX)
    row(0, 0xC5, special=SP_VEX)
    # moffs forms + far/IO/loop encodings the transcription does not pin:
    # park rather than guess (host decode is cheap and authoritative)
    for op in (0xA0, 0xA1, 0xA2, 0xA3, 0xE0, 0xE1, 0xE2):
        row(0, op, kind=K_UNKNOWN)

    # ---- 0F map -----------------------------------------------------------
    # default UNKNOWN (the `_decode_0f_sse` fall-through and everything not
    # explicitly matched parks to the host) — NOT invalid: the host decodes
    # SSE/x87 forms this subset does not model
    row(1, 0x05, opc=U.OPC_SYSCALL, osz=OSZ_8)
    row(1, 0x07, opc=U.OPC_SYSCALL, sub=1, osz=OSZ_8)
    row(1, 0x0B, opc=U.OPC_INT, sub=6, osz=OSZ_8)
    row(1, 0x0D, opc=U.OPC_NOP, modrm=1, osz=OSZ_8)  # prefetchw
    for op in range(0x18, 0x20):          # hint nops: ModRM consumed
        row(1, op, opc=U.OPC_NOP, modrm=1, osz=OSZ_8)
    row(1, 0x30, opc=U.OPC_MSR, sub=1, osz=OSZ_8)
    row(1, 0x31, opc=U.OPC_RDTSC, osz=OSZ_8)
    row(1, 0x32, opc=U.OPC_MSR, sub=0, osz=OSZ_8)
    for op in range(0x40, 0x50):
        row(1, op, opc=U.OPC_CMOVCC, cond=op & 0xF, modrm=1, form=F_REG_RM)
    for op in range(0x80, 0x90):
        row(1, op, opc=U.OPC_JCC, cond=op & 0xF, osz=OSZ_8, imm=IMM_32SX)
    for op in range(0x90, 0xA0):
        row(1, op, opc=U.OPC_SETCC, cond=op & 0xF, modrm=1, form=F_RM_DST,
            size8=1)
    row(1, 0xA2, opc=U.OPC_CPUID, osz=OSZ_8)
    for op, sub in ((0xA3, U.BT_BT), (0xAB, U.BT_BTS), (0xB3, U.BT_BTR),
                    (0xBB, U.BT_BTC)):
        row(1, op, opc=U.OPC_BT, sub=sub, modrm=1, form=F_RM_REG)
    for op, sub in ((0xA4, U.SH_SHLD), (0xAC, U.SH_SHRD)):
        row(1, op, opc=U.OPC_SHIFT, sub=sub, modrm=1, form=F_RM_REG,
            imm=IMM_8ZX, sext=3)
        row(1, op + 1, opc=U.OPC_SHIFT, sub=sub, modrm=1, form=F_RM_REG,
            sext=4)
    row(1, 0xAF, opc=U.OPC_MUL, sub=U.MUL_2OP, modrm=1, form=F_REG_RM)
    row(1, 0xB0, opc=U.OPC_CMPXCHG, modrm=1, form=F_RM_REG, size8=1)
    row(1, 0xB1, opc=U.OPC_CMPXCHG, modrm=1, form=F_RM_REG)
    row(1, 0xB6, opc=U.OPC_MOV, modrm=1, form=F_REG_RM, rm8=1, srcsize=1)
    row(1, 0xB7, opc=U.OPC_MOV, modrm=1, form=F_REG_RM, srcsize=2)
    row(1, 0xBE, opc=U.OPC_MOV, modrm=1, form=F_REG_RM, rm8=1, srcsize=1,
        sext=1)
    row(1, 0xBF, opc=U.OPC_MOV, modrm=1, form=F_REG_RM, srcsize=2, sext=1)
    row(1, 0xB8, opc=U.OPC_BITSCAN, sub=U.BS_POPCNT, modrm=1,
        form=F_REG_RM, special=SP_POPCNT)
    for d in range(4):
        invalid(1, 0xBA, digit=d, modrm=1)
    for d in range(4, 8):
        row(1, 0xBA, digit=d, opc=U.OPC_BT, sub=d - 4, modrm=1,
            form=F_RM_DST, imm=IMM_8ZX, kimm=1)
    row(1, 0xBC, opc=U.OPC_BITSCAN, sub=U.BS_BSF, modrm=1, form=F_REG_RM,
        special=SP_BSCAN)
    row(1, 0xBD, opc=U.OPC_BITSCAN, sub=U.BS_BSR, modrm=1, form=F_REG_RM,
        special=SP_BSCAN)
    row(1, 0xC0, opc=U.OPC_XADD, modrm=1, form=F_RM_REG, size8=1)
    row(1, 0xC1, opc=U.OPC_XADD, modrm=1, form=F_RM_REG)
    for op in range(0xC8, 0xD0):
        row(1, op, opc=U.OPC_BSWAP, form=F_OPREG_DST, osz=OSZ_W84)
    return lut


_LUT = _build_lut()

# ---------------------------------------------------------------------------
# Traced scalar decode of one 15-byte window -> uop record (vmap for lanes)
# ---------------------------------------------------------------------------


class DecUop(NamedTuple):
    known: jax.Array   # bool: within the device subset (False -> park)
    f: jax.Array       # int32[NF] in uops.INT_FIELDS order
    disp: jax.Array    # uint64 (sign-extended, masked)
    imm: jax.Array     # uint64


def _rd(win: jax.Array, i: jax.Array) -> jax.Array:
    """Clamped byte read: out-of-window indices only occur on encodings
    whose consumed length exceeds the window, which decode as the host's
    _Truncated all-default INVALID — the clamped value is never used."""
    return win[jnp.clip(i, 0, MAX_LEN - 1)].astype(jnp.int32)


def _sx_u64(v: jax.Array, bits: int) -> jax.Array:
    sign = jnp.uint64(1 << (bits - 1))
    return (v ^ sign) - sign  # u64 wraparound == host _sx mask


def _read_le_u64(win: jax.Array, i: jax.Array) -> jax.Array:
    v = jnp.uint64(0)
    for k in range(8):
        v = v | (_rd(win, i + k).astype(jnp.uint64) << jnp.uint64(8 * k))
    return v


_LUT_FLAT = jnp.asarray(_LUT.reshape(2 * 256 * 8, N_COL))


def decode_window(win: jax.Array) -> DecUop:
    """Decode the instruction at win[0:15] (uint8[15]).  Replicates
    cpu.decoder.decode bit for bit over the device subset; anything the
    LUT marks UNKNOWN returns known=False for a host park."""
    i32 = jnp.int32

    # prefix scan (cpu.decoder._decode_prefixes): legacy prefixes in any
    # order/count, then at most one REX immediately before the opcode
    def pfx_body(_, c):
        pos, done, osize, asize, lock, repne, rep, seg, anyleg = c
        b = _rd(win, pos)
        is66, is67 = b == 0x66, b == 0x67
        isf0, isf2, isf3 = b == 0xF0, b == 0xF2, b == 0xF3
        is64, is65 = b == 0x64, b == 0x65
        isnull = (b == 0x26) | (b == 0x2E) | (b == 0x36) | (b == 0x3E)
        legacy = is66 | is67 | isf0 | isf2 | isf3 | is64 | is65 | isnull
        take = jnp.logical_and(~done, legacy)
        seg = jnp.where(take & is64, i32(U.SEG_FS),
                        jnp.where(take & is65, i32(U.SEG_GS), seg))
        return (pos + take.astype(i32), done | ~legacy,
                osize | (take & is66), asize | (take & is67),
                lock | (take & isf0), repne | (take & isf2),
                rep | (take & isf3), seg,
                anyleg | (take & (is66 | isf0 | isf2 | isf3)))

    f_ = jnp.bool_(False)
    pos, _, osize, asize, lock, repne, rep, seg, anyleg = lax.fori_loop(
        0, MAX_LEN, pfx_body,
        (i32(0), f_, f_, f_, f_, f_, f_, i32(U.SEG_NONE), f_))

    b = _rd(win, pos)
    isrex = (b >= 0x40) & (b <= 0x4F)
    rex = jnp.where(isrex, b & 0xF, 0)
    rexp = isrex
    pos = pos + isrex.astype(i32)
    rex_w, rex_r = (rex >> 3) & 1, (rex >> 2) & 1
    rex_x, rex_b = (rex >> 1) & 1, rex & 1

    op = _rd(win, pos)
    pos = pos + 1
    map1 = op == 0x0F
    op2 = _rd(win, pos)
    pos = pos + map1.astype(i32)          # position after the opcode
    opv = jnp.where(map1, op2, op)

    row = _LUT_FLAT[(map1.astype(i32) * 256 + opv) * 8
                    + ((_rd(win, pos) >> 3) & 7)]
    known = row[C_KIND] == K_KNOWN
    special = row[C_SPECIAL]
    has_modrm = (row[C_MODRM] > 0) & known

    # speculative ModRM/SIB/disp parse (cpu.decoder._ModRM)
    modrm = _rd(win, pos)
    mod = modrm >> 6
    regf = ((modrm >> 3) & 7) | (rex_r << 3)
    rm = modrm & 7
    is_mem = mod != 3
    rm_reg = rm | (rex_b << 3)
    sib = _rd(win, pos + 1)
    sib_present = has_modrm & is_mem & (rm == 4)
    sidx = ((sib >> 3) & 7) | (rex_x << 3)
    sbase = (sib & 7) | (rex_b << 3)
    rip_rel = has_modrm & is_mem & (rm == 5) & (mod == 0)
    sib_disp32 = sib_present & ((sbase & 7) == 5) & (mod == 0)
    disp8 = has_modrm & is_mem & (mod == 1)
    disp32 = (has_modrm & is_mem & (mod == 2)) | rip_rel | sib_disp32
    disp_off = pos + 1 + sib_present.astype(i32)
    disp_len = jnp.where(disp8, 1, jnp.where(disp32, 4, 0))
    modrm_len = jnp.where(has_modrm,
                          1 + sib_present.astype(i32) + disp_len, 0)
    draw = _read_le_u64(win, disp_off)
    disp = jnp.where(disp8, _sx_u64(draw & jnp.uint64(0xFF), 8),
                     jnp.where(disp32,
                               _sx_u64(draw & jnp.uint64(0xFFFFFFFF), 32),
                               jnp.uint64(0)))
    base_reg = jnp.where(
        rip_rel, i32(U.REG_RIP),
        jnp.where(sib_present,
                  jnp.where(sib_disp32, i32(U.REG_NONE), sbase),
                  jnp.where(is_mem, rm_reg, i32(U.REG_NONE))))
    base_reg = jnp.where(has_modrm & is_mem, base_reg, i32(U.REG_NONE))
    idx_reg = jnp.where(sib_present & (sidx != 4), sidx, i32(U.REG_NONE))
    scale = jnp.where(sib_present, i32(1) << (sib >> 6), i32(1))

    # operation size
    size8 = row[C_SIZE8] > 0
    osz = row[C_OSZ]
    opsize = jnp.where(
        size8, 1,
        jnp.where(osz == OSZ_PP, jnp.where(osize, 2, 8),
                  jnp.where(osz == OSZ_8, 8,
                            jnp.where(osz == OSZ_W84,
                                      jnp.where(rex_w > 0, 8, 4),
                                      jnp.where(rex_w > 0, 8,
                                                jnp.where(osize, 2, 4))))))

    # immediate
    immc = row[C_IMM]
    imm_len = jnp.where(
        (immc == IMM_8SX) | (immc == IMM_8ZX), 1,
        jnp.where(immc == IMM_16ZX, 2,
                  jnp.where(immc == IMM_STD,
                            jnp.where(opsize == 2, 2, 4),
                            jnp.where(immc == IMM_32SX, 4,
                                      jnp.where(immc == IMM_MOVABS,
                                                jnp.where(opsize == 8, 8,
                                                          jnp.where(opsize == 2,
                                                                    2, 4)),
                                                0)))))
    ipos = pos + modrm_len
    iraw = _read_le_u64(win, ipos)
    shift = jnp.uint64(64) - (imm_len.astype(jnp.uint64) << jnp.uint64(3))
    masked = jnp.where(imm_len > 0, (iraw << shift) >> shift, jnp.uint64(0))
    imm = jnp.where(
        immc == IMM_8SX, _sx_u64(masked, 8),
        jnp.where(immc == IMM_STD,
                  jnp.where(opsize == 2, _sx_u64(masked, 16),
                            _sx_u64(masked, 32)),
                  jnp.where(immc == IMM_32SX, _sx_u64(masked, 32),
                            jnp.where(immc == IMM_ONE, jnp.uint64(1),
                                      masked))))
    length = ipos + imm_len + (special == SP_CD).astype(i32)

    # specials
    sub = row[C_SUB]
    cond = row[C_COND]
    kind_unknown = ~known
    sub = jnp.where(special == SP_CD, _rd(win, ipos), sub)
    cond = jnp.where(special == SP_E3,
                     jnp.where(asize, i32(17), i32(16)), cond)
    bs = special == SP_BSCAN
    sub = jnp.where(bs & rep & (sub == U.BS_BSF), i32(U.BS_TZCNT),
                    jnp.where(bs & rep & (sub == U.BS_BSR),
                              i32(U.BS_LZCNT), sub))
    kind_unknown = kind_unknown | ((special == SP_POPCNT) & ~rep)
    # C4/C5: VEX (-> host) unless a legacy/REX prefix #UDs it into the
    # primary map's unmatched INVALID
    vex = special == SP_VEX
    vex_invalid = vex & (anyleg | rexp)
    kind_unknown = kind_unknown | (vex & ~vex_invalid)

    # operand synthesis
    def g8(r):
        return jnp.where((rex == 0) & (r >= 4) & (r <= 7),
                         U.REG_AH_BASE + (r - 4), r)

    form = row[C_FORM]
    opreg = (opv & 7) | (rex_b << 3)
    rm_is_dst = ((form == F_RM_REG) | (form == F_RM_DST)
                 | (form == F_RM_CL))
    rm_is_src = (form == F_REG_RM) | (form == F_RM_SRC)
    rm_used = rm_is_dst | rm_is_src
    rm8 = size8 | (row[C_RM8] > 0)
    rm_regv = jnp.where(rm8, g8(rm_reg), rm_reg)
    reg_regv = jnp.where(size8, g8(regf), regf)
    mem_side = rm_used & is_mem

    dst_kind = jnp.where(
        rm_is_dst, jnp.where(is_mem, i32(U.K_MEM), i32(U.K_REG)),
        jnp.where((form == F_REG_RM) | (form == F_LEA)
                  | (form == F_OPREG_DST) | (form == F_OPREG_DST8)
                  | (form == F_XCHG_ACC) | (form == F_ACC),
                  i32(U.K_REG), i32(U.K_NONE)))
    dst_reg = jnp.where(
        rm_is_dst & ~is_mem, rm_regv,
        jnp.where(form == F_REG_RM, reg_regv,
                  jnp.where(form == F_LEA, regf,
                            jnp.where(form == F_OPREG_DST, opreg,
                                      jnp.where(form == F_OPREG_DST8,
                                                g8(opreg),
                                                jnp.where(form == F_XCHG_ACC,
                                                          opreg, i32(0)))))))
    dst_reg = jnp.where(dst_kind == U.K_REG, dst_reg, i32(0))
    src_kind = jnp.where(
        rm_is_src, jnp.where(is_mem, i32(U.K_MEM), i32(U.K_REG)),
        jnp.where((form == F_RM_REG) | (form == F_OPREG_SRC)
                  | (form == F_XCHG_ACC) | (form == F_RM_CL),
                  i32(U.K_REG), i32(U.K_NONE)))
    src_reg = jnp.where(
        rm_is_src & ~is_mem, rm_regv,
        jnp.where(form == F_RM_REG, reg_regv,
                  jnp.where(form == F_OPREG_SRC, opreg,
                            jnp.where(form == F_RM_CL, i32(1), i32(0)))))
    src_reg = jnp.where(src_kind == U.K_REG, src_reg, i32(0))
    src_kind = jnp.where(row[C_KIMM] > 0, i32(U.K_IMM), src_kind)

    lea_mem = (form == F_LEA) & is_mem
    use_mem = mem_side | lea_mem
    segv = jnp.where(mem_side, seg, i32(U.SEG_NONE))  # lea ignores seg
    base_reg = jnp.where(use_mem, base_reg, i32(U.REG_NONE))
    idx_reg = jnp.where(use_mem, idx_reg, i32(U.REG_NONE))
    scale = jnp.where(use_mem, scale, i32(1))
    disp = jnp.where(use_mem, disp, jnp.uint64(0))

    repv = jnp.where(row[C_REP] > 0,
                     jnp.where(rep, i32(U.REP_REP),
                               jnp.where(repne, i32(U.REP_REPNE),
                                         i32(U.REP_NONE))),
                     i32(U.REP_NONE))

    opc = row[C_OPC]
    # lea reg-form: INVALID after the (consumed) ModRM
    lea_invalid = (form == F_LEA) & ~is_mem
    invalid = (opc == U.OPC_INVALID) | lea_invalid | vex_invalid

    def inv(val, default):
        return jnp.where(invalid, default, val)

    opc = jnp.where(invalid, i32(U.OPC_INVALID), opc)
    length = jnp.where(vex_invalid, pos, length)
    # lea reg-form: the host sets opsize before bailing to INVALID
    opsize_out = jnp.where(invalid & ~lea_invalid, i32(8), opsize)
    fields = [
        opc, inv(sub, i32(0)), inv(cond, i32(0)), length,
        opsize_out, inv(row[C_SRCSIZE], i32(0)),
        inv(row[C_SEXT], i32(0)),
        inv(dst_kind, i32(U.K_NONE)), inv(dst_reg, i32(0)),
        inv(src_kind, i32(U.K_NONE)), inv(src_reg, i32(0)),
        inv(base_reg, i32(U.REG_NONE)), inv(idx_reg, i32(U.REG_NONE)),
        inv(scale, i32(1)), inv(segv, i32(U.SEG_NONE)),
        inv(repv, i32(U.REP_NONE)), lock.astype(i32), asize.astype(i32)]
    disp = inv(disp, jnp.uint64(0))
    imm = inv(imm, jnp.uint64(0))

    # truncation: the host raises _Truncated at the first needed byte past
    # the window and returns the ALL-default INVALID (a32/lock included)
    f = jnp.stack(fields)
    trunc = length > MAX_LEN
    default = jnp.zeros((NF,), i32).at[_IX["opc"]].set(U.OPC_INVALID) \
        .at[_IX["length"]].set(1).at[_IX["opsize"]].set(8) \
        .at[_IX["base_reg"]].set(U.REG_NONE) \
        .at[_IX["idx_reg"]].set(U.REG_NONE).at[_IX["scale"]].set(1)
    f = jnp.where(trunc, default, f)
    disp = jnp.where(trunc, jnp.uint64(0), disp)
    imm = jnp.where(trunc, jnp.uint64(0), imm)
    return DecUop(known=~kind_unknown, f=f, disp=disp, imm=imm)

# ---------------------------------------------------------------------------
# Service pass: per-lane block compute (parallel) + global sequential commit
# ---------------------------------------------------------------------------
#
# A service pass replicates one round of `runner._service_decode` in-graph:
#
#   phase 1 (lane-parallel, vmapped; mesh: shard-local): each NEED_DECODE
#     lane fetches and decodes its miss and runs the LIFO prefetch walk
#     against the ROUND-START table plus its own records, yielding a block
#     of up to RECS publish records;
#   phase 2 (sequential, replicated on every shard after an all-gather):
#     blocks commit in global lane order against the LIVE table.  The
#     commit enforces the host's gates exactly — `cache.has` at the miss
#     (drop block, resume lane), the capacity margin mid-walk (drop the
#     block's tail, keep the lane serviced) — and detects every case the
#     phase-1 walk could have diverged from the host's strictly-sequential
#     service (a record already published by an earlier lane THIS round, a
#     hash-probe failure, capacity, or an encoding outside the device
#     subset).  Any such lane rolls back its partial block and parks, and
#     so does EVERY needy lane after it: the host then services the parked
#     lanes in lane order, which preserves the one invariant everything
#     downstream leans on — entry indices (and so coverage-bitmap bits)
#     identical to a run where the host serviced every miss itself.

_STATUS_NEED_DECODE = 8   # StatusCode.NEED_DECODE (core/results.py)
_STATUS_RUNNING = 0       # StatusCode.RUNNING
_STATUS_PAGE_FAULT = 7    # StatusCode.PAGE_FAULT
_CTR_MEM_FAULT = 1        # machine.CTR_MEM_FAULT

_N_META = NF + 3          # uoptable meta_i32 columns (fields, pfn0, pfn1, bp)

# uoptable.meta_u64 column order
MU_DISP, MU_IMM, MU_RAW_LO, MU_RAW_HI = range(4)


def _splitmix_lo(key: jax.Array) -> jax.Array:
    """splitmix64 low 32 bits (utils/hashing.py bit for bit); the hash
    mask is < 2^32 so (h + k) & mask == (h_lo + k) & mask."""
    x = key + jnp.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> jnp.uint64(31))
    return (x & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)


def _probe_slots(hash_rows: jax.Array, key: jax.Array) -> jax.Array:
    """The 8 probe slot indices for `key` (same sequence as
    `DecodeCache._hash_insert`)."""
    mask = jnp.uint32(hash_rows.shape[0] - 1)
    h = _splitmix_lo(key)
    return ((h + jnp.arange(PROBES, dtype=jnp.uint32)) & mask).astype(
        jnp.int32)


def _probe_entry(hash_rows: jax.Array, key: jax.Array) -> jax.Array:
    """Live-table lookup: entry index or -1.  `hash_rows` is the widened
    [hash_size, 3] (entry, key_lo, key_hi) table (uoptable.device)."""
    rows = hash_rows[_probe_slots(hash_rows, key)]
    klo = (key & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32).astype(jnp.int32)
    khi = (key >> jnp.uint64(32)).astype(jnp.uint32).astype(jnp.int32)
    hit = (rows[:, 0] >= 0) & (rows[:, 1] == klo) & (rows[:, 2] == khi)
    return jnp.max(jnp.where(hit, rows[:, 0], -1))


def _key_of(rip: jax.Array, tenant: jax.Array) -> jax.Array:
    return rip ^ (tenant.astype(jnp.uint64) << jnp.uint64(48))


class LaneBlock(NamedTuple):
    """One lane's phase-1 result: its miss outcome plus publish records
    (record 0 = the miss; the rest the prefetch walk, in walk order)."""

    needy: jax.Array    # bool: lane was NEED_DECODE
    fault: jax.Array    # bool: 15-byte fetch at the miss rip faulted
    parked: jax.Array   # bool: miss or walk left the device subset
    rip: jax.Array      # u64 miss rip (fault_gva on the fault path)
    n: jax.Array        # i32 record count (0 on fault/park-at-miss)
    keys: jax.Array     # u64[RECS] tagged probe keys
    fi: jax.Array       # i32[RECS, NF+3] uoptable meta_i32 rows
    fu: jax.Array       # u64[RECS, 4] uoptable meta_u64 rows


def _pack_raw_u64(win: jax.Array, length: jax.Array):
    """Device `_pack_raw`: the first `length` window bytes LE-packed into
    (lo, hi), zero beyond — bit-identical to the host's ljust-with-NULs
    since decode lengths never exceed MAX_LEN < 16."""
    w16 = jnp.concatenate([win, jnp.zeros((1,), jnp.uint8)])
    lo = jnp.uint64(0)
    hi = jnp.uint64(0)
    for k in range(8):
        lo = lo | (w16[k].astype(jnp.uint64) << jnp.uint64(8 * k))
        hi = hi | (w16[8 + k].astype(jnp.uint64) << jnp.uint64(8 * k))
    nlo = jnp.minimum(length, 8)
    nhi = jnp.maximum(length - 8, 0)
    lo_mask = _M64 >> (jnp.uint64(64) - jnp.uint64(8) * nlo.astype(jnp.uint64))
    hi_mask = jnp.where(
        nhi > 0,
        _M64 >> (jnp.uint64(64) - jnp.uint64(8) * nhi.astype(jnp.uint64)),
        jnp.uint64(0))
    return lo & lo_mask, hi & hi_mask


def _record_row(image, overlay, cr3, at: jax.Array, d: DecUop,
                pfn0: jax.Array, win: jax.Array, bp_keys, n_bp,
                key: jax.Array):
    """Assemble the uoptable meta rows for a decoded instruction —
    pfn1 (`runner._decode_at`: translate of the last byte, pfn0 on
    fault), bp (pending-breakpoint membership), raw packing."""
    length = d.f[_IX["length"]]
    t1 = paging.translate(
        image, overlay, cr3,
        at + jnp.maximum(length - 1, 0).astype(jnp.uint64))
    pfn1 = jnp.where(t1.ok, (t1.gpa >> jnp.uint64(12)).astype(jnp.int32),
                     pfn0)
    nb = jnp.arange(bp_keys.shape[0], dtype=jnp.int32) < n_bp
    bp = jnp.any(nb & (bp_keys == key)).astype(jnp.int32)
    fi = jnp.concatenate([d.f, jnp.stack([pfn0, pfn1, bp])])
    lo, hi = _pack_raw_u64(win, length)
    fu = jnp.stack([d.disp, d.imm, lo, hi])
    return fi, fu


def _succs(fi: jax.Array, fu: jax.Array, at: jax.Array):
    """`runner._prefetch_block.succs` — (push_a, push_b, count) with the
    host's extend order (fallthrough pushed first, so the branch target
    pops first off the LIFO stack)."""
    opc = fi[_IX["opc"]]
    nxt = at + fi[_IX["length"]].astype(jnp.uint64)
    tgt = nxt + fu[MU_IMM]
    terminal = ((opc == U.OPC_RET) | (opc == U.OPC_IRET)
                | (opc == U.OPC_HLT) | (opc == U.OPC_INT)
                | (opc == U.OPC_INT1) | (opc == U.OPC_INVALID)
                | (opc == U.OPC_SYSCALL))
    is_imm = fi[_IX["src_kind"]] == U.K_IMM
    two = (opc == U.OPC_JCC) | ((opc == U.OPC_CALL) & is_imm)
    jmp = opc == U.OPC_JMP
    i32 = jnp.int32
    n = jnp.where(terminal, i32(0),
                  jnp.where(two, i32(2),
                            jnp.where(jmp,
                                      jnp.where(is_imm, i32(1), i32(0)),
                                      i32(1))))
    a = jnp.where(jmp, tgt, nxt)
    return a, tgt, n


def lane_block(tab, image, overlay, cr3: jax.Array, rip: jax.Array,
               status: jax.Array, bp_keys: jax.Array,
               n_bp: jax.Array) -> LaneBlock:
    """Phase 1 for ONE lane (vmap over lanes; every argument scalar or
    lane-sliced, `tab` the ROUND-START table).  Runs regardless of
    status — the commit gates on `needy` — so the vmapped pass has one
    uniform shape."""
    i32 = jnp.int32
    tenant = image.tenant
    needy = status == i32(_STATUS_NEED_DECODE)

    win, fault = paging.virt_read(image, overlay, cr3, rip, MAX_LEN)
    t0 = paging.translate(image, overlay, cr3, rip)
    pfn0 = (t0.gpa >> jnp.uint64(12)).astype(i32)
    d = decode_window(win)
    key0 = _key_of(rip, tenant)
    fi0, fu0 = _record_row(image, overlay, cr3, rip, d, pfn0, win,
                           bp_keys, n_bp, key0)

    keys = jnp.zeros((RECS,), jnp.uint64).at[0].set(key0)
    fis = jnp.zeros((RECS, _N_META), i32).at[0].set(fi0)
    fus = jnp.zeros((RECS, 4), jnp.uint64).at[0].set(fu0)

    parked0 = ~fault & ~d.known
    ok0 = ~fault & d.known

    # LIFO walk seeded with the miss uop's successors
    a, b, ns = _succs(fi0, fu0, rip)
    stack = jnp.zeros((STACK,), jnp.uint64).at[0].set(a).at[1].set(b)
    sp = jnp.where(ok0, ns, i32(0))

    def body(_, c):
        keys, fis, fus, stack, sp, n, budget, parked = c
        act = (sp > 0) & (budget > 0) & ~parked & (n < RECS)
        at = stack[jnp.maximum(sp - 1, 0)]
        sp2 = jnp.where(act, sp - 1, sp)
        key = _key_of(at, tenant)
        seen = (_probe_entry(tab.hash_tab, key) >= 0) | jnp.any(
            (jnp.arange(RECS, dtype=i32) < n) & (keys == key))
        w, f = paging.virt_read(image, overlay, cr3, at, MAX_LEN)
        t = paging.translate(image, overlay, cr3, at)
        p0 = (t.gpa >> jnp.uint64(12)).astype(i32)
        dd = decode_window(w)
        take = act & ~seen & ~f
        parked2 = parked | (take & ~dd.known)
        add = take & dd.known & (dd.f[_IX["opc"]] != U.OPC_INVALID)
        fi, fu = _record_row(image, overlay, cr3, at, dd, p0, w,
                             bp_keys, n_bp, key)
        slot = jnp.where(add, n, RECS - 1)
        keys2 = jnp.where(add, keys.at[slot].set(key), keys)
        fis2 = jnp.where(add, fis.at[slot].set(fi), fis)
        fus2 = jnp.where(add, fus.at[slot].set(fu), fus)
        n2 = n + add.astype(i32)
        budget2 = budget - add.astype(i32)
        sa, sb, sn = _succs(fi, fu, at)
        push = jnp.where(add, sn, 0)
        stack2 = stack.at[jnp.minimum(sp2, STACK - 1)].set(
            jnp.where(push >= 1, sa, stack[jnp.minimum(sp2, STACK - 1)]))
        stack3 = stack2.at[jnp.minimum(sp2 + 1, STACK - 1)].set(
            jnp.where(push >= 2, sb,
                      stack2[jnp.minimum(sp2 + 1, STACK - 1)]))
        sp3 = sp2 + push
        # stack bound: net growth is +1 per published record, so STACK
        # cannot overflow before RECS does; park if it ever would
        parked3 = parked2 | (sp3 > STACK - 1)
        return (keys2, fis2, fus2, stack3, jnp.minimum(sp3, STACK - 1),
                n2, budget2, parked3)

    keys, fis, fus, _, _, n, _, parked = lax.fori_loop(
        0, WALK_ITERS, body,
        (keys, fis, fus, stack, sp, jnp.where(ok0, i32(1), i32(0)),
         i32(PREFETCH_BUDGET), parked0))

    return LaneBlock(
        needy=needy, fault=fault & needy, parked=parked & needy, rip=rip,
        n=jnp.where(ok0, n, i32(0)), keys=keys, fi=fis, fu=fus)


def compute_blocks(tab, image: MemImage, machine, bp_keys: jax.Array,
                   n_bp: jax.Array) -> LaneBlock:
    """Vmapped phase 1 over all local lanes."""
    from wtf_tpu.mem.physmem import IMAGE_IN_AXES

    return jax.vmap(
        lane_block,
        in_axes=(None, IMAGE_IN_AXES, 0, 0, 0, 0, None, None),
    )(tab, image, machine.overlay, machine.cr3, machine.rip,
      machine.status, bp_keys, n_bp)


@jax.jit
def gather_windows(image: MemImage, overlay, cr3: jax.Array,
                   rips: jax.Array, idx: jax.Array):
    """Code windows for the HOST service path (the `--device-decode`
    satellite of runner._service_decode): for each lane index in `idx`,
    the 15-byte fetch window at its rip plus the page-walk facts the
    host decode needs — gathered ON DEVICE in one dispatch, so the host
    transfers k x 15 bytes instead of pulling whole overlay pages and
    walking page tables through the HostView.

    Returns (win u8[k, 15], fault bool[k], pfn0 i32[k], pfn14 i32[k]):
    `fault` mirrors HostView.virt_read's any-byte-faults contract;
    `pfn14` is the frame of the window's last byte (== pfn0 unless the
    window crosses a page), which is all the host needs to reproduce
    `_decode_at`'s pfn1 without a second walk — a successful 15-byte
    read guarantees the last instruction byte's translation succeeds."""
    from wtf_tpu.mem.physmem import IMAGE_IN_AXES, lane_image

    n_lanes = cr3.shape[0]
    img = lane_image(image, n_lanes)
    img_g = img._replace(tenant=img.tenant[idx])
    ov_g = jax.tree.map(lambda x: x[idx], overlay)

    def one(image_l, overlay_l, cr3_l, rip):
        win, fault = paging.virt_read(image_l, overlay_l, cr3_l, rip,
                                      MAX_LEN)
        t0 = paging.translate(image_l, overlay_l, cr3_l, rip)
        pfn0 = (t0.gpa >> jnp.uint64(12)).astype(jnp.int32)
        t14 = paging.translate(image_l, overlay_l, cr3_l,
                               rip + jnp.uint64(MAX_LEN - 1))
        pfn14 = jnp.where(
            t14.ok, (t14.gpa >> jnp.uint64(12)).astype(jnp.int32), pfn0)
        return win, fault, pfn0, pfn14

    return jax.vmap(one, in_axes=(IMAGE_IN_AXES, 0, 0, 0))(
        img_g, ov_g, cr3[idx], rips[idx])


class CommitOut(NamedTuple):
    """Phase-2 result: updated table + per-GLOBAL-lane machine deltas
    (the caller applies its local slice) + stats."""

    tab: object          # UopTable with committed rows
    count: jax.Array     # i32 live entries
    status: jax.Array    # i32[Lg] post-service status
    fault_gva: jax.Array   # u64[Lg]
    fault_mask: jax.Array  # bool[Lg] lanes whose fault fields apply
    mem_fault_inc: jax.Array  # u32[Lg] CTR_MEM_FAULT increments
    parked: jax.Array    # bool[Lg] lanes left for the host
    stats: jax.Array     # i32[3]: serviced lanes, published entries, parks


def commit_blocks(tab, count: jax.Array, blocks: LaneBlock,
                  statuses: jax.Array, capacity: int) -> CommitOut:
    """Phase 2: replay every lane's block in global lane order against
    the live table.  Pure function of (tab, count, blocks, statuses) —
    identical on every shard when blocks/statuses are all-gathered."""
    i32 = jnp.int32
    n_lanes = statuses.shape[0]

    def insert(hash_rows, key, idx):
        """Claim the first free probe slot (host `_hash_insert`);
        returns (rows, slot, ok)."""
        slots = _probe_slots(hash_rows, key)
        free = hash_rows[slots, 0] < 0
        anyfree = jnp.any(free)
        k = jnp.argmax(free)          # first free slot in probe order
        slot = slots[k]
        klo = (key & jnp.uint64(0xFFFFFFFF)).astype(
            jnp.uint32).astype(i32)
        khi = (key >> jnp.uint64(32)).astype(jnp.uint32).astype(i32)
        row = jnp.stack([idx, klo, khi])
        rows2 = jnp.where(anyfree, hash_rows.at[slot].set(row), hash_rows)
        return rows2, slot, anyfree

    def lane_step(g, carry):
        (hash_rows, rip_l, mi, mu, count, park_rest, status, fault_gva,
         fault_mask, mf_inc, parked, stats) = carry
        blk = jax.tree_util.tree_map(lambda a: a[g], blocks)
        needy = blk.needy & (status[g] == i32(_STATUS_NEED_DECODE))

        hit0 = _probe_entry(hash_rows, blk.keys[0]) >= 0
        resume = needy & ~park_rest & hit0      # host `cache.has` gate
        faulted = needy & ~park_rest & ~hit0 & blk.fault
        try_commit = needy & ~park_rest & ~hit0 & ~blk.fault & ~blk.parked
        park_now = needy & ~park_rest & ~hit0 & ~blk.fault & blk.parked

        def rec_step(j, rc):
            (rows, rl, mi2, mu2, cnt, slots_used, ncommit, aborted,
             stopped) = rc
            live = try_commit & (j < blk.n) & ~aborted & ~stopped
            # host walk margin: checked before every pop AFTER the miss
            stop2 = stopped | (live & (j > 0)
                               & (cnt >= i32(capacity - MARGIN)))
            live = live & ~stop2
            key = blk.keys[j]
            # divergence vs an earlier lane's same-round commit
            dup = live & (j > 0) & (_probe_entry(rows, key) >= 0)
            full = live & (cnt >= i32(capacity))
            ins = live & ~dup & ~full
            rows2, slot, ok = insert(rows, key, cnt)
            rows3 = jnp.where(ins, rows2, rows)
            abort2 = aborted | dup | full | (ins & ~ok)
            did = ins & ok
            klo = (key & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
            khi = (key >> jnp.uint64(32)).astype(jnp.uint32)
            at = jnp.where(did, cnt, i32(0))
            rl2 = jnp.where(did,
                            rl.at[at].set(jnp.stack([klo, khi])), rl)
            mi3 = jnp.where(did, mi2.at[at].set(blk.fi[j]), mi2)
            mu3 = jnp.where(did, mu2.at[at].set(blk.fu[j]), mu2)
            su2 = slots_used.at[j].set(jnp.where(did, slot, -1))
            return (rows3, rl2, mi3, mu3, cnt + did.astype(i32), su2,
                    ncommit + did.astype(i32), abort2, stop2)

        slots0 = jnp.full((RECS,), -1, i32)
        (rows, rl, mi2, mu2, cnt, slots_used, ncommit, aborted,
         _stopped) = lax.fori_loop(
            0, RECS, rec_step,
            (hash_rows, rip_l, mi, mu, count, slots0, i32(0),
             jnp.bool_(False), jnp.bool_(False)))

        # an aborted block needs no explicit rollback: the whole-table
        # `where` below re-selects the pre-lane arrays, dropping every
        # slot it claimed
        committed = try_commit & ~aborted
        hash_rows2 = jnp.where(committed, rows, hash_rows)
        rip_l2 = jnp.where(committed, rl, rip_l)
        mi3 = jnp.where(committed, mi2, mi)
        mu3 = jnp.where(committed, mu2, mu)
        count2 = jnp.where(committed, cnt, count)

        parked_g = park_now | (aborted & try_commit) | (park_rest & needy)
        status2 = status.at[g].set(jnp.where(
            resume | committed, i32(_STATUS_RUNNING),
            jnp.where(faulted, i32(_STATUS_PAGE_FAULT), status[g])))
        fault_gva2 = jnp.where(faulted, fault_gva.at[g].set(blk.rip),
                               fault_gva)
        fault_mask2 = fault_mask.at[g].set(faulted)
        mf2 = jnp.where(faulted,
                        mf_inc.at[g].set(jnp.uint32(1)), mf_inc)
        parked2 = parked.at[g].set(parked_g)
        stats2 = (stats
                  .at[0].add(committed.astype(i32))
                  .at[1].add(jnp.where(committed, ncommit, 0))
                  .at[2].add(parked_g.astype(i32)))
        return (hash_rows2, rip_l2, mi3, mu3, count2,
                park_rest | parked_g, status2, fault_gva2, fault_mask2,
                mf2, parked2, stats2)

    init = (tab.hash_tab, tab.rip_l, tab.meta_i32, tab.meta_u64, count,
            jnp.bool_(False), statuses,
            jnp.zeros((n_lanes,), jnp.uint64),
            jnp.zeros((n_lanes,), bool),
            jnp.zeros((n_lanes,), jnp.uint32),
            jnp.zeros((n_lanes,), bool), jnp.zeros((3,), i32))
    (hash_rows, rip_l, mi, mu, count, _pr, status, fault_gva, fault_mask,
     mf_inc, parked, stats) = lax.fori_loop(0, n_lanes, lane_step, init)
    tab2 = tab._replace(hash_tab=hash_rows, rip_l=rip_l, meta_i32=mi,
                        meta_u64=mu)
    return CommitOut(tab=tab2, count=count, status=status,
                     fault_gva=fault_gva, fault_mask=fault_mask,
                     mem_fault_inc=mf_inc, parked=parked, stats=stats)
