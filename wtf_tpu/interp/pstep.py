"""Pallas fused-step fast path: K interpreter steps in ONE kernel dispatch.

PERF.md's performance model says the batched step's wall-clock on real TPUs
is proportional to the NUMBER of unfusable gather/scatter kernels XLA emits
(decode probe, uop fetch, page walks, window reads, coverage scatters —
~13 per step), not to FLOPs.  This module is open lever 3: the hot integer
core of the interpreter runs as one Pallas kernel that advances every lane
up to K instructions per dispatch, so a hot stretch costs ONE kernel launch
instead of ~13 per instruction.  It is the de-risking prototype for the
fully fused interpreter — the persistent-kernel shape Concordia uses to keep
inference inside one long-lived device kernel, and the Linear-Algebraic
Hypervisor's "interpretation belongs inside the accelerator's execution
model" argument, landed as shippable code.

Hot subset (everything the u32-limb library already covers, PR 2):
  decode-cache hash probe, uop fetch, breakpoint/bp_skip gate, dirty-code
  check, register/immediate MOV (incl. movzx/movsx), LEA, the integer ALU
  and UNARY classes with their flag images, SETCC/CMOVCC, condition
  evaluation, Jcc/JMP/fallthrough rip updates, coverage + edge-hash bits,
  the icount/limit (TIMEDOUT) bookkeeping, and the device counter block.

Anything else — memory-operand forms, stack ops, shifts/mul/div, strings,
SSE/x87, system instructions, an armed breakpoint, or code bytes that are
overlay-dirty or diverge from the decode-time raw bytes — PARKS the lane
BEFORE executing: state is untouched and status becomes NEEDS_XLA.  The
runner's chunk ladder (interp/runner.py) then resumes parked lanes with a
short XLA chunk and re-enters the kernel, so the fused path is a pure fast
path layered UNDER the existing executor: every instruction retires through
exactly one of the two engines and the final state is bit-exact vs the
XLA-only ladder (tests/test_pstep.py pins this differentially, including
the park-and-resume seam).

Authoring notes (TPU target, validated via interpret=True on CPU):
  * all arithmetic is u32 limb math (interp/limbs.py) — Pallas TPU kernels
    cannot hold 64-bit integers, which is exactly why PR 2 packed the hot
    state; every u64-typed machine leaf crosses into the kernel through a
    free bitcast at the wrapper seam
  * the grid iterates lanes; per-lane work is scalar (dynamic-index loads
    from the uop table / image implement the gather emulation the XLA path
    pays per-step dispatches for), with the K-step fori_loop carrying the
    register file as a value
  * tier-1 runs the kernel under `interpret=True` on the CPU platform —
    the Mosaic lowering is exercised only when a real TPU backend is
    attached (`interpret=None` auto-detects)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from wtf_tpu.core.results import StatusCode
from wtf_tpu.cpu import uops as U
from wtf_tpu.interp import limbs as L
from wtf_tpu.interp import step as S
from wtf_tpu.interp.machine import (
    CTR_DECODE_MISS, CTR_FUSED, CTR_INSTR, Machine, N_CTRS,
)
from wtf_tpu.interp.uoptable import (
    F_A32, F_BASE_REG, F_COND, F_DST_KIND, F_DST_REG, F_IDX_REG, F_LENGTH,
    F_OPC, F_OPSIZE, F_SCALE, F_SEG, F_SEXT, F_SRCSIZE, F_SRC_KIND,
    F_SRC_REG, F_SUB, M_BP, M_PFN0, M_PFN1, PROBES, UopTable,
)
from wtf_tpu.mem.physmem import (
    IMAGE_IN_AXES, MemImage, PAGE_WORDS, lane_image,
)

_RUNNING = int(StatusCode.RUNNING)
_NEED_DECODE = int(StatusCode.NEED_DECODE)
_NEEDS_XLA = int(StatusCode.NEEDS_XLA)
_TIMEDOUT = int(StatusCode.TIMEDOUT)

# The opclass set this kernel CLAIMS to execute in-kernel (each still
# subject to the per-uop operand conditions in `hot_class` below — e.g.
# MOV only with a register destination and reg/imm source).  The static
# analyzer (wtf_tpu/analysis/parity.py) AST-checks this claim against
# the actual `hot_class` predicate AND against step.py's dispatch /
# `unsupported` expressions, so the two engines cannot drift silently.
FUSED_OPCLASSES = frozenset({
    "NOP", "FENCE", "MOV", "LEA", "ALU", "UNARY", "SETCC", "CMOVCC",
    "JCC", "JMP",
})

# memoized jitted entry points, keyed (k_steps, interpret) /
# (n_steps, donate); jit itself re-specializes per array shapes
_FUSED_CACHE: dict = {}
_RESUME_CACHE: dict = {}


def _u32(x) -> jnp.ndarray:
    return jnp.uint32(x)


def fused_available(interpret: bool = True) -> bool:
    """Whether this jax build can run the fused kernel (tier-1's
    skip-with-reason guard: some jax builds ship without pallas interpret
    support).  Cached after the first probe."""
    global _FUSED_OK
    try:
        return _FUSED_OK
    except NameError:
        pass
    try:
        from jax.experimental import pallas as pl

        def probe(i_ref, o_ref):
            o_ref[0] = i_ref[0] + jnp.uint32(1)

        out = pl.pallas_call(
            probe,
            out_shape=jax.ShapeDtypeStruct((1,), jnp.uint32),
            interpret=interpret,
        )(jnp.zeros(1, jnp.uint32))
        _FUSED_OK = int(out[0]) == 1
    except Exception:  # noqa: BLE001 - any failure means "not available"
        _FUSED_OK = False
    return _FUSED_OK


def _build_kernel(k_steps: int, n_fields: int, hash_size: int,
                  nframes: int, ebits: int):
    """The kernel body, specialized on the static table geometry."""
    hmask = hash_size - 1

    def kernel(hash_ref, trip_ref, tmeta_ref, tmu_ref, pages_ref, ftab_ref,
               ovpfn_ref, limit_ref, tenant_ref,
               gpr_in, rip_in, rf_in, st_in, ic_in, bp_in, ctr_in, cov_in,
               edge_in,
               gpr_out, rip_out, rf_out, st_out, ic_out, bp_out, ctr_out,
               cov_out, edge_out):
        # coverage/edge bitmaps copy through, then take in-loop RMW bits
        cov_out[...] = cov_in[...]
        edge_out[...] = edge_in[...]
        ov_row = ovpfn_ref[0]                       # [slots] i32, read once
        limit_l = (limit_ref[0], limit_ref[1])
        limit_on = (limit_ref[0] | limit_ref[1]) != _u32(0)
        z = _u32(0)
        zero2 = (z, z)
        # the lane's base-image id (wtf_tpu/tenancy): selects the frame-
        # table row and tags the decode-probe key, exactly like step_lane
        tenant = tenant_ref[0]
        ttag = tenant.astype(jnp.uint32) << 16      # bit 48 = hi limb bit 16

        def probe(rip_l):
            """uop_lookup's open-addressed probe, one slot at a time (the
            scalar gather emulation of the XLA path's 8-slot gather pair;
            first live match wins, same result by insertion uniqueness).
            Probes the tenant-tagged key, like step_lane."""
            key_l = (rip_l[0], rip_l[1] ^ ttag)
            h_lo, _ = L.splitmix64(key_l)

            def body(k, found):
                slot = ((h_lo + _u32(0) + k.astype(jnp.uint32))
                        & _u32(hmask)).astype(jnp.int32)
                e = hash_ref[slot]
                ec = jnp.maximum(e, 0)
                ok = ((e >= 0) & (trip_ref[ec, 0] == key_l[0])
                      & (trip_ref[ec, 1] == key_l[1]))
                return jnp.where((found < 0) & ok, e, found)

            return lax.fori_loop(0, PROBES, body, jnp.int32(-1))

        def slot_of(pfn):
            """frame_slot: pfn -> image page slot (0 = absent/zero page),
            through the lane's tenant row of the stacked frame table."""
            in_range = (pfn >= 0) & (pfn < nframes)
            safe = jnp.clip(pfn, 0, nframes - 1)
            return jnp.where(in_range, ftab_ref[tenant, safe], 0)

        def step_body(_, carry):
            gl, rip_l, rf_lo, status, ic_l, bpskip, d_instr, d_miss = carry
            run = status == jnp.int32(_RUNNING)

            # -- 1. decode-cache probe (identical to step.uop_lookup) ----
            idx = probe(rip_l)
            miss = run & (idx < 0)
            idxc = jnp.maximum(idx, 0)
            f = tmeta_ref[idxc]                     # [NF+3] i32 row
            mu = tmu_ref[idxc]                      # [8] u32 row
            opc = f[F_OPC]
            sub = f[F_SUB]
            cond = f[F_COND]
            length = f[F_LENGTH]
            opsize = f[F_OPSIZE]
            srcsize0 = f[F_SRCSIZE]
            sext_f = f[F_SEXT]
            dk, dr = f[F_DST_KIND], f[F_DST_REG]
            sk, sr = f[F_SRC_KIND], f[F_SRC_REG]
            disp_l = (mu[0], mu[1])
            imm_l = (mu[2], mu[3])
            raw_lo_l = (mu[4], mu[5])
            raw_hi_l = (mu[6], mu[7])

            # -- 2. breakpoint gate (honoring bp_skip, like step_lane) ---
            at_bp = run & ~miss & (f[M_BP] == 1) & (bpskip == 0)

            # -- 3. hot-subset eligibility: operands must be registers or
            # immediates; LEA additionally needs no segment base (fs/gs
            # live outside the kernel).  Everything else parks.
            reg_dst = dk == U.K_REG
            src_ri = (sk == U.K_REG) | (sk == U.K_IMM)
            hot_class = (
                (opc == U.OPC_NOP) | (opc == U.OPC_FENCE)
                | ((opc == U.OPC_MOV) & reg_dst & src_ri)
                | ((opc == U.OPC_LEA) & (f[F_SEG] == 0))
                | ((opc == U.OPC_ALU) & reg_dst & src_ri)
                | ((opc == U.OPC_UNARY) & reg_dst)
                | ((opc == U.OPC_SETCC) & reg_dst)
                | ((opc == U.OPC_CMOVCC) & (sk != U.K_MEM))
                | (opc == U.OPC_JCC)
                | ((opc == U.OPC_JMP) & src_ri))

            # -- 4. dirty/diverged code check.  The XLA step compares live
            # code bytes THROUGH the overlay; the kernel reads the base
            # image and parks any lane whose code page frames appear in
            # its overlay, so a clean compare here is exactly the XLA
            # verdict and a dirty page falls through to the full check.
            pfn0, pfn1 = f[M_PFN0], f[M_PFN1]
            code_dirty = jnp.any((ov_row == pfn0) | (ov_row == pfn1))
            code_off = (rip_l[0] & _u32(0xFFF)).astype(jnp.int32)
            crosses = (code_off + 16) > 4096
            s_first = slot_of(pfn0)
            s_last = jnp.where(crosses, slot_of(pfn1), s_first)
            w0 = code_off >> 3
            words = []
            for j in range(3):
                on_first = (w0 + j) < PAGE_WORDS
                widx = jnp.where(on_first, w0 + j, w0 + j - PAGE_WORDS)
                slot = jnp.where(on_first, s_first, s_last)
                words.append((pages_ref[slot, 2 * widx],
                              pages_ref[slot, 2 * widx + 1]))
            sh = (rip_l[0] & _u32(7)) * _u32(8)
            inv = _u32(64) - sh
            code_lo = L.or64(L.shr64(words[0], sh), L.shl64(words[1], inv))
            code_hi = L.or64(L.shr64(words[1], sh), L.shl64(words[2], inv))
            lm_lo = L.size_mask(jnp.minimum(length, 8))
            lm_hi = L.size_mask(jnp.maximum(length - 8, 0))
            smc_risk = (code_dirty
                        | ~L.is_zero64(
                            L.and64(L.xor64(code_lo, raw_lo_l), lm_lo))
                        | ~L.is_zero64(
                            L.and64(L.xor64(code_hi, raw_hi_l), lm_hi)))

            park = run & ~miss & (at_bp | ~hot_class | smc_risk)
            commit = run & ~miss & ~park

            # -- 5. execute (ported paths of step_lane, scalar per lane) -
            next_rip_l = L.add64_u32(rip_l, length.astype(jnp.uint32))
            base_val_l = L.where64(f[F_BASE_REG] == U.REG_RIP, next_rip_l,
                                   S._read64_l(gl, f[F_BASE_REG]))
            idx_val_l = S._scale_idx_l(S._read64_l(gl, f[F_IDX_REG]),
                                       f[F_SCALE])
            ea_l = S.ea_limb(disp_l, base_val_l, idx_val_l, zero2, f[F_A32])
            srcsize = jnp.where(srcsize0 == 0, opsize, srcsize0)
            src_raw_l = L.where64(sk == U.K_REG,
                                  S._read_reg_l(gl, sr, srcsize), zero2)
            src_ext_l = L.where64(
                sext_f == 1, L.zext(L.sext(src_raw_l, srcsize), opsize),
                L.zext(src_raw_l, opsize))
            src_val_l = L.where64(sk == U.K_IMM, L.zext(imm_l, opsize),
                                  src_ext_l)
            dst_val_l = L.where64(dk == U.K_REG,
                                  S._read_reg_l(gl, dr, opsize), zero2)
            cf_in = (rf_lo & _u32(L.CF)) != z
            alu_r, alu_rf_lo, alu_writes = S.alu_limb(
                sub, dst_val_l, src_val_l, cf_in, opsize, rf_lo)
            un_r, un_rf_lo = S.unary_limb(sub, dst_val_l, cf_in, opsize,
                                          rf_lo)
            rcx_l = (gl[1, 0], gl[1, 1])
            cc = L.eval_cond(rf_lo, rcx_l, cond)
            cc01 = (jnp.where(cc, _u32(1), z), z)
            jcc_t = L.add64(next_rip_l, imm_l)
            jmp_t = L.where64(sk == U.K_IMM, jcc_t, src_val_l)

            is_mov = opc == U.OPC_MOV
            is_lea = opc == U.OPC_LEA
            is_alu = opc == U.OPC_ALU
            is_unary = opc == U.OPC_UNARY
            is_setcc = opc == U.OPC_SETCC
            is_cmov = opc == U.OPC_CMOVCC
            is_jcc = opc == U.OPC_JCC
            is_jmp = opc == U.OPC_JMP
            w1_cond = L.sel(
                [is_mov, is_lea, is_alu, is_unary, is_setcc, is_cmov],
                [jnp.bool_(True), jnp.bool_(True), alu_writes,
                 jnp.bool_(True), jnp.bool_(True), jnp.bool_(True)],
                jnp.bool_(False))
            w1_val = L.select64(
                [is_mov, is_lea, is_alu, is_unary, is_setcc, is_cmov],
                [src_val_l, ea_l, alu_r, un_r, cc01,
                 L.where64(cc, src_val_l, dst_val_l)], zero2)
            gl_new = S._gpr_write_l(gl, commit & w1_cond, dr, w1_val,
                                    opsize)

            rf_exec_lo = jnp.where(is_alu, alu_rf_lo,
                                   jnp.where(is_unary, un_rf_lo, rf_lo))
            new_rf_lo = jnp.where(commit, rf_exec_lo | _u32(0x2), rf_lo)

            rip_exec = L.select64(
                [is_jmp, is_jcc],
                [jmp_t, L.where64(cc, jcc_t, next_rip_l)], next_rip_l)
            new_rip = L.where64(commit, rip_exec, rip_l)

            # -- 6. bookkeeping: icount/limit, counters, coverage, edges -
            new_ic = L.where64(commit, L.add64_u32(ic_l, _u32(1)), ic_l)
            timed = commit & limit_on & ~L.ltu64(new_ic, limit_l)
            new_bpskip = jnp.where(commit, jnp.int32(0), bpskip)
            new_status = jnp.where(
                miss, jnp.int32(_NEED_DECODE),
                jnp.where(park, jnp.int32(_NEEDS_XLA),
                          jnp.where(timed, jnp.int32(_TIMEDOUT), status)))

            wi = idxc >> 5
            cov_bit = jnp.where(
                commit, _u32(1) << (idxc & 31).astype(jnp.uint32), z)
            cov_out[0, wi] = cov_out[0, wi] | cov_bit
            eh_lo = L.mix64(rip_l)[0] ^ rip_exec[0]
            ei = (eh_lo & _u32(ebits - 1)).astype(jnp.int32)
            edge_bit = jnp.where(
                commit & (is_jmp | is_jcc),
                _u32(1) << (ei & 31).astype(jnp.uint32), z)
            edge_out[0, ei >> 5] = edge_out[0, ei >> 5] | edge_bit

            one = jnp.where(commit, _u32(1), z)
            return (gl_new, new_rip, new_rf_lo, new_status, new_ic,
                    new_bpskip, d_instr + one,
                    d_miss + jnp.where(miss, _u32(1), z))

        init = (gpr_in[0], (rip_in[0, 0], rip_in[0, 1]), rf_in[0, 0],
                st_in[0], (ic_in[0, 0], ic_in[0, 1]), bp_in[0],
                _u32(0), _u32(0))
        (gl, rip_l, rf_lo, status, ic_l, bpskip, d_instr,
         d_miss) = lax.fori_loop(0, k_steps, step_body, init)

        gpr_out[0] = gl
        rip_out[0, 0], rip_out[0, 1] = rip_l[0], rip_l[1]
        rf_out[0, 0] = rf_lo
        rf_out[0, 1] = rf_in[0, 1]      # hot classes never touch bits 32+
        st_out[0] = status
        ic_out[0, 0], ic_out[0, 1] = ic_l[0], ic_l[1]
        bp_out[0] = bpskip
        delta = jnp.zeros(N_CTRS, jnp.uint32)
        delta = delta.at[CTR_INSTR].set(d_instr)
        delta = delta.at[CTR_DECODE_MISS].set(d_miss)
        # every kernel-retired instruction is by definition a fused one
        delta = delta.at[CTR_FUSED].set(d_instr)
        ctr_out[0] = ctr_in[0] + delta

    return kernel


def make_run_fused(k_steps: int, interpret: Optional[bool] = None):
    """Build (or fetch) the jitted fused-step executor: up to `k_steps`
    hot-subset instructions per lane per dispatch.

    `interpret=None` auto-selects: real Mosaic lowering on a TPU backend,
    the Pallas interpreter elsewhere (the tier-1/CPU validation mode)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    key = (k_steps, interpret)
    cached = _FUSED_CACHE.get(key)
    if cached is not None:
        return cached

    from jax.experimental import pallas as pl

    @jax.jit
    def run_fused(tab: UopTable, image: MemImage, machine: Machine, limit):
        n_lanes = machine.status.shape[0]
        image = lane_image(image, n_lanes)
        n_fields = tab.meta_i32.shape[1]
        hash_size = tab.hash_tab.shape[0]
        capacity = tab.rip_l.shape[0]
        n_tenants, nframes = image.frame_table.shape
        slots = machine.overlay.pfn.shape[1]
        cov_w = machine.cov.shape[1]
        edge_w = machine.edge.shape[1]
        ebits = edge_w * 32
        n_slots_img = image.pages.shape[0]

        # u64 leaves cross the kernel boundary as free u32 bitcasts
        tmu32 = lax.bitcast_convert_type(
            tab.meta_u64, jnp.uint32).reshape(capacity, 8)
        pages32 = lax.bitcast_convert_type(
            image.pages, jnp.uint32).reshape(n_slots_img, 2 * PAGE_WORDS)
        ic32 = lax.bitcast_convert_type(machine.icount, jnp.uint32)
        limit32 = lax.bitcast_convert_type(
            jnp.asarray(limit, jnp.uint64).reshape(1),
            jnp.uint32).reshape(2)

        kernel = _build_kernel(k_steps, n_fields, hash_size, nframes, ebits)

        def full(shape):
            nd = len(shape)
            return pl.BlockSpec(shape, lambda i, _n=nd: (0,) * _n)

        def lane(shape_tail):
            nd = 1 + len(shape_tail)
            return pl.BlockSpec((1,) + shape_tail,
                                lambda i, _n=nd: (i,) + (0,) * (_n - 1))

        out = pl.pallas_call(
            kernel,
            grid=(n_lanes,),
            in_specs=[
                full((hash_size,)),
                full((capacity, 2)),
                full((capacity, n_fields)),
                full((capacity, 8)),
                full((n_slots_img, 2 * PAGE_WORDS)),
                full((n_tenants, nframes)),
                lane((slots,)),
                full((2,)),
                lane(()),
                lane((16, 2)),
                lane((2,)),
                lane((2,)),
                lane(()),
                lane((2,)),
                lane(()),
                lane((N_CTRS,)),
                lane((cov_w,)),
                lane((edge_w,)),
            ],
            out_specs=[
                lane((16, 2)),
                lane((2,)),
                lane((2,)),
                lane(()),
                lane((2,)),
                lane(()),
                lane((N_CTRS,)),
                lane((cov_w,)),
                lane((edge_w,)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n_lanes, 16, 2), jnp.uint32),
                jax.ShapeDtypeStruct((n_lanes, 2), jnp.uint32),
                jax.ShapeDtypeStruct((n_lanes, 2), jnp.uint32),
                jax.ShapeDtypeStruct((n_lanes,), jnp.int32),
                jax.ShapeDtypeStruct((n_lanes, 2), jnp.uint32),
                jax.ShapeDtypeStruct((n_lanes,), jnp.int32),
                jax.ShapeDtypeStruct((n_lanes, N_CTRS), jnp.uint32),
                jax.ShapeDtypeStruct((n_lanes, cov_w), jnp.uint32),
                jax.ShapeDtypeStruct((n_lanes, edge_w), jnp.uint32),
            ],
            interpret=interpret,
        )(tab.hash_tab, tab.rip_l, tab.meta_i32, tmu32, pages32,
          image.frame_table, machine.overlay.pfn, limit32, image.tenant,
          machine.gpr_l, machine.rip_l, machine.rflags_l, machine.status,
          ic32, machine.bp_skip, machine.ctr, machine.cov, machine.edge)
        gpr_l, rip_l, rf_l, status, ic_out, bp_skip, ctr, cov, edge = out
        return machine._replace(
            gpr_l=gpr_l, rip_l=rip_l, rflags_l=rf_l, status=status,
            icount=lax.bitcast_convert_type(ic_out, jnp.uint64),
            bp_skip=bp_skip, ctr=ctr, cov=cov, edge=edge)

    _FUSED_CACHE[key] = run_fused
    return run_fused


def make_run_resume(n_steps: int, donate: bool = None):
    """The fused ladder's XLA resume leg: run a SHORT chunk of the full
    transition function (interp/step.py) for the lanes the kernel parked,
    so the one instruction that parked each lane retires on the precise
    path, then control returns to the kernel.

    The leg swaps statuses around the chunk: parked (NEEDS_XLA) lanes run,
    while still-RUNNING lanes — hot lanes that simply exhausted the
    kernel's K steps — are HELD for its duration and released after.
    Without the hold every round would retire `n_steps` hot instructions
    on the XLA path per lane, capping fused occupancy at K/(K+n) even on
    all-hot code; with it, occupancy equals the stream's hot fraction.
    `n_steps` stays small (default 1) because every XLA-retired
    instruction is lost occupancy for lanes that park.

    Same memoization/donation policy as step.make_run_chunk (donation is
    unsound on the XLA CPU backend — see that docstring)."""
    if donate is None:
        donate = jax.default_backend() != "cpu"
    key = (n_steps, donate)
    cached = _RESUME_CACHE.get(key)
    if cached is not None:
        return cached

    from functools import partial

    from wtf_tpu.interp.step import step_lane

    step_v = jax.vmap(step_lane, in_axes=(None, IMAGE_IN_AXES, 0, None))
    running = jnp.int32(_RUNNING)
    parked = jnp.int32(_NEEDS_XLA)

    @partial(jax.jit, donate_argnums=(2,) if donate else ())
    def run_resume(tab: UopTable, image: MemImage, machine: Machine, limit):
        image = lane_image(image, machine.status.shape[0])
        st = machine.status
        machine = machine._replace(status=jnp.where(
            st == parked, running, jnp.where(st == running, parked, st)))

        def cond(carry):
            i, m = carry
            return (i < n_steps) & jnp.any(m.status == running)

        def body(carry):
            i, m = carry
            return i + 1, step_v(tab, image, m, limit)

        _, out = lax.while_loop(cond, body, (jnp.int32(0), machine))
        # release held lanes (step_lane never emits NEEDS_XLA itself, so
        # every remaining NEEDS_XLA is a lane held above)
        return out._replace(status=jnp.where(
            out.status == parked, running, out.status))

    _RESUME_CACHE[key] = run_resume
    return run_resume
