"""Pallas fused-step fast path: K interpreter steps in ONE kernel dispatch.

PERF.md's performance model says the batched step's wall-clock on real TPUs
is proportional to the NUMBER of unfusable gather/scatter kernels XLA emits
(decode probe, uop fetch, page walks, window reads, coverage scatters —
~13 per step), not to FLOPs.  This module is open lever 3: the hot integer
core of the interpreter runs as one Pallas kernel that advances every lane
up to K instructions per dispatch, so a hot stretch costs ONE kernel launch
instead of ~13 per instruction.  It is the de-risking prototype for the
fully fused interpreter — the persistent-kernel shape Concordia uses to keep
inference inside one long-lived device kernel, and the Linear-Algebraic
Hypervisor's "interpretation belongs inside the accelerator's execution
model" argument, landed as shippable code.

Hot subset — now including the MEMORY path: the 4-level page walk
(`translate_vec_l`'s semantics, scalar per lane) and the delta-overlay
probe run INSIDE the kernel, so memory-operand forms execute in-kernel:

  decode-cache hash probe, uop fetch, breakpoint/bp_skip gate, the
  overlay-aware SMC byte compare, MOV (register, immediate AND memory
  operands, incl. movzx/movsx), LEA, the integer ALU class (reg/imm/mem
  src, reg/mem dst — CMP/TEST included), SHIFT/ROT (incl. shld/shrd and
  mem-dst forms), MUL (2/3-op imul + widening mul/imul), UNARY (reg/mem),
  SETCC (reg/mem), CMOVCC (reg/mem src), Jcc/JMP (imm/reg/mem targets),
  the stack ops PUSH/POP/CALL/RET, condition evaluation, coverage +
  edge-hash bits, the icount/limit (TIMEDOUT) bookkeeping, and the device
  counter block.  Guest stores commit straight into the lane's delta
  overlay (allocation included) inside the kernel.

Anything else — strings, DIV, BT/BITSCAN/BSWAP/XCHG/CMPXCHG, SSE/x87,
system instructions, an armed breakpoint, code bytes that diverge from the
decode-time raw bytes — PARKS the lane BEFORE executing: state is
untouched and status becomes NEEDS_XLA.  A lane whose memory access would
FAULT (non-present / non-writable walk, out-of-range store frame, overlay
slot exhaustion) also parks — the XLA leg then re-executes that one
instruction on the precise path and raises the exact PAGE_FAULT /
OVERLAY_FULL status, fault address and counters.  The two park families
are attributed separately (CTR_PARK_SUBSET vs CTR_PARK_MEM) so occupancy
loss is diagnosable from telemetry.  The runner's chunk ladder
(interp/runner.py) resumes parked lanes with a short XLA chunk and
re-enters the kernel, so the fused path is a pure fast path layered UNDER
the existing executor: every instruction retires through exactly one of
the two engines and the final state is bit-exact vs the XLA-only ladder
(tests/test_pstep.py pins this differentially, including the
park-and-resume seam, the in-kernel walk vs translate_vec_l, and
in-kernel stores vs the overlay word-window path).

Authoring notes (TPU target, validated via interpret=True on CPU):
  * all arithmetic is u32 limb math (interp/limbs.py) — Pallas TPU kernels
    cannot hold 64-bit integers, which is exactly why PR 2 packed the hot
    state; every u64-typed machine leaf (incl. cr3, the overlay word/valid
    planes) crosses into the kernel through a free bitcast at the wrapper
    seam
  * the grid iterates lanes; per-lane work is scalar (dynamic-index loads
    from the uop table / image / overlay implement the gather emulation
    the XLA path pays per-step dispatches for), with the K-step fori_loop
    carrying the register file as a value and the overlay living in
    in+out refs (copy-in at kernel start, RMW in place) so loads observe
    earlier in-kernel stores
  * tier-1 runs the kernel under `interpret=True` on the CPU platform —
    the Mosaic lowering is exercised only when a real TPU backend is
    attached (`interpret=None` auto-detects)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from wtf_tpu.core.results import StatusCode
from wtf_tpu.cpu import uops as U
from wtf_tpu.interp import limbs as L
from wtf_tpu.interp import step as S
from wtf_tpu.interp.machine import (
    CTR_DECODE_MISS, CTR_FUSED, CTR_INSTR, CTR_PARK_MEM, CTR_PARK_SUBSET,
    Machine, N_CTRS,
)
from wtf_tpu.interp.uoptable import (
    F_A32, F_BASE_REG, F_COND, F_DST_KIND, F_DST_REG, F_IDX_REG, F_LENGTH,
    F_OPC, F_OPSIZE, F_SCALE, F_SEG, F_SEXT, F_SRCSIZE, F_SRC_KIND,
    F_SRC_REG, F_SUB, M_BP, M_PFN0, M_PFN1, PROBES, UopTable,
)
from wtf_tpu.mem.physmem import (
    IMAGE_IN_AXES, MemImage, PAGE_WORDS, lane_image,
)

_RUNNING = int(StatusCode.RUNNING)
_NEED_DECODE = int(StatusCode.NEED_DECODE)
_NEEDS_XLA = int(StatusCode.NEEDS_XLA)
_TIMEDOUT = int(StatusCode.TIMEDOUT)

# paging constants (mem/paging.py, as u32 limb pairs at trace time)
_PHYS_MASK = 0x000F_FFFF_FFFF_F000
_PHYS_MASK_1G = 0x000F_FFFF_C000_0000
_PHYS_MASK_2M = 0x000F_FFFF_FFE0_0000
_PFN_OOB = 0x7FFFFFFF  # mem/overlay.py sentinel: never matches, slot 0

# The opclass set this kernel CLAIMS to execute in-kernel.  Since the
# page walk and overlay live in-kernel, the claim is a PURE opclass
# test — memory operands are fully fused, and a lane leaves the kernel
# only on DYNAMIC outcomes (failing/unwritable walk, overlay
# exhaustion, SMC-risk code, an armed breakpoint), not on static
# operand shapes.  The static analyzer (wtf_tpu/analysis/parity.py)
# AST-checks this claim against the actual `hot_class` predicate AND
# against step.py's dispatch / `unsupported` expressions, so the two
# engines cannot drift silently.
FUSED_OPCLASSES = frozenset({
    "NOP", "FENCE", "MOV", "LEA", "ALU", "UNARY", "SETCC", "CMOVCC",
    "JCC", "JMP", "SHIFT", "MUL", "PUSH", "POP", "CALL", "RET",
})

# memoized jitted entry points, keyed (k_steps, interpret) /
# (n_steps, donate); jit itself re-specializes per array shapes
_FUSED_CACHE: dict = {}
_RESUME_CACHE: dict = {}


def _u32(x) -> jnp.ndarray:
    return jnp.uint32(x)


def _pair(v: int):
    return (_u32(v & 0xFFFFFFFF), _u32((v >> 32) & 0xFFFFFFFF))


def fused_available(interpret: bool = True) -> bool:
    """Whether this jax build can run the fused kernel (tier-1's
    skip-with-reason guard: some jax builds ship without pallas interpret
    support).  Cached after the first probe."""
    global _FUSED_OK
    try:
        return _FUSED_OK
    except NameError:
        pass
    try:
        from jax.experimental import pallas as pl

        def probe(i_ref, o_ref):
            o_ref[0] = i_ref[0] + jnp.uint32(1)

        out = pl.pallas_call(
            probe,
            out_shape=jax.ShapeDtypeStruct((1,), jnp.uint32),
            interpret=interpret,
        )(jnp.zeros(1, jnp.uint32))
        _FUSED_OK = int(out[0]) == 1
    except Exception:  # noqa: BLE001 - any failure means "not available"
        _FUSED_OK = False
    return _FUSED_OK


def _build_kernel(k_steps: int, n_fields: int, hash_size: int,
                  nframes: int, ebits: int, capacity: int):
    """The kernel body, specialized on the static table geometry.
    `capacity` is the per-lane overlay slot count."""
    from jax.experimental import pallas as pl

    hmask = hash_size - 1
    vwords = PAGE_WORDS // 4        # u32-packed valid bytes per page

    def kernel(hash_ref, trip_ref, tmeta_ref, tmu_ref, pages_ref, ftab_ref,
               limit_ref, tenant_ref, cr3_ref, fs_ref, gs_ref,
               gpr_in, rip_in, rf_in, st_in, ic_in, bp_in, ctr_in, cov_in,
               edge_in, ovpfn_in, ovdata_in, ovvalid_in, ovcount_in,
               gpr_out, rip_out, rf_out, st_out, ic_out, bp_out, ctr_out,
               cov_out, edge_out, ovpfn_out, ovdata_out, ovvalid_out,
               ovcount_out):
        # state the loop RMWs lives in the out refs: copy through once,
        # then every read below observes earlier in-kernel stores
        cov_out[...] = cov_in[...]
        edge_out[...] = edge_in[...]
        ovpfn_out[...] = ovpfn_in[...]
        ovdata_out[...] = ovdata_in[...]
        ovvalid_out[...] = ovvalid_in[...]
        ovcount_out[...] = ovcount_in[...]

        limit_l = (limit_ref[0], limit_ref[1])
        limit_on = (limit_ref[0] | limit_ref[1]) != _u32(0)
        z = _u32(0)
        one = _u32(1)
        zero2 = (z, z)
        cr3_l = (cr3_ref[0, 0], cr3_ref[0, 1])
        fs_l = (fs_ref[0, 0], fs_ref[0, 1])
        gs_l = (gs_ref[0, 0], gs_ref[0, 1])
        PM = _pair(_PHYS_MASK)
        PM1G = _pair(_PHYS_MASK_1G)
        PM2M = _pair(_PHYS_MASK_2M)
        iota_slots = lax.iota(jnp.int32, capacity)
        # the lane's base-image id (wtf_tpu/tenancy): selects the frame-
        # table row and tags the decode-probe key, exactly like step_lane
        tenant = tenant_ref[0]
        ttag = tenant.astype(jnp.uint32) << 16      # bit 48 = hi limb bit 16

        def probe(rip_l):
            """uop_lookup's open-addressed probe, one slot at a time (the
            scalar gather emulation of the XLA path's 8-slot row gather;
            first live match wins, same result by insertion uniqueness).
            Probes the tenant-tagged key, like step_lane — the key limbs
            ride in the hash row, so no dependent rip_l chase."""
            key_l = (rip_l[0], rip_l[1] ^ ttag)
            h_lo, _ = L.splitmix64(key_l)

            def body(k, found):
                slot = ((h_lo + _u32(0) + k.astype(jnp.uint32))
                        & _u32(hmask)).astype(jnp.int32)
                e = hash_ref[slot, 0]
                ok = ((e >= 0)
                      & (hash_ref[slot, 1].astype(jnp.uint32) == key_l[0])
                      & (hash_ref[slot, 2].astype(jnp.uint32) == key_l[1]))
                return jnp.where((found < 0) & ok, e, found)

            return lax.fori_loop(0, PROBES, body, jnp.int32(-1))

        def slot_of(pfn):
            """frame_slot: pfn -> image page slot (0 = absent/zero page),
            through the lane's tenant row of the stacked frame table."""
            in_range = (pfn >= 0) & (pfn < nframes)
            safe = jnp.clip(pfn, 0, nframes - 1)
            return jnp.where(in_range, ftab_ref[tenant, safe], 0)

        def ov_lookup(pfn):
            """overlay.lookup: first slot holding `pfn` (min-rank, not
            argmax — argmax's reduce would run an s64 iota under x64)."""
            eq = ovpfn_out[0] == pfn
            rank = jnp.where(eq, iota_slots, jnp.int32(capacity))
            first = jnp.min(rank)
            return jnp.minimum(first, capacity - 1), first < capacity

        def read_word(pfn, widx):
            """One overlay-aware aligned u64 word as a u32 pair — the
            in-kernel form of overlay.read_words_vec (delta rows: a word
            routes to the overlay only when its valid byte is set)."""
            row, hit = ov_lookup(pfn)
            vword = ovvalid_out[0, row, widx >> 2]
            sh8 = ((widx & 3) * 8).astype(jnp.uint32)
            use_ov = hit & (((vword >> sh8) & _u32(0xFF)) != z)
            slot = slot_of(pfn)
            lo = jnp.where(use_ov, ovdata_out[0, row, 2 * widx],
                           pages_ref[slot, 2 * widx])
            hi = jnp.where(use_ov, ovdata_out[0, row, 2 * widx + 1],
                           pages_ref[slot, 2 * widx + 1])
            return lo, hi

        def pfn_of(addr_l):
            """split_gpa: physical address -> int32 pfn with the OOB
            sentinel (never matches an overlay row; slot 0 image page)."""
            p = L.shr64_const(addr_l, 12)
            in_range = (p[1] == z) & (p[0] < _u32(nframes))
            return jnp.where(in_range, p[0],
                             _u32(_PFN_OOB)).astype(jnp.int32)

        def read_phys_u64(addr_l):
            widx = ((addr_l[0] & _u32(0xFFF)) >> 3).astype(jnp.int32)
            return read_word(pfn_of(addr_l), widx)

        def walk(gva_l):
            """translate_vec_l's 4-level long-mode walk, scalar per lane
            on u32 limbs: PTE reads go through the lane's overlay (guest-
            modified tables honored), 1GiB/2MiB large pages supported,
            A/D bits not set (the documented divergence).  Returns
            (gpa pair, ok, writable)."""
            top = L.shr64_const(gva_l, 47)
            ok = (((top[0] == z) & (top[1] == z))
                  | ((top[0] == _u32(0x1FFFF)) & (top[1] == z)))
            writable = jnp.bool_(True)
            done = jnp.bool_(False)
            gpa = zero2
            table = L.and64(cr3_l, PM)
            for shift, large_mask, page_bits in (
                    (39, None, 0), (30, PM1G, 30), (21, PM2M, 21),
                    (12, None, 0)):
                idx9 = L.shr64_const(gva_l, shift)[0] & _u32(0x1FF)
                entry = read_phys_u64(L.add64(table, (idx9 << 3, z)))
                present = (entry[0] & one) != z
                ok = ok & (done | present)
                writable = writable & (done | ((entry[0] & _u32(2)) != z))
                if large_mask is not None:
                    is_large = present & ((entry[0] & _u32(0x80)) != z) \
                        & ~done
                    pmask = _pair((1 << page_bits) - 1)
                    large_gpa = L.or64(L.and64(entry, large_mask),
                                       L.and64(gva_l, pmask))
                    gpa = L.where64(is_large, large_gpa, gpa)
                    done = done | is_large
                if shift == 12:
                    leaf = L.or64(L.and64(entry, PM),
                                  (gva_l[0] & _u32(0xFFF), z))
                    gpa = L.where64(done, gpa, leaf)
                table = L.and64(entry, PM)
            return gpa, ok, writable

        def load_win16_pfn(pfn_a, pfn_b, off):
            """16 bytes starting at page offset `off` (u32) of pfn_a,
            straddling into pfn_b — 3 aligned words + shifts, exactly
            overlay.load_window3/extract_pair but overlay-aware per
            word."""
            w0 = (off >> 3).astype(jnp.int32)
            words = []
            for j in range(3):
                on_first = (w0 + j) < PAGE_WORDS
                widx = jnp.where(on_first, w0 + j, w0 + j - PAGE_WORDS)
                pfn = jnp.where(on_first, pfn_a, pfn_b)
                words.append(read_word(pfn, widx))
            sh = (off & _u32(7)) * _u32(8)
            inv = _u32(64) - sh
            lo = L.or64(L.shr64(words[0], sh), L.shl64(words[1], inv))
            hi = L.or64(L.shr64(words[1], sh), L.shl64(words[2], inv))
            return lo, hi

        def load_win16(gpa0_l, gpa1_l):
            return load_win16_pfn(pfn_of(gpa0_l), pfn_of(gpa1_l),
                                  gpa0_l[0] & _u32(0xFFF))

        def step_body(_, carry):
            (gl, rip_l, rf_lo, status, ic_l, bpskip, d_instr, d_miss,
             d_ps, d_pm) = carry
            run = status == jnp.int32(_RUNNING)

            # -- 1. decode-cache probe (identical to step.uop_lookup) ----
            idx = probe(rip_l)
            miss = run & (idx < 0)
            idxc = jnp.maximum(idx, 0)
            f = tmeta_ref[idxc]                     # [NF+3] i32 row
            mu = tmu_ref[idxc]                      # [8] u32 row
            opc = f[F_OPC]
            sub = f[F_SUB]
            cond = f[F_COND]
            length = f[F_LENGTH]
            opsize = f[F_OPSIZE]
            srcsize0 = f[F_SRCSIZE]
            sext_f = f[F_SEXT]
            dk, dr = f[F_DST_KIND], f[F_DST_REG]
            sk, sr = f[F_SRC_KIND], f[F_SRC_REG]
            disp_l = (mu[0], mu[1])
            imm_l = (mu[2], mu[3])
            raw_lo_l = (mu[4], mu[5])
            raw_hi_l = (mu[6], mu[7])

            # -- 2. breakpoint gate (honoring bp_skip, like step_lane) ---
            at_bp = run & ~miss & (f[M_BP] == 1) & (bpskip == 0)

            # -- 3. hot-subset eligibility: the claimed opclasses
            # (FUSED_OPCLASSES); memory operands are fair game now that
            # the walk + overlay live in-kernel.  Everything else parks.
            is_mov = opc == U.OPC_MOV
            is_lea = opc == U.OPC_LEA
            is_alu = opc == U.OPC_ALU
            is_shift = opc == U.OPC_SHIFT
            is_mul = opc == U.OPC_MUL
            is_unary = opc == U.OPC_UNARY
            is_setcc = opc == U.OPC_SETCC
            is_cmov = opc == U.OPC_CMOVCC
            is_jcc = opc == U.OPC_JCC
            is_jmp = opc == U.OPC_JMP
            is_push = opc == U.OPC_PUSH
            is_pop = opc == U.OPC_POP
            is_call = opc == U.OPC_CALL
            is_ret = opc == U.OPC_RET
            hot_class = (
                (opc == U.OPC_NOP) | (opc == U.OPC_FENCE)
                | is_mov | is_lea | is_alu | is_shift | is_mul
                | is_unary | is_setcc | is_cmov | is_jcc | is_jmp
                | is_push | is_pop | is_call | is_ret)

            # -- 4. addresses (ported paths of step_lane, u32 limbs) -----
            next_rip_l = L.add64_u32(rip_l, length.astype(jnp.uint32))
            base_val_l = L.where64(f[F_BASE_REG] == U.REG_RIP, next_rip_l,
                                   S._read64_l(gl, f[F_BASE_REG]))
            idx_val_l = S._scale_idx_l(S._read64_l(gl, f[F_IDX_REG]),
                                       f[F_SCALE])
            seg_l = L.select64(
                [f[F_SEG] == U.SEG_FS, f[F_SEG] == U.SEG_GS],
                [fs_l, gs_l], zero2)
            ea_l = S.ea_limb(disp_l, base_val_l, idx_val_l, seg_l,
                             f[F_A32])
            rsp_l = (gl[4, 0], gl[4, 1])
            srcsize = jnp.where(srcsize0 == 0, opsize, srcsize0)
            push_size = jnp.where(is_call, jnp.int32(8), opsize)

            l1_need = run & ~miss & hot_class & (
                (sk == U.K_MEM) | is_pop | is_ret)
            l1_addr = L.where64(is_pop | is_ret, rsp_l, ea_l)
            l1_size = jnp.where(is_ret, jnp.int32(8),
                                jnp.where(is_pop, opsize, srcsize))
            # store-only destinations (MOV/SETCC/POP) never read [mem],
            # so only the read-modify classes issue the l2 load — their
            # fault is then a WRITE fault, matching step_lane
            l2_need = run & ~miss & hot_class & (dk == U.K_MEM) \
                & (is_alu | is_shift | is_unary)
            st_addr = L.where64(is_push | is_call,
                                L.sub64(rsp_l,
                                        (push_size.astype(jnp.uint32), z)),
                                ea_l)
            # stores and pushes span the same byte count (step.py's
            # st_size only diverges for x87 stores, which are not fused)
            st_size = push_size

            def span_last(addr_l, size):
                return L.add64_u32(addr_l, (size - 1).astype(jnp.uint32))

            # -- 4a. six in-kernel page walks (first/last byte of the
            # l1 load, the l2 read-modify operand, and the store) --------
            l1g0, l1ok0, _w0 = walk(l1_addr)
            l1g1, l1ok1, _w1 = walk(span_last(l1_addr, l1_size))
            l2g0, l2ok0, _w2 = walk(ea_l)
            l2g1, l2ok1, _w3 = walk(span_last(ea_l, opsize))
            stg0, stok0, stw0 = walk(st_addr)
            stg1, stok1, stw1 = walk(span_last(st_addr, st_size))

            # -- 4b. SMC check through the overlay (live code bytes vs
            # decode-time raw — exactly step_lane's verdict; in-kernel
            # stores that dirty a code page are caught the same way) -----
            code_off = rip_l[0] & _u32(0xFFF)
            code_crosses = (code_off + _u32(16)) > _u32(4096)
            pfn0c, pfn1c = f[M_PFN0], f[M_PFN1]
            code_lo, code_hi = load_win16_pfn(
                pfn0c, jnp.where(code_crosses, pfn1c, pfn0c), code_off)
            lm_lo = L.size_mask(jnp.minimum(length, 8))
            lm_hi = L.size_mask(jnp.maximum(length - 8, 0))
            smc_risk = (
                ~L.is_zero64(L.and64(L.xor64(code_lo, raw_lo_l), lm_lo))
                | ~L.is_zero64(L.and64(L.xor64(code_hi, raw_hi_l), lm_hi)))

            # -- 4c. operand loads through the overlay -------------------
            l1_pair = load_win16(l1g0, l1g1)[0]     # low 8 bytes
            l2_pair = load_win16(l2g0, l2g1)[0]

            # -- 5. execute (ported paths of step_lane, scalar per lane) -
            src_raw_l = L.where64(
                sk == U.K_REG, S._read_reg_l(gl, sr, srcsize),
                L.where64(sk == U.K_MEM, L.zext(l1_pair, srcsize), zero2))
            src_ext_l = L.where64(
                sext_f == 1, L.zext(L.sext(src_raw_l, srcsize), opsize),
                L.zext(src_raw_l, opsize))
            src_val_l = L.where64(sk == U.K_IMM, L.zext(imm_l, opsize),
                                  src_ext_l)
            dst_val_l = L.where64(
                dk == U.K_REG, S._read_reg_l(gl, dr, opsize),
                L.where64(dk == U.K_MEM, L.zext(l2_pair, opsize), zero2))
            cf_in = (rf_lo & _u32(L.CF)) != z
            alu_r, alu_rf_lo, alu_writes = S.alu_limb(
                sub, dst_val_l, src_val_l, cf_in, opsize, rf_lo)
            un_r, un_rf_lo = S.unary_limb(sub, dst_val_l, cf_in, opsize,
                                          rf_lo)
            filler_l = S._read_reg_l(gl, sr, opsize)
            sh_r, sh_rf_lo, sh_writes = S.shift_limb(
                sub, sext_f, dst_val_l, filler_l, gl[1, 0], src_val_l[0],
                imm_l[0], cf_in, opsize, rf_lo)
            is_mul2 = sub == U.MUL_2OP
            mul_r1, mul_r2, mul_rf_lo = S.mul_limb(
                sub, sext_f, dst_val_l, src_val_l,
                S._read_reg_l(gl, jnp.int32(0), opsize), imm_l, opsize,
                rf_lo)
            rcx_l = (gl[1, 0], gl[1, 1])
            cc = L.eval_cond(rf_lo, rcx_l, cond)
            cc01 = (jnp.where(cc, one, z), z)
            jcc_t = L.add64(next_rip_l, imm_l)
            jmp_t = L.where64(sk == U.K_IMM, jcc_t, src_val_l)
            pop_val = L.zext(l1_pair, opsize)

            # -- 5a. store plan + park decision (BEFORE any mutation) ----
            mem_writes = (is_mov | (is_alu & alu_writes)
                          | (is_shift & sh_writes) | is_unary | is_setcc
                          | is_pop)
            st_need = run & ~miss & hot_class & (
                ((dk == U.K_MEM) & mem_writes) | is_push | is_call)
            s_off = stg0[0] & _u32(0xFFF)
            st_size_u = st_size.astype(jnp.uint32)
            crosses = (s_off + st_size_u) > _u32(4096)
            s_pfn0 = pfn_of(stg0)
            s_pfn1 = pfn_of(stg1)
            row0, hit0 = ov_lookup(s_pfn0)
            row1, hit1 = ov_lookup(s_pfn1)
            # aliased mappings: a virtual page crossing can land both
            # halves on ONE physical frame — the second half must reuse
            # the first's (possibly just-claimed) row, never a duplicate
            # (overlay lookup takes the first match; step.py's second
            # ensure_page hits the row the first one claimed)
            st_alias = s_pfn1 == s_pfn0
            oob = (s_pfn0 == _PFN_OOB) | (crosses & (s_pfn1 == _PFN_OOB))
            need_new = ((~hit0).astype(jnp.int32)
                        + (crosses & ~hit1 & ~st_alias).astype(jnp.int32))
            cnt_now = ovcount_out[0]
            can_alloc = (cnt_now + need_new) <= capacity

            f_l1 = l1_need & ~(l1ok0 & l1ok1)
            f_l2 = l2_need & ~(l2ok0 & l2ok1)
            f_st = st_need & ~(stok0 & stok1 & stw0 & stw1)
            mem_park = f_l1 | f_l2 | f_st | (st_need & (oob | ~can_alloc))

            park = run & ~miss & (at_bp | ~hot_class | smc_risk | mem_park)
            commit = run & ~miss & ~park
            # park-reason attribution: a MEM park is a lane the subset
            # would have run (hot class, clean code, no bp) that the
            # memory path diverted — the occupancy-loss split telemetry
            # and bench.py --fused-compare report
            park_mem_evt = (run & ~miss & ~at_bp & hot_class & ~smc_risk
                            & mem_park)
            park_sub_evt = park & ~park_mem_evt
            do_store = commit & st_need

            # -- 5b. in-kernel store: overlay slot claim (delta rows:
            # claiming clears word validity, never copies the base page)
            # + the <=8-byte 3-word masked read-modify-write of
            # overlay.store_window3 ---------------------------------------
            @pl.when(do_store)
            def _store():
                cnt0 = ovcount_out[0]
                alloc0 = ~hit0
                rowa = jnp.where(alloc0, cnt0, row0)

                @pl.when(alloc0)
                def _():
                    ovpfn_out[0, rowa] = s_pfn0
                    ovvalid_out[0, rowa, :] = jnp.zeros((vwords,),
                                                        jnp.uint32)

                cnt1 = cnt0 + alloc0.astype(jnp.int32)
                alloc1 = crosses & ~hit1 & ~st_alias
                rowb = jnp.where(
                    alloc1, cnt1,
                    jnp.where(crosses & hit1, row1, rowa))

                @pl.when(alloc1)
                def _():
                    ovpfn_out[0, rowb] = s_pfn1
                    ovvalid_out[0, rowb, :] = jnp.zeros((vwords,),
                                                        jnp.uint32)

                ovcount_out[0] = cnt1 + alloc1.astype(jnp.int32)

                st_val = L.select64(
                    [is_mov | is_push, is_alu, is_shift, is_unary,
                     is_setcc, is_pop, is_call],
                    [src_val_l, alu_r, sh_r, un_r, cc01, pop_val,
                     next_rip_l], zero2)
                sh = (s_off & _u32(7)) * _u32(8)
                end_bit = sh + st_size_u * _u32(8)
                v0 = L.shl64(st_val, sh)
                v1 = L.shr64(st_val, _u32(64) - sh)
                w0i = (s_off >> 3).astype(jnp.int32)
                for j, vj in enumerate((v0, v1, zero2)):
                    lo_bit = _u32(64 * j)
                    start_in = jnp.maximum(sh, lo_bit)
                    end_in = jnp.minimum(end_bit, lo_bit + _u32(64))
                    has = end_in > start_in
                    n_bits = jnp.where(has, end_in - start_in, z)
                    off_in = jnp.where(has, start_in - lo_bit, z)
                    # n_bits == 64 wraps (1 << 64 -> 0) to all-ones
                    mask = L.shl64(
                        L.sub64(L.shl64((one, z), n_bits), (one, z)),
                        off_in)
                    on_first = (w0i + j) < PAGE_WORDS
                    widx = jnp.where(on_first, w0i + j,
                                     w0i + j - PAGE_WORDS)
                    row = jnp.where(on_first, rowa, rowb)
                    pfn_j = jnp.where(on_first, s_pfn0, s_pfn1)
                    vword = ovvalid_out[0, row, widx >> 2]
                    sh8 = ((widx & 3) * 8).astype(jnp.uint32)
                    was_valid = ((vword >> sh8) & _u32(0xFF)) != z
                    slot = slot_of(pfn_j)
                    old_lo = jnp.where(was_valid,
                                       ovdata_out[0, row, 2 * widx],
                                       pages_ref[slot, 2 * widx])
                    old_hi = jnp.where(was_valid,
                                       ovdata_out[0, row, 2 * widx + 1],
                                       pages_ref[slot, 2 * widx + 1])
                    touched = (mask[0] | mask[1]) != z
                    # an untouched word writes `old` back (a no-op by
                    # value), so the block needs no nested predication
                    ovdata_out[0, row, 2 * widx] = \
                        (old_lo & ~mask[0]) | (vj[0] & mask[0])
                    ovdata_out[0, row, 2 * widx + 1] = \
                        (old_hi & ~mask[1]) | (vj[1] & mask[1])
                    ovvalid_out[0, row, widx >> 2] = jnp.where(
                        touched, vword | (one << sh8), vword)

            # -- 5c. register writes (step_lane order: rsp, aux, primary)
            w3_cond = is_push | is_call | is_pop | is_ret
            w3_val = L.select64(
                [is_push | is_call, is_pop],
                [L.sub64(rsp_l, (push_size.astype(jnp.uint32), z)),
                 L.add64_u32(rsp_l, opsize.astype(jnp.uint32))],
                L.add64(L.add64_u32(rsp_l, _u32(8)), imm_l))
            gl1 = S._gpr_write_l(gl, commit & w3_cond, jnp.int32(4),
                                 w3_val, jnp.int32(8))
            w2_cond = is_mul & ~is_mul2 & (opsize > 1)
            gl2 = S._gpr_write_l(gl1, commit & w2_cond, jnp.int32(2),
                                 mul_r2, opsize)
            w1_cond = L.sel(
                [is_mov, is_lea, is_alu, is_shift, is_unary, is_mul,
                 is_pop, is_setcc, is_cmov],
                [dk == U.K_REG, jnp.bool_(True),
                 alu_writes & (dk == U.K_REG),
                 sh_writes & (dk == U.K_REG), dk == U.K_REG,
                 jnp.bool_(True), dk == U.K_REG, dk == U.K_REG,
                 jnp.bool_(True)],
                jnp.bool_(False))
            w1_idx = jnp.where(is_mul,
                               jnp.where(is_mul2, dr, jnp.int32(0)), dr)
            w1_val = L.select64(
                [is_mov, is_lea, is_alu, is_shift, is_unary, is_mul,
                 is_pop, is_setcc, is_cmov],
                [src_val_l, ea_l, alu_r, sh_r, un_r, mul_r1, pop_val,
                 cc01, L.where64(cc, src_val_l, dst_val_l)], zero2)
            w1_size = jnp.where(
                is_mul,
                jnp.where(is_mul2, opsize,
                          jnp.where(opsize == 1, jnp.int32(2), opsize)),
                opsize)
            gl_new = S._gpr_write_l(gl2, commit & w1_cond, w1_idx, w1_val,
                                    w1_size)

            # -- 5d. rflags / rip ----------------------------------------
            hot_rf = is_alu | is_unary | is_shift | is_mul
            rf_exec_lo = L.sel([is_alu, is_unary, is_shift],
                               [alu_rf_lo, un_rf_lo, sh_rf_lo], mul_rf_lo)
            rf_cand = jnp.where(hot_rf, rf_exec_lo, rf_lo)
            new_rf_lo = jnp.where(commit, rf_cand | _u32(0x2), rf_lo)
            rip_exec = L.select64(
                [is_jmp | is_call, is_jcc, is_ret],
                [jmp_t, L.where64(cc, jcc_t, next_rip_l), l1_pair],
                next_rip_l)
            new_rip = L.where64(commit, rip_exec, rip_l)

            # -- 6. bookkeeping: icount/limit, counters, coverage, edges -
            new_ic = L.where64(commit, L.add64_u32(ic_l, _u32(1)), ic_l)
            timed = commit & limit_on & ~L.ltu64(new_ic, limit_l)
            new_bpskip = jnp.where(commit, jnp.int32(0), bpskip)
            new_status = jnp.where(
                miss, jnp.int32(_NEED_DECODE),
                jnp.where(park, jnp.int32(_NEEDS_XLA),
                          jnp.where(timed, jnp.int32(_TIMEDOUT), status)))

            wi = idxc >> 5
            cov_bit = jnp.where(
                commit, one << (idxc & 31).astype(jnp.uint32), z)
            cov_out[0, wi] = cov_out[0, wi] | cov_bit
            eh_lo = L.mix64(rip_l)[0] ^ rip_exec[0]
            ei = (eh_lo & _u32(ebits - 1)).astype(jnp.int32)
            is_branch = is_jmp | is_jcc | is_call | is_ret
            edge_bit = jnp.where(
                commit & is_branch,
                one << (ei & 31).astype(jnp.uint32), z)
            edge_out[0, ei >> 5] = edge_out[0, ei >> 5] | edge_bit

            inc = jnp.where(commit, one, z)
            return (gl_new, new_rip, new_rf_lo, new_status, new_ic,
                    new_bpskip, d_instr + inc,
                    d_miss + jnp.where(miss, one, z),
                    d_ps + jnp.where(park_sub_evt, one, z),
                    d_pm + jnp.where(park_mem_evt, one, z))

        init = (gpr_in[0], (rip_in[0, 0], rip_in[0, 1]), rf_in[0, 0],
                st_in[0], (ic_in[0, 0], ic_in[0, 1]), bp_in[0],
                _u32(0), _u32(0), _u32(0), _u32(0))
        (gl, rip_l, rf_lo, status, ic_l, bpskip, d_instr, d_miss,
         d_ps, d_pm) = lax.fori_loop(0, k_steps, step_body, init)

        gpr_out[0] = gl
        rip_out[0, 0], rip_out[0, 1] = rip_l[0], rip_l[1]
        rf_out[0, 0] = rf_lo
        rf_out[0, 1] = rf_in[0, 1]      # hot classes never touch bits 32+
        st_out[0] = status
        ic_out[0, 0], ic_out[0, 1] = ic_l[0], ic_l[1]
        bp_out[0] = bpskip
        delta = jnp.zeros(N_CTRS, jnp.uint32)
        delta = delta.at[CTR_INSTR].set(d_instr)
        delta = delta.at[CTR_DECODE_MISS].set(d_miss)
        # every kernel-retired instruction is by definition a fused one
        delta = delta.at[CTR_FUSED].set(d_instr)
        delta = delta.at[CTR_PARK_SUBSET].set(d_ps)
        delta = delta.at[CTR_PARK_MEM].set(d_pm)
        ctr_out[0] = ctr_in[0] + delta

    return kernel


def fused_call_impl(tab: UopTable, image: MemImage, machine: Machine,
                    limit, *, k_steps: int, interpret: bool):
    """One fused-kernel dispatch, un-jitted: the bitcast pack seam, the
    pallas_call, and the unpack seam back to a Machine.  Shared by the
    jitted standalone executor (make_run_fused) and the fused megachunk
    window body (fuzz/megachunk.py), which inlines this inside its own
    while_loop so the kernel IS the window's step engine.

    The kernel's machine-state operands (gpr..overlay, positions 11-23)
    are aliased 1:1 to its 13 outputs via `input_output_aliases`, so the
    `[lanes, slots, words]` overlay slab — the largest HBM-resident
    operand — updates in place instead of copying through the kernel per
    dispatch.  XLA still inserts a defensive copy when an operand is a
    non-donated entry parameter; pairing this with donation on the
    enclosing executable (the window's donate_argnums) removes that last
    copy too."""
    from jax.experimental import pallas as pl

    n_lanes = machine.status.shape[0]
    image = lane_image(image, n_lanes)
    n_fields = tab.meta_i32.shape[1]
    hash_size = tab.hash_tab.shape[0]
    capacity = tab.rip_l.shape[0]
    n_tenants, nframes = image.frame_table.shape
    slots = machine.overlay.pfn.shape[1]
    cov_w = machine.cov.shape[1]
    edge_w = machine.edge.shape[1]
    ebits = edge_w * 32
    n_slots_img = image.pages.shape[0]
    vwords = PAGE_WORDS // 4

    # u64 leaves cross the kernel boundary as free u32 bitcasts; the
    # overlay's u8 valid plane packs 4 bytes per u32 the same way
    tmu32 = lax.bitcast_convert_type(
        tab.meta_u64, jnp.uint32).reshape(capacity, 8)
    pages32 = lax.bitcast_convert_type(
        image.pages, jnp.uint32).reshape(n_slots_img, 2 * PAGE_WORDS)
    ic32 = lax.bitcast_convert_type(machine.icount, jnp.uint32)
    cr32 = lax.bitcast_convert_type(machine.cr3, jnp.uint32)
    limit32 = lax.bitcast_convert_type(
        jnp.asarray(limit, jnp.uint64).reshape(1),
        jnp.uint32).reshape(2)
    ov = machine.overlay
    ovdata32 = lax.bitcast_convert_type(
        ov.data, jnp.uint32).reshape(n_lanes, slots, 2 * PAGE_WORDS)
    ovvalid32 = lax.bitcast_convert_type(
        ov.valid.reshape(n_lanes, slots, vwords, 4), jnp.uint32)

    kernel = _build_kernel(k_steps, n_fields, hash_size, nframes,
                           ebits, slots)

    def full(shape):
        nd = len(shape)
        return pl.BlockSpec(shape, lambda i, _n=nd: (0,) * _n)

    def lane(shape_tail):
        nd = 1 + len(shape_tail)
        return pl.BlockSpec((1,) + shape_tail,
                            lambda i, _n=nd: (i,) + (0,) * (_n - 1))

    out = pl.pallas_call(
        kernel,
        grid=(n_lanes,),
        in_specs=[
            full((hash_size, 3)),
            full((capacity, 2)),
            full((capacity, n_fields)),
            full((capacity, 8)),
            full((n_slots_img, 2 * PAGE_WORDS)),
            full((n_tenants, nframes)),
            full((2,)),
            lane(()),
            lane((2,)),
            lane((2,)),
            lane((2,)),
            lane((16, 2)),
            lane((2,)),
            lane((2,)),
            lane(()),
            lane((2,)),
            lane(()),
            lane((N_CTRS,)),
            lane((cov_w,)),
            lane((edge_w,)),
            lane((slots,)),
            lane((slots, 2 * PAGE_WORDS)),
            lane((slots, vwords)),
            lane(()),
        ],
        out_specs=[
            lane((16, 2)),
            lane((2,)),
            lane((2,)),
            lane(()),
            lane((2,)),
            lane(()),
            lane((N_CTRS,)),
            lane((cov_w,)),
            lane((edge_w,)),
            lane((slots,)),
            lane((slots, 2 * PAGE_WORDS)),
            lane((slots, vwords)),
            lane(()),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_lanes, 16, 2), jnp.uint32),
            jax.ShapeDtypeStruct((n_lanes, 2), jnp.uint32),
            jax.ShapeDtypeStruct((n_lanes, 2), jnp.uint32),
            jax.ShapeDtypeStruct((n_lanes,), jnp.int32),
            jax.ShapeDtypeStruct((n_lanes, 2), jnp.uint32),
            jax.ShapeDtypeStruct((n_lanes,), jnp.int32),
            jax.ShapeDtypeStruct((n_lanes, N_CTRS), jnp.uint32),
            jax.ShapeDtypeStruct((n_lanes, cov_w), jnp.uint32),
            jax.ShapeDtypeStruct((n_lanes, edge_w), jnp.uint32),
            jax.ShapeDtypeStruct((n_lanes, slots), jnp.int32),
            jax.ShapeDtypeStruct((n_lanes, slots, 2 * PAGE_WORDS),
                                 jnp.uint32),
            jax.ShapeDtypeStruct((n_lanes, slots, vwords),
                                 jnp.uint32),
            jax.ShapeDtypeStruct((n_lanes,), jnp.int32),
        ],
        interpret=interpret,
        # machine-state operands 11..23 alias kernel outputs 0..12 — the
        # overlay slab and machine planes update in place instead of
        # copying through the kernel every dispatch
        input_output_aliases={11 + i: i for i in range(13)},
    )(tab.hash_tab, tab.rip_l, tab.meta_i32, tmu32, pages32,
      image.frame_table, limit32, image.tenant, cr32,
      machine.fs_base_l, machine.gs_base_l,
      machine.gpr_l, machine.rip_l, machine.rflags_l, machine.status,
      ic32, machine.bp_skip, machine.ctr, machine.cov, machine.edge,
      ov.pfn, ovdata32, ovvalid32, ov.count)
    (gpr_l, rip_l, rf_l, status, ic_out, bp_skip, ctr, cov, edge,
     ovpfn, ovdata, ovvalid, ovcount) = out
    overlay = ov._replace(
        pfn=ovpfn,
        data=lax.bitcast_convert_type(
            ovdata.reshape(n_lanes, slots, PAGE_WORDS, 2),
            jnp.uint64),
        valid=lax.bitcast_convert_type(
            ovvalid, jnp.uint8).reshape(n_lanes, slots, PAGE_WORDS),
        count=ovcount)
    return machine._replace(
        gpr_l=gpr_l, rip_l=rip_l, rflags_l=rf_l, status=status,
        icount=lax.bitcast_convert_type(ic_out, jnp.uint64),
        bp_skip=bp_skip, ctr=ctr, cov=cov, edge=edge, overlay=overlay)


def make_run_fused(k_steps: int, interpret: Optional[bool] = None):
    """Build (or fetch) the jitted fused-step executor: up to `k_steps`
    hot-subset instructions per lane per dispatch.  Thin jit wrapper over
    fused_call_impl (the megachunk window inlines the impl directly).

    `interpret=None` auto-selects: real Mosaic lowering on a TPU backend,
    the Pallas interpreter elsewhere (the tier-1/CPU validation mode)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    key = (k_steps, interpret)
    cached = _FUSED_CACHE.get(key)
    if cached is not None:
        return cached

    @jax.jit
    def run_fused(tab: UopTable, image: MemImage, machine: Machine, limit):
        return fused_call_impl(tab, image, machine, limit,
                               k_steps=k_steps, interpret=interpret)

    _FUSED_CACHE[key] = run_fused
    return run_fused


def fused_resume_impl(tab: UopTable, image: MemImage, machine: Machine,
                      limit, *, n_steps: int):
    """The resume leg, un-jitted: returns (machine, xla_sweeps) where
    xla_sweeps counts the step_v iterations the bounded while executed —
    the fused window's ladder-engine round currency.  Shared by the
    jitted standalone leg (make_run_resume, which discards the count)
    and the fused megachunk window body."""
    from wtf_tpu.interp.step import step_lane

    step_v = jax.vmap(step_lane, in_axes=(None, IMAGE_IN_AXES, 0, None))
    running = jnp.int32(_RUNNING)
    parked = jnp.int32(_NEEDS_XLA)

    image = lane_image(image, machine.status.shape[0])
    st = machine.status
    machine = machine._replace(status=jnp.where(
        st == parked, running, jnp.where(st == running, parked, st)))

    def cond(carry):
        i, m = carry
        return (i < n_steps) & jnp.any(m.status == running)

    def body(carry):
        i, m = carry
        return i + 1, step_v(tab, image, m, limit)

    iters, out = lax.while_loop(cond, body, (jnp.int32(0), machine))
    # release held lanes (step_lane never emits NEEDS_XLA itself, so
    # every remaining NEEDS_XLA is a lane held above)
    out = out._replace(status=jnp.where(
        out.status == parked, running, out.status))
    return out, iters


def make_run_resume(n_steps: int, donate: bool = None):
    """The fused ladder's XLA resume leg: run a SHORT chunk of the full
    transition function (interp/step.py) for the lanes the kernel parked,
    so the one instruction that parked each lane retires on the precise
    path, then control returns to the kernel.

    The leg swaps statuses around the chunk: parked (NEEDS_XLA) lanes run,
    while still-RUNNING lanes — hot lanes that simply exhausted the
    kernel's K steps — are HELD for its duration and released after.
    Without the hold every round would retire `n_steps` hot instructions
    on the XLA path per lane, capping fused occupancy at K/(K+n) even on
    all-hot code; with it, occupancy equals the stream's hot fraction.
    `n_steps` stays small (default 1) because every XLA-retired
    instruction is lost occupancy for lanes that park.

    Same memoization/donation policy as step.make_run_chunk (donation is
    unsound on the XLA CPU backend — see that docstring)."""
    if donate is None:
        donate = jax.default_backend() != "cpu"
    key = (n_steps, donate)
    cached = _RESUME_CACHE.get(key)
    if cached is not None:
        return cached

    from functools import partial

    @partial(jax.jit, donate_argnums=(2,) if donate else ())
    def run_resume(tab: UopTable, image: MemImage, machine: Machine, limit):
        out, _ = fused_resume_impl(tab, image, machine, limit,
                                   n_steps=n_steps)
        return out

    _RESUME_CACHE[key] = run_resume
    return run_resume
