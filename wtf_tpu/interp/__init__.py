"""The vmapped x86-64 interpreter: the TPU-native replacement for bochscpu.

Where the reference runs one guest at a time inside an instrumented emulator
(reference src/wtf/bochscpu_backend.cc), this package runs a *batch* of
guests in lockstep on the device:

  uoptable.py - host-managed decode cache resident on device (bytes are
                decoded once per unique RIP, like a JIT's translation cache)
  machine.py  - per-lane architectural state as SoA arrays [lanes, ...]
  step.py     - the single-instruction transition function, vmapped over
                lanes, with lane masking for divergence and per-lane
                status codes for anything needing host servicing
  runner.py   - host orchestration: chunked device runs, decode servicing,
                breakpoint dispatch, oracle fallback for rare instructions
"""
