"""Symbolization layer (reference L3, debugger.{h,cc})."""

from wtf_tpu.symbols.debugger import Debugger

__all__ = ["Debugger"]
