"""Symbol store + symbolization (reference `Debugger_t`).

The reference has two modes: DbgEng COM symbolization on Windows
(debugger.h:17-342) and a flat `symbol-store.json` name->address map on
Linux (debugger.h:343-386); every live resolution is persisted into the
store (AddSymbol, debugger.h:92-108) so Linux runs symbolize offline.
This framework has no DbgEng, so the store IS the source of truth —
what bdump/symbolizer tooling exported with the snapshot.

Provides both directions:
  get_symbol(name)  name -> address            (debugger.h:281-299)
  get_name(addr)    address -> 'module!sym+0x12', nearest-preceding
                    symbol, with a cache      (debugger.h:301-341)
  add_symbol(...)   insert + optional persist  (debugger.h:92-108)
"""

from __future__ import annotations

import bisect
import json
from pathlib import Path
from typing import Dict, List, Optional, Tuple


class Debugger:
    def __init__(self, symbols: Optional[Dict[str, int]] = None,
                 store_path: Optional[Path] = None):
        self._symbols: Dict[str, int] = dict(symbols or {})
        self._store_path = Path(store_path) if store_path else None
        self._name_cache: Dict[int, str] = {}
        self._sorted: Optional[List[Tuple[int, str]]] = None

    # -- loading / persistence ---------------------------------------------
    @classmethod
    def load(cls, store_path) -> "Debugger":
        """Load symbol-store.json ({'module!sym': '0xaddr' | int})."""
        store_path = Path(store_path)
        symbols: Dict[str, int] = {}
        if store_path.exists():
            raw = json.loads(store_path.read_text())
            symbols = {
                k: (int(v, 0) if isinstance(v, str) else int(v))
                for k, v in raw.items()
            }
        return cls(symbols, store_path=store_path)

    def save(self) -> None:
        if self._store_path is None:
            return
        self._store_path.write_text(json.dumps(
            {k: hex(v) for k, v in sorted(self._symbols.items())},
            indent=1))

    # -- name -> address ----------------------------------------------------
    def get_symbol(self, name: str) -> int:
        addr = self._symbols.get(name)
        if addr is None:
            raise KeyError(f"symbol {name!r} not in store "
                           f"({len(self._symbols)} symbols)")
        return addr

    def try_get_symbol(self, name: str) -> Optional[int]:
        return self._symbols.get(name)

    def add_symbol(self, name: str, address: int,
                   persist: bool = True) -> None:
        """Insert a resolution (reference persists every one so offline
        runs can symbolize, debugger.h:92-108)."""
        self._symbols[name] = address
        self._sorted = None
        self._name_cache.clear()
        if persist:
            self.save()

    # -- address -> name ----------------------------------------------------
    def _sorted_symbols(self) -> List[Tuple[int, str]]:
        if self._sorted is None:
            self._sorted = sorted(
                (addr, name) for name, addr in self._symbols.items())
        return self._sorted

    def get_name(self, address: int, style: str = "full") -> str:
        """Nearest preceding symbol + offset; raw hex when nothing
        precedes.  style='modoff' gives 'module+0xoff' (the reference's
        two DbgEng styles)."""
        cached = self._name_cache.get(address)
        if cached is not None and style == "full":
            return cached
        table = self._sorted_symbols()
        idx = bisect.bisect_right(table, (address, "\xff")) - 1
        if idx < 0 or not table:
            return f"{address:#x}"
        base, name = table[idx]
        offset = address - base
        if style == "modoff":
            module = name.split("!", 1)[0]
            out = module if offset == 0 else f"{module}+{offset:#x}"
        else:
            out = name if offset == 0 else f"{name}+{offset:#x}"
        if style == "full":
            self._name_cache[address] = out
        return out

    def __len__(self) -> int:
        return len(self._symbols)

    def __contains__(self, name: str) -> bool:
        return name in self._symbols
