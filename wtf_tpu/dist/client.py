"""Fuzz node: dial the master, execute testcases, report coverage+result.

Reference `Client_t` (src/wtf/client.cc): Run (:210-263) = Target.Init once,
Dial, then loop { Receive testcase -> RunTestcaseAndRestore -> SendResult }.
`run_testcase_and_restore` below is the canonical per-testcase sequence
(client.cc:88-180): InsertTestcase -> Run -> (Timedout? revoke coverage)
-> Target.Restore -> Backend.Restore.

Two node shapes:

  Client      - one connection, one testcase at a time (any Backend; the
                reference's process-per-core model)
  BatchClient - one *lane batch* per round against a TpuBackend: opens
                n_lanes connections so the master remains completely
                unmodified (the north-star property — the master cannot
                tell a TPU pod from n_lanes ordinary clients), collects one
                testcase per connection, runs them as one device batch, and
                replies on each connection with that lane's coverage delta.
"""

from __future__ import annotations

import socket
from typing import List, Optional, Set, Tuple

from wtf_tpu.core.results import TestcaseResult, Timedout
from wtf_tpu.dist import wire
from wtf_tpu.fuzz.loop import CampaignStats
from wtf_tpu import telemetry
from wtf_tpu.telemetry import Registry


class _NodeTelemetry:
    """Shared node-side telemetry: the same `campaign.*` counters and
    heartbeat line shape as the fused loop/master (cov/corp omitted — a
    node doesn't track them), wired identically for both node shapes."""

    def _init_telemetry(self, backend, registry, events,
                        stats_every: float, print_stats: bool) -> None:
        self.registry, self.events = telemetry.resolve(
            backend, registry, events)
        self.stats = CampaignStats(self.registry)
        self.stats_every = stats_every
        self.print_stats = print_stats

    def _heartbeat(self) -> None:
        self.stats.maybe_heartbeat(self.events, self.registry,
                                   every=self.stats_every,
                                   print_stats=self.print_stats)


def run_testcase_and_restore(backend, target, data: bytes,
                             ) -> Tuple[TestcaseResult, Set[int]]:
    """The canonical sequence (client.cc:88-180)."""
    target.insert_testcase(backend, data)
    result = backend.run()
    if isinstance(result, Timedout):
        backend.revoke_last_new_coverage()  # client.cc:122-125
    coverage = backend.last_new_coverage()
    target.restore()
    backend.restore()
    return result, coverage


class Client(_NodeTelemetry):
    """Single-slot node (reference shape)."""

    def __init__(self, backend, target, address: str,
                 registry: Optional[Registry] = None, events=None,
                 stats_every: float = 10.0, print_stats: bool = False):
        self.backend = backend
        self.target = target
        self.address = address
        self.runs = 0
        self._init_telemetry(backend, registry, events, stats_every,
                             print_stats)

    def run(self, max_runs: int = 0) -> int:
        """Serve until the master closes (or max_runs served)."""
        self.target.init(self.backend)
        sock = wire.dial(self.address, retry_for=10.0)
        wire.send_msg(sock, wire.encode_hello(1))
        try:
            while max_runs == 0 or self.runs < max_runs:
                try:
                    testcase = wire.recv_msg(sock)
                except (OSError, ValueError):
                    break  # reset or desynced frame: same as master gone
                if testcase is None:
                    break  # master gone: node exits (client.cc:228-231)
                result, coverage = run_testcase_and_restore(
                    self.backend, self.target, testcase)
                self.stats.account(result)
                try:
                    wire.send_msg(
                        sock, wire.encode_result(testcase, coverage, result))
                except OSError:
                    break  # master hung up mid-report (shutdown race)
                self.runs += 1
                self._heartbeat()
        finally:
            sock.close()
        return self.runs


class BatchClient(_NodeTelemetry):
    """TPU node: one device batch per round against the master.

    Two wire shapes (selected by `mux`):
      mux=False  n_lanes connections, one hello(1) each — byte-compatible
                 with the reference's process-per-core nodes; the master
                 cannot tell a TPU pod from n_lanes ordinary clients.
      mux=True   ONE connection with hello(n_lanes): the master sends a
                 batch frame of up to n_lanes testcases per round and gets
                 one batch frame of results back.  This is what scales a
                 4096-lane node: 1 fd instead of 4096.
    """

    def __init__(self, backend, target, address: str, mux: bool = False,
                 registry: Optional[Registry] = None, events=None,
                 stats_every: float = 10.0, print_stats: bool = False):
        self.backend = backend
        self.target = target
        self.address = address
        self.mux = mux
        self.rounds = 0
        self.runs = 0
        self._init_telemetry(backend, registry, events, stats_every,
                             print_stats)

    def run(self, max_rounds: int = 0) -> int:
        if self.mux:
            return self._run_mux(max_rounds)
        self.target.init(self.backend)
        n = self.backend.n_lanes
        socks: List[socket.socket] = []
        for _ in range(n):
            sock = wire.dial(self.address, retry_for=10.0)
            wire.send_msg(sock, wire.encode_hello(1))
            socks.append(sock)
        try:
            while max_rounds == 0 or self.rounds < max_rounds:
                batch: List[bytes] = []
                live: List[socket.socket] = []
                for sock in socks:
                    try:
                        tc = wire.recv_msg(sock)
                    except (OSError, ValueError):
                        tc = None  # reset/desynced: lane's master is gone
                    if tc is None:
                        sock.close()  # lane retired: don't leak the fd
                        continue
                    batch.append(tc)
                    live.append(sock)
                if not batch:
                    break
                socks = live
                results = self.backend.run_batch(batch, self.target)
                kept: List[socket.socket] = []
                for lane, (sock, data, result) in enumerate(
                        zip(socks, batch, results)):
                    coverage = self.backend.lane_coverage(lane)
                    if isinstance(result, Timedout):
                        coverage = set()  # revoked (client.cc:122-125)
                    elif not self.backend.lane_found_new_coverage(lane):
                        coverage = set()  # nothing new to report
                    self.stats.account(result)
                    try:
                        wire.send_msg(
                            sock, wire.encode_result(data, coverage, result))
                    except OSError:
                        sock.close()  # master hung up mid-report
                        continue
                    kept.append(sock)
                    self.runs += 1
                socks = kept
                self.target.restore()
                self.backend.restore()
                self.rounds += 1
                self._heartbeat()
        finally:
            for sock in socks:
                sock.close()
        return self.runs

    def _run_mux(self, max_rounds: int = 0) -> int:
        """Multiplexed rounds: one batch frame in, one batch frame out."""
        self.target.init(self.backend)
        sock = wire.dial(self.address, retry_for=10.0)
        wire.send_msg(sock, wire.encode_hello(self.backend.n_lanes))
        try:
            while max_rounds == 0 or self.rounds < max_rounds:
                try:
                    frame = wire.recv_msg(sock)
                except (OSError, ValueError):
                    break  # reset or desynced frame: master gone
                if frame is None:
                    break
                batch = wire.decode_batch(frame)
                if not batch:
                    break
                results = self.backend.run_batch(batch, self.target)
                replies = []
                for lane, (data, result) in enumerate(zip(batch, results)):
                    coverage = self.backend.lane_coverage(lane)
                    if isinstance(result, Timedout):
                        coverage = set()  # revoked (client.cc:122-125)
                    elif not self.backend.lane_found_new_coverage(lane):
                        coverage = set()  # nothing new to report
                    self.stats.account(result)
                    replies.append(
                        wire.encode_result(data, coverage, result))
                    self.runs += 1
                try:
                    wire.send_msg(sock, wire.encode_batch(replies))
                except OSError:
                    break  # master hung up mid-report
                self.target.restore()
                self.backend.restore()
                self.rounds += 1
                self._heartbeat()
        finally:
            sock.close()
        return self.runs
