"""Fuzz node: dial the master, execute testcases, report coverage+result.

Reference `Client_t` (src/wtf/client.cc): Run (:210-263) = Target.Init once,
Dial, then loop { Receive testcase -> RunTestcaseAndRestore -> SendResult }.
`run_testcase_and_restore` below is the canonical per-testcase sequence
(client.cc:88-180): InsertTestcase -> Run -> (Timedout? revoke coverage)
-> Target.Restore -> Backend.Restore.

Two node shapes:

  Client      - one connection, one testcase at a time (any Backend; the
                reference's process-per-core model)
  BatchClient - one *lane batch* per round against a TpuBackend: opens
                n_lanes connections so the master remains completely
                unmodified (the north-star property — the master cannot
                tell a TPU pod from n_lanes ordinary clients), collects one
                testcase per connection, runs them as one device batch, and
                replies on each connection with that lane's coverage delta.
"""

from __future__ import annotations

import logging
import random
import time
from typing import List, Optional, Set, Tuple

from wtf_tpu.core.results import (
    Crash, OverlayFull, TestcaseResult, Timedout,
)


def _coverage_revoked(result) -> bool:
    """Results whose coverage must not be reported (client.cc:122-125;
    overlay-full lanes ran on truncated memory): the delta path must
    also not piggyback unacked-bit repair on them — the master credits
    any new addresses on a frame to THAT frame's testcase."""
    return isinstance(result, (Timedout, OverlayFull))
from wtf_tpu.dist import wire
from wtf_tpu.fuzz.loop import CampaignStats
from wtf_tpu import telemetry
from wtf_tpu.telemetry import NULL, Registry

log = logging.getLogger(__name__)


class MasterLink:
    """One resilient master connection: dial + tagged hello, transparent
    reconnect with jittered exponential backoff bounded by
    `max_retry_secs` (0 = reference behavior: any loss ends the node).

    The re-handshake story: on socket loss the master reclaims this
    link's in-flight testcases (dist/server.py _drop) and re-serves them
    elsewhere, so the link never resends anything — it reconnects, says
    hello again, and asks for fresh work.  An unsent result is simply
    abandoned: its testcase re-executes somewhere, the master counts it
    once.  A TAG_BYE frame is the orderly end (budget done / drain) and
    permanently stops reconnection."""

    BACKOFF_BASE = 0.05
    BACKOFF_CAP = 2.0

    def __init__(self, address: str, n_slots: int = 1,
                 max_retry_secs: float = 0.0,
                 registry: Optional[Registry] = None, events=None,
                 rng: Optional[random.Random] = None,
                 tagged: bool = True, cursor=None):
        self.address = address
        self.n_slots = n_slots
        self.max_retry_secs = max_retry_secs
        self.registry = registry if registry is not None else Registry()
        self.events = events if events is not None else NULL
        self.rng = rng if rng is not None else random.Random()
        # tagged=False = full legacy (v1) wire behavior against a master
        # that predates WTF2: raw downstream frames, no BYE — and
        # therefore NO reconnect (a clean close is indistinguishable
        # from an orderly end on v1, so retrying would spin against a
        # finished master).  The rolling-upgrade escape hatch
        # (`fuzz --wire-v1`).
        self.tagged = tagged
        # streaming-coverage cursor (wtf_tpu/fleet/delta.DeltaCursor):
        # upgrades the hello to WTF3 and every upstream result to a
        # TAG_COVDELTA frame; the link drives the cursor's handshake
        # (TAG_CURSOR after (re)connect) and implicit acks (each WORK
        # frame proves the master accounted everything sent before it)
        self.cursor = cursor if tagged else None
        self.sock = None
        self._bye = False

    def connect(self, retry_for: float = 10.0) -> None:
        """Initial dial + hello (the node-before-master startup race is
        covered by wire.dial's own transient retry window)."""
        self._drop_socket()  # never strand a previous fd
        sock = wire.dial(self.address, retry_for=retry_for)
        try:
            if self.cursor is not None:
                hello = wire.encode_hello_delta(self.n_slots,
                                                self.cursor.client_id)
            else:
                hello = wire.encode_hello(self.n_slots, tagged=self.tagged)
            wire.send_msg(sock, hello)
        except OSError:
            # hello lost with the connection (master died between accept
            # and read — the crash-loop shape): close, don't leak the fd
            # once per backoff attempt
            sock.close()
            raise
        self.sock = sock

    def close(self) -> None:
        if self.sock is not None:
            self.sock.close()
            self.sock = None

    def _drop_socket(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def _reconnect(self) -> bool:
        """Backoff-reconnect within the retry budget; True once the
        re-handshake landed.  Every attempt is a `dist.retries` count and
        a `retry` event — the fleet's flap rate is an ops signal."""
        if self.max_retry_secs <= 0 or self._bye or not self.tagged:
            return False
        deadline = time.monotonic() + self.max_retry_secs
        delay = self.BACKOFF_BASE
        attempt = 0
        while time.monotonic() < deadline:
            attempt += 1
            self.registry.counter("dist.retries").inc()
            self.events.emit("retry", attempt=attempt,
                             address=self.address)
            try:
                self.connect(retry_for=0.0)
            except OSError:
                # jittered exponential backoff: a thousand nodes losing
                # one master must not reconnect in lockstep
                remaining = deadline - time.monotonic()
                sleep = min(delay, max(remaining, 0.0)) \
                    * (0.5 + self.rng.random() * 0.5)
                if sleep > 0:
                    time.sleep(sleep)
                delay = min(delay * 2, self.BACKOFF_CAP)
                continue
            log.warning("reconnected to master after %d attempt(s)",
                        attempt)
            self.events.emit("reconnect", attempts=attempt,
                             address=self.address)
            return True
        log.warning("master gone for > %.1fs; giving up",
                    self.max_retry_secs)
        return False

    def recv_work(self) -> Optional[bytes]:
        """The next work payload (testcase, or batch frame for mux
        links); None = campaign over — BYE received, or the connection
        died and the retry budget is spent."""
        while True:
            if self.sock is None and not self._reconnect():
                return None
            try:
                if self.tagged:
                    got = wire.recv_tagged(self.sock)
                else:
                    payload = wire.recv_msg(self.sock)
                    got = (None if payload is None
                           else (wire.TAG_WORK, payload))
            except (OSError, ValueError):
                got = None  # reset / desynced frame
            if got is None:
                # lost mid-campaign (or master closed without BYE, which
                # for a retrying node means "maybe it restarts")
                self._drop_socket()
                if not self._reconnect():
                    return None
                continue
            tag, payload = got
            if tag == wire.TAG_BYE:
                self._bye = True
                self._drop_socket()
                return None
            if tag == wire.TAG_CURSOR and self.cursor is not None:
                # the master names the ack cursor it holds for us:
                # resume sparse deltas or fall back to a bitmap resync.
                # A truncated frame (desynced master) is a connection
                # problem, not a node-fatal one — same error surface as
                # the master's own frame decode.
                import struct as _struct

                try:
                    self.cursor.on_cursor(*wire.decode_cursor(payload))
                except (ValueError, IndexError, _struct.error):
                    self._drop_socket()
                    if not self._reconnect():
                        return None
                continue
            if self.cursor is not None:
                # a WORK frame is the implicit ack: the master only
                # serves after accounting our previous result frame
                self.cursor.on_ack()
            return payload

    def send_delta(self, body: bytes) -> bool:
        """Send one TAG_COVDELTA frame (delta-result body, or a batch
        frame of them on mux links)."""
        return self.send(bytes((wire.TAG_COVDELTA,)) + body)

    def send_telem(self, body: bytes) -> bool:
        """Send one TAG_TELEM frame (wire.encode_telem body).  WTF3
        links only — v1/v2 masters would read the tag byte as the start
        of a result body.  Best-effort like every upstream send: a lost
        snapshot is superseded by the next one (they are cumulative)."""
        if self.cursor is None:
            return False
        return self.send(bytes((wire.TAG_TELEM,)) + body)

    def send(self, body: bytes) -> bool:
        """Best-effort result send.  On failure the socket drops and the
        result is abandoned (see class docstring); the next recv_work
        reconnects.  Returns False when the send was lost."""
        if self.sock is None:
            return False
        try:
            wire.send_msg(self.sock, body)
            return True
        except OSError:
            self._drop_socket()
            return False


class _NodeTelemetry:
    """Shared node-side telemetry: the same `campaign.*` counters and
    heartbeat line shape as the fused loop/master (cov/corp omitted — a
    node doesn't track them), wired identically for both node shapes.

    WTF3 nodes additionally ship a TAG_TELEM frame on the heartbeat
    cadence: the node's CUMULATIVE Registry.snapshot() plus a digest of
    recent node events, sequence-numbered so the master's aggregator
    stays idempotent under reconnect replays.  Emission rides the
    EXISTING heartbeat throttle — snapshot serialization never touches
    the per-testcase (or per-batch dispatch) path, which the telemetry
    lint family pins statically."""

    def _init_telemetry(self, backend, registry, events,
                        stats_every: float, print_stats: bool) -> None:
        self.registry, self.events = telemetry.resolve(
            backend, registry, events)
        # recent-event digest ring: node-level events (retry/reconnect/
        # crash/...) tap in here on their way to the JSONL sink and ride
        # the next telem frame upstream
        from collections import deque

        from wtf_tpu.telemetry import TapEventLog

        self._recent_events = deque(maxlen=64)
        self.events = TapEventLog(self.events, self._tap_event)
        self.stats = CampaignStats(self.registry)
        self.stats_every = stats_every
        self.print_stats = print_stats
        self._telem_seq = 0
        self._telem_last = 0.0
        self._telem_link: Optional[MasterLink] = None

    def _tap_event(self, type_: str, fields: dict) -> None:
        if type_ == "heartbeat":
            return  # carried whole by the telem frame itself
        digest = {"type": type_}
        for key in ("name", "kind", "count", "attempts", "bucket"):
            if key in fields:
                digest[key] = fields[key]
        self._recent_events.append(digest)

    def _heartbeat(self) -> None:
        self.stats.maybe_heartbeat(self.events, self.registry,
                                   every=self.stats_every,
                                   print_stats=self.print_stats)
        # telem emission has its OWN throttle: a node with no local
        # event log (maybe_heartbeat early-returns there) still reports
        # to the master's fleet plane
        now = time.time()
        if now - self._telem_last >= self.stats_every:
            self._telem_last = now
            self._send_telem()

    def _send_telem(self) -> None:
        """One TAG_TELEM frame on the designated WTF3 link (no-op for
        v1/v2 wire shapes — those masters predate the frame)."""
        link = self._telem_link
        if link is None or link.cursor is None:
            return
        self._telem_seq += 1
        recent = list(self._recent_events)
        if link.send_telem(wire.encode_telem(
                self._telem_seq, self.registry.snapshot(), recent)):
            self._recent_events.clear()
            self.registry.counter("dist.telem_sent").inc()


def run_testcase_and_restore(backend, target, data: bytes,
                             want_bucket: bool = False):
    """The canonical sequence (client.cc:88-180).  `want_bucket` adds
    the PR-9 triage bucket of a crash as a third return — it must be
    computed BEFORE the restore rolls the faulting state back, which is
    why it lives inside this sequence."""
    target.insert_testcase(backend, data)
    result = backend.run()
    if isinstance(result, Timedout):
        backend.revoke_last_new_coverage()  # client.cc:122-125
    coverage = backend.last_new_coverage()
    bucket = ""
    if want_bucket and isinstance(result, Crash):
        from wtf_tpu.triage.bucket import bucket_of

        bucket = bucket_of(backend, 0, result)
    target.restore()
    backend.restore()
    if want_bucket:
        return result, coverage, bucket
    return result, coverage


class Client(_NodeTelemetry):
    """Single-slot node (reference shape).  `max_retry_secs` > 0 makes it
    survive mid-campaign socket loss: reconnect with jittered backoff,
    re-handshake, keep serving — a BYE (or the budget running out) still
    ends it, so the reference's 'master gone -> node exits' remains the
    terminal behavior (client.cc:228-231)."""

    def __init__(self, backend, target, address: str,
                 registry: Optional[Registry] = None, events=None,
                 stats_every: float = 10.0, print_stats: bool = False,
                 max_retry_secs: float = 0.0,
                 retry_rng: Optional[random.Random] = None,
                 wire_v1: bool = False, cov_delta: bool = False,
                 client_id: Optional[bytes] = None):
        self.backend = backend
        self.target = target
        self.address = address
        self.max_retry_secs = max_retry_secs
        self.retry_rng = retry_rng
        self.wire_v1 = wire_v1
        # cov_delta: speak WTF3 — results carry only newly-set coverage
        # bits against the master's ack cursor (wtf_tpu/fleet/delta)
        # instead of the whole coverage set.  Needs a delta-capable
        # master; --no-cov-delta is the rolling-upgrade escape hatch.
        self.cov_delta = cov_delta and not wire_v1
        self.client_id = client_id
        self.runs = 0
        self._init_telemetry(backend, registry, events, stats_every,
                             print_stats)

    def run(self, max_runs: int = 0) -> int:
        """Serve until the master says BYE / stays gone (or max_runs)."""
        from wtf_tpu.fleet.delta import AddressDeltaCursor

        self.target.init(self.backend)
        cursor = (AddressDeltaCursor(self.client_id, self.registry)
                  if self.cov_delta else None)
        link = MasterLink(self.address, 1, self.max_retry_secs,
                          registry=self.registry, events=self.events,
                          rng=self.retry_rng, tagged=not self.wire_v1,
                          cursor=cursor)
        link.connect(retry_for=10.0)
        self._telem_link = link
        try:
            while max_runs == 0 or self.runs < max_runs:
                testcase = link.recv_work()
                if testcase is None:
                    break  # campaign over / master gone for good
                result, coverage, bucket = run_testcase_and_restore(
                    self.backend, self.target, testcase, want_bucket=True)
                self.stats.account(result)
                # a lost result is fine: the master reclaimed this
                # testcase with the socket and re-serves it elsewhere
                if cursor is not None:
                    if _coverage_revoked(result):
                        body = cursor.encode_empty(testcase, result,
                                                   bucket=bucket)
                    else:
                        body = cursor.encode_result(
                            testcase, result, coverage, bucket=bucket)
                    link.send_delta(body)
                else:
                    link.send(wire.encode_result(testcase, coverage,
                                                 result))
                self.runs += 1
                self._heartbeat()
        finally:
            link.close()
        return self.runs


class BatchClient(_NodeTelemetry):
    """TPU node: one device batch per round against the master.

    Two wire shapes (selected by `mux`):
      mux=False  n_lanes connections, one hello(1) each — byte-compatible
                 with the reference's process-per-core nodes; the master
                 cannot tell a TPU pod from n_lanes ordinary clients.
      mux=True   ONE connection with hello(n_lanes): the master sends a
                 batch frame of up to n_lanes testcases per round and gets
                 one batch frame of results back.  This is what scales a
                 4096-lane node: 1 fd instead of 4096.
    """

    def __init__(self, backend, target, address: str, mux: bool = False,
                 registry: Optional[Registry] = None, events=None,
                 stats_every: float = 10.0, print_stats: bool = False,
                 max_retry_secs: float = 0.0,
                 retry_rng: Optional[random.Random] = None,
                 wire_v1: bool = False, cov_delta: bool = False):
        self.backend = backend
        self.target = target
        self.address = address
        self.mux = mux
        self.max_retry_secs = max_retry_secs
        self.retry_rng = retry_rng
        self.wire_v1 = wire_v1
        # WTF3 streaming deltas (wtf_tpu/fleet/delta).  On the mux link
        # the cursor rides the backend's native `[words, 32]` bit space
        # — delta extraction is one XOR against the last-acked aggregate
        # and no per-lane address decode happens at all; on the
        # 1-fd-per-lane shape each link keeps its own address cursor.
        self.cov_delta = cov_delta and not wire_v1
        self.rounds = 0
        self.runs = 0
        self._init_telemetry(backend, registry, events, stats_every,
                             print_stats)

    def _link(self, n_slots: int, cursor=None) -> MasterLink:
        return MasterLink(self.address, n_slots, self.max_retry_secs,
                          registry=self.registry, events=self.events,
                          rng=self.retry_rng, tagged=not self.wire_v1,
                          cursor=cursor)

    def _lane_reportable(self, lane: int, result) -> bool:
        """Does this lane have coverage worth shipping?  Timeout lanes
        are revoked (client.cc:122-125) and no-new-coverage lanes have
        nothing the master hasn't seen from this client."""
        return (not isinstance(result, Timedout)
                and self.backend.lane_found_new_coverage(lane))

    def _bucket(self, lane: int, result) -> str:
        if not isinstance(result, Crash):
            return ""
        from wtf_tpu.triage.bucket import bucket_of

        return bucket_of(self.backend, lane, result)

    def run(self, max_rounds: int = 0) -> int:
        if self.mux:
            return self._run_mux(max_rounds)
        from wtf_tpu.fleet.delta import AddressDeltaCursor

        self.target.init(self.backend)
        links: List[MasterLink] = []
        for _ in range(self.backend.n_lanes):
            cursor = (AddressDeltaCursor(registry=self.registry)
                      if self.cov_delta else None)
            link = self._link(1, cursor=cursor)
            link.connect(retry_for=10.0)
            links.append(link)
        try:
            while max_rounds == 0 or self.rounds < max_rounds:
                batch: List[bytes] = []
                live: List[MasterLink] = []
                for link in links:
                    tc = link.recv_work()  # reconnects under the hood
                    if tc is None:
                        link.close()  # lane retired (BYE / budget spent)
                        if not link._bye:
                            # this lane burned its WHOLE retry budget:
                            # the master is gone for every lane — zero
                            # the siblings' budgets so shutdown costs
                            # one window, not n_lanes windows (they
                            # still drain whatever their live sockets
                            # already hold)
                            for rest in links:
                                rest.max_retry_secs = 0.0
                        continue
                    batch.append(tc)
                    live.append(link)
                if not batch:
                    break
                links = live
                results = self.backend.run_batch(batch, self.target)
                for lane, (link, data, result) in enumerate(
                        zip(links, batch, results)):
                    # the lane's whole coverage set decodes ONLY when
                    # there is something new to report (the v2 path used
                    # to pull it per lane unconditionally)
                    coverage = (self.backend.lane_coverage(lane)
                                if self._lane_reportable(lane, result)
                                else set())
                    self.stats.account(result)
                    # lost sends abandon the result (master reclaims);
                    # the lane stays — its next recv_work reconnects
                    if link.cursor is not None:
                        bucket = self._bucket(lane, result)
                        if _coverage_revoked(result):
                            body = link.cursor.encode_empty(
                                data, result, bucket=bucket)
                        else:
                            body = link.cursor.encode_result(
                                data, result, coverage, bucket=bucket)
                        link.send_delta(body)
                    else:
                        link.send(wire.encode_result(data, coverage,
                                                     result))
                    self.runs += 1
                self.target.restore()
                self.backend.restore()
                self.rounds += 1
                # ONE lane link carries the node's telem frames (the
                # registry is node-wide; one identity owns its totals)
                self._telem_link = links[0] if links else None
                self._heartbeat()
        finally:
            for link in links:
                link.close()
        return self.runs

    def _run_mux(self, max_rounds: int = 0) -> int:
        """Multiplexed rounds: one batch frame in, one batch frame out."""
        from wtf_tpu.fleet.delta import BitmapDeltaCursor

        self.target.init(self.backend)
        cursor = (BitmapDeltaCursor(self.backend, registry=self.registry)
                  if self.cov_delta else None)
        link = self._link(self.backend.n_lanes, cursor=cursor)
        link.connect(retry_for=10.0)
        self._telem_link = link
        try:
            while max_rounds == 0 or self.rounds < max_rounds:
                frame = link.recv_work()
                if frame is None:
                    break  # campaign over / master gone for good
                batch = wire.decode_batch(frame)
                if not batch:
                    break
                results = self.backend.run_batch(batch, self.target)
                if cursor is not None:
                    replies = self._delta_replies(cursor, batch, results)
                    for result in results:
                        self.stats.account(result)
                    self.runs += len(batch)
                    link.send_delta(wire.encode_batch(replies))
                else:
                    replies = []
                    for lane, (data, result) in enumerate(
                            zip(batch, results)):
                        coverage = (self.backend.lane_coverage(lane)
                                    if self._lane_reportable(lane, result)
                                    else set())
                        self.stats.account(result)
                        replies.append(
                            wire.encode_result(data, coverage, result))
                        self.runs += 1
                    link.send(wire.encode_batch(replies))
                self.target.restore()
                self.backend.restore()
                self.rounds += 1
                self._heartbeat()
        finally:
            link.close()
        return self.runs

    def _delta_replies(self, cursor, batch, results) -> List[bytes]:
        """One round's delta bodies: each reportable lane carries the
        bits it is FIRST to claim against the acked aggregate (claim
        chaining mirrors the device merge's prefix credit); bits no lane
        of this round covers — coverage whose frame was lost with a
        dropped connection — ride the first NON-revoked body, so the
        link repairs loss by re-extraction, never by retransmission
        bookkeeping.  Revoked results (timeouts, overlay-full) always
        go out as empty bodies: the master credits a frame's addresses
        to its testcase, and a hang must never earn corpus admission."""
        import numpy as np

        agg = np.asarray(self.backend.coverage_state()[0], np.uint32)
        lane_words = {
            lane: self.backend.lane_cov_words(lane)
            for lane, result in enumerate(results)
            if self._lane_reportable(lane, result)}
        carried = np.zeros_like(agg)
        for words in lane_words.values():
            carried |= np.asarray(words, np.uint32)
        stale = cursor.unacked(agg) & ~carried
        carrier = next((lane for lane, result in enumerate(results)
                        if not _coverage_revoked(result)), None)
        claimed = np.zeros_like(agg)
        replies = []
        first = True
        for lane, (data, result) in enumerate(zip(batch, results)):
            bucket = self._bucket(lane, result)
            if _coverage_revoked(result):
                replies.append(cursor.encode_empty(data, result,
                                                   bucket=bucket))
                continue
            words = lane_words.get(lane)
            if lane == carrier and stale.any():
                words = stale if words is None \
                    else np.asarray(words, np.uint32) | stale
            replies.append(cursor.encode_lane(
                data, result, words, claimed, bucket=bucket,
                first=first))
            first = False
        return replies
