"""Fuzz node: dial the master, execute testcases, report coverage+result.

Reference `Client_t` (src/wtf/client.cc): Run (:210-263) = Target.Init once,
Dial, then loop { Receive testcase -> RunTestcaseAndRestore -> SendResult }.
`run_testcase_and_restore` below is the canonical per-testcase sequence
(client.cc:88-180): InsertTestcase -> Run -> (Timedout? revoke coverage)
-> Target.Restore -> Backend.Restore.

Two node shapes:

  Client      - one connection, one testcase at a time (any Backend; the
                reference's process-per-core model)
  BatchClient - one *lane batch* per round against a TpuBackend: opens
                n_lanes connections so the master remains completely
                unmodified (the north-star property — the master cannot
                tell a TPU pod from n_lanes ordinary clients), collects one
                testcase per connection, runs them as one device batch, and
                replies on each connection with that lane's coverage delta.
"""

from __future__ import annotations

import logging
import random
import time
from typing import List, Optional, Set, Tuple

from wtf_tpu.core.results import TestcaseResult, Timedout
from wtf_tpu.dist import wire
from wtf_tpu.fuzz.loop import CampaignStats
from wtf_tpu import telemetry
from wtf_tpu.telemetry import NULL, Registry

log = logging.getLogger(__name__)


class MasterLink:
    """One resilient master connection: dial + tagged hello, transparent
    reconnect with jittered exponential backoff bounded by
    `max_retry_secs` (0 = reference behavior: any loss ends the node).

    The re-handshake story: on socket loss the master reclaims this
    link's in-flight testcases (dist/server.py _drop) and re-serves them
    elsewhere, so the link never resends anything — it reconnects, says
    hello again, and asks for fresh work.  An unsent result is simply
    abandoned: its testcase re-executes somewhere, the master counts it
    once.  A TAG_BYE frame is the orderly end (budget done / drain) and
    permanently stops reconnection."""

    BACKOFF_BASE = 0.05
    BACKOFF_CAP = 2.0

    def __init__(self, address: str, n_slots: int = 1,
                 max_retry_secs: float = 0.0,
                 registry: Optional[Registry] = None, events=None,
                 rng: Optional[random.Random] = None,
                 tagged: bool = True):
        self.address = address
        self.n_slots = n_slots
        self.max_retry_secs = max_retry_secs
        self.registry = registry if registry is not None else Registry()
        self.events = events if events is not None else NULL
        self.rng = rng if rng is not None else random.Random()
        # tagged=False = full legacy (v1) wire behavior against a master
        # that predates WTF2: raw downstream frames, no BYE — and
        # therefore NO reconnect (a clean close is indistinguishable
        # from an orderly end on v1, so retrying would spin against a
        # finished master).  The rolling-upgrade escape hatch
        # (`fuzz --wire-v1`).
        self.tagged = tagged
        self.sock = None
        self._bye = False

    def connect(self, retry_for: float = 10.0) -> None:
        """Initial dial + hello (the node-before-master startup race is
        covered by wire.dial's own transient retry window)."""
        self._drop_socket()  # never strand a previous fd
        sock = wire.dial(self.address, retry_for=retry_for)
        try:
            wire.send_msg(sock, wire.encode_hello(self.n_slots,
                                                  tagged=self.tagged))
        except OSError:
            # hello lost with the connection (master died between accept
            # and read — the crash-loop shape): close, don't leak the fd
            # once per backoff attempt
            sock.close()
            raise
        self.sock = sock

    def close(self) -> None:
        if self.sock is not None:
            self.sock.close()
            self.sock = None

    def _drop_socket(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def _reconnect(self) -> bool:
        """Backoff-reconnect within the retry budget; True once the
        re-handshake landed.  Every attempt is a `dist.retries` count and
        a `retry` event — the fleet's flap rate is an ops signal."""
        if self.max_retry_secs <= 0 or self._bye or not self.tagged:
            return False
        deadline = time.monotonic() + self.max_retry_secs
        delay = self.BACKOFF_BASE
        attempt = 0
        while time.monotonic() < deadline:
            attempt += 1
            self.registry.counter("dist.retries").inc()
            self.events.emit("retry", attempt=attempt,
                             address=self.address)
            try:
                self.connect(retry_for=0.0)
            except OSError:
                # jittered exponential backoff: a thousand nodes losing
                # one master must not reconnect in lockstep
                remaining = deadline - time.monotonic()
                sleep = min(delay, max(remaining, 0.0)) \
                    * (0.5 + self.rng.random() * 0.5)
                if sleep > 0:
                    time.sleep(sleep)
                delay = min(delay * 2, self.BACKOFF_CAP)
                continue
            log.warning("reconnected to master after %d attempt(s)",
                        attempt)
            self.events.emit("reconnect", attempts=attempt,
                             address=self.address)
            return True
        log.warning("master gone for > %.1fs; giving up",
                    self.max_retry_secs)
        return False

    def recv_work(self) -> Optional[bytes]:
        """The next work payload (testcase, or batch frame for mux
        links); None = campaign over — BYE received, or the connection
        died and the retry budget is spent."""
        while True:
            if self.sock is None and not self._reconnect():
                return None
            try:
                if self.tagged:
                    got = wire.recv_tagged(self.sock)
                else:
                    payload = wire.recv_msg(self.sock)
                    got = (None if payload is None
                           else (wire.TAG_WORK, payload))
            except (OSError, ValueError):
                got = None  # reset / desynced frame
            if got is None:
                # lost mid-campaign (or master closed without BYE, which
                # for a retrying node means "maybe it restarts")
                self._drop_socket()
                if not self._reconnect():
                    return None
                continue
            tag, payload = got
            if tag == wire.TAG_BYE:
                self._bye = True
                self._drop_socket()
                return None
            return payload

    def send(self, body: bytes) -> bool:
        """Best-effort result send.  On failure the socket drops and the
        result is abandoned (see class docstring); the next recv_work
        reconnects.  Returns False when the send was lost."""
        if self.sock is None:
            return False
        try:
            wire.send_msg(self.sock, body)
            return True
        except OSError:
            self._drop_socket()
            return False


class _NodeTelemetry:
    """Shared node-side telemetry: the same `campaign.*` counters and
    heartbeat line shape as the fused loop/master (cov/corp omitted — a
    node doesn't track them), wired identically for both node shapes."""

    def _init_telemetry(self, backend, registry, events,
                        stats_every: float, print_stats: bool) -> None:
        self.registry, self.events = telemetry.resolve(
            backend, registry, events)
        self.stats = CampaignStats(self.registry)
        self.stats_every = stats_every
        self.print_stats = print_stats

    def _heartbeat(self) -> None:
        self.stats.maybe_heartbeat(self.events, self.registry,
                                   every=self.stats_every,
                                   print_stats=self.print_stats)


def run_testcase_and_restore(backend, target, data: bytes,
                             ) -> Tuple[TestcaseResult, Set[int]]:
    """The canonical sequence (client.cc:88-180)."""
    target.insert_testcase(backend, data)
    result = backend.run()
    if isinstance(result, Timedout):
        backend.revoke_last_new_coverage()  # client.cc:122-125
    coverage = backend.last_new_coverage()
    target.restore()
    backend.restore()
    return result, coverage


class Client(_NodeTelemetry):
    """Single-slot node (reference shape).  `max_retry_secs` > 0 makes it
    survive mid-campaign socket loss: reconnect with jittered backoff,
    re-handshake, keep serving — a BYE (or the budget running out) still
    ends it, so the reference's 'master gone -> node exits' remains the
    terminal behavior (client.cc:228-231)."""

    def __init__(self, backend, target, address: str,
                 registry: Optional[Registry] = None, events=None,
                 stats_every: float = 10.0, print_stats: bool = False,
                 max_retry_secs: float = 0.0,
                 retry_rng: Optional[random.Random] = None,
                 wire_v1: bool = False):
        self.backend = backend
        self.target = target
        self.address = address
        self.max_retry_secs = max_retry_secs
        self.retry_rng = retry_rng
        self.wire_v1 = wire_v1
        self.runs = 0
        self._init_telemetry(backend, registry, events, stats_every,
                             print_stats)

    def run(self, max_runs: int = 0) -> int:
        """Serve until the master says BYE / stays gone (or max_runs)."""
        self.target.init(self.backend)
        link = MasterLink(self.address, 1, self.max_retry_secs,
                          registry=self.registry, events=self.events,
                          rng=self.retry_rng, tagged=not self.wire_v1)
        link.connect(retry_for=10.0)
        try:
            while max_runs == 0 or self.runs < max_runs:
                testcase = link.recv_work()
                if testcase is None:
                    break  # campaign over / master gone for good
                result, coverage = run_testcase_and_restore(
                    self.backend, self.target, testcase)
                self.stats.account(result)
                # a lost result is fine: the master reclaimed this
                # testcase with the socket and re-serves it elsewhere
                link.send(wire.encode_result(testcase, coverage, result))
                self.runs += 1
                self._heartbeat()
        finally:
            link.close()
        return self.runs


class BatchClient(_NodeTelemetry):
    """TPU node: one device batch per round against the master.

    Two wire shapes (selected by `mux`):
      mux=False  n_lanes connections, one hello(1) each — byte-compatible
                 with the reference's process-per-core nodes; the master
                 cannot tell a TPU pod from n_lanes ordinary clients.
      mux=True   ONE connection with hello(n_lanes): the master sends a
                 batch frame of up to n_lanes testcases per round and gets
                 one batch frame of results back.  This is what scales a
                 4096-lane node: 1 fd instead of 4096.
    """

    def __init__(self, backend, target, address: str, mux: bool = False,
                 registry: Optional[Registry] = None, events=None,
                 stats_every: float = 10.0, print_stats: bool = False,
                 max_retry_secs: float = 0.0,
                 retry_rng: Optional[random.Random] = None,
                 wire_v1: bool = False):
        self.backend = backend
        self.target = target
        self.address = address
        self.mux = mux
        self.max_retry_secs = max_retry_secs
        self.retry_rng = retry_rng
        self.wire_v1 = wire_v1
        self.rounds = 0
        self.runs = 0
        self._init_telemetry(backend, registry, events, stats_every,
                             print_stats)

    def _link(self, n_slots: int) -> MasterLink:
        return MasterLink(self.address, n_slots, self.max_retry_secs,
                          registry=self.registry, events=self.events,
                          rng=self.retry_rng, tagged=not self.wire_v1)

    def run(self, max_rounds: int = 0) -> int:
        if self.mux:
            return self._run_mux(max_rounds)
        self.target.init(self.backend)
        links: List[MasterLink] = []
        for _ in range(self.backend.n_lanes):
            link = self._link(1)
            link.connect(retry_for=10.0)
            links.append(link)
        try:
            while max_rounds == 0 or self.rounds < max_rounds:
                batch: List[bytes] = []
                live: List[MasterLink] = []
                for link in links:
                    tc = link.recv_work()  # reconnects under the hood
                    if tc is None:
                        link.close()  # lane retired (BYE / budget spent)
                        if not link._bye:
                            # this lane burned its WHOLE retry budget:
                            # the master is gone for every lane — zero
                            # the siblings' budgets so shutdown costs
                            # one window, not n_lanes windows (they
                            # still drain whatever their live sockets
                            # already hold)
                            for rest in links:
                                rest.max_retry_secs = 0.0
                        continue
                    batch.append(tc)
                    live.append(link)
                if not batch:
                    break
                links = live
                results = self.backend.run_batch(batch, self.target)
                for lane, (link, data, result) in enumerate(
                        zip(links, batch, results)):
                    coverage = self.backend.lane_coverage(lane)
                    if isinstance(result, Timedout):
                        coverage = set()  # revoked (client.cc:122-125)
                    elif not self.backend.lane_found_new_coverage(lane):
                        coverage = set()  # nothing new to report
                    self.stats.account(result)
                    # lost sends abandon the result (master reclaims);
                    # the lane stays — its next recv_work reconnects
                    link.send(wire.encode_result(data, coverage, result))
                    self.runs += 1
                self.target.restore()
                self.backend.restore()
                self.rounds += 1
                self._heartbeat()
        finally:
            for link in links:
                link.close()
        return self.runs

    def _run_mux(self, max_rounds: int = 0) -> int:
        """Multiplexed rounds: one batch frame in, one batch frame out."""
        self.target.init(self.backend)
        link = self._link(self.backend.n_lanes)
        link.connect(retry_for=10.0)
        try:
            while max_rounds == 0 or self.rounds < max_rounds:
                frame = link.recv_work()
                if frame is None:
                    break  # campaign over / master gone for good
                batch = wire.decode_batch(frame)
                if not batch:
                    break
                results = self.backend.run_batch(batch, self.target)
                replies = []
                for lane, (data, result) in enumerate(zip(batch, results)):
                    coverage = self.backend.lane_coverage(lane)
                    if isinstance(result, Timedout):
                        coverage = set()  # revoked (client.cc:122-125)
                    elif not self.backend.lane_found_new_coverage(lane):
                        coverage = set()  # nothing new to report
                    self.stats.account(result)
                    replies.append(
                        wire.encode_result(data, coverage, result))
                    self.runs += 1
                link.send(wire.encode_batch(replies))
                self.target.restore()
                self.backend.restore()
                self.rounds += 1
                self._heartbeat()
        finally:
            link.close()
        return self.runs
