"""Distribution plane: master/node fuzzing over TCP or Unix sockets.

Reference layer L5 (SURVEY.md §2.3): `Server_t` master + `Client_t` nodes
speaking u32-length-prefixed messages.  The master is completely backend-
agnostic — a TPU batch node (client.BatchClient) looks like n_lanes
ordinary single-testcase nodes, preserving the reference's master
unmodified (the BASELINE.json north-star property).

  wire    - address scheme, framing, result serialization
  server  - master reactor: corpus replay -> mutation, coverage set-union,
            crash saving, runs budget / minset mode
  client  - node loop: run_testcase_and_restore over any Backend
"""

from wtf_tpu.dist.client import (
    BatchClient, Client, MasterLink, run_testcase_and_restore,
)
from wtf_tpu.dist.server import Server, ServerStats

__all__ = [
    "BatchClient", "Client", "MasterLink", "Server", "ServerStats",
    "run_testcase_and_restore",
]
