"""Wire protocol: address scheme + length-prefixed framed messages.

Reference surface (src/wtf/socket.{h,cc}): `tcp://host:port/` and
`unix:///path` address strings (socket.cc:70-225), Listen/Dial with
TCP_NODELAY (socket.cc:227-308), u32-length-prefixed messages
(Send socket.cc:310-323, Receive :325-358).  The reference serializes with
yas binary archives; SURVEY.md §2.6 notes the wire format is an internal
detail, not a contract — this module uses an explicit little-endian struct
layout instead:

  server -> client:  the raw testcase bytes (server.h:720-736 sends just
                     the testcase string)
  client -> server:  u32 testcase_len | testcase
                     u32 n_cov | n_cov * u64 coverage addresses
                     u8 result kind (0 ok, 1 timedout, 2 cr3change, 3 crash,
                                     4 overlay-full: node resource limit —
                                     master requeues the testcase)
                     u16 name_len | crash name utf-8
                     (client.cc:187-200 / server.h:771-779 message shape)
"""

from __future__ import annotations

import errno
import socket
import struct
from typing import Optional, Set, Tuple

from wtf_tpu.core.results import (
    Cr3Change, Crash, Ok, OverlayFull, TestcaseResult, Timedout,
)

MAX_MSG = 64 * 1024 * 1024  # sanity bound on a frame


# ---------------------------------------------------------------------------
# addresses
# ---------------------------------------------------------------------------

def parse_address(address: str):
    """'tcp://host:port/' -> (AF_INET, (host, port));
    'unix:///path' -> (AF_UNIX, path).  (socket.cc:70-225)"""
    if address.startswith("tcp://"):
        rest = address[len("tcp://"):].rstrip("/")
        host, _, port = rest.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"bad tcp address {address!r}")
        return socket.AF_INET, (host, int(port))
    if address.startswith("unix://"):
        path = address[len("unix://"):]
        if not path:
            raise ValueError(f"bad unix address {address!r}")
        return socket.AF_UNIX, path
    raise ValueError(f"unsupported address scheme {address!r}")


def listen(address: str, backlog: int = 64) -> socket.socket:
    family, addr = parse_address(address)
    sock = socket.socket(family, socket.SOCK_STREAM)
    if family == socket.AF_INET:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.bind(addr)
    sock.listen(backlog)
    return sock


# Connect errors worth retrying inside a dial window: the master not up
# yet (refused / unix socket file missing) plus the transient network
# conditions a rebooting master or flapping route produces.  Anything
# else (bad address family, EACCES, ...) is a configuration error and
# aborts immediately — retrying would just mask it for retry_for seconds.
_TRANSIENT_ERRNOS = frozenset({
    errno.ECONNREFUSED, errno.ECONNRESET, errno.ECONNABORTED,
    errno.EHOSTUNREACH, errno.ENETUNREACH, errno.ENETDOWN,
    errno.ETIMEDOUT, errno.EINTR, errno.EAGAIN,
})


def _transient_connect_error(e: OSError) -> bool:
    if isinstance(e, (ConnectionRefusedError, FileNotFoundError,
                      socket.timeout)):
        return True
    return e.errno in _TRANSIENT_ERRNOS


def dial(address: str, timeout: Optional[float] = None,
         retry_for: float = 0.0) -> socket.socket:
    """Connect to a master.  `retry_for` seconds of connect retries cover
    the node-starts-before-master race (the reference leaves this to the
    operator; nodes here are commonly spawned together with the master)
    AND transient network failures (EHOSTUNREACH/ETIMEDOUT/EINTR/...) —
    a blip must not abort a node that was told to keep trying.  Past the
    deadline the last error re-raises."""
    import time

    family, addr = parse_address(address)
    deadline = time.monotonic() + retry_for
    while True:
        sock = socket.socket(family, socket.SOCK_STREAM)
        if timeout is not None:
            sock.settimeout(timeout)
        try:
            sock.connect(addr)
        except OSError as e:
            sock.close()
            if not _transient_connect_error(e) \
                    or time.monotonic() >= deadline:
                raise
            time.sleep(0.05)
            continue
        if family == socket.AF_INET:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock


# ---------------------------------------------------------------------------
# framing (u32 length prefix, socket.cc:310-358)
# ---------------------------------------------------------------------------

def send_msg(sock: socket.socket, body: bytes) -> None:
    sock.sendall(struct.pack("<I", len(body)) + body)


def recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None  # peer closed
        buf += chunk
    return bytes(buf)


def recv_msg(sock: socket.socket) -> Optional[bytes]:
    hdr = recv_exact(sock, 4)
    if hdr is None:
        return None
    (length,) = struct.unpack("<I", hdr)
    if length > MAX_MSG:
        raise ValueError(f"oversized frame ({length} bytes)")
    return recv_exact(sock, length)


# ---------------------------------------------------------------------------
# hello + batch frames (lane multiplexing)
# ---------------------------------------------------------------------------
# Every node opens with a hello frame claiming n_slots.  n_slots == 1 is
# the reference shape (one testcase frame in flight, one result frame
# back).  n_slots > 1 multiplexes a whole lane batch over ONE connection:
# the master sends a batch frame of up to n_slots testcases and the node
# replies with one batch frame of results — what lets a 4096-lane TPU node
# talk to the master through a single fd instead of 4096 (the reference
# is architecturally 1 fd per core, server.h:386-389, and its select()
# master caps out at FD_SETSIZE).

HELLO_MAGIC = b"WTFH"    # v1: server->client frames are raw payloads
HELLO2_MAGIC = b"WTF2"   # v2: server->client frames carry a 1-byte tag
HELLO3_MAGIC = b"WTF3"   # v3: v2 + streaming coverage deltas (fleet tier)

# v2 downstream frame tags.  v1 has no in-band way to distinguish "the
# campaign is over, don't come back" from "the master died" — the raw
# testcase payload can be any bytes, so nothing can ride in-band without
# colliding.  A v2 hello opts the connection into tagged frames:
#   TAG_WORK  payload = one testcase (slots == 1) or a batch frame (mux)
#   TAG_BYE   orderly end (budget done / drain): do NOT reconnect
# v1 clients (and any reference-shaped client) keep getting untagged
# frames and learn about shutdown the way they always did: a close.
#
# v3 (WTF3 hello: 16-byte client identity appended) additionally opts
# the connection into streaming coverage deltas (wtf_tpu/fleet/delta):
#   TAG_CURSOR    master->node, right after the hello: the ack cursor
#                 the master holds for this client identity, so a
#                 reconnecting node resumes sparse deltas instead of
#                 resending its whole bitmap
#   TAG_COVDELTA  node->master: every post-hello upstream frame carries
#                 this tag + a delta-result body (or a batch frame of
#                 delta-result bodies on mux links) — newly-set coverage
#                 bits only, as sparse word-index+mask pairs over the
#                 client's own bit space, with bit->address table
#                 registrations riding alongside
#   TAG_TELEM     node->master (tagged/delta connections): a periodic
#                 telemetry snapshot — the node's CUMULATIVE registry
#                 state plus a digest of recent events, sequence-numbered
#                 per connection epoch.  Pure observability: carries no
#                 campaign state, so the master may drop it on decode
#                 error without touching slot accounting, and a re-sent
#                 frame can never double-count (the aggregator keeps the
#                 latest snapshot per client identity).
TAG_WORK = 0
TAG_BYE = 1
TAG_CURSOR = 2
TAG_COVDELTA = 3
TAG_TELEM = 4

CLIENT_ID_LEN = 16


def encode_hello(n_slots: int, tagged: bool = False) -> bytes:
    return (HELLO2_MAGIC if tagged else HELLO_MAGIC) \
        + struct.pack("<I", n_slots)


def encode_hello_delta(n_slots: int, client_id: bytes) -> bytes:
    """The WTF3 hello: tagged frames + streaming coverage deltas.  The
    client identity survives reconnects (and master restarts, via the
    persisted cursor state) — it is what per-client ack cursors key on."""
    if len(client_id) != CLIENT_ID_LEN:
        raise ValueError(f"client id must be {CLIENT_ID_LEN} bytes")
    return HELLO3_MAGIC + struct.pack("<I", n_slots) + client_id


def decode_hello(body: bytes) -> Optional[int]:
    """n_slots when `body` is a hello frame (any version), else None."""
    if len(body) == 8 and body[:4] in (HELLO_MAGIC, HELLO2_MAGIC):
        return struct.unpack_from("<I", body, 4)[0]
    if len(body) == 8 + CLIENT_ID_LEN and body[:4] == HELLO3_MAGIC:
        return struct.unpack_from("<I", body, 4)[0]
    return None


def hello_is_tagged(body: bytes) -> bool:
    """True when a hello frame opted into tagged downstream frames."""
    return (len(body) == 8 and body[:4] == HELLO2_MAGIC) \
        or hello_is_delta(body)


def hello_is_delta(body: bytes) -> bool:
    """True when a hello frame opted into streaming coverage deltas."""
    return len(body) == 8 + CLIENT_ID_LEN and body[:4] == HELLO3_MAGIC


def hello_client_id(body: bytes) -> Optional[bytes]:
    """The 16-byte client identity of a WTF3 hello, else None."""
    return body[8:] if hello_is_delta(body) else None


def send_work(sock: socket.socket, body: bytes, tagged: bool) -> None:
    """Master->node work frame, tagged per the connection's hello."""
    send_msg(sock, bytes((TAG_WORK,)) + body if tagged else body)


def send_bye(sock: socket.socket) -> None:
    """Orderly-shutdown frame (tagged connections only)."""
    send_msg(sock, bytes((TAG_BYE,)))


def encode_cursor(n_table: int, digest: bytes) -> bytes:
    """Master->node ack-cursor frame body: how many bit->address table
    entries the master holds for this client identity plus an 8-byte
    digest of the whole acked state (table + acked bitmap).  The node
    compares against its own state: match -> resume sparse deltas;
    mismatch (fresh master, lost cursor) -> whole-bitmap resync."""
    if len(digest) != 8:
        raise ValueError("cursor digest must be 8 bytes")
    return bytes((TAG_CURSOR,)) + struct.pack("<I", n_table) + digest


def decode_cursor(payload: bytes) -> Tuple[int, bytes]:
    """(n_table, digest8) of a TAG_CURSOR frame payload."""
    (n_table,) = struct.unpack_from("<I", payload, 0)
    digest = payload[4:12]
    if len(digest) != 8:
        raise ValueError("short cursor frame")
    return n_table, digest


def recv_tagged(sock: socket.socket) -> Optional[Tuple[int, bytes]]:
    """Node-side receive on a tagged connection: (tag, payload), or None
    when the peer closed.  An empty frame is a protocol violation on a
    tagged link (every frame carries at least its tag byte)."""
    body = recv_msg(sock)
    if body is None:
        return None
    if not body:
        raise ValueError("empty frame on tagged connection")
    return body[0], body[1:]


def encode_batch(items) -> bytes:
    """Concatenate length-prefixed blobs into one batch frame body."""
    parts = [struct.pack("<I", len(items))]
    for item in items:
        parts.append(struct.pack("<I", len(item)))
        parts.append(item)
    return b"".join(parts)


def decode_batch(body: bytes) -> list:
    (n,) = struct.unpack_from("<I", body, 0)
    off = 4
    items = []
    for _ in range(n):
        (length,) = struct.unpack_from("<I", body, off)
        off += 4
        items.append(body[off:off + length])
        off += length
    return items


# ---------------------------------------------------------------------------
# result message body
# ---------------------------------------------------------------------------

_KIND = {Ok: 0, Timedout: 1, Cr3Change: 2, Crash: 3, OverlayFull: 4}


def encode_result(testcase: bytes, coverage: Set[int],
                  result: TestcaseResult) -> bytes:
    kind = _KIND[type(result)]
    name = (result.name or "").encode() if isinstance(result, Crash) else b""
    parts = [
        struct.pack("<I", len(testcase)), testcase,
        struct.pack("<I", len(coverage)),
        struct.pack(f"<{len(coverage)}Q", *sorted(coverage)),
        struct.pack("<B", kind),
        struct.pack("<H", len(name)), name,
    ]
    return b"".join(parts)


def decode_result(body: bytes) -> Tuple[bytes, Set[int], TestcaseResult]:
    off = 0

    def take(fmt):
        nonlocal off
        size = struct.calcsize(fmt)
        vals = struct.unpack_from(fmt, body, off)
        off += size
        return vals

    (tc_len,) = take("<I")
    testcase = body[off:off + tc_len]
    off += tc_len
    (n_cov,) = take("<I")
    coverage = set(take(f"<{n_cov}Q")) if n_cov else set()
    (kind,) = take("<B")
    (name_len,) = take("<H")
    name = body[off:off + name_len].decode()
    off += name_len
    result: TestcaseResult
    if kind == 0:
        result = Ok()
    elif kind == 1:
        result = Timedout()
    elif kind == 2:
        result = Cr3Change()
    elif kind == 4:
        result = OverlayFull()
    else:
        result = Crash(name or None)
    return testcase, coverage, result


# ---------------------------------------------------------------------------
# delta-result message body (WTF3 / TAG_COVDELTA upstream frames)
# ---------------------------------------------------------------------------
# Where a v1/v2 result ships the lane's WHOLE coverage set (n_cov u64
# addresses — O(covered blocks) per new-coverage result), a delta result
# ships only the bits newly set since the master's last ack:
#
#   u8  flags            bit 0: full resync (master must drop any prior
#                        cursor state for this client before applying)
#   u32 testcase_len | testcase
#   u32 table_base       first bit index of the address registrations
#   u32 n_addrs | n_addrs * u64        bit->address table entries for
#                                      indices [table_base, table_base+n)
#   u32 n_pairs | n_pairs * (u32 word_index, u32 mask)   the delta bits,
#                                      sparse over the client's bit space
#   u8  kind | u16 name_len | name     as in the v1/v2 result body
#   u16 bucket_len | bucket            PR-9 triage bucket (crash dedup
#                                      service key; empty when unknown)
#
# Bit indices are CLIENT-local (decode order); the table registrations
# are what make them meaningful master-side.  The cursor state machines
# that produce/consume these live in wtf_tpu/fleet/delta.py.

FLAG_FULL = 1


class DeltaFrame:
    """Decoded coverage-delta payload of one result."""

    __slots__ = ("full", "table_base", "addrs", "pairs")

    def __init__(self, full: bool, table_base: int, addrs, pairs):
        self.full = full
        self.table_base = table_base
        self.addrs = list(addrs)
        self.pairs = list(pairs)

    def cov_bytes(self) -> int:
        """Wire bytes of the coverage sections (table_base + n_addrs +
        n_pairs u32 headers, 8 per address, 8 per pair) — the part the
        delta scheme shrinks; testcase/result bytes are common to both
        protocols."""
        return 12 + 8 * len(self.addrs) + 8 * len(self.pairs)


def encode_result_delta(testcase: bytes, result: TestcaseResult,
                        delta: DeltaFrame, bucket: str = "") -> bytes:
    kind = _KIND[type(result)]
    name = (result.name or "").encode() if isinstance(result, Crash) else b""
    bucket_b = bucket.encode()
    pairs_flat = []
    for word, mask in delta.pairs:
        pairs_flat.append(word)
        pairs_flat.append(mask)
    parts = [
        struct.pack("<B", FLAG_FULL if delta.full else 0),
        struct.pack("<I", len(testcase)), testcase,
        struct.pack("<II", delta.table_base, len(delta.addrs)),
        struct.pack(f"<{len(delta.addrs)}Q", *delta.addrs),
        struct.pack("<I", len(delta.pairs)),
        struct.pack(f"<{len(pairs_flat)}I", *pairs_flat),
        struct.pack("<B", kind),
        struct.pack("<H", len(name)), name,
        struct.pack("<H", len(bucket_b)), bucket_b,
    ]
    return b"".join(parts)


def decode_result_delta(body: bytes):
    """-> (testcase, DeltaFrame, result, bucket)."""
    off = 0

    def take(fmt):
        nonlocal off
        size = struct.calcsize(fmt)
        vals = struct.unpack_from(fmt, body, off)
        off += size
        return vals

    (flags,) = take("<B")
    (tc_len,) = take("<I")
    testcase = body[off:off + tc_len]
    off += tc_len
    table_base, n_addrs = take("<II")
    addrs = list(take(f"<{n_addrs}Q")) if n_addrs else []
    (n_pairs,) = take("<I")
    flat = take(f"<{2 * n_pairs}I") if n_pairs else ()
    pairs = [(flat[i], flat[i + 1]) for i in range(0, len(flat), 2)]
    (kind,) = take("<B")
    (name_len,) = take("<H")
    name = body[off:off + name_len].decode()
    off += name_len
    (bucket_len,) = take("<H")
    bucket = body[off:off + bucket_len].decode()
    off += bucket_len
    result: TestcaseResult
    if kind == 0:
        result = Ok()
    elif kind == 1:
        result = Timedout()
    elif kind == 2:
        result = Cr3Change()
    elif kind == 4:
        result = OverlayFull()
    else:
        result = Crash(name or None)
    delta = DeltaFrame(bool(flags & FLAG_FULL), table_base, addrs, pairs)
    return testcase, delta, result, bucket


# ---------------------------------------------------------------------------
# telemetry snapshot body (TAG_TELEM upstream frames)
# ---------------------------------------------------------------------------
# Observability piggybacks on the work connection instead of opening a
# second control plane: once per node heartbeat the client ships its
# CUMULATIVE Registry.snapshot() plus a short digest of recent events.
# The payload is JSON — telemetry names are an open set (tenant
# namespaces, backend counters) and this frame is heartbeat-rate, not
# per-testcase, so schema flexibility beats struct packing here.  The
# u32 seq is per connection epoch and strictly increasing; the master's
# aggregator drops seq <= last-applied for the same client identity, so
# a frame replayed across a reconnect can never double-count.

def encode_telem(seq: int, snapshot: dict, events=()) -> bytes:
    """Body of a TAG_TELEM frame (tag byte NOT included, matching
    encode_result_delta): u32 seq | json({"snapshot", "events"})."""
    import json

    payload = json.dumps({"snapshot": snapshot, "events": list(events)},
                         default=str).encode()
    return struct.pack("<I", seq) + payload


def decode_telem(body: bytes) -> Tuple[int, dict, list]:
    """-> (seq, snapshot, events) of a TAG_TELEM frame payload."""
    import json

    (seq,) = struct.unpack_from("<I", body, 0)
    payload = json.loads(body[4:].decode())
    return seq, payload.get("snapshot", {}), payload.get("events", [])
