"""Master node: testcase generation, coverage aggregation, corpus, crashes.

Reference `Server_t` (src/wtf/server.h): a single-threaded select() reactor
(Run server.h:361-598) in lock-step request/response with each client
(state machine server.h:249-255).  Semantics preserved here:

  - seed paths: inputs/ files are streamed to clients biggest-first before
    any mutation happens (server.h:399-414, :629-706)
  - GetTestcase: corpus-file replay first, else mutate (server.h:629-714)
  - HandleNewResult: merge client coverage into the global set; if it grew,
    feed the mutator cross-over and save the testcase into outputs/
    (server.h:785-886); named crashes saved under crashes/ (:861-877)
  - run budget: stop once `mutations >= runs` and no seed paths remain
    (server.h:552-556); `runs=0` = minset mode — only replay the seeds,
    outputs/ ends up holding the coverage-minimal subset (README.md:81-92)
  - elasticity: clients may join/leave anytime; a dropped fd is just
    removed from the reactor (server.h:534-544,605-623)
"""

from __future__ import annotations

import hashlib
import logging
import re
import selectors
import socket
import struct
import time
from collections import deque
from pathlib import Path
from typing import Deque, Dict, List, Optional, Set

from wtf_tpu.core.results import OverlayFull
from wtf_tpu.dist import wire
from wtf_tpu.utils.atomicio import atomic_write_bytes, atomic_write_text
from wtf_tpu.fuzz.corpus import Corpus
from wtf_tpu.fuzz.loop import CampaignStats
from wtf_tpu.fuzz.mutator import Mutator
from wtf_tpu.telemetry import NULL, Registry
from wtf_tpu.utils.hashing import hex_digest
from wtf_tpu.utils.human import number_to_human, seconds_to_human

log = logging.getLogger(__name__)


class ServerStats(CampaignStats):
    """Status-line counters (reference ServerStats_t, server.h:24-240).
    Registry-backed via CampaignStats — the master's numbers live in the
    same `campaign.*` namespace the fused loop uses, so one report tool
    reads both — plus the master-only lastcov age."""

    def __init__(self, registry: Optional[Registry] = None):
        super().__init__(registry)
        self.last_cov = time.time()

    def line(self, cov: int, corpus_len: int, clients: int) -> str:
        dt = time.time() - self.start
        execs = self.testcases / dt if dt > 0 else 0.0
        return (f"#{number_to_human(self.testcases)} cov: {cov} "
                f"corp: {corpus_len} exec/s: {execs:.1f} "
                f"nodes: {clients} lastcov: "
                f"{seconds_to_human(time.time() - self.last_cov)} "
                f"crash: {self.crashes} timeout: {self.timeouts} "
                f"cr3: {self.cr3s} uptime: {seconds_to_human(dt)}")


class _Conn:
    """Per-connection master state: slot count from the node's hello frame
    (1 = reference shape; >1 = lane-multiplexed batch frames), the
    testcases in flight on it, whether the node speaks tagged (v2)
    frames or coverage deltas (v3, with its client identity), and when
    the in-flight batch was sent (reclaim timeout)."""

    __slots__ = ("slots", "mux", "inflight", "tagged", "since", "delta",
                 "client_id")

    def __init__(self):
        self.slots = 1
        self.mux = False
        self.inflight: List[bytes] = []
        self.tagged = False
        self.since = 0.0
        self.delta = False
        self.client_id: Optional[str] = None


class Server:
    def __init__(
        self,
        address: str,
        mutator: Mutator,
        corpus: Corpus,
        inputs_dir: Optional[Path] = None,
        crashes_dir: Optional[Path] = None,
        runs: int = 0,
        max_len: int = 1024 * 1024,
        stats_every: float = 10.0,
        print_stats: bool = False,
        coverage_path: Optional[Path] = None,
        registry: Optional[Registry] = None,
        events=None,
        reclaim_timeout: float = 0.0,
        drain_grace: float = 5.0,
        store=None,
        cursor_cap: int = 4096,
        telemetry_dir: Optional[Path] = None,
    ):
        self.address = address
        self.mutator = mutator
        self.corpus = corpus
        self.crashes_dir = Path(crashes_dir) if crashes_dir else None
        if self.crashes_dir:
            self.crashes_dir.mkdir(parents=True, exist_ok=True)
        self.runs = runs
        self.max_len = max_len
        self.coverage_path = Path(coverage_path) if coverage_path else None
        self.registry = registry if registry is not None else Registry()
        self.events = events if events is not None else NULL
        self.stats = ServerStats(self.registry)
        self.stats_every = stats_every
        self.print_stats = print_stats
        # seed queue: inputs/ plus any prior campaign's outputs/ — a
        # restarted master resumes by replaying its persisted corpus
        # (SURVEY §5.4; reference server.h:399-414).  Entries are Paths
        # read lazily at serve time (a resumed multi-GB corpus must not
        # materialize in memory at startup); dirwatch injections are bytes.
        from wtf_tpu.fuzz.corpus import seed_paths

        self._paths: Deque = deque(
            p for p, _ in seed_paths([inputs_dir, corpus.outputs_dir]))
        self._dirwatch = None
        self._dirwatch_last = 0.0
        if inputs_dir:
            # mid-campaign injection: operators drop seeds into inputs/
            # while the master runs (reference dirwatch.h); constructed
            # even when the dir doesn't exist yet — it may appear later
            from wtf_tpu.fuzz.dirwatch import DirWatcher

            self._dirwatch = DirWatcher(inputs_dir)
        self.coverage: Set[int] = set()
        self.mutations = 0
        self.crash_names: Set[str] = set()
        # crash dedup service: keyed by the PR-9 triage bucket when the
        # node reports one (WTF3 frames), by sanitized name otherwise —
        # only novel keys are persisted/announced
        self.crash_buckets: Set[str] = set()
        # content-addressed corpus/crash store (wtf_tpu/fleet/store);
        # None keeps the flat-directory behavior
        self.store = store
        if store is not None:
            corpus.store = store
        self._ovf_requeued: Set[str] = set()
        self._ever_served = False
        # streaming-coverage ack cursors, keyed by client identity
        # (wtf_tpu/fleet/delta.ServerCursor); persisted with the
        # coverage file so a restarted master resumes them instead of
        # forcing whole-bitmap resyncs.  `_restored` holds addresses
        # implied by restored state: part of the persisted/served
        # aggregate but NOT of the corpus-admission test, so the
        # replayed outputs/ corpus still re-earns its entries.
        self._cursors: Dict[str, object] = {}
        # eviction bound: a cursor is a near-copy of the address table
        # per client IDENTITY, and identities are fresh per node
        # process/link — without a cap, restarts accumulate dead tables
        # in memory and in the persisted coverage file forever.  LRU
        # over the cap, never a cursor with a live connection; an
        # evicted identity that comes back just pays one bitmap resync.
        self.cursor_cap = cursor_cap
        self._restored: Set[int] = set()
        self._cov_dirty = False
        self._last_persist = time.time()
        self._load_coverage_state()
        self._listener: Optional[socket.socket] = None
        self._clients: Dict[socket.socket, _Conn] = {}
        self._sel: Optional[selectors.BaseSelector] = None
        # fault tolerance: in-flight work of a dead or silent node is
        # reclaimed to the pending deque (`dist.reclaimed`); 0 disables
        # the silence timeout (drop-detection reclaim is always on)
        self.reclaim_timeout = reclaim_timeout
        # fleet observability: TAG_TELEM snapshots from WTF3 nodes merge
        # here (wtf_tpu/fleet/telemetry); exports land next to the other
        # interval persistence when a telemetry dir is configured
        from wtf_tpu.fleet.telemetry import FleetTelemetry

        self.fleet_telem = FleetTelemetry(export_dir=telemetry_dir)
        # SIGTERM drain: stop serving, give in-flight results this long
        # to land, persist, notify nodes, exit the reactor cleanly
        self.drain_grace = drain_grace
        self._draining = False
        self._drain_deadline: Optional[float] = None
        self.drained = False

    @property
    def paths(self) -> Deque:
        """Seed queue (deque: popleft each serve, prepend on requeue —
        a plain list's pop(0)/[:0] is quadratic under a large resumed
        corpus with churn).  Assignment accepts any iterable."""
        return self._paths

    @paths.setter
    def paths(self, items) -> None:
        self._paths = deque(items)

    # -- testcase generation (server.h:629-714) ----------------------------
    def _torn_corpus_file(self, path: Path, data: bytes) -> bool:
        """outputs/ entries are content-addressed (name == digest): a
        mismatch means the file was torn by a kill mid-write (pre-atomic
        writers, or an external copy).  A restarted master must skip it
        loudly, not replay garbage or abort the whole resume."""
        if self.corpus.outputs_dir is None \
                or path.parent != self.corpus.outputs_dir:
            return False  # inputs/ names are operator-chosen: no contract
        name = path.name
        if len(name) != 64 or any(c not in "0123456789abcdef"
                                  for c in name):
            return False
        # the SAME digest Corpus.add names outputs/ files with — an
        # inline hash here would silently disagree if the content-digest
        # choice ever changes, and then "skip torn files" would discard
        # the entire persisted corpus on restart
        return hex_digest(data) != name

    def _next_seed(self) -> Optional[bytes]:
        while self.paths:
            item = self.paths.popleft()
            if isinstance(item, Path):
                try:
                    data = item.read_bytes()
                except OSError:
                    continue  # vanished since the startup scan
                if self._torn_corpus_file(item, data):
                    log.warning("skipping torn corpus file %s "
                                "(content fails its digest name)", item)
                    self.events.emit("error", kind="torn-corpus-file",
                                     path=str(item), size=len(data))
                    continue
                return data[:self.max_len]
            return item[:self.max_len]
        return None

    def get_testcase(self) -> Optional[bytes]:
        seed = self._next_seed()
        if seed is not None:
            return seed
        if self.runs and self.mutations >= self.runs:
            return None
        if self.runs == 0:
            return None  # minset mode: seeds only (server.h:552-556)
        self.mutations += 1
        return self.mutator.get_new_testcase(self.corpus)[:self.max_len]

    def done(self) -> bool:
        outstanding = any(conn.inflight for conn in self._clients.values())
        if outstanding:
            return False
        gen_done = self.mutations >= self.runs if self.runs else True
        if not gen_done:
            return False
        if self.paths:
            # original seed FILES (lazy Paths) always wait for a client to
            # (re)connect — a mid-replay disconnect must not end a minset
            # with the bulk unserved.  Only requeued/injected BYTE entries
            # (in-flight testcases of dead clients) are treated as lost
            # once every client is gone, as in the reference.
            if any(isinstance(item, Path) for item in self.paths):
                return False
            return self._ever_served and not self._clients
        return True

    # -- result handling (server.h:785-886) --------------------------------
    def handle_result(self, body: bytes) -> None:
        self._account_result(*wire.decode_result(body))

    def _account_result(self, testcase, coverage, result,
                        bucket: str = "") -> None:
        new = coverage - self.coverage
        if new:
            self.coverage |= new
            self._cov_dirty = self._cov_dirty or bool(new - self._restored)
            self.stats.last_cov = time.time()
            self.stats.new_coverage += 1  # same per-testcase semantics as
            self.mutator.on_new_coverage(testcase)  # FuzzLoop's counter
            self.corpus.add(testcase)
            self.events.emit("new-coverage", new_addresses=len(new),
                             total=len(self.coverage), size=len(testcase))
        if self.stats.account(result):
            self._save_crash(testcase, result, bucket)
        elif isinstance(result, OverlayFull):
            # node resource limit, not a finding: requeue ONCE for an
            # honest re-run (ideally on a node with more overlay slots);
            # never saved under crashes/, never bounced forever
            digest = hashlib.blake2b(testcase, digest_size=16).hexdigest()
            if digest not in self._ovf_requeued:
                self._ovf_requeued.add(digest)
                self.paths.append(testcase)

    def _save_crash(self, testcase: bytes, result, bucket: str) -> None:
        """Crash intake: dedup by triage bucket (reported by WTF3 nodes;
        sanitized name otherwise), persist only novel keys, and name the
        file from the digest of the BYTES — the one hex_digest source of
        truth, same as the torn-corpus check — so a malicious or buggy
        node can neither steer the write path nor collide/overwrite
        another node's crash file with a chosen name."""
        if not result.name:
            return
        # the name crossed the WIRE: whitelist-sanitize before any use
        # (events, store journal) — never trusted as a filename anymore
        name = re.sub(r"[^A-Za-z0-9._-]", "_",
                      result.name).lstrip(".")[:200] or "crash-unnamed"
        self.crash_names.add(name)
        key = bucket or name
        if key in self.crash_buckets:
            # known bucket: counted in the stats, but neither persisted
            # nor announced — the dedup half of the crash service
            self.registry.counter("fleet.bucket_dedup").inc()
            return
        self.crash_buckets.add(key)
        digest = hex_digest(testcase)
        self.events.emit("crash", name=name, size=len(testcase),
                         digest=digest, bucket=bucket or None, new=True)
        try:
            if self.store is not None:
                self.store.put(testcase, kind="crash", name=name,
                               bucket=bucket or None)
                if self.crashes_dir:
                    # flat digest-named view for operators/old tooling
                    self.store.link_into(self.crashes_dir, digest)
            elif self.crashes_dir:
                # atomic (tmp+fsync+rename): a kill mid-save must not
                # leave a torn repro under crashes/
                atomic_write_bytes(self.crashes_dir / digest, testcase)
        except (OSError, ValueError) as e:
            log.warning("crash save failed for %r: %s", name, e)
            self.events.emit("error", kind="crash-save",
                             name=name, detail=str(e))

    # -- drain (SIGTERM) ---------------------------------------------------
    def request_drain(self) -> None:
        """Graceful-shutdown request (SIGTERM handler, or any thread):
        stop serving new testcases, give in-flight results `drain_grace`
        seconds to land, persist, notify nodes (BYE on tagged
        connections), and return from run() with `drained` set.  Safe to
        call from a signal handler — it only flips a flag the reactor
        polls."""
        self._draining = True

    def _drain_step(self, now: float) -> bool:
        """True when the drain is complete and the reactor should exit."""
        if self._drain_deadline is None:
            self._drain_deadline = now + self.drain_grace
            outstanding = sum(len(c.inflight)
                              for c in self._clients.values())
            log.warning("drain requested: %d client(s), %d in-flight "
                        "testcase(s), grace %.1fs",
                        len(self._clients), outstanding, self.drain_grace)
            self.events.emit("drain", clients=len(self._clients),
                             inflight=outstanding,
                             grace_seconds=self.drain_grace)
        if not any(c.inflight for c in self._clients.values()):
            return True
        return now > self._drain_deadline

    # -- reactor (server.h:361-598) ----------------------------------------
    def run(self, max_seconds: Optional[float] = None) -> ServerStats:
        """Event loop on `selectors` (epoll on Linux) — unlike the
        reference's select() reactor (server.h:386-389) there is no
        FD_SETSIZE ceiling, so thousands of 1-fd-per-lane nodes work; a
        multiplexed node (wire.encode_hello(n) with n > 1) needs only ONE
        fd for a whole lane batch on top of that."""
        self._listener = wire.listen(self.address)
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._listener, selectors.EVENT_READ)
        deadline = time.time() + max_seconds if max_seconds else None
        restore_sigterm = self._install_sigterm()
        try:
            while True:
                if self.done():
                    break
                now = time.time()
                if self._draining and self._drain_step(now):
                    self.drained = True
                    break
                if deadline and now > deadline:
                    break
                for key, events in self._sel.select(timeout=0.5):
                    sock = key.fileobj
                    if sock is self._listener:
                        conn, _ = self._listener.accept()
                        self._clients[conn] = _Conn()
                        # not writable until the hello names its slot count
                        self._sel.register(conn, selectors.EVENT_READ)
                        continue
                    if sock not in self._clients:
                        continue  # dropped earlier in this pass
                    if events & selectors.EVENT_WRITE:
                        self._feed(sock)
                    if (events & selectors.EVENT_READ
                            and sock in self._clients):
                        self._on_readable(sock)
                now = time.time()
                if self.reclaim_timeout:
                    self._reclaim_silent(now)
                if (self._dirwatch is not None
                        and now - self._dirwatch_last >= 1.0):
                    # throttled: a directory scan per reactor pass would
                    # steal time from serving nodes on a hot master
                    self._dirwatch_last = now
                    injected = []
                    for path in self._dirwatch.poll():
                        try:
                            injected.append(path.read_bytes()[:self.max_len])
                        except OSError:
                            continue  # vanished after the scan
                    # prepend: freshly dropped seeds are served next,
                    # ahead of any undrained initial corpus
                    self.paths.extendleft(reversed(injected))
                self._maybe_print()
                if now - self._last_persist >= self.stats_every:
                    # interval persistence (dirty-flagged: no-op when the
                    # aggregate and cursors are unchanged) — what lets a
                    # restarted master resume client ack cursors
                    self._last_persist = now
                    self._evict_cursors()
                    self._write_coverage()
                    self.fleet_telem.write_exports()
        finally:
            restore_sigterm()
            for sock, conn in list(self._clients.items()):
                # orderly end (budget done / drain): tell v2 nodes not to
                # reconnect-retry against the closing listener
                if conn.tagged:
                    try:
                        wire.send_bye(sock)
                    except OSError:
                        pass
                sock.close()
            self._clients.clear()
            self._sel.close()
            self._sel = None
            self._listener.close()
            self._listener = None
            self._write_coverage(final=True)
            self.fleet_telem.close()
        return self.stats

    def _install_sigterm(self):
        """SIGTERM -> request_drain, main thread only (signal.signal
        raises elsewhere; threaded embedders call request_drain
        directly).  Returns a restore callable for the finally block."""
        import signal
        import threading

        if threading.current_thread() is not threading.main_thread():
            return lambda: None
        previous = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            self.request_drain()

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            return lambda: None
        return lambda: signal.signal(signal.SIGTERM, previous)

    def _reclaim_silent(self, now: float) -> None:
        """Heartbeat-timeout reclaim: a node holding in-flight testcases
        in silence past `reclaim_timeout` is presumed dead (wedged chip,
        half-open TCP after a power cut) — its work goes back to the
        pending deque and the connection drops.  A merely-slow node
        reconnects and keeps serving; its late results are simply lost
        with the closed socket, so nothing double-counts."""
        for sock, conn in list(self._clients.items()):
            if conn.inflight and now - conn.since > self.reclaim_timeout:
                log.warning("reclaiming %d testcase(s) from silent node "
                            "(%.1fs > %.1fs timeout)", len(conn.inflight),
                            now - conn.since, self.reclaim_timeout)
                self._drop(sock, reason="timeout")

    def _load_coverage_state(self) -> None:
        """Resume the delta ack cursors (and the aggregate they imply)
        from a prior master's coverage file: a reconnecting WTF3 node
        whose cursor still matches resumes sparse deltas instead of a
        whole-bitmap resync.  Restored addresses land in `_restored`
        (served/persisted, but corpus admission still re-earns through
        the outputs/ replay).  Best-effort: an unreadable or pre-fleet
        file simply starts fresh."""
        if self.coverage_path is None or not self.coverage_path.exists():
            return
        import json

        from wtf_tpu.fleet.delta import ServerCursor

        try:
            doc = json.loads(self.coverage_path.read_text(encoding="utf-8"))
            cursors = doc.get("cursors", {})
            for cid, state in cursors.items():
                self._cursors[cid] = ServerCursor.from_state(state)
            self._restored = set(int(a) for a in doc.get("addresses", []))
        except (ValueError, KeyError, OSError) as e:
            log.warning("coverage state unusable (%s); starting fresh", e)
            self._cursors = {}
            self._restored = set()
            return
        if self._cursors:
            self.registry.counter("fleet.cursor_resumes").inc(
                len(self._cursors))
            self.events.emit("cursor-resume", clients=len(self._cursors),
                             addresses=len(self._restored))

    def _write_coverage(self, final: bool = False) -> None:
        """Persist the aggregate coverage in the .cov JSON shape
        (reference coverage.cov aggregate, README.md:166; integer
        addresses per the gen_coveragefile_* format) plus the per-client
        delta ack cursors, so campaigns resume/compare offline and a
        restarted master resumes cursors.  Dirty-flagged: an interval
        where nothing changed costs no write.  Best-effort: also runs in
        the reactor's finally block and must not mask an in-flight
        exception."""
        if self.coverage_path is None:
            return
        if not self._cov_dirty and not (final
                                        and not self.coverage_path.exists()):
            return
        import json

        doc = {
            "name": "aggregate",
            "addresses": sorted(self.coverage | self._restored),
        }
        if self._cursors:
            doc["cursors"] = {cid: cur.state()
                              for cid, cur in self._cursors.items()}
        try:
            # atomic (utils/atomicio): a kill mid-write must leave the
            # previous coverage file intact, never a torn JSON — this is
            # the file a resumed/offline analysis reads
            atomic_write_text(self.coverage_path, json.dumps(doc))
            self._cov_dirty = False
            self.registry.counter("fleet.coverage_writes").inc()
        except OSError as e:
            log.warning("coverage.cov write failed: %s", e)
            self.events.emit("error", kind="coverage-write",
                             path=str(self.coverage_path), detail=str(e))

    def _set_writable(self, sock: socket.socket, want: bool) -> None:
        events = selectors.EVENT_READ | (selectors.EVENT_WRITE if want else 0)
        self._sel.modify(sock, events)

    def _feed(self, sock: socket.socket) -> None:
        conn = self._clients[sock]
        if self._draining:
            # drain: no new work leaves the master; the node is told to
            # go away for good (BYE) instead of reconnect-retrying
            self._drop(sock, bye=True)
            return
        batch: List[bytes] = []
        while len(batch) < conn.slots:
            testcase = self.get_testcase()
            if testcase is None:
                break
            batch.append(testcase)
        if not batch:
            # no work at all (budget exhausted / seeds drained): close the
            # idle client now — a batch node would otherwise block on this
            # socket while the master waits for its siblings (tail deadlock)
            self._drop(sock, bye=True)
            return
        try:
            if conn.mux:
                wire.send_work(sock, wire.encode_batch(batch), conn.tagged)
            else:
                wire.send_work(sock, batch[0], conn.tagged)
            conn.inflight = batch  # in-flight until their results return
            conn.since = time.time()
            self._ever_served = True
            self._set_writable(sock, False)
        except OSError:
            # undelivered: requeue (budget stays consumed — the requeued
            # entries re-serve from paths without a new mutation, so the
            # campaign executes exactly `runs` testcases as long as any
            # client remains connected; elasticity, server.h:534-544)
            self._clients[sock].inflight = []
            self._drop(sock)
            self.paths.extendleft(reversed(batch))

    def _on_readable(self, sock: socket.socket) -> None:
        conn = self._clients[sock]
        try:
            body = wire.recv_msg(sock)
        except (OSError, ValueError):
            body = None
        if body is None:
            self._drop(sock)
            return
        n_slots = wire.decode_hello(body)
        if n_slots is not None:
            conn.slots = max(1, n_slots)
            conn.mux = conn.slots > 1
            conn.tagged = wire.hello_is_tagged(body)
            client_id = wire.hello_client_id(body)
            if client_id is not None:
                conn.delta = True
                conn.client_id = client_id.hex()
                cursor = self._cursor_for(conn)
                try:
                    # name the ack cursor we hold for this identity so a
                    # reconnecting node resumes sparse deltas (or learns
                    # it must resync) BEFORE any work flows
                    wire.send_msg(sock, wire.encode_cursor(
                        *cursor.summary()))
                except OSError:
                    self._drop(sock)
                    return
            if not conn.inflight:
                self._set_writable(sock, True)  # greeted: open for work
            return
        if conn.delta and body and body[0] == wire.TAG_TELEM:
            # observability frame: no slot accounting, no writability
            # change — it rides BETWEEN work exchanges.  Malformed telem
            # is dropped without dropping the node (it carries no
            # campaign state, unlike a malformed result frame).
            self._handle_telem(conn, body[1:])
            return
        try:
            # decode EVERYTHING before accounting ANYTHING: a malformed
            # tail in a mux batch must not leave already-counted results
            # that then get requeued (double execution, stat skew)
            if conn.delta:
                items = self._decode_delta_frame(conn, body)
            elif conn.mux:
                items = [wire.decode_result(b) + ("",)
                         for b in wire.decode_batch(body)]
            else:
                items = [wire.decode_result(body) + ("",)]
        except (ValueError, IndexError, struct.error) as e:
            # desynced/malformed result frame: a broken node must not
            # take the master down — drop it, requeue its in-flight work.
            # Loudly: if every node trips this, the fleet has a wire
            # mismatch and the operator needs to see it.
            log.warning("dropping node (malformed result frame: %r); "
                        "requeueing %d in-flight testcase(s)",
                        e, len(conn.inflight))
            self.events.emit("error", kind="malformed-frame",
                             detail=repr(e), requeued=len(conn.inflight))
            self._drop(sock)
            return
        for item in items:
            self._account_result(*item)
        conn.inflight = []
        self._set_writable(sock, True)

    def _handle_telem(self, conn: _Conn, payload: bytes) -> None:
        """One TAG_TELEM frame: merge the node's cumulative snapshot into
        the fleet aggregate, keyed by its WTF3 client identity.  The seq
        check inside the aggregator makes re-sent frames (reconnect
        replays, reclaim races) free of double-counting."""
        try:
            seq, snapshot, events = wire.decode_telem(payload)
        except (ValueError, KeyError, struct.error,
                UnicodeDecodeError) as e:
            self.registry.counter("fleet.telem_errors").inc()
            self.events.emit("error", kind="malformed-telem",
                             detail=repr(e))
            return
        applied = self.fleet_telem.apply(conn.client_id, seq, snapshot,
                                         events)
        self.registry.counter("fleet.telem_frames").inc()
        if not applied:
            self.registry.counter("fleet.telem_duplicates").inc()

    def _cursor_for(self, conn: _Conn):
        from wtf_tpu.fleet.delta import ServerCursor

        cursor = self._cursors.get(conn.client_id)
        if cursor is None:
            cursor = self._cursors[conn.client_id] = ServerCursor()
        cursor.touch()
        return cursor

    def _evict_cursors(self) -> None:
        """Drop the least-recently-active cursors over `cursor_cap`,
        skipping identities with a live connection.  An evicted node
        that reconnects sees a fresh cursor and performs one
        whole-bitmap resync — slower, never wrong."""
        over = len(self._cursors) - self.cursor_cap
        if over <= 0:
            return
        live = {conn.client_id for conn in self._clients.values()
                if conn.client_id}
        victims = sorted(
            (cid for cid in self._cursors if cid not in live),
            key=lambda cid: self._cursors[cid].last_seen)[:over]
        for cid in victims:
            del self._cursors[cid]
        if victims:
            self._cov_dirty = True
            self.registry.counter("fleet.cursor_evictions").inc(
                len(victims))

    def _decode_delta_frame(self, conn: _Conn, body: bytes) -> List[tuple]:
        """One WTF3 upstream frame -> [(testcase, addresses, result,
        bucket)].  Applying a delta mutates only the CURSOR (idempotent
        set-union state); master accounting happens strictly after the
        whole frame decoded+mapped, so a malformed tail still accounts
        nothing and the re-served testcases re-send their bits."""
        if not body or body[0] != wire.TAG_COVDELTA:
            raise ValueError("untagged frame on a delta connection")
        payload = body[1:]
        bodies = wire.decode_batch(payload) if conn.mux else [payload]
        decoded = [wire.decode_result_delta(b) for b in bodies]
        cursor = self._cursor_for(conn)
        items = []
        changed = False
        for testcase, delta, result, bucket in decoded:
            if delta.full:
                self.registry.counter("fleet.full_resyncs").inc()
            changed = changed or delta.full or bool(delta.pairs) \
                or bool(delta.addrs)
            items.append((testcase, cursor.apply(delta), result, bucket))
        self.registry.counter("fleet.delta_frames").inc(len(bodies))
        self.registry.counter("fleet.delta_bytes").inc(len(body))
        if changed:
            self._cov_dirty = True
        return items

    def _drop(self, sock: socket.socket, bye: bool = False,
              reason: str = "drop") -> None:
        # a dying client's in-flight testcases are re-served to others —
        # the reclaim that makes node death cost retransmission, not work
        conn = self._clients.pop(sock, None)
        if conn is not None and conn.inflight:
            self.paths.extendleft(reversed(conn.inflight))
            self.registry.counter("dist.reclaimed").inc(len(conn.inflight))
            self.events.emit("reclaim", count=len(conn.inflight),
                             reason=reason)
        if bye and conn is not None and conn.tagged:
            # orderly goodbye: a v2 node stops reconnect-retrying
            try:
                wire.send_bye(sock)
            except OSError:
                pass
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError):
            pass
        sock.close()

    def _maybe_print(self) -> None:
        self.stats.maybe_heartbeat(
            self.events, self.registry,
            lambda: self.stats.line(len(self.coverage), len(self.corpus),
                                    len(self._clients)),
            every=self.stats_every, print_stats=self.print_stats,
            nodes=len(self._clients))
