"""Triage smoke (`make triage-smoke`, wired into `make verify`).

A tiny end-to-end pass over the batched triage engine (wtf_tpu/triage)
on demo_tlv, CPU-only, no hardware:

  minimize  a seeded crasher (junk records around a type-3 stack smash)
            must shrink to the known-minimal 34-byte reproducer of the
            SAME crash bucket — header + zeroed filler + the 8 bytes
            that become the smashed return address;
  distill   the kept minset must be a subset of the input corpus (by
            content digest) with the full corpus' aggregate coverage
            (the set-cover invariant; distill() asserts equality).

Exit 0 = all held; any assertion prints and exits 1.
"""

from __future__ import annotations

import sys
import tempfile
from pathlib import Path

# the canonical demo_tlv crasher family (tests/test_triage.py shares the
# layout): type-3 record copies 32 bytes into an 8-byte stack buffer —
# offsets 16..23 smash the saved rbp, 24..31 the return address
SMASH = bytes([3, 32]) + bytes(range(65, 89)) + b"\x41" * 8
CRASHER = b"\x01\x02XY" + SMASH + b"\x01\x03ZZZ"
MINIMAL = bytes([3, 32]) + bytes(24) + b"\x41" * 8

SEEDS = {
    "a": b"\x01\x02XY",
    "b": b"\x01\x03ABC",
    "c": b"\x02\x08QQQQQQQQ",
    "d": b"\x01\x02XY\x02\x08WWWWWWWW",
    "e": b"\x03\x04abcd",
}


def main() -> int:
    from wtf_tpu.cli import main as cli_main
    from wtf_tpu.utils.hashing import hex_digest

    with tempfile.TemporaryDirectory(prefix="wtf-triage-smoke-") as td:
        root = Path(td)
        crash = root / "crash.bin"
        crash.write_bytes(CRASHER)
        target = root / "t"
        (target / "inputs").mkdir(parents=True)
        for name, data in SEEDS.items():
            (target / "inputs" / name).write_bytes(data)

        # -- minimize leg --------------------------------------------
        rc = cli_main(["triage", "minimize", "--name", "demo_tlv",
                       "--input", str(crash), "--lanes", "16",
                       "--limit", "20000"])
        assert rc == 0, f"minimize rc={rc}"
        minimized = (root / "crash.bin.min").read_bytes()
        assert len(minimized) < len(CRASHER), (
            f"reproducer did not shrink: {len(minimized)} vs "
            f"{len(CRASHER)}")
        assert minimized == MINIMAL, (
            f"not the known-minimal reproducer: {minimized.hex()}")
        print(f"[triage-smoke] minimize: {len(CRASHER)} -> "
              f"{len(minimized)} bytes (known-minimal, same bucket)")

        # -- distill leg ---------------------------------------------
        rc = cli_main(["triage", "distill", "--name", "demo_tlv",
                       "--target", str(target), "--lanes", "16",
                       "--limit", "20000"])
        assert rc == 0, f"distill rc={rc}"
        corpus_digests = {hex_digest(d) for d in SEEDS.values()}
        kept = sorted((target / "outputs").iterdir())
        assert kept, "distill kept nothing"
        assert len(kept) < len(SEEDS), (
            f"minset did not shrink: {len(kept)}/{len(SEEDS)}")
        for p in kept:
            digest = hex_digest(p.read_bytes())
            assert digest in corpus_digests, (
                f"minset member {p.name} is not in the input corpus")
            assert p.name == digest, f"non-digest-named output {p.name}"
        print(f"[triage-smoke] distill: kept {len(kept)}/{len(SEEDS)} "
              "seeds, minset ⊆ corpus, coverage preserved")
    print("[triage-smoke] PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
