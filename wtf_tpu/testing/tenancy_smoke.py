"""Tenancy smoke (`make tenancy-smoke`, wired into `make verify`).

A fast end-to-end pass over the multi-tenant campaign stack
(wtf_tpu/tenancy) on CPU, no hardware:

  isolation   a demo_tlv campaign run as a lane-subset of a mixed
              demo_tlv+demo_kernel batch must be bit-identical — local
              coverage plane, edge plane, corpus stream, crash buckets —
              to the same campaign run alone, and BOTH tenants of the
              mixed batch must find coverage (the heterogeneous dispatch
              really executes both base images);
  preemption  the `wtf-tpu sched` drill: tenant A is checkpointed
              mid-campaign at a quantum boundary, its lanes backfilled
              with tenant B, and A resumed later — A's final corpus
              manifest, crash buckets and coverage planes must equal an
              uninterrupted run of the same job.

Exit 0 = all held; any assertion prints and exits 1.
"""

from __future__ import annotations

import json
import sys
import tempfile
from pathlib import Path

SEED_TLV = b"\x01\x04AAAA\x02\x08BBBBBBBB"
SEED_KERN = b"hello-world-123"
LIMIT = 50_000


def _runtime_cfg():
    return [("alice", "demo_tlv", 4, "tlv", 42, SEED_TLV),
            ("bob", "demo_kernel", 4, "mangle", 1337, SEED_KERN)]


def _run_mixed(cfg, batches):
    """Per-tenant fingerprints of a mixed MultiTenantLoop run."""
    from wtf_tpu.harness.targets import Targets, load_builtin_targets
    from wtf_tpu.tenancy.backend import TenantSpec, create_tenancy_backend
    from wtf_tpu.tenancy.loop import MultiTenantLoop, TenantRuntime
    from wtf_tpu.tenancy.state import extract_bits

    load_builtin_targets()
    targets = Targets.instance()
    specs = [TenantSpec(n, targets.get(t), targets.get(t).snapshot(), q)
             for n, t, q, _m, _s, _seed in cfg]
    backend = create_tenancy_backend(specs, sum(c[2] for c in cfg),
                                     limit=LIMIT)
    backend.initialize()
    for i, s in enumerate(specs):
        with backend.tenant_context(i):
            s.target.init(backend)
    runtimes, lane_lo = [], 0
    for i, (n, _t, q, m, seed, corpus_seed) in enumerate(cfg):
        rt = TenantRuntime(specs[i], seed=seed, runs=1 << 20,
                           mutator_name=m, max_len=256, lane_lo=lane_lo)
        rt.corpus.add(corpus_seed)
        runtimes.append(rt)
        lane_lo += q
    loop = MultiTenantLoop(backend, runtimes, stats_every=1e9)
    for _ in range(batches):
        loop.run_one_batch()
    out = {}
    for i, rt in enumerate(runtimes):
        cov, edge = backend.tenant_coverage_state(i)
        entries = backend.runner.cache.tenant_entries(i)
        local = extract_bits(cov, [e[0] for e in entries])
        out[rt.name] = {
            "local_cov": local.tobytes(),
            "edge": edge.tobytes(),
            "corpus": list(rt.corpus),
            "buckets": sorted(rt.crash_buckets),
            "covbits": int(sum(bin(int(w)).count("1") for w in cov)),
        }
    return out


def _ckpt_state(directory: Path) -> dict:
    from wtf_tpu.resume.checkpoint import load_campaign

    state, _ = load_campaign(directory)
    return state


def main() -> int:
    cfg = _runtime_cfg()

    # -- isolation leg ---------------------------------------------------
    solo = _run_mixed(cfg[:1], batches=3)
    mixed = _run_mixed(cfg, batches=3)
    for name in ("alice", "bob"):
        assert mixed[name]["covbits"] > 0, f"{name}: no coverage in mix"
    for key in ("local_cov", "edge", "corpus", "buckets"):
        assert solo["alice"][key] == mixed["alice"][key], (
            f"isolation broken: alice {key} differs between solo and "
            "mixed batch")
    print(f"[tenancy-smoke] isolation: mixed batch == solo "
          f"(alice cov {mixed['alice']['covbits']} bits, "
          f"bob cov {mixed['bob']['covbits']} bits)")

    # -- preemption leg (`wtf-tpu sched` drill) --------------------------
    from wtf_tpu.cli import main as cli_main

    with tempfile.TemporaryDirectory(prefix="wtf-tenancy-smoke-") as td:
        root = Path(td)
        (root / "inputs_a").mkdir()
        (root / "inputs_a" / "seed").write_bytes(SEED_TLV)
        (root / "inputs_b").mkdir()
        (root / "inputs_b" / "seed").write_bytes(SEED_KERN)
        jobs = {"jobs": [
            {"name": "alice", "target": "demo_tlv", "lanes": 8,
             "runs": 48, "seed": 42, "mutator": "tlv", "max_len": 256,
             "inputs": str(root / "inputs_a")},
            {"name": "bob", "target": "demo_kernel", "lanes": 8,
             "runs": 32, "seed": 7, "mutator": "mangle", "max_len": 256,
             "inputs": str(root / "inputs_b")},
        ]}
        (root / "jobs.json").write_text(json.dumps(jobs))
        # lanes=8 fits ONE job at a time: with quantum=2 the scheduler
        # must preempt alice for bob and resume her later
        rc = cli_main(["sched", "--jobs", str(root / "jobs.json"),
                       "--workdir", str(root / "sched"),
                       "--lanes", "8", "--quantum", "2",
                       "--limit", str(LIMIT),
                       "--telemetry-dir", str(root / "tele")])
        assert rc in (0, 2), f"sched rc={rc}"
        events = [json.loads(line) for line in
                  (root / "tele" / "events.jsonl").read_text().splitlines()]
        kinds = {e["type"] for e in events}
        assert "sched-preempt" in kinds, "no preemption happened"
        completes = [e["tenant"] for e in events
                     if e["type"] == "sched-complete"]
        assert sorted(completes) == ["alice", "bob"], (
            f"jobs did not both complete: {completes}")
        resumes = [e for e in events if e["type"] == "tenant-resume"]
        assert resumes, "preempted job never resumed from its checkpoint"

        # parity: the preempted-and-resumed alice must end with the SAME
        # corpus manifest / crash buckets / coverage planes as one
        # uninterrupted run of the identical job
        from wtf_tpu.tenancy.sched import Job, Scheduler

        straight = Scheduler(
            [Job(name="alice", target="demo_tlv", lanes=8, runs=48,
                 seed=42, mutator="tlv", max_len=256,
                 inputs=str(root / "inputs_a"))],
            n_lanes=8, workdir=root / "straight", limit=LIMIT,
            quantum=1 << 20)
        straight.run()
        got = _ckpt_state(root / "sched" / "alice" / "checkpoint")
        want = _ckpt_state(root / "straight" / "alice" / "checkpoint")
        for key in ("corpus_manifest", "crash_buckets", "batches"):
            assert got[key] == want[key], (
                f"preemption parity broken: {key} differs\n"
                f"  scheduled: {got[key]}\n  straight:  {want[key]}")
        for plane in ("cov", "edge"):
            assert (got["coverage"][plane]
                    == want["coverage"][plane]).all(), (
                f"preemption parity broken: {plane} plane differs")
        n_pre = sum(1 for e in events if e["type"] == "sched-preempt")
        print(f"[tenancy-smoke] preemption: {n_pre} preemption(s), "
              f"both jobs complete, resumed alice bit-identical to the "
              f"uninterrupted run ({len(got['corpus_manifest'])} corpus "
              f"entries, {len(got['crash_buckets'])} crash buckets)")
    print("[tenancy-smoke] PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
