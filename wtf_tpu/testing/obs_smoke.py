"""Observability smoke: the fleet telemetry plane + the trace timeline.

Two legs, both on production code paths:

  fleet    a real master (dist/server.py reactor) and 4 WTF3 sim
           clients (fleet/soak.py) with PRIVATE metric registries,
           scripted socket faults and scripted verbatim-duplicate
           TAG_TELEM frames.  Asserts the aggregated fleet snapshot is
           byte-equal to the serial sum (merge_snapshots) of the
           per-node snapshots the clients last sent — reconnects and
           re-sent frames must not double-count — and that the export
           surface (status.json / telemetry.prom / fleet-telem.jsonl)
           landed.

  local    one short demo_tlv megachunk campaign through the real CLI
           with --telemetry-dir and --trace-out from the SAME run.
           Asserts `wtf-tpu status` renders (human and --json), and the
           Chrome-trace JSON is schema-valid with >=1 fenced device
           span and >=1 megachunk-window span.

Exit 0 and a PASS line on success; any broken invariant raises.
"""

from __future__ import annotations

import json
import logging
import sys
import tempfile
import threading
from pathlib import Path


def _fleet_leg(tmp: Path, clients: int = 4, runs_per_client: int = 24,
               seed: int = 0x0B5) -> dict:
    from wtf_tpu.dist.server import Server
    from wtf_tpu.fleet.soak import CoverageModel, SimClient, _drive
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.mutator import ByteMutator
    from wtf_tpu.telemetry import Registry
    from wtf_tpu.telemetry.metrics import merge_snapshots

    import random

    export = tmp / "export"
    address = f"unix://{tmp}/obs.sock"
    rng = random.Random(seed)
    seeds = [bytes(rng.randrange(256) for _ in range(32))]
    runs = clients * runs_per_client
    corpus = Corpus(outputs_dir=tmp / "outputs", rng=rng)
    server = Server(address, ByteMutator(rng, 64), corpus,
                    crashes_dir=tmp / "crashes", runs=runs,
                    coverage_path=tmp / "coverage.cov",
                    stats_every=2.0, telemetry_dir=export)
    server.paths = list(seeds)
    server_thread = threading.Thread(target=server.run,
                                     kwargs={"max_seconds": 300.0})
    server_thread.start()

    model = CoverageModel(common=200)
    sims = []
    for i in range(clients):
        # every client: telem each run, every 3rd frame sent twice; the
        # first takes a pre-send drop (reclaim), the second a post-send
        # reset (pure reconnect) — the chaos dial of the fleet soak
        faults = {}
        if i == 0:
            faults[2] = "drop"
        elif i == 1:
            faults[3] = "reset"
        sims.append(SimClient(address, model, "delta", seed ^ (i << 8),
                              Registry(), faults=faults,
                              telem_every=1, telem_dup_every=3))
    _drive(sims)
    server_thread.join(timeout=300.0)
    assert not server_thread.is_alive(), "master did not finish"

    fleet = server.fleet_telem
    assert len(fleet.nodes) == clients, \
        f"aggregator saw {len(fleet.nodes)} nodes, expected {clients}"
    dups_sent = sum(s.telem_dups_sent for s in sims)
    assert dups_sent > 0, "no duplicate frames were scripted"
    # a duplicate riding a socket a scripted fault then kills can be
    # lost with its original (symmetric, harmless), so the bar is that
    # the seq-dedup path FIRED — tests/test_obs.py pins exact counts
    # fault-free — and that it never misfired into an error
    assert fleet.duplicates >= 1, \
        "scripted duplicate frames were not dropped by sequence number"
    assert server.registry.counter("fleet.telem_errors").value == 0, \
        "telemetry frames were rejected as malformed"
    faults_hit = sum(s.drops + s.resets for s in sims)
    assert faults_hit >= 2, "scripted socket faults did not fire"

    # THE tentpole exactness bar: the aggregate == the serial sum of
    # what the nodes last reported, byte-equal after canonical dumps
    want = merge_snapshots(
        s.last_telem for s in sims if s.last_telem is not None)
    got = fleet.fleet_snapshot()
    assert json.dumps(got, sort_keys=True) == \
        json.dumps(want, sort_keys=True), \
        ("fleet aggregate diverged from the serial sum of node "
         f"snapshots: {len(got)} vs {len(want)} metrics")
    execs = sum(int((s.last_telem.get("campaign.testcases") or
                     {}).get("value", 0)) for s in sims if s.last_telem)
    assert execs > 0, "node snapshots carried no testcase counters"

    status = json.loads((export / "status.json").read_text())
    assert status["kind"] == "fleet" and status["nodes"] == clients
    assert len(status["per_node"]) == clients
    prom = (export / "telemetry.prom").read_text()
    assert prom.startswith("# TYPE wtf_") and "wtf_campaign_testcases" \
        in prom, "prometheus export malformed"
    stream = [json.loads(line) for line in
              (export / "fleet-telem.jsonl").read_text().splitlines()]
    assert len(stream) == fleet.frames, \
        f"stream has {len(stream)} records, aggregator applied " \
        f"{fleet.frames}"
    return {"nodes": clients, "frames": fleet.frames,
            "duplicates_dropped": fleet.duplicates,
            "faults": faults_hit, "fleet_execs": execs}


def _local_leg(tmp: Path) -> dict:
    from wtf_tpu.cli import main as cli_main

    camp = tmp / "campaign"
    trace_path = tmp / "trace.json"
    rc = cli_main(["campaign", "--name", "demo_tlv", "--backend", "tpu",
                   "--runs", "64", "--lanes", "8", "--limit", "200",
                   "--mutator", "devmangle", "--megachunk", "2",
                   "--seed", "7", "--telemetry-dir", str(camp),
                   "--trace-out", str(trace_path)])
    assert rc == 0, f"campaign exited {rc}"

    status = json.loads((camp / "status.json").read_text())
    assert status["kind"] == "campaign" and status["line"], \
        "campaign status.json missing the heartbeat line"
    assert cli_main(["status", str(camp)]) == 0
    assert cli_main(["status", str(camp), "--json"]) == 0

    doc = json.loads(trace_path.read_text())
    events = doc["traceEvents"]
    assert isinstance(events, list) and events, "empty trace"
    for ev in events:
        assert ev["ph"] in ("X", "i") and ev["ts"] >= 0
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
    device_spans = [ev for ev in events
                    if ev["ph"] == "X" and ev["cat"] == "device"]
    window_spans = [ev for ev in events
                    if ev["name"] == "megachunk-window"]
    assert device_spans, "no fenced device span in the trace"
    assert window_spans, "no megachunk-window span in the trace"
    return {"trace_events": len(events),
            "device_spans": len(device_spans),
            "window_spans": len(window_spans)}


def main(argv=None) -> int:
    logging.getLogger("wtf_tpu").setLevel(logging.ERROR)
    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        fleet = _fleet_leg(tmp)
        local = _local_leg(tmp)
    report = {**fleet, **local}
    print(json.dumps(report, indent=1))
    print(f"obs smoke PASS ({report['nodes']} nodes aggregate == serial "
          f"sum with {report['duplicates_dropped']} duplicate(s) "
          f"dropped; trace valid with {report['device_spans']} device + "
          f"{report['window_spans']} window span(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
