"""`make chaos-smoke`: the seeded end-to-end recovery soak.

Two legs, both against the real seams with a deterministic FaultPlan:

  dist leg        master + one emu node over a unix socket; the node's
                  sockets take scheduled resets/partial frames
                  mid-campaign.  Asserts >=1 reconnect, >=1 reclaim, and
                  ZERO lost testcases: the master accounts exactly
                  seeds + runs results, its corpus dedup is exact.
  resume leg      a seeded demo_tlv devmangle campaign on the batched
                  tpu backend checkpoints every batch and is killed at a
                  batch boundary; the NEWEST checkpoint is then torn
                  (truncated) so the resume must detect the digest
                  mismatch and fall back to `.prev`.  Asserts the
                  resumed run's final coverage, crash set, corpus and
                  stats are bit-identical to an uninterrupted reference.

Exit 0 only when every assertion held.  Run via
`python -m wtf_tpu.testing.chaos_smoke [seed]`.
"""

from __future__ import annotations

import os
import random
import sys
import tempfile
import threading
from pathlib import Path

SEED = 0xC4A05


def _dist_leg(seed: int) -> dict:
    from wtf_tpu.backend import create_backend
    from wtf_tpu.dist import Client, Server
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.mutator import TlvStructureMutator
    from wtf_tpu.harness import demo_tlv
    from wtf_tpu.telemetry import Registry
    from wtf_tpu.testing.faultinject import (
        FaultPlan, PARTIAL_SEND, RESET, chaos_dialing,
    )

    runs = 24
    with tempfile.TemporaryDirectory() as tmp:
        address = f"unix://{tmp}/master.sock"
        rng = random.Random(seed)
        corpus = Corpus(outputs_dir=Path(tmp) / "outputs", rng=rng)
        seeds = [b"\x01\x04AAAA\x02\x08BBBBBBBB", b"\x02\x02XY"]
        server = Server(address, TlvStructureMutator(rng, 128), corpus,
                        crashes_dir=Path(tmp) / "crashes", runs=runs,
                        coverage_path=Path(tmp) / "coverage.cov")
        server.paths = list(seeds)
        thread = threading.Thread(target=server.run,
                                  kwargs={"max_seconds": 120})
        thread.start()
        backend = create_backend("emu", demo_tlv.build_snapshot())
        backend.initialize()
        registry = Registry()
        # scripted, not rate-based: the node's op pattern is
        # send(hello)=0 then recv,recv,send per testcase, so sends land
        # on ops ≡ 0 (mod 3).  Socket 0 resets on its 4th result send
        # (master holds in-flight -> reclaim); the reconnect's socket
        # tears a result frame halfway (partial send -> torn frame on
        # the master, second reclaim); the next reconnect runs clean.
        plan = FaultPlan([{9: RESET}, {6: PARTIAL_SEND}, {}, {}, {}],
                         delay_secs=0.002)
        with chaos_dialing(plan):
            client = Client(backend, demo_tlv.TARGET, address,
                            registry=registry, max_retry_secs=30.0,
                            retry_rng=random.Random(seed ^ 0x5A))
            served = client.run()
        thread.join(timeout=120)
        assert not thread.is_alive(), "master did not finish"
        expected = len(seeds) + runs
        got = server.stats.testcases
        assert got == expected, \
            f"lost testcases: master accounted {got}, expected {expected}"
        assert server.mutations == runs, server.mutations
        retries = registry.counter("dist.retries").value
        reclaimed = server.registry.counter("dist.reclaimed").value
        assert retries >= 1, "chaos plan produced no reconnect"
        assert reclaimed >= 1, "chaos plan produced no reclaim"
        # exact server-side dedup: outputs/ is content-addressed and
        # every file's digest matches its name
        from wtf_tpu.utils.hashing import hex_digest

        for p in (Path(tmp) / "outputs").iterdir():
            assert hex_digest(p.read_bytes()) == p.name, p
        return {"served": served, "accounted": got, "retries": retries,
                "reclaimed": reclaimed, "faults": len(plan.fired)}


def _resume_leg(seed: int) -> dict:
    import numpy as np

    from wtf_tpu.analysis.trace import build_tlv_campaign
    from wtf_tpu.resume import load_campaign, restore_campaign
    from wtf_tpu.testing.faultinject import fuzz_until_killed, tear_file

    lanes, batches = 8, 4
    runs = lanes * batches
    build = dict(n_lanes=lanes, mutator="devmangle", limit=20_000,
                 seed=seed & 0xFFFF, chunk_steps=128, overlay_slots=16)

    # uninterrupted reference
    ref = build_tlv_campaign(**build)
    ref.fuzz(runs)
    ref_state = (ref._coverage(), sorted(ref.corpus.digests),
                 sorted(ref.crash_names), ref.stats.testcases,
                 np.asarray(ref.backend.coverage_state()[1]).sum())

    with tempfile.TemporaryDirectory() as tmp:
        ckpt = Path(tmp) / "checkpoint"
        victim = build_tlv_campaign(**build)
        victim.checkpoint_dir = ckpt
        victim.checkpoint_every = 1
        fuzz_until_killed(victim, runs, kill_at_batch=2)
        # the kill also tore the newest checkpoint: the loader must
        # reject it by digest and fall back to .prev (batch 1)
        tear_file(ckpt / "checkpoint.json")
        state, fell_back = load_campaign(ckpt)
        assert fell_back, "torn newest checkpoint was not detected"
        resumed = build_tlv_campaign(**build)
        resumed.checkpoint_dir = ckpt
        resumed.checkpoint_every = 1
        batch = restore_campaign(resumed, state, ckpt)
        assert batch == 1, batch
        resumed.fuzz(runs)
        res_state = (resumed._coverage(), sorted(resumed.corpus.digests),
                     sorted(resumed.crash_names), resumed.stats.testcases,
                     np.asarray(resumed.backend.coverage_state()[1]).sum())
        assert res_state == ref_state, \
            f"resume parity broken:\n ref {ref_state}\n got {res_state}"
        return {"coverage": ref_state[0], "corpus": len(ref_state[1]),
                "resumed_from_batch": batch, "fell_back": fell_back}


def main(argv=None) -> int:
    seed = int((argv or sys.argv[1:] or [SEED])[0])
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    # same persistent compile cache the test suite uses: the resume leg
    # compiles the demo_tlv chunk executor, ~40s cold on a 1-core box
    jax.config.update("jax_compilation_cache_dir",
                      os.path.expanduser("~/.cache/wtf_tpu_xla"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    print(f"chaos-smoke seed={seed:#x}")
    dist = _dist_leg(seed)
    print(f"dist leg OK: {dist}")
    res = _resume_leg(seed)
    print(f"resume leg OK: {res}")
    print("chaos-smoke PASS (>=1 reconnect, >=1 reclaim, torn-checkpoint "
          "fallback, zero lost testcases, resume parity)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
