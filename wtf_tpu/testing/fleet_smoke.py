"""`make fleet-smoke`: the fast fleet-tier end-to-end check.

A small (but still ≥64-client) run of the real soak harness
(wtf_tpu/fleet/soak): simulated clients over the real WTF2/WTF3 wire —
master reactor, MasterLink reconnects, delta cursors, the
content-addressed store — with scripted result-frame drops and
post-send resets.  Asserts zero lost testcases, aggregate coverage
byte-identical to a serial replay (persisted coverage.cov included),
and coverage wire bytes ≥10x smaller than the whole-bitmap exchange,
then fsck's the store it just filled.

Exit 0 only when every assertion held.  Run via
`python -m wtf_tpu.testing.fleet_smoke [seed]`.
"""

from __future__ import annotations

import json
import logging
import sys
import tempfile
from pathlib import Path

SEED = 0xF1EE7


def main(argv=None) -> int:
    seed = int((argv or sys.argv[1:] or [SEED])[0])
    # the scripted resets produce reconnect warnings by design; keep the
    # smoke's stdout to the report
    logging.getLogger("wtf_tpu").setLevel(logging.ERROR)
    from wtf_tpu.fleet.soak import run_soak
    from wtf_tpu.fleet.store import FleetStore

    print(f"fleet-smoke seed={seed:#x}")
    with tempfile.TemporaryDirectory() as tmp:
        report = run_soak(tmp, clients=64, runs_per_client=40,
                          threads=8, seed=seed, min_ratio=10.0)
        # the store the soak filled must fsck clean (RUNBOOK drill:
        # "recover the corpus store after a torn write" runs the same
        # verify with repair=True)
        fsck = FleetStore(Path(tmp) / "store").verify()
        assert not fsck["torn"] and not fsck["missing"], fsck
        report["store_fsck_ok"] = fsck["ok"]
    print(json.dumps(report, indent=1))
    print("fleet-smoke PASS (zero lost, aggregate == serial replay, "
          f"delta {report['delta_ratio']}x smaller, store fsck clean)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
