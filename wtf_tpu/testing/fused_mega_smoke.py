"""Fused-megachunk smoke (`make fused-mega-smoke`, wired into
`make verify`).

PR 19's three window-level bars, CPU-only, interpret mode, no hardware:

  parity      a devmangle campaign through megachunk windows whose
              quiesce body is the Pallas fused kernel + bounded resume
              (fused_step=on) must be bit-identical to the XLA-ladder
              window campaign at equal seeds — aggregate coverage/edge
              bitmap bytes, corpus digests, crash buckets, every
              counter — and must actually dispatch the kernel
              (device.fused_window_rounds > 0), with the donation
              bookkeeping exact (bytes-saved = rounds x aliased plane
              bytes);
  occupancy   >= 0.95 of the fused campaign's retired instructions
              retire INSIDE the kernel (device.fused_steps /
              device.instructions) — the windows run the kernel, not
              the park-resume path;
  donation    `run_megachunk_rules` is clean: the jaxpr kernel census
              matches the budgets.json `megachunk_window_fused` pin,
              every pallas_call output is aliased to its operand, and
              every donated machine/aggregate leaf is aliased in the
              compiled window executable (zero copy-through).

Exit 0 = all held; any assertion prints and exits 1.
"""

from __future__ import annotations

import sys


def _parity_and_occupancy_leg() -> None:
    from wtf_tpu.analysis.trace import build_tlv_campaign
    from wtf_tpu.utils.hashing import hex_digest

    def campaign(mode):
        loop = build_tlv_campaign(
            mutator="devmangle", seed=0x5EED, megachunk=3, n_lanes=4,
            limit=10_000, chunk_steps=128, overlay_slots=16,
            fused_step=mode)
        # 8 batches: finds land in IN-GRAPH batches, so the find-stop
        # slab seam — where fused/ladder skew would surface — is hit
        loop.fuzz(runs=4 * 8)
        cov, edge = loop.backend.coverage_state()
        return loop, {
            "cov": cov.tobytes(), "edge": edge.tobytes(),
            "corpus": [hex_digest(d) for d in loop.corpus],
            "buckets": sorted(loop.crash_buckets),
            "testcases": loop.stats.testcases,
            "crashes": loop.stats.crashes,
            "timeouts": loop.stats.timeouts,
        }

    ladder, fp_ladder = campaign("off")
    fused, fp_fused = campaign("on")
    for key in fp_ladder:
        assert fp_fused[key] == fp_ladder[key], (
            f"fused window diverged from the ladder window on {key}")
    reg = fused.registry
    rounds = int(reg.counter("device.fused_window_rounds").value)
    assert rounds > 0, "fused campaign never dispatched the kernel"
    assert int(ladder.registry.counter(
        "device.fused_window_rounds").value) == 0
    saved = int(reg.counter("device.fused_window_bytes_saved").value)
    per = fused.backend._fused_alias_bytes()
    assert saved == rounds * per, (
        f"donation bytes-saved {saved} != {rounds} rounds x {per} "
        f"aliased plane bytes")
    print(f"[fused-mega-smoke] fused-window parity held "
          f"({fp_ladder['testcases']} testcases, {rounds} kernel "
          f"dispatches, {saved} donated bytes kept in place)")

    instr = int(reg.counter("device.instructions").value)
    in_kernel = int(reg.counter("device.fused_steps").value)
    occ = in_kernel / max(instr, 1)
    print(f"[fused-mega-smoke] in-window occupancy {occ:.4f} "
          f"({in_kernel}/{instr} retired in-kernel)")
    assert instr > 1000, "campaign barely ran"
    assert occ >= 0.95, (
        f"in-window occupancy {occ:.4f} < 0.95 — lanes are retiring on "
        f"the park-resume leg instead of inside the kernel")


def _donation_lint_leg() -> None:
    from wtf_tpu.analysis.rules import run_megachunk_rules

    findings, info = run_megachunk_rules()
    assert not findings, (
        "megachunk donation/budget rules not clean: "
        + "; ".join(f.message for f in findings))
    counts = info["mega_counts"]
    assert counts["pallas-call"] >= 1
    print(f"[fused-mega-smoke] donation lint clean "
          f"({counts['total']} census ops incl. "
          f"{counts['pallas-call']} pallas-call; every donated leaf "
          f"aliased in the compiled window)")


def main() -> int:
    try:
        _parity_and_occupancy_leg()
        _donation_lint_leg()
    except AssertionError as e:
        print(f"[fused-mega-smoke] FAILED: {e}")
        return 1
    print("[fused-mega-smoke] OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
