"""Device-decode smoke (`make decode-smoke`, wired into `make verify`).

The zero-host-steady-state acceptance drill, CPU-only, no hardware: a
COLD-CACHE demo_tlv devmangle campaign with `--device-decode` must

  * complete its megachunk windows with ZERO host decode services —
    every decode-cache miss (cold start included) serviced in-graph,
    the host decoder running only as the harvest cross-check oracle;
  * cross-check CLEAN: every device-published entry byte-identical to
    the host decoder (mismatch counter == 0);
  * stay bit-identical to the host-serviced reference at equal seeds —
    coverage/edge bitmap bytes, corpus digests, crash buckets, decode
    cache entry INDICES (the coverage-bit mapping);
  * overlap harvest with execution: steady-state windows prelaunch, and
    at least one speculative window is adopted.

Exit 0 = all held; any assertion prints and exits 1.
"""

from __future__ import annotations

import sys


def _leg() -> None:
    from wtf_tpu.analysis.trace import build_tlv_campaign
    from wtf_tpu.utils.hashing import hex_digest

    def campaign(**kw):
        loop = build_tlv_campaign(
            mutator="devmangle", seed=0x5EED, megachunk=4, n_lanes=4,
            limit=10_000, chunk_steps=128, overlay_slots=16, **kw)
        # long enough that finds land in-graph AND steady-state windows
        # (complete, find-free) exist for the prelaunch to ride
        loop.fuzz(runs=4 * 16)
        cov, edge = loop.backend.coverage_state()
        return loop, {
            "cov": cov.tobytes(), "edge": edge.tobytes(),
            "corpus": [hex_digest(d) for d in loop.corpus],
            "buckets": sorted(loop.crash_buckets),
            "testcases": loop.stats.testcases,
            "crashes": loop.stats.crashes,
            "timeouts": loop.stats.timeouts,
            "entries": loop.backend.runner.cache.checkpoint_entries(),
        }

    ref_loop, ref = campaign()
    dd_loop, dd = campaign(device_decode=True)
    for key in ref:
        assert dd[key] == ref[key], (
            f"--device-decode diverged from the host-serviced "
            f"reference on {key}")
    reg = dd_loop.backend.registry
    published = reg.counter("devdec.published").value
    mismatches = reg.counter("devdec.crosscheck_mismatches").value
    host_decodes = dd_loop.backend.runner.stats["decodes"]
    zero_windows = reg.counter("devdec.zero_host_windows").value
    windows = reg.counter("megachunk.windows").value
    hits = reg.counter("megachunk.prelaunch_hits").value
    assert published > 0, "no device-published decode entries"
    assert mismatches == 0, (
        f"{mismatches} device entries disagreed with the host decoder")
    assert host_decodes == 0, (
        f"{host_decodes} host decode services in a --device-decode "
        f"campaign — the zero-host window broke")
    assert zero_windows > 0, "no zero-host windows recorded"
    assert hits > 0, "pipelined harvest never adopted a prelaunch"
    print(f"[decode-smoke] zero-host steady state held: "
          f"{published} entries device-published, cross-check clean, "
          f"0 host decode services ({ref_loop.backend.runner.stats['decodes']} "
          f"in the reference), {zero_windows}/{windows} zero-host "
          f"windows, {hits} prelaunch adoptions")


def main() -> int:
    try:
        _leg()
    except AssertionError as e:
        print(f"[decode-smoke] FAILED: {e}")
        return 1
    print("[decode-smoke] OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
