"""Deterministic chaos harness: seeded fault schedules over real seams.

Every recovery path in the fault-tolerance tier is exercised against the
code that actually ships — not mocks of it — by wrapping two seams:

  socket I/O     `chaos_dialing(plan)` wraps every socket `wire.dial`
                 returns in a ChaosSocket that injects connection
                 resets, partial sends/recvs, and delays on scheduled
                 operation indices
  checkpoint I/O `chaos_checkpoint_io(plan)` arms utils/atomicio's
                 `_WRITE_FAULT` hook to raise ENOSPC (or any OSError) on
                 scheduled atomic writes
  process death  `fuzz_until_killed(loop, ...)` drives the REAL
                 FuzzLoop.fuzz loop and "kills" it at a chosen batch
                 boundary; `tear_file(path)` simulates the torn file a
                 pre-atomic kill would have left
  device plane   `chaos_device(plan)` arms wtf_tpu/supervise's
                 `_DEVICE_FAULT` hook: scripted hangs, device errors and
                 lane poisoning fire on exact GLOBAL DISPATCH INDICES
                 (every supervised seam counts one), so watchdog /
                 rebuild / quarantine recovery is provable in CI with no
                 wall-clock — an injected hang raises DispatchHang
                 immediately rather than sleeping out a real timeout

Determinism contract: a schedule is either scripted explicitly or drawn
once from `random.Random(seed)` at plan construction.  Faults fire on
per-socket / per-write OPERATION INDICES, not on wall clock or rates, so
the same plan against the same (single-threaded) node code faults at
exactly the same points on every run — what lets tier-1 assert "one
reset at op 7 loses zero testcases" instead of flaking.
"""

from __future__ import annotations

import errno
import random
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

from wtf_tpu.dist import wire
from wtf_tpu.supervise import (
    DEVICE_ERROR, DEVICE_HANG, DEVICE_POISON, MACHINE_SEAMS,
)
from wtf_tpu.supervise import supervisor as _supervisor
from wtf_tpu.utils import atomicio

RESET = "reset"
PARTIAL_SEND = "partial-send"
PARTIAL_RECV = "partial-recv"
DELAY = "delay"

_KINDS = (RESET, PARTIAL_SEND, PARTIAL_RECV, DELAY)
_DEVICE_KINDS = (DEVICE_HANG, DEVICE_ERROR, DEVICE_POISON)


class SimulatedKill(Exception):
    """Raised by fuzz_until_killed at the scheduled batch boundary."""


class FaultPlan:
    """A fixed sequence of per-socket fault schedules plus a set of
    faulting atomic-write indices.

    `socket_schedules[i]` is handed to the i-th socket the harness wraps
    (dial order — deterministic for the single-threaded node loops); it
    maps that socket's operation index (each sendall/recv call counts
    one) to a fault kind.  Sockets beyond the list run fault-free.
    `write_faults` are global atomic-write indices that raise
    `write_error` (default ENOSPC) before any byte lands."""

    def __init__(self, socket_schedules: Optional[List[Dict[int, str]]]
                 = None, write_faults=(), delay_secs: float = 0.005,
                 write_error: Optional[OSError] = None,
                 device_faults: Optional[Dict[int, object]] = None):
        self.socket_schedules = [dict(s) for s in (socket_schedules or [])]
        self.write_faults = set(write_faults)
        self.delay_secs = delay_secs
        self.write_error = write_error
        # {global supervised-dispatch index: kind | (kind, arg)} — arg is
        # the lane for DEVICE_POISON
        self.device_faults = dict(device_faults or {})
        self._next_socket = 0
        self._next_write = 0
        # observability for assertions: what actually fired
        self.fired: List[tuple] = []

    @classmethod
    def seeded(cls, seed: int, n_sockets: int, faults_per_socket: int = 1,
               ops_range: tuple = (2, 40), kinds=(RESET, PARTIAL_SEND,
                                                  PARTIAL_RECV, DELAY),
               delay_secs: float = 0.005) -> "FaultPlan":
        """Draw a reproducible plan from `seed`: for each of `n_sockets`,
        `faults_per_socket` faults at operation indices uniform in
        `ops_range` with kinds uniform over `kinds`."""
        rng = random.Random(seed)
        schedules = []
        for _ in range(n_sockets):
            sched: Dict[int, str] = {}
            for _ in range(faults_per_socket):
                sched[rng.randrange(*ops_range)] = rng.choice(list(kinds))
            schedules.append(sched)
        return cls(schedules, delay_secs=delay_secs)

    def next_schedule(self) -> Dict[int, str]:
        i = self._next_socket
        self._next_socket += 1
        if i < len(self.socket_schedules):
            return self.socket_schedules[i]
        return {}

    def note(self, *what) -> None:
        self.fired.append(what)

    def count_fired(self, kind: str) -> int:
        return sum(1 for f in self.fired if f[0] == kind)

    # -- the supervise hook ------------------------------------------------
    def _device_hook(self, seam: str, index: int):
        """Supervisor.dispatch consults this with the seam name and the
        global dispatch index.  Poison scheduled on a seam whose output
        carries no machine state (devmut-generate) slides to the next
        index instead of silently vanishing — the plan stays meaningful
        whatever dispatch interleaving the ladder rung produces."""
        fault = self.device_faults.pop(index, None)
        if fault is None:
            return None
        kind, arg = fault if isinstance(fault, tuple) else (fault, None)
        if kind == DEVICE_POISON and seam not in MACHINE_SEAMS:
            self.device_faults[index + 1] = (kind, arg)
            return None
        self.note(kind, seam, index)
        return (kind, arg)

    # -- the atomicio hook -------------------------------------------------
    def _write_hook(self, path) -> None:
        i = self._next_write
        self._next_write += 1
        if i in self.write_faults:
            self.note("write-fault", i, str(path))
            raise self.write_error or OSError(
                errno.ENOSPC, f"chaos: injected ENOSPC for {path}")


class ChaosSocket:
    """Socket proxy executing one FaultPlan schedule.  Everything not
    faulted delegates to the real socket, so framing, TCP_NODELAY, and
    close semantics are exactly production's."""

    def __init__(self, sock, schedule: Dict[int, str], plan: FaultPlan):
        # object.__setattr__-free: plain attributes, delegation via
        # __getattr__ only for names not defined here
        self._sock = sock
        self._sched = dict(schedule)
        self._plan = plan
        self._op = 0

    def _fault(self) -> Optional[str]:
        kind = self._sched.pop(self._op, None)
        self._op += 1
        return kind

    def _die(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        raise ConnectionResetError(errno.ECONNRESET,
                                   "chaos: injected connection reset")

    def sendall(self, data):
        kind = self._fault()
        if kind == RESET:
            self._plan.note(RESET, "send")
            self._die()
        if kind == PARTIAL_SEND:
            # half the bytes land, then the connection dies: the peer
            # sees a torn frame (recv_exact returns None mid-body)
            self._plan.note(PARTIAL_SEND, len(data))
            try:
                self._sock.sendall(data[:max(1, len(data) // 2)])
            except OSError:
                pass
            self._die()
        if kind == DELAY:
            self._plan.note(DELAY, "send")
            time.sleep(self._plan.delay_secs)
        return self._sock.sendall(data)

    def recv(self, n):
        kind = self._fault()
        if kind == RESET:
            self._plan.note(RESET, "recv")
            self._die()
        if kind == PARTIAL_RECV:
            # deliver a single byte now and schedule the reset for the
            # very next operation: the reader tears mid-frame
            self._plan.note(PARTIAL_RECV, n)
            self._sched[self._op] = RESET
            return self._sock.recv(min(1, n) if n else n)
        if kind == DELAY:
            self._plan.note(DELAY, "recv")
            time.sleep(self._plan.delay_secs)
        return self._sock.recv(n)

    def __getattr__(self, name):
        return getattr(self._sock, name)


@contextmanager
def chaos_dialing(plan: FaultPlan):
    """Within the context, every socket `wire.dial` hands out is wrapped
    with the plan's next schedule (dial order)."""
    original = wire.dial

    def dial(*args, **kwargs):
        return ChaosSocket(original(*args, **kwargs),
                           plan.next_schedule(), plan)

    wire.dial = dial
    try:
        yield plan
    finally:
        wire.dial = original


@contextmanager
def chaos_checkpoint_io(plan: FaultPlan):
    """Within the context, scheduled atomic writes (utils/atomicio —
    checkpoints, coverage files, crash saves, corpus entries) raise the
    plan's write error before touching disk."""
    previous = atomicio._WRITE_FAULT
    atomicio._WRITE_FAULT = plan._write_hook
    try:
        yield plan
    finally:
        atomicio._WRITE_FAULT = previous


@contextmanager
def chaos_device(plan: FaultPlan):
    """Within the context, every supervised device dispatch consults the
    plan's device schedule (supervise/supervisor.py's `_DEVICE_FAULT`
    global — the same arming pattern as atomicio's `_WRITE_FAULT`).
    Supervisors stay on their fast path when the plan has no device
    faults left, so an exhausted plan costs one dict lookup."""
    previous = _supervisor._DEVICE_FAULT
    _supervisor._DEVICE_FAULT = plan._device_hook
    try:
        yield plan
    finally:
        _supervisor._DEVICE_FAULT = previous


def fuzz_until_killed(loop, runs: int, kill_at_batch: int) -> None:
    """Drive the REAL FuzzLoop.fuzz loop and simulate a kill at the end
    of batch `kill_at_batch` — after that batch's checkpoint cadence ran,
    exactly where a SIGKILL between batches lands.  The loop object is
    left as the dead process would have left its disk state: resume from
    the checkpoint dir with a FRESH loop, never reuse this one."""
    original = loop._heartbeat

    def heartbeat(print_stats):
        if loop.batches_done >= kill_at_batch:
            raise SimulatedKill(f"killed at batch {loop.batches_done}")
        original(print_stats)

    loop._heartbeat = heartbeat
    try:
        loop.fuzz(runs)
        raise AssertionError(
            f"campaign finished {runs} runs before batch {kill_at_batch}")
    except SimulatedKill:
        pass
    finally:
        loop._heartbeat = original


def tear_file(path, keep_fraction: float = 0.5) -> None:
    """Truncate `path` mid-content — the torn file a kill during a
    non-atomic write (or a bit-rotted disk) leaves behind.  Used to
    prove digest detection + .prev fallback on real checkpoint bytes."""
    from pathlib import Path

    path = Path(path)
    data = path.read_bytes()
    path.write_bytes(data[:max(1, int(len(data) * keep_fraction))])
