"""`make device-chaos-smoke`: the seeded self-healing-runtime soak.

Every leg drives the same seeded demo_tlv devmangle campaign (8 lanes x
4 batches) through the supervisor with a deterministic, scripted
device-fault plan (wtf_tpu/testing/faultinject.py — faults trigger on
the Nth supervised dispatch, never on wall-clock), and asserts the
final campaign state — coverage count, edge-byte sum, corpus digests,
crash buckets, testcase count — is BIT-IDENTICAL to the fault-free
reference:

  error leg       a scripted device error mid-campaign on the plain
                  batch path: the batch is abandoned, the backend is
                  rebuilt from host-side state, the ladder degrades one
                  rung and re-promotes after clean batches.
  megachunk leg   supervised megachunk windows are bit-identical to the
                  plain run, then a scripted HANG fires the dispatch
                  watchdog mid-window: the window is abandoned, the
                  ladder drops to batch-at-a-time, replays, and
                  re-promotes back to megachunk.
  quarantine leg  scripted lane poison with quarantine_threshold=1: the
                  integrity check flags the lane, the supervisor masks
                  it idle (never harvested) and the campaign completes
                  all testcases on the surviving lanes.

Exit 0 only when every parity and counter assertion held (>=1 watchdog
fire, >=1 degradation AND >=1 re-promotion, >=1 quarantined lane across
the legs).  Run via `python -m wtf_tpu.testing.device_chaos_smoke
[seed]`.
"""

from __future__ import annotations

import os
import sys

SEED = 0xC4A05

LANES, BATCHES = 8, 4
RUNS = LANES * BATCHES


def _build(seed: int) -> dict:
    return dict(n_lanes=LANES, mutator="devmangle", limit=20_000,
                seed=seed & 0xFFFF, chunk_steps=128, overlay_slots=16)


def _state_of(loop) -> tuple:
    """The bit-identity tuple: coverage count, sorted corpus digests,
    crash buckets, testcases, and the raw edge-byte sum."""
    import numpy as np

    return (loop._coverage(), sorted(loop.corpus.digests),
            sorted(loop.crash_names), loop.stats.testcases,
            int(np.asarray(loop.backend.coverage_state()[1]).sum()))


def _error_leg(seed: int) -> dict:
    from wtf_tpu.analysis.trace import build_tlv_campaign
    from wtf_tpu.supervise import DEVICE_ERROR
    from wtf_tpu.testing.faultinject import FaultPlan, chaos_device

    build = _build(seed)
    ref = build_tlv_campaign(**build)
    ref.fuzz(RUNS)
    ref_state = _state_of(ref)

    # supervised fault-free: parity AND the dispatch count that anchors
    # the scripted fault index (operation-indexed, not wall-clock)
    sup = build_tlv_campaign(supervise=True, dispatch_timeout=30.0, **build)
    sup.fuzz(RUNS)
    assert _state_of(sup) == ref_state, "supervised fault-free parity broken"
    n_disp = sup.backend.supervisor.registry.counter(
        "supervise.dispatches").value

    plan = FaultPlan([], device_faults={n_disp // 2: DEVICE_ERROR})
    err = build_tlv_campaign(supervise=True, dispatch_timeout=30.0,
                             promote_after=2, **build)
    with chaos_device(plan):
        err.fuzz(RUNS)
    err_state = _state_of(err)
    assert err_state == ref_state, \
        f"error-recovery parity broken:\n ref {ref_state}\n got {err_state}"
    reg = err.backend.supervisor.registry
    out = {"dispatches": n_disp,
           "retries": reg.counter("supervise.batch_retries").value,
           "rebuilds": reg.counter("supervise.rebuilds").value,
           "degradations": reg.counter("supervise.degradations").value,
           "promotions": reg.counter("supervise.promotions").value,
           "fired": list(plan.fired)}
    assert out["rebuilds"] >= 1, "scripted error forced no rebuild"
    assert out["degradations"] >= 1 and out["promotions"] >= 1, \
        f"ladder never cycled: {out}"
    return out, ref_state


def _megachunk_leg(seed: int, ref_state: tuple) -> dict:
    from wtf_tpu.analysis.trace import build_tlv_campaign
    from wtf_tpu.supervise import DEVICE_HANG
    from wtf_tpu.testing.faultinject import FaultPlan, chaos_device

    build = _build(seed)
    msup = build_tlv_campaign(megachunk=2, supervise=True,
                              dispatch_timeout=30.0, **build)
    msup.fuzz(RUNS)
    assert _state_of(msup) == ref_state, \
        "supervised megachunk parity vs plain broken"
    n_disp = msup.backend.supervisor.registry.counter(
        "supervise.dispatches").value

    # a hang mid-schedule: the watchdog abandons the in-flight window,
    # the ladder degrades to batch-at-a-time, and promote_after=1
    # re-promotes to megachunk within the same short campaign
    plan = FaultPlan([], device_faults={n_disp // 2: DEVICE_HANG})
    mh = build_tlv_campaign(megachunk=2, supervise=True,
                            dispatch_timeout=30.0, promote_after=1, **build)
    with chaos_device(plan):
        mh.fuzz(RUNS)
    mh_state = _state_of(mh)
    assert mh_state == ref_state, \
        f"megachunk hang parity broken:\n ref {ref_state}\n got {mh_state}"
    reg = mh.backend.supervisor.registry
    out = {"dispatches": n_disp,
           "watchdog_fires": reg.counter("supervise.watchdog_fires").value,
           "degradations": reg.counter("supervise.degradations").value,
           "promotions": reg.counter("supervise.promotions").value,
           "fired": list(plan.fired)}
    assert out["watchdog_fires"] >= 1, "scripted hang never fired watchdog"
    assert out["degradations"] >= 1 and out["promotions"] >= 1, \
        f"ladder never cycled on the megachunk leg: {out}"
    return out


def _quarantine_leg(seed: int) -> dict:
    from wtf_tpu.analysis.trace import build_tlv_campaign
    from wtf_tpu.supervise import DEVICE_POISON
    from wtf_tpu.testing.faultinject import FaultPlan, chaos_device

    build = _build(seed)
    # poison lane 3 on dispatch 6 (a mid-campaign chunk dispatch on the
    # plain supervised schedule); threshold=1 quarantines on first sight
    plan = FaultPlan([], device_faults={6: (DEVICE_POISON, 3)})
    q = build_tlv_campaign(supervise=True, dispatch_timeout=30.0,
                           quarantine_threshold=1, **build)
    with chaos_device(plan):
        q.fuzz(RUNS)
    sup = q.backend.supervisor
    assert sup.quarantined == {3}, \
        f"expected lane 3 quarantined, got {sorted(sup.quarantined)}"
    assert q.stats.testcases == RUNS, \
        f"campaign did not complete around the quarantined lane: " \
        f"{q.stats.testcases}/{RUNS}"
    reg = sup.registry
    return {"quarantined": sorted(sup.quarantined),
            "quarantined_counter": reg.counter("device.quarantined").value,
            "poisoned_lanes": reg.counter("supervise.poisoned_lanes").value,
            "testcases": q.stats.testcases,
            "coverage": q._coverage()}


def main(argv=None) -> int:
    seed = int((argv or sys.argv[1:] or [SEED])[0])
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    # same persistent compile cache the test suite uses — the legs
    # compile the chunk + megachunk executors, slow cold on a 1-core box
    jax.config.update("jax_compilation_cache_dir",
                      os.path.expanduser("~/.cache/wtf_tpu_xla"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    print(f"device-chaos-smoke seed={seed:#x}")
    err, ref_state = _error_leg(seed)
    print(f"error leg OK: {err}")
    mega = _megachunk_leg(seed, ref_state)
    print(f"megachunk leg OK: {mega}")
    quar = _quarantine_leg(seed)
    print(f"quarantine leg OK: {quar}")
    print("device-chaos-smoke PASS (>=1 watchdog fire, >=1 degradation + "
          "re-promotion, >=1 quarantined lane, recovery bit-identical to "
          "the fault-free run)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
