"""Deterministic fault-injection tooling for the fault-tolerance tier.

Not shipped behavior — test/ops harnesses that exercise the recovery
paths (dist reconnect/reclaim, checkpoint/resume, torn-file fallback)
against the REAL seams, reproducibly:

  faultinject  seeded fault schedules over the socket and checkpoint-I/O
               seams (reset, partial send/recv, delay, ENOSPC,
               kill-at-batch-N)
  chaos_smoke  the `make chaos-smoke` end-to-end soak
"""

from wtf_tpu.testing.faultinject import (  # noqa: F401
    ChaosSocket, FaultPlan, SimulatedKill, chaos_dialing,
    chaos_checkpoint_io, fuzz_until_killed, tear_file,
)
