"""Fused-step + megachunk smoke (`make fused-smoke`, wired into
`make verify`).

A tiny end-to-end pass over PR 12's two fused layers, CPU-only, no
hardware:

  occupancy   the widened Pallas kernel (interp/pstep.py: in-kernel page
              walk + delta-overlay probe + memory-operand/stack forms)
              must keep >= 0.95 of demo_tlv's retired instructions
              in-kernel under interpret mode at small lanes — the
              ISSUE-14 acceptance bar, measured from the device counter
              block (CTR_FUSED / CTR_INSTR), with the park split
              reported so a regression names its reason;
  megachunk   a short devmangle campaign through one-dispatch
              multi-batch windows (wtf_tpu/fuzz/megachunk.py) must be
              bit-identical to the batch-at-a-time device loop at equal
              seeds — aggregate coverage/edge bitmap bytes, corpus
              digests, crash buckets, every counter.

Exit 0 = all held; any assertion prints and exits 1.
"""

from __future__ import annotations

import sys

import numpy as np


def _occupancy_leg() -> None:
    from wtf_tpu.harness import demo_tlv
    from wtf_tpu.interp.machine import (
        CTR_FUSED, CTR_INSTR, CTR_PARK_MEM, CTR_PARK_SUBSET,
    )
    from wtf_tpu.interp.runner import Runner, warm_decode_cache

    payload = b"\x01\x08AAAAAAAA" * 50
    r = Runner(demo_tlv.build_snapshot(), n_lanes=2, chunk_steps=64,
               fused_step="on")
    r.limit = 4_000
    warm_decode_cache(r, demo_tlv.TARGET, payload)
    view = r.view()
    for lane in range(2):
        view.virt_write(lane, demo_tlv.INPUT_GVA, payload)
        view.r["gpr"][lane, 2] = np.uint64(len(payload))
    r.push(view)
    r.run()
    ctr = np.asarray(r.machine.ctr)
    instr = int(ctr[:, CTR_INSTR].sum(dtype=np.uint64))
    fused = int(ctr[:, CTR_FUSED].sum(dtype=np.uint64))
    occ = fused / max(instr, 1)
    print(f"[fused-smoke] occupancy {occ:.4f} "
          f"({fused}/{instr} in-kernel; parks "
          f"subset={int(ctr[:, CTR_PARK_SUBSET].sum())} "
          f"mem={int(ctr[:, CTR_PARK_MEM].sum())})")
    assert instr > 1000, "demo_tlv hot loop barely ran"
    assert occ >= 0.95, (
        f"fused occupancy {occ:.4f} < 0.95 — the memory subset "
        f"regressed out of the kernel (check the park split above)")


def _megachunk_leg() -> None:
    import jax

    from wtf_tpu.analysis.trace import build_tlv_campaign
    from wtf_tpu.utils.hashing import hex_digest

    def campaign(mega):
        loop = build_tlv_campaign(
            mutator="devmangle", seed=0x5EED, megachunk=mega, n_lanes=4,
            limit=10_000, chunk_steps=128, overlay_slots=16)
        # 8 batches, not a cold-cache handful: finds must land in
        # IN-GRAPH batches so the find-stop slab schedule (the seam
        # where parity can skew) is actually exercised
        loop.fuzz(runs=4 * 8)
        cov, edge = loop.backend.coverage_state()
        return {
            "cov": cov.tobytes(), "edge": edge.tobytes(),
            "corpus": [hex_digest(d) for d in loop.corpus],
            "buckets": sorted(loop.crash_buckets),
            "testcases": loop.stats.testcases,
            "crashes": loop.stats.crashes,
            "timeouts": loop.stats.timeouts,
        }

    legacy = campaign(0)
    windowed = campaign(3)
    for key in legacy:
        assert windowed[key] == legacy[key], (
            f"megachunk diverged from the batch-at-a-time loop on {key}")
    print(f"[fused-smoke] megachunk parity held "
          f"({legacy['testcases']} testcases, "
          f"{legacy['crashes']} crashes, "
          f"{len(legacy['corpus'])} corpus entries)")


def main() -> int:
    try:
        _occupancy_leg()
        _megachunk_leg()
    except AssertionError as e:
        print(f"[fused-smoke] FAILED: {e}")
        return 1
    print("[fused-smoke] OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
