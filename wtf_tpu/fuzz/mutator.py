"""Mutation engines.

Reference surface (src/wtf/mutator.h:10-20): `Mutator_t` with
`GetNewTestcase(corpus)` and `OnNewCoverage(testcase)` (cross-over seeding),
backed by two generic engines — LLVM libFuzzer's MutationDispatcher and the
honggfuzz mangle port (honggfuzz.cc:836) — plus per-target custom mutators
(fuzzer_tlv_server.cc:204-365).  This module provides original equivalents
of all three roles:

  ByteMutator      - libFuzzer-style single-op dispatch (erase / insert /
                     change byte / change bit / copy part / change ASCII
                     integer / cross-over)
  MangleMutator    - honggfuzz-style: several mutations per testcase drawn
                     from a wider op table (magic values, expands, shifts)
  TlvStructureMutator - structure-aware {type,len,payload} record mutator,
                     the example custom mutator for the demo_tlv target

All engines are seeded-deterministic (reference --seed, wtf.cc:108,363).
"""

from __future__ import annotations

import random
import struct
from typing import List, Optional

_MAGIC = [
    b"\x00", b"\xff", b"\x7f", b"\x80", b"\x01",
    b"\x00\x00", b"\xff\xff", b"\xff\x7f", b"\x00\x80",
    b"\x00\x00\x00\x00", b"\xff\xff\xff\xff", b"\xff\xff\xff\x7f",
    b"\x00\x00\x00\x80",
    b"\xff\xff\xff\xff\xff\xff\xff\xff",
    b"\x00\x00\x00\x00\x00\x00\x00\x80",
]


def generate_fresh(rng: random.Random, max_len: int) -> bytes:
    """Empty-corpus testcase synthesis, shared by every engine: 1..64
    random bytes, bounded by the campaign's max_len contract."""
    n = rng.randint(1, min(64, max(1, max_len)))
    return bytes(rng.randrange(256) for _ in range(n))


class Mutator:
    """Interface (reference mutator.h:10-20)."""

    def get_new_testcase(self, corpus) -> bytes:
        raise NotImplementedError

    def on_new_coverage(self, testcase: bytes) -> None:
        """Called when `testcase` produced new coverage; engines use it to
        seed cross-over (reference LibfuzzerMutator_t::SetCrossOverWith)."""

    # -- checkpoint/resume (wtf_tpu/resume) --------------------------------
    # Engine-private state beyond the shared campaign RNG (which the
    # checkpoint carries separately).  The default covers every host
    # engine here and the native binding: the only such state is the
    # cross-over seed.  Engines with more (devmut's slab + batch cursor)
    # override both.

    def checkpoint_state(self) -> dict:
        cross = getattr(self, "_cross", None)
        return {"cross": cross.hex() if cross else None}

    def restore_state(self, state: dict) -> None:
        if hasattr(self, "_cross"):
            cross = state.get("cross")
            self._cross = bytes.fromhex(cross) if cross else None


class ByteMutator(Mutator):
    """One mutation per testcase, libFuzzer-dispatch style."""

    def __init__(self, rng: random.Random, max_len: int):
        self.rng = rng
        self.max_len = max_len
        self._cross: Optional[bytes] = None

    def on_new_coverage(self, testcase: bytes) -> None:
        self._cross = testcase

    def get_new_testcase(self, corpus) -> bytes:
        base = corpus.pick() if corpus is not None else None
        if not base:
            return generate_fresh(self.rng, self.max_len)
        data = bytearray(base)
        self._mutate_once(data)
        return bytes(data[:self.max_len])

    def _mutate_once(self, data: bytearray) -> None:
        rng = self.rng
        op = rng.randrange(8)
        if op == 0 and len(data) > 1:          # erase range
            start = rng.randrange(len(data))
            count = rng.randint(1, max(1, len(data) - start))
            del data[start:start + count]
        elif op == 1 and len(data) < self.max_len:   # insert byte(s)
            pos = rng.randrange(len(data) + 1)
            data[pos:pos] = bytes(rng.randrange(256)
                                  for _ in range(rng.randint(1, 8)))
        elif op == 2 and data:                 # change byte
            data[rng.randrange(len(data))] = rng.randrange(256)
        elif op == 3 and data:                 # change bit
            pos = rng.randrange(len(data))
            data[pos] ^= 1 << rng.randrange(8)
        elif op == 4 and len(data) >= 2:       # copy part within
            src = rng.randrange(len(data))
            count = rng.randint(1, len(data) - src)
            dst = rng.randrange(len(data))
            data[dst:dst + count] = data[src:src + count]
            del data[self.max_len:]
        elif op == 5 and data:                 # change ASCII integer
            self._change_ascii_int(data)
        elif op == 6 and data:                 # interesting byte (libFuzzer
            pos = rng.randrange(len(data))     #  InterestingValues role)
            if rng.randrange(2):
                data[pos] = rng.choice((0x00, 0x01, 0x7F, 0x80, 0xFF, 0x20))
            else:
                data[pos] = 0x20 + rng.randrange(95)  # printable ascii
        else:                                  # cross-over
            other = self._cross
            if other and data:
                pos = rng.randrange(len(data))
                take = rng.randrange(len(other) + 1)
                data[pos:] = other[:take]
                del data[self.max_len:]
            elif data:
                data[rng.randrange(len(data))] = rng.randrange(256)

    def _change_ascii_int(self, data: bytearray) -> None:
        rng = self.rng
        digits = [i for i, b in enumerate(data) if 0x30 <= b <= 0x39]
        if not digits:
            data[rng.randrange(len(data))] = rng.randrange(256)
            return
        i = rng.choice(digits)
        data[i] = 0x30 + rng.randrange(10)


class MangleMutator(Mutator):
    """Several mutations per testcase from a wide op table, the
    honggfuzz-mangle approach (reference applies 5 per run, mutator.cc:66)."""

    N_PER_RUN = 5

    def __init__(self, rng: random.Random, max_len: int):
        self.rng = rng
        self.max_len = max_len
        self._cross: Optional[bytes] = None

    def on_new_coverage(self, testcase: bytes) -> None:
        self._cross = testcase

    def get_new_testcase(self, corpus) -> bytes:
        base = corpus.pick() if corpus is not None else None
        if not base:
            return generate_fresh(self.rng, self.max_len)
        data = bytearray(base)
        for _ in range(self.rng.randint(1, self.N_PER_RUN)):
            self._mangle(data)
            if not data:
                data = bytearray(b"\x00")
        return bytes(data[:self.max_len])

    def _mangle(self, data: bytearray) -> None:
        rng = self.rng
        op = rng.randrange(10)
        n = len(data)
        if op == 0 and n:                      # bit flip
            pos = rng.randrange(n)
            data[pos] ^= 1 << rng.randrange(8)
        elif op == 1 and n:                    # random byte
            data[rng.randrange(n)] = rng.randrange(256)
        elif op == 2 and n:                    # inc/dec byte
            pos = rng.randrange(n)
            data[pos] = (data[pos] + rng.choice((1, 255))) & 0xFF
        elif op == 3:                          # magic value splice
            magic = rng.choice(_MAGIC)
            if n >= len(magic):
                pos = rng.randrange(n - len(magic) + 1)
                data[pos:pos + len(magic)] = magic
        elif op == 4 and n >= 2:               # shift/copy block
            src = rng.randrange(n)
            count = rng.randint(1, min(n - src, 32))
            dst = rng.randrange(n)
            data[dst:dst] = data[src:src + count]
            del data[self.max_len:]
        elif op == 5 and n and len(data) < self.max_len:  # expand (dup tail)
            pos = rng.randrange(n)
            count = rng.randint(1, min(16, self.max_len - n))
            data[pos:pos] = bytes(data[pos:pos + count])
        elif op == 6 and n > 1:                # shrink
            start = rng.randrange(n)
            count = rng.randint(1, max(1, (n - start) // 2 or 1))
            del data[start:start + count]
        elif op == 7 and n >= 4:               # ascii-num rewrite
            pos = rng.randrange(n - 3)
            data[pos:pos + 4] = str(rng.randrange(10000)).zfill(4).encode()
        elif op == 8 and n >= 2:               # swap two bytes
            i, j = rng.randrange(n), rng.randrange(n)
            data[i], data[j] = data[j], data[i]
        else:                                  # cross-over splice
            other = self._cross
            if other and n:
                pos = rng.randrange(n)
                take = rng.randrange(min(len(other), self.max_len - pos) + 1)
                data[pos:pos + take] = other[:take]


class TlvStructureMutator(Mutator):
    """Structure-aware mutator for {type:u8, len:u8, payload} record lists
    (the example custom mutator role, fuzzer_tlv_server.cc:204-365):
    generates, duplicates, deletes and corrupts whole records — including
    the len-field lies that trigger parser overflows."""

    def __init__(self, rng: random.Random, max_len: int):
        self.rng = rng
        self.max_len = max_len

    def _parse(self, data: bytes) -> List[bytearray]:
        records, pos = [], 0
        while pos + 2 <= len(data):
            length = data[pos + 1]
            end = min(pos + 2 + length, len(data))
            records.append(bytearray(data[pos:end]))
            pos = end
        return records

    def _random_record(self) -> bytearray:
        rng = self.rng
        rtype = rng.choice((1, 2, 3, rng.randrange(256)))
        length = rng.choice((0, 1, 8, rng.randrange(64), rng.randrange(256)))
        payload = bytes(rng.randrange(256) for _ in range(min(length, 64)))
        return bytearray([rtype, length]) + payload

    def get_new_testcase(self, corpus) -> bytes:
        base = corpus.pick() if corpus is not None else None
        records = self._parse(base) if base else []
        rng = self.rng
        op = rng.randrange(5)
        if not records or op == 0:             # append fresh record
            records.append(self._random_record())
        elif op == 1:                          # duplicate a record
            records.append(bytearray(rng.choice(records)))
        elif op == 2 and len(records) > 1:     # delete a record
            records.pop(rng.randrange(len(records)))
        elif op == 3:                          # corrupt a len field
            rec = rng.choice(records)
            rec[1] = rng.randrange(256)
        else:                                  # mutate payload bytes
            rec = rng.choice(records)
            if len(rec) > 2:
                rec[2 + rng.randrange(len(rec) - 2)] = rng.randrange(256)
        out = b"".join(bytes(r) for r in records)
        return out[:self.max_len]

    def on_new_coverage(self, testcase: bytes) -> None:
        pass


def create_mutator(name: str, rng: random.Random, max_len: int) -> Mutator:
    """By-name factory (reference CLI picks libfuzzer vs honggfuzz).

    "devmangle" is the device-resident engine (wtf_tpu/devmut): mangle
    semantics, but the whole batch is generated in-graph from the HBM
    corpus slab — requires the batched tpu backend and a target with a
    DeviceInsertSpec.  Its determinism contract is the campaign seed,
    so it draws one 64-bit seed from `rng` and never touches it again.
    """
    if name == "devmangle":
        from wtf_tpu.devmut.mutator import DevMangleMutator

        return DevMangleMutator(seed=rng.getrandbits(64), max_len=max_len)
    engines = {
        "byte": ByteMutator,
        "mangle": MangleMutator,
        "tlv": TlvStructureMutator,
    }
    if name not in engines:
        raise ValueError(f"unknown mutator {name!r} "
                         f"(known: {sorted(engines) + ['devmangle']})")
    return engines[name](rng, max_len)
