"""The standalone fuzz loop: mutate -> batch-execute -> harvest.

This is the single-process campaign driver — the reference needs a master
process + N client processes even on one machine (README.md:34-110); here
one process drives a whole device batch, and the distributed mode
(dist/client.py speaking to dist/server.py) reuses the same harvest logic
per node.

Per batch (the batched RunTestcaseAndRestore, client.cc:88-180):
  1. draw one testcase per lane from the mutator (corpus-seeded)
  2. backend.run_batch: insert + run every lane
  3. harvest: new-coverage lanes -> corpus + mutator cross-over seed;
     crashes -> crashes/<name>; timeouts already coverage-revoked
  4. target.restore + backend.restore

Telemetry: every batch phase is a span (mutate / execute / harvest /
restore — they tile run_one_batch, so their totals account for the
campaign's wall-clock), counters live in the metrics registry behind
`CampaignStats`, and crash / new-coverage / timeout / heartbeat records
land in the JSONL event log when one is wired.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Optional

from wtf_tpu.core.results import (
    Cr3Change, Crash, OverlayFull, TestcaseResult, Timedout,
)
from wtf_tpu.fuzz.corpus import Corpus
from wtf_tpu.fuzz.mutator import Mutator
from wtf_tpu import telemetry
from wtf_tpu.telemetry import NULL, Registry
from wtf_tpu.utils.hashing import hex_digest
from wtf_tpu.utils.human import seconds_to_human


def _campaign_counter(name: str):
    """Property proxying one `campaign.<name>` registry counter, so the
    reference-shaped attribute API (`stats.crashes += 1`) stays while the
    value lives in the registry (one namespace for the heartbeat line,
    the JSONL dump, and print_run_stats)."""
    key = f"campaign.{name}"

    def fget(self):
        return self.registry.counter(key).value

    def fset(self, value):
        self.registry.counter(key).set(value)

    return property(fget, fset)


class CampaignStats:
    """Counters behind the status line (reference ServerStats_t / client
    stats, server.h:24-240, client.cc:7-84), registry-backed."""

    testcases = _campaign_counter("testcases")
    crashes = _campaign_counter("crashes")
    timeouts = _campaign_counter("timeouts")
    cr3s = _campaign_counter("cr3s")
    overlay_fulls = _campaign_counter("overlay_fulls")
    new_coverage = _campaign_counter("new_coverage")

    def __init__(self, registry: Optional[Registry] = None):
        self.registry = registry if registry is not None else Registry()
        self.start = time.time()
        self.last_print = 0.0

    def execs_per_sec(self) -> float:
        dt = time.time() - self.start
        return self.testcases / dt if dt > 0 else 0.0

    def account(self, result: TestcaseResult) -> bool:
        """Count one testcase result; returns True for a crash (saving /
        requeueing is the caller's business).  The ONE accounting path
        shared by fuzz, minset, the dist master, and the dist clients."""
        self.testcases += 1
        if isinstance(result, Timedout):
            self.timeouts += 1
        elif isinstance(result, Cr3Change):
            self.cr3s += 1
        elif isinstance(result, OverlayFull):
            self.overlay_fulls += 1
        elif isinstance(result, Crash):
            self.crashes += 1
            return True
        return False

    def line(self, corpus_len: Optional[int] = None,
             cov: Optional[int] = None) -> str:
        """The human heartbeat line (format stable — downstream eyeballs
        and scripts parse it).  cov/corp are omitted by callers that
        don't track them (dist clients)."""
        uptime = seconds_to_human(time.time() - self.start)
        ovf = f" ovf: {self.overlay_fulls}" if self.overlay_fulls else ""
        mid = ""
        if cov is not None:
            mid += f"cov: {cov} "
        if corpus_len is not None:
            mid += f"corp: {corpus_len} "
        return (f"#{self.testcases} {mid}"
                f"exec/s: {self.execs_per_sec():.1f} "
                f"crash: {self.crashes} timeout: {self.timeouts} "
                f"cr3: {self.cr3s}{ovf} uptime: {uptime}")

    def maybe_heartbeat(self, events, registry=None, line_fn=None,
                        every: float = 10.0, print_stats: bool = False,
                        **fields) -> Optional[str]:
        """Throttled heartbeat — the ONE emission path shared by the fused
        loop, the dist master, and the dist nodes: at most one per `every`
        seconds, print() the human line when asked (print, not logging —
        the line must reach stdout even for library callers that never
        configure logging), and land a JSONL heartbeat record carrying
        the full registry dump.  Returns the line when one was emitted."""
        if not print_stats and (events is None or type(events) is type(NULL)):
            # nobody consumes the line: skip building it — line_fn can
            # cost a device coverage readback.  Exact-type check: EventLog
            # SUBCLASSES NullEventLog and must not match.
            return None
        now = time.time()
        if now - self.last_print < every:
            return None
        self.last_print = now
        line = line_fn() if line_fn is not None else self.line()
        if print_stats:
            print(line)
        events.heartbeat(registry, line=line, **fields)
        return line


class FuzzLoop:
    def __init__(
        self,
        backend,
        target,
        mutator: Mutator,
        corpus: Corpus,
        crashes_dir: Optional[Path] = None,
        batch_size: Optional[int] = None,
        stats_every: float = 10.0,
        registry: Optional[Registry] = None,
        events=None,
        checkpoint_dir: Optional[Path] = None,
        checkpoint_every: int = 0,
        store=None,
        megachunk: int = 0,
        xprof_dir: Optional[Path] = None,
        xprof_batches: int = 4,
        xprof_skip: int = 2,
    ):
        self.backend = backend
        self.target = target
        self.mutator = mutator
        self.corpus = corpus
        self.crashes_dir = Path(crashes_dir) if crashes_dir else None
        if self.crashes_dir:
            self.crashes_dir.mkdir(parents=True, exist_ok=True)
        self.batch_size = batch_size or getattr(backend, "n_lanes", 1)
        # default onto the BACKEND's registry/events so runner spans nest
        # under this loop's execute phase and one dump carries everything
        self.registry, self.events = telemetry.resolve(
            backend, registry, events)
        # device-resident mutation engine (wtf_tpu/devmut): the whole
        # mutate->insert phase moves in-graph and batches run through
        # _run_one_batch_device.  bind() raises early for backends or
        # targets that can't take the device path.
        self.mutate_on_device = bool(getattr(mutator, "is_device", False))
        if self.mutate_on_device:
            mutator.bind(backend, target, registry=self.registry,
                         events=self.events)
            mutator.seed_from(corpus)
        # one-dispatch multi-batch windows (wtf_tpu/fuzz/megachunk.py):
        # generation + insert + the run ladder + the coverage merge +
        # restore fused into ONE compiled program per up-to-`megachunk`
        # batches; host work per batch collapses to the status pull and
        # the crash/new-coverage harvest
        self.megachunk = int(megachunk or 0)
        if self.megachunk:
            if not self.mutate_on_device:
                raise ValueError(
                    "--megachunk needs the device mutation engine "
                    "(--mutator devmangle): generation must live "
                    "in-graph for the window to fuse it")
            if not hasattr(backend, "run_megachunk"):
                raise ValueError(
                    "--megachunk requires the batched tpu backend")
            if not getattr(backend, "limit", 0):
                raise ValueError(
                    "--megachunk needs a nonzero --limit: the in-graph "
                    "run ladder quiesces on the instruction budget")
        self._runs_budget = 0
        self.stats = CampaignStats(self.registry)
        self.stats_every = stats_every
        self.crash_names = set()
        # triage-grade crash dedup (wtf_tpu/triage/bucket.py): found
        # crashes bucket by (kind, faulting RIP, top-of-stack hash), not
        # by output filename — two bugs faulting on the same wild
        # address stay distinct, and the minimizer's "same crash"
        # test agrees with the harvest's.  crash_names keeps tracking
        # the reference-shaped filenames the saves land under.
        self.crash_buckets = set()
        # overlay-exhausted testcases get ONE honest re-run (they executed
        # on truncated memory); a second exhaustion drops them — the input
        # genuinely needs more dirty pages than the lane has slots
        self._requeue: list = []
        self._requeue_digests = set()
        # crash-safe checkpointing (wtf_tpu/resume): every
        # `checkpoint_every` batches the minimal resumable state lands in
        # `checkpoint_dir` atomically; a kill at any point costs at most
        # one checkpoint interval, and --resume replays bit-identically
        self.checkpoint_dir = Path(checkpoint_dir) if checkpoint_dir else None
        self.checkpoint_every = checkpoint_every
        self.batches_done = 0
        # content-addressed corpus/crash store (wtf_tpu/fleet/store):
        # when attached, finds and crashes are journaled there and the
        # flat outputs//crashes/ dirs become hardlink views
        self.store = store
        if store is not None and getattr(corpus, "store", None) is None:
            corpus.store = store
        # elastic-campaign policy hook (wtf_tpu/fleet/elastic): a
        # callable(loop) -> Optional[int] consulted at every batch
        # boundary; returning a device count checkpoints the campaign
        # (PR-8 format) and hands control back to the driver, which
        # re-places it onto that many devices and resumes bit-identically
        self.reshard_policy = None
        self.reshard_to: Optional[int] = None
        # self-healing device runtime (wtf_tpu/supervise): when the
        # backend's supervisor is armed, every batch runs under the
        # snapshot -> dispatch -> (recover + replay)* -> post_batch
        # wrapper in run_one_batch; the ladder attaches lazily at the
        # first batch (the backend may not be initialized yet here)
        self.supervisor = getattr(backend, "supervisor", None)
        # --xprof-dir: one jax.profiler.trace window over N STEADY-STATE
        # batches (the first `xprof_skip` are compile/warmup noise — the
        # profile must show the regime PERF.md's numbers describe, not
        # tracing).  One window per campaign; device-level truth for
        # what the span timeline (--trace-out) claims from the host side
        self.xprof_dir = Path(xprof_dir) if xprof_dir else None
        self.xprof_batches = int(xprof_batches)
        self.xprof_skip = int(xprof_skip)
        self._xprof_active = False
        self._xprof_done = False
        self._xprof_start_batch = 0
        if self.checkpoint_every and not hasattr(backend, "coverage_state"):
            # fail at construction, not at the first cadence hit deep
            # into a campaign (the checkpoint needs the batched backend's
            # device state seams)
            raise ValueError(
                "checkpointing requires the batched tpu backend "
                "(--backend=tpu); this backend has no coverage_state seam")

    def _account(self, data: bytes, result: TestcaseResult,
                 requeue: bool = False, lane: Optional[int] = None) -> int:
        """Per-result accounting shared by fuzz and minset (they used to
        carry copy-pasted blocks of this): counters via CampaignStats,
        crash saving + events, optional overlay-full requeue.  Returns 1
        for a crash so batch loops can sum."""
        if not self.stats.account(result):
            if requeue and isinstance(result, OverlayFull):
                digest = hex_digest(data)
                if digest not in self._requeue_digests:
                    self._requeue_digests.add(digest)
                    self._requeue.append(data)
            return 0
        from wtf_tpu.triage.bucket import bucket_of

        self._save_crash(data, result, bucket_of(self.backend, lane, result))
        return 1

    def _harvest_lane(self, lane: int, data: bytes, result: TestcaseResult,
                      requeue: bool = False, found_new=None) -> int:
        """The ONE per-lane harvest body shared by the host, device and
        megachunk batch paths: result accounting (+ optional
        overlay-full requeue) and the new-coverage -> corpus/mutator/
        event chain.  `found_new` overrides the backend's last-batch
        flag for callers harvesting several batches at once (the
        megachunk window's per-batch flag rows).  Returns 1 for a
        crash."""
        crashes = self._account(data, result, requeue=requeue, lane=lane)
        if (self.backend.lane_found_new_coverage(lane)
                if found_new is None else found_new):
            self.stats.new_coverage += 1
            if self.corpus.add(data):
                self.mutator.on_new_coverage(data)
                self.events.emit("new-coverage", digest=hex_digest(data),
                                 size=len(data))
        return crashes

    def _emit_timeouts(self, timeouts_before: int) -> None:
        """Aggregated: one record per batch, not one per timed-out lane."""
        timeouts = self.stats.timeouts - timeouts_before
        if timeouts:
            self.events.emit("timeout", count=timeouts)

    def _restore_batch(self) -> None:
        with self.registry.spans.span("restore"):
            self.target.restore()
            self.backend.restore()

    def _supervised(self):
        """The armed supervisor, ladder attached — or None (the common,
        unsupervised case)."""
        sup = self.supervisor
        if sup is None or not sup.enabled:
            return None
        if sup.ladder is None:
            sup.attach_loop(self)
        return sup

    def run_one_batch(self) -> int:
        """Returns the number of crashes found in this batch (for a
        megachunk window: in the whole window; the window's extra
        completed batches advance `batches_done` internally).

        Under supervision (wtf_tpu/supervise) the batch body runs inside
        the recovery wrapper: a DispatchFailure (hang, device error,
        poisoned lane) rebuilds the device plane from the batch-boundary
        snapshot and REPLAYS the batch — bit-identical, because the
        failed attempt consumed no host randomness and its decode work
        is a prefix of the same deterministic stream."""
        sup = self._supervised()
        if sup is None:
            return self._dispatch_batch()
        from wtf_tpu.supervise import DispatchFailure

        sup.pre_batch(self)
        attempts = 0
        while True:
            try:
                crashes = self._dispatch_batch()
            except DispatchFailure as failure:
                attempts += 1
                if attempts > sup.max_batch_retries:
                    raise
                sup.recover(self, failure)
                continue
            sup.post_batch(self)
            return crashes

    def _dispatch_batch(self) -> int:
        if self.mutate_on_device:
            sup = self.supervisor
            if self.megachunk and not (
                    sup is not None and sup.megachunk_disabled):
                return self._run_megachunk_window()
            return self._run_one_batch_device()
        spans = self.registry.spans
        with spans.span("mutate"):
            requeued, self._requeue = \
                self._requeue[:self.batch_size], []
            fresh = self.batch_size - len(requeued)
            if hasattr(self.mutator, "get_new_batch"):
                # native engines mutate the whole batch in one C call
                testcases = requeued + (self.mutator.get_new_batch(
                    self.corpus, fresh) if fresh else [])
            else:
                testcases = requeued + [
                    self.mutator.get_new_testcase(self.corpus)
                    for _ in range(fresh)]
        with spans.span("execute"):
            results = self.backend.run_batch(testcases, self.target)
        crashes = 0
        timeouts_before = self.stats.timeouts
        with spans.span("harvest"):
            for lane, (data, result) in enumerate(zip(testcases, results)):
                crashes += self._harvest_lane(lane, data, result,
                                              requeue=True)
        self._emit_timeouts(timeouts_before)
        self._restore_batch()
        return crashes

    def _run_one_batch_device(self) -> int:
        """The devmangle batch: generation + insertion are device
        programs, so `mutate`'s HOST share is dispatch overhead and the
        device wait is measured under the nested `mutate/device` span.
        Double-buffered: batch N+1's generation is prelaunched at the top
        of N's harvest, so by N+1's mutate fence the work has been
        overlapping host-side harvest/restore/heartbeat wall-clock (the
        slab it samples is as of N-1's finds — the one-batch lag of a
        pipelined generator).  Host code only pulls the lanes the
        harvest wants (crashes, new coverage); overlay-full requeue does
        not apply — the stream has no host bytes to requeue."""
        spans = self.registry.spans
        with spans.span("mutate"):
            with spans.span("device") as sp:
                _, lens = self.mutator.take_batch()
                sp.fence(lens)
        with spans.span("execute"):
            results = self.backend.run_batch_device(self.mutator,
                                                    self.target)
        crashes = 0
        timeouts_before = self.stats.timeouts
        with spans.span("harvest"):
            # double-buffer: batch N+1 generates while we harvest batch N
            self.mutator.prelaunch()
            wanted = [lane for lane, result in enumerate(results)
                      if self.backend.lane_found_new_coverage(lane)
                      or isinstance(result, Crash)]
            datas = self.mutator.fetch(wanted)
            for lane, result in enumerate(results):
                crashes += self._harvest_lane(lane, datas.get(lane, b""),
                                              result)
        self._emit_timeouts(timeouts_before)
        self._restore_batch()
        return crashes

    def _run_megachunk_window(self) -> int:
        """One megachunk window: up to `self.megachunk` whole batches in
        ONE compiled dispatch (restore/mutate/insert/execute/reduce all
        in-graph), then a host harvest of just the batches' finds.  The
        effective window is capped so batch boundaries still line up
        with the checkpoint cadence and the runs budget — a `--resume`
        from any such boundary stays bit-identical (PR-8 contract)."""
        spans = self.registry.spans
        # legacy->window handoff (megachunk re-promotion after a
        # degradation episode): a prelaunched legacy batch in flight is
        # discarded and the cursor rewound, so the window regenerates
        # the same stream index in-graph (DevMangleMutator.cancel_pending
        # — skipping it would skip one batch of the deterministic stream)
        self.mutator.cancel_pending()
        window = self.megachunk
        if self.checkpoint_every:
            window = min(window, self.checkpoint_every
                         - self.batches_done % self.checkpoint_every)
        if self._runs_budget:
            remaining = self._runs_budget - self.stats.testcases
            lanes = self.batch_size
            window = min(window, max(1, -(-int(remaining) // lanes)))
        with spans.span("execute"):
            # the mark draws the WHOLE one-dispatch window in the trace
            # timeline (--trace-out) — its extent against the device
            # leaves inside run_megachunk is the visual form of the
            # zero-host claim.  A trace-only mark, not a nested span:
            # the device wait must keep recording under the flat
            # execute/device path the host-share accounting reads.
            with spans.trace_mark("megachunk-window"):
                batches = self.backend.run_megachunk(
                    self.mutator, self.target, self.megachunk, window)
        crashes = 0
        timeouts_before = self.stats.timeouts
        with spans.span("harvest"):
            for j, (results, flags, datas) in enumerate(batches):
                if j == len(batches) - 1:
                    # pin the NEXT window's entitled slab view BEFORE
                    # the final batch's finds enter the corpus — the
                    # legacy prelaunch samples batch k+1's slab at
                    # exactly this point of batch k's harvest, and the
                    # bit-identical claim rides on reproducing it
                    self.mutator.snapshot_entitled_slab()
                for lane, result in enumerate(results):
                    crashes += self._harvest_lane(
                        lane, datas.get(lane, b""), result,
                        found_new=bool(flags[lane]))
        self._emit_timeouts(timeouts_before)
        with spans.span("restore"):
            # machine restore is in-graph (each batch's first phase);
            # only the target's host-side state rolls back here, ONCE
            # per window — megachunk targets are declarative-insert
            # targets whose restore carries no per-batch host state
            self.target.restore()
        # the caller (fuzz) advances batches_done by one per
        # run_one_batch; fold this window's extra completed batches in
        self.batches_done += len(batches) - 1
        return crashes

    def _save_crash(self, data: bytes, result: Crash,
                    bucket: Optional[str] = None) -> None:
        name = result.name or f"crash-{hex_digest(data)[:16]}"
        bucket = bucket or name
        new = bucket not in self.crash_buckets
        self.crash_buckets.add(bucket)
        self.crash_names.add(name)
        if self.crashes_dir:
            from wtf_tpu.utils.atomicio import atomic_write_bytes

            try:
                # atomic (tmp+fsync+rename): a kill mid-save must not
                # leave a torn repro, and a full disk must not abort the
                # campaign from inside the harvest loop (same contract
                # as the dist master's crash save).  With a store the
                # blob is journaled content-addressed (bucket-deduped)
                # and crashes/<name> becomes a view of it — names stay
                # reference-shaped for the single-process driver.
                if self.store is not None:
                    digest, _ = self.store.put(data, kind="crash",
                                               name=name, bucket=bucket)
                    if self.store.has(digest):
                        self.store.link_into(self.crashes_dir, digest,
                                             name=name)
                else:
                    atomic_write_bytes(self.crashes_dir / name, data)
            except OSError as e:
                import logging

                logging.getLogger(__name__).warning(
                    "crash save failed for %r: %s", name, e)
                self.events.emit("error", kind="crash-save", name=name,
                                 detail=str(e))
        self.events.emit("crash", name=name, size=len(data), new=new,
                         bucket=bucket)

    def _peek(self, name: str):
        """Counter value WITHOUT registering it — the heartbeat must not
        seed zero-valued metrics into dumps of campaigns that never
        touched the subsystem."""
        metric = self.registry._metrics.get(name)
        return metric.value if metric is not None else 0

    def steady_state_fields(self) -> dict:
        """The PR-14 zero-host steady-state numbers, as heartbeat fields
        — live visibility for the claim telemetry_report proves
        post-mortem.  Empty for campaigns that never ran a window."""
        fields = {}
        windows = self._peek("megachunk.windows")
        if windows:
            fields["zero_host_window_rate"] = round(
                self._peek("devdec.zero_host_windows") / windows, 3)
        prelaunched = self._peek("megachunk.prelaunched")
        if prelaunched:
            fields["prelaunch_hits"] = self._peek(
                "megachunk.prelaunch_hits")
            fields["prelaunch_dropped"] = self._peek(
                "megachunk.prelaunch_dropped")
        crosschecks = self._peek("devdec.crosscheck_mismatches")
        if self._peek("devdec.published") or crosschecks:
            fields["devdec_crosscheck_mismatches"] = crosschecks
        return fields

    def _steady_line_suffix(self, fields: dict) -> str:
        """The same numbers on the human line — shown only when the
        campaign runs windows, so plain-campaign line format is
        untouched."""
        out = ""
        if "zero_host_window_rate" in fields:
            out += f" zh: {fields['zero_host_window_rate']:.0%}"
        if "prelaunch_hits" in fields:
            launched = self._peek("megachunk.prelaunched")
            out += f" pre: {fields['prelaunch_hits']}/{launched}"
            if fields.get("prelaunch_dropped"):
                out += f"(-{fields['prelaunch_dropped']})"
        return out

    def _heartbeat(self, print_stats: bool) -> None:
        """stats_every cadence: the stable human line + one JSONL
        heartbeat carrying the full registry dump (per-phase span totals
        included) + an atomic status.json refresh next to the event log
        (what `wtf-tpu status` tails on a live local campaign)."""
        fields = (self.supervisor.heartbeat_fields()
                  if self.supervisor is not None
                  and self.supervisor.enabled else {})
        steady = self.steady_state_fields()
        fields.update(steady)
        line = self.stats.maybe_heartbeat(
            self.events, self.registry,
            lambda: self.stats.line(len(self.corpus), self._coverage())
            + self._steady_line_suffix(steady),
            every=self.stats_every, print_stats=print_stats, **fields)
        if line is not None:
            self._write_status(line)

    def _write_status(self, line: str) -> None:
        """status.json beside events.jsonl, atomically replaced every
        heartbeat — readers (wtf-tpu status --watch) always see either
        the previous complete document or this one, never a torn
        middle.  Best-effort like every telemetry side channel."""
        path = getattr(self.events, "path", None)
        if path is None:
            return
        import json

        from wtf_tpu.utils.atomicio import atomic_write_text

        doc = {"kind": "campaign", "ts": time.time(), "line": line,
               "batches": self.batches_done,
               "metrics": self.registry.dump()}
        try:
            atomic_write_text(Path(path).parent / "status.json",
                              json.dumps(doc, default=str), fsync=False)
        except OSError:
            pass

    def _maybe_xprof(self) -> None:
        """Arm/disarm the one device-profiler window at batch
        boundaries.  Best-effort: a platform without profiler support
        logs once and the campaign proceeds unprofiled."""
        if self.xprof_dir is None or self._xprof_done:
            return
        if not self._xprof_active:
            if self.batches_done < self.xprof_skip:
                return
            try:
                import jax

                jax.profiler.start_trace(str(self.xprof_dir))
            except Exception as e:  # noqa: BLE001 - profiler is optional
                import logging

                logging.getLogger(__name__).warning(
                    "xprof trace unavailable: %s", e)
                self.events.emit("error", kind="xprof-start",
                                 detail=str(e))
                self._xprof_done = True
                return
            self._xprof_active = True
            self._xprof_start_batch = self.batches_done
            self.events.emit("xprof-start", batch=self.batches_done,
                             dir=str(self.xprof_dir))
            return
        if (self.batches_done
                >= self._xprof_start_batch + self.xprof_batches):
            self._stop_xprof()

    def _stop_xprof(self) -> None:
        if not self._xprof_active:
            return
        self._xprof_active = False
        self._xprof_done = True
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            self.events.emit("error", kind="xprof-stop", detail=str(e))
            return
        self.events.emit("xprof-stop", batch=self.batches_done,
                         batches=self.batches_done
                         - self._xprof_start_batch,
                         dir=str(self.xprof_dir))

    def minset(self, outputs_dir, print_stats: bool = False) -> Corpus:
        """`--runs=0` mode: replay the seed corpus exactly once — no
        mutation — and write the coverage-increasing subset to outputs/
        (the reference master's minset, server.h:552-556; seeds are
        visited biggest-first per Corpus.load_dir, so the subset is
        coverage-minimal under that ordering).  Returns the kept Corpus
        (callers prune subsumed stale files with its digest set).

        Runs on the triage batch-replay core (wtf_tpu/triage/replay.py)
        — minset and `triage distill` share ONE batched execution path;
        the accounting, span names, keep rule (first-hit credit via the
        backend's batch merge) and printed stats are unchanged."""
        from wtf_tpu.triage.replay import ReplayCore

        # Corpus handles digest-named persistence + dedup; outputs_dir=None
        # (no outputs configured) counts without writing
        kept = Corpus(outputs_dir=outputs_dir)
        core = ReplayCore(self.backend, self.target,
                          registry=self.registry, events=self.events,
                          batch_size=self.batch_size)

        def harvest(start, batch, results):
            for lane, (data, result) in enumerate(zip(batch, results)):
                self._account(data, result, lane=lane)
                if self.backend.lane_found_new_coverage(lane):
                    self.stats.new_coverage += 1
                    kept.add(data)

        core.replay(list(self.corpus), on_batch=harvest,
                    after_batch=lambda: self._heartbeat(print_stats))
        return kept

    def fuzz(self, runs: int, print_stats: bool = False,
             stop_on_crash: bool = False) -> CampaignStats:
        """Run until `runs` testcases executed (0 = forever; the CLI maps
        --runs=0 to `minset` instead, matching the reference)."""
        self.reshard_to = None
        self._runs_budget = runs
        try:
            while runs == 0 or self.stats.testcases < runs:
                self._maybe_xprof()
                found = self.run_one_batch()
                self.batches_done += 1
                self._maybe_checkpoint()
                if self._maybe_reshard():
                    break
                self._heartbeat(print_stats)
                if stop_on_crash and found:
                    break
        finally:
            self._stop_xprof()
        return self.stats

    def _maybe_reshard(self) -> bool:
        """The elastic-campaign policy hook (wtf_tpu/fleet/elastic): at
        each batch boundary the policy may name a new device count; the
        loop then checkpoints (PR-8 format — placement-free) and stops,
        leaving `reshard_to` for the driver to rebuild against.  True
        when a reshard was requested."""
        if self.reshard_policy is None:
            return False
        want = self.reshard_policy(self)
        if want is None:
            return False
        if self.checkpoint_dir is None:
            raise ValueError("resharding needs a checkpoint_dir")
        from wtf_tpu.resume import save_campaign

        self.reshard_to = int(want)
        # count BEFORE the save: the checkpoint's counter state carries
        # the reshard tally across placements (telemetry continuity)
        self.registry.counter("campaign.reshards").inc()
        self.events.emit("reshard", batch=self.batches_done,
                         devices=self.reshard_to,
                         testcases=self.stats.testcases)
        save_campaign(self, self.checkpoint_dir)
        return True

    def _maybe_checkpoint(self) -> None:
        """--checkpoint-every cadence: persist the resumable state at the
        batch boundary (wtf_tpu/resume).  Best-effort like every other
        persistence side channel — a full disk degrades checkpointing
        with a warning + error event, it never aborts the campaign."""
        if not (self.checkpoint_dir and self.checkpoint_every):
            return
        if self.batches_done % self.checkpoint_every:
            return
        from wtf_tpu.resume import save_campaign

        spans = self.registry.spans
        before = spans.seconds("checkpoint")
        try:
            with spans.span("checkpoint"):
                info = save_campaign(self, self.checkpoint_dir)
        except OSError as e:
            import logging

            logging.getLogger(__name__).warning(
                "checkpoint write failed at batch %d: %s",
                self.batches_done, e)
            self.events.emit("error", kind="checkpoint-write",
                             batch=self.batches_done, detail=str(e))
            return
        self.registry.counter("campaign.checkpoints").inc()
        self.events.emit("checkpoint", batch=self.batches_done,
                         bytes=info["bytes"], path=info["path"],
                         seconds=round(spans.seconds("checkpoint")
                                       - before, 4))

    def _coverage(self) -> int:
        try:
            import numpy as np

            return int(np.count_nonzero(
                np.unpackbits(
                    np.asarray(self.backend._agg_cov).view("uint8"))))
        except Exception:
            return len(getattr(self.backend, "_aggregate_cov", ()))
