"""The standalone fuzz loop: mutate -> batch-execute -> harvest.

This is the single-process campaign driver — the reference needs a master
process + N client processes even on one machine (README.md:34-110); here
one process drives a whole device batch, and the distributed mode
(dist/client.py speaking to dist/server.py) reuses the same harvest logic
per node.

Per batch (the batched RunTestcaseAndRestore, client.cc:88-180):
  1. draw one testcase per lane from the mutator (corpus-seeded)
  2. backend.run_batch: insert + run every lane
  3. harvest: new-coverage lanes -> corpus + mutator cross-over seed;
     crashes -> crashes/<name>; timeouts already coverage-revoked
  4. target.restore + backend.restore
"""

from __future__ import annotations

import random
import time
from pathlib import Path
from typing import Optional

from wtf_tpu.core.results import Crash, Cr3Change, Ok, OverlayFull, Timedout
from wtf_tpu.fuzz.corpus import Corpus
from wtf_tpu.fuzz.mutator import Mutator
from wtf_tpu.utils.hashing import hex_digest
from wtf_tpu.utils.human import seconds_to_human


class CampaignStats:
    """Counters behind the status line (reference ServerStats_t / client
    stats, server.h:24-240, client.cc:7-84)."""

    def __init__(self):
        self.testcases = 0
        self.crashes = 0
        self.timeouts = 0
        self.cr3s = 0
        self.overlay_fulls = 0
        self.new_coverage = 0
        self.start = time.time()
        self.last_print = 0.0

    def execs_per_sec(self) -> float:
        dt = time.time() - self.start
        return self.testcases / dt if dt > 0 else 0.0

    def line(self, corpus_len: int, cov: int) -> str:
        uptime = seconds_to_human(time.time() - self.start)
        ovf = f" ovf: {self.overlay_fulls}" if self.overlay_fulls else ""
        return (f"#{self.testcases} cov: {cov} corp: {corpus_len} "
                f"exec/s: {self.execs_per_sec():.1f} "
                f"crash: {self.crashes} timeout: {self.timeouts} "
                f"cr3: {self.cr3s}{ovf} uptime: {uptime}")


class FuzzLoop:
    def __init__(
        self,
        backend,
        target,
        mutator: Mutator,
        corpus: Corpus,
        crashes_dir: Optional[Path] = None,
        batch_size: Optional[int] = None,
        stats_every: float = 10.0,
    ):
        self.backend = backend
        self.target = target
        self.mutator = mutator
        self.corpus = corpus
        self.crashes_dir = Path(crashes_dir) if crashes_dir else None
        if self.crashes_dir:
            self.crashes_dir.mkdir(parents=True, exist_ok=True)
        self.batch_size = batch_size or getattr(backend, "n_lanes", 1)
        self.stats = CampaignStats()
        self.stats_every = stats_every
        self.crash_names = set()
        # overlay-exhausted testcases get ONE honest re-run (they executed
        # on truncated memory); a second exhaustion drops them — the input
        # genuinely needs more dirty pages than the lane has slots
        self._requeue: list = []
        self._requeue_digests = set()

    def run_one_batch(self) -> int:
        """Returns the number of crashes found in this batch."""
        requeued, self._requeue = self._requeue[:self.batch_size], []
        fresh = self.batch_size - len(requeued)
        if hasattr(self.mutator, "get_new_batch"):
            # native engines mutate the whole batch in one C call
            testcases = requeued + (self.mutator.get_new_batch(
                self.corpus, fresh) if fresh else [])
        else:
            testcases = requeued + [
                self.mutator.get_new_testcase(self.corpus)
                for _ in range(fresh)]
        results = self.backend.run_batch(testcases, self.target)
        crashes = 0
        for lane, (data, result) in enumerate(zip(testcases, results)):
            self.stats.testcases += 1
            if isinstance(result, Timedout):
                self.stats.timeouts += 1
            elif isinstance(result, Cr3Change):
                self.stats.cr3s += 1
            elif isinstance(result, OverlayFull):
                self.stats.overlay_fulls += 1
                digest = hex_digest(data)
                if digest not in self._requeue_digests:
                    self._requeue_digests.add(digest)
                    self._requeue.append(data)
            elif isinstance(result, Crash):
                self.stats.crashes += 1
                crashes += 1
                self._save_crash(data, result)
            if self.backend.lane_found_new_coverage(lane):
                self.stats.new_coverage += 1
                if self.corpus.add(data):
                    self.mutator.on_new_coverage(data)
        self.target.restore()
        self.backend.restore()
        return crashes

    def _save_crash(self, data: bytes, result: Crash) -> None:
        name = result.name or f"crash-{hex_digest(data)[:16]}"
        self.crash_names.add(name)
        if self.crashes_dir:
            (self.crashes_dir / name).write_bytes(data)

    def minset(self, outputs_dir, print_stats: bool = False) -> Corpus:
        """`--runs=0` mode: replay the seed corpus exactly once — no
        mutation — and write the coverage-increasing subset to outputs/
        (the reference master's minset, server.h:552-556; seeds are
        visited biggest-first per Corpus.load_dir, so the subset is
        coverage-minimal under that ordering).  Returns the kept Corpus
        (callers prune subsumed stale files with its digest set)."""
        # Corpus handles digest-named persistence + dedup; outputs_dir=None
        # (no outputs configured) counts without writing
        kept = Corpus(outputs_dir=outputs_dir)
        seeds = list(self.corpus)
        for start in range(0, len(seeds), self.batch_size):
            batch = seeds[start:start + self.batch_size]
            results = self.backend.run_batch(batch, self.target)
            for lane, (data, result) in enumerate(zip(batch, results)):
                self.stats.testcases += 1
                if isinstance(result, Timedout):
                    self.stats.timeouts += 1
                elif isinstance(result, Cr3Change):
                    self.stats.cr3s += 1
                elif isinstance(result, OverlayFull):
                    self.stats.overlay_fulls += 1
                elif isinstance(result, Crash):
                    self.stats.crashes += 1
                    self._save_crash(data, result)
                if self.backend.lane_found_new_coverage(lane):
                    self.stats.new_coverage += 1
                    kept.add(data)
            self.target.restore()
            self.backend.restore()
            now = time.time()
            if print_stats and now - self.stats.last_print >= self.stats_every:
                self.stats.last_print = now
                print(self.stats.line(len(self.corpus), self._coverage()))
        return kept

    def fuzz(self, runs: int, print_stats: bool = False,
             stop_on_crash: bool = False) -> CampaignStats:
        """Run until `runs` testcases executed (0 = forever; the CLI maps
        --runs=0 to `minset` instead, matching the reference)."""
        while runs == 0 or self.stats.testcases < runs:
            found = self.run_one_batch()
            now = time.time()
            if print_stats and now - self.stats.last_print >= self.stats_every:
                self.stats.last_print = now
                print(self.stats.line(len(self.corpus), self._coverage()))
            if stop_on_crash and found:
                break
        return self.stats

    def _coverage(self) -> int:
        try:
            import numpy as np

            return int(np.count_nonzero(
                np.unpackbits(
                    np.asarray(self.backend._agg_cov).view("uint8"))))
        except Exception:
            return len(getattr(self.backend, "_aggregate_cov", ()))
