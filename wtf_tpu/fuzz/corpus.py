"""Corpus: the set of coverage-increasing testcases.

Reference `Corpus_t` (src/wtf/corpus.h): an in-memory vector of buffers with
uniform-random `PickTestcase` (corpus.h:89-102) and digest-named saves into
outputs/ (corpus.h:56-87; names are content hashes so re-finding the same
input is a no-op).
"""

from __future__ import annotations

import random
from pathlib import Path
from typing import List, Optional, Tuple

from wtf_tpu.utils.hashing import hex_digest


class Corpus:
    def __init__(self, outputs_dir: Optional[Path] = None,
                 rng: Optional[random.Random] = None, store=None):
        self.outputs_dir = Path(outputs_dir) if outputs_dir else None
        if self.outputs_dir:
            self.outputs_dir.mkdir(parents=True, exist_ok=True)
        self.rng = rng or random.Random()
        # content-addressed store (wtf_tpu/fleet/store.FleetStore): when
        # attached, the store is the system of record and the flat
        # outputs/ dir becomes a hardlink VIEW of it — same digest-named
        # files for the seed replay scan and minset pruning, but writes
        # land once, journaled, in the sharded blob tree
        self.store = store
        self._items: List[bytes] = []
        self._digests = set()
        self.bytes_total = 0

    def add(self, data: bytes) -> bool:
        """Insert + persist; returns False for duplicates (content hash)."""
        return self.add_digested(data, hex_digest(data))

    def add_digested(self, data: bytes, digest: str) -> bool:
        """`add` for callers that already hold the content digest."""
        if digest in self._digests:
            return False
        self._digests.add(digest)
        self._items.append(data)
        self.bytes_total += len(data)
        if self.store is not None:
            self.store.put(data, kind="corpus")
            if self.outputs_dir:
                self.store.link_into(self.outputs_dir, digest)
        elif self.outputs_dir:
            # atomic: a campaign killed mid-save must not leave a torn
            # outputs/ entry for the restarted master to replay (the
            # file IS the persistence the resume path relies on)
            from wtf_tpu.utils.atomicio import atomic_write_bytes

            atomic_write_bytes(self.outputs_dir / digest, data)
        return True

    def clear(self) -> None:
        """Drop every in-memory testcase (checkpoint restore rebuilds the
        corpus in manifest order).  Persisted outputs/ files stay — they
        are content-addressed and the restore re-adds by digest."""
        self._items.clear()
        self._digests.clear()
        self.bytes_total = 0

    def pick(self) -> Optional[bytes]:
        """Uniform random pick (corpus.h:89-102); None while empty."""
        if not self._items:
            return None
        return self.rng.choice(self._items)

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    @property
    def digests(self) -> set:
        return set(self._digests)

    @staticmethod
    def load_dir(path: Path, rng: Optional[random.Random] = None,
                 outputs_dir: Optional[Path] = None) -> "Corpus":
        """Seed from a directory of input files, biggest first (the
        reference master replays inputs/ sorted by size, server.h:399-414)."""
        corpus = Corpus(outputs_dir=outputs_dir, rng=rng)
        # with_data: each file is read exactly once (seed_paths already
        # read+digested it; a second read_bytes would double startup I/O
        # and open a TOCTOU window between digest and content)
        for _f, digest, data in seed_paths([path], with_data=True):
            corpus.add_digested(data, digest)
        return corpus


def seed_paths(dirs, with_data: bool = False,
               keep_dups: bool = False) -> List[tuple]:
    """Seed files from one or more directories as (path, content digest)
    pairs — (path, digest, bytes) triples when `with_data` — size-sorted
    biggest first and content-deduped (the reference master's replay
    ordering, server.h:399-414): the ONE implementation of that policy.
    `keep_dups` keeps content-duplicate files in the listing (callers
    that also need the full directory census, e.g. minset pruning).
    Without `with_data`, bytes are read transiently for digesting; files
    vanishing mid-scan are skipped either way."""
    sized = []
    for d in dirs:
        if not (d and Path(d).is_dir()):
            continue
        for p in Path(d).iterdir():
            try:
                if p.is_file():
                    sized.append((p.stat().st_size, p))
            except OSError:
                continue  # vanished mid-scan
    seen, out = set(), []
    for _, p in sorted(sized, key=lambda t: t[0], reverse=True):
        try:
            data = p.read_bytes()
        except OSError:
            continue  # vanished mid-scan
        digest = hex_digest(data)
        if keep_dups or digest not in seen:
            seen.add(digest)
            out.append((p, digest, data) if with_data else (p, digest))
    return out
