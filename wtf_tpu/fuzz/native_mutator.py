"""Native (C++) mutation engine binding.

SURVEY §2.6: the reference's mutator engines are compiled code (LLVM
libFuzzer's MutationDispatcher + the honggfuzz mangle port) because at
target throughput a per-testcase interpreted mutation call dominates the
host plane (round-2 VERDICT weak #7).  `NativeMangleMutator` drives
native/mangle.cc over ctypes; `get_new_batch` mutates a whole device
batch in ONE native call.  Falls back to the Python MangleMutator when no
toolchain is available.
"""

from __future__ import annotations

import ctypes
import random
from typing import List, Optional

import numpy as np

from wtf_tpu.fuzz.mutator import (
    MangleMutator, Mutator, generate_fresh,
)

_LIB: Optional[ctypes.CDLL] = None
_LIB_TRIED = False


def _native_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    from wtf_tpu.native import build_library

    path = build_library("wtfmangle", ["mangle.cc"])
    if path is None:
        return None
    lib = ctypes.CDLL(str(path))
    lib.wtf_mangle.restype = ctypes.c_uint64
    lib.wtf_mangle.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_uint64, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
    ]
    lib.wtf_mangle_batch.restype = None
    lib.wtf_mangle_batch.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
    ]
    _LIB = lib
    return lib


def native_available() -> bool:
    return _native_lib() is not None


class NativeMangleMutator(Mutator):
    """honggfuzz-mangle-role engine running in C++ (5 mutations per
    testcase like the reference wiring, mutator.cc:66)."""

    N_PER_RUN = 5

    def __init__(self, rng: random.Random, max_len: int):
        lib = _native_lib()
        if lib is None:
            raise RuntimeError(
                "native mangle library unavailable (no toolchain); "
                "use create_mutator('mangle', ...) instead")
        self._lib = lib
        self.rng = rng
        self.max_len = max_len
        self._cross: Optional[bytes] = None
        self._arena: Optional[np.ndarray] = None  # reused across batches

    def on_new_coverage(self, testcase: bytes) -> None:
        self._cross = testcase

    def _cross_args(self):
        if self._cross:
            buf = (ctypes.c_uint8 * len(self._cross)).from_buffer_copy(
                self._cross)
            return buf, len(self._cross)
        return None, 0

    def get_new_testcase(self, corpus) -> bytes:
        base = corpus.pick() if corpus is not None else None
        if not base:
            return generate_fresh(self.rng, self.max_len)
        buf = bytearray(base[:self.max_len].ljust(1, b"\x00"))
        buf.extend(b"\x00" * (self.max_len - len(buf)))
        arr = (ctypes.c_uint8 * self.max_len).from_buffer(buf)
        cross, cross_len = self._cross_args()
        new_len = self._lib.wtf_mangle(
            arr, min(len(base), self.max_len), self.max_len,
            self.rng.getrandbits(64), self.rng.randint(1, self.N_PER_RUN),
            cross, cross_len)
        return bytes(buf[:new_len])

    def get_new_batch(self, corpus, count: int) -> List[bytes]:
        """Mutate `count` testcases in one native call (one Python->C
        transition per device batch).

        The arena stride is sized to what this batch can actually grow to
        — NOT max_len, which defaults to 1 MiB and would make the arena a
        gigabyte at 1024 lanes.  Per-item growth per call is bounded by
        the op table (inserts and cross-over splices).  The arena is kept
        across batches and only reallocated when it must grow."""
        bases: List[bytes] = []
        for _ in range(count):
            base = corpus.pick() if corpus is not None else None
            if not base:
                base = generate_fresh(self.rng, self.max_len)
            bases.append(base[:self.max_len])
        cross_len = len(self._cross) if self._cross else 0
        max_base = max(len(b) for b in bases)
        # each of the <= N_PER_RUN ops can grow by an insert (<=16B) or a
        # cross-over splice (<= cross_len), so bound by the worst op mix
        cap = min(self.max_len,
                  max(64, max_base + self.N_PER_RUN * max(16, cross_len)))
        arena = self._arena
        if (arena is None or arena.shape[0] < count
                or arena.shape[1] < cap):
            arena = np.zeros((count, cap), dtype=np.uint8)
            self._arena = arena
        cap = arena.shape[1]
        lens = np.zeros(count, dtype=np.uint64)
        for i, base in enumerate(bases):
            arena[i, :len(base)] = np.frombuffer(base, dtype=np.uint8)
            lens[i] = len(base)
        cross, cross_n = self._cross_args()
        self._lib.wtf_mangle_batch(
            arena.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            cap, count, self.rng.getrandbits(64), self.N_PER_RUN,
            cross, cross_n)
        return [bytes(arena[i, :int(lens[i])].tobytes())
                for i in range(count)]


def best_mangle_mutator(rng: random.Random, max_len: int) -> Mutator:
    """Native engine when the toolchain allows, Python otherwise."""
    if native_available():
        return NativeMangleMutator(rng, max_len)
    return MangleMutator(rng, max_len)
