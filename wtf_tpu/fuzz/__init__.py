"""Fuzz plane: corpus management + mutation engines (SURVEY.md §2.3).

  corpus.py  - in-memory corpus with digest-named persistence
               (reference src/wtf/corpus.h)
  mutator.py - mutator interface + byte-level and honggfuzz-mangle-style
               engines + the structure-aware TLV example
               (reference src/wtf/mutator.{h,cc}, honggfuzz.cc:836)
"""

from wtf_tpu.fuzz.corpus import Corpus  # noqa: F401
from wtf_tpu.fuzz.mutator import (  # noqa: F401
    ByteMutator, MangleMutator, Mutator, TlvStructureMutator, create_mutator,
)
