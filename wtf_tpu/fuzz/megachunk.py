"""The megachunk: a whole multi-batch fuzz window as ONE compiled program.

The batch-at-a-time device loop (fuzz/loop.py `_run_one_batch_device`)
still consults the host between every phase of every batch: devmut
generation, the fused insert, the chunk ladder, the coverage merge, and
the overlay restore are five separate dispatches with host glue between
them.  This module folds them into one compiled multi-batch program — the
Concordia posture ROADMAP item 2(b) names: per batch, IN-GRAPH,

    restore -> devmut generate -> device insert -> run to quiescence ->
    finish-breakpoint rewrite -> prefix-credit coverage merge

iterated under a `lax.while_loop` for up to `n_batches` batches, so the
host's per-batch share collapses to the status pull and the harvest of
crash/new-coverage lanes.  The window returns early exactly when the host
is genuinely needed:

  * a batch ends with a SERVICEABLE lane (decode miss, SMC, oracle
    fallback, a non-finish breakpoint, deliverable fault): the machine
    comes back mid-batch and the ordinary Runner.run servicing loop
    finishes that batch — the cold-start path, byte-identical to the
    batch-at-a-time loop's servicing because it IS that loop;
  * a batch finds NEW COVERAGE: the window runs at most ONE more batch
    and stops, so the host can fold the finds into the corpus slab
    before any batch that is entitled to see them is generated;
  * a batch has a NON-CLEAN terminal (crash/fault/overlay-full): the
    window stops right there, so the machine the host reads for crash
    naming and stack-hash bucketing is exactly that batch's final state.

Slab schedule (the PR-6 prelaunch lag, preserved exactly): batch k's
generation samples the slab with finds from batches <= k-2.  The window
therefore takes TWO slab views — `slab_first` for its first batch,
`slab_rest` (the current host slab) for the batches after — and the
find-stop rule above guarantees no batch inside a window ever needs a
slab newer than `slab_rest`.  `slab_first` is the view the harvest
PINNED just before the previous window's FINAL batch's corpus adds
(DevMangleMutator.snapshot_entitled_slab): the next window's first
batch is absolute batch m+1 where m was that final batch, so its
entitlement is finds <= m-1 — exactly the pre-m's-adds state, and
exactly when the legacy prelaunch would have sampled it.  With
`n_batches=1` the program IS the batch-at-a-time device loop's
schedule, which is what the parity tests pin (tests/test_megachunk.py:
12-batch campaigns with finds in IN-GRAPH batches, B=4 vs B=1 vs the
legacy loop, byte-identical).

The finish-breakpoint rewrite is the declarative form of the stop
handler every wtf-style target plants at its return address
(`b.stop(Ok())`): a lane parked at BREAKPOINT with rip ==
`DeviceInsertSpec.finish_gva` becomes OK in-graph, bit-for-bit what the
host handler would have done (the breakpointed instruction never
executes, no coverage bit, no icount).  Targets with richer handlers
simply park the batch to the host path — correct, just not fused.

The mesh variant wraps the SAME body in shard_map: machine/template/
seeds lane-sharded, slabs and the uop table replicated, the per-batch
merge the shard-aware prefix-credit core (meshrun/reduce
.mesh_merge_local), and the loop-control scalars (stop/find/incomplete)
all-reduced so every shard's while_loop stays in lockstep.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from wtf_tpu.core.results import StatusCode
from wtf_tpu.interp.machine import Machine, N_CTRS, _machine_restore_impl
from wtf_tpu.interp.runner import device_insert_impl
from wtf_tpu.interp.step import step_lane
from wtf_tpu.interp.uoptable import UopTable
from wtf_tpu.mem.physmem import IMAGE_IN_AXES, MemImage, lane_image
from wtf_tpu.meshrun.reduce import merge_coverage, mesh_merge_local

_RUNNING = int(StatusCode.RUNNING)
_OK = int(StatusCode.OK)
_TIMEDOUT = int(StatusCode.TIMEDOUT)
_CR3 = int(StatusCode.CR3_CHANGE)
_OVF = int(StatusCode.OVERLAY_FULL)
_BP = int(StatusCode.BREAKPOINT)

# statuses the host servicing loop owns; PAGE_FAULT/DIVIDE_ERROR join
# when the campaign delivers guest exceptions (runner.deliver_exceptions)
SERVICEABLE_BASE = (int(StatusCode.NEED_DECODE), int(StatusCode.SMC),
                    int(StatusCode.UNSUPPORTED), int(StatusCode.BREAKPOINT))
SERVICEABLE_DELIVER = SERVICEABLE_BASE + (int(StatusCode.PAGE_FAULT),
                                          int(StatusCode.DIVIDE_ERROR))

# rip sentinel for "no declarative finish breakpoint": unaligned-odd and
# non-canonical-adjacent, unreachable as an armed-breakpoint rip
NO_FINISH = 1

_MEGA_CACHE: dict = {}


class MegaSnap(NamedTuple):
    """Per-batch harvest snapshot carried for the last two processed
    batches: the generated testcase words/lens, so crash/new-coverage
    lanes' bytes are fetchable without regenerating the batch.  Crash
    DETAIL never needs snapshotting — a non-clean terminal stops the
    window, so the live machine IS that batch's final state."""

    words: jax.Array       # uint32[L, W]
    lens: jax.Array        # int32[L]


class MegaOut(NamedTuple):
    machine: Machine
    agg_cov: jax.Array
    agg_edge: jax.Array
    batches: jax.Array       # int32: COMPLETED batches this window
    incomplete: jax.Array    # bool: machine is mid-batch `batches`
    statuses: jax.Array      # int32[B, L]; -1 = batch not completed
    new_flags: jax.Array     # bool[B, L] per-batch new-coverage credit
    ctr_sums: jax.Array      # uint64[B, N_CTRS] per-batch counter totals
    new_words: jax.Array     # uint32[cov_w] last completed batch's delta
    prev: MegaSnap
    cur: MegaSnap


def _snap(words, lens) -> MegaSnap:
    return MegaSnap(words=words, lens=lens)


def _make_body(max_batches: int, n_pages: int, len_gpr: int, ptr_gpr: int,
               rounds: int, deliver: bool, merge_fn, any_fn, sum_fn):
    """The window body shared by the single-device and mesh programs.
    `merge_fn` is the batch coverage merge, `any_fn` a (possibly
    cross-shard) boolean any, `sum_fn` a (possibly psum'd) per-batch
    counter total."""
    from wtf_tpu.devmut.engine import generate

    insert = device_insert_impl(n_pages, len_gpr, ptr_gpr)
    step_v = jax.vmap(step_lane, in_axes=(None, IMAGE_IN_AXES, 0, None))
    serviceable = SERVICEABLE_DELIVER if deliver else SERVICEABLE_BASE
    B = max_batches

    def run_quiesce(tab, image, m, limit):
        """The run-chunk ladder folded in: step until NO lane is RUNNING
        (decode misses, breakpoints and terminals all leave RUNNING, and
        a nonzero instruction budget bounds the rest — the driver
        enforces limit > 0 before building a megachunk)."""

        def cond(mm):
            return jnp.any(mm.status == jnp.int32(_RUNNING))

        def body(mm):
            return step_v(tab, image, mm, limit)

        return lax.while_loop(cond, body, m)

    def window(tab: UopTable, image: MemImage, machine: Machine,
               template: Machine, slab_first: Tuple, slab_rest: Tuple,
               seeds, pfns, gva_l, finish_l, limit, n_batches,
               agg_cov, agg_edge) -> MegaOut:
        n_lanes = machine.status.shape[0]
        image = lane_image(image, n_lanes)
        n_words = slab_first[0].shape[1]
        statuses0 = jnp.full((B, n_lanes), -1, jnp.int32)
        flags0 = jnp.zeros((B, n_lanes), bool)
        ctrs0 = jnp.zeros((B, N_CTRS), jnp.uint64)
        snap0 = MegaSnap(
            words=jnp.zeros((n_lanes, n_words), jnp.uint32),
            lens=jnp.zeros((n_lanes,), jnp.int32))
        nw0 = jnp.zeros_like(agg_cov)

        def cond(carry):
            b, stop = carry[0], carry[1]
            return (b < n_batches) & ~stop

        def body(carry):
            (b, _stop, incomplete, find_b, m, agg_c, agg_e, sts, flags,
             ctrs, nw, prev, cur) = carry
            first = b == 0
            data = jnp.where(first, slab_first[0], slab_rest[0])
            lens_s = jnp.where(first, slab_first[1], slab_rest[1])
            cumw = jnp.where(first, slab_first[2], slab_rest[2])
            m = _machine_restore_impl(m, template)
            words, lens = generate(data, lens_s, cumw, seeds[b],
                                   rounds=rounds)
            m = insert(m, words, lens, pfns, gva_l)
            m = run_quiesce(tab, image, m, limit)
            # declarative stop: BREAKPOINT at the finish rip == the
            # host handler's stop(Ok()) — pre-execution, so no icount /
            # coverage for the breakpointed instruction, like the host
            st = jnp.where((m.status == jnp.int32(_BP))
                           & (m.rip == finish_l), jnp.int32(_OK), m.status)
            m = m._replace(status=st)

            svc = jnp.zeros_like(st, bool)
            for s in serviceable:
                svc = svc | (st == jnp.int32(s))
            need_service = any_fn(svc)
            complete = ~need_service

            include = ((st != jnp.int32(_TIMEDOUT))
                       & (st != jnp.int32(_OVF)))
            agg_c2, agg_e2, new_lane, new_w = merge_fn(
                agg_c, agg_e, m.cov, m.edge, include)
            agg_c3 = jnp.where(complete, agg_c2, agg_c)
            agg_e3 = jnp.where(complete, agg_e2, agg_e)
            new_lane = new_lane & complete
            clean = ((st == jnp.int32(_OK)) | (st == jnp.int32(_TIMEDOUT))
                     | (st == jnp.int32(_CR3)))
            crashy = complete & any_fn(~clean)
            has_cov_find = complete & any_fn(new_lane)
            find_b2 = jnp.where(has_cov_find & (find_b >= B), b, find_b)

            sts2 = sts.at[b].set(jnp.where(complete, st, sts[b]))
            flags2 = flags.at[b].set(new_lane)
            ctrs2 = ctrs.at[b].set(jnp.where(
                complete, sum_fn(m.ctr), ctrs[b]))
            nw2 = jnp.where(complete, new_w, nw)
            prev2, cur2 = cur, _snap(words, lens)
            b2 = b + complete.astype(jnp.int32)
            # find-stop: after a new-coverage find at batch j the window
            # may run j+1 (its slab view is still entitled) and must then
            # return so the host folds the finds before j+2 generates; a
            # non-clean terminal stops immediately so the live machine
            # stays that batch's final state for crash naming/bucketing
            stop2 = need_service | crashy \
                | (complete & (b + 1 > find_b2 + 1))
            return (b2, stop2, incomplete | need_service, find_b2, m,
                    agg_c3, agg_e3, sts2, flags2, ctrs2, nw2, prev2, cur2)

        init = (jnp.int32(0), jnp.bool_(False), jnp.bool_(False),
                jnp.int32(B), machine, agg_cov, agg_edge, statuses0,
                flags0, ctrs0, nw0, snap0, snap0)
        (b, _stop, incomplete, _fb, m, agg_c, agg_e, sts, flags, ctrs,
         nw, prev, cur) = lax.while_loop(cond, body, init)
        return MegaOut(machine=m, agg_cov=agg_c, agg_edge=agg_e,
                       batches=b, incomplete=incomplete, statuses=sts,
                       new_flags=flags, ctr_sums=ctrs, new_words=nw,
                       prev=prev, cur=cur)

    return window


def make_megachunk(max_batches: int, n_pages: int, len_gpr: int,
                   ptr_gpr: int, rounds: int, deliver: bool):
    """Build (or fetch) the jitted single-device megachunk window:
    (tab, image, machine, template, slab_first, slab_rest, seeds[B,L,2],
    pfns, gva_l, finish, limit, n_batches, agg_cov, agg_edge) -> MegaOut.

    No donation: the CPU stand-in is where tier-1 runs this (donation is
    unsound on XLA CPU, step.make_run_chunk's caveat), and the first
    hardware window will revisit the policy with the rest of the
    donation ledger."""
    key = ("1dev", max_batches, n_pages, len_gpr, ptr_gpr, rounds,
           deliver)
    cached = _MEGA_CACHE.get(key)
    if cached is not None:
        return cached

    def sum_fn(ctr):
        return jnp.sum(ctr.astype(jnp.uint64), axis=0)

    body = _make_body(max_batches, n_pages, len_gpr, ptr_gpr, rounds,
                      deliver, merge_fn=merge_coverage, any_fn=jnp.any,
                      sum_fn=sum_fn)
    fn = jax.jit(body)
    _MEGA_CACHE[key] = fn
    return fn


def make_mesh_megachunk(max_batches: int, n_pages: int, len_gpr: int,
                        ptr_gpr: int, rounds: int, deliver: bool, mesh):
    """The megachunk window per shard under shard_map: machine/template/
    seed-stream/snapshots lane-sharded, slabs + uop table + aggregates
    replicated, the per-batch merge the shard-aware prefix-credit core,
    and every loop-control scalar all-reduced so the shards' while_loops
    stay in lockstep (identical trip counts, matched collectives)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from wtf_tpu.meshrun.executor import IMAGE_SPEC
    from wtf_tpu.meshrun.mesh import LANE_AXIS

    key = ("mesh", max_batches, n_pages, len_gpr, ptr_gpr, rounds,
           deliver, mesh)
    cached = _MEGA_CACHE.get(key)
    if cached is not None:
        return cached

    def any_fn(x):
        return lax.pmax(jnp.any(x).astype(jnp.int32), LANE_AXIS) > 0

    def sum_fn(ctr):
        return lax.psum(jnp.sum(ctr.astype(jnp.uint64), axis=0),
                        LANE_AXIS)

    def merge_fn(agg_cov, agg_edge, cov, edge, include):
        return mesh_merge_local(agg_cov, agg_edge, cov, edge, include,
                                LANE_AXIS)

    body = _make_body(max_batches, n_pages, len_gpr, ptr_gpr, rounds,
                      deliver, merge_fn=merge_fn, any_fn=any_fn,
                      sum_fn=sum_fn)
    lane_snap = MegaSnap(words=P(LANE_AXIS), lens=P(LANE_AXIS))
    fn = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(), IMAGE_SPEC, P(LANE_AXIS), P(LANE_AXIS),
                  (P(), P(), P()), (P(), P(), P()), P(None, LANE_AXIS),
                  P(), P(), P(), P(), P(), P(), P()),
        out_specs=MegaOut(
            machine=P(LANE_AXIS), agg_cov=P(), agg_edge=P(),
            batches=P(), incomplete=P(), statuses=P(None, LANE_AXIS),
            new_flags=P(None, LANE_AXIS), ctr_sums=P(), new_words=P(),
            prev=lane_snap, cur=lane_snap),
        check_rep=False))
    _MEGA_CACHE[key] = fn
    return fn
