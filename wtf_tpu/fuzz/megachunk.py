"""The megachunk: a whole multi-batch fuzz window as ONE compiled program.

The batch-at-a-time device loop (fuzz/loop.py `_run_one_batch_device`)
still consults the host between every phase of every batch: devmut
generation, the fused insert, the chunk ladder, the coverage merge, and
the overlay restore are five separate dispatches with host glue between
them.  This module folds them into one compiled multi-batch program — the
Concordia posture ROADMAP item 2(b) names: per batch, IN-GRAPH,

    restore -> devmut generate -> device insert -> run to quiescence ->
    finish-breakpoint rewrite -> prefix-credit coverage merge

iterated under a `lax.while_loop` for up to `n_batches` batches, so the
host's per-batch share collapses to the status pull and the harvest of
crash/new-coverage lanes.  The window returns early exactly when the host
is genuinely needed:

  * a batch ends with a SERVICEABLE lane (decode miss, SMC, oracle
    fallback, a non-finish breakpoint, deliverable fault): the machine
    comes back mid-batch and the ordinary Runner.run servicing loop
    finishes that batch — the cold-start path, byte-identical to the
    batch-at-a-time loop's servicing because it IS that loop;
  * a batch finds NEW COVERAGE: the window runs at most ONE more batch
    and stops, so the host can fold the finds into the corpus slab
    before any batch that is entitled to see them is generated;
  * a batch has a NON-CLEAN terminal (crash/fault/overlay-full): the
    window stops right there, so the machine the host reads for crash
    naming and stack-hash bucketing is exactly that batch's final state.

Slab schedule (the PR-6 prelaunch lag, preserved exactly): batch k's
generation samples the slab with finds from batches <= k-2.  The window
therefore takes TWO slab views — `slab_first` for its first batch,
`slab_rest` (the current host slab) for the batches after — and the
find-stop rule above guarantees no batch inside a window ever needs a
slab newer than `slab_rest`.  `slab_first` is the view the harvest
PINNED just before the previous window's FINAL batch's corpus adds
(DevMangleMutator.snapshot_entitled_slab): the next window's first
batch is absolute batch m+1 where m was that final batch, so its
entitlement is finds <= m-1 — exactly the pre-m's-adds state, and
exactly when the legacy prelaunch would have sampled it.  With
`n_batches=1` the program IS the batch-at-a-time device loop's
schedule, which is what the parity tests pin (tests/test_megachunk.py:
12-batch campaigns with finds in IN-GRAPH batches, B=4 vs B=1 vs the
legacy loop, byte-identical).

The finish-breakpoint rewrite is the declarative form of the stop
handler every wtf-style target plants at its return address
(`b.stop(Ok())`): a lane parked at BREAKPOINT with rip ==
`DeviceInsertSpec.finish_gva` becomes OK in-graph, bit-for-bit what the
host handler would have done (the breakpointed instruction never
executes, no coverage bit, no icount).  Targets with richer handlers
simply park the batch to the host path — correct, just not fused.

The mesh variant wraps the SAME body in shard_map: machine/template/
seeds lane-sharded, slabs and the uop table replicated, the per-batch
merge the shard-aware prefix-credit core (meshrun/reduce
.mesh_merge_local), and the loop-control scalars (stop/find/incomplete)
all-reduced so every shard's while_loop stays in lockstep.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from wtf_tpu.core.results import StatusCode
from wtf_tpu.interp.machine import Machine, N_CTRS, _machine_restore_impl
from wtf_tpu.interp.runner import device_insert_impl
from wtf_tpu.interp.step import step_lane
from wtf_tpu.interp.uoptable import UopTable
from wtf_tpu.mem.physmem import IMAGE_IN_AXES, MemImage, lane_image
from wtf_tpu.meshrun.reduce import merge_coverage, mesh_merge_local

_RUNNING = int(StatusCode.RUNNING)
_OK = int(StatusCode.OK)
_TIMEDOUT = int(StatusCode.TIMEDOUT)
_CR3 = int(StatusCode.CR3_CHANGE)
_OVF = int(StatusCode.OVERLAY_FULL)
_BP = int(StatusCode.BREAKPOINT)

# statuses the host servicing loop owns; PAGE_FAULT/DIVIDE_ERROR join
# when the campaign delivers guest exceptions (runner.deliver_exceptions)
SERVICEABLE_BASE = (int(StatusCode.NEED_DECODE), int(StatusCode.SMC),
                    int(StatusCode.UNSUPPORTED), int(StatusCode.BREAKPOINT))
SERVICEABLE_DELIVER = SERVICEABLE_BASE + (int(StatusCode.PAGE_FAULT),
                                          int(StatusCode.DIVIDE_ERROR))

# rip sentinel for "no declarative finish breakpoint": unaligned-odd and
# non-canonical-adjacent, unreachable as an armed-breakpoint rip
NO_FINISH = 1

_MEGA_CACHE: dict = {}


class MegaSnap(NamedTuple):
    """Per-batch harvest snapshot carried for the last two processed
    batches: the generated testcase words/lens, so crash/new-coverage
    lanes' bytes are fetchable without regenerating the batch.  Crash
    DETAIL never needs snapshotting — a non-clean terminal stops the
    window, so the live machine IS that batch's final state."""

    words: jax.Array       # uint32[L, W]
    lens: jax.Array        # int32[L]


class MegaOut(NamedTuple):
    machine: Machine
    agg_cov: jax.Array
    agg_edge: jax.Array
    batches: jax.Array       # int32: COMPLETED batches this window
    incomplete: jax.Array    # bool: machine is mid-batch `batches`
    statuses: jax.Array      # int32[B, L]; -1 = batch not completed
    new_flags: jax.Array     # bool[B, L] per-batch new-coverage credit
    ctr_sums: jax.Array      # uint64[B, N_CTRS] per-batch counter totals
    new_words: jax.Array     # uint32[cov_w] last completed batch's delta
    prev: MegaSnap
    cur: MegaSnap
    # --device-decode outputs (== inputs when the window was built
    # without devdec): the post-window table with device-published rows,
    # its live entry count (-1 when devdec off), and i32[4] stats
    # (serviced lanes, published entries, parked lanes, service rounds)
    tab: UopTable
    count: jax.Array
    dd_stats: jax.Array
    # step-engine round census, int32[2] = [XLA step_v sweeps, Pallas
    # kernel dispatches] summed over the window (psum'd across shards on
    # a mesh).  The window's data-dependent kernel count derives from
    # this: sweeps x the per-step census (budgets.json `xla_step` total)
    # + one kernel per Pallas dispatch — the ablate fused-mega currency.
    engine_rounds: jax.Array


def _snap(words, lens) -> MegaSnap:
    return MegaSnap(words=words, lens=lens)


def _make_body(max_batches: int, n_pages: int, len_gpr: int, ptr_gpr: int,
               rounds: int, deliver: bool, merge_fn, any_fn, sum_fn,
               devdec_on: bool = False, gather_fn=None,
               lane_base_fn=None, fused: bool = False, fused_k: int = 32,
               fused_resume_steps: int = 1, interpret: bool = True,
               rsum_fn=None):
    """The window body shared by the single-device and mesh programs.
    `merge_fn` is the batch coverage merge, `any_fn` a (possibly
    cross-shard) boolean any, `sum_fn` a (possibly psum'd) per-batch
    counter total, `rsum_fn` the (possibly psum'd) engine-round total.

    With `fused` the quiesce runs the Pallas kernel (interp/pstep) as
    the window's step engine: each round is ONE kernel dispatch
    advancing every lane up to `fused_k` hot instructions, then the XLA
    resume leg retires the one instruction each parked lane stopped on
    (`fused_resume_steps` sweeps, statuses swapped/held exactly like
    Runner._fused_dispatch).  The ladder quiesce — one step_v sweep per
    data-dependent kernel census — remains the park-resume leg only, so
    a steady-state window pays ~1 kernel per `fused_k` instructions
    instead of the full per-step census.  Every instruction still
    retires bit-exact through exactly one engine (the pstep parity
    contract), so fused-window campaigns are byte-identical to
    ladder-window ones.

    With `devdec_on` the window grows three operands — the live decode
    cache count, the padded pending-breakpoint key vector, and its live
    length — and decode misses are serviced IN-GRAPH (interp/devdec):
    each quiesce that leaves NEED_DECODE lanes runs a service round
    (per-lane block decode + walk, then a sequential global commit that
    replays the host service's publish order exactly), re-quiescing
    until every miss is serviced or parked.  Parked lanes stay
    NEED_DECODE, so the ordinary early-return -> host service path picks
    them up — bit-identical tables either way.  On a mesh, `gather_fn`
    all-gathers the per-shard blocks so every shard runs the SAME
    replicated commit (slot reservation is shard-correct by
    construction: one deterministic global order, no per-shard
    partitioning to reconcile), and `lane_base_fn` locates the shard's
    lane span in the committed global arrays."""
    from wtf_tpu.devmut.engine import generate
    from wtf_tpu.interp import devdec as DD
    from wtf_tpu.interp.machine import CTR_MEM_FAULT

    insert = device_insert_impl(n_pages, len_gpr, ptr_gpr)
    step_v = jax.vmap(step_lane, in_axes=(None, IMAGE_IN_AXES, 0, None))
    serviceable = SERVICEABLE_DELIVER if deliver else SERVICEABLE_BASE
    _ND = int(StatusCode.NEED_DECODE)
    B = max_batches
    if rsum_fn is None:
        def rsum_fn(r):
            return r

    if fused:
        from wtf_tpu.interp.pstep import fused_call_impl, fused_resume_impl

        def run_quiesce(tab, image, m, limit):
            """The FUSED quiesce: the Pallas kernel is the step engine,
            the XLA ladder only the park-resume leg.  Terminates for the
            same reason the ladder does — every round retires >= 1
            instruction per still-RUNNING lane (in-kernel, or precisely
            via the resume sweep for an immediately-parking lane), and a
            nonzero instruction budget bounds the rest.  Returns
            (machine, int32[2] = [xla sweeps, pallas dispatches])."""

            def cond(c):
                return jnp.any(c[0].status == jnp.int32(_RUNNING))

            def qbody(c):
                mm, xla_n, pl_n = c
                mm = fused_call_impl(tab, image, mm, limit,
                                     k_steps=fused_k, interpret=interpret)
                mm, iters = fused_resume_impl(
                    tab, image, mm, limit, n_steps=fused_resume_steps)
                return mm, xla_n + iters, pl_n + jnp.int32(1)

            m, xla_n, pl_n = lax.while_loop(
                cond, qbody, (m, jnp.int32(0), jnp.int32(0)))
            return m, jnp.stack([xla_n, pl_n])
    else:
        def run_quiesce(tab, image, m, limit):
            """The run-chunk ladder folded in: step until NO lane is
            RUNNING (decode misses, breakpoints and terminals all leave
            RUNNING, and a nonzero instruction budget bounds the rest —
            the driver enforces limit > 0 before building a megachunk).
            Returns (machine, int32[2] = [xla sweeps, 0])."""

            def cond(c):
                return jnp.any(c[0].status == jnp.int32(_RUNNING))

            def qbody(c):
                mm, n = c
                return step_v(tab, image, mm, limit), n + jnp.int32(1)

            m, n = lax.while_loop(cond, qbody, (m, jnp.int32(0)))
            return m, jnp.stack([n, jnp.int32(0)])

    def _window(tab: UopTable, image: MemImage, machine: Machine,
                template: Machine, slab_first: Tuple, slab_rest: Tuple,
                seeds, pfns, gva_l, finish_l, limit, n_batches,
                agg_cov, agg_edge, dd) -> MegaOut:
        n_lanes = machine.status.shape[0]
        image = lane_image(image, n_lanes)
        n_words = slab_first[0].shape[1]
        statuses0 = jnp.full((B, n_lanes), -1, jnp.int32)
        flags0 = jnp.zeros((B, n_lanes), bool)
        ctrs0 = jnp.zeros((B, N_CTRS), jnp.uint64)
        snap0 = MegaSnap(
            words=jnp.zeros((n_lanes, n_words), jnp.uint32),
            lens=jnp.zeros((n_lanes,), jnp.int32))
        nw0 = jnp.zeros_like(agg_cov)
        if devdec_on:
            count0, bp_keys, n_bp = dd
            capacity = tab.rip_l.shape[0]
            lane_base = (lane_base_fn(n_lanes) if lane_base_fn is not None
                         else jnp.int32(0))

            def gather(tree):
                if gather_fn is None:
                    return tree
                return jax.tree.map(gather_fn, tree)

            def lane_slice(a):
                if gather_fn is None:
                    return a
                return lax.dynamic_slice_in_dim(a, lane_base, n_lanes, 0)

            def service(tabst, cnt, m, dstats, er):
                """In-graph decode-miss service rounds around the
                quiesce: compute per-lane blocks against the round-start
                table, commit them in global lane order (replicated on a
                mesh), apply this shard's lane deltas, re-quiesce.
                Stops when no un-parked lane is NEED_DECODE."""

                def scond(c):
                    _tabst, _cnt, m, _dstats, _er, parked = c
                    return any_fn((m.status == jnp.int32(_ND)) & ~parked)

                def sbody(c):
                    tabst, cnt, m, dstats, er, parked = c
                    tl = tab._replace(
                        hash_tab=tabst[0], rip_l=tabst[1],
                        meta_i32=tabst[2], meta_u64=tabst[3])
                    blocks = jax.vmap(
                        DD.lane_block,
                        in_axes=(None, IMAGE_IN_AXES, 0, 0, 0, 0, None,
                                 None),
                    )(tl, image, m.overlay, m.cr3, m.rip, m.status,
                      bp_keys, n_bp)
                    out = DD.commit_blocks(tl, cnt, gather(blocks),
                                           gather(m.status), capacity)
                    fm = lane_slice(out.fault_mask)
                    m2 = m._replace(
                        status=lane_slice(out.status),
                        fault_gva=jnp.where(
                            fm, lane_slice(out.fault_gva), m.fault_gva),
                        fault_write=jnp.where(
                            fm, jnp.int32(0), m.fault_write),
                        ctr=m.ctr.at[:, CTR_MEM_FAULT].add(
                            lane_slice(out.mem_fault_inc)))
                    dstats2 = dstats + jnp.concatenate(
                        [out.stats, jnp.ones((1,), jnp.int32)])
                    m3, dr = run_quiesce(out.tab, image, m2, limit)
                    return ((out.tab.hash_tab, out.tab.rip_l,
                             out.tab.meta_i32, out.tab.meta_u64),
                            out.count, m3, dstats2, er + dr,
                            parked | lane_slice(out.parked))

                parked0 = jnp.zeros((n_lanes,), bool)
                tabst, cnt, m, dstats, er, _parked = lax.while_loop(
                    scond, sbody, (tabst, cnt, m, dstats, er, parked0))
                return tabst, cnt, m, dstats, er

        def cond(carry):
            b, stop = carry[0], carry[1]
            return (b < n_batches) & ~stop

        def body(carry):
            (b, _stop, incomplete, find_b, m, agg_c, agg_e, sts, flags,
             ctrs, nw, prev, cur, tabst, cnt, dstats, er) = carry
            tab_b = (tab._replace(hash_tab=tabst[0], rip_l=tabst[1],
                                  meta_i32=tabst[2], meta_u64=tabst[3])
                     if devdec_on else tab)
            first = b == 0
            data = jnp.where(first, slab_first[0], slab_rest[0])
            lens_s = jnp.where(first, slab_first[1], slab_rest[1])
            cumw = jnp.where(first, slab_first[2], slab_rest[2])
            m = _machine_restore_impl(m, template)
            words, lens = generate(data, lens_s, cumw, seeds[b],
                                   rounds=rounds)
            m = insert(m, words, lens, pfns, gva_l)
            m, dr = run_quiesce(tab_b, image, m, limit)
            if devdec_on:
                tabst, cnt, m, dstats, dr = service(tabst, cnt, m,
                                                    dstats, dr)
                tab_b = tab._replace(
                    hash_tab=tabst[0], rip_l=tabst[1], meta_i32=tabst[2],
                    meta_u64=tabst[3])
            # the quiesce trip counts are per-shard local (no collectives
            # inside); fold them here, in the lockstep outer body
            er = er + rsum_fn(dr)
            # declarative stop: BREAKPOINT at the finish rip == the
            # host handler's stop(Ok()) — pre-execution, so no icount /
            # coverage for the breakpointed instruction, like the host
            st = jnp.where((m.status == jnp.int32(_BP))
                           & (m.rip == finish_l), jnp.int32(_OK), m.status)
            m = m._replace(status=st)

            svc = jnp.zeros_like(st, bool)
            for s in serviceable:
                svc = svc | (st == jnp.int32(s))
            need_service = any_fn(svc)
            complete = ~need_service

            include = ((st != jnp.int32(_TIMEDOUT))
                       & (st != jnp.int32(_OVF)))
            agg_c2, agg_e2, new_lane, new_w = merge_fn(
                agg_c, agg_e, m.cov, m.edge, include)
            agg_c3 = jnp.where(complete, agg_c2, agg_c)
            agg_e3 = jnp.where(complete, agg_e2, agg_e)
            new_lane = new_lane & complete
            clean = ((st == jnp.int32(_OK)) | (st == jnp.int32(_TIMEDOUT))
                     | (st == jnp.int32(_CR3)))
            crashy = complete & any_fn(~clean)
            has_cov_find = complete & any_fn(new_lane)
            find_b2 = jnp.where(has_cov_find & (find_b >= B), b, find_b)

            sts2 = sts.at[b].set(jnp.where(complete, st, sts[b]))
            flags2 = flags.at[b].set(new_lane)
            ctrs2 = ctrs.at[b].set(jnp.where(
                complete, sum_fn(m.ctr), ctrs[b]))
            nw2 = jnp.where(complete, new_w, nw)
            prev2, cur2 = cur, _snap(words, lens)
            b2 = b + complete.astype(jnp.int32)
            # find-stop: after a new-coverage find at batch j the window
            # may run j+1 (its slab view is still entitled) and must then
            # return so the host folds the finds before j+2 generates; a
            # non-clean terminal stops immediately so the live machine
            # stays that batch's final state for crash naming/bucketing
            stop2 = need_service | crashy \
                | (complete & (b + 1 > find_b2 + 1))
            return (b2, stop2, incomplete | need_service, find_b2, m,
                    agg_c3, agg_e3, sts2, flags2, ctrs2, nw2, prev2,
                    cur2, tabst, cnt, dstats, er)

        if devdec_on:
            tabst0 = (tab.hash_tab, tab.rip_l, tab.meta_i32, tab.meta_u64)
            cnt0 = count0
        else:
            # devdec off: zero-size sentinels keep ONE carry structure
            tabst0 = ()
            cnt0 = jnp.int32(-1)
        dstats0 = jnp.zeros((4,), jnp.int32)
        er0 = jnp.zeros((2,), jnp.int32)
        init = (jnp.int32(0), jnp.bool_(False), jnp.bool_(False),
                jnp.int32(B), machine, agg_cov, agg_edge, statuses0,
                flags0, ctrs0, nw0, snap0, snap0, tabst0, cnt0, dstats0,
                er0)
        (b, _stop, incomplete, _fb, m, agg_c, agg_e, sts, flags, ctrs,
         nw, prev, cur, tabst, cnt, dstats, er) = lax.while_loop(
            cond, body, init)
        tab_out = (tab._replace(hash_tab=tabst[0], rip_l=tabst[1],
                                meta_i32=tabst[2], meta_u64=tabst[3])
                   if devdec_on else tab)
        return MegaOut(machine=m, agg_cov=agg_c, agg_edge=agg_e,
                       batches=b, incomplete=incomplete, statuses=sts,
                       new_flags=flags, ctr_sums=ctrs, new_words=nw,
                       prev=prev, cur=cur, tab=tab_out, count=cnt,
                       dd_stats=dstats, engine_rounds=er)

    if devdec_on:
        def window(tab, image, machine, template, slab_first, slab_rest,
                   seeds, pfns, gva_l, finish_l, limit, n_batches,
                   agg_cov, agg_edge, count, bp_keys, n_bp):
            return _window(tab, image, machine, template, slab_first,
                           slab_rest, seeds, pfns, gva_l, finish_l,
                           limit, n_batches, agg_cov, agg_edge,
                           (count, bp_keys, n_bp))
    else:
        def window(tab, image, machine, template, slab_first, slab_rest,
                   seeds, pfns, gva_l, finish_l, limit, n_batches,
                   agg_cov, agg_edge):
            return _window(tab, image, machine, template, slab_first,
                           slab_rest, seeds, pfns, gva_l, finish_l,
                           limit, n_batches, agg_cov, agg_edge, None)

    return window


# window operand positions donated through the executable: machine (2),
# agg_cov (12), agg_edge (13).  tab/image/template/slabs are shared
# across windows and never donated.
WINDOW_DONATE_ARGNUMS = (2, 12, 13)


def make_megachunk(max_batches: int, n_pages: int, len_gpr: int,
                   ptr_gpr: int, rounds: int, deliver: bool,
                   devdec: bool = False, fused: bool = False,
                   fused_k: int = 32, fused_resume_steps: int = 1,
                   interpret: bool = None, donate: bool = None):
    """Build (or fetch) the jitted single-device megachunk window:
    (tab, image, machine, template, slab_first, slab_rest, seeds[B,L,2],
    pfns, gva_l, finish, limit, n_batches, agg_cov, agg_edge
    [, count, bp_keys, n_bp when devdec]) -> MegaOut.

    `fused` swaps the quiesce's step engine for the Pallas kernel (see
    _make_body); `interpret=None` auto-selects the kernel mode like
    pstep.make_run_fused.  `donate=None` follows the repo donation
    policy (off on the XLA CPU backend where donation is unsound —
    step.make_run_chunk's caveat, the PR-2 finding; on elsewhere): with
    donation the machine and aggregate planes — including the kernel's
    `[lanes, slots, words]` overlay slab, aliased through the Pallas
    call itself — update in place across the whole window instead of
    copying through the executable."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if donate is None:
        donate = jax.default_backend() != "cpu"
    key = ("1dev", max_batches, n_pages, len_gpr, ptr_gpr, rounds,
           deliver, devdec, fused, fused_k, fused_resume_steps,
           interpret, donate)
    cached = _MEGA_CACHE.get(key)
    if cached is not None:
        return cached

    def sum_fn(ctr):
        return jnp.sum(ctr.astype(jnp.uint64), axis=0)

    body = _make_body(max_batches, n_pages, len_gpr, ptr_gpr, rounds,
                      deliver, merge_fn=merge_coverage, any_fn=jnp.any,
                      sum_fn=sum_fn, devdec_on=devdec, fused=fused,
                      fused_k=fused_k,
                      fused_resume_steps=fused_resume_steps,
                      interpret=interpret)
    fn = jax.jit(body, donate_argnums=WINDOW_DONATE_ARGNUMS if donate
                 else ())
    _MEGA_CACHE[key] = fn
    return fn


def make_mesh_megachunk(max_batches: int, n_pages: int, len_gpr: int,
                        ptr_gpr: int, rounds: int, deliver: bool, mesh,
                        devdec: bool = False, fused: bool = False,
                        fused_k: int = 32, fused_resume_steps: int = 1,
                        interpret: bool = None, donate: bool = None):
    """The megachunk window per shard under shard_map: machine/template/
    seed-stream/snapshots lane-sharded, slabs + uop table + aggregates
    replicated, the per-batch merge the shard-aware prefix-credit core,
    and every loop-control scalar all-reduced so the shards' while_loops
    stay in lockstep (identical trip counts, matched collectives).

    With `devdec`, decode-miss service rounds all-gather the per-shard
    lane blocks and run ONE replicated sequential commit, so the table
    (and its slot/coverage-bit order) stays bit-identical on every shard
    AND to the single-device program — slots never partition by shard."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from wtf_tpu.meshrun.executor import IMAGE_SPEC
    from wtf_tpu.meshrun.mesh import LANE_AXIS

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if donate is None:
        donate = jax.default_backend() != "cpu"
    key = ("mesh", max_batches, n_pages, len_gpr, ptr_gpr, rounds,
           deliver, mesh, devdec, fused, fused_k, fused_resume_steps,
           interpret, donate)
    cached = _MEGA_CACHE.get(key)
    if cached is not None:
        return cached

    def any_fn(x):
        return lax.pmax(jnp.any(x).astype(jnp.int32), LANE_AXIS) > 0

    def sum_fn(ctr):
        return lax.psum(jnp.sum(ctr.astype(jnp.uint64), axis=0),
                        LANE_AXIS)

    def rsum_fn(r):
        return lax.psum(r, LANE_AXIS)

    def merge_fn(agg_cov, agg_edge, cov, edge, include):
        return mesh_merge_local(agg_cov, agg_edge, cov, edge, include,
                                LANE_AXIS)

    def gather_fn(a):
        return lax.all_gather(a, LANE_AXIS, axis=0, tiled=True)

    def lane_base_fn(n_local):
        return lax.axis_index(LANE_AXIS).astype(jnp.int32) * n_local

    body = _make_body(max_batches, n_pages, len_gpr, ptr_gpr, rounds,
                      deliver, merge_fn=merge_fn, any_fn=any_fn,
                      sum_fn=sum_fn, devdec_on=devdec,
                      gather_fn=gather_fn if devdec else None,
                      lane_base_fn=lane_base_fn if devdec else None,
                      fused=fused, fused_k=fused_k,
                      fused_resume_steps=fused_resume_steps,
                      interpret=interpret, rsum_fn=rsum_fn)
    lane_snap = MegaSnap(words=P(LANE_AXIS), lens=P(LANE_AXIS))
    in_specs = (P(), IMAGE_SPEC, P(LANE_AXIS), P(LANE_AXIS),
                (P(), P(), P()), (P(), P(), P()), P(None, LANE_AXIS),
                P(), P(), P(), P(), P(), P(), P())
    if devdec:
        in_specs = in_specs + (P(), P(), P())
    fn = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=in_specs,
        out_specs=MegaOut(
            machine=P(LANE_AXIS), agg_cov=P(), agg_edge=P(),
            batches=P(), incomplete=P(), statuses=P(None, LANE_AXIS),
            new_flags=P(None, LANE_AXIS), ctr_sums=P(), new_words=P(),
            prev=lane_snap, cur=lane_snap, tab=P(), count=P(),
            dd_stats=P(), engine_rounds=P()),
        check_rep=False),
        donate_argnums=WINDOW_DONATE_ARGNUMS if donate else ())
    _MEGA_CACHE[key] = fn
    return fn
