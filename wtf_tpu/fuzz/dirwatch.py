"""Directory watcher: mid-campaign corpus injection.

Reference `DirWatcher_t` (src/wtf/dirwatch.h): polls a directory and
returns newly appeared files, size-sorted, so operators can drop seeds
into a running master.  The master calls poll() between reactor
iterations and prepends results to its seed paths.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional


class DirWatcher:
    def __init__(self, directory):
        self.directory = Path(directory)
        self._seen = set()
        if self.directory.is_dir():
            self._seen = {p.name for p in self.directory.iterdir()}

    def poll(self) -> List[Path]:
        """New files since the last poll, biggest first (matching the
        master's seed ordering, server.h:399-414).  Robust against files
        vanishing mid-scan (atomic-rename temp files, operator cleanup)."""
        if not self.directory.is_dir():
            return []
        fresh = []
        for p in self.directory.iterdir():
            if p.name in self._seen:
                continue
            try:
                if p.is_file():
                    fresh.append((p.stat().st_size, p))
                    self._seen.add(p.name)
            except OSError:
                continue  # vanished between iterdir and stat; not seen
        return [p for _, p in sorted(fresh, key=lambda t: t[0],
                                     reverse=True)]
