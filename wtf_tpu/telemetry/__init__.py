"""Unified telemetry: metrics registry, phase spans, JSONL event log.

One namespace for everything the snapshot→execute→restore loop needs to
explain itself (the stats role the reference spreads over ServerStats_t
/ client stats / PrintRunStats, plus the phase/time accounting it never
had):

  metrics.Registry   named counters/gauges/histograms, labeled children
  spans.Spans        phase timers with explicit device fencing
  events.EventLog    append-only JSONL stream (+ NullEventLog/NULL sink)

The fourth leg — device-side per-lane counters (instructions retired,
memory faults, decode-cache misses) — lives in the machine state itself
(interp/machine.py `Machine.ctr`, accumulated in interp/step.py, folded
into a Registry by the backend once per burst).
"""

from wtf_tpu.telemetry.events import (  # noqa: F401
    NULL, EventLog, NullEventLog, TapEventLog, open_event_log, read_events,
)
from wtf_tpu.telemetry.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, LabeledView, Registry, StatsDict,
    get_registry, merge_snapshots,
)
from wtf_tpu.telemetry.spans import Spans, TraceCollector  # noqa: F401


def resolve(backend=None, registry=None, events=None):
    """Resolve the (registry, events) pair a driver should aggregate into:
    explicit argument, else the backend's own, else a fresh Registry / the
    NULL sink.  The one sharing policy — every layer (backends, fuzz loop,
    dist nodes) defaults through here so they can't silently fragment onto
    different registries."""
    if registry is None:
        registry = getattr(backend, "registry", None) or Registry()
    if events is None:
        events = getattr(backend, "events", None) or NULL
    return registry, events
