"""Phase spans: where the wall-clock goes, fenced against async dispatch.

JAX dispatch is asynchronous — `machine = run_chunk(...)` returns before
the TPU finishes, so a naive timer around it measures Python dispatch,
not device execution, and the "missing" time surfaces in whichever later
span happens to synchronize first (the classic async-profiling lie; the
Concordia/Cudagrind phase-accounting papers in PAPERS.md exist because
of it).  A Span therefore exposes `fence(value)` — an explicit
`jax.block_until_ready` barrier the caller drops on the device values it
just produced, so the span's end time is taken AFTER the device work is
actually done:

    with spans.span("device-step") as sp:
        machine = run_chunk(tab, image, machine, limit)
        sp.fence(machine.status)

Spans nest: a span opened inside another records under the joined path
("execute/device-step"), so a report can both account top-level phases
against wall-clock (paths without "/") and break a phase down.  Totals
land in the owning registry as `phase.seconds{path}` / `phase.calls{path}`
labeled counters — one metric namespace shared with everything else, one
heartbeat dump carries it all.
"""

from __future__ import annotations

import time
from typing import List, Optional

from wtf_tpu.telemetry.metrics import Registry

SECONDS = "phase.seconds"
CALLS = "phase.calls"

# Span leaves that measure DEVICE work (each is fenced with
# jax.block_until_ready before its span closes): the device-step/
# pallas-step executors, the fused devmut generation / insert /
# megachunk-window waits ("device" under mutate/insert/execute), the
# overlay restore, and the coverage readback.  Everything else inside a
# top-level phase is host time.  The ONE list the host/device wall
# breakdown rides on — tools/telemetry_report.py and ablate.py's
# host-share A/B both consume it, so the split cannot drift between
# the report and the benchmark.
DEVICE_SPAN_LEAVES = frozenset((
    "device", "device-step", "pallas-step", "overlay-restore",
    "cov-readback",
))


def block_until_ready(value) -> None:
    """Fence: wait until every device array in `value` has materialized.
    No-op for host values and when jax isn't importable (telemetry stays
    usable from pure-host tools)."""
    if value is None:
        return
    try:
        import jax
    except Exception:  # pragma: no cover - jax is baked into this image
        return
    try:
        jax.block_until_ready(value)
    except Exception:
        pass  # non-pytree host object: already materialized


class Span:
    """One open phase measurement (context-managed via Spans.span)."""

    __slots__ = ("path", "_spans", "_t0")

    def __init__(self, spans: "Spans", path: str):
        self.path = path
        self._spans = spans
        self._t0 = spans._clock()

    def fence(self, value) -> None:
        """Block until `value`'s device buffers are ready — call on the
        chunk's outputs before the span closes so async dispatch can't
        shift its time into a later span."""
        block_until_ready(value)

    @property
    def elapsed(self) -> float:
        return self._spans._clock() - self._t0


class Spans:
    """Registry-owned span timer.  Single-threaded by design (the run
    loop is); the nesting stack is just a list."""

    def __init__(self, registry: Registry, clock=time.perf_counter):
        self._registry = registry
        self._clock = clock
        self._stack: List[str] = []

    def span(self, name: str) -> "_SpanCtx":
        """Open a phase span (context manager; call sp.fence(value) inside
        the with-block on the device values the phase produced)."""
        return _SpanCtx(self, name)

    def seconds(self, path: str) -> float:
        """Accumulated seconds recorded under `path` (0.0 if never hit)."""
        children = self._registry.counter(SECONDS).children
        child = children.get(path)
        return child.value if child is not None else 0.0

    def _record(self, path: str, dt: float) -> None:
        self._registry.counter(SECONDS).labels(path).inc(dt)
        self._registry.counter(CALLS).labels(path).inc()


class _SpanCtx:
    __slots__ = ("_spans", "_name", "_span")

    def __init__(self, spans: Spans, name: str):
        self._spans = spans
        self._name = name
        self._span: Optional[Span] = None

    def __enter__(self) -> Span:
        spans = self._spans
        path = "/".join(spans._stack + [self._name])
        spans._stack.append(self._name)
        self._span = Span(spans, path)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> None:
        # record even on an in-span exception: a crashed phase's time
        # is exactly what a post-mortem wants attributed
        spans = self._spans
        dt = self._span.elapsed
        if spans._stack and spans._stack[-1] == self._name:
            spans._stack.pop()
        spans._record(self._span.path, dt)
        return None
