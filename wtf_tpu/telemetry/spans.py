"""Phase spans: where the wall-clock goes, fenced against async dispatch.

JAX dispatch is asynchronous — `machine = run_chunk(...)` returns before
the TPU finishes, so a naive timer around it measures Python dispatch,
not device execution, and the "missing" time surfaces in whichever later
span happens to synchronize first (the classic async-profiling lie; the
Concordia/Cudagrind phase-accounting papers in PAPERS.md exist because
of it).  A Span therefore exposes `fence(value)` — an explicit
`jax.block_until_ready` barrier the caller drops on the device values it
just produced, so the span's end time is taken AFTER the device work is
actually done:

    with spans.span("device-step") as sp:
        machine = run_chunk(tab, image, machine, limit)
        sp.fence(machine.status)

Spans nest: a span opened inside another records under the joined path
("execute/device-step"), so a report can both account top-level phases
against wall-clock (paths without "/") and break a phase down.  Totals
land in the owning registry as `phase.seconds{path}` / `phase.calls{path}`
labeled counters — one metric namespace shared with everything else, one
heartbeat dump carries it all.
"""

from __future__ import annotations

import time
from typing import List, Optional

from wtf_tpu.telemetry.metrics import Registry

SECONDS = "phase.seconds"
CALLS = "phase.calls"

# Span leaves that measure DEVICE work (each is fenced with
# jax.block_until_ready before its span closes): the device-step/
# pallas-step executors, the fused devmut generation / insert /
# megachunk-window waits ("device" under mutate/insert/execute), the
# overlay restore, and the coverage readback.  Everything else inside a
# top-level phase is host time.  The ONE list the host/device wall
# breakdown rides on — tools/telemetry_report.py and ablate.py's
# host-share A/B both consume it, so the split cannot drift between
# the report and the benchmark.
DEVICE_SPAN_LEAVES = frozenset((
    "device", "device-step", "pallas-step", "overlay-restore",
    "cov-readback",
))


def block_until_ready(value) -> None:
    """Fence: wait until every device array in `value` has materialized.
    No-op for host values and when jax isn't importable (telemetry stays
    usable from pure-host tools)."""
    if value is None:
        return
    try:
        import jax
    except Exception:  # pragma: no cover - jax is baked into this image
        return
    try:
        jax.block_until_ready(value)
    except Exception:
        pass  # non-pytree host object: already materialized


class Span:
    """One open phase measurement (context-managed via Spans.span)."""

    __slots__ = ("path", "_spans", "_t0")

    def __init__(self, spans: "Spans", path: str):
        self.path = path
        self._spans = spans
        self._t0 = spans._clock()

    def fence(self, value) -> None:
        """Block until `value`'s device buffers are ready — call on the
        chunk's outputs before the span closes so async dispatch can't
        shift its time into a later span."""
        block_until_ready(value)

    @property
    def elapsed(self) -> float:
        return self._spans._clock() - self._t0


class TraceCollector:
    """Chrome-trace-event sink for spans: every closed span becomes one
    "ph":"X" complete event (ts/dur in microseconds, category "device"
    for the fenced DEVICE_SPAN_LEAVES, "host" otherwise), and point
    events (compile, checkpoint, prelaunch drops, supervisor recoveries)
    become "ph":"i" instants — so chrome://tracing / Perfetto renders
    the host-vs-device overlap per batch directly.

    Collection is O(1) appends on close; nothing is serialized until
    write() (keeping the emit path off the dispatch seams — the
    telemetry lint family pins this).  `max_events` bounds memory on
    long campaigns by dropping the oldest half once full (the steady
    state is what a timeline capture is for)."""

    def __init__(self, clock=time.perf_counter, max_events: int = 200_000):
        self._clock = clock
        self._events: List[tuple] = []  # ("X", path, t0, dur) | ("i", ...)
        self._max = max_events
        self.dropped = 0

    def complete(self, path: str, t0: float, dur: float) -> None:
        self._append(("X", path, t0, dur))

    def instant(self, name: str, args=None) -> None:
        self._append(("i", name, self._clock(), args))

    def _append(self, event: tuple) -> None:
        if len(self._events) >= self._max:
            keep = self._max // 2
            self.dropped += len(self._events) - keep
            self._events = self._events[-keep:]
        self._events.append(event)

    def trace_events(self) -> List[dict]:
        """The Chrome trace-event list (ts rebased to the first event)."""
        if not self._events:
            return []
        epoch = min(ev[2] for ev in self._events)
        out = []
        for ev in self._events:
            ts = round((ev[2] - epoch) * 1e6, 3)
            if ev[0] == "X":
                path = ev[1]
                leaf = path.rsplit("/", 1)[-1]
                cat = "device" if leaf in DEVICE_SPAN_LEAVES else "host"
                out.append({"name": leaf, "cat": cat, "ph": "X",
                            "ts": ts, "dur": round(ev[3] * 1e6, 3),
                            "pid": 1, "tid": 1, "args": {"path": path}})
            else:
                record = {"name": ev[1], "cat": "event", "ph": "i",
                          "ts": ts, "pid": 1, "tid": 1, "s": "t"}
                if ev[3]:
                    record["args"] = ev[3]
                out.append(record)
        out.sort(key=lambda e: e["ts"])
        return out

    def write(self, path) -> int:
        """Write the JSON object form ({"traceEvents": [...]}) — the
        schema both chrome://tracing and Perfetto load.  Returns the
        event count."""
        import json
        from pathlib import Path

        events = self.trace_events()
        payload = {"traceEvents": events, "displayTimeUnit": "ms",
                   "otherData": {"producer": "wtf-tpu",
                                 "dropped_events": self.dropped}}
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh)
        return len(events)


class Spans:
    """Registry-owned span timer.  Single-threaded by design (the run
    loop is); the nesting stack is just a list.  `collector` (normally
    None) mirrors every closed span into a TraceCollector for --trace-out
    timeline export."""

    def __init__(self, registry: Registry, clock=time.perf_counter):
        self._registry = registry
        self._clock = clock
        self._stack: List[str] = []
        self.collector: Optional[TraceCollector] = None

    def span(self, name: str) -> "_SpanCtx":
        """Open a phase span (context manager; call sp.fence(value) inside
        the with-block on the device values the phase produced)."""
        return _SpanCtx(self, name)

    def seconds(self, path: str) -> float:
        """Accumulated seconds recorded under `path` (0.0 if never hit)."""
        children = self._registry.counter(SECONDS).children
        child = children.get(path)
        return child.value if child is not None else 0.0

    def trace_mark(self, name: str) -> "_TraceMarkCtx":
        """A trace-timeline-only span: emits an "X" event to the
        collector (if attached) but does NOT enter the nesting stack or
        the phase.seconds counters — for visual grouping boxes whose
        extra path level would skew path-keyed accounting (e.g. the
        megachunk window drawn around execute/device)."""
        return _TraceMarkCtx(self, name)

    def _record(self, path: str, dt: float) -> None:
        self._registry.counter(SECONDS).labels(path).inc(dt)
        self._registry.counter(CALLS).labels(path).inc()


class _SpanCtx:
    __slots__ = ("_spans", "_name", "_span")

    def __init__(self, spans: Spans, name: str):
        self._spans = spans
        self._name = name
        self._span: Optional[Span] = None

    def __enter__(self) -> Span:
        spans = self._spans
        path = "/".join(spans._stack + [self._name])
        spans._stack.append(self._name)
        self._span = Span(spans, path)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> None:
        # record even on an in-span exception: a crashed phase's time
        # is exactly what a post-mortem wants attributed
        spans = self._spans
        dt = self._span.elapsed
        if spans._stack and spans._stack[-1] == self._name:
            spans._stack.pop()
        spans._record(self._span.path, dt)
        if spans.collector is not None:
            spans.collector.complete(self._span.path, self._span._t0, dt)
        return None


class _TraceMarkCtx:
    __slots__ = ("_spans", "_name", "_t0")

    def __init__(self, spans: Spans, name: str):
        self._spans = spans
        self._name = name
        self._t0 = 0.0

    def __enter__(self) -> "_TraceMarkCtx":
        self._t0 = self._spans._clock()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        spans = self._spans
        if spans.collector is not None:
            path = "/".join(spans._stack + [self._name])
            spans.collector.complete(path, self._t0,
                                     spans._clock() - self._t0)
        return None
