"""Append-only JSONL event log: the machine-readable campaign stream.

The reference's operational record is whatever scrolled past on stdout;
here every noteworthy campaign event is one JSON object per line in
`<telemetry-dir>/events.jsonl`, so a run can be replayed, diffed, and
summarized offline (tools/telemetry_report.py) while the human heartbeat
line stays exactly what it always was.

Schema (every record):
  ts    float unix seconds
  seq   monotonically increasing per-log sequence number
  type  event type string
plus per-type payload fields.  The well-known types:

  run-start     campaign start (subcommand, name, backend, argv)
  heartbeat     periodic: the human status `line` + a full registry
                `metrics` dump (per-phase span totals ride in here)
  new-coverage  new coverage entered the corpus — fuzz loop records
                carry (digest, size); master records carry
                (new_addresses, total, size)
  crash         a crash was recorded (name, size, new) — cli run-mode
                records carry (name, input)
  timeout       per-batch timeout count (aggregated — a 4096-lane batch
                of timeouts is one record, not 4096)
  compile       a chunk executor's first dispatch pays its XLA compile
                (chunk_steps, donate); the wall shows inside the next
                device-step span
  error         operational failure that used to be a bare print()
                (kind, detail + per-kind fields)
  run-end       final registry dump at campaign end (metrics)

The fault-tolerance tier adds retry/reconnect/reclaim/drain/
checkpoint/resume records (wtf_tpu/resume, dist hardening) and the
fleet tier adds:

  store-put     a blob entered the content-addressed store
                (store, kind, digest, size, bucket)
  cursor-resume a restarted master resumed persisted delta ack
                cursors (clients, addresses)
  reshard       elastic placement change requested at a batch boundary
                (batch, devices, testcases); the campaign checkpoints
                and the driver re-places it

`crash` records from the delta-speaking master additionally carry
(digest, bucket) — files are digest-named and bucket-deduped there.

Call sites hold a sink unconditionally: `NullEventLog` swallows
everything, so `self.events.emit(...)` never needs a None check on a hot
path.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Optional


class NullEventLog:
    """No-op sink with the full EventLog surface."""

    path = None

    def emit(self, type: str, **fields) -> None:  # noqa: A002
        pass

    def heartbeat(self, registry=None, line: Optional[str] = None,
                  **fields) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "NullEventLog":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


NULL = NullEventLog()


class EventLog(NullEventLog):
    """JSONL sink.  Every record is flushed on write — event rates are
    heartbeat-scale (not per-testcase), and a crashed run must not lose
    its tail."""

    def __init__(self, path, clock=time.time,
                 max_bytes: Optional[int] = None):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = open(self.path, "a", encoding="utf-8")
        self._clock = clock
        self._seq = 0
        self._broken = False
        # size-based rotation (events.jsonl -> events.jsonl.1): a
        # 1000-client soak or multi-day campaign must not grow the sink
        # unboundedly.  None (the default) keeps the historical
        # append-forever behavior; WTF_TPU_EVENTS_MAX_BYTES sets a
        # process-wide default cap.
        if max_bytes is None:
            env = os.environ.get("WTF_TPU_EVENTS_MAX_BYTES")
            max_bytes = int(env) if env else None
        self.max_bytes = max_bytes

    @classmethod
    def for_dir(cls, directory, max_bytes: Optional[int] = None
                ) -> "EventLog":
        """The --telemetry-dir convention: events.jsonl inside it."""
        return cls(Path(directory) / "events.jsonl", max_bytes=max_bytes)

    def emit(self, type: str, **fields) -> None:  # noqa: A002
        # Telemetry is an observability side-channel: a full disk or a
        # yanked --telemetry-dir must degrade it to a no-op, never abort
        # the campaign it is narrating (the crash-save/coverage-write
        # paths make the same call).  One warning, then silence.
        if self._broken:
            return
        record = {"ts": self._clock(), "seq": self._seq, "type": type}
        record.update(fields)
        self._seq += 1
        try:
            self._fh.write(json.dumps(record, default=str) + "\n")
            self._fh.flush()
            if self.max_bytes is not None and \
                    self._fh.tell() >= self.max_bytes:
                self._rotate()
        except OSError as e:
            self._disable(e)

    def _rotate(self) -> None:
        """events.jsonl -> events.jsonl.1 (replacing any prior .1) and
        reopen fresh.  One generation of history is the deliberate cap:
        the stream's job is the recent past; the registry carries the
        cumulative totals.  Torn tails survive rotation because readers
        (read_events) skip unparseable lines in EVERY generation."""
        self._fh.close()
        rotated = self.path.with_name(self.path.name + ".1")
        os.replace(self.path, rotated)
        self._fh = open(self.path, "a", encoding="utf-8")

    def heartbeat(self, registry=None, line: Optional[str] = None,
                  **fields) -> None:
        payload = dict(fields)
        if line is not None:
            payload["line"] = line
        if registry is not None:
            payload["metrics"] = registry.dump()
        self.emit("heartbeat", **payload)

    def flush(self) -> None:
        if self._broken:
            return
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except OSError as e:
            self._disable(e)

    def _disable(self, e: OSError) -> None:
        self._broken = True
        import logging

        logging.getLogger(__name__).warning(
            "telemetry write failed (%s); disabling event log %s",
            e, self.path)

    def close(self) -> None:
        if not self._fh.closed:
            try:
                self._fh.close()
            except OSError:
                pass


def open_event_log(telemetry_dir) -> NullEventLog:
    """EventLog for a --telemetry-dir value, NULL for None — the one-line
    wiring every CLI driver uses."""
    if telemetry_dir is None:
        return NULL
    return EventLog.for_dir(telemetry_dir)


class TapEventLog(NullEventLog):
    """Wraps a sink and mirrors every record to a tap callable
    `tap(type, fields)` — how --trace-out turns point events (compile,
    checkpoint, recovery, prelaunch drops) into trace instants without
    every emitter learning about tracing.  Tap failures are swallowed:
    observability must never abort the campaign."""

    def __init__(self, inner, tap):
        self._inner = inner
        self._tap = tap

    @property
    def path(self):  # type: ignore[override]
        return self._inner.path

    def emit(self, type: str, **fields) -> None:  # noqa: A002
        try:
            self._tap(type, fields)
        except Exception:
            pass
        self._inner.emit(type, **fields)

    def heartbeat(self, registry=None, line: Optional[str] = None,
                  **fields) -> None:
        try:
            # the tap sees the light fields, not the full metrics dump —
            # serializing the registry belongs to the sink, not the trace
            self._tap("heartbeat", dict(fields, line=line))
        except Exception:
            pass
        self._inner.heartbeat(registry=registry, line=line, **fields)

    def flush(self) -> None:
        self._inner.flush()

    def close(self) -> None:
        self._inner.close()


def read_events(path, rotated: bool = False):
    """Yield records from an events.jsonl (skipping any torn final line —
    a killed run may die mid-write; rotation can freeze a torn tail into
    the .1 generation, so EVERY generation gets the same tolerance).
    With rotated=True, records from `<path>.1` come first."""
    paths = [Path(str(path) + ".1"), Path(path)] if rotated else [Path(path)]
    for p in paths:
        if not p.exists():
            continue
        with open(p, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    continue
