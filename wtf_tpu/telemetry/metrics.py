"""Metrics registry: named counters / gauges / histograms with labels.

The reference keeps its campaign numbers in ad-hoc structs
(ServerStats_t server.h:24-240, BochscpuRunStats_t backend.h:17-45) and
this repo grew three disconnected copies of that idea (CampaignStats,
ServerStats, Runner.stats).  The registry replaces all of them with one
namespace of metrics cheap enough for hot paths (attribute increments on
plain Python ints — no locks, no string formatting until dump time):

  reg = Registry()
  reg.counter("runner.fallbacks").inc()
  reg.counter("runner.fallbacks_by_opclass").labels("ssefp").inc()
  reg.gauge("runner.max_chunk_steps").set(4096)
  reg.histogram("phase.seconds").observe(0.012)
  reg.dump()  # one JSON-able dict of everything

Scoping: metrics aggregate per-Registry, and every component creates a
PRIVATE registry unless handed one — a FuzzLoop in a test does not bleed
counters into the next test.  The CLI passes ONE registry to the
backend, the loop/server, and the event log, which is what makes the
heartbeat line and the JSONL stream consistent; `get_registry()` is the
process-global default for code with no better scope.

`StatsDict` / `LabeledView` are dict facades over registry metrics so
existing call sites (`runner.stats["fallbacks"] += 1`,
`dict(stats["fallbacks_by_opclass"])`) keep working unchanged while the
values live in the registry.
"""

from __future__ import annotations

from collections.abc import Mapping, MutableMapping
from typing import Dict, Iterable, Optional, Tuple, Union

Number = Union[int, float]


class Counter:
    """Monotonic-by-convention accumulator.  `set` exists because the
    dict facades (and gauges-by-another-name like max_chunk_steps) need
    read-modify-write assignment."""

    __slots__ = ("name", "value", "_children")

    def __init__(self, name: str):
        self.name = name
        self.value: Number = 0
        self._children: Optional[Dict[str, "Counter"]] = None

    def inc(self, n: Number = 1) -> None:
        self.value += n

    def set(self, value: Number) -> None:
        self.value = value

    def labels(self, label: str) -> "Counter":
        """Child counter keyed by one label value (e.g. the opclass in
        fallbacks{opclass=ssefp}).  Children are cached; the parent's own
        value stays independent (normally unused when labeled)."""
        if self._children is None:
            self._children = {}
        child = self._children.get(label)
        if child is None:
            child = Counter(f"{self.name}{{{label}}}")
            self._children[label] = child
        return child

    @property
    def children(self) -> Dict[str, "Counter"]:
        return self._children or {}

    def dump(self):
        if self._children is not None:
            return {k: c.value for k, c in self._children.items()}
        return self.value


class Gauge(Counter):
    """A value that goes up and down (set-dominant)."""


class Histogram:
    """Constant-space summary: count / sum / min / max.  Cheap enough
    for per-span observation on the hot loop; full distributions belong
    in the JSONL stream, not here."""

    __slots__ = ("name", "count", "sum", "min", "max")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, value: Number) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value

    def dump(self):
        return {"count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max}


class Registry:
    """Process- or campaign-scoped namespace of metrics."""

    def __init__(self):
        self._metrics: Dict[str, Union[Counter, Gauge, Histogram]] = {}
        self._spans = None

    def _get(self, name: str, cls):
        metric = self._metrics.get(name)
        if metric is None:
            metric = cls(name)
            self._metrics[name] = metric
        elif type(metric) is not cls:
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(metric).__name__}, not {cls.__name__}")
        return metric

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    @property
    def spans(self):
        """This registry's phase-span timer (telemetry.spans.Spans),
        created lazily so metrics-only users never import the fencing
        machinery."""
        if self._spans is None:
            from wtf_tpu.telemetry.spans import Spans

            self._spans = Spans(self)
        return self._spans

    def dump(self) -> Dict[str, object]:
        """JSON-able snapshot of every metric: plain value for unlabeled
        counters/gauges, {label: value} for labeled ones,
        {count,sum,min,max} for histograms."""
        return {name: m.dump() for name, m in sorted(self._metrics.items())}

    # -- fleet snapshots (dist TAG_TELEM / fleet/telemetry.py) -------------
    def snapshot(self) -> Dict[str, object]:
        """Wire-portable FULL state — unlike counters_state this includes
        histograms and every namespace, because the fleet aggregator's
        job is to reproduce the node's registry exactly:
          {name: {"kind": "c"|"g", "value": n}           unlabeled
                 {"kind": "c"|"g", "labels": {l: n}}     labeled
                 {"kind": "h", "count","sum","min","max"} histogram}
        Snapshots are CUMULATIVE (a node resends its running totals), so
        the merge keeps only the latest per node and re-sends/reconnects
        can never double-count."""
        out: Dict[str, object] = {}
        for name, metric in self._metrics.items():
            if isinstance(metric, Histogram):
                out[name] = {"kind": "h", "count": metric.count,
                             "sum": metric.sum, "min": metric.min,
                             "max": metric.max}
                continue
            kind = "g" if isinstance(metric, Gauge) else "c"
            if metric._children is not None:
                out[name] = {"kind": kind, "labels": {
                    label: c.value for label, c in metric._children.items()}}
            else:
                out[name] = {"kind": kind, "value": metric.value}
        return out

    def restore_snapshot(self, state: Dict[str, object]) -> None:
        """Install a snapshot() (or merge_snapshots()) dict into this
        registry — the fleet aggregator renders its merged state through
        a real Registry so dump()/report code works unchanged."""
        for name, entry in state.items():
            kind = entry.get("kind")
            if kind == "h":
                hist = self.histogram(name)
                hist.count = entry.get("count", 0)
                hist.sum = entry.get("sum", 0.0)
                hist.min = entry.get("min")
                hist.max = entry.get("max")
                continue
            metric = self.gauge(name) if kind == "g" else self.counter(name)
            if "labels" in entry:
                if metric._children is None:
                    metric._children = {}  # declared labeled: dump as {}
                for label, v in entry["labels"].items():
                    metric.labels(label).set(v)
            else:
                metric.set(entry.get("value", 0))

    # -- checkpoint/resume (wtf_tpu/resume) --------------------------------
    def counters_state(self, prefixes) -> Dict[str, object]:
        """Counters/gauges under `prefixes` as {name: {kind, value}} —
        the resumable half of the registry (histograms and span timers
        are wall-clock observations of the killed process; they restart
        from zero by design)."""
        out: Dict[str, object] = {}
        for name, metric in self._metrics.items():
            if isinstance(metric, Histogram):
                continue
            if not any(name.startswith(p) for p in prefixes):
                continue
            kind = "g" if isinstance(metric, Gauge) else "c"
            out[name] = {"kind": kind, "value": metric.dump()}
        return out

    def restore_counters(self, state: Dict[str, object]) -> None:
        """Install counters_state() output (resume overwrites whatever the
        fresh process accumulated during its own warmup)."""
        for name, entry in state.items():
            getter = self.gauge if entry.get("kind") == "g" else self.counter
            metric = getter(name)
            value = entry.get("value")
            if isinstance(value, dict):
                for label, v in value.items():
                    metric.labels(label).set(v)
            else:
                metric.set(value)


def merge_snapshots(snapshots: Iterable[Dict[str, object]]
                    ) -> Dict[str, object]:
    """Sum N Registry.snapshot() dicts into one fleet-wide snapshot:
    counters and gauges add (per label for labeled ones), histograms
    combine (count/sum add, min/max extremize).  Kind conflicts take the
    first writer — a fleet of same-version nodes never has any.  The
    result is itself snapshot-shaped, so it round-trips through
    Registry.restore_snapshot for rendering."""
    merged: Dict[str, dict] = {}
    for snap in snapshots:
        for name, entry in snap.items():
            kind = entry.get("kind")
            cur = merged.get(name)
            if cur is None:
                cur = ({"kind": "h", "count": 0, "sum": 0.0,
                        "min": None, "max": None} if kind == "h"
                       else {"kind": kind}
                       | ({"labels": {}} if "labels" in entry
                          else {"value": 0}))
                merged[name] = cur
            if kind == "h":
                cur["count"] += entry.get("count", 0)
                cur["sum"] += entry.get("sum", 0.0)
                for field, pick in (("min", min), ("max", max)):
                    v = entry.get(field)
                    if v is not None:
                        cur[field] = (v if cur[field] is None
                                      else pick(cur[field], v))
            elif "labels" in entry:
                labels = cur.setdefault("labels", {})
                for label, v in entry["labels"].items():
                    labels[label] = labels.get(label, 0) + v
            else:
                cur["value"] = cur.get("value", 0) + entry.get("value", 0)
    return {name: merged[name] for name in sorted(merged)}


_GLOBAL: Optional[Registry] = None


def get_registry() -> Registry:
    """The process-global default registry (for code with no
    campaign-scoped registry in reach)."""
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = Registry()
    return _GLOBAL


class LabeledView(MutableMapping):
    """dict facade over one labeled counter: `view["ssefp"] += 1`,
    `dict(view)`, `view.get(k, 0)` all work; values live in the
    counter's children."""

    __slots__ = ("_counter",)

    def __init__(self, counter: Counter):
        self._counter = counter

    def __getitem__(self, label: str) -> Number:
        children = self._counter.children
        if label not in children:
            raise KeyError(label)
        return children[label].value

    def __setitem__(self, label: str, value: Number) -> None:
        self._counter.labels(label).set(value)

    def __delitem__(self, label: str) -> None:
        raise TypeError("labeled metrics cannot be deleted")

    def __iter__(self):
        return iter(self._counter.children)

    def __len__(self) -> int:
        return len(self._counter.children)

    def __repr__(self) -> str:
        return repr({k: c.value for k, c in self._counter.children.items()})


class StatsDict(MutableMapping):
    """dict facade over a fixed family of registry metrics under a
    prefix — what Runner.stats / backend.stats migrate onto without
    changing a single call site.

    `fields` declares the plain (counter-backed) keys, `gauges` the
    set-dominant ones, `labeled` the keys that expose a LabeledView.
    Unknown keys assigned later become counters (prefix applied), so the
    facade stays open like the dict it replaces.
    """

    def __init__(self, registry: Registry, prefix: str,
                 fields: Iterable[str] = (),
                 gauges: Iterable[str] = (),
                 labeled: Iterable[str] = ()):
        self._registry = registry
        self._prefix = prefix
        self._gauges = set(gauges)
        self._labeled = set(labeled)
        self._keys = list(fields) + list(gauges) + list(labeled)
        for key in self._keys:
            self._metric(key)  # register now so dump()/iteration see zeros

    def _name(self, key: str) -> str:
        return f"{self._prefix}.{key}"

    def _metric(self, key: str):
        if key in self._gauges:
            return self._registry.gauge(self._name(key))
        counter = self._registry.counter(self._name(key))
        if key in self._labeled and counter._children is None:
            counter._children = {}  # declared labeled: dump as {} not 0
        return counter

    def __getitem__(self, key: str):
        if key not in self._keys:
            raise KeyError(key)
        if key in self._labeled:
            return LabeledView(self._metric(key))
        return self._metric(key).value

    def __setitem__(self, key: str, value) -> None:
        if key not in self._keys:
            self._keys.append(key)
        if key in self._labeled:
            if not isinstance(value, Mapping):
                raise TypeError(f"{key} takes a mapping")
            counter = self._metric(key)
            for label, v in value.items():
                counter.labels(label).set(v)
            return
        self._metric(key).set(value)

    def __delitem__(self, key: str) -> None:
        raise TypeError("stats keys cannot be deleted")

    def __iter__(self):
        return iter(self._keys)

    def __len__(self) -> int:
        return len(self._keys)

    def __repr__(self) -> str:
        return repr({k: (dict(self[k]) if k in self._labeled else self[k])
                     for k in self._keys})
