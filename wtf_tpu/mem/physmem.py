"""Guest physical memory as an HBM-resident page store.

Replaces the reference's `Ram_t` (reference src/wtf/ram.h:96-152) and the
backends' demand-paging machinery (bochscpu lazy page handler
bochscpu_backend.cc:36-138, KVM userfaultfd kvm_backend.cc:2114-2304): on TPU
the whole snapshot image is uploaded once into HBM as a dense `[slots, 4096]`
uint8 array shared read-only by every lane, plus an int32 frame table mapping
guest page-frame-number -> slot.  Slot 0 is a shared zero page; pages absent
from the dump read as zeros, matching the reference's zero-fill semantics
(ram.h:249-262).

Guest writes NEVER touch this image — they go to the per-lane dirty overlay
(wtf_tpu/mem/overlay.py), which is what makes `Restore()` O(1).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from wtf_tpu.core.gxa import PAGE_SHIFT, PAGE_SIZE


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


PAGE_WORDS = PAGE_SIZE // 8


class MemImage(NamedTuple):
    """Device half of PhysMem; broadcast (unmapped) under vmap over lanes.

    Pages are stored as little-endian uint64 WORDS, not bytes: the
    interpreter's accesses (page-table entries, operand loads/stores,
    code fetch) read aligned word windows and extract bytes with shifts,
    cutting gather counts ~5-8x vs a byte-granular layout (a 16-byte
    unaligned access is 3 word gathers instead of 16 byte gathers; a PTE
    read is 1 instead of 8).

    Multi-tenancy (wtf_tpu/tenancy): `frame_table` carries a leading
    TENANT axis — one pfn->slot row per base image, padded to a common
    page-span layout — and the optional `tenant` leaf is the per-lane
    row selector (int32[L] at dispatch; scalar under vmap).  A
    single-snapshot image is the degenerate [1, span] table with
    tenant=None (row 0 statically), so the pre-tenancy contract is
    unchanged and the pytree gains no leaf."""

    pages: jax.Array       # uint64[slots, PAGE_WORDS]; slot 0 = zero page
    frame_table: jax.Array # int32[tenants, span]; pfn -> slot (0 = absent)
    tenant: Optional[jax.Array] = None  # int32[L] lane -> frame-table row


@dataclasses.dataclass
class PhysMem:
    """Host-side container: builds the device image from a sparse page dict."""

    image: MemImage
    nframes: int
    present: np.ndarray  # bool[nframes] — page was present in the dump

    @classmethod
    def from_pages(cls, pages: Dict[int, bytes], min_frames: int = 16) -> "PhysMem":
        """Build from {pfn: 4KiB page bytes}.

        Equivalent of `Ram_t::Populate` (ram.h:96-152) — but produces a dense
        packed array (only pages present in the dump occupy slots) instead of
        a flat mmap sized to the biggest GPA.
        """
        if pages:
            max_pfn = max(pages)
        else:
            max_pfn = 0
        # Pad both array dims to powers of two: guests of similar size then
        # share XLA-compiled executables (shape-polymorphism by padding).
        nframes = _next_pow2(max(max_pfn + 1, min_frames))

        pfns = sorted(pages)
        packed = np.zeros((_next_pow2(len(pfns) + 1), PAGE_SIZE), dtype=np.uint8)
        frame_table = np.zeros(nframes, dtype=np.int32)
        present = np.zeros(nframes, dtype=bool)
        for slot, pfn in enumerate(pfns, start=1):
            data = pages[pfn]
            if len(data) != PAGE_SIZE:
                raise ValueError(f"page {pfn:#x} has size {len(data)}")
            packed[slot] = np.frombuffer(data, dtype=np.uint8)
            frame_table[pfn] = slot
            present[pfn] = True

        image = MemImage(
            pages=jnp.asarray(packed.view(np.uint64)),  # LE word view
            frame_table=jnp.asarray(frame_table[None, :]),  # [1, span]
        )
        return cls(image=image, nframes=nframes, present=present)

    @property
    def nbytes(self) -> int:
        return int(self.image.pages.size * 8
                   + self.image.frame_table.size * 4)

    def host_read(self, gpa: int, size: int) -> bytes:
        """Debug/host-side read of the *base* image (no overlay)."""
        if not hasattr(self, "_host_pages"):
            # Cache host copies once; the image is immutable after build.
            self._host_pages = np.asarray(self.image.pages).view(np.uint8)
            self._host_table = np.asarray(self.image.frame_table)[0]
        out = bytearray()
        pos = gpa
        end = gpa + size
        while pos < end:
            pfn = pos >> PAGE_SHIFT
            off = pos & (PAGE_SIZE - 1)
            chunk = min(end - pos, PAGE_SIZE - off)
            slot = int(self._host_table[pfn]) if pfn < self.nframes else 0
            out += self._host_pages[slot, off : off + chunk].tobytes()
            pos += chunk
        return bytes(out)


# vmap in_axes for a dispatch image: pages/frame_table broadcast, the
# per-lane tenant selector mapped.  Only valid for images normalized
# through `lane_image` (tenant populated).
IMAGE_IN_AXES = MemImage(pages=None, frame_table=None, tenant=0)


def lane_image(image: MemImage, n_lanes: int) -> MemImage:
    """Normalize a dispatch image so `tenant` is always a populated
    int32[n_lanes] row selector (zeros for the single-image case) —
    executors normalize in-body so legacy callers passing a bare
    PhysMem image and tenancy runners share one vmap structure."""
    if image.tenant is None:
        return image._replace(tenant=jnp.zeros((n_lanes,), jnp.int32))
    return image


def frame_slot(image: MemImage, pfn: jax.Array) -> jax.Array:
    """pfn (int32) -> slot, with out-of-range pfns mapping to the zero page.

    The lane's frame-table row comes from `image.tenant` (the per-lane
    base-image selector, scalar under the interpreter's vmap); tenant=None
    is the single-image case and indexes row 0 statically — same program
    as the pre-tenancy 1-D table."""
    span = image.frame_table.shape[-1]
    in_range = (pfn >= 0) & (pfn < span)
    safe_pfn = jnp.clip(pfn, 0, span - 1)
    row = jnp.int32(0) if image.tenant is None else image.tenant
    return jnp.where(in_range, image.frame_table[row, safe_pfn], 0)
