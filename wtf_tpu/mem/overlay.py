"""Per-lane dirty-page overlay: copy-on-write guest memory.

This is the TPU-native replacement for the reference's dirty-page tracking +
restore machinery (bochs write hooks bochscpu_backend.cc:550-593, KVM dirty
bitmaps kvm_backend.cc:1568-1637, WHV R-X write-protection faults
whv_backend.cc:1163-1189, and `Ram_t::Restore` ram.h:235-280).  Instead of
mutating guest RAM and rolling dirty pages back after every testcase, each
lane owns a small copy-on-write overlay: the first write to a page copies it
from the shared HBM image into the lane's overlay slot, and every later
read/write checks the overlay first.  `Restore()` is then a counter reset —
no page data ever moves.

Layout: page data is uint64 WORDS (little-endian), matching MemImage.  The
hot primitives operate on aligned word windows — `pte_read` (1 word),
`load_window3` (3 words cover any <=16-byte span), `store_window3` (3-word
read-modify-write) — so a memory access costs a handful of word gathers
instead of per-byte gathers.  Byte-granular `gather_bytes`/`scatter_span`
remain for host-driven paths (testcase insertion, traces, tests).

All functions here operate on a SINGLE lane's overlay and are `vmap`ped over
the lane axis by the interpreter (MemImage broadcast, Overlay mapped).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from wtf_tpu.core.gxa import PAGE_SHIFT, PAGE_SIZE
from wtf_tpu.mem.physmem import MemImage, PAGE_WORDS, frame_slot

# pfn sentinel for "out of physical range" — never matches a stored pfn and
# frame_slot() maps it to the zero page.  Plain int: module import must not
# touch the device (jnp scalars would initialize the backend).
_PFN_OOB = 0x7FFFFFFF

_U64_MAX = (1 << 64) - 1


def _u(x: int) -> jnp.ndarray:
    return jnp.uint64(x & _U64_MAX)


def _shl(x, s):
    """x << s with s >= 64 yielding 0 (XLA leaves it undefined)."""
    return jnp.where(s >= _u(64), _u(0), x << jnp.minimum(s, _u(63)))


def _shr(x, s):
    return jnp.where(s >= _u(64), _u(0), x >> jnp.minimum(s, _u(63)))


class DirtyOverlay(NamedTuple):
    """One lane's dirty pages (batched: leading lane axis on every field).

    Rows are DELTAS, not copies: `valid[row, w]` marks the words of `data`
    that have been written; reads take the overlay word when its valid
    byte is set and the base image word otherwise.  Allocating a slot
    therefore never copies the 4 KiB base page — the former copy-on-write
    fill was the hot path's dominant memory traffic (16 KiB/lane/step on
    store-heavy code)."""

    pfn: jax.Array       # int32[capacity]; -1 = free slot
    data: jax.Array      # uint64[capacity, PAGE_WORDS]
    valid: jax.Array     # uint8[capacity, PAGE_WORDS]; 1 = word overlaid
    count: jax.Array     # int32 scalar: allocated slots
    overflow: jax.Array  # bool scalar: lane ran out of overlay slots


def overlay_init(n_lanes: int, capacity: int) -> DirtyOverlay:
    """Allocate the batched overlay store for `n_lanes` lanes."""
    return DirtyOverlay(
        pfn=jnp.full((n_lanes, capacity), -1, dtype=jnp.int32),
        data=jnp.zeros((n_lanes, capacity, PAGE_WORDS), dtype=jnp.uint64),
        valid=jnp.zeros((n_lanes, capacity, PAGE_WORDS), dtype=jnp.uint8),
        count=jnp.zeros((n_lanes,), dtype=jnp.int32),
        overflow=jnp.zeros((n_lanes,), dtype=bool),
    )


def overlay_reset(overlay: DirtyOverlay) -> DirtyOverlay:
    """Restore(): drop every dirty page, O(1) in page data.

    Replaces `Ram_t::Restore` + per-backend dirty loops (ram.h:235-280)."""
    return DirtyOverlay(
        pfn=jnp.full_like(overlay.pfn, -1),
        data=overlay.data,   # stale data is unreachable once pfn is -1
        valid=overlay.valid,  # stale too: cleared when a slot reallocates
        count=jnp.zeros_like(overlay.count),
        overflow=jnp.zeros_like(overlay.overflow),
    )


def split_gpa(image: MemImage, gpa: jax.Array):
    """gpa (uint64) -> (pfn int32 with OOB sentinel, offset int32)."""
    nframes = image.frame_table.shape[-1]
    pfn64 = gpa >> PAGE_SHIFT
    in_range = pfn64 < jnp.uint64(nframes)
    pfn = jnp.where(in_range, pfn64, jnp.uint64(_PFN_OOB)).astype(jnp.int32)
    off = (gpa & jnp.uint64(PAGE_SIZE - 1)).astype(jnp.int32)
    return pfn, off


def lookup(overlay: DirtyOverlay, pfn: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Find `pfn` in this lane's overlay -> (slot index, hit)."""
    eq = overlay.pfn == pfn
    idx = jnp.argmax(eq).astype(jnp.int32)
    hit = eq[idx]
    return idx, hit


def lookup_vec(
    overlay: DirtyOverlay, pfn_vec: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Find K pfns in this lane's overlay at once -> (idx[K], hit[K]).

    One [K, capacity] compare + one row-wise argmax instead of K scalar
    probes: the interpreter batches every overlay lookup a step needs into
    a single call, cutting the per-step count of unfusable gather kernels
    (the TPU cost is per-kernel dispatch latency, not the compares)."""
    eq = overlay.pfn[None, :] == pfn_vec[:, None]
    idx = jnp.argmax(eq, axis=1).astype(jnp.int32)
    hit = jnp.any(eq, axis=1)  # gather-free: argmax picks the first True
    return idx, hit


def read_words_vec(
    image: MemImage,
    overlay: DirtyOverlay,
    slot_vec: jax.Array,    # int32[K] image page slots
    row_vec: jax.Array,     # int32[K] overlay rows
    use_ov_vec: jax.Array,  # bool[K]: the page hit an overlay slot
    widx_vec: jax.Array,    # int32[K] word index within the page
) -> jax.Array:
    """K overlay-aware aligned words in three gathers (image + overlay
    data + overlay word-validity)."""
    base = image.pages[slot_vec, widx_vec]
    ov = overlay.data[row_vec, widx_vec]
    ov_valid = overlay.valid[row_vec, widx_vec] != 0
    return jnp.where(use_ov_vec & ov_valid, ov, base)


def pte_read_vec(
    image: MemImage, overlay: DirtyOverlay, gpa_vec: jax.Array
) -> jax.Array:
    """K 8-aligned little-endian u64 reads (one page-walk level for every
    translation a step needs) -> u64[K]."""
    pfn, off = split_gpa(image, gpa_vec)
    row, hit = lookup_vec(overlay, pfn)
    slot = frame_slot(image, pfn)
    return read_words_vec(image, overlay, slot, row, hit, off >> 3)


def load_windows3_vec(
    image: MemImage,
    overlay: DirtyOverlay,
    gpa_first_vec: jax.Array,  # uint64[K] first-byte GPA per window
    gpa_last_vec: jax.Array,   # uint64[K] last-byte GPA per window
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """K 3-word windows (any <=16-byte span each) -> (w0[K], w1[K], w2[K]).

    The batched form of `load_window3`: one overlay lookup over the 2K
    page frames and one 3K-word gather pair, instead of K independent
    window loads."""
    k = gpa_first_vec.shape[0]
    pfn0, off0 = split_gpa(image, gpa_first_vec)
    pfn1, _ = split_gpa(image, gpa_last_vec)
    rows, hits = lookup_vec(overlay, jnp.concatenate([pfn0, pfn1]))
    row0, row1 = rows[:k], rows[k:]
    hit0, hit1 = hits[:k], hits[k:]
    slot0 = frame_slot(image, pfn0)
    slot1 = frame_slot(image, pfn1)

    w_start = off0 >> 3
    j = jnp.arange(3, dtype=jnp.int32)[:, None]           # [3, 1]
    on_first = (w_start[None, :] + j) < PAGE_WORDS        # [3, K]
    widx = jnp.where(on_first, w_start[None, :] + j,
                     w_start[None, :] + j - PAGE_WORDS)
    slot = jnp.where(on_first, slot0[None, :], slot1[None, :])
    row = jnp.where(on_first, row0[None, :], row1[None, :])
    use_ov = jnp.where(on_first, hit0[None, :], hit1[None, :])
    words = read_words_vec(image, overlay, slot.reshape(-1), row.reshape(-1),
                           use_ov.reshape(-1), widx.reshape(-1)).reshape(3, k)
    return words[0], words[1], words[2]


def ensure_page(
    image: MemImage, overlay: DirtyOverlay, pfn: jax.Array, enabled: jax.Array
) -> Tuple[DirtyOverlay, jax.Array, jax.Array]:
    """Claim an overlay slot for `pfn` when `enabled` (delta semantics: the
    row's data words are MEANINGLESS until their valid bytes are set by a
    store — always read through `read_words_vec`, never `data` directly).

    Returns (overlay', slot index, ok).  ok=False when the overlay is full
    (the run loop surfaces that lane as a hard error) or pfn is out of range.
    """
    capacity = overlay.pfn.shape[0]
    idx0, hit = lookup(overlay, pfn)

    in_range = pfn != _PFN_OOB
    can_alloc = overlay.count < capacity
    do_alloc = enabled & ~hit & can_alloc & in_range
    idx = jnp.where(hit, idx0, overlay.count % capacity).astype(jnp.int32)

    # delta rows: allocation just claims the slot and clears its word
    # validity (512 bytes) — no 4 KiB base-page copy
    valid = overlay.valid.at[idx].set(
        jnp.where(do_alloc, jnp.zeros(PAGE_WORDS, jnp.uint8),
                  overlay.valid[idx]))
    pfns = overlay.pfn.at[idx].set(
        jnp.where(do_alloc, pfn, overlay.pfn[idx]).astype(jnp.int32)
    )
    count = overlay.count + do_alloc.astype(jnp.int32)
    overflow = overlay.overflow | (enabled & ~hit & ~can_alloc & in_range)

    ok = (hit | do_alloc) & in_range
    return DirtyOverlay(pfns, overlay.data, valid, count, overflow), idx, ok


# ---------------------------------------------------------------------------
# hot word-window primitives (the interpreter's memory path)
# ---------------------------------------------------------------------------

def pte_read(image: MemImage, overlay: DirtyOverlay, gpa: jax.Array) -> jax.Array:
    """Read an 8-aligned little-endian u64 (page-table entries).  K=1
    wrapper over `pte_read_vec` — one implementation of the overlay-aware
    word read."""
    return pte_read_vec(image, overlay,
                        jnp.asarray(gpa, jnp.uint64).reshape(1))[0]


def load_window3(
    image: MemImage,
    overlay: DirtyOverlay,
    gpa_first: jax.Array,
    gpa_last: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Scalar (K=1) convenience wrapper over `load_windows3_vec` — one
    3-word window covering any <=16-byte span."""
    w0, w1, w2 = load_windows3_vec(
        image, overlay, gpa_first[None], gpa_last[None])
    return w0[0], w1[0], w2[0]


def extract_pair(w0, w1, w2, gpa_first):
    """(lo, hi) u64 value pair of the 16 bytes starting at gpa_first,
    from its 3-word window."""
    sh = (gpa_first & _u(7)) * _u(8)
    inv = _u(64) - sh
    lo = _shr(w0, sh) | _shl(w1, inv)
    hi = _shr(w1, sh) | _shl(w2, inv)
    return lo, hi


def store_window3(
    image: MemImage,
    overlay: DirtyOverlay,
    t_first,               # Translation-like with .gpa of first byte
    t_last,                # Translation-like with .gpa of last byte
    size,                  # traced int32, 1..16
    lo: jax.Array,
    hi: jax.Array,
    enabled: jax.Array,
) -> Tuple[DirtyOverlay, jax.Array]:
    """Commit up to 16 bytes (value (lo, hi), little-endian) through the
    lane overlay: copy-on-write the one or two touched pages, then a
    3-word read-modify-write with per-word bitmasks.  Returns
    (overlay', ok); !ok = overlay full."""
    pfn0, off0 = split_gpa(image, t_first.gpa)
    pfn1, _ = split_gpa(image, t_last.gpa)
    crosses = (off0 + size) > PAGE_SIZE
    overlay, row0, ok0 = ensure_page(image, overlay, pfn0, enabled)
    overlay, row1, ok1 = ensure_page(image, overlay, pfn1, enabled & crosses)
    ok = ok0 & (ok1 | ~crosses)
    do = enabled & ok
    slot0 = frame_slot(image, pfn0)
    slot1 = frame_slot(image, pfn1)

    sh = (off0.astype(jnp.uint64) & _u(7)) * _u(8)
    inv = _u(64) - sh
    # value spread over the 3-word window
    v0 = _shl(lo, sh)
    v1 = _shr(lo, inv) | _shl(hi, sh)
    v2 = _shr(hi, inv)
    # bit span [sh, sh + size*8) within the 192-bit window
    end_bit = sh + size.astype(jnp.uint64) * _u(8)

    w_start = off0 >> 3
    rows = []
    widxs = []
    news = []
    vnews = []
    for j, vj in enumerate((v0, v1, v2)):
        on_first = (w_start + j) < PAGE_WORDS
        widx = jnp.where(on_first, w_start + j, w_start + j - PAGE_WORDS)
        row = jnp.where(on_first, row0, row1)
        slot = jnp.where(on_first, slot0, slot1)
        lo_bit = _u(64 * j)
        # mask of the bits of word j inside the span [sh, end_bit)
        start_in = jnp.maximum(sh, lo_bit)
        end_in = jnp.minimum(end_bit, lo_bit + _u(64))
        has = end_in > start_in
        n_bits = jnp.where(has, end_in - start_in, _u(0))
        off_in = jnp.where(has, start_in - lo_bit, _u(0))
        # n_bits == 64 wraps (1 << 64 -> 0) to the all-ones mask, correct
        mask = _shl(_shl(_u(1), n_bits) - _u(1), off_in)
        # delta rows: a partial write to a not-yet-valid word merges with
        # the base image word; the stored word is then complete -> valid
        was_valid = overlay.valid[row, widx] != 0
        old = jnp.where(was_valid, overlay.data[row, widx],
                        image.pages[slot, widx])
        touched = do & (mask != _u(0))
        new = jnp.where(touched, (old & ~mask) | (vj & mask), old)
        rows.append(row)
        widxs.append(widx)
        news.append(new)
        vnews.append(jnp.where(touched, jnp.uint8(1),
                               was_valid.astype(jnp.uint8)))
    # ONE scatter for all three words (the (row, widx) pairs are distinct
    # by construction: word indices strictly increase within a page and
    # the straddle moves to another row) — sequential single-word
    # scatters would each materialize an overlay copy on some backends
    rows3, widxs3 = jnp.stack(rows), jnp.stack(widxs)
    data = overlay.data.at[rows3, widxs3].set(jnp.stack(news))
    valid = overlay.valid.at[rows3, widxs3].set(jnp.stack(vnews))
    return overlay._replace(data=data, valid=valid), ok


# ---------------------------------------------------------------------------
# byte-granular compatibility paths (host-driven I/O, traces, tests)
# ---------------------------------------------------------------------------

def gather_bytes(
    image: MemImage,
    overlay: DirtyOverlay,
    gpa_vec: jax.Array,   # uint64[size]: per-byte physical address
    first_mask: jax.Array # bool[size]: byte belongs to page of gpa_vec[0]
) -> jax.Array:
    """Overlay-aware read of bytes spread over at most two physical pages."""
    size = gpa_vec.shape[0]
    pfn0, _ = split_gpa(image, gpa_vec[0])
    pfn1, _ = split_gpa(image, gpa_vec[size - 1])

    idx0, hit0 = lookup(overlay, pfn0)
    idx1, hit1 = lookup(overlay, pfn1)
    slot0 = frame_slot(image, pfn0)
    slot1 = frame_slot(image, pfn1)

    byte_off = (gpa_vec & jnp.uint64(PAGE_SIZE - 1)).astype(jnp.int32)
    word_idx = byte_off >> 3
    shift = ((byte_off & 7) * 8).astype(jnp.uint64)
    slot = jnp.where(first_mask, slot0, slot1)
    row = jnp.where(first_mask, idx0, idx1)
    use_ov = jnp.where(first_mask, hit0, hit1)

    words = read_words_vec(image, overlay, slot, row, use_ov, word_idx)
    return ((words >> shift) & jnp.uint64(0xFF)).astype(jnp.uint8)


def scatter_span(
    image: MemImage,
    overlay: DirtyOverlay,
    gpa_first: jax.Array,  # translated GPA of the span's first byte
    gpa_last: jax.Array,   # translated GPA of the span's last byte
    values: jax.Array,     # uint8[size], a virtually-contiguous span
    enabled: jax.Array,    # bool scalar
) -> Tuple[DirtyOverlay, jax.Array]:
    """Overlay-aware write of a contiguous span over at most two physical
    pages -> (overlay', ok).  Bytes are packed into aligned words and
    committed with ONE collision-free word scatter.  Every guest-visible
    write lands in the overlay and is therefore "dirty" by construction
    (VirtWriteDirty, backend.cc:91-127)."""
    size = values.shape[0]
    pfn0, off0 = split_gpa(image, gpa_first)
    pfn1, _ = split_gpa(image, gpa_last)
    two_pages = pfn1 != pfn0

    overlay, idx0, ok0 = ensure_page(image, overlay, pfn0, enabled)
    overlay, idx1, ok1 = ensure_page(image, overlay, pfn1, enabled & two_pages)
    ok = ok0 & jnp.where(two_pages, ok1, True)
    do = enabled & ok

    # pack bytes into the aligned word window [w_start, w_start + W)
    head = (off0 & 7).astype(jnp.int32)
    n_words = (int(size) + 7 + 7) // 8  # worst-case unaligned span
    w_start = off0 >> 3
    vals64 = values.astype(jnp.uint64)
    slot0 = frame_slot(image, pfn0)
    slot1 = frame_slot(image, pfn1)
    rows, widxs, news, vnews = [], [], [], []
    for j in range(n_words):
        # byte indices of this word: i such that head + i in [8j, 8j+8)
        i0 = 8 * j - head  # may be negative (traced)
        k = jnp.arange(8, dtype=jnp.int32)
        src = i0 + k
        valid = (src >= 0) & (src < size)
        src_c = jnp.clip(src, 0, size - 1)
        word_val = jnp.sum(
            jnp.where(valid, vals64[src_c], jnp.uint64(0))
            << (k.astype(jnp.uint64) * jnp.uint64(8)))
        mask = jnp.sum(
            jnp.where(valid, jnp.uint64(0xFF), jnp.uint64(0))
            << (k.astype(jnp.uint64) * jnp.uint64(8)))
        on_first = (w_start + j) < PAGE_WORDS
        widx = jnp.where(on_first, w_start + j, w_start + j - PAGE_WORDS)
        row = jnp.where(on_first, idx0, jnp.where(two_pages, idx1, idx0))
        slot = jnp.where(on_first, slot0, jnp.where(two_pages, slot1, slot0))
        # delta rows: merge partial words with the base image word
        was_valid = overlay.valid[row, widx] != 0
        old = jnp.where(was_valid, overlay.data[row, widx],
                        image.pages[slot, widx])
        touched = do & (mask != 0)
        rows.append(row)
        widxs.append(widx)
        news.append(jnp.where(touched,
                              (old & ~mask) | (word_val & mask), old))
        vnews.append(jnp.where(touched, jnp.uint8(1),
                               was_valid.astype(jnp.uint8)))
    # one scatter: (row, widx) pairs are distinct (word indices strictly
    # increase within each page; the straddle changes row)
    rws, wxs = jnp.stack(rows), jnp.stack(widxs)
    data = overlay.data.at[rws, wxs].set(jnp.stack(news))
    validmap = overlay.valid.at[rws, wxs].set(jnp.stack(vnews))
    return overlay._replace(data=data, valid=validmap), ok


def _contiguous_vec(gpa: jax.Array, size: int):
    offs = jnp.arange(size, dtype=jnp.uint64)
    gpa_vec = gpa + offs
    page_off = (gpa & jnp.uint64(PAGE_SIZE - 1)).astype(jnp.int32)
    first_mask = (page_off + jnp.arange(size, dtype=jnp.int32)) < PAGE_SIZE
    return gpa_vec, first_mask


def phys_read(
    image: MemImage, overlay: DirtyOverlay, gpa: jax.Array, size: int
) -> jax.Array:
    """Contiguous overlay-aware physical read (size <= PAGE_SIZE)."""
    gpa_vec, first_mask = _contiguous_vec(gpa, size)
    return gather_bytes(image, overlay, gpa_vec, first_mask)


def phys_write(
    image: MemImage,
    overlay: DirtyOverlay,
    gpa: jax.Array,
    values: jax.Array,
    enabled: jax.Array,
) -> Tuple[DirtyOverlay, jax.Array]:
    """Contiguous overlay-aware physical write (size <= PAGE_SIZE)."""
    last = gpa + jnp.uint64(values.shape[0] - 1)
    return scatter_span(image, overlay, gpa, last, values, enabled)


def phys_read_u64(image: MemImage, overlay: DirtyOverlay, gpa: jax.Array) -> jax.Array:
    """Read a little-endian u64 (used for page-table entries; PTEs are
    8-aligned so this is a single word)."""
    return pte_read(image, overlay, gpa)
