"""Per-lane dirty-page overlay: copy-on-write guest memory.

This is the TPU-native replacement for the reference's dirty-page tracking +
restore machinery (bochs write hooks bochscpu_backend.cc:550-593, KVM dirty
bitmaps kvm_backend.cc:1568-1637, WHV R-X write-protection faults
whv_backend.cc:1163-1189, and `Ram_t::Restore` ram.h:235-280).  Instead of
mutating guest RAM and rolling dirty pages back after every testcase, each
lane owns a small copy-on-write overlay: the first write to a page copies it
from the shared HBM image into the lane's overlay slot, and every later
read/write checks the overlay first.  `Restore()` is then a counter reset —
no page data ever moves.

All functions here operate on a SINGLE lane's overlay and are `vmap`ped over
the lane axis by the interpreter (MemImage broadcast, Overlay mapped).

Memory accesses are at most `PAGE_SIZE` bytes, so they touch at most two
pages.  The core primitives (`gather_bytes` / `scatter_bytes`) therefore take
a per-byte GPA vector plus a boolean mask saying which of the two candidate
pages (that of byte 0 / that of byte size-1) each byte belongs to.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from wtf_tpu.core.gxa import PAGE_SHIFT, PAGE_SIZE
from wtf_tpu.mem.physmem import MemImage, frame_slot

# pfn sentinel for "out of physical range" — never matches a stored pfn and
# frame_slot() maps it to the zero page.  Plain int: module import must not
# touch the device (jnp scalars would initialize the backend).
_PFN_OOB = 0x7FFFFFFF


class DirtyOverlay(NamedTuple):
    """One lane's dirty pages (batched: leading lane axis on every field)."""

    pfn: jax.Array       # int32[capacity]; -1 = free slot
    data: jax.Array      # uint8[capacity, PAGE_SIZE]
    count: jax.Array     # int32 scalar: allocated slots
    overflow: jax.Array  # bool scalar: lane ran out of overlay slots


def overlay_init(n_lanes: int, capacity: int) -> DirtyOverlay:
    """Allocate the batched overlay store for `n_lanes` lanes."""
    return DirtyOverlay(
        pfn=jnp.full((n_lanes, capacity), -1, dtype=jnp.int32),
        data=jnp.zeros((n_lanes, capacity, PAGE_SIZE), dtype=jnp.uint8),
        count=jnp.zeros((n_lanes,), dtype=jnp.int32),
        overflow=jnp.zeros((n_lanes,), dtype=bool),
    )


def overlay_reset(overlay: DirtyOverlay) -> DirtyOverlay:
    """Restore(): drop every dirty page, O(1) in page data.

    Replaces `Ram_t::Restore` + per-backend dirty loops (ram.h:235-280)."""
    return DirtyOverlay(
        pfn=jnp.full_like(overlay.pfn, -1),
        data=overlay.data,  # stale data is unreachable once pfn is -1
        count=jnp.zeros_like(overlay.count),
        overflow=jnp.zeros_like(overlay.overflow),
    )


def split_gpa(image: MemImage, gpa: jax.Array):
    """gpa (uint64) -> (pfn int32 with OOB sentinel, offset int32)."""
    nframes = image.frame_table.shape[0]
    pfn64 = gpa >> PAGE_SHIFT
    in_range = pfn64 < jnp.uint64(nframes)
    pfn = jnp.where(in_range, pfn64, jnp.uint64(_PFN_OOB)).astype(jnp.int32)
    off = (gpa & jnp.uint64(PAGE_SIZE - 1)).astype(jnp.int32)
    return pfn, off


def lookup(overlay: DirtyOverlay, pfn: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Find `pfn` in this lane's overlay -> (slot index, hit)."""
    eq = overlay.pfn == pfn
    idx = jnp.argmax(eq).astype(jnp.int32)
    hit = eq[idx]
    return idx, hit


def ensure_page(
    image: MemImage, overlay: DirtyOverlay, pfn: jax.Array, enabled: jax.Array
) -> Tuple[DirtyOverlay, jax.Array, jax.Array]:
    """Make `pfn` resident in the overlay (copy-on-write) when `enabled`.

    Returns (overlay', slot index, ok).  ok=False when the overlay is full
    (the run loop surfaces that lane as a hard error) or pfn is out of range.
    """
    capacity = overlay.pfn.shape[0]
    idx0, hit = lookup(overlay, pfn)

    in_range = pfn != _PFN_OOB
    can_alloc = overlay.count < capacity
    do_alloc = enabled & ~hit & can_alloc & in_range
    idx = jnp.where(hit, idx0, overlay.count % capacity).astype(jnp.int32)

    base = image.pages[frame_slot(image, pfn)]
    new_row = jnp.where(do_alloc, base, overlay.data[idx])
    data = overlay.data.at[idx].set(new_row)
    pfns = overlay.pfn.at[idx].set(
        jnp.where(do_alloc, pfn, overlay.pfn[idx]).astype(jnp.int32)
    )
    count = overlay.count + do_alloc.astype(jnp.int32)
    overflow = overlay.overflow | (enabled & ~hit & ~can_alloc & in_range)

    ok = (hit | do_alloc) & in_range
    return DirtyOverlay(pfns, data, count, overflow), idx, ok


def gather_bytes(
    image: MemImage,
    overlay: DirtyOverlay,
    gpa_vec: jax.Array,   # uint64[size]: per-byte physical address
    first_mask: jax.Array # bool[size]: byte belongs to page of gpa_vec[0]
) -> jax.Array:
    """Overlay-aware read of bytes spread over at most two physical pages."""
    size = gpa_vec.shape[0]
    pfn0, _ = split_gpa(image, gpa_vec[0])
    pfn1, _ = split_gpa(image, gpa_vec[size - 1])

    idx0, hit0 = lookup(overlay, pfn0)
    idx1, hit1 = lookup(overlay, pfn1)
    slot0 = frame_slot(image, pfn0)
    slot1 = frame_slot(image, pfn1)

    byte_off = (gpa_vec & jnp.uint64(PAGE_SIZE - 1)).astype(jnp.int32)
    slot = jnp.where(first_mask, slot0, slot1)
    row = jnp.where(first_mask, idx0, idx1)
    use_ov = jnp.where(first_mask, hit0, hit1)

    base_vals = image.pages[slot, byte_off]
    ov_vals = overlay.data[row, byte_off]
    return jnp.where(use_ov, ov_vals, base_vals).astype(jnp.uint8)


def scatter_bytes(
    image: MemImage,
    overlay: DirtyOverlay,
    gpa_vec: jax.Array,    # uint64[size]
    first_mask: jax.Array, # bool[size]
    values: jax.Array,     # uint8[size]
    enabled: jax.Array,    # bool scalar
) -> Tuple[DirtyOverlay, jax.Array]:
    """Overlay-aware write over at most two physical pages -> (overlay', ok).

    Every guest-visible write lands in the overlay and is therefore "dirty"
    by construction — the device-side counterpart of the reference's
    `VirtWriteDirty` contract (backend.cc:91-127).
    """
    size = gpa_vec.shape[0]
    pfn0, _ = split_gpa(image, gpa_vec[0])
    pfn1, _ = split_gpa(image, gpa_vec[size - 1])
    two_pages = pfn1 != pfn0

    overlay, idx0, ok0 = ensure_page(image, overlay, pfn0, enabled)
    overlay, idx1, ok1 = ensure_page(image, overlay, pfn1, enabled & two_pages)
    ok = ok0 & jnp.where(two_pages, ok1, True)

    byte_off = (gpa_vec & jnp.uint64(PAGE_SIZE - 1)).astype(jnp.int32)
    row = jnp.where(first_mask, idx0, jnp.where(two_pages, idx1, idx0))

    current = overlay.data[row, byte_off]
    new_vals = jnp.where(enabled & ok, values.astype(jnp.uint8), current)
    data = overlay.data.at[row, byte_off].set(new_vals)
    return overlay._replace(data=data), ok


def _contiguous_vec(gpa: jax.Array, size: int):
    offs = jnp.arange(size, dtype=jnp.uint64)
    gpa_vec = gpa + offs
    page_off = (gpa & jnp.uint64(PAGE_SIZE - 1)).astype(jnp.int32)
    first_mask = (page_off + jnp.arange(size, dtype=jnp.int32)) < PAGE_SIZE
    return gpa_vec, first_mask


def phys_read(
    image: MemImage, overlay: DirtyOverlay, gpa: jax.Array, size: int
) -> jax.Array:
    """Contiguous overlay-aware physical read (size <= PAGE_SIZE)."""
    gpa_vec, first_mask = _contiguous_vec(gpa, size)
    return gather_bytes(image, overlay, gpa_vec, first_mask)


def phys_write(
    image: MemImage,
    overlay: DirtyOverlay,
    gpa: jax.Array,
    values: jax.Array,
    enabled: jax.Array,
) -> Tuple[DirtyOverlay, jax.Array]:
    """Contiguous overlay-aware physical write (size <= PAGE_SIZE)."""
    gpa_vec, first_mask = _contiguous_vec(gpa, values.shape[0])
    return scatter_bytes(image, overlay, gpa_vec, first_mask, values, enabled)


def phys_read_u64(image: MemImage, overlay: DirtyOverlay, gpa: jax.Array) -> jax.Array:
    """Read a little-endian u64 (used for page-table entries; PTEs are
    8-aligned so this never crosses a page)."""
    raw = phys_read(image, overlay, gpa, 8)
    shifts = jnp.arange(8, dtype=jnp.uint64) * 8
    return jnp.sum(raw.astype(jnp.uint64) << shifts)
