"""x86-64 long-mode address translation as a pure, traceable function.

Same 4-level walk the reference implements in software for KVM/WHV
(reference kvm_backend.cc:1937-1998 `VirtTranslate`, whv_backend.cc:650-721
`TranslateGva`), expressed as straight-line JAX with where-accumulation
instead of early returns so it vmaps over lanes.  Large pages (1GiB PDPTE.PS,
2MiB PDE.PS) are supported; accessed/dirty PTE bits are NOT set (documented
divergence — bochs sets them, which only grows the dirty-page set, and our
restore is overlay-based so nothing is lost).

Page-table reads go through the lane's dirty overlay so guest-modified page
tables are honored within a testcase.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from wtf_tpu.core.gxa import PAGE_SIZE
from wtf_tpu.mem.overlay import (
    DirtyOverlay,
    gather_bytes,
    phys_read_u64,
    pte_read_vec,
    scatter_span,
)
from wtf_tpu.mem.physmem import MemImage

# Plain ints (promote against uint64 arrays): importing this module must not
# initialize the JAX backend.
PHYS_MASK = 0x000F_FFFF_FFFF_F000
PHYS_MASK_1G = 0x000F_FFFF_C000_0000
PHYS_MASK_2M = 0x000F_FFFF_FFE0_0000

PTE_PRESENT = 1
PTE_WRITE = 1 << 1
PTE_USER = 1 << 2
PTE_PS = 1 << 7


class Translation(NamedTuple):
    gpa: jax.Array       # uint64
    ok: jax.Array        # bool: canonical and present all the way down
    writable: jax.Array  # bool: AND of W bits along the walk
    user: jax.Array      # bool: AND of U/S bits along the walk


def is_canonical(gva: jax.Array) -> jax.Array:
    """48-bit canonical check (bits 63:47 all equal)."""
    top = gva >> jnp.uint64(47)
    return (top == jnp.uint64(0)) | (top == jnp.uint64(0x1FFFF))


def translate(
    image: MemImage, overlay: DirtyOverlay, cr3: jax.Array, gva: jax.Array
) -> Translation:
    """Walk PML4 -> PDPT -> PD -> PT for one GVA (single lane; vmapped).

    K=1 wrapper over `translate_vec` so the walk has exactly one
    implementation (host-side reads and the device step cannot diverge)."""
    t = translate_vec(image, overlay, cr3,
                      jnp.asarray(gva, jnp.uint64).reshape(1))
    return Translation(gpa=t.gpa[0], ok=t.ok[0],
                       writable=t.writable[0], user=t.user[0])


def translate_vec_l(
    image: MemImage, overlay: DirtyOverlay, cr3: jax.Array, gva_l: jax.Array
) -> Translation:
    """`translate_vec` over u32 limb-packed GVAs (uint32[K, 2], limb 0 low).

    This is the pack_u64 boundary adapter for the limb-packed interpreter
    hot path (interp/limbs.py): addresses are computed in u32 limbs, and
    the page walk — gather-bound, not elementwise-bound — converts at this
    seam with a free bitcast and runs in u64 as before.
    """
    from wtf_tpu.interp.limbs import pack_u64

    return translate_vec(image, overlay, cr3, pack_u64(gva_l))


def translate_vec(
    image: MemImage, overlay: DirtyOverlay, cr3: jax.Array, gva_vec: jax.Array
) -> Translation:
    """Walk K GVAs at once -> Translation with [K] fields.

    Bit-identical to `translate` per element; the K walks share one
    overlay lookup + PTE gather per level (the interpreter's six
    translations per step collapse from 24 scalar PTE reads into 4
    vectorized ones)."""
    table = jnp.broadcast_to(cr3 & PHYS_MASK, gva_vec.shape)
    ok = is_canonical(gva_vec)
    writable = jnp.ones_like(ok)
    user = jnp.ones_like(ok)
    done = jnp.zeros_like(ok)
    gpa = jnp.zeros_like(gva_vec)

    levels = ((39, None), (30, PHYS_MASK_1G), (21, PHYS_MASK_2M), (12, None))
    for shift, large_mask in levels:
        index = (gva_vec >> jnp.uint64(shift)) & jnp.uint64(0x1FF)
        entry = pte_read_vec(image, overlay, table + index * jnp.uint64(8))
        present = (entry & PTE_PRESENT) != 0
        ok = ok & (done | present)
        writable = writable & (done | ((entry & PTE_WRITE) != 0))
        user = user & (done | ((entry & PTE_USER) != 0))

        if large_mask is not None:
            is_large = present & ((entry & PTE_PS) != 0) & ~done
            page_mask = (jnp.uint64(1) << jnp.uint64(shift)) - jnp.uint64(1)
            large_gpa = (entry & large_mask) | (gva_vec & page_mask)
            gpa = jnp.where(is_large, large_gpa, gpa)
            done = done | is_large
        if shift == 12:
            leaf_gpa = (entry & PHYS_MASK) | (gva_vec & jnp.uint64(0xFFF))
            gpa = jnp.where(done, gpa, leaf_gpa)

        table = entry & PHYS_MASK

    return Translation(gpa=gpa, ok=ok, writable=writable, user=user)


def _virt_byte_addrs(gva: jax.Array, size: int, first: Translation, last: Translation):
    """Per-byte GPA vector for a virtual span touching at most two pages."""
    offs = jnp.arange(size, dtype=jnp.uint64)
    page_off = (gva & jnp.uint64(PAGE_SIZE - 1)).astype(jnp.int32)
    first_mask = (page_off + jnp.arange(size, dtype=jnp.int32)) < PAGE_SIZE
    gpa_first = first.gpa + offs
    gpa_last = last.gpa - jnp.uint64(size - 1) + offs
    gpa_vec = jnp.where(first_mask, gpa_first, gpa_last)
    return gpa_vec, first_mask


def virt_read(
    image: MemImage,
    overlay: DirtyOverlay,
    cr3: jax.Array,
    gva: jax.Array,
    size: int,
) -> Tuple[jax.Array, jax.Array]:
    """Read uint8[size] at a guest-virtual address -> (bytes, fault).

    Two-translation form of the reference's page-by-page `VirtRead`
    (backend.cc:30-77): translate the first and last byte, stitch the spans.
    """
    first = translate(image, overlay, cr3, gva)
    last = translate(image, overlay, cr3, gva + jnp.uint64(size - 1))
    fault = ~(first.ok & last.ok)
    gpa_vec, first_mask = _virt_byte_addrs(gva, size, first, last)
    data = gather_bytes(image, overlay, gpa_vec, first_mask)
    return data, fault


def virt_write(
    image: MemImage,
    overlay: DirtyOverlay,
    cr3: jax.Array,
    gva: jax.Array,
    values: jax.Array,
    enabled: jax.Array,
    enforce_writable: bool = False,
) -> Tuple[DirtyOverlay, jax.Array]:
    """Write uint8[size] at a guest-virtual address -> (overlay', fault).

    `enforce_writable=True` is the guest-store path: writes to mappings whose
    walk lacks the W bit fault like a real CPU with CR0.WP would.  Host-side
    writes (InsertTestcase etc.) keep the reference's semantics of writing
    through protection (backend.cc VirtWrite is a raw memcpy).
    """
    size = values.shape[0]
    first = translate(image, overlay, cr3, gva)
    last = translate(image, overlay, cr3, gva + jnp.uint64(size - 1))
    fault = ~(first.ok & last.ok)
    if enforce_writable:
        fault = fault | ~(first.writable & last.writable)
    overlay, ok = scatter_span(
        image, overlay, first.gpa, last.gpa, values, enabled & ~fault
    )
    return overlay, fault | (enabled & ~fault & ~ok)


def virt_read_u64(
    image: MemImage,
    overlay: DirtyOverlay,
    cr3: jax.Array,
    gva: jax.Array,
    size: int = 8,
) -> Tuple[jax.Array, jax.Array]:
    """Read a <=8-byte little-endian integer -> (uint64 value, fault)."""
    raw, fault = virt_read(image, overlay, cr3, gva, size)
    shifts = jnp.arange(size, dtype=jnp.uint64) * 8
    return jnp.sum(raw.astype(jnp.uint64) << shifts), fault
