from wtf_tpu.mem.physmem import PhysMem
from wtf_tpu.mem.overlay import DirtyOverlay, overlay_init, overlay_reset
