"""devmangle host reference: the authoritative, jax-free op spec.

The device engine (wtf_tpu/devmut/engine.py) and this module implement
the SAME algorithm — one vectorized over lanes in u32 XLA ops, one as
plain Python ints — and the property tests (tests/test_devmut.py) pin
them bit-for-bit against each other.  When the two disagree, THIS file
is the spec: every op below is written as the scalar loop the device
formulas must reproduce.

Algorithm (per lane, per batch):

  PRNG      splitmix64 stream (utils.hashing semantics, matching
            interp/limbs.py bit-for-bit): state += GOLDEN; out =
            mix64(state).  All derived quantities use the LOW 32 bits
            of a draw (the device holds draws as u32 limb pairs).
  draws     r_slot, r_len, r_fill, r_other up front, then exactly
            (r_op, r1, r2, r3) per mangle round — the draw count is
            fixed so device and host streams can never skew.
  base      weighted corpus-slot pick (cumulative-weight inverse); an
            empty corpus synthesizes 1..64 fresh bytes from the stream.
  rounds    `rounds` mangle ops, each drawn uniformly from the 8-op
            table (honggfuzz-mangle classes, reference mutator.h role):
            byte/word overwrite, arith delta, magic value, block copy,
            insert(dup), erase, splice/cross-over with a second slot.
  invariant 1 <= len <= max_len always; bytes at positions >= len are
            ZERO after every round (the padded-slab contract device
            insertion relies on for deterministic page contents).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from wtf_tpu.fuzz.mutator import _MAGIC
from wtf_tpu.utils.hashing import MASK64, mix64

GOLDEN = 0x9E3779B97F4A7C15
M32 = 0xFFFFFFFF

# op table (order is the wire format of `op = r_op % N_OPS`; changing it
# changes every seeded campaign's stream)
OP_BYTE, OP_WORD, OP_ARITH, OP_MAGIC = 0, 1, 2, 3
OP_COPY, OP_INSERT, OP_ERASE, OP_SPLICE = 4, 5, 6, 7
N_OPS = 8
OP_NAMES = ("byte", "word", "arith", "magic",
            "copy", "insert", "erase", "splice")

# magic-value table shared with the host mangle engine (one table, one
# campaign behavior); padded to 8 bytes device-side
MAGIC: Tuple[bytes, ...] = tuple(_MAGIC)
N_MAGIC = len(MAGIC)
MAG_BYTES_NP = np.zeros((N_MAGIC, 8), dtype=np.uint32)
MAG_LEN_NP = np.zeros((N_MAGIC,), dtype=np.uint32)
for _i, _m in enumerate(MAGIC):
    MAG_LEN_NP[_i] = len(_m)
    for _j, _c in enumerate(_m):
        MAG_BYTES_NP[_i, _j] = _c

# favor weight for coverage-increasing finds vs plain seeds (weight 1)
FAVOR_WEIGHT = 4


def _mix64_np(z: np.ndarray) -> np.ndarray:
    """mix64 vectorized over uint64 arrays (wrapping multiply), bit-exact
    with utils.hashing.mix64 — asserted by tests/test_devmut.py."""
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def lane_seeds(seed: int, batch: int, n_lanes: int) -> np.ndarray:
    """Per-lane PRNG seeds as uint32[L, 2] limb pairs: a splitmix-style
    stream indexed by the flat (batch, lane) counter — deterministic for
    a given campaign seed, distinct across lanes AND batches.
    Vectorized (this runs on every batch dispatch; a python loop here
    would put O(n_lanes) host work back on the mutate path)."""
    idx = np.arange(n_lanes, dtype=np.uint64)
    counter = np.uint64(batch % (1 << 64)) * np.uint64(n_lanes) + idx \
        + np.uint64(1)
    with np.errstate(over="ignore"):
        s = _mix64_np(np.uint64(seed & MASK64)
                      + np.uint64(GOLDEN) * counter)
    out = np.empty((n_lanes, 2), dtype=np.uint32)
    out[:, 0] = (s & np.uint64(M32)).astype(np.uint32)
    out[:, 1] = (s >> np.uint64(32)).astype(np.uint32)
    return out


class _Stream:
    """The splitmix64 draw stream (device: prng_next on limb pairs)."""

    def __init__(self, seed: int):
        self.state = seed & MASK64

    def draw(self) -> int:
        self.state = (self.state + GOLDEN) & MASK64
        return mix64(self.state)


def _pick_slot(cumw: Sequence[int], r: int) -> int:
    """Weighted slot pick: inverse of the inclusive cumulative-weight
    table (zero-weight slots are never chosen)."""
    total = cumw[-1] if len(cumw) else 0
    rr = (r & M32) % max(total, 1)
    cnt = sum(1 for c in cumw if c <= rr)
    return min(cnt, len(cumw) - 1)


def _slab_bytes(data_u32: np.ndarray, length: int, max_len: int) -> List[int]:
    """One corpus slab row -> byte list (zero-padded to max_len)."""
    raw = np.ascontiguousarray(data_u32).view(np.uint8)[:max_len]
    b = [0] * max_len
    for i in range(min(length, max_len)):
        b[i] = int(raw[i])
    return b


def host_generate_lane(
    data: np.ndarray,        # uint32[S, W] corpus slab
    lens: np.ndarray,        # int32[S]
    cumw: np.ndarray,        # uint32[S] inclusive cumulative weights
    seed: int,               # this lane's 64-bit seed
    rounds: int,
    op_trace: Optional[List[int]] = None,
) -> Tuple[bytes, int]:
    """Generate ONE lane's testcase; returns (padded bytes[max_len], len).
    `op_trace`, when given, collects the op code of every round (test
    instrumentation for op-coverage assertions)."""
    max_len = data.shape[1] * 4
    st = _Stream(seed)
    r_slot, r_len, r_fill, r_other = (st.draw(), st.draw(), st.draw(),
                                      st.draw())
    total = int(cumw[-1]) if len(cumw) else 0

    if total > 0:
        slot = _pick_slot(cumw, r_slot)
        ln = max(1, min(int(lens[slot]), max_len))
        b = _slab_bytes(data[slot], ln, max_len)
    else:
        ln = 1 + ((r_len & M32) % min(64, max_len))
        ln = max(1, min(ln, max_len))
        b = [0] * max_len
        for i in range(ln):
            b[i] = mix64((r_fill + i) & MASK64) & 0xFF
    for i in range(ln, max_len):
        b[i] = 0

    if total > 0:
        oslot = _pick_slot(cumw, r_other)
        oln = max(1, min(int(lens[oslot]), max_len))
        ob = _slab_bytes(data[oslot], oln, max_len)
    else:
        ob, oln = list(b), ln

    for _ in range(rounds):
        r_op, r1, r2, r3 = st.draw(), st.draw(), st.draw(), st.draw()
        op = (r_op & M32) % N_OPS
        if op_trace is not None:
            op_trace.append(op)
        snap = list(b)
        if op == OP_BYTE:
            pos = (r1 & M32) % ln
            b[pos] = r2 & 0xFF
        elif op == OP_WORD:
            pos = (r1 & M32) % ln
            for j in range(4):
                if pos + j < ln:
                    b[pos + j] = (r2 >> (8 * j)) & 0xFF
        elif op == OP_ARITH:
            pos = (r1 & M32) % ln
            delta = (((r2 & M32) % 71) + 221) & 0xFF
            b[pos] = (b[pos] + delta) & 0xFF
        elif op == OP_MAGIC:
            m = (r1 & M32) % N_MAGIC
            pos = (r2 & M32) % ln
            for j, c in enumerate(MAGIC[m]):
                if pos + j < ln:
                    b[pos + j] = c
        elif op == OP_COPY:
            src = (r1 & M32) % ln
            dst = (r2 & M32) % ln
            k = 1 + ((r3 & M32) % 16)
            for j in range(k):
                if dst + j < ln and src + j < ln:
                    b[dst + j] = snap[src + j]
        elif op == OP_INSERT:
            pos = (r1 & M32) % ln
            k = min(1 + ((r2 & M32) % 16), max_len - ln)
            if k:
                b = (snap[:pos + k] + snap[pos:max_len - k])[:max_len]
                ln += k
        elif op == OP_ERASE:
            if ln > 1:
                pos = (r1 & M32) % ln
                k = min(1 + ((r2 & M32) % 16), ln - pos, ln - 1)
                b = (snap[:pos] + snap[pos + k:] + [0] * k)[:max_len]
                ln -= k
        else:  # OP_SPLICE
            cut = (r2 & M32) % (ln + 1)
            cut2 = (r3 & M32) % (oln + 1)
            take = min(oln - cut2, max_len - cut)
            new_ln = max(1, cut + take)
            b = [(snap[i] if i < cut
                  else ob[min(cut2 + (i - cut), max_len - 1)])
                 for i in range(new_ln)] + [0] * (max_len - new_ln)
            ln = new_ln
        for i in range(ln, max_len):
            b[i] = 0

    return bytes(b), ln


def host_generate(
    data: np.ndarray,
    lens: np.ndarray,
    cumw: np.ndarray,
    seeds: np.ndarray,       # uint32[L, 2] from lane_seeds()
    rounds: int,
    op_trace: Optional[List[int]] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """The whole-batch mirror of engine.generate: returns
    (words uint32[L, W], lens int32[L])."""
    n_lanes = seeds.shape[0]
    words = np.zeros((n_lanes, data.shape[1]), dtype=np.uint32)
    out_lens = np.zeros((n_lanes,), dtype=np.int32)
    for lane in range(n_lanes):
        seed = int(seeds[lane, 0]) | (int(seeds[lane, 1]) << 32)
        raw, ln = host_generate_lane(data, lens, cumw, seed, rounds,
                                     op_trace=op_trace)
        words[lane] = np.frombuffer(raw, dtype=np.uint8).view(
            np.uint32).copy()
        out_lens[lane] = ln
    return words, out_lens
