"""DevMangleMutator: the `devmangle` engine behind the Mutator contract.

Where every other engine's `get_new_testcase` returns host bytes for the
backend to insert lane-by-lane, this one generates the WHOLE batch on
device (devmut/engine.py) and hands the batched `[lanes, words]` u32
array straight to the Runner's fused insert seam — the testcase stream
never leaves HBM.  Host code only ever pulls the few lanes the harvest
actually wants (crashes, new coverage) via `fetch`.

Double buffering: `prelaunch()` dispatches generation of batch N+1
(async, device-queue only) while the host is still harvesting batch N;
`take_batch()` then just swaps it in, so the campaign's `mutate` phase
shrinks to a fence on already-finished work.  The corpus a prelaunched
batch samples is the slab as of batch N-1's harvest — the standard
one-batch lag of a pipelined generator.

Determinism: the whole stream is a pure function of (campaign seed,
batch index, lane) via hostref.lane_seeds, and slab evolution is
host-ordered — a seeded `--mutator devmangle` campaign replays exactly.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from wtf_tpu.devmut import hostref
from wtf_tpu.devmut.corpus import DeviceCorpus
from wtf_tpu.fuzz.mutator import Mutator
from wtf_tpu.telemetry import NULL, Registry, StatsDict

# generator executor shapes dispatched at least once in this process —
# compile events fire exactly when jit actually compiles (same contract
# as interp.runner._DISPATCHED_EXECUTORS)
_DISPATCHED_GEN = set()


class DevMangleMutator(Mutator):
    """Device-resident mangle engine (fuzz.mutator name: "devmangle")."""

    is_device = True

    def __init__(self, seed: int, max_len: int, rounds: int = 5,
                 slots: int = 256):
        self.seed = seed & ((1 << 64) - 1)
        self.max_len = max_len
        self.rounds = rounds
        self.slots = slots
        self.corpus: Optional[DeviceCorpus] = None
        self.spec = None
        self.pfns: List[int] = []
        self.n_lanes = 0
        self._batch = 0
        self._pending: Optional[Tuple] = None
        self._current: Optional[Tuple] = None
        self.registry: Registry = Registry()
        self.events = NULL
        self.stats: Optional[StatsDict] = None

    # -- device binding ----------------------------------------------------
    def bind(self, backend, target, registry: Optional[Registry] = None,
             events=None) -> None:
        """Attach to the batched backend + target insert spec.  Called by
        FuzzLoop before the first batch; raises early (with the fix) for
        backends/targets that can't run the device path."""
        spec = getattr(target, "device_insert", None)
        if spec is None:
            raise ValueError(
                f"target {getattr(target, 'name', target)!r} has no "
                "device_insert spec — devmangle needs the declarative "
                "insert seam (harness.targets.DeviceInsertSpec)")
        runner = getattr(backend, "runner", None)
        if runner is None or not hasattr(backend, "run_batch_device"):
            raise ValueError(
                "devmangle requires the batched tpu backend "
                "(--backend=tpu); host backends have no device to "
                "generate on")
        from wtf_tpu import telemetry

        self.registry, self.events = telemetry.resolve(
            backend, registry, events)
        self.stats = StatsDict(
            self.registry, "devmut",
            fields=("batches", "generated", "fetched", "corpus_syncs"),
            gauges=("corpus_slots",))
        self.max_len = min(self.max_len, spec.max_len)
        self.corpus = DeviceCorpus(self.slots, self.max_len)
        self.spec = spec
        self.runner = runner
        self.n_lanes = runner.n_lanes
        # input-region pfns through lane 0's page tables — the snapshot
        # mapping is static, so translate ONCE at bind time and the
        # insert seam never page-walks again
        page = 4096
        n_pages = (self.max_len + page - 1) // page
        view = runner.view()
        self.pfns = [view.translate(0, spec.gva + i * page) >> 12
                     for i in range(n_pages)]

    def seed_from(self, corpus) -> int:
        """Load a host Corpus' testcases into the device slab (campaign
        startup: inputs/ seeds).  Returns how many entered."""
        n = 0
        for data in corpus:
            n += bool(self.corpus.add(data))
        return n

    # -- batch generation --------------------------------------------------
    def _dispatch(self) -> Tuple:
        data, lens, cumw, synced = self.corpus.arrays()
        if synced:
            self.stats["corpus_syncs"] += 1
        self.stats["corpus_slots"] = len(self.corpus)
        seeds = hostref.lane_seeds(self.seed, self._batch, self.n_lanes)
        key = (self.rounds, data.shape, seeds.shape, self.runner.exec_sig)
        if key not in _DISPATCHED_GEN:
            _DISPATCHED_GEN.add(key)
            self.events.emit("compile", kind="devmut-gen",
                             rounds=self.rounds, slots=data.shape[0],
                             words=data.shape[1], lanes=self.n_lanes)
        # through the runner's generation seam: a mesh runner runs the
        # generator per shard (slab replicated, seed stream lane-sharded)
        # with the identical per-lane program, so the byte stream stays
        # bit-exact against hostref.lane_seeds on any mesh size
        out = self.generate(self.rounds, data, lens, cumw, seeds)
        self._batch += 1
        self.stats["batches"] += 1
        self.stats["generated"] += self.n_lanes
        return out

    def generate(self, rounds: int, data, lens, cumw, seeds):
        """The generation dispatch — overridable seam: the campaign path
        routes through the runner (mesh runners shard the seed stream);
        tenant-scoped engines (wtf_tpu/tenancy) dispatch the plain
        engine over their lane quota, which is bit-exact by the same
        per-lane program.  Routed through the runner's supervisor with
        wait=False: prelaunch is deliberately async (the double-buffer
        overlap), so a hang here surfaces at the next fenced seam."""
        return self.runner.supervisor.dispatch(
            "devmut-generate", self.runner.devmut_generate,
            rounds, data, lens, cumw, seeds, wait=False)

    def prelaunch(self) -> None:
        """Dispatch generation of the NEXT batch onto the device queue
        (async; no host sync) — the double-buffer half that overlaps
        device generation with host harvest."""
        if self._pending is None:
            self._pending = self._dispatch()

    def take_batch(self) -> Tuple:
        """The batch to execute now: the prelaunched one when present
        (first batch, or after a corpus reseed, it dispatches inline).
        Returns (words u32[L, W], lens i32[L]) device arrays."""
        if self._pending is None:
            self._pending = self._dispatch()
        self._current, self._pending = self._pending, None
        return self._current

    def current_batch(self) -> Tuple:
        """The batch taken for execution (what the insert seam writes)."""
        if self._current is None:
            raise RuntimeError("no device batch taken yet "
                               "(call take_batch first)")
        return self._current

    # -- megachunk window seams (wtf_tpu/fuzz/megachunk.py) ----------------
    def window_slabs(self) -> Tuple:
        """(slab_first, slab_rest) device-array triples for one megachunk
        window: the first batch samples the slab as the device LAST saw
        it — which the harvest pinned via `snapshot_entitled_slab` to
        exclude exactly the PREVIOUS window's final batch's finds (the
        legacy prelaunch lag, preserved exactly) — later batches the
        current host slab.  Pays the re-upload the legacy loop's next
        take_batch would have paid."""
        first, rest, synced = self.corpus.arrays_pair()
        if synced:
            self.stats["corpus_syncs"] += 1
        self.stats["corpus_slots"] = len(self.corpus)
        return first, rest

    def window_seeds(self, n: int):
        """Per-lane splitmix seeds for the next `n` batches of the
        stream — hostref.lane_seeds at consecutive ABSOLUTE batch
        indices, so the byte stream is the same whether batches are
        generated one-at-a-time or in-window (no double-generate, no
        stream skew; tests/test_megachunk.py pins this vs hostref)."""
        import jax.numpy as jnp

        seeds = np.stack([
            hostref.lane_seeds(self.seed, self._batch + j, self.n_lanes)
            for j in range(n)])
        return jnp.asarray(seeds)

    def snapshot_entitled_slab(self) -> None:
        """Pin the slab view the NEXT window's FIRST batch is entitled
        to.  The legacy prelaunch generates batch k+1 during batch k's
        harvest BEFORE k's finds are folded in, so batch k+1 samples
        finds <= k-1.  The harvest therefore calls this just BEFORE the
        window's final processed batch's corpus adds: the re-upload
        makes the as-uploaded view (window_slabs' slab_first) exclude
        exactly that batch's finds — one extra upload only on windows
        that found something (a clean window's slab is not dirty and
        this is free)."""
        *_rest, synced = self.corpus.arrays()
        if synced:
            self.stats["corpus_syncs"] += 1

    def consume_window(self, n: int) -> None:
        """Advance the stream cursor past `n` in-graph-generated batches
        (the megachunk's take_batch).  No prelaunch state exists in
        window mode, so checkpoints carry pending=False and resume
        regenerates nothing."""
        self._batch += n
        self._pending = None
        self.stats["batches"] += n
        self.stats["generated"] += n * self.n_lanes

    def cancel_pending(self) -> None:
        """Entering window mode with a prelaunched legacy batch in
        flight (megachunk re-promotion after a degradation episode, or
        the first window after a batch-at-a-time replay): discard the
        prelaunched arrays and REWIND the cursor so the window
        regenerates the same stream index in-graph — without the rewind,
        consume_window would skip one batch of the deterministic stream.
        The discarded dispatch's output is simply dropped unread; the
        slab's as-uploaded view (synced by that prelaunch, before any
        harvest adds) is exactly the view the window's first batch is
        entitled to, so the in-graph regeneration is byte-identical."""
        if self._pending is not None:
            self._pending = None
            self._batch -= 1
            self.stats["batches"] -= 1
            self.stats["generated"] -= self.n_lanes

    def set_current(self, words, lens) -> None:
        """Point the harvest seam (fetch / current_batch) at one window
        batch's device arrays — the megachunk outputs snapshots of the
        last two batches; the driver swaps each in before fetching its
        crash/new-coverage lanes."""
        self._current = (words, lens)

    # -- host harvest seam -------------------------------------------------
    def fetch(self, lanes: Sequence[int]) -> Dict[int, bytes]:
        """Pull the generated bytes of just `lanes` to the host (crash
        saving / corpus insertion) — the only point where testcase bytes
        leave HBM."""
        if not lanes:
            return {}
        import jax

        words, lens = self.current_batch()
        lens_h = np.asarray(jax.device_get(lens))
        # ONE gather + ONE transfer for all wanted lanes — per-lane
        # device_get would cost len(lanes) round trips, and early
        # batches mark nearly every lane as new coverage.  The index
        # vector is PADDED to a power-of-two bucket (repeating the first
        # lane): the gather's jit executable keys on the index SHAPE,
        # and find counts vary per batch — unpadded, a find-heavy
        # campaign compiles a fresh gather for every distinct count
        # (tens of ms each, a measurable slice of harvest host time).
        lane_list = list(lanes)
        bucket = 1
        while bucket < len(lane_list):
            bucket *= 2
        lane_arr = np.asarray(
            lane_list + [lane_list[0]] * (bucket - len(lane_list)),
            dtype=np.int32)
        rows = np.asarray(jax.device_get(words[lane_arr]))
        out = {int(lane): rows[j].tobytes()[:int(lens_h[lane])]
               for j, lane in enumerate(lane_list)}
        self.stats["fetched"] += len(lane_list)
        return out

    # -- checkpoint/resume (wtf_tpu/resume) --------------------------------
    def checkpoint_state(self) -> dict:
        """Everything a bit-identical resume of the device stream needs:
        the engine seed (drawn once from the campaign RNG at create time
        — the restored run must NOT redraw), the batch cursor, whether a
        prelaunched batch is in flight, and both slab views
        (DeviceCorpus.checkpoint_state).  The byte stream is a pure
        function of (seed, batch, lane, slab-as-uploaded), so this is
        sufficient: the restore regenerates the pending batch instead of
        persisting its bytes."""
        if self.corpus is None:
            raise RuntimeError("devmangle checkpoint before bind()")
        return {
            "seed": self.seed,
            "batch": self._batch,
            "pending": self._pending is not None,
            "slab": self.corpus.checkpoint_state(),
        }

    def restore_state(self, state: dict,
                      regenerate: Optional[bool] = None) -> None:
        """Install a checkpoint into a freshly-bound mutator (bind() and
        seed_from() already ran; their slab is discarded wholesale).
        Regenerates the in-flight prelaunched batch from the slab view
        the original run uploaded, then marks the slab stale so the next
        prelaunch re-uploads the current (post-harvest) view — exactly
        the upload the uninterrupted run would have paid.

        `regenerate` defaults to the checkpoint's own pending flag.  The
        supervisor's recovery passes True: a megachunk-boundary snapshot
        carries pending=False, but when the replay runs batch-at-a-time
        (the ladder stepped below megachunk) the NEXT batch is still
        entitled to the as-uploaded slab view — without regeneration,
        take_batch's inline dispatch would re-upload the newer host slab
        (mark_stale below) and break the one-batch lag."""
        if self.corpus is None:
            raise RuntimeError("devmangle restore before bind()")
        self.seed = int(state["seed"]) & ((1 << 64) - 1)
        self.corpus.restore(state["slab"])
        self._current = None
        self._pending = None
        had_pending = bool(state.get("pending"))
        if had_pending if regenerate is None else regenerate:
            # _dispatch consumes the cached uploaded view; the cursor of
            # a pending=True checkpoint already counted the prelaunched
            # batch, a window-boundary one did not
            self._batch = int(state["batch"]) - (1 if had_pending else 0)
            if int(state["slab"]["uploaded"]["count"]) == 0:
                # the snapshot predates the FIRST slab upload (batch 0):
                # the undo log reconstructs an empty as-uploaded view, so
                # there is nothing to honor — the entitled view is a
                # fresh sync of the current slab, exactly the upload the
                # inline take_batch dispatch would have paid
                self.corpus.mark_stale()
            self._pending = self._dispatch()
        else:
            self._batch = int(state["batch"])
        self.corpus.mark_stale()

    # -- Mutator contract --------------------------------------------------
    def on_new_coverage(self, testcase: bytes) -> None:
        self.corpus.add(testcase, weight=hostref.FAVOR_WEIGHT)

    def get_new_testcase(self, corpus) -> bytes:
        raise RuntimeError(
            "devmangle generates whole batches on device; drive it "
            "through FuzzLoop's device path (or pick a host engine)")
