"""Device-resident mutation engine: the testcase stream lives in HBM.

ROADMAP item 3's mutate-on-device leg.  The host mutate->insert phase
was the serialization point of every batch (PR 3's phase spans put it
squarely on the host); this package moves the mangle-class mutators
in-graph so `mutate -> insert -> execute` is one device program per
batch and the host touches testcase bytes only for crashes, new
coverage, and corpus I/O:

  corpus.py   DeviceCorpus — the [slots, max_len/4] u32 HBM seed slab
              with per-slot lengths and favor weights
  engine.py   the vectorized u32 mangle engine (per-lane splitmix64
              streams on interp/limbs.py; 8-op honggfuzz-class table);
              exports PORTED_LIMB_PATHS so `wtf-tpu lint` pins it
              u64/f64-free like the step's ported paths
  hostref.py  the authoritative jax-free op spec + bit-exact host
              mirror the property tests compare against
  mutator.py  DevMangleMutator — the `devmangle` fuzz.mutator engine,
              double-buffered so generation of batch N+1 overlaps
              host harvest of batch N

The insert seam lives in interp/runner.py (`Runner.device_insert`) and
the batch driver in backend/tpu.py (`run_batch_device`) /
fuzz/loop.py (`FuzzLoop` device path).
"""

from wtf_tpu.devmut.corpus import DeviceCorpus  # noqa: F401
from wtf_tpu.devmut.hostref import (  # noqa: F401
    FAVOR_WEIGHT, N_OPS, OP_NAMES, host_generate, lane_seeds,
)
from wtf_tpu.devmut.mutator import DevMangleMutator  # noqa: F401
