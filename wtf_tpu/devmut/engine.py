"""The device-resident mangle engine: a whole batch of testcases in-graph.

This is the devmangle generator core — ROADMAP item 3's "move the
mangle-class mutators on-device as vectorized u32 ops so the testcase
stream never leaves HBM".  One `generate` dispatch produces every lane's
next testcase from the HBM corpus slab (devmut/corpus.py): per-lane
splitmix64 PRNG streams built on interp/limbs.py, the 8-op mangle table
(hostref.OP_NAMES) vectorized over [lanes, max_len] byte planes, and a
pack back to the u32 words the fused insert seam (interp/runner.py
`device_insert`) writes straight into the per-lane overlay.

Contracts:
  * bit-for-bit equal to devmut/hostref.py (the spec; property-tested)
  * u32/i32/bool ONLY — every public helper here is exported through
    `PORTED_LIMB_PATHS` so `wtf-tpu lint`'s dtype family compiles it
    under the zero-u64/f64 pin, exactly like the step's ported paths
  * all shapes static: jit keys on (slots, words, lanes); `rounds` is a
    python int closed over by `make_generate`

Byte plane: ops run on u32[L, max_len] arrays holding one BYTE per
element (unpacked from the slab's packed u32 words, repacked at the
end).  Positional ops are broadcast compares against an iota — no
scatter; the shifting ops (insert/erase/copy/splice) are ONE gather
each via clamped source-index maps.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from wtf_tpu.devmut.hostref import (
    GOLDEN, MAG_BYTES_NP, MAG_LEN_NP, N_MAGIC, N_OPS,
)
from wtf_tpu.interp import limbs


def prng_next(state):
    """One splitmix64 draw on a (lo, hi) u32 limb-pair state:
    state += GOLDEN; out = mix64(state).  Returns (state', out)."""
    state = limbs.add64(state, limbs.const_pair(GOLDEN))
    return state, limbs.mix64(state)


def pick_slot(cumw, r32):
    """Weighted corpus-slot pick for a batch of u32 draws `r32[L]`:
    inverse of the inclusive cumulative-weight table `cumw[S]` —
    count-of-(cumw <= r % total), so zero-weight slots are never chosen.
    Returns int32[L]."""
    total = jnp.maximum(cumw[-1], jnp.uint32(1))
    rr = r32 % total
    cnt = jnp.sum((cumw[None, :] <= rr[:, None]).astype(jnp.uint32),
                  axis=1, dtype=jnp.uint32)
    return jnp.minimum(
        cnt, jnp.uint32(cumw.shape[-1] - 1)).astype(jnp.int32)


def unpack_bytes(rows):
    """Packed u32 words [..., W] -> byte plane [..., W*4] (little-endian;
    each output element holds one byte value 0..255 in a u32)."""
    shifts = jnp.asarray([0, 8, 16, 24], dtype=jnp.uint32)
    b = (rows[..., None] >> shifts) & jnp.uint32(0xFF)
    return b.reshape(rows.shape[:-1] + (rows.shape[-1] * 4,))


def pack_words(b):
    """Byte plane [..., 4*W] -> packed u32 words [..., W]."""
    return (b[..., 0::4] | (b[..., 1::4] << jnp.uint32(8))
            | (b[..., 2::4] << jnp.uint32(16))
            | (b[..., 3::4] << jnp.uint32(24)))


def generate(data, lens, cumw, seeds, *, rounds: int = 5
             ) -> Tuple[jax.Array, jax.Array]:
    """Generate one testcase per lane, entirely in-graph.

    data  uint32[S, W]   corpus slab (zero-padded past each length)
    lens  int32[S]       per-slot byte lengths (>= 1 for live slots)
    cumw  uint32[S]      inclusive cumulative favor weights (0-total =
                         empty corpus -> fresh synthesis path)
    seeds uint32[L, 2]   per-lane splitmix64 seeds (hostref.lane_seeds)

    Returns (words uint32[L, W], lens int32[L]).  Mirror of
    hostref.host_generate — see that module for the op spec.
    """
    n_slots, n_words = data.shape
    n_lanes = seeds.shape[0]
    max_len = n_words * 4
    ml = jnp.uint32(max_len)
    idx = lax.broadcasted_iota(jnp.uint32, (n_lanes, max_len), 1)
    lane = lax.broadcasted_iota(jnp.int32, (n_lanes, max_len), 0)
    mag_bytes = jnp.asarray(MAG_BYTES_NP)
    mag_lens = jnp.asarray(MAG_LEN_NP)

    def take(b, src_u32):
        """b[lane, min(src, max_len-1)] — the clamped gather every
        shifting op uses (out-of-window sources are selected away)."""
        src = jnp.minimum(src_u32, ml - jnp.uint32(1)).astype(jnp.int32)
        return b[lane, src]

    st = (seeds[:, 0], seeds[:, 1])
    st, r_slot = prng_next(st)
    st, r_len = prng_next(st)
    st, r_fill = prng_next(st)
    st, r_other = prng_next(st)

    have = cumw[-1] > jnp.uint32(0)

    def slab_row(r):
        slot = pick_slot(cumw, r[0])
        row_ln = jnp.clip(lens[slot], 1, max_len).astype(jnp.uint32)
        return unpack_bytes(data[slot]), row_ln

    base_b, base_ln = slab_row(r_slot)
    # empty-corpus synthesis: 1..64 stream bytes (generate_fresh role)
    fresh_ln = jnp.uint32(1) + (r_len[0] % jnp.uint32(min(64, max_len)))
    fill = limbs.mix64(limbs.add64(
        (jnp.broadcast_to(r_fill[0][:, None], idx.shape),
         jnp.broadcast_to(r_fill[1][:, None], idx.shape)),
        (idx, jnp.zeros_like(idx))))[0] & jnp.uint32(0xFF)
    b = jnp.where(have, base_b, fill)
    ln = jnp.where(have, base_ln, jnp.minimum(fresh_ln, ml))
    ln = jnp.maximum(ln, jnp.uint32(1))
    b = jnp.where(idx < ln[:, None], b, jnp.uint32(0))

    # splice partner: drawn once per testcase (self when corpus empty)
    ob_slab, oln_slab = slab_row(r_other)
    ob = jnp.where(have, ob_slab, b)
    oln = jnp.where(have, oln_slab, ln)

    def body(_, carry):
        b, ln, slo, shi = carry
        st = (slo, shi)
        st, r_op = prng_next(st)
        st, r1 = prng_next(st)
        st, r2 = prng_next(st)
        st, r3 = prng_next(st)
        op = r_op[0] % jnp.uint32(N_OPS)
        lnc = ln[:, None]

        # 0/1/2: byte overwrite / word overwrite / arith delta at r1%len
        pos = (r1[0] % ln)[:, None]
        b_byte = jnp.where(idx == pos,
                           (r2[0] & jnp.uint32(0xFF))[:, None], b)
        wwin = (idx >= pos) & (idx < pos + jnp.uint32(4)) & (idx < lnc)
        wsh = ((idx - pos) & jnp.uint32(3)) * jnp.uint32(8)
        b_word = jnp.where(
            wwin, (r2[0][:, None] >> wsh) & jnp.uint32(0xFF), b)
        delta = ((r2[0] % jnp.uint32(71)) + jnp.uint32(221)) & jnp.uint32(0xFF)
        b_arith = jnp.where(
            idx == pos, (b + delta[:, None]) & jnp.uint32(0xFF), b)

        # 3: magic value (clipped to len)
        mrow = mag_bytes[(r1[0] % jnp.uint32(N_MAGIC)).astype(jnp.int32)]
        mlen = mag_lens[(r1[0] % jnp.uint32(N_MAGIC)).astype(jnp.int32)]
        mpos = (r2[0] % ln)[:, None]
        mwin = (idx >= mpos) & (idx < mpos + mlen[:, None]) & (idx < lnc)
        mj = ((idx - mpos) & jnp.uint32(7)).astype(jnp.int32)
        b_magic = jnp.where(mwin, mrow[lane, mj], b)

        # 4: block copy (reads the round-input bytes, memcpy-from-snapshot)
        csrc = r1[0] % ln
        cdst = (r2[0] % ln)[:, None]
        ck = (jnp.uint32(1) + (r3[0] % jnp.uint32(16)))[:, None]
        sidx = csrc[:, None] + (idx - cdst)
        cwin = ((idx >= cdst) & (idx < cdst + ck) & (idx < lnc)
                & (sidx < lnc))
        b_copy = jnp.where(cwin, take(b, sidx), b)

        # 5: insert — duplicate the k bytes at pos, tail shifts right
        ipos = r1[0] % ln
        ik = jnp.minimum(jnp.uint32(1) + (r2[0] % jnp.uint32(16)), ml - ln)
        isrc = jnp.where(idx < (ipos + ik)[:, None], idx, idx - ik[:, None])
        b_ins = take(b, isrc)
        ln_ins = ln + ik

        # 6: erase k bytes at pos (len stays >= 1)
        can = ln > jnp.uint32(1)
        epos = r1[0] % ln
        ek = jnp.uint32(1) + (r2[0] % jnp.uint32(16))
        ek = jnp.minimum(jnp.minimum(ek, ln - epos), ln - jnp.uint32(1))
        ek = jnp.where(can, ek, jnp.uint32(0))
        esrc = jnp.where(idx < epos[:, None], idx, idx + ek[:, None])
        b_erase = take(b, esrc)
        ln_erase = ln - ek

        # 7: splice — our prefix [0, cut) + partner's bytes from cut2
        cut = r2[0] % (ln + jnp.uint32(1))
        cut2 = r3[0] % (oln + jnp.uint32(1))
        stake = jnp.minimum(oln - cut2, ml - cut)
        ssrc = cut2[:, None] + (idx - cut[:, None])
        b_spl = jnp.where(idx < cut[:, None], b, take(ob, ssrc))
        ln_spl = jnp.maximum(cut + stake, jnp.uint32(1))

        cands = ((b_byte, ln), (b_word, ln), (b_arith, ln), (b_magic, ln),
                 (b_copy, ln), (b_ins, ln_ins), (b_erase, ln_erase),
                 (b_spl, ln_spl))
        nb, nl = b, ln
        for code, (cb, cl) in enumerate(cands):
            is_op = op == jnp.uint32(code)
            nb = jnp.where(is_op[:, None], cb, nb)
            nl = jnp.where(is_op, cl, nl)
        # padded-slab contract: bytes past the new length are zero
        nb = jnp.where(idx < nl[:, None], nb, jnp.uint32(0))
        return nb, nl, st[0], st[1]

    b, ln, _, _ = lax.fori_loop(0, rounds, body, (b, ln, st[0], st[1]))
    return pack_words(b), ln.astype(jnp.int32)


@lru_cache(maxsize=None)
def make_generate(rounds: int = 5):
    """The jitted batch generator for a given round count (shape
    specialization is jit's own; one executor per (slots, words, lanes))."""
    return jax.jit(partial(generate, rounds=rounds))


# Export hook for the static analyzer, mirroring step.PORTED_LIMB_PATHS:
# every engine path is compiled standalone under the zero-u64/f64 dtype
# rule by `wtf-tpu lint` and tests/test_limbs.py (argument recipes live
# in analysis/rules._dtype_arg_recipes).
PORTED_LIMB_PATHS = {
    "devmut.prng_next": prng_next,
    "devmut.pick_slot": pick_slot,
    "devmut.unpack_bytes": unpack_bytes,
    "devmut.pack_words": pack_words,
    "devmut.generate": generate,
}
