"""DeviceCorpus: the HBM-resident seed slab the devmangle engine reads.

Host-managed, device-consumed: the host keeps the authoritative numpy
slab (`[slots, max_len/4]` u32 words, per-slot byte lengths and favor
weights) and uploads it lazily — `arrays()` returns the cached device
triple and re-uploads only after a mutating `add`.  The engine never
reads testcase bytes back; the slab is write-mostly from the host's
perspective (one upload per harvest round that found something) and
read-every-batch from the device's.

Slot policy: fill empty slots first; when full, evict the lowest-weight
slot (first index on ties) — coverage-increasing finds enter with
`hostref.FAVOR_WEIGHT`, plain seeds with weight 1, so favored testcases
both survive eviction longer AND are drawn proportionally more often by
the engine's cumulative-weight pick.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from wtf_tpu.utils.hashing import hex_digest


class DeviceCorpus:
    def __init__(self, slots: int, max_len: int):
        if max_len < 4:
            raise ValueError("devmut max_len must be >= 4 bytes")
        self.slots = slots
        self.max_len = max_len
        self.words = (max_len + 3) // 4
        self._data = np.zeros((slots, self.words), dtype=np.uint32)
        self._len = np.zeros((slots,), dtype=np.int32)
        self._weight = np.zeros((slots,), dtype=np.uint32)
        self._slot_of: Dict[str, int] = {}   # digest -> slot
        self._digest_of: Dict[int, str] = {}
        self.count = 0
        self._dirty = True
        self._dev: Optional[Tuple] = None
        # Undo log since the last device upload (checkpoint/resume): the
        # prelaunched batch N+1 was generated from the slab AS UPLOADED,
        # which by checkpoint time has diverged from the host-authoritative
        # slab (batch N's harvest added finds).  Recording each slot's
        # pre-image at its first post-upload mutation lets uploaded_state()
        # reconstruct exactly what the pending batch sampled — without
        # keeping a full second copy of a possibly-huge slab.
        self._undo: Dict[int, Tuple] = {}
        self._uploaded_count = 0

    def __len__(self) -> int:
        return self.count

    def add(self, data: bytes, weight: int = 1) -> bool:
        """Insert a testcase (truncated to max_len, zero-padded into its
        slot).  Returns False for empties and content duplicates —
        a duplicate re-add BUMPS the existing slot to max(old, weight)
        so a favored re-find upgrades its seed."""
        data = data[:self.max_len]
        if not data:
            return False
        digest = hex_digest(data)
        slot = self._slot_of.get(digest)
        if slot is not None:
            if weight > self._weight[slot]:
                self._note_undo(slot)
                self._weight[slot] = weight
                self._dirty = True
            return False
        if self.count < self.slots:
            slot = self.count
            self.count += 1
        else:
            slot = int(np.argmin(self._weight))
            self._slot_of.pop(self._digest_of.pop(slot, ""), None)
        self._note_undo(slot)
        buf = np.zeros(self.words * 4, dtype=np.uint8)
        buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        self._data[slot] = buf.view(np.uint32)
        self._len[slot] = len(data)
        self._weight[slot] = max(weight, 1)
        self._slot_of[digest] = slot
        self._digest_of[slot] = digest
        self._dirty = True
        return True

    def cumulative_weights(self) -> np.ndarray:
        """Inclusive cumulative favor weights (the engine's pick table).
        u32 by contract: weights are small ints, so the total cannot
        approach 2^32 at any plausible slot count."""
        cum = np.cumsum(self._weight, dtype=np.uint64)
        assert cum[-1] < (1 << 32), "favor-weight total overflows u32"
        return cum.astype(np.uint32)

    def arrays(self) -> Tuple:
        """(data, lens, cumw) as device arrays; re-uploads only when a
        host-side add dirtied the slab.  Returns a 4th element `synced`
        telling the caller whether this call paid an upload."""
        synced = False
        if self._dirty or self._dev is None:
            import jax.numpy as jnp

            self._dev = (jnp.asarray(self._data), jnp.asarray(self._len),
                         jnp.asarray(self.cumulative_weights()))
            self._dirty = False
            synced = True
            # device now matches the host slab: new undo baseline
            self._undo.clear()
            self._uploaded_count = self.count
        return (*self._dev, synced)

    def arrays_pair(self) -> Tuple:
        """((data, lens, cumw) as-last-uploaded, (data, lens, cumw)
        current, synced) — the megachunk window's two slab views
        (fuzz/megachunk.py slab schedule).  The as-uploaded view is what
        a legacy prelaunched batch would have sampled (the lag-preserving
        first batch of a window); identical to the current view when no
        add landed since the last upload."""
        old = self._dev
        data, lens, cumw, synced = self.arrays()
        if old is None:
            old = (data, lens, cumw)
        return old, (data, lens, cumw), synced

    # -- checkpoint/resume (wtf_tpu/resume) --------------------------------
    def _note_undo(self, slot: int) -> None:
        """Record `slot`'s pre-image before its first mutation since the
        last upload (see _undo in __init__)."""
        if slot not in self._undo:
            self._undo[slot] = (self._data[slot].copy(),
                                int(self._len[slot]),
                                int(self._weight[slot]))

    def uploaded_state(self) -> dict:
        """The slab exactly as the device last saw it (undo applied over
        the current host slab) — what a prelaunched batch was generated
        from.  Rows are truncated at the upload-time slot count."""
        data = self._data.copy()
        lens = self._len.copy()
        weight = self._weight.copy()
        for slot, (d, ln, wt) in self._undo.items():
            data[slot] = d
            lens[slot] = ln
            weight[slot] = wt
        return {"count": self._uploaded_count, "data": data,
                "lens": lens, "weight": weight}

    def checkpoint_state(self) -> dict:
        """Both slab views a resumable campaign needs: `current` (the
        host-authoritative slab with digests — future evolution) and
        `uploaded` (what the in-flight prelaunched batch sampled)."""
        return {
            "current": {
                "count": self.count,
                "data": self._data.copy(),
                "lens": self._len.copy(),
                "weight": self._weight.copy(),
                "digests": [(slot, digest)
                            for slot, digest in sorted(
                                self._digest_of.items())],
            },
            "uploaded": self.uploaded_state(),
        }

    def restore(self, state: dict) -> None:
        """Install a checkpoint_state(): host slab = `current`, device
        arrays = `uploaded` (so the pending batch regenerates from the
        exact slab it originally sampled), with the undo log rebuilt as
        the diff between the two — a checkpoint taken before the next
        upload still reconstructs `uploaded` faithfully."""
        import jax.numpy as jnp

        cur, up = state["current"], state["uploaded"]
        shape = tuple(np.asarray(cur["data"]).shape)
        if shape != (self.slots, self.words):
            raise ValueError(
                f"devmut slab shape mismatch: checkpoint {shape} vs "
                f"configured ({self.slots}, {self.words}) — resume needs "
                "the same slot count and max_len")
        self._data = np.array(cur["data"], dtype=np.uint32)
        self._len = np.array(cur["lens"], dtype=np.int32)
        self._weight = np.array(cur["weight"], dtype=np.uint32)
        self.count = int(cur["count"])
        self._digest_of = {int(s): d for s, d in cur["digests"]}
        self._slot_of = {d: s for s, d in self._digest_of.items()}
        up_data = np.array(up["data"], dtype=np.uint32)
        up_len = np.array(up["lens"], dtype=np.int32)
        up_weight = np.array(up["weight"], dtype=np.uint32)
        self._uploaded_count = int(up["count"])
        cum = np.cumsum(up_weight, dtype=np.uint64).astype(np.uint32)
        self._dev = (jnp.asarray(up_data), jnp.asarray(up_len),
                     jnp.asarray(cum))
        self._dirty = False
        self._undo = {
            slot: (up_data[slot].copy(), int(up_len[slot]),
                   int(up_weight[slot]))
            for slot in range(self.slots)
            if (not np.array_equal(self._data[slot], up_data[slot])
                or self._len[slot] != up_len[slot]
                or self._weight[slot] != up_weight[slot])}

    def mark_stale(self) -> None:
        """Force the next arrays() call to re-upload the host slab.  The
        restoring mutator calls this AFTER regenerating its pending batch
        from the cached uploaded view — marking stale earlier would make
        the regeneration re-upload the current slab and sample the wrong
        corpus (see DevMangleMutator.restore_state)."""
        self._dirty = True
