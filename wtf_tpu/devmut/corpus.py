"""DeviceCorpus: the HBM-resident seed slab the devmangle engine reads.

Host-managed, device-consumed: the host keeps the authoritative numpy
slab (`[slots, max_len/4]` u32 words, per-slot byte lengths and favor
weights) and uploads it lazily — `arrays()` returns the cached device
triple and re-uploads only after a mutating `add`.  The engine never
reads testcase bytes back; the slab is write-mostly from the host's
perspective (one upload per harvest round that found something) and
read-every-batch from the device's.

Slot policy: fill empty slots first; when full, evict the lowest-weight
slot (first index on ties) — coverage-increasing finds enter with
`hostref.FAVOR_WEIGHT`, plain seeds with weight 1, so favored testcases
both survive eviction longer AND are drawn proportionally more often by
the engine's cumulative-weight pick.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from wtf_tpu.utils.hashing import hex_digest


class DeviceCorpus:
    def __init__(self, slots: int, max_len: int):
        if max_len < 4:
            raise ValueError("devmut max_len must be >= 4 bytes")
        self.slots = slots
        self.max_len = max_len
        self.words = (max_len + 3) // 4
        self._data = np.zeros((slots, self.words), dtype=np.uint32)
        self._len = np.zeros((slots,), dtype=np.int32)
        self._weight = np.zeros((slots,), dtype=np.uint32)
        self._slot_of: Dict[str, int] = {}   # digest -> slot
        self._digest_of: Dict[int, str] = {}
        self.count = 0
        self._dirty = True
        self._dev: Optional[Tuple] = None

    def __len__(self) -> int:
        return self.count

    def add(self, data: bytes, weight: int = 1) -> bool:
        """Insert a testcase (truncated to max_len, zero-padded into its
        slot).  Returns False for empties and content duplicates —
        a duplicate re-add BUMPS the existing slot to max(old, weight)
        so a favored re-find upgrades its seed."""
        data = data[:self.max_len]
        if not data:
            return False
        digest = hex_digest(data)
        slot = self._slot_of.get(digest)
        if slot is not None:
            if weight > self._weight[slot]:
                self._weight[slot] = weight
                self._dirty = True
            return False
        if self.count < self.slots:
            slot = self.count
            self.count += 1
        else:
            slot = int(np.argmin(self._weight))
            self._slot_of.pop(self._digest_of.pop(slot, ""), None)
        buf = np.zeros(self.words * 4, dtype=np.uint8)
        buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        self._data[slot] = buf.view(np.uint32)
        self._len[slot] = len(data)
        self._weight[slot] = max(weight, 1)
        self._slot_of[digest] = slot
        self._digest_of[slot] = digest
        self._dirty = True
        return True

    def cumulative_weights(self) -> np.ndarray:
        """Inclusive cumulative favor weights (the engine's pick table).
        u32 by contract: weights are small ints, so the total cannot
        approach 2^32 at any plausible slot count."""
        cum = np.cumsum(self._weight, dtype=np.uint64)
        assert cum[-1] < (1 << 32), "favor-weight total overflows u32"
        return cum.astype(np.uint32)

    def arrays(self) -> Tuple:
        """(data, lens, cumw) as device arrays; re-uploads only when a
        host-side add dirtied the slab.  Returns a 4th element `synced`
        telling the caller whether this call paid an upload."""
        synced = False
        if self._dirty or self._dev is None:
            import jax.numpy as jnp

            self._dev = (jnp.asarray(self._data), jnp.asarray(self._len),
                         jnp.asarray(self.cumulative_weights()))
            self._dirty = False
            synced = True
        return (*self._dev, synced)
