# Developer / CI targets.  `make verify` is the PR gate: tier-1 tests
# plus the graph-invariant linter (wtf_tpu/analysis) — both CPU-only.

PY ?= python

.PHONY: verify test lint lint-rebaseline slow

verify: test lint

# tier-1 (the ROADMAP.md command without the driver's log plumbing)
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# hot-path contract lint: fails (exit 1) on ANY finding.  JSON output so
# CI logs carry the kernel counts + finding provenance machine-readably.
lint:
	JAX_PLATFORMS=cpu $(PY) -m wtf_tpu.analysis --json

# re-pin analysis/budgets.json after a PR that legitimately changes the
# step ladder's kernel count — record the why in PERF.md (round 9)
lint-rebaseline:
	JAX_PLATFORMS=cpu $(PY) -m wtf_tpu.analysis --rebaseline

slow:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m slow \
		-p no:cacheprovider
