# Developer / CI targets.  `make verify` is the PR gate: tier-1 tests
# plus the graph-invariant linter (wtf_tpu/analysis) — both CPU-only.
# `make mesh-smoke` is the fast end-to-end check of the mesh campaign
# driver (wtf_tpu/meshrun) on a forced 8-device CPU mesh; run it when
# touching the sharded executors or the --mesh-devices path.

PY ?= python

.PHONY: verify test lint lint-rebaseline slow mesh-smoke chaos-smoke \
	triage-smoke tenancy-smoke fleet-smoke fused-smoke \
	fused-mega-smoke device-chaos-smoke decode-smoke obs-smoke \
	bench-guard

verify: test lint chaos-smoke triage-smoke tenancy-smoke fleet-smoke \
	fused-smoke fused-mega-smoke device-chaos-smoke decode-smoke \
	obs-smoke bench-guard

# tier-1 (the ROADMAP.md command without the driver's log plumbing)
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# hot-path contract lint: fails (exit 1) on ANY finding.  JSON output so
# CI logs carry the kernel counts + finding provenance machine-readably.
# All families run, including the dataflow contract trio
# (state/transfer/thread) and the contracts.json hygiene family; the
# jaxpr host-transfer census rides the budget family's traces for free,
# so --deep is only needed when filtering the budget family out.
lint:
	JAX_PLATFORMS=cpu $(PY) -m wtf_tpu.analysis --json --deep

# re-pin analysis/budgets.json AND analysis/contracts.json after a PR
# that legitimately changes the step ladder's kernel count or the
# contract surfaces — record the why in PERF.md (rounds 9 and 21).
# Both files ratchet: growth requires --allow-regression.
lint-rebaseline:
	JAX_PLATFORMS=cpu $(PY) -m wtf_tpu.analysis --rebaseline

slow:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m slow \
		-p no:cacheprovider

# fast forced-8-device mesh campaign smoke: the whole
# `campaign --mesh-devices N --mutator devmangle` path (shard_map
# executors, on-chip coverage merge, device mutation per shard) in one
# process with no hardware
mesh-smoke:
	JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m wtf_tpu campaign --name demo_tlv --mesh-devices 8 \
		--mutator devmangle --lanes 16 --runs 32 --limit 20000 --seed 7

# batched-triage smoke (wtf_tpu/testing/triage_smoke): tiny demo_tlv
# minimize + distill through the real CLI — the seeded crasher must
# shrink to the known-minimal reproducer of the SAME crash bucket, and
# the distilled minset must be a corpus subset with full coverage
triage-smoke:
	JAX_PLATFORMS=cpu $(PY) -m wtf_tpu.testing.triage_smoke

# multi-tenant smoke (wtf_tpu/testing/tenancy_smoke): a mixed
# demo_tlv+demo_kernel batch must be bit-identical per tenant to the
# same campaigns run alone, and the `wtf-tpu sched` preemption drill
# (checkpoint tenant A, backfill with B, resume A) must end
# bit-identical to an uninterrupted run
tenancy-smoke:
	JAX_PLATFORMS=cpu $(PY) -m wtf_tpu.testing.tenancy_smoke

# fleet-tier soak (wtf_tpu/testing/fleet_smoke): 64 simulated clients
# over the real WTF2/WTF3 wire with scripted frame drops + resets —
# zero lost testcases, aggregate coverage byte-identical to a serial
# replay, coverage wire bytes >=10x smaller than whole-bitmap
# exchange, store fsck clean
fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) -m wtf_tpu.testing.fleet_smoke

# fused-step + megachunk smoke (wtf_tpu/testing/fused_smoke): demo_tlv
# occupancy >= 0.95 through the widened Pallas kernel (in-kernel page
# walk + memory operands, interpret mode, small lanes) and a megachunk
# window campaign bit-identical to the batch-at-a-time device loop
fused-smoke:
	JAX_PLATFORMS=cpu $(PY) -m wtf_tpu.testing.fused_smoke

# fused-megachunk smoke (wtf_tpu/testing/fused_mega_smoke): the Pallas
# kernel as the window's step engine must be bit-identical to the
# XLA-ladder window at equal seeds, keep >=0.95 in-window occupancy,
# and pass the donation lint (every donated/overlay leaf aliased in the
# compiled window; jaxpr census on the megachunk_window_fused pin)
fused-mega-smoke:
	JAX_PLATFORMS=cpu $(PY) -m wtf_tpu.testing.fused_mega_smoke

# deterministic fault-tolerance soak (wtf_tpu/testing/chaos_smoke):
# seeded fault schedule over the real socket + checkpoint seams —
# >=1 reconnect, >=1 reclaim, >=1 torn-checkpoint .prev fallback, zero
# lost testcases, bit-identical kill/resume parity.  Exit 0 = all held.
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m wtf_tpu.testing.chaos_smoke

# self-healing device runtime soak (wtf_tpu/testing/device_chaos_smoke):
# scripted device hang/error/poison against the supervised dispatch
# seams — >=1 watchdog fire, >=1 ladder degradation + re-promotion,
# >=1 quarantined lane, and every recovery bit-identical to the
# fault-free run (coverage, edge bytes, corpus digests, crash buckets)
device-chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m wtf_tpu.testing.device_chaos_smoke

# zero-host steady-state smoke (wtf_tpu/testing/decode_smoke): a
# cold-cache --device-decode demo_tlv campaign must finish its
# megachunk windows with ZERO host decode services, a clean
# device-vs-host cross-check, >=1 adopted pipelined-harvest prelaunch,
# and stay bit-identical to the host-serviced reference
decode-smoke:
	JAX_PLATFORMS=cpu $(PY) -m wtf_tpu.testing.decode_smoke

# observability smoke (wtf_tpu/testing/obs_smoke): a real master + 4
# WTF3 sim clients under scripted faults and re-sent TAG_TELEM frames —
# the fleet aggregate must be byte-equal to the serial sum of node
# snapshots — plus one campaign run producing a schema-valid Chrome
# trace (>=1 fenced device span, >=1 megachunk window) and a rendering
# `wtf-tpu status` surface
obs-smoke:
	JAX_PLATFORMS=cpu $(PY) -m wtf_tpu.testing.obs_smoke

# perf-regression guard self-test (tools/bench_guard.py): extraction
# over the checked-in BENCH_r06/r07 rounds must compare clean while a
# synthetic 2x regression must be flagged.  To gate a fresh run:
#   python bench.py > /tmp/bench.json && \
#   python tools/bench_guard.py /tmp/bench.json
bench-guard:
	$(PY) tools/bench_guard.py --self-test
