"""Device-executor differentials for the x87 subset (OPC_X87).

Round 4 pinned the oracle's f64-value x87 model to the live host CPU
(tests/test_x87.py); this file closes the loop for the DEVICE step the
same way test_step_fp.py does for SSE: the hardware-pinned snippet grids
re-run through `assert_matches_oracle`, which now compares the full
fpst/fpsw/fptw/fpcw state as well.  Transitively:
hardware == oracle == device.

With this green, x87-touching lanes leave the per-instruction oracle
round trip — only the FXSAVE-class state movers still divert.
"""

import struct

import pytest

from emurunner import DATA_BASE
from test_step import assert_matches_oracle, make_runner
from test_x87 import _EPILOGUE, _PRELUDE, F64


def _dev(snippet, regs):
    assert_matches_oracle(snippet + "\nhlt", regs=regs)


ARITH_BODIES = [
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfaddp st(1), st",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfsubp st(1), st",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfsubrp st(1), st",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfmulp st(1), st",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfdivp st(1), st",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfdivrp st(1), st",
    "fld qword ptr [rsp]\nfadd qword ptr [rsp+8]",
    "fld qword ptr [rsp]\nfmul qword ptr [rsp+8]",
    "fld qword ptr [rsp]\nfdiv qword ptr [rsp+8]",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfadd st, st(1)\n"
    "fstp st(1)",
    "fld qword ptr [rsp]\nfld qword ptr [rsp+8]\nfxch\nfsubp st(1), st",
    "fld qword ptr [rsp]\nfchs",
    "fld qword ptr [rsp]\nfabs",
    "fld1\nfld qword ptr [rsp]\nfaddp st(1), st",
    "fldz\nfld qword ptr [rsp]\nfsubp st(1), st",
]


@pytest.mark.parametrize("body", ARITH_BODIES)
@pytest.mark.parametrize("a_name,b_name", [
    ("one5", "two25"), ("pi", "e"), ("big", "tiny"),
    ("pinf", "ninf"), ("qnan", "one5"), ("denorm", "denorm"),
])
def test_x87_arith_device_vs_oracle(body, a_name, b_name):
    snippet = (_PRELUDE + body
               + "\nfstp qword ptr [rsp+16]\nmov rax, [rsp+16]"
               + _EPILOGUE)
    _dev(snippet, {"rax": F64[a_name], "rcx": F64[b_name]})


@pytest.mark.parametrize("ival", [0, 1, -1 & (1 << 64) - 1, 123456789,
                                  0xFFFFFFFF00000000, 1 << 52])
@pytest.mark.parametrize("width", ["word", "dword", "qword"])
def test_fild_fistp_device_vs_oracle(ival, width):
    snippet = (_PRELUDE
               + f"fild qword ptr [rsp]\nfistp {width} ptr [rsp+16]\n"
               + "mov rax, [rsp+16]" + _EPILOGUE)
    _dev(snippet, {"rax": ival})


@pytest.mark.parametrize("rc", [0, 1, 2, 3])
def test_fist_rounding_modes_device_vs_oracle(rc):
    """fist honors fpcw.RC; fisttp always chops."""
    cw = 0x27F | (rc << 10)
    snippet = f"""
        sub rsp, 40
        mov word ptr [rsp+34], {cw}
        fldcw [rsp+34]
        mov [rsp], rax
        fld qword ptr [rsp]
        fist dword ptr [rsp+16]
        fisttp qword ptr [rsp+24]
        mov rax, [rsp+16]
        mov rcx, [rsp+24]
        add rsp, 40
    """
    _dev(snippet, {"rax": 0xC002_4CCC_CCCC_CCCD})   # -2.2875


@pytest.mark.parametrize("a_name,b_name", [
    ("one5", "two25"), ("two25", "one5"), ("one5", "one5"),
    ("qnan", "one5"), ("pinf", "big"),
])
def test_fcomi_fnstsw_device_vs_oracle(a_name, b_name):
    snippet = (_PRELUDE + """
    fld qword ptr [rsp+8]
    fld qword ptr [rsp]
    fcomip st, st(1)
    pushfq
    pop r8
    fstp st(0)
    fld qword ptr [rsp+8]
    fld qword ptr [rsp]
    fucompp
    fnstsw ax
    movzx rdx, ax
""" + _EPILOGUE)
    _dev(snippet, {"rax": F64[a_name], "rcx": F64[b_name]})


def test_x87_control_ops_device_vs_oracle():
    snippet = """
        sub rsp, 48
        fninit
        fnstcw [rsp]
        fld1
        fldz
        ffree st(1)
        fnclex
        fnstsw [rsp+8]
        emms
        fnstcw [rsp+16]
        stmxcsr [rsp+24]
        ldmxcsr [rsp+24]
        mov rax, [rsp]
        mov rcx, [rsp+8]
        mov rdx, [rsp+16]
        add rsp, 48
    """
    _dev(snippet, {})


def test_fst_m32_and_fld_m32_device_vs_oracle():
    data = struct.pack("<f", 1.75) + struct.pack("<f", -0.375)
    assert_matches_oracle(f"""
        mov rbx, {DATA_BASE}
        fld dword ptr [rbx]
        fadd dword ptr [rbx+4]
        fst dword ptr [rbx+8]
        fstp qword ptr [rbx+16]
        hlt""", data={DATA_BASE: data.ljust(0x1000, b"\x00")})


@pytest.mark.parametrize("op", ["fsubrp st(1), st", "fdivrp st(1), st",
                                "fsubp st(1), st", "fdivp st(1), st"])
def test_x87_two_nan_payload_routing(op):
    """Reversed arith (fsubr/fdivr: b OP a) propagates the FIRST operand
    of the OPERATION's NaN — st's payload for the reversed-p forms — so
    two distinct NaNs must route exactly like the oracle (review fix)."""
    snippet = (_PRELUDE
               + f"fld qword ptr [rsp]\nfld qword ptr [rsp+8]\n{op}\n"
               + "fstp qword ptr [rsp+16]\nmov rax, [rsp+16]" + _EPILOGUE)
    _dev(snippet, {"rax": 0x7FF8000000000001, "rcx": 0x7FF8000000000002})


def test_x87_m32_denormal_operand_diverts():
    """An m32 arith operand in the f32 denormal range must divert to the
    oracle (DAZ in the widening would flush it before the f64-level
    check could see it — review fix).  On the CPU backend results match
    either way; the assertion is that the divert HAPPENED."""
    data = struct.pack("<I", 0x00000001) + struct.pack("<d", 1.0)
    runner = make_runner(f"""
        mov rbx, {DATA_BASE}
        fld qword ptr [rbx+4]
        fmul dword ptr [rbx]
        fstp qword ptr [rbx+16]
        hlt""", data={DATA_BASE: data.ljust(0x1000, b"\x00")}, n_lanes=2)
    runner.run()
    assert runner.stats["fallbacks"] >= 2  # both lanes diverted on fmul


def test_x87_loop_no_fallback():
    """An x87 compute loop must run with ZERO oracle round trips now —
    the round-4 situation (every x87 insn a per-lane host single-step)
    is the regression this guards."""
    data = struct.pack("<dd", 100.0, 1.0625).ljust(0x1000, b"\x00")
    runner = make_runner(f"""
        mov rbx, {DATA_BASE}
        fld qword ptr [rbx]
        mov ecx, 40
    top:
        fmul qword ptr [rbx+8]
        fld1
        faddp st(1), st
        dec ecx
        jnz top
        fstp qword ptr [rbx+16]
        hlt""", data={DATA_BASE: data}, n_lanes=4)
    status = runner.run()
    from wtf_tpu.core.results import StatusCode

    assert all(StatusCode(int(s)) == StatusCode.CRASH for s in status)
    assert runner.stats["fallbacks"] == 0, runner.stats
