"""Oracle-CPU semantics tests.

Two layers (mirroring the reference's cross-backend differential methodology,
SURVEY.md §4.3):
  1. hardware-differential: register-only snippets run on the REAL host CPU
     (tests/nativeharness.py) and on the Python oracle; full GPR+flag compare.
  2. hand-checked: memory/stack/control-flow/SSE snippets with explicit
     expected values, run on the oracle inside a synthetic snapshot.
"""

import pytest

from tests.emurunner import CODE_BASE, DATA_BASE, STACK_TOP, run_emu
from tests.nativeharness import run_native
from wtf_tpu.core.cpustate import GPR_NAMES
from wtf_tpu.core.gxa import PAGE_SIZE
from wtf_tpu.cpu.decoder import decode
from wtf_tpu.cpu import uops as U
from tests.asmhelper import assemble

# rflags bits we compare (TF/IF/reserved excluded)
FLAGS_MASK = 0x8D5  # CF|PF|AF|ZF|SF|OF
NO_AF = 0x8C5      # for ops where AF is architecturally undefined


# ---------------------------------------------------------------------------
# 1. hardware-differential tests
# ---------------------------------------------------------------------------

# (name, snippet, flags_mask) — snippets only touch GPRs/flags, balanced stack.
HW_CASES = [
    ("add64", "add rax, rbx", FLAGS_MASK),
    ("add_neg", "mov rax, -5\nadd rax, 3", FLAGS_MASK),
    ("adc", "stc\nadc rax, rbx", FLAGS_MASK),
    ("sub", "sub rcx, rdx", FLAGS_MASK),
    ("sbb", "stc\nsbb rcx, rdx", FLAGS_MASK),
    ("cmp", "cmp rsi, rdi", FLAGS_MASK),
    ("and", "and rax, rbx", NO_AF),
    ("or", "or rax, r8", NO_AF),
    ("xor", "xor rdx, r9", NO_AF),
    ("test", "test r10, r11", NO_AF),
    ("add32", "add eax, ebx", FLAGS_MASK),
    ("add16", "add ax, bx", FLAGS_MASK),
    ("add8", "add al, bl", FLAGS_MASK),
    ("add8h", "add ah, ch", FLAGS_MASK),
    ("inc", "inc rax", FLAGS_MASK),
    ("dec", "dec rbx", FLAGS_MASK),
    ("inc_preserve_cf", "stc\ninc rax", FLAGS_MASK),
    ("neg", "neg rcx", FLAGS_MASK),
    ("neg_zero", "mov rcx, 0\nneg rcx", FLAGS_MASK),
    ("not", "not rdx", FLAGS_MASK),
    ("imm8_sx", "add rax, -16", FLAGS_MASK),
    ("imm32", "add rax, 0x12345678", FLAGS_MASK),
    ("shl", "shl rax, 5", 0xC5),
    ("shl1", "shl rax, 1", NO_AF),
    ("shl_cl", "mov cl, 3\nshl rbx, cl", 0xC5),
    ("shl_zero_count", "mov cl, 0\nshl rbx, cl", NO_AF),
    ("shr", "shr rax, 9", 0xC5),
    ("sar", "sar rax, 4", 0xC5),
    ("sar32", "sar eax, 31", 0xC5),
    ("rol", "rol rax, 7", 0x1),
    ("ror", "ror rbx, 3", 0x1),
    ("rol1", "rol rax, 1", 0x801),
    ("rcl", "stc\nrcl rax, 4", 0x1),
    ("rcr", "rcr rax, 2", 0x1),
    ("shld", "shld rax, rbx, 11", 0xC5),
    ("shrd", "shrd rax, rbx, 7", 0xC5),
    ("mul", "mul rbx", 0x801),          # only CF/OF defined
    ("mul32", "mul ebx", 0x801),
    ("mul8", "mul bl", 0x801),
    ("imul1op", "imul rbx", 0x801),
    ("imul2op", "imul rax, rbx", 0x801),
    ("imul3op", "imul rax, rbx, 37", 0x801),
    ("imul3op8", "imul rax, rbx, -3", 0x801),
    ("div", "mov rdx, 0\nmov rbx, 7\ndiv rbx", 0),
    ("div8", "mov ax, 1234\nmov bl, 7\ndiv bl", 0),
    ("idiv", "mov rax, -100\ncqo\nmov rbx, 7\nidiv rbx", 0),
    ("cbw", "cbw", 0x8D5),
    ("cwde", "cwde", FLAGS_MASK),
    ("cdqe", "cdqe", FLAGS_MASK),
    ("cqo", "cqo", FLAGS_MASK),
    ("cdq", "cdq", FLAGS_MASK),
    ("movzx8", "movzx rax, bl", FLAGS_MASK),
    ("movzx16", "movzx eax, cx", FLAGS_MASK),
    ("movsx8", "movsx rax, bl", FLAGS_MASK),
    ("movsx16", "movsx rax, cx", FLAGS_MASK),
    ("movsxd", "movsxd rax, ebx", FLAGS_MASK),
    ("mov_r8_high", "mov ah, bl", FLAGS_MASK),
    ("mov32_zeroext", "mov eax, ebx", FLAGS_MASK),
    ("xchg", "xchg rax, rbx", FLAGS_MASK),
    ("xchg8h", "xchg ah, dl", FLAGS_MASK),
    ("lea", "lea rax, [rbx + rcx*4 + 0x30]", FLAGS_MASK),
    ("lea32", "lea eax, [rbx + rdi*2 - 5]", FLAGS_MASK),
    # 67h address-size override: EA truncated to 32 bits (lea exposes the
    # masked EA without a memory access — hardware-differential safe)
    ("lea_a32", "lea rax, [ebx + ecx*4 + 0x30]", FLAGS_MASK),
    ("lea_a32_neg", "lea rax, [edi - 5]", FLAGS_MASK),
    ("setcc", "cmp rax, rbx\nsete cl\nsetl dl\nsetb r8b", FLAGS_MASK),
    ("cmov_taken", "cmp rax, rax\ncmove rbx, rcx", FLAGS_MASK),
    ("cmov_nottaken", "cmp rax, rax\ncmovne rbx, rcx", FLAGS_MASK),
    ("cmov32_zeroext", "cmp rax, rax\ncmovne ebx, ecx", FLAGS_MASK),
    ("bt_reg", "bt rax, rbx", 0x1),
    ("bts_reg", "bts rax, rbx", 0x1),
    ("btr_reg", "btr rax, 3", 0x1),
    ("btc_reg", "btc rax, 63", 0x1),
    ("bsf", "bsf rax, rbx", 0x40),      # ZF only
    ("bsr", "bsr rax, rbx", 0x40),
    ("bsf_zero", "xor rbx, rbx\nbsf rax, rbx", 0x40),
    ("popcnt", "popcnt rax, rbx", 0x8D5),
    ("tzcnt", "tzcnt rax, rbx", 0x41),
    ("lzcnt", "lzcnt rax, rbx", 0x41),
    ("bswap32", "bswap eax", FLAGS_MASK),
    ("bswap64", "bswap rax", FLAGS_MASK),
    ("cmpxchg_eq", "mov rax, rbx\ncmpxchg rbx, rcx", FLAGS_MASK),
    ("cmpxchg_ne", "mov rax, 1\nmov rbx, 2\ncmpxchg rbx, rcx", FLAGS_MASK),
    ("xadd", "xadd rax, rbx", FLAGS_MASK),
    ("push_pop", "push rax\npush rbx\npop rcx\npop rdx", FLAGS_MASK),
    ("pushf_popf", "stc\npushfq\npop rax\nand rax, 1", NO_AF),
    ("lahf_sahf", "stc\nlahf\nmov cl, ah\nsahf", FLAGS_MASK),
    ("clc_stc_cmc", "stc\ncmc", FLAGS_MASK),
    ("cld_std", "std\ncld", FLAGS_MASK),
    ("flags_chain", "add rax, rbx\nadc rcx, rdx\nsbb rsi, rdi", FLAGS_MASK),
    # flags depend on the (differing) rsp value — compare registers only
    ("stack_red", "sub rsp, 32\nmov [rsp], rax\nmov rbx, [rsp]\nadd rsp, 32", 0),
    # BMI1/BMI2 (VEX-encoded; masks follow the SDM's defined-flags sets)
    ("andn", "andn rax, rbx, rcx", 0x8C1),
    ("andn32", "andn eax, ebx, ecx", 0x8C1),
    ("bzhi", "bzhi rax, rbx, rcx", 0x8C1),
    ("bzhi_over", "mov rcx, 200\nbzhi rax, rbx, rcx", 0x8C1),
    ("bextr", "bextr rax, rbx, rcx", 0x841),
    ("shlx", "shlx rax, rbx, rcx", 0),
    ("shrx", "shrx rax, rbx, rcx", 0),
    ("sarx", "sarx rax, rbx, rcx", 0),
    ("pdep", "pdep rax, rbx, rcx", 0),
    ("pext", "pext rax, rbx, rcx", 0),
    ("rorx", "rorx rax, rbx, 13", 0),
    ("rorx32", "rorx eax, ebx, 5", 0),
    ("blsr", "blsr rax, rbx", 0x8C1),
    ("blsr_zero", "xor rbx, rbx\nblsr rax, rbx", 0x8C1),
    ("blsmsk", "blsmsk rax, rbx", 0x881),
    ("blsi", "blsi rax, rbx", 0x8C1),
    ("blsi_zero", "xor rbx, rbx\nblsi rax, rbx", 0x8C1),
    ("vzeroupper", "vzeroupper", FLAGS_MASK),  # no-op in this model
]

_INIT_REGS = [
    0x0123456789ABCDEF, 0x0000000000000001, 0xFFFFFFFFFFFFFFFF,
    0x8000000000000000, 0, 0x00007FFF_00001000, 0x5555555555555555,
    0xAAAAAAAAAAAAAAAA, 0x7FFFFFFFFFFFFFFF, 0x0000000080000000,
    0x00000000FFFFFFFF, 0x123, 0xCAFEBABE_DEADBEEF, 0x31, 0x40, 0xFF,
]

_ALT_REGS = [
    0xFFFFFFFF80000000, 0x3F, 0x7FFFFFFF, 0xFFFF, 0, 0x10000, 0x2,
    0xFFFFFFFF00000000, 0x1000000000000000, 0x0F0F0F0F0F0F0F0F,
    0x8000000000000001, 0x7F, 0x80, 0xFFFE, 0x1F, 0x8642,
]


@pytest.mark.parametrize("name,snippet,fmask",
                         [(c[0], c[1], c[2]) for c in HW_CASES])
@pytest.mark.parametrize("initset", ["a", "b"])
def test_hw_differential(name, snippet, fmask, initset):
    init = list(_INIT_REGS if initset == "a" else _ALT_REGS)
    hw_regs, hw_flags = run_native(snippet, init)

    regs = {n: v for n, v in zip(GPR_NAMES, init)}
    regs.pop("rsp")
    cpu = run_emu(snippet + "\nhlt", regs=regs)

    for i, gname in enumerate(GPR_NAMES):
        if gname == "rsp":
            continue
        assert cpu.gpr[i] == hw_regs[i], (
            f"{name}: {gname} emu={cpu.gpr[i]:#x} hw={hw_regs[i]:#x}")
    assert cpu.rflags & fmask == hw_flags & fmask, (
        f"{name}: flags emu={cpu.rflags:#x} hw={hw_flags:#x} mask={fmask:#x}")


# ---------------------------------------------------------------------------
# 2. memory / control flow / strings (hand-checked on the oracle)
# ---------------------------------------------------------------------------

def test_mem_load_store():
    cpu = run_emu(
        f"""
        mov rbx, {DATA_BASE}
        mov r9, 0x1122334455667788
        mov [rbx], r9
        mov eax, [rbx]
        mov cx, [rbx+6]
        mov dl, [rbx+7]
        mov r8, [rbx]
        hlt
        """,
        data={DATA_BASE: b"\x00" * 64},
    )
    assert cpu.gpr[0] == 0x55667788
    assert cpu.gpr[1] & 0xFFFF == 0x1122
    assert cpu.gpr[2] & 0xFF == 0x11
    assert cpu.gpr[8] == 0x1122334455667788


def test_mem_page_crossing():
    base = DATA_BASE + PAGE_SIZE - 4
    cpu = run_emu(
        f"""
        mov rbx, {base}
        mov rax, 0xA1B2C3D4E5F60718
        mov [rbx], rax
        mov rcx, [rbx]
        hlt
        """,
        data={DATA_BASE: b"\x00" * (2 * PAGE_SIZE)},
    )
    assert cpu.gpr[1] == 0xA1B2C3D4E5F60718


def test_rip_relative():
    cpu = run_emu(
        """
        lea rax, [rip + tag]
        mov rbx, [rip + tag]
        hlt
        tag: .quad 0xDEADBEEFCAFEF00D
        """,
    )
    assert cpu.gpr[3] == 0xDEADBEEFCAFEF00D
    assert cpu.gpr[0] > CODE_BASE


def test_call_ret_stack():
    cpu = run_emu(
        """
        call f
        mov rbx, 7
        hlt
        f:
        mov rax, 42
        ret
        """,
    )
    assert cpu.gpr[0] == 42
    assert cpu.gpr[3] == 7
    assert cpu.gpr[4] == STACK_TOP - 0x100  # balanced


def test_loop_fib():
    cpu = run_emu(
        """
        mov rax, 0
        mov rbx, 1
        mov rcx, 20
        l:
        mov rdx, rax
        add rdx, rbx
        mov rax, rbx
        mov rbx, rdx
        dec rcx
        jnz l
        hlt
        """,
    )
    fib = [0, 1]
    for _ in range(20):
        fib.append(fib[-1] + fib[-2])
    assert cpu.gpr[0] == fib[20]


def test_rep_movsb():
    src = DATA_BASE
    dst = DATA_BASE + 0x100
    payload = bytes(range(64))
    cpu = run_emu(
        f"""
        mov rsi, {src}
        mov rdi, {dst}
        mov rcx, 64
        rep movsb
        hlt
        """,
        data={DATA_BASE: payload + b"\x00" * 0x200},
    )
    assert cpu.virt_read(dst, 64) == payload
    assert cpu.gpr[1] == 0
    assert cpu.gpr[6] == src + 64
    assert cpu.gpr[7] == dst + 64


def test_rep_stosq_and_scasb():
    cpu = run_emu(
        f"""
        mov rdi, {DATA_BASE}
        mov rax, 0x4141414141414141
        mov rcx, 8
        rep stosq
        mov rdi, {DATA_BASE}
        mov al, 0x42
        mov byte ptr [rdi+17], 0x42
        mov rcx, 64
        repne scasb
        hlt
        """,
        data={DATA_BASE: b"\x00" * 0x100},
    )
    assert cpu.virt_read(DATA_BASE, 8) == b"\x41" * 8
    # scasb stops after matching index 17 -> rdi = base+18
    assert cpu.gpr[7] == DATA_BASE + 18
    assert cpu.gpr[1] == 64 - 18


def test_repe_cmpsb():
    a = DATA_BASE
    b = DATA_BASE + 0x80
    blob = b"identical-prefix-X" + b"\x00" * 32
    blob2 = b"identical-prefix-Y" + b"\x00" * 32
    cpu = run_emu(
        f"""
        mov rsi, {a}
        mov rdi, {b}
        mov rcx, 32
        repe cmpsb
        hlt
        """,
        data={a: blob, b: blob2},
    )
    # mismatch at offset 17 ('X' vs 'Y') -> stop after 18 iterations
    assert cpu.gpr[6] == a + 18
    assert not cpu.get_flag(0x40)  # ZF clear


def test_movs_df_backwards():
    cpu = run_emu(
        f"""
        std
        mov rsi, {DATA_BASE + 7}
        mov rdi, {DATA_BASE + 0x47}
        mov rcx, 8
        rep movsb
        cld
        hlt
        """,
        data={DATA_BASE: bytes(range(16)) + b"\x00" * 0x100},
    )
    assert cpu.virt_read(DATA_BASE + 0x40, 8) == bytes(range(8))


def test_jcc_spectrum():
    cpu = run_emu(
        """
        xor rax, rax
        mov rbx, 5
        cmp rbx, 5
        jne bad
        je ok1
        jmp bad
        ok1:
        cmp rbx, 9
        ja bad
        jb ok2
        jmp bad
        ok2:
        cmp rbx, -1
        jl bad
        jg ok3
        jmp bad
        ok3:
        mov rax, 1
        hlt
        bad:
        mov rax, 0xBAD
        hlt
        """,
    )
    assert cpu.gpr[0] == 1


def test_jrcxz():
    cpu = run_emu(
        """
        xor rcx, rcx
        jrcxz ok
        mov rax, 0xBAD
        hlt
        ok:
        mov rax, 0x600D
        hlt
        """,
    )
    assert cpu.gpr[0] == 0x600D


def test_push_imm_and_leave():
    cpu = run_emu(
        """
        push rbp
        mov rbp, rsp
        sub rsp, 0x20
        push 0x1234
        pop rax
        leave
        hlt
        """,
    )
    assert cpu.gpr[0] == 0x1234
    assert cpu.gpr[4] == STACK_TOP - 0x100


def test_bt_mem_bitstring():
    cpu = run_emu(
        f"""
        mov rbx, {DATA_BASE}
        mov rax, 77        # bit 77 = byte 9 bit 5
        bts [rbx], rax
        mov rcx, 200
        bts [rbx], rcx
        bt  [rbx], rax
        setc dl
        hlt
        """,
        data={DATA_BASE: b"\x00" * 64},
    )
    mem = cpu.virt_read(DATA_BASE, 32)
    assert mem[9] & (1 << 5)
    assert mem[25] & (1 << 0)
    assert cpu.gpr[2] & 0xFF == 1


def test_xchg_mem():
    cpu = run_emu(
        f"""
        mov rbx, {DATA_BASE}
        mov qword ptr [rbx], 0x1111
        mov rax, 0x2222
        xchg [rbx], rax
        hlt
        """,
        data={DATA_BASE: b"\x00" * 32},
    )
    assert cpu.gpr[0] == 0x1111
    assert cpu.read_u(DATA_BASE, 8) == 0x2222


def test_sse_roundtrip_and_pxor():
    cpu = run_emu(
        f"""
        mov rbx, {DATA_BASE}
        movdqu xmm0, [rbx]
        movdqu xmm1, [rbx+16]
        pxor xmm0, xmm1
        movdqu [rbx+32], xmm0
        pcmpeqb xmm1, xmm1
        pmovmskb eax, xmm1
        hlt
        """,
        data={DATA_BASE: bytes(range(32)) + b"\x00" * 32},
    )
    expect = bytes(a ^ b for a, b in zip(range(16), range(16, 32)))
    assert cpu.virt_read(DATA_BASE + 32, 16) == expect
    assert cpu.gpr[0] == 0xFFFF


def test_sse_movq_movd():
    cpu = run_emu(
        """
        mov rax, 0x1122334455667788
        movq xmm3, rax
        movq rbx, xmm3
        movd ecx, xmm3
        hlt
        """,
    )
    assert cpu.gpr[3] == 0x1122334455667788
    assert cpu.gpr[1] == 0x55667788


def test_syscall_transition():
    cpu = run_emu(
        """
        mov r10, 0x99
        syscall
        hlt
        .org 0x40
        mov rax, 0x5CA11
        hlt
        """,
        regs={"lstar": CODE_BASE + 0x40, "sfmask": 0x700},
    )
    assert cpu.gpr[0] == 0x5CA11        # landed at lstar
    assert cpu.gpr[1] == CODE_BASE + len(assemble("mov r10, 0x99\nsyscall"))
    assert cpu.gpr[11] & 0x2            # r11 = pre-syscall rflags


def test_rdrand_deterministic():
    cpu1 = run_emu("rdrand rax\nrdrand rbx\nhlt")
    cpu2 = run_emu("rdrand rax\nrdrand rbx\nhlt")
    assert cpu1.gpr[0] == cpu2.gpr[0]
    assert cpu1.gpr[3] == cpu2.gpr[3]
    assert cpu1.gpr[0] != cpu1.gpr[3]


def test_cpuid_identity():
    cpu = run_emu("xor rax, rax\nxor rcx, rcx\ncpuid\nhlt")
    assert cpu.gpr[0] == 0xD
    assert cpu.gpr[3] == 0x756E6547  # "Genu"


def test_decoder_lengths_cover_stream():
    """Decode the whole assembled stream instruction-by-instruction: lengths
    must chain exactly and nothing may decode as INVALID."""
    src = "\n".join(s for _, s, _ in HW_CASES) + "\nhlt\n"
    code = assemble(src)
    pos = 0
    while pos < len(code):
        uop = decode(code[pos : pos + 15], pos)
        assert uop.opc != U.OPC_INVALID, (
            f"invalid decode at +{pos:#x}: {code[pos:pos+15].hex()}")
        assert uop.length > 0
        pos += uop.length
    assert pos == len(code)


IRETQ_ASM = """
    lea r8, [rip + after]
    mov r9, rsp
    push 0x23
    push r9
    pushfq
    pop r11
    or r11, 0x400
    push r11
    push 0x33
    push r8
    iretq
    ud2
after:
    mov rax, 0x17e7
    hlt
"""


def test_iretq_returns_through_frame():
    cpu = run_emu(IRETQ_ASM)
    assert cpu.gpr[0] == 0x17e7          # landed at `after`
    assert cpu.rflags & 0x400            # DF from the popped frame
    # rsp restored from the frame (r9 captured it before the pushes)
    assert cpu.gpr[4] == cpu.gpr[9]


def test_decoder_total_on_random_bytes():
    """The decoder is total: any byte window decodes to SOME uop (invalid
    encodings map to OPC_INVALID, never an exception) with a sane length —
    a fuzzer's decoder sees every byte sequence the mutator can produce."""
    import random as _random

    from wtf_tpu.cpu.decoder import decode

    rng = _random.Random(0xDEC0DE)
    for _ in range(3000):
        window = bytes(rng.randrange(256) for _ in range(15))
        uop = decode(window, 0x1000)
        assert 1 <= uop.length <= 15


def test_vex_after_prefix_is_invalid():
    """A legacy or REX prefix before VEX #UDs on hardware; the decoder
    must reject the sequence rather than decode the VEX form."""
    from wtf_tpu.cpu.uops import OPC_INVALID, OPC_PEXT

    shlx = assemble("shlx rax, rbx, rcx")
    assert decode(shlx + b"\x90" * 8).opc == OPC_PEXT
    for prefix in (b"\x66", b"\xF2", b"\xF3", b"\x40", b"\x48"):
        uop = decode(prefix + shlx + b"\x90" * 8)
        assert uop.opc == OPC_INVALID, prefix.hex()
    # segment overrides are LEGAL before VEX (they scope the mem operand)
    gs_andn = b"\x65" + assemble("andn rax, rbx, [rcx]")
    uop = decode(gs_andn + b"\x90" * 8)
    assert uop.opc == OPC_PEXT and uop.seg == U.SEG_GS
    # rorx requires encoded VEX.vvvv == 1111b; hardware #UDs otherwise
    assert decode(bytes([0xC4, 0xE3, 0x43, 0xF0, 0xC3, 0x0D]) +
                  b"\x90" * 8).opc == OPC_INVALID
    # vzeroupper is strict too: pp != 0 or vvvv != 1111b #UDs
    from wtf_tpu.cpu.uops import OPC_VZEROALL

    vz = decode(bytes([0xC5, 0xF8, 0x77]) + b"\x90" * 8)
    assert (vz.opc, vz.sub) == (OPC_VZEROALL, 1)
    assert decode(bytes([0xC5, 0xF9, 0x77]) + b"\x90" * 8).opc == OPC_INVALID
    assert decode(bytes([0xC5, 0xB8, 0x77]) + b"\x90" * 8).opc == OPC_INVALID


def test_vzeroall_zeroes_xmm_state():
    """vzeroall (VEX.256 0F 77) zeroes the FULL vector registers — XMM
    state included; vzeroupper (VEX.128) zeroes only the upper YMM halves,
    leaving XMM intact.  ADVICE r3: previously decoded INVALID and
    produced a spurious invalid-opcode crash."""
    from wtf_tpu.cpu.decoder import decode
    from wtf_tpu.cpu.uops import OPC_VZEROALL

    assert decode(bytes([0xC5, 0xFC, 0x77]) + b"\x90" * 8).opc == OPC_VZEROALL
    assert decode(bytes([0xC5, 0xF8, 0x77]) + b"\x90" * 8).sub == 1
    cpu = run_emu("""
        mov rax, 0x1122334455667788
        movq xmm3, rax
        movq xmm9, rax
        vzeroupper
        movq rbx, xmm3
        vzeroall
        movq rcx, xmm9
        hlt
    """)
    assert cpu.gpr[3] == 0x1122334455667788  # vzeroupper kept xmm3
    assert cpu.gpr[1] == 0                   # vzeroall cleared xmm9
    assert all(cpu.xmm[i] == [0, 0] for i in range(16))


def test_a32_memory_access_and_riprel():
    """67h memory forms: the EA truncates to 32 bits before translation —
    a base register with garbage upper bits still hits the low mapping;
    eip-relative truncates the same way (oracle-level: rip is guest-chosen
    so a hardware differential can't pin it)."""
    low = 0x2000_0000  # must fit in 32 bits for the 67h-masked access
    cpu = run_emu(
        f"""
        mov rbx, {0xDEAD_0000_0000 + low}   # garbage upper bits
        mov rax, [ebx]                      # 67h: EA masks back to `low`
        lea rcx, [eip]
        hlt
        """,
        data={low: (0x1122334455667788).to_bytes(8, "little")})
    assert cpu.gpr[0] == 0x1122334455667788
    # lea rcx,[eip]: rip after the lea (10-byte movabs + 4-byte 67h load
    # + 8-byte 67h rip-relative lea), truncated to 32 bits
    assert cpu.gpr[1] == (CODE_BASE + 22) & 0xFFFFFFFF


def test_retf_same_and_inter_privilege():
    """Far returns (VERDICT r3 'far forms'): retf pops rip+cs; with a
    CPL change it also pops SS:RSP; retf imm16 adjusts past callee args."""
    cpu = run_emu(
        f"""
        lea rax, [rip + same_ret]
        push 0x33                 # cs (same CPL as the synthetic guest)
        push rax
        retf
    same_ret:
        mov rbx, 1
        lea rax, [rip + inter_ret]
        push 0x2B                 # new ss
        push 0x7FFDF000           # new rsp
        push 0x10                 # cs with DIFFERENT rpl -> inter-priv
        push rax
        retf
    inter_ret:
        mov rcx, rsp              # observe the switched stack
        hlt
        """)
    assert cpu.gpr[3] == 1          # same-CPL path taken
    assert cpu.cs_sel == 0x10
    assert cpu.ss_sel == 0x2B
    assert cpu.gpr[1] == 0x7FFDF000  # rsp came from the far frame


def test_retf_imm16_inter_privilege_releases_new_stack():
    """SDM RET-far: with a CPL change, imm16 releases parameter bytes from
    BOTH stacks — the old one (before popping SS:RSP) and the new one
    (after).  The restored rsp must be new_rsp + imm (ADVICE r4)."""
    from tests.emurunner import STACK_TOP

    new_rsp = STACK_TOP - 0x200
    cpu = run_emu(
        f"""
        lea rax, [rip + landed]
        push 0x2B                 # new ss
        mov rbx, {new_rsp}
        push rbx                  # new rsp
        sub rsp, 0x10             # the imm16 param bytes sit between
        push 0x10                 # cs (different RPL -> inter-priv)
        push rax
        retf 0x10
    landed:
        mov rcx, rsp
        hlt
        """)
    assert cpu.cs_sel == 0x10
    assert cpu.gpr[1] == new_rsp + 0x10  # imm released on the NEW stack too


def test_jecxz_a32():
    """67h jecxz tests ECX, not RCX (ADVICE r4: the a32 form must not
    silently take jrcxz semantics)."""
    cpu = run_emu(
        """
        mov rcx, 0xF00000000     # ECX == 0 but RCX != 0
        jrcxz bad
        jecxz ok                 # 67 E3: must branch on ECX == 0
    bad:
        mov rax, 0xBAD
        hlt
    ok:
        mov rax, 0x600D
        hlt
        """)
    assert cpu.gpr[0] == 0x600D


def test_enter_leave_roundtrip():
    """enter size,0 pairs with leave; nested-level forms stay INVALID."""
    from tests.asmhelper import assemble as _asm
    from wtf_tpu.cpu.uops import OPC_INVALID, OPC_LEAVE

    assert decode(_asm("enter 0x20, 0") + b"\x90" * 8).opc == OPC_LEAVE
    assert decode(_asm("enter 0x20, 0") + b"\x90" * 8).sub == 1
    assert decode(_asm("enter 0x20, 3") + b"\x90" * 8).opc == OPC_INVALID
    cpu = run_emu("""
        mov rbp, 0x1122334455667788
        mov rdi, rsp
        enter 0x40, 0
        mov rax, rbp              # frame pointer = rsp after the push
        mov rbx, [rbp]            # saved old rbp
        lea rcx, [rbp-0x40]       # allocation
        leave
        mov rdx, rsp              # balanced again
        hlt
    """)
    assert cpu.gpr[0] == cpu.gpr[7] - 8          # rbp = old rsp - 8
    assert cpu.gpr[3] == 0x1122334455667788      # old rbp was pushed
    assert cpu.gpr[1] == cpu.gpr[0] - 0x40       # the 0x40 allocation
    assert cpu.gpr[2] == cpu.gpr[7]              # leave rebalanced rsp
    assert cpu.gpr[5] == 0x1122334455667788      # leave restored rbp
