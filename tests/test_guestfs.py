"""Guest filesystem emulation tests (fshooks/guestfile/handle-table roles).

VERDICT round-2 item 9's done criterion: a target reads a pre-mapped fake
file, deterministic across restore.
"""

import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.core import nt
from wtf_tpu.core.results import Ok
from wtf_tpu.harness import demo_fs, guestfs


# ---------------------------------------------------------------------------
# unit: streams / tables
# ---------------------------------------------------------------------------

def test_guestfile_stream_and_restore():
    f = guestfs.GuestFile("x.txt", b"hello world")
    f.save()
    assert f.read(5) == b"hello"
    assert f.read(100) == b" world"
    assert f.read(5) == b""
    f.write(b"MORE")
    assert bytes(f.data) == b"hello worldMORE"
    f.restore()
    assert bytes(f.data) == b"hello world"
    assert f.cursor == 0


def test_guestfile_offset_io():
    f = guestfs.GuestFile("x", b"0123456789")
    assert f.read(3, offset=4) == b"456"
    assert f.cursor == 7
    f.write(b"AB", offset=0)
    assert bytes(f.data) == b"AB23456789"


def test_handle_table_alloc_close_restore():
    t = guestfs.HandleTable()
    f = guestfs.GuestFile("x")
    t.save()
    h1 = t.allocate(f)
    h2 = t.allocate(f)
    assert h1 == guestfs.HANDLE_BASE
    assert h2 < h1  # counts down (handle_table.h:56-141)
    assert t.get(h1) is f
    assert t.close(h1)
    assert not t.close(h1)
    t.restore()
    assert t.get(h1) is None  # pre-save state: nothing allocated
    assert t.allocate(f) == guestfs.HANDLE_BASE


def test_fs_table_lookup_rules():
    t = guestfs.FsHandleTable()
    f = t.map_existing_guest_file("\\??\\C:\\dir\\input.txt", b"data")
    assert t.lookup("\\??\\C:\\dir\\input.txt") is f
    assert t.lookup("C:\\other\\input.txt") is f  # leaf-name match
    assert t.lookup("missing.bin") is None
    t.blacklist_file("secret.txt")
    t.map_existing_guest_file("secret.txt")
    assert t.lookup("secret.txt") is None
    ghost = t.map_nonexisting_guest_file("ghost.txt")
    assert not ghost.exists
    calls = []
    t.unknown_file_handler = lambda name: calls.append(name) or None
    assert t.lookup("what.dll") is None
    assert calls == ["what.dll"]


# ---------------------------------------------------------------------------
# end to end: the demo_fs guest on both backends
# ---------------------------------------------------------------------------

def make_backend(name, **kw):
    backend = create_backend(name, demo_fs.build_snapshot(),
                             limit=100_000, **kw)
    backend.initialize()
    demo_fs.TARGET.init(backend)
    return backend


@pytest.mark.parametrize("backend_name", ["emu", "tpu"])
def test_fs_guest_reads_testcase_as_file(backend_name):
    backend = make_backend(backend_name, **(
        {"n_lanes": 2} if backend_name == "tpu" else {}))
    results = backend.run_batch([b"HELLOWORLD123456"], demo_fs.TARGET)
    assert isinstance(results[0], Ok), results[0]
    # lane-0 view: the guest copied the file's first qword to OUTSLOT
    if backend_name == "tpu":
        view = backend.runner.view()
        out = view.virt_read(0, demo_fs.OUTSLOT, 8)
    else:
        out = backend.virt_read(demo_fs.OUTSLOT, 8)
    assert out == b"HELLOWOR"


def test_fs_batch_lanes_isolated():
    """Each lane sees ITS testcase as the file content — per-lane clones
    of the template fs, not shared mutable state."""
    backend = make_backend("tpu", n_lanes=4)
    cases = [b"LANE0AAABBBBCCCC", b"LANE1XXXYYYYZZZZ", b"LANE2...padding."]
    results = backend.run_batch(cases, demo_fs.TARGET)
    assert all(isinstance(r, Ok) for r in results), results
    view = backend.runner.view()
    for lane, content in enumerate(cases):
        out = view.virt_read(lane, demo_fs.OUTSLOT, 8)
        assert out == content[:8], f"lane {lane}: {out!r}"


def test_fs_deterministic_across_restore():
    backend = make_backend("emu")
    for content in (b"AAAABBBBCCCCDDDD", b"AAAABBBBCCCCDDDD"):
        demo_fs.TARGET.insert_testcase(backend, content)
        result = backend.run()
        assert isinstance(result, Ok)
        assert backend.virt_read(demo_fs.OUTSLOT, 8) == b"AAAABBBB"
        assert demo_fs._FS.stats["opens"] >= 1
        # lane-0 handle table rolled back each run: the same fake handle
        # was handed out both times (fresh clone from the template)
        _, handles = demo_fs._FS.lane_state(0)
        assert handles._next == guestfs.HANDLE_BASE - 2
        demo_fs.TARGET.restore()
        backend.restore()
        assert demo_fs._FS.lane_state(0)[1]._next == guestfs.HANDLE_BASE


def test_fs_not_found_path():
    backend = make_backend("emu")
    demo_fs._FS.fs.blacklist_file(demo_fs.FILE_NAME)
    demo_fs.TARGET.insert_testcase(backend, b"whatever")
    result = backend.run()
    # NtCreateFile fails -> guest skips to finish -> Ok, OUTSLOT untouched
    assert isinstance(result, Ok)
    assert backend.virt_read(demo_fs.OUTSLOT, 8) == b"\x00" * 8
    assert demo_fs._FS.stats["not_found"] == 1


def test_unicode_string_reader():
    writes = {}

    def virt_read(ptr, size):
        blob = {0x1000: b"\x0a\x00\x0c\x00\x00\x00\x00\x00"
                        b"\x00\x20\x00\x00\x00\x00\x00\x00",
                0x2000: "hello".encode("utf-16-le")}[ptr]
        return blob[:size]

    assert nt.read_unicode_string(virt_read, 0x1000) == "hello"
