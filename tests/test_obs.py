"""Observability-plane tests (ISSUE 18): fleet telemetry aggregation
exactness (merge semantics, sequence-number dedup, reconnect epochs,
per-node rates), the TAG_TELEM wire codec, the `wtf-tpu status`
surface, the bench_guard regression gate, and the telemetry lint
family.  The socket-level end-to-end (a real master + faulted clients)
lives in wtf_tpu/testing/obs_smoke.py; these tests pin the EXACT counts
that chaos makes racy there."""

import json
import sys
from pathlib import Path

import pytest

from wtf_tpu.dist import wire
from wtf_tpu.fleet.telemetry import (
    FleetTelemetry, NodeTelemetry, render_prometheus,
)
from wtf_tpu.telemetry import Registry
from wtf_tpu.telemetry.metrics import merge_snapshots

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))


def _node_registry(testcases, crashes=0, lat=()):
    reg = Registry()
    reg.counter("campaign.testcases").inc(testcases)
    if crashes:
        reg.counter("campaign.crashes").inc(crashes)
    reg.gauge("supervise.rung").set(2)
    reg.counter("fallbacks").labels("ssefp").inc(testcases % 5)
    for v in lat:
        reg.histogram("chunk.lat").observe(v)
    return reg


# ---------------------------------------------------------------------------
# snapshot merge semantics
# ---------------------------------------------------------------------------

def test_merge_snapshots_equals_serial_sum():
    regs = [_node_registry(10, 1, lat=(0.5, 1.5)),
            _node_registry(20, 0, lat=(1.0,)),
            _node_registry(3, 2, lat=(0.1, 9.0))]
    merged = merge_snapshots(r.snapshot() for r in regs)
    # counters sum per label
    assert merged["campaign.testcases"]["value"] == 33
    assert merged["campaign.crashes"]["value"] == 3
    assert merged["fallbacks"]["labels"]["ssefp"] == sum(
        n % 5 for n in (10, 20, 3))
    # gauges sum (a fleet gauge is capacity-like: total across nodes)
    assert merged["supervise.rung"]["value"] == 6
    # histograms: count/sum add, min/max extremize
    h = merged["chunk.lat"]
    assert h["count"] == 5 and h["sum"] == pytest.approx(12.1)
    assert h["min"] == 0.1 and h["max"] == 9.0


def test_snapshot_restore_round_trip():
    reg = _node_registry(7, 1, lat=(2.0, 3.0))
    clone = Registry()
    clone.restore_snapshot(reg.snapshot())
    assert json.dumps(clone.snapshot(), sort_keys=True) == \
        json.dumps(reg.snapshot(), sort_keys=True)
    assert clone.dump() == reg.dump()


def test_telem_wire_round_trip():
    snapshot = _node_registry(5).snapshot()
    events = [{"type": "crash", "name": "crash-read-0x1"}]
    body = wire.encode_telem(42, snapshot, events)
    seq, snap2, ev2 = wire.decode_telem(body)
    assert seq == 42 and ev2 == events
    assert json.dumps(snap2, sort_keys=True) == \
        json.dumps(snapshot, sort_keys=True)


# ---------------------------------------------------------------------------
# aggregator: exact no-double-count accounting (fault-free)
# ---------------------------------------------------------------------------

def test_node_telemetry_drops_stale_and_duplicate_frames():
    node = NodeTelemetry("aa")
    s1 = {"campaign.testcases": {"kind": "c", "value": 10}}
    s2 = {"campaign.testcases": {"kind": "c", "value": 20}}
    assert node.apply(1, s1, now=1.0)
    assert not node.apply(1, s1, now=2.0)   # verbatim re-send
    assert node.apply(2, s2, now=3.0)
    assert not node.apply(1, s1, now=4.0)   # stale replay
    assert node.seq == 2 and node.snapshot == s2
    # rate between the two applied frames: 10 execs over 2s
    assert node.execs_per_s == pytest.approx(5.0)


def test_node_telemetry_reconnect_epoch_resets_sequence():
    node = NodeTelemetry("bb")
    assert node.apply(5, {"campaign.testcases": {"kind": "c", "value": 9}},
                      now=1.0)
    # reconnect: the client's cursor restarts at seq 0 (well, 1 after
    # its first frame) — seq 0 explicitly reopens the window
    assert node.apply(0, {"campaign.testcases": {"kind": "c", "value": 9}},
                      now=2.0)
    assert node.epoch == 1
    assert node.apply(1, {"campaign.testcases": {"kind": "c", "value": 12}},
                      now=3.0)
    assert node.seq == 1 and node.epoch == 1


def test_fleet_telemetry_exact_counts_under_resends(tmp_path):
    """The obs_smoke invariant, fault-free so the counts are EXACT: N
    applied frames, every scripted duplicate dropped, aggregate equal to
    the serial sum of the latest per-node snapshots."""
    clock = iter(float(t) for t in range(1, 100))
    fleet = FleetTelemetry(export_dir=tmp_path / "export",
                           clock=lambda: next(clock))
    last = {}
    dup_sends = 0
    for step in (1, 2, 3):
        for i, cid in enumerate((b"\x01" * 8, b"\x02" * 8, b"\x03" * 8)):
            snapshot = _node_registry(step * 10 + i).snapshot()
            assert fleet.apply(cid, step, snapshot)
            last[cid] = snapshot
            if step == 2:  # re-send every node's frame once
                assert not fleet.apply(cid, step, snapshot)
                dup_sends += 1
    assert fleet.frames == 9
    assert fleet.duplicates == dup_sends == 3
    assert json.dumps(fleet.fleet_snapshot(), sort_keys=True) == \
        json.dumps(merge_snapshots(last.values()), sort_keys=True)

    # reconnect replay: node 1 comes back at seq 0 with its running
    # totals — supersedes, never adds
    replay = last[b"\x01" * 8]
    assert fleet.apply(b"\x01" * 8, 0, replay)
    assert json.dumps(fleet.fleet_snapshot(), sort_keys=True) == \
        json.dumps(merge_snapshots(last.values()), sort_keys=True)
    assert fleet.nodes[(b"\x01" * 8).hex()].epoch == 1

    # exports: status doc + prom text + one stream record per applied
    assert fleet.write_exports()
    status = json.loads((tmp_path / "export" / "status.json").read_text())
    assert status["kind"] == "fleet" and status["nodes"] == 3
    assert status["frames"] == 10 and status["duplicates_dropped"] == 3
    rows = {r["node"]: r for r in status["per_node"]}
    assert rows[(b"\x02" * 8).hex()]["testcases"] == 31
    assert status["metrics"]["campaign.testcases"] == 31 + 30 + 32
    prom = (tmp_path / "export" / "telemetry.prom").read_text()
    assert "# TYPE wtf_campaign_testcases counter" in prom
    assert f"wtf_campaign_testcases {31 + 30 + 32}" in prom
    stream = [json.loads(ln) for ln in
              (tmp_path / "export" / "fleet-telem.jsonl")
              .read_text().splitlines()]
    assert len(stream) == fleet.frames == 10
    fleet.close()


def test_render_prometheus_shapes():
    reg = Registry()
    reg.counter("a.b").inc(2)
    reg.gauge("g").set(7)
    reg.counter("lab").labels('x"y\\z').inc(3)
    reg.histogram("h").observe(1.5)
    text = render_prometheus(reg.snapshot())
    assert "# TYPE wtf_a_b counter\nwtf_a_b 2" in text
    assert "# TYPE wtf_g gauge\nwtf_g 7" in text
    assert 'wtf_lab{label="x\\"y\\\\z"} 3' in text
    assert "wtf_h_count 1" in text and "wtf_h_sum 1.5" in text
    assert "wtf_h_min 1.5" in text and "wtf_h_max 1.5" in text


# ---------------------------------------------------------------------------
# `wtf-tpu status`
# ---------------------------------------------------------------------------

CAMPAIGN_DOC = {
    "kind": "campaign", "ts": 0.0, "batches": 12,
    "line": "#768 cov: 41 corp: 9 exec/s: 504.9 zh: 100% pre: 4/5(-1)",
    "metrics": {
        "campaign.testcases": 768,
        "device.instructions": 1000,
        "device.fused_steps": 861,
        "megachunk.windows": 5,
        "devdec.zero_host_windows": 5,
        "megachunk.prelaunched": 5,
        "megachunk.prelaunch_hits": 4,
        "megachunk.prelaunch_dropped": 1,
        "supervise.dispatches": 40,
        "supervise.rung": 1,
        "supervise.rebuilds": 2,
        "supervise.quarantined_lanes": 1,
        "dist.cov_bytes_delta": 100,
        "dist.cov_bytes_bitmap": 1700,
        "tenant.demo_tlv.testcases": 700,
        "tenant.demo_tlv.new_coverage": 41,
        "tenant.demo_tlv.crashes": 2,
        "phase.seconds": {"execute": 10.0, "execute/device-step": 9.0,
                          "harvest": 1.0},
    },
}


def test_status_json_golden(tmp_path, capsys):
    """--json emits the status.json document verbatim — the machine
    surface dashboards scrape."""
    from wtf_tpu.cli import main

    (tmp_path / "status.json").write_text(json.dumps(CAMPAIGN_DOC))
    assert main(["status", str(tmp_path), "--json"]) == 0
    out = capsys.readouterr().out.strip()
    assert json.loads(out) == CAMPAIGN_DOC


def test_status_renders_derived_rows(tmp_path, capsys):
    from wtf_tpu.cli import main

    (tmp_path / "status.json").write_text(json.dumps(CAMPAIGN_DOC))
    assert main(["status", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "campaign: batch 12" in out
    assert CAMPAIGN_DOC["line"] in out
    assert "fused occupancy: 86.1%" in out
    assert "zero-host windows: 5/5 (100%)" in out
    assert "prelaunch: 4/5 adopted, 1 dropped" in out
    # top-level 11s, device-fenced 9s -> host share 2/11
    assert "host share: 18.2% of accounted wall" in out
    assert "supervisor: rung 1, 2 rebuilds, 1 lanes quarantined" in out
    assert "delta frames: 1600 cov bytes saved (17.0x smaller)" in out
    assert "tenant demo_tlv: execs=700 newcov=41 crashes=2" in out


def test_status_minimal_campaign_has_no_phantom_rows(tmp_path, capsys):
    """Subsystem rows appear only when the subsystem ran: a plain emu
    campaign shows the heartbeat line and nothing else."""
    from wtf_tpu.cli import main

    doc = {"kind": "campaign", "ts": 0.0, "batches": 1,
           "line": "#10 exec/s: 5.0",
           "metrics": {"campaign.testcases": 10}}
    (tmp_path / "status.json").write_text(json.dumps(doc))
    assert main(["status", str(tmp_path)]) == 0
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln]
    assert len(lines) == 2  # header + heartbeat line


def test_status_missing_dir_fails_cleanly(tmp_path, capsys):
    from wtf_tpu.cli import main

    assert main(["status", str(tmp_path)]) == 1
    assert "no status.json" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# bench_guard
# ---------------------------------------------------------------------------

def test_bench_guard_extract_all_shapes():
    import bench_guard

    wrapped = {"n": 1, "rc": 0, "parsed": {
        "value": 100.0, "unit": "execs/s",
        "microbench": {"branchy_instr_per_s": 5.0,
                       "chunk512_wall_s": 2.0,
                       "chunk_dispatch_floor_s": 0.1}}}
    rows = bench_guard.extract(wrapped)
    assert rows == {"headline.execs_per_s": 100.0,
                    "micro.branchy_instr_per_s": 5.0,
                    "micro.chunk512_wall_s": 2.0,
                    "micro.chunk_dispatch_floor_s": 0.1}
    structured = {
        "fused_compare": {"fused_on": {"fused_occupancy": 1.0}},
        "megachunk_host_share": {"megachunk": {
            "execs_per_s": 500.0, "host_share_of_wall": 0.03}},
        "devmut_ab": {"device": {"execs_per_s": 88.0}},
        "kernel_budget": {"xla_step_total": 166}}
    rows = bench_guard.extract(structured)
    assert rows["fused.occupancy"] == 1.0
    assert rows["megachunk.execs_per_s"] == 500.0
    assert rows["budget.xla_step_total"] == 166


def test_bench_guard_noise_band_and_verdicts():
    import bench_guard

    base = {"micro.chunk512_wall_s": 10.0, "headline.execs_per_s": 100.0,
            "budget.xla_step_total": 166}
    # inside the ±25% band (single metric, container noise): OK
    ok = bench_guard.compare(base, {"micro.chunk512_wall_s": 12.0,
                                    "headline.execs_per_s": 80.0,
                                    "budget.xla_step_total": 166})
    assert not ok["fail"] and not ok["regressed"]
    # one metric past the SQUARED band: hard fail
    hard = bench_guard.compare(base, {"micro.chunk512_wall_s": 16.0,
                                      "headline.execs_per_s": 100.0,
                                      "budget.xla_step_total": 166})
    assert hard["fail"] and hard["hard_regressions"] == \
        ["micro.chunk512_wall_s"]
    # two metrics past the single band: fail even though neither is hard
    two = bench_guard.compare(base, {"micro.chunk512_wall_s": 13.0,
                                     "headline.execs_per_s": 70.0,
                                     "budget.xla_step_total": 166})
    assert two["fail"] and len(two["regressed"]) == 2 \
        and not two["hard_regressions"]
    # the deterministic kernel budget has NO noise excuse
    exact = bench_guard.compare(base, {"budget.xla_step_total": 167})
    assert exact["fail"] and exact["hard_regressions"] == \
        ["budget.xla_step_total"]
    # improvements are not regressions
    up = bench_guard.compare(base, {"micro.chunk512_wall_s": 5.0,
                                    "headline.execs_per_s": 200.0})
    assert not up["fail"]
    assert up["metrics"]["headline.execs_per_s"]["verdict"] == "improved"


def test_bench_guard_self_test_passes():
    import bench_guard

    result = bench_guard.self_test(noise=0.25)
    assert result["real"]["compared"] >= 1
    assert result["synthetic_flagged"]
    assert bench_guard.main(["--self-test"]) == 0


# ---------------------------------------------------------------------------
# telemetry lint family
# ---------------------------------------------------------------------------

def test_telemetry_lint_flags_inline_serialization():
    """The family's teeth: a seam whose source serializes the registry
    (here: write_exports, which legitimately calls json.dumps — standing
    in for a dispatch seam that shouldn't) is a finding; a serialization-
    free seam is clean."""
    from wtf_tpu.analysis.rules import check_telemetry_seams

    dirty = check_telemetry_seams(sites={
        "exports": "wtf_tpu.fleet.telemetry:FleetTelemetry.write_exports"})
    assert len(dirty) == 1
    f = dirty[0]
    assert f.rule == "telemetry.seam-serialization"
    assert "json.dumps(" in f.primitive
    clean = check_telemetry_seams(sites={
        "apply": "wtf_tpu.fleet.telemetry:NodeTelemetry.apply"})
    assert clean == []
    # unresolvable sites are the supervise family's finding, not ours
    assert check_telemetry_seams(sites={"x": "no.such.module:Nope"}) == []


def test_telemetry_lint_real_seams_are_clean():
    """The live SEAM_SITES enumeration must hold the pin today — the
    dispatch hot path serializes nothing."""
    from wtf_tpu.analysis.rules import check_telemetry_seams

    assert check_telemetry_seams() == []
