"""CLI tests: the `run` repro workflow and `campaign` driver
(reference wtf.cc:33-371 + subcommands.cc:16-101)."""

import random
from pathlib import Path

import pytest

from wtf_tpu.cli import build_parser, main
from wtf_tpu.config import TargetPaths

from test_harness import BENIGN, OVERFLOW


def test_parser_subcommands():
    parser = build_parser()
    args = parser.parse_args(
        ["run", "--name", "demo_tlv", "--input", "/tmp/x",
         "--trace-type", "cov", "--limit", "500"])
    assert args.subcommand == "run"
    assert args.trace_type == "cov"
    assert args.limit == 500
    with pytest.raises(SystemExit):
        parser.parse_args(["run"])  # --name/--input required
    with pytest.raises(SystemExit):
        parser.parse_args(["bogus"])


def test_target_paths_resolve(tmp_path):
    paths = TargetPaths(target=tmp_path / "t").resolve()
    assert paths.inputs == tmp_path / "t" / "inputs"
    assert paths.outputs == tmp_path / "t" / "outputs"
    assert paths.crashes == tmp_path / "t" / "crashes"
    assert paths.state == tmp_path / "t" / "state"
    # explicit dirs win over the convention
    paths = TargetPaths(target=tmp_path, inputs=tmp_path / "else").resolve()
    assert paths.inputs == tmp_path / "else"


def test_run_repro_with_trace(tmp_path, capsys):
    """`run --input crash.bin --trace-path t.txt` reproduces the crash and
    writes the rip trace (the de-facto repro/regression workflow,
    README.md:67-79)."""
    crash_file = tmp_path / "crash.bin"
    crash_file.write_bytes(OVERFLOW)
    trace = tmp_path / "t.txt"
    rc = main(["run", "--name", "demo_tlv", "--backend", "emu",
               "--input", str(crash_file), "--trace-path", str(trace),
               "--trace-type", "rip"])
    assert rc == 2  # crash reproduced
    out = capsys.readouterr().out
    assert "crash-" in out
    lines = trace.read_text().splitlines()
    assert len(lines) > 10
    assert all(l.startswith("0x") for l in lines)
    # first rip = parser entry
    from wtf_tpu.harness import demo_tlv

    assert int(lines[0], 16) == demo_tlv.CODE_GVA


def test_run_over_directory(tmp_path, capsys):
    inputs = tmp_path / "inputs"
    inputs.mkdir()
    (inputs / "benign").write_bytes(BENIGN)
    (inputs / "boom").write_bytes(OVERFLOW)
    traces = tmp_path / "traces"
    rc = main(["run", "--name", "demo_tlv", "--backend", "emu",
               "--input", str(inputs), "--trace-path", str(traces),
               "--trace-type", "cov"])
    assert rc == 2
    out = capsys.readouterr().out
    assert "benign: ok" in out
    assert "boom: crash" in out
    assert (traces / "benign.trace").exists()
    assert (traces / "boom.trace").exists()


def test_snapshot_conversion_roundtrip(tmp_path, capsys):
    """npz -> dmp -> load -> run: the snapshot subcommand round-trips a
    working guest through the Windows crash-dump format."""
    from wtf_tpu.harness import demo_tlv

    state_npz = tmp_path / "npz"
    demo_tlv.build_snapshot().save_raw(state_npz)
    rc = main(["snapshot", "--state", str(state_npz),
               "--out", str(tmp_path / "dmp"), "--format", "dmp-bmp"])
    assert rc == 0
    assert (tmp_path / "dmp" / "mem.dmp").exists()
    crash_file = tmp_path / "crash.bin"
    crash_file.write_bytes(OVERFLOW)
    rc = main(["run", "--name", "demo_tlv", "--backend", "emu",
               "--state", str(tmp_path / "dmp"),
               "--input", str(crash_file)])
    assert rc == 2  # planted crash reproduces from the converted dump
    assert "crash-" in capsys.readouterr().out


def test_campaign_emu_finds_crash(tmp_path, capsys):
    rc = main(["campaign", "--name", "demo_tlv", "--backend", "emu",
               "--runs", "600", "--seed", "5", "--max_len", "128",
               "--crashes", str(tmp_path / "crashes"), "--stop-on-crash"])
    assert rc == 2
    assert any((tmp_path / "crashes").iterdir())


def test_campaign_minset(tmp_path, capsys):
    """--runs=0 = minset (reference server.h:552-556): replay seeds only,
    outputs/ = the coverage-minimal subset, no mutations, no seed copies."""
    inputs = tmp_path / "inputs"
    inputs.mkdir()
    # two identical-coverage seeds (type-1 only), one bigger seed covering
    # types 1+2, and one small seed reaching the type-3 path nothing else
    # covers: minset = {big, type-3 representative}
    (inputs / "a").write_bytes(b"\x01\x02XY")
    (inputs / "b").write_bytes(b"\x01\x02ZW")
    (inputs / "c").write_bytes(b"\x01\x02AA\x02\x08BBBBBBBB")
    (inputs / "d").write_bytes(b"\x03\x02ok")
    rc = main(["campaign", "--name", "demo_tlv", "--backend", "tpu",
               "--lanes", "4", "--target", str(tmp_path), "--runs", "0",
               "--limit", "100000"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "minset: kept" in out
    kept = list((tmp_path / "outputs").glob("*"))
    # the two identical-coverage seeds collapse to one representative
    assert len(kept) == 2, [p.name for p in kept]

    # re-minimizing with a stale subsumed find in outputs/ prunes it:
    # outputs is always exactly the measured minimal subset
    from wtf_tpu.utils.hashing import hex_digest

    stale = b"\x01\x02QQ"  # type-1 only: subsumed by the big seed
    (tmp_path / "outputs" / hex_digest(stale)).write_bytes(stale)
    rc = main(["campaign", "--name", "demo_tlv", "--backend", "tpu",
               "--lanes", "4", "--target", str(tmp_path), "--runs", "0",
               "--limit", "100000"])
    assert rc == 0
    kept2 = sorted(p.name for p in (tmp_path / "outputs").glob("*"))
    assert kept2 == sorted(p.name for p in kept), kept2
