"""The measured bench denominator (native/bochsref.cc) must be a faithful
executor of the demo_tlv workload: same ok/crash verdicts as the oracle
on the same testcase stream, or its exec/s means nothing."""

import ctypes
import random

import pytest

from wtf_tpu.backend.emu import EmuBackend
from wtf_tpu.core.results import Crash, Ok, Timedout
from wtf_tpu.fuzz.corpus import Corpus
from wtf_tpu.fuzz.native_mutator import best_mangle_mutator
from wtf_tpu.harness import demo_tlv as T
from wtf_tpu.native import build_library


def _make_vm(lib):
    u64, u8p = ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8)
    rsp = T.STACK_TOP - 0x1000
    stack_base = T.STACK_TOP - 0x8000
    stack = bytearray(0x9000)
    stack[rsp - stack_base:rsp - stack_base + 8] = T.FINISH_GVA.to_bytes(
        8, "little")
    spans = [
        (T.CODE_GVA, T._GUEST_CODE.ljust(0x1000, b"\xcc")),
        (T.FINISH_GVA, b"\x90\xf4".ljust(0x1000, b"\xcc")),
        (T.INPUT_GVA, bytes(T.MAX_INPUT)),
        (T.SCRATCH_GVA, bytes(0x1000)),
        (stack_base, bytes(stack)),
    ]
    bases = (u64 * len(spans))(*[s[0] for s in spans])
    sizes = (u64 * len(spans))(*[len(s[1]) for s in spans])
    bufs = [(ctypes.c_uint8 * len(s[1])).from_buffer_copy(s[1])
            for s in spans]
    datas = (u8p * len(spans))(*[ctypes.cast(b, u8p) for b in bufs])
    return lib.bochsref_create(bases, sizes, datas, len(spans)), rsp


def test_bochsref_matches_oracle_verdicts():
    path = build_library("bochsref", ["bochsref.cc"])
    if path is None:
        pytest.skip("no native toolchain")
    lib = ctypes.CDLL(str(path))
    u64, u32, u8p = (ctypes.c_uint64, ctypes.c_uint32,
                     ctypes.POINTER(ctypes.c_uint8))
    lib.bochsref_create.restype = ctypes.c_void_p
    lib.bochsref_create.argtypes = [ctypes.POINTER(u64), ctypes.POINTER(u64),
                                    ctypes.POINTER(u8p), ctypes.c_int]
    lib.bochsref_campaign.argtypes = [
        ctypes.c_void_p, u64, u64, u64, u64, u64,
        u8p, ctypes.POINTER(u32), ctypes.c_int, u64, u64,
        ctypes.POINTER(u64), ctypes.POINTER(u64), ctypes.POINTER(u64)]
    lib.bochsref_destroy.argtypes = [ctypes.c_void_p]

    rng = random.Random(0xBEEF)
    corpus = Corpus(rng=rng)
    corpus.add(b"\x01\x04AAAA\x02\x08BBBBBBBB")
    corpus.add(b"\x03\x30" + b"C" * 0x30)     # the planted smash
    mutator = best_mangle_mutator(rng, max_len=0x200)
    tcs = [mutator.get_new_testcase(corpus) for _ in range(64)]
    tcs += [b"\x01\x04AAAA", b"\x03\x30" + b"C" * 0x30, b"", b"\x02\x03AB"]

    # oracle verdicts
    be = EmuBackend(T.build_snapshot(), limit=100_000)
    be.initialize()
    T.TARGET.init(be)
    oracle = []
    for tc in tcs:
        T.TARGET.insert_testcase(be, tc)
        r = be.run()
        oracle.append(
            "ok" if isinstance(r, Ok)
            else "timeout" if isinstance(r, Timedout) else "crash")
        be.restore()

    # bochsref verdicts, one testcase at a time
    vm, rsp = _make_vm(lib)
    native = []
    for tc in tcs:
        flat = (ctypes.c_uint8 * max(len(tc), 1)).from_buffer_copy(
            tc if tc else b"\x00")
        lens = (u32 * 1)(len(tc))
        execs = u64(0)
        instr = u64(0)
        crashes = u64(0)
        lib.bochsref_campaign(
            vm, T.CODE_GVA, rsp, T.INPUT_GVA, T.FINISH_GVA, T.SCRATCH_GVA,
            ctypes.cast(flat, u8p), lens, 1, 100_000, 1,
            ctypes.byref(execs), ctypes.byref(instr), ctypes.byref(crashes))
        native.append("crash" if crashes.value else "ok")
    lib.bochsref_destroy(vm)

    for tc, o, n in zip(tcs, oracle, native):
        assert o == n, f"verdict diverged on {tc.hex()}: oracle={o} native={n}"
