"""Run register-only x86-64 snippets on the REAL host CPU as a semantics
oracle.

The reference's correctness story leans on cross-backend differential runs
(develop on bochscpu, validate on kvm — SURVEY.md §4.3).  Our analog chain:
host hardware (this harness) validates the Python oracle (cpu/emu.py), which
in turn validates the JAX executor.  Snippets used here must only touch
GPRs/flags and keep the stack balanced — they execute inside the test
process.

Protocol: a 17×u64 buffer (16 GPRs in encoding order + rflags) is loaded
into the registers, the snippet runs, registers and flags are captured back.
rsp (slot 4) is not loaded or compared.
"""

from __future__ import annotations

import ctypes
import hashlib
import struct
import subprocess
import tempfile
from functools import lru_cache
from pathlib import Path
from typing import List, Tuple

_CACHE_DIR = Path(tempfile.gettempdir()) / "wtf_tpu_native_cache"

_WRAPPER = """
.intel_syntax noprefix
.text
.global snippet_run
snippet_run:
    push rbx
    push rbp
    push r12
    push r13
    push r14
    push r15
    push rdi              # keep regs pointer
    mov rax, [rdi+16*8]   # initial rflags
    push rax
    popfq
    mov rax, [rdi+0*8]
    mov rcx, [rdi+1*8]
    mov rdx, [rdi+2*8]
    mov rbx, [rdi+3*8]
    mov rbp, [rdi+5*8]
    mov rsi, [rdi+6*8]
    mov r8,  [rdi+8*8]
    mov r9,  [rdi+9*8]
    mov r10, [rdi+10*8]
    mov r11, [rdi+11*8]
    mov r12, [rdi+12*8]
    mov r13, [rdi+13*8]
    mov r14, [rdi+14*8]
    mov r15, [rdi+15*8]
    mov rdi, [rdi+7*8]
/* --- snippet --- */
{snippet}
/* --- capture --- */
    xchg rdi, [rsp]       # rdi = regs ptr; [rsp] = snippet's rdi
    mov [rdi+0*8], rax
    pushfq
    pop rax
    mov [rdi+16*8], rax
    mov [rdi+1*8], rcx
    mov [rdi+2*8], rdx
    mov [rdi+3*8], rbx
    mov [rdi+5*8], rbp
    mov [rdi+6*8], rsi
    pop rax
    mov [rdi+7*8], rax
    mov [rdi+8*8],  r8
    mov [rdi+9*8],  r9
    mov [rdi+10*8], r10
    mov [rdi+11*8], r11
    mov [rdi+12*8], r12
    mov [rdi+13*8], r13
    mov [rdi+14*8], r14
    mov [rdi+15*8], r15
    pop r15
    pop r14
    pop r13
    pop r12
    pop rbp
    pop rbx
    ret
"""


@lru_cache(maxsize=None)
def _build(snippet: str) -> str:
    _CACHE_DIR.mkdir(exist_ok=True)
    key = hashlib.sha256(snippet.encode()).hexdigest()[:24]
    sofile = _CACHE_DIR / f"{key}.so"
    if not sofile.exists():
        with tempfile.TemporaryDirectory() as tmp:
            src = Path(tmp) / "snip.S"
            src.write_text(_WRAPPER.format(snippet=snippet))
            subprocess.run(
                ["gcc", "-shared", "-o", str(sofile), str(src)],
                check=True, capture_output=True,
            )
    return str(sofile)


def run_native(snippet: str, regs: List[int], rflags: int = 0x202) -> Tuple[List[int], int]:
    """Execute `snippet` on the host CPU -> (gprs, rflags)."""
    lib = ctypes.CDLL(_build(snippet))
    buf = (ctypes.c_uint64 * 17)(*(list(regs) + [rflags]))
    lib.snippet_run(ctypes.byref(buf))
    out = list(buf)
    return out[:16], out[16]
