"""Self-healing device runtime tests (wtf_tpu/supervise).

The acceptance contract (ISSUE 16): every device dispatch routes through
the supervisor (lint-pinned seam enumeration); a hung dispatch is
abandoned by the watchdog and the batch replays BIT-IDENTICALLY after a
backend rebuild from live host-side state; repeated failures walk the
degradation ladder (megachunk -> batch-at-a-time -> fused-off ->
fixed-chunk) and hysteresis re-promotes after clean batches, every rung
bit-identical at equal seeds; lanes failing the on-device integrity
check are quarantined (masked idle, never harvested) while the campaign
completes; the max_chunks satellite revokes stuck lanes as per-lane
TIMEDOUT instead of aborting the batch; and the scripted device-fault
chaos (hang/error/poison on the Nth dispatch) is operation-indexed,
never wall-clock.
"""

import sys
import time
import types
from pathlib import Path

import numpy as np
import pytest

from wtf_tpu.analysis.rules import (
    check_seam_enumeration, check_supervised_seams,
)
from wtf_tpu.analysis.trace import build_tlv_campaign
from wtf_tpu.harness import demo_tlv
from wtf_tpu.interp.runner import Runner, warm_decode_cache
from wtf_tpu.core.results import StatusCode
from wtf_tpu.resume import load_campaign, restore_campaign
from wtf_tpu.supervise import (
    DEVICE_ERROR, DEVICE_HANG, DEVICE_POISON, SEAM_SITES, DegradationLadder,
    DispatchError, DispatchHang, Supervisor,
)
from wtf_tpu.supervise import integrity
from wtf_tpu.telemetry import EventLog, Registry
from wtf_tpu.testing.faultinject import (
    FaultPlan, chaos_device, fuzz_until_killed,
)

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

# the chaos/device-chaos smoke shapes: compile-cache-shared across suite
LANES, BATCHES = 8, 4
RUNS = LANES * BATCHES
SEED = 0xC4A05 & 0xFFFF
BUILD = dict(n_lanes=LANES, mutator="devmangle", limit=20_000, seed=SEED,
             chunk_steps=128, overlay_slots=16)


def _state_of(loop) -> tuple:
    return (loop._coverage(), sorted(loop.corpus.digests),
            sorted(loop.crash_names), loop.stats.testcases,
            int(np.asarray(loop.backend.coverage_state()[1]).sum()))


@pytest.fixture(scope="module")
def ref_state():
    """The unsupervised fault-free reference: the bit-identity bar for
    every recovery leg (megachunk and mesh baselines are pinned equal to
    the plain path by their own parity tiers)."""
    loop = build_tlv_campaign(**BUILD)
    loop.fuzz(RUNS)
    return _state_of(loop)


# ---------------------------------------------------------------------------
# supervisor unit: dispatch guard, watchdog, timeout scaling
# ---------------------------------------------------------------------------

def test_dispatch_passthrough_when_inactive():
    """Supervision off and no chaos armed: the guard is a plain call —
    no op-index advance, no counters, nothing wrapped."""
    sup = Supervisor()
    sentinel = object()
    assert sup.dispatch("chunk", lambda: sentinel) is sentinel
    assert sup.registry.counter("supervise.dispatches").value == 0


def test_watchdog_abandons_hung_dispatch(monkeypatch):
    """A dispatch that never completes within the deadline raises
    DispatchHang from the host timer thread — the waiter is abandoned,
    not joined, so the guard returns promptly."""
    from wtf_tpu.supervise import supervisor as sup_mod

    monkeypatch.setattr(sup_mod, "_wait_ready",
                        lambda value: time.sleep(5.0))
    sup = Supervisor(enabled=True, dispatch_timeout=0.05)
    t0 = time.monotonic()
    with pytest.raises(DispatchHang) as ei:
        sup.dispatch("chunk", lambda: object())
    assert time.monotonic() - t0 < 2.0, "watchdog waited on the dead wait"
    assert ei.value.seam == "chunk"
    assert sup.registry.counter("supervise.watchdog_fires").value == 1


def test_dispatch_error_wraps_backend_exception():
    sup = Supervisor(enabled=True)
    boom = ValueError("XlaRuntimeError stand-in")

    def fn():
        raise boom

    with pytest.raises(DispatchError) as ei:
        sup.dispatch("chunk", fn)
    assert ei.value.__cause__ is boom
    assert ei.value.index == 0
    assert sup.registry.counter("supervise.device_errors").value == 1


def test_timeout_scales_with_steps_and_window():
    """--dispatch-timeout is calibrated to one base chunk; adaptive
    rungs and megachunk windows get proportionally longer."""
    sup = Supervisor(enabled=True, dispatch_timeout=2.0)
    assert sup.timeout_for(0, 1) == 2.0
    assert sup.timeout_for(128, 1) == 2.0          # below base: no shrink
    assert sup.timeout_for(512, 1) == 4.0          # 2x the 256 base
    assert sup.timeout_for(0, 3) == 6.0            # 3-batch window
    assert sup.timeout_for(512, 2) == 8.0


def test_scripted_faults_are_operation_indexed(monkeypatch):
    """The chaos schedule keys on the global dispatch index — the same
    plan fires on the same dispatch every run, no wall-clock anywhere."""
    plan = FaultPlan([], device_faults={2: DEVICE_HANG, 4: DEVICE_ERROR})
    sup = Supervisor(enabled=True)
    seen = []
    with chaos_device(plan):
        for i in range(6):
            try:
                sup.dispatch("chunk", lambda: i)
            except DispatchHang:
                seen.append(("hang", i))
            except DispatchError:
                seen.append(("error", i))
    assert seen == [("hang", 2), ("error", 4)]
    assert [f[:2] for f in plan.fired] == [("device-hang", "chunk"),
                                           ("device-error", "chunk")]


# ---------------------------------------------------------------------------
# degradation ladder unit
# ---------------------------------------------------------------------------

def _stub_loop(megachunk=2, fused=True, adaptive=True):
    runner = types.SimpleNamespace(fused_enabled=fused,
                                   adaptive_chunks=adaptive)
    return types.SimpleNamespace(
        backend=types.SimpleNamespace(runner=runner), megachunk=megachunk)


def test_ladder_rungs_skip_inapplicable_features():
    full = DegradationLadder(_stub_loop())
    assert full.rungs == ["full", "no-megachunk", "no-fused", "fixed-chunk"]
    bare = DegradationLadder(_stub_loop(megachunk=0, fused=False,
                                        adaptive=False))
    assert bare.rungs == ["full"]
    assert not bare.on_failure()       # nothing left to turn off
    assert bare.wants_reshard


def test_ladder_degrade_apply_and_hysteresis_promotion():
    loop = _stub_loop()
    ladder = DegradationLadder(loop, promote_after=2)
    assert ladder.rung_name == "full" and not ladder.megachunk_off

    assert ladder.on_failure() and ladder.rung_name == "no-megachunk"
    assert ladder.megachunk_off
    assert ladder.on_failure() and ladder.rung_name == "no-fused"
    ladder.apply(loop)
    assert loop.backend.runner.fused_enabled is False
    assert loop.backend.runner.adaptive_chunks is True

    # hysteresis: promote_after CONSECUTIVE cleans win one rung back
    assert not ladder.on_clean()
    assert ladder.on_clean() and ladder.rung_name == "no-megachunk"
    assert not ladder.on_clean()       # streak reset by the promotion
    ladder.on_failure()                # a failure resets the streak too
    assert ladder.rung_name == "no-fused"
    assert not ladder.on_clean()
    assert ladder.on_clean()

    ladder.apply(loop)                 # back at no-megachunk: fused back on
    assert loop.backend.runner.fused_enabled is True


def test_ladder_bottom_requests_reshard():
    ladder = DegradationLadder(_stub_loop())
    for _ in range(len(ladder.rungs) - 1):
        assert ladder.on_failure()
    assert ladder.rung_name == "fixed-chunk"
    assert not ladder.wants_reshard
    assert not ladder.on_failure()     # bottom: no rung change
    assert ladder.wants_reshard


def test_heartbeat_fields():
    sup = Supervisor(enabled=True)
    assert sup.heartbeat_fields() == {"supervise_rung": "full",
                                     "supervise_quarantined": 0}
    sup.ladder = DegradationLadder(_stub_loop())
    sup.ladder.on_failure()
    sup.quarantined.add(3)
    fields = sup.heartbeat_fields()
    assert fields["supervise_rung"] == "no-megachunk"
    assert fields["supervise_quarantined"] == 1


# ---------------------------------------------------------------------------
# integrity check unit (real machine pytree)
# ---------------------------------------------------------------------------

def test_integrity_flags_only_the_poisoned_lane():
    loop = build_tlv_campaign(**BUILD)
    machine = loop.backend.runner.machine
    bad, digest = integrity.check_machine(machine)
    assert not np.asarray(bad).any(), "clean snapshot machine flagged"

    poisoned = integrity.poison_machine(machine, 2)
    bad2, digest2 = integrity.check_machine(poisoned)
    assert np.asarray(bad2).tolist() == [lane == 2 for lane in range(LANES)]
    assert int(np.asarray(digest2)) != int(np.asarray(digest))

    # the write-side mask parks lanes the way untasked lanes idle
    masked = integrity.mask_idle(poisoned, np.arange(LANES) == 2)
    assert int(np.asarray(masked.status)[2]) == int(StatusCode.OK)


# ---------------------------------------------------------------------------
# recovery parity: every leg bit-identical to the fault-free reference
# ---------------------------------------------------------------------------

def test_supervised_fault_free_is_bit_identical(ref_state):
    sup = build_tlv_campaign(supervise=True, dispatch_timeout=30.0, **BUILD)
    sup.fuzz(RUNS)
    assert _state_of(sup) == ref_state
    reg = sup.backend.supervisor.registry
    assert reg.counter("supervise.dispatches").value > 0
    assert reg.counter("supervise.integrity_checks").value >= BATCHES
    assert reg.counter("supervise.rebuilds").value == 0


def test_error_recovery_replays_bit_identical(ref_state):
    """A scripted device error mid-campaign: abandon, rebuild from host
    state, replay the batch — and the ladder cycles down then back up."""
    plan = FaultPlan([], device_faults={10: DEVICE_ERROR})
    loop = build_tlv_campaign(supervise=True, dispatch_timeout=30.0,
                              promote_after=2, **BUILD)
    with chaos_device(plan):
        loop.fuzz(RUNS)
    assert _state_of(loop) == ref_state
    reg = loop.backend.supervisor.registry
    assert reg.counter("supervise.batch_retries").value >= 1
    assert reg.counter("supervise.rebuilds").value >= 1
    assert reg.counter("supervise.degradations").value >= 1
    assert reg.counter("supervise.promotions").value >= 1
    assert len(plan.fired) == 1


def test_hang_recovery_replays_bit_identical(ref_state):
    plan = FaultPlan([], device_faults={6: DEVICE_HANG})
    loop = build_tlv_campaign(supervise=True, dispatch_timeout=30.0,
                              **BUILD)
    with chaos_device(plan):
        loop.fuzz(RUNS)
    assert _state_of(loop) == ref_state
    reg = loop.backend.supervisor.registry
    assert reg.counter("supervise.watchdog_fires").value == 1
    assert reg.counter("supervise.rebuilds").value >= 1


def test_transient_poison_replays_bit_identical(ref_state):
    """Below the quarantine threshold a poisoned lane is a replay, not a
    quarantine: the batch re-runs clean and nothing is masked."""
    plan = FaultPlan([], device_faults={13: (DEVICE_POISON, 3)})
    loop = build_tlv_campaign(supervise=True, dispatch_timeout=30.0,
                              **BUILD)
    with chaos_device(plan):
        loop.fuzz(RUNS)
    assert _state_of(loop) == ref_state
    sup = loop.backend.supervisor
    assert sup.registry.counter("supervise.poisoned_lanes").value >= 1
    assert sup.quarantined == set()


def test_persistent_quarantine_masks_lane_and_completes():
    """quarantine_threshold=1: the violating lane is quarantined on
    first sight, masked idle (never harvested), and the campaign still
    completes every testcase on the surviving lanes."""
    plan = FaultPlan([], device_faults={6: (DEVICE_POISON, 3)})
    loop = build_tlv_campaign(supervise=True, dispatch_timeout=30.0,
                              quarantine_threshold=1, **BUILD)
    with chaos_device(plan):
        loop.fuzz(RUNS)
    sup = loop.backend.supervisor
    assert sup.quarantined == {3}
    assert loop.stats.testcases == RUNS
    assert sup.registry.counter("device.quarantined").value == 1
    assert sup.heartbeat_fields()["supervise_quarantined"] == 1
    # quarantine forces the batch-at-a-time path (windows can't mask)
    assert sup.megachunk_disabled


def test_megachunk_hang_degrades_and_repromotes(ref_state):
    """A hang mid-window: the watchdog abandons the in-flight window,
    the ladder drops to batch-at-a-time, replays bit-identically, and
    promote_after=1 re-promotes to megachunk within the campaign."""
    plan = FaultPlan([], device_faults={3: DEVICE_HANG})
    loop = build_tlv_campaign(megachunk=2, supervise=True,
                              dispatch_timeout=30.0, promote_after=1,
                              **BUILD)
    with chaos_device(plan):
        loop.fuzz(RUNS)
    assert _state_of(loop) == ref_state
    reg = loop.backend.supervisor.registry
    assert reg.counter("supervise.watchdog_fires").value == 1
    assert reg.counter("supervise.degradations").value >= 1
    assert reg.counter("supervise.promotions").value >= 1


def test_fused_window_hang_recovery_replays_bit_identical(ref_state):
    """DEVICE_HANG mid-FUSED-window (the PR-19 window body): the
    watchdog abandons the in-flight fused window — whose machine/overlay
    planes are donated into the dispatch on real hardware — the rebuild
    reconstructs them from live host-side state, and the replayed
    campaign is bit-identical to the fault-free reference.  Index 17 is
    a steady-state fused window in the supervised dispatch schedule
    (0 = cold window, 1-16 = cold-decode fused servicing)."""
    plan = FaultPlan([], device_faults={17: DEVICE_HANG})
    loop = build_tlv_campaign(megachunk=2, fused_step="on",
                              supervise=True, dispatch_timeout=30.0,
                              promote_after=1, **BUILD)
    with chaos_device(plan):
        loop.fuzz(RUNS)
    assert _state_of(loop) == ref_state
    assert [f[:2] for f in plan.fired] == [("device-hang", "megachunk")]
    reg = loop.backend.supervisor.registry
    assert reg.counter("supervise.watchdog_fires").value == 1
    assert reg.counter("supervise.rebuilds").value >= 1
    # the fault really interrupted the fused body, not a ladder window
    assert loop.registry.counter("device.fused_window_rounds").value > 0


def test_fused_window_error_recovery_replays_bit_identical(ref_state):
    """DEVICE_ERROR on the COLD fused window (dispatch 0): the very
    first window's donated operands are rebuilt from the pristine host
    snapshot and the campaign replays bit-identically."""
    plan = FaultPlan([], device_faults={0: DEVICE_ERROR})
    loop = build_tlv_campaign(megachunk=2, fused_step="on",
                              supervise=True, dispatch_timeout=30.0,
                              promote_after=1, **BUILD)
    with chaos_device(plan):
        loop.fuzz(RUNS)
    assert _state_of(loop) == ref_state
    assert [f[:2] for f in plan.fired] == [("device-error", "megachunk")]
    reg = loop.backend.supervisor.registry
    assert reg.counter("supervise.rebuilds").value >= 1
    assert loop.registry.counter("device.fused_window_rounds").value > 0


def test_no_fused_rung_disables_fused_window_body(ref_state):
    """The no-fused rung's apply() clears runner.fused_enabled; the
    megachunk WINDOW BODY must follow at the next dispatch (the flag is
    read at call time and the compiled-window cache keys on it): pallas
    dispatches stop, the XLA-ladder windows take over, and the campaign
    stays bit-identical across the mid-campaign body swap."""
    loop = build_tlv_campaign(megachunk=2, fused_step="on", **BUILD)
    ladder = DegradationLadder(loop)
    loop.fuzz(RUNS // 2)
    reg = loop.registry
    rounds_mid = reg.counter("device.fused_window_rounds").value
    sweeps_mid = reg.counter("device.fused_window_xla_steps").value
    assert rounds_mid > 0, "fused window body never ran"
    while ladder.rung_name != "no-fused":
        assert ladder.on_failure()
    ladder.apply(loop)
    assert loop.backend.runner.fused_enabled is False
    loop.fuzz(RUNS)
    assert reg.counter("device.fused_window_rounds").value == rounds_mid
    assert reg.counter("device.fused_window_xla_steps").value > sweeps_mid
    assert _state_of(loop) == ref_state


@pytest.mark.slow
def test_megachunk_hang_parity_at_every_dispatch_index(ref_state):
    """The window->legacy->window transition soak: a hang at EVERY index
    of the supervised megachunk dispatch schedule (window, cold-decode
    chunk servicing, resumed windows) recovers bit-identically."""
    probe = build_tlv_campaign(megachunk=2, supervise=True,
                               dispatch_timeout=30.0, **BUILD)
    probe.fuzz(RUNS)
    n_disp = probe.backend.supervisor.registry.counter(
        "supervise.dispatches").value
    assert _state_of(probe) == ref_state
    for idx in range(n_disp):
        plan = FaultPlan([], device_faults={idx: DEVICE_HANG})
        loop = build_tlv_campaign(megachunk=2, supervise=True,
                                  dispatch_timeout=30.0, promote_after=1,
                                  **BUILD)
        with chaos_device(plan):
            loop.fuzz(RUNS)
        assert _state_of(loop) == ref_state, \
            f"megachunk hang at dispatch {idx} broke parity ({plan.fired})"


def test_mesh_error_recovery_replays_bit_identical(ref_state):
    """On the conftest's forced 8-device mesh: a device error abandons
    the batch, the rebuilt sharded runner replays bit-identically."""
    plan = FaultPlan([], device_faults={8: DEVICE_ERROR})
    loop = build_tlv_campaign(mesh_devices=8, supervise=True,
                              dispatch_timeout=30.0, **BUILD)
    with chaos_device(plan):
        loop.fuzz(RUNS)
    assert _state_of(loop) == ref_state
    assert loop.backend.supervisor.registry.counter(
        "supervise.rebuilds").value >= 1


def test_device_chaos_with_kill_and_resume_parity(ref_state, tmp_path):
    """The combined soak: a supervised campaign takes a scripted device
    error, checkpoints every batch, is killed at a batch boundary, and
    the resumed campaign ends bit-identical to the fault-free run."""
    ckpt = tmp_path / "checkpoint"
    victim = build_tlv_campaign(supervise=True, dispatch_timeout=30.0,
                                **BUILD)
    victim.checkpoint_dir = ckpt
    victim.checkpoint_every = 1
    plan = FaultPlan([], device_faults={4: DEVICE_ERROR})
    with chaos_device(plan):
        fuzz_until_killed(victim, RUNS, kill_at_batch=2)
    assert len(plan.fired) == 1, "scripted error never fired before kill"

    state, fell_back = load_campaign(ckpt)
    assert not fell_back
    resumed = build_tlv_campaign(supervise=True, dispatch_timeout=30.0,
                                 **BUILD)
    resumed.checkpoint_dir = ckpt
    resumed.checkpoint_every = 1
    batch = restore_campaign(resumed, state, ckpt)
    assert batch == 2
    resumed.fuzz(RUNS)
    assert _state_of(resumed) == ref_state


# ---------------------------------------------------------------------------
# max_chunks satellite: per-lane TIMEDOUT revocation, not a batch abort
# ---------------------------------------------------------------------------

def test_max_chunks_revokes_stuck_lanes_as_timedout():
    snapshot = demo_tlv.build_snapshot()
    runner = Runner(snapshot, n_lanes=4, uop_capacity=1 << 10,
                    overlay_slots=16, edge_bits=12, chunk_steps=8)
    payload = b"\x01\x02AB\x03\x08CCCCCCCC"
    warm_decode_cache(runner, demo_tlv.TARGET, payload, limit=4096)
    view = runner.view()
    for lane in range(runner.n_lanes):
        view.virt_write(lane, demo_tlv.INPUT_GVA, payload)
        view.r["gpr"][lane, 2] = np.uint64(len(payload))
    runner.push(view)
    # 8 steps is nowhere near enough to parse the TLV stream: with the
    # chunk budget exhausted the lanes are revoked per-lane, not raised
    statuses = runner.run(max_chunks=1)
    assert (statuses == int(StatusCode.TIMEDOUT)).all()
    assert runner.registry.counter(
        "runner.max_chunks_timeouts").value == runner.n_lanes
    for lane in range(runner.n_lanes):
        assert "max_chunks" in runner.lane_errors[lane]


# ---------------------------------------------------------------------------
# lint: the supervise rule family
# ---------------------------------------------------------------------------

def test_lint_supervise_family_clean_on_real_tree():
    assert check_supervised_seams() == []
    assert check_seam_enumeration() == []
    # the enumeration covers every dispatch entry point the runtime has
    assert set(SEAM_SITES) >= {"chunk", "fused", "fused-resume",
                               "device-insert", "devmut-generate",
                               "megachunk"}


def test_lint_supervise_flags_unrouted_seam():
    """A seam whose source never calls supervisor.dispatch with its own
    name is a finding — the rule reads the LIVE source, so a refactor
    that bypasses the guard fails lint immediately."""
    findings = check_supervised_seams(sites={
        "chunk": "wtf_tpu.supervise.ladder:DegradationLadder.apply"})
    assert len(findings) == 1
    assert findings[0].rule == "supervise.seam-routing"
    assert "chunk" in findings[0].message


def test_lint_supervise_flags_unresolvable_site():
    findings = check_supervised_seams(sites={
        "chunk": "wtf_tpu.supervise.no_such_module:Missing.fn"})
    assert len(findings) == 1
    assert findings[0].rule == "supervise.seam-routing"


# ---------------------------------------------------------------------------
# telemetry report: the device-resilience section
# ---------------------------------------------------------------------------

def test_telemetry_report_device_resilience_section(tmp_path, capsys):
    import telemetry_report

    reg = Registry()
    reg.counter("supervise.dispatches").inc(40)
    reg.counter("supervise.watchdog_fires").inc(1)
    reg.counter("supervise.device_errors").inc(2)
    reg.counter("supervise.rebuilds").inc(3)
    reg.counter("supervise.batch_retries").inc(3)
    reg.counter("supervise.degradations").inc(2)
    reg.counter("supervise.promotions").inc(1)
    reg.counter("supervise.integrity_checks").inc(12)
    reg.counter("device.quarantined").inc(1)
    reg.gauge("supervise.rung").set(1)
    reg.gauge("supervise.quarantined_lanes").set(1)
    sec = reg.counter("phase.seconds")
    sec.labels("execute").inc(9.0)
    sec.labels("execute/integrity").inc(0.06)
    sec.labels("execute/supervise-snapshot").inc(0.04)
    sec.labels("supervise-recover").inc(0.5)

    path = tmp_path / "events.jsonl"
    clock = iter([0.0, 10.0])
    with EventLog(path, clock=lambda: next(clock)) as log:
        log.emit("run-start")
        log.emit("run-end", metrics=reg.dump())
    summary = telemetry_report.summarize(path)
    dres = summary["device_resilience"]
    assert dres["watchdog_fires"] == 1
    assert dres["rebuilds"] == 3
    assert dres["quarantined_total"] == 1
    assert dres["quarantined_now"] == 1
    assert dres["final_rung"] == 1
    assert dres["integrity_seconds"] == 0.06
    assert dres["recover_seconds"] == 0.5
    # steady-state overhead = (integrity + snapshot) / wall, recovery out
    assert dres["overhead_share"] == round(0.1 / 10.0, 4)
    assert telemetry_report.main([str(path)]) == 0
    assert "device resilience" in capsys.readouterr().out

    # unsupervised stream: the section stays None (quiet runs stay quiet)
    path2 = tmp_path / "plain.jsonl"
    clock2 = iter([0.0, 1.0])
    with EventLog(path2, clock=lambda: next(clock2)) as log:
        log.emit("run-start")
        log.emit("run-end", metrics=Registry().dump())
    assert telemetry_report.summarize(path2)["device_resilience"] is None
