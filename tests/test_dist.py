"""Distribution-plane tests: wire protocol, master reactor, node loops.

The reference's cheap localhost story (SURVEY.md §4.5): master + fuzz
processes on one machine over tcp://localhost or a unix socket.  Here the
master runs on a thread and the nodes in the test thread — the protocol
crosses a real socketpair either way.
"""

import random
import threading
from pathlib import Path

import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.core.results import Crash, Cr3Change, Ok, Timedout
from wtf_tpu.dist import BatchClient, Client, Server, wire
from wtf_tpu.fuzz.corpus import Corpus
from wtf_tpu.fuzz.mutator import TlvStructureMutator
from wtf_tpu.harness import demo_tlv

from test_harness import BENIGN, OVERFLOW, tlv


# ---------------------------------------------------------------------------
# wire
# ---------------------------------------------------------------------------

def test_parse_address():
    import socket

    assert wire.parse_address("tcp://localhost:31337/") == (
        socket.AF_INET, ("localhost", 31337))
    assert wire.parse_address("tcp://10.0.0.1:50") == (
        socket.AF_INET, ("10.0.0.1", 50))
    assert wire.parse_address("unix:///tmp/x.sock") == (
        socket.AF_UNIX, "/tmp/x.sock")
    for bad in ("tcp://nohost/", "udp://x:1/", "unix://"):
        with pytest.raises(ValueError):
            wire.parse_address(bad)


@pytest.mark.parametrize("result", [
    Ok(), Timedout(), Cr3Change(), Crash("crash-write-0xdead"), Crash(None),
])
def test_result_roundtrip(result):
    tc = b"\x01\x02some testcase"
    cov = {0x1400001000, 0x1400001005, 0x7fff0000}
    body = wire.encode_result(tc, cov, result)
    tc2, cov2, result2 = wire.decode_result(body)
    assert tc2 == tc
    assert cov2 == cov
    assert type(result2) is type(result)
    if isinstance(result, Crash):
        assert result2.name == result.name


def test_framing_roundtrip():
    import socket

    a, b = socket.socketpair()
    try:
        wire.send_msg(a, b"hello")
        wire.send_msg(a, b"")
        assert wire.recv_msg(b) == b"hello"
        assert wire.recv_msg(b) == b""
        a.close()
        assert wire.recv_msg(b) is None  # peer closed -> None
    finally:
        b.close()


# ---------------------------------------------------------------------------
# master + nodes end to end (emu backend: fast, deterministic)
# ---------------------------------------------------------------------------

def _addr(tmp_path: Path) -> str:
    return f"unix://{tmp_path}/master.sock"


def _serve(server, seconds=60.0):
    t = threading.Thread(target=server.run, kwargs={"max_seconds": seconds})
    t.start()
    return t


def test_minset_mode(tmp_path):
    """runs=0: replay the seeds only; outputs/ = coverage-minimal subset
    (reference --runs=0 minset, server.h:552-556, README.md:81-92)."""
    inputs = tmp_path / "inputs"
    inputs.mkdir()
    # two seeds with identical coverage + one that adds coverage: the
    # minset must keep one of the twins, drop the other
    (inputs / "twin_a").write_bytes(tlv((1, b"\x01\x02")))
    (inputs / "twin_b").write_bytes(tlv((1, b"\x09\x08")))
    (inputs / "stores").write_bytes(tlv((2, b"ABCDEFGH")))
    rng = random.Random(1)
    corpus = Corpus(outputs_dir=tmp_path / "outputs", rng=rng)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 64), corpus,
                    inputs_dir=inputs, runs=0)
    thread = _serve(server)
    backend = create_backend("emu", demo_tlv.build_snapshot())
    backend.initialize()
    client = Client(backend, demo_tlv.TARGET, _addr(tmp_path))
    served = client.run()
    thread.join(timeout=60)
    assert not thread.is_alive()
    assert served == 3
    assert server.stats.testcases == 3
    saved = list((tmp_path / "outputs").iterdir())
    assert len(saved) == 2, [p.name for p in saved]  # one twin + stores


def test_fuzz_to_crash_single_client(tmp_path):
    """Master + one emu node fuzz demo_tlv to the planted stack smash."""
    rng = random.Random(0x5EED)
    corpus = Corpus(rng=rng)
    corpus.add(BENIGN)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 128), corpus,
                    crashes_dir=tmp_path / "crashes", runs=800,
                    coverage_path=tmp_path / "coverage.cov")
    thread = _serve(server, seconds=120)
    backend = create_backend("emu", demo_tlv.build_snapshot(), limit=50_000)
    backend.initialize()
    client = Client(backend, demo_tlv.TARGET, _addr(tmp_path))
    client.run()
    thread.join(timeout=120)
    assert not thread.is_alive()
    assert server.stats.crashes >= 1, server.stats.testcases
    crashes = list((tmp_path / "crashes").iterdir())
    assert crashes, "no crash file saved"
    # server-side crash files are named by the digest of the BYTES (one
    # hex_digest source of truth, like outputs/): a hostile node cannot
    # collide/overwrite another node's crash file with a chosen name
    from wtf_tpu.utils.hashing import hex_digest

    for p in crashes:
        assert hex_digest(p.read_bytes()) == p.name, p.name
    assert server.crash_names, "reported names still tracked"
    assert len(server.coverage) > 0
    # aggregate coverage persisted in the .cov format we also ingest
    from wtf_tpu.utils.covfiles import parse_cov_files

    assert parse_cov_files(tmp_path) == server.coverage


def test_two_heterogeneous_clients(tmp_path):
    """An emu node and a TPU batch node serve the same master
    concurrently — the reference's N-processes-one-master shape with
    mixed backend types (elasticity, server.h:534-544)."""
    rng = random.Random(77)
    corpus = Corpus(rng=rng)
    corpus.add(BENIGN)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 64), corpus,
                    crashes_dir=tmp_path / "crashes", runs=200)
    thread = _serve(server, seconds=180)

    emu_backend = create_backend("emu", demo_tlv.build_snapshot(),
                                 limit=50_000)
    emu_backend.initialize()
    tpu_backend = create_backend("tpu", demo_tlv.build_snapshot(),
                                 n_lanes=4, limit=50_000)
    tpu_backend.initialize()
    node_a = Client(emu_backend, demo_tlv.TARGET, _addr(tmp_path))
    node_b = BatchClient(tpu_backend, demo_tlv.TARGET, _addr(tmp_path))
    t_a = threading.Thread(target=node_a.run)
    t_a.start()
    served_b = node_b.run()
    t_a.join(timeout=180)
    assert not t_a.is_alive(), "emu client thread hung"
    thread.join(timeout=180)
    assert not thread.is_alive()
    # both node types served work and the master accounted every run
    # (crash discovery is asserted in the deterministic single-client
    # test; two-client interleaving makes the mutation stream
    # scheduling-dependent)
    assert node_a.runs > 0 and served_b > 0
    assert node_a.runs + served_b == server.stats.testcases == 200
    assert len(server.coverage) > 0


def test_batch_client_looks_like_n_nodes(tmp_path):
    """A TPU batch node is indistinguishable from n_lanes ordinary nodes:
    the master (unmodified) feeds it per-connection and aggregates per-lane
    results (the BASELINE.json master-oblivious property)."""
    rng = random.Random(3)
    corpus = Corpus(rng=rng)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 64), corpus,
                    crashes_dir=tmp_path / "crashes", runs=8)
    # seed paths so the first batch round is fully deterministic
    inputs = tmp_path / "inputs"
    inputs.mkdir()
    (inputs / "a").write_bytes(BENIGN)
    (inputs / "b").write_bytes(OVERFLOW)
    (inputs / "c").write_bytes(tlv((2, b"ABCDEFGH")))
    (inputs / "d").write_bytes(tlv((1, b"\x05")))
    server.paths = [p.read_bytes() for p in sorted(
        inputs.iterdir(), key=lambda p: p.stat().st_size, reverse=True)]
    thread = _serve(server, seconds=180)
    backend = create_backend("tpu", demo_tlv.build_snapshot(),
                             n_lanes=4, limit=50_000)
    backend.initialize()
    node = BatchClient(backend, demo_tlv.TARGET, _addr(tmp_path))
    served = node.run(max_rounds=3)
    thread.join(timeout=180)
    assert not thread.is_alive()
    assert served == server.stats.testcases == 12  # 4 seeds + 8 mutations
    assert server.stats.crashes >= 1  # OVERFLOW seed crashed
    assert len(server.coverage) > 0
    assert len(corpus) >= 1


def test_hello_and_batch_frames():
    assert wire.decode_hello(wire.encode_hello(1)) == 1
    assert wire.decode_hello(wire.encode_hello(4096)) == 4096
    assert wire.decode_hello(b"\x04\x00\x00\x00AAAA") is None  # result body
    assert wire.decode_hello(b"") is None
    items = [b"", b"x", b"y" * 1000]
    assert wire.decode_batch(wire.encode_batch(items)) == items
    assert wire.decode_batch(wire.encode_batch([])) == []


def test_mux_batch_client_campaign(tmp_path):
    """mux=True: the whole lane batch rides ONE master connection via
    batch frames; results, crash saves, and accounting match the
    1-fd-per-lane shape."""
    rng = random.Random(3)
    corpus = Corpus(rng=rng)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 64), corpus,
                    crashes_dir=tmp_path / "crashes", runs=8)
    server.paths = [BENIGN, OVERFLOW, tlv((2, b"ABCDEFGH")),
                    tlv((1, b"\x05"))]
    thread = _serve(server, seconds=180)
    backend = create_backend("tpu", demo_tlv.build_snapshot(),
                             n_lanes=4, limit=50_000)
    backend.initialize()
    node = BatchClient(backend, demo_tlv.TARGET, _addr(tmp_path), mux=True)
    served = node.run(max_rounds=3)
    thread.join(timeout=180)
    assert not thread.is_alive()
    assert served == server.stats.testcases == 12  # 4 seeds + 8 mutations
    assert server.stats.crashes >= 1  # OVERFLOW seed crashed
    assert len(server.coverage) > 0


def test_wide_mux_node_completes(tmp_path):
    """VERDICT r3 item 5 done criterion: a 4096-lane BatchClient completes
    a localhost campaign against one master — impossible in the
    1-fd-per-lane shape with a select() master (FD_SETSIZE), routine with
    one multiplexed connection and the selectors reactor."""
    import struct

    rng = random.Random(9)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 8),
                    Corpus(rng=rng), runs=0)
    # 4096 tiny spin seeds (counts 0..6 -> a few dozen instructions each)
    server.paths = [struct.pack("<I", k % 7) for k in range(4096)]
    thread = _serve(server, seconds=540)
    from wtf_tpu.harness import demo_spin

    backend = create_backend("tpu", demo_spin.build_snapshot(),
                             n_lanes=4096, limit=5_000, chunk_steps=64,
                             overlay_slots=4, uop_capacity=1 << 10,
                             edge_bits=12)
    backend.initialize()
    node = BatchClient(backend, demo_spin.TARGET, _addr(tmp_path), mux=True)
    served = node.run()
    thread.join(timeout=540)
    assert not thread.is_alive()
    assert served == server.stats.testcases == 4096
    assert len(server.coverage) > 0


def test_master_resume_replays_outputs(tmp_path):
    """A restarted master replays its persisted corpus: outputs/ files
    from a prior campaign seed the replay queue alongside inputs/,
    deduped by content (SURVEY §5.4 campaign-level resume)."""
    import random

    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.mutator import ByteMutator

    inputs = tmp_path / "inputs"
    outputs = tmp_path / "outputs"
    inputs.mkdir()
    outputs.mkdir()
    (inputs / "seed1").write_bytes(b"AAAA")
    (outputs / "prior1").write_bytes(b"BBBBBBBB")     # prior find
    (outputs / "dup-of-seed1").write_bytes(b"AAAA")   # content-dup
    rng = random.Random(0)
    corpus = Corpus(outputs_dir=outputs, rng=rng)
    server = Server("tcp://127.0.0.1:0/", ByteMutator(rng, 64), corpus,
                    inputs_dir=inputs, runs=10)
    # entries are lazily-read Paths, biggest first, content-deduped
    assert [server._next_seed(), server._next_seed(), server._next_seed()] \
        == [b"BBBBBBBB", b"AAAA", None]


def test_malformed_result_frame_drops_node_not_master(tmp_path):
    """A desynced/garbage result frame must drop that connection and
    requeue its in-flight work — never crash the reactor, never count
    anything from the bad frame."""
    rng = random.Random(5)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 16),
                    Corpus(rng=rng), runs=0)
    server.paths = [BENIGN]
    thread = _serve(server, seconds=60)
    # a broken node: hello, take the testcase, answer with garbage
    sock = wire.dial(_addr(tmp_path), retry_for=10.0)
    wire.send_msg(sock, wire.encode_hello(1))
    assert wire.recv_msg(sock) is not None
    wire.send_msg(sock, b"\xFF" * 7)  # not a decodable result body
    thread.join(timeout=60)           # reactor exits CLEANLY, not by crash
    sock.close()
    assert not thread.is_alive()
    assert server.stats.testcases == 0     # nothing counted from garbage
    assert list(server.paths) == [BENIGN]  # in-flight work requeued


def test_partial_mux_batch_is_all_or_nothing(tmp_path):
    """A mux reply whose tail is garbage must account NOTHING from that
    frame (decode-everything-first) and requeue the WHOLE in-flight set —
    otherwise the already-counted half would execute twice elsewhere."""
    from wtf_tpu.core.results import Ok as OkR

    rng = random.Random(11)
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 16),
                    Corpus(rng=rng), runs=0)
    seeds = [BENIGN, tlv((2, b"ABCDEFGH"))]
    server.paths = list(seeds)
    thread = _serve(server, seconds=60)
    sock = wire.dial(_addr(tmp_path), retry_for=10.0)
    wire.send_msg(sock, wire.encode_hello(2))  # mux node, 2 slots
    got = wire.decode_batch(wire.recv_msg(sock))
    assert sorted(got) == sorted(seeds)
    # one VALID result + one garbage blob in the same batch frame
    valid = wire.encode_result(got[0], {0x1400001000}, OkR())
    wire.send_msg(sock, wire.encode_batch([valid, b"\x00"]))
    thread.join(timeout=60)
    sock.close()
    assert not thread.is_alive()
    # nothing from the poisoned frame was accounted, and BOTH testcases
    # went back on the queue for an honest execution
    assert server.stats.testcases == 0
    assert len(server.coverage) == 0
    assert sorted(server.paths) == sorted(seeds)


def test_wire_crash_name_is_sanitized(tmp_path):
    """A hostile node cannot steer the crash-save path: separators and
    leading dots in the wire-supplied name are neutralized and the file
    lands inside crashes/."""
    rng = random.Random(12)
    crashes = tmp_path / "crashes"
    server = Server(_addr(tmp_path), TlvStructureMutator(rng, 16),
                    Corpus(rng=rng), crashes_dir=crashes, runs=0)
    server.paths = [BENIGN]
    thread = _serve(server, seconds=60)
    sock = wire.dial(_addr(tmp_path), retry_for=10.0)
    wire.send_msg(sock, wire.encode_hello(1))
    tc = wire.recv_msg(sock)
    evil = Crash("../../outside/evil")
    wire.send_msg(sock, wire.encode_result(tc, set(), evil))
    sock.close()
    thread.join(timeout=60)
    assert not thread.is_alive()
    assert not (tmp_path / "outside").exists()
    saved = [p.name for p in crashes.iterdir()]
    assert saved and all("/" not in n and not n.startswith(".")
                         for n in saved), saved
