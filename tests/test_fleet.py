"""Fleet-tier tests (wtf_tpu/fleet): streaming coverage deltas, the
content-addressed corpus/crash store, and elastic campaign resharding.

The acceptance contracts (ISSUE 13):
  - wire back-compat matrix: raw v1, whole-bitmap WTF2 and delta WTF3
    clients all end with byte-exact aggregate coverage vs a
    single-client serial run
  - delta loss recovery: lost frames repair by re-extraction against
    the ack cursor; a fresh master forces a whole-bitmap resync, a
    restarted master with persisted cursors does not
  - the store dedups on content and by triage bucket, journals every
    accepted blob, and fsck-recovers from torn writes
  - a devmangle campaign checkpointed mid-run and resumed under a
    different --mesh-devices count is bit-identical to uninterrupted
"""

import json
import random
import threading
from pathlib import Path

import numpy as np
import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.core.results import Crash, Ok, Timedout
from wtf_tpu.dist import wire
from wtf_tpu.dist.client import Client, MasterLink
from wtf_tpu.dist.server import Server
from wtf_tpu.fleet.delta import (
    AddressDeltaCursor, ServerCursor, cursor_digest, pairs_of,
)
from wtf_tpu.fleet.soak import CoverageModel, SimClient, run_soak
from wtf_tpu.fleet.store import FleetStore
from wtf_tpu.fuzz.corpus import Corpus
from wtf_tpu.fuzz.mutator import TlvStructureMutator
from wtf_tpu.harness import demo_tlv
from wtf_tpu.telemetry import Registry
from wtf_tpu.utils.hashing import hex_digest

from test_harness import BENIGN, tlv


# ---------------------------------------------------------------------------
# wire codecs
# ---------------------------------------------------------------------------

def test_hello3_roundtrip_and_backcompat():
    cid = bytes(range(16))
    body = wire.encode_hello_delta(4, cid)
    assert wire.decode_hello(body) == 4
    assert wire.hello_is_tagged(body)
    assert wire.hello_is_delta(body)
    assert wire.hello_client_id(body) == cid
    # v1/v2 hellos: unchanged, and not delta
    for tagged in (False, True):
        old = wire.encode_hello(2, tagged=tagged)
        assert wire.decode_hello(old) == 2
        assert not wire.hello_is_delta(old)
        assert wire.hello_client_id(old) is None
    # a result body is not a hello
    assert wire.decode_hello(b"\x00" * 24) is None


def test_cursor_frame_codec():
    frame = wire.encode_cursor(7, b"12345678")
    assert frame[0] == wire.TAG_CURSOR
    assert wire.decode_cursor(frame[1:]) == (7, b"12345678")
    with pytest.raises(ValueError):
        wire.decode_cursor(b"\x01\x00\x00\x00oops")


@pytest.mark.parametrize("result,bucket", [
    (Ok(), ""), (Timedout(), ""),
    (Crash("crash-write-0xdead"), "write.0x1400.aa55"),
    (Crash(None), ""),
])
def test_result_delta_roundtrip(result, bucket):
    delta = wire.DeltaFrame(False, 3, [0x1000, 0x2000],
                            [(0, 0x80000001), (9, 0x10)])
    body = wire.encode_result_delta(b"payload", result, delta, bucket)
    tc, d2, r2, b2 = wire.decode_result_delta(body)
    assert tc == b"payload"
    assert (d2.full, d2.table_base, d2.addrs, d2.pairs) \
        == (False, 3, [0x1000, 0x2000], [(0, 0x80000001), (9, 0x10)])
    assert type(r2) is type(result)
    if isinstance(result, Crash):
        assert r2.name == result.name
    assert b2 == bucket
    # 3 u32 headers + 2 addrs x 8 + 2 pairs x 8 — exactly the coverage
    # sections, nothing else (the metric the soak's ratio is built on)
    assert d2.cov_bytes() == 12 + 16 + 16


# ---------------------------------------------------------------------------
# cursor state machines
# ---------------------------------------------------------------------------

def _exchange(client, server, cov, result=Ok(), ack=True):
    body = client.encode_result(b"t", result, cov)
    _, delta, _, _ = wire.decode_result_delta(body)
    addrs = server.apply(delta)
    if ack:
        client.on_ack()
    return addrs, delta


def test_delta_sparse_flow_and_loss_recovery():
    client = AddressDeltaCursor(client_id=b"\x07" * 16)
    server = ServerCursor()
    client.on_cursor(*server.summary())
    addrs, delta = _exchange(client, server, {0x10, 0x20, 0x30})
    assert addrs == {0x10, 0x20, 0x30}
    # steady state: nothing new -> empty coverage sections
    _, delta = _exchange(client, server, {0x10, 0x20})
    assert not delta.pairs and not delta.addrs
    # a LOST frame (sent, never acked, never applied): the bits stay
    # unacked and re-extract into the next frame — no retransmission
    # bookkeeping, the OR-merge makes duplicates free
    lost = client.encode_result(b"t", Ok(), {0x40})
    _, lost_delta, _, _ = wire.decode_result_delta(lost)
    assert lost_delta.pairs  # the bit was in the lost frame
    client.on_cursor(*server.summary())  # reconnect: master never saw it
    assert not client.wants_full        # acked state still matches
    addrs, delta = _exchange(client, server, {0x50})
    assert addrs == {0x40, 0x50}        # lost bit repaired by re-extraction


def test_delta_full_resync_on_fresh_master():
    client = AddressDeltaCursor(client_id=b"\x07" * 16)
    server = ServerCursor()
    client.on_cursor(*server.summary())
    _exchange(client, server, {0x10, 0x20})
    fresh = ServerCursor()  # restarted master, cursors lost
    client.on_cursor(*fresh.summary())
    assert client.wants_full
    addrs, delta = _exchange(client, fresh, {0x30})
    assert delta.full and delta.table_base == 0
    assert addrs == {0x10, 0x20, 0x30}  # the whole bitmap came across
    assert client.full_resyncs == 1


def test_delta_pending_fold_on_cursor_match():
    """Master processed the frame but the ack (work frame) was lost:
    the reconnect cursor matches acked+pending and the client folds."""
    client = AddressDeltaCursor(client_id=b"\x07" * 16)
    server = ServerCursor()
    client.on_cursor(*server.summary())
    body = client.encode_result(b"t", Ok(), {0x10})
    _, delta, _, _ = wire.decode_result_delta(body)
    server.apply(delta)          # master merged it...
    # ...but no ack arrived.  Reconnect: server names the folded state.
    client.on_cursor(*server.summary())
    assert not client.wants_full
    _, d2 = _exchange(client, server, {0x10})
    assert not d2.pairs          # nothing re-sent: the fold happened


def test_server_cursor_rejects_protocol_violations():
    server = ServerCursor()
    with pytest.raises(ValueError):   # registration gap
        server.apply(wire.DeltaFrame(False, 5, [0x1], []))
    server.apply(wire.DeltaFrame(False, 0, [0x1, 0x2], [(0, 0b11)]))
    with pytest.raises(ValueError):   # conflicting re-registration
        server.apply(wire.DeltaFrame(False, 0, [0x999], []))
    with pytest.raises(ValueError):   # bit beyond the table
        server.apply(wire.DeltaFrame(False, 2, [], [(1, 0x1)]))
    # idempotent re-send of the identical registration is fine
    assert server.apply(wire.DeltaFrame(False, 0, [0x1, 0x2],
                                        [(0, 0b01)])) == {0x1}


def test_cursor_state_persistence_roundtrip():
    server = ServerCursor()
    server.apply(wire.DeltaFrame(False, 0, [0xA, 0xB, 0xC], [(0, 0b101)]))
    clone = ServerCursor.from_state(server.state())
    assert clone.summary() == server.summary()
    assert clone.table == server.table
    # digest canonicalization: allocation length differences never
    # change the summary
    n = len(server.table)
    assert cursor_digest(server.table, np.zeros(64, np.uint32)
                         | server.words[0], n) \
        == cursor_digest(server.table, server.words, n)


def test_revoked_results_never_carry_repair_bits():
    """Timeout/overlay-full results go out as EMPTY bodies even when
    unacked bits are owed: the master credits a frame's addresses to
    its testcase, and a hang must never earn corpus admission.  The
    owed bits ride the next non-revoked frame instead."""
    client = AddressDeltaCursor(client_id=b"\x07" * 16)
    server = ServerCursor()
    client.on_cursor(*server.summary())
    client.encode_result(b"t", Ok(), {0x10})  # sent, LOST (no ack)
    client.on_cursor(*server.summary())       # reconnect: still unacked
    body = client.encode_empty(b"hang", Timedout())
    _, delta, result, _ = wire.decode_result_delta(body)
    assert isinstance(result, Timedout)
    assert not delta.pairs and not delta.addrs and not delta.full
    assert server.apply(delta) == set()
    # the repair lands on the next healthy result
    addrs, delta = _exchange(client, server, {0x20})
    assert addrs == {0x10, 0x20}


def test_server_cursor_eviction_is_bounded_and_lru(tmp_path):
    from wtf_tpu.dist.server import _Conn

    rng = random.Random(3)
    server = Server("tcp://127.0.0.1:0/", TlvStructureMutator(rng, 16),
                    Corpus(rng=rng), cursor_cap=2)
    conns = []
    for i in range(3):
        conn = _Conn()
        conn.client_id = f"{i:032x}"
        conns.append(conn)
        server._cursor_for(conn)
    server._cursors["0" * 31 + "0"].last_seen = 0.0  # oldest: client 0
    server._evict_cursors()
    assert len(server._cursors) == 2
    assert "0" * 31 + "0" not in server._cursors
    assert server.registry.counter("fleet.cursor_evictions").value == 1
    # a cursor with a LIVE connection is never evicted, even when oldest
    server._clients = {object(): conns[1]}
    server._cursors[conns[1].client_id].last_seen = 0.0
    server.cursor_cap = 1
    server._evict_cursors()
    assert conns[1].client_id in server._cursors
    assert len(server._cursors) == 1


def test_pairs_of_sparse_encoding():
    words = np.zeros(8, np.uint32)
    words[2] = 0x80000001
    words[7] = 5
    assert pairs_of(words) == [(2, 0x80000001), (7, 5)]


# ---------------------------------------------------------------------------
# content-addressed store
# ---------------------------------------------------------------------------

def test_store_put_dedup_and_journal(tmp_path):
    reg = Registry()
    store = FleetStore(tmp_path / "store", registry=reg)
    digest, new = store.put(b"hello")
    assert new and digest == hex_digest(b"hello")
    assert store.blob_path(digest).read_bytes() == b"hello"
    assert store.blob_path(digest).parent.name == digest[:2]  # fanout
    assert store.put(b"hello") == (digest, False)  # content dedup
    assert reg.counter("fleet.store_dedup").value == 1
    # journal reload sees the same content
    again = FleetStore(tmp_path / "store")
    assert again.has(digest) and len(again) == 1
    assert again.get(digest) == b"hello"


def test_store_bucket_dedup(tmp_path):
    reg = Registry()
    store = FleetStore(tmp_path / "store", registry=reg)
    d1, new1 = store.put(b"crash-a", kind="crash", name="crash-w-0x1",
                         bucket="write.0x1.aa")
    assert new1
    # DIFFERENT bytes, same triage bucket: not persisted, not journaled
    d2, new2 = store.put(b"crash-b", kind="crash", name="crash-w-0x1",
                         bucket="write.0x1.aa")
    assert not new2 and not store.has(d2)
    assert reg.counter("fleet.bucket_dedup").value == 1
    # a novel bucket persists
    _, new3 = store.put(b"crash-c", kind="crash", bucket="read.0x2.bb")
    assert new3
    assert set(store.buckets) == {"write.0x1.aa", "read.0x2.bb"}


def test_store_torn_journal_tail_tolerated(tmp_path):
    store = FleetStore(tmp_path / "store")
    store.put(b"one")
    store.put(b"two")
    with open(store.journal_path, "a") as fh:
        fh.write('{"digest": "torn-mid-')  # kill mid-append
    reloaded = FleetStore(tmp_path / "store")
    assert len(reloaded) == 2


def test_store_namespaces_are_isolated(tmp_path):
    root = FleetStore(tmp_path / "store")
    a = root.namespace("tenant-a")
    b = root.namespace("tenant-b")
    da, _ = a.put(b"payload")
    assert a.has(da) and not b.has(da) and not root.has(da)
    assert (tmp_path / "store" / "tenant-a").is_dir()


def test_store_fsck_recovers_torn_blob_and_lost_journal(tmp_path):
    """The RUNBOOK drill: a torn blob is quarantined, a lost journal is
    rebuilt from the surviving blobs."""
    store = FleetStore(tmp_path / "store")
    d_ok, _ = store.put(b"intact")
    d_torn, _ = store.put(b"will-be-torn-by-a-kill")
    # tear one blob behind the store's back (pre-atomic writer / disk rot)
    store.blob_path(d_torn).write_bytes(b"will-")
    report = FleetStore(tmp_path / "store").verify(repair=True)
    assert report["torn"] == [d_torn]
    recovered = FleetStore(tmp_path / "store")
    assert recovered.has(d_ok) and not recovered.has(d_torn)
    assert recovered.get(d_ok) == b"intact"
    # lost journal: fsck re-journals orphan blobs
    recovered.journal_path.unlink()
    rebuilt = FleetStore(tmp_path / "store")
    assert len(rebuilt) == 0
    report = rebuilt.verify(repair=True)
    assert report["orphans"] == [d_ok]
    assert FleetStore(tmp_path / "store").get(d_ok) == b"intact"


def test_corpus_outputs_is_a_view_of_the_store(tmp_path):
    store = FleetStore(tmp_path / "store")
    corpus = Corpus(outputs_dir=tmp_path / "outputs", store=store)
    assert corpus.add(b"finding")
    digest = hex_digest(b"finding")
    flat = tmp_path / "outputs" / digest
    assert flat.read_bytes() == b"finding"        # flat view intact
    assert store.get(digest) == b"finding"        # store is the record
    assert not corpus.add(b"finding")             # dedup unchanged


# ---------------------------------------------------------------------------
# wire back-compat matrix (emu campaigns over real sockets)
# ---------------------------------------------------------------------------

def _addr(tmp_path: Path, tag: str) -> str:
    return f"unix://{tmp_path}/{tag}.sock"


def _run_campaign(tmp_path, tag, runs=60, **client_kwargs):
    """One seeded master + one emu client; returns the server (its
    aggregate coverage is the matrix comparison point)."""
    rng = random.Random(0xFEE7)
    corpus = Corpus(rng=rng)
    corpus.add(BENIGN)
    server = Server(_addr(tmp_path, tag), TlvStructureMutator(rng, 128),
                    corpus, crashes_dir=tmp_path / f"crashes-{tag}",
                    runs=runs)
    thread = threading.Thread(target=server.run,
                              kwargs={"max_seconds": 120})
    thread.start()
    backend = create_backend("emu", demo_tlv.build_snapshot(),
                             limit=50_000)
    backend.initialize()
    registry = Registry()
    client = Client(backend, demo_tlv.TARGET, _addr(tmp_path, tag),
                    registry=registry, **client_kwargs)
    client.run()
    thread.join(timeout=120)
    assert not thread.is_alive()
    assert server.stats.testcases == runs  # zero lost (no seed paths)
    server._client_registry = registry
    return server


def test_wire_backcompat_matrix(tmp_path):
    """v1 raw, whole-bitmap WTF2, and delta WTF3 clients against the
    delta-speaking master: identical seeds -> byte-exact aggregate
    coverage vs the single-client serial (v1) run."""
    serial = _run_campaign(tmp_path, "v1", wire_v1=True)
    v2 = _run_campaign(tmp_path, "v2", cov_delta=False)
    v3 = _run_campaign(tmp_path, "v3", cov_delta=True)
    ref = sorted(serial.coverage)
    assert sorted(v2.coverage) == ref
    assert sorted(v3.coverage) == ref
    assert len(ref) > 0
    # the delta campaign actually spoke WTF3 and saved coverage bytes
    reg = v3._client_registry
    assert v3.registry.counter("fleet.delta_frames").value == 60
    assert reg.counter("dist.cov_bytes_delta").value > 0
    assert reg.counter("dist.cov_bytes_bitmap").value > 0
    # (the >=10x byte ratio is a property of fleet-scale workloads —
    # asserted by the soak, where whole coverage sets are large; this
    # tiny campaign only proves both meters run)
    # crash sets (by digest-named files) agree too
    for tag in ("v2", "v3"):
        assert (sorted(p.name for p in
                       (tmp_path / f"crashes-{tag}").iterdir())
                == sorted(p.name for p in
                          (tmp_path / "crashes-v1").iterdir()))


def test_delta_client_reconnect_zero_lost(tmp_path):
    """Scheduled mid-campaign resets on a WTF3 link: reconnect +
    re-handshake (TAG_CURSOR), master reclaims in-flight work, and the
    aggregate still matches the serial run byte-exactly — the delta
    path's loss story is re-extraction against the resumed cursor."""
    from wtf_tpu.testing.faultinject import (
        FaultPlan, RESET, chaos_dialing,
    )

    serial = _run_campaign(tmp_path, "serial")
    rng = random.Random(0xFEE7)
    corpus = Corpus(rng=rng)
    corpus.add(BENIGN)
    server = Server(_addr(tmp_path, "chaos"),
                    TlvStructureMutator(rng, 128), corpus, runs=60)
    thread = threading.Thread(target=server.run,
                              kwargs={"max_seconds": 120})
    thread.start()
    backend = create_backend("emu", demo_tlv.build_snapshot(),
                             limit=50_000)
    backend.initialize()
    registry = Registry()
    plan = FaultPlan([{12: RESET}, {30: RESET}, {}, {}],
                     delay_secs=0.002)
    with chaos_dialing(plan):
        client = Client(backend, demo_tlv.TARGET,
                        _addr(tmp_path, "chaos"), registry=registry,
                        max_retry_secs=30.0, cov_delta=True,
                        retry_rng=random.Random(3))
        client.run()
    thread.join(timeout=120)
    assert not thread.is_alive()
    assert plan.count_fired(RESET) >= 1
    assert registry.counter("dist.retries").value >= 1
    assert server.stats.testcases == 60
    assert sorted(server.coverage) == sorted(serial.coverage)


def test_cursor_resume_vs_fresh_master(tmp_path):
    """Master restart, both ways: WITH the persisted cursor state the
    reconnecting client resumes sparse deltas (zero full resyncs);
    WITHOUT it the cursor mismatch forces exactly one whole-bitmap
    resync — and the aggregate is complete either way."""
    model = CoverageModel(common=64)
    cov_path = tmp_path / "coverage.cov"

    def serve(tag, runs, coverage_path):
        rng = random.Random(5)
        server = Server(_addr(tmp_path, tag), TlvStructureMutator(rng, 32),
                        Corpus(rng=rng), runs=runs,
                        coverage_path=coverage_path)
        server.paths = [b"\x01\x04SEED"]
        thread = threading.Thread(target=server.run,
                                  kwargs={"max_seconds": 60})
        thread.start()
        return server, thread

    registry = Registry()
    sim = SimClient(_addr(tmp_path, "m1"), model, "delta", 1, registry)
    server1, t1 = serve("m1", 8, cov_path)
    sim.connect()
    while sim.step():
        pass
    t1.join(60)
    assert server1.registry.counter("fleet.full_resyncs").value == 0
    assert json.loads(cov_path.read_text())["cursors"]  # persisted

    # restarted master WITH the cursor file: sparse resume
    server2, t2 = serve("m2", 4, cov_path)
    sim2 = SimClient(_addr(tmp_path, "m2"), model, "delta", 1, registry)
    sim2.link.cursor = sim.link.cursor  # same node identity + state
    sim2.local = sim.local              # ...and execution history
    sim2.connect()
    while sim2.step():
        pass
    t2.join(60)
    assert server2.registry.counter("fleet.cursor_resumes").value == 1
    assert server2.registry.counter("fleet.full_resyncs").value == 0

    # restarted master WITHOUT it: cursor reset -> one full resync
    server3, t3 = serve("m3", 4, None)
    sim3 = SimClient(_addr(tmp_path, "m3"), model, "delta", 1, registry)
    sim3.link.cursor = sim.link.cursor
    sim3.local = sim2.local
    sim3.connect()
    while sim3.step():
        pass
    t3.join(60)
    assert server3.registry.counter("fleet.full_resyncs").value == 1
    # complete despite the reset: every address the client ever saw that
    # rode a post-reset frame is mapped; the full frame carried the rest
    assert server3.coverage <= sim3.local


def test_malformed_delta_frame_drops_node_not_master(tmp_path):
    """A delta frame violating the cursor protocol (table gap) drops
    that node and requeues its work — reactor stays up, nothing
    counted."""
    rng = random.Random(5)
    server = Server(_addr(tmp_path, "bad"), TlvStructureMutator(rng, 16),
                    Corpus(rng=rng), runs=0)
    server.paths = [BENIGN]
    thread = threading.Thread(target=server.run,
                              kwargs={"max_seconds": 60})
    thread.start()
    sock = wire.dial(_addr(tmp_path, "bad"), retry_for=10.0)
    wire.send_msg(sock, wire.encode_hello_delta(1, b"\x09" * 16))
    got = wire.recv_msg(sock)
    assert got[0] == wire.TAG_CURSOR
    tag, tc = wire.recv_tagged(sock)
    assert tag == wire.TAG_WORK
    bad = wire.encode_result_delta(
        tc, Ok(), wire.DeltaFrame(False, 99, [0x1], []))
    wire.send_msg(sock, bytes((wire.TAG_COVDELTA,)) + bad)
    thread.join(timeout=60)
    sock.close()
    assert not thread.is_alive()
    assert server.stats.testcases == 0
    assert list(server.paths) == [BENIGN]


def test_coverage_write_is_dirty_flagged(tmp_path):
    """Satellite: the aggregate file is written only when something
    changed since the last persist."""
    rng = random.Random(1)
    server = Server("tcp://127.0.0.1:0/", TlvStructureMutator(rng, 16),
                    Corpus(rng=rng),
                    coverage_path=tmp_path / "coverage.cov")
    server._write_coverage()
    assert not (tmp_path / "coverage.cov").exists()  # nothing to say
    server._account_result(b"t", {0x10, 0x20}, Ok())
    server._write_coverage()
    assert server.registry.counter("fleet.coverage_writes").value == 1
    server._write_coverage()                      # unchanged: no write
    assert server.registry.counter("fleet.coverage_writes").value == 1
    server._account_result(b"t", {0x10}, Ok())    # no new coverage
    server._write_coverage()
    assert server.registry.counter("fleet.coverage_writes").value == 1
    server._account_result(b"t", {0x30}, Ok())
    server._write_coverage()
    assert server.registry.counter("fleet.coverage_writes").value == 2
    doc = json.loads((tmp_path / "coverage.cov").read_text())
    assert doc["addresses"] == [0x10, 0x20, 0x30]


def test_server_crash_intake_bucket_dedup(tmp_path):
    """Two crashes with the SAME triage bucket but different bytes and
    names: one file persisted (digest-named), one bucket-dedup hit."""
    rng = random.Random(2)
    crashes = tmp_path / "crashes"
    server = Server("tcp://127.0.0.1:0/", TlvStructureMutator(rng, 16),
                    Corpus(rng=rng), crashes_dir=crashes)
    server._account_result(b"AAAA", set(), Crash("crash-write-0x10"),
                           bucket="write.0x10.aa")
    server._account_result(b"BBBB", set(), Crash("crash-write-0x20"),
                           bucket="write.0x10.aa")
    saved = list(crashes.iterdir())
    assert [p.name for p in saved] == [hex_digest(b"AAAA")]
    assert server.registry.counter("fleet.bucket_dedup").value == 1
    assert server.stats.crashes == 2  # both counted, one persisted
    # a different bucket persists its own digest-named file
    server._account_result(b"CCCC", set(), Crash("crash-read-0x30"),
                           bucket="read.0x30.bb")
    assert sorted(p.name for p in crashes.iterdir()) \
        == sorted([hex_digest(b"AAAA"), hex_digest(b"CCCC")])


def _batch_campaign(tmp_path, tag, mux, runs=24, **client_kwargs):
    """Seeded master + one 4-lane TPU batch node; returns the server."""
    from wtf_tpu.dist.client import BatchClient

    rng = random.Random(0xFEE7)
    corpus = Corpus(rng=rng)
    corpus.add(BENIGN)
    server = Server(_addr(tmp_path, tag), TlvStructureMutator(rng, 128),
                    corpus, runs=runs)
    thread = threading.Thread(target=server.run,
                              kwargs={"max_seconds": 180})
    thread.start()
    backend = create_backend("tpu", demo_tlv.build_snapshot(),
                             n_lanes=4, limit=50_000)
    backend.initialize()
    node = BatchClient(backend, demo_tlv.TARGET, _addr(tmp_path, tag),
                       mux=mux, registry=Registry(), **client_kwargs)
    node.run()
    thread.join(timeout=180)
    assert not thread.is_alive()
    assert server.stats.testcases == runs
    return server


def test_batch_client_delta_matches_bitmap(tmp_path):
    """The TPU batch node's delta paths — per-link address cursors
    (1 fd/lane) and the mux link's bitmap cursor (decode-cache bit
    space, no address decode) — end with the same aggregate coverage
    as the whole-bitmap v2 node at equal seeds."""
    ref = _batch_campaign(tmp_path, "b-v2", mux=False, cov_delta=False)
    per_link = _batch_campaign(tmp_path, "b-d1", mux=False,
                               cov_delta=True)
    muxed = _batch_campaign(tmp_path, "b-dm", mux=True, cov_delta=True)
    want = sorted(ref.coverage)
    assert len(want) > 0
    assert sorted(per_link.coverage) == want
    assert sorted(muxed.coverage) == want
    # the mux node spoke ONE delta connection for the whole lane batch
    assert muxed.registry.counter("fleet.delta_frames").value > 0
    assert len(muxed._cursors) == 1
    assert len(per_link._cursors) == 4


# ---------------------------------------------------------------------------
# soak (small) — the big one runs via `make fleet-smoke` / fleet soak
# ---------------------------------------------------------------------------

def test_fleet_soak_small(tmp_path):
    report = run_soak(tmp_path, clients=16, runs_per_client=25,
                      threads=4, seed=0xF1EE7, min_ratio=10.0)
    assert report["accounted"] == report["runs"] + 2
    assert report["delta_ratio"] >= 10.0
    assert report["reclaimed"] >= 1
    assert report["store_puts"] > 0


def test_telemetry_report_fleet_section(tmp_path):
    import sys

    sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
    import telemetry_report

    from wtf_tpu.telemetry import EventLog

    tdir = tmp_path / "telemetry"
    events = EventLog(tdir / "events.jsonl")
    registry = Registry()
    registry.counter("fleet.delta_frames").inc(10)
    registry.counter("fleet.store_puts").inc(4)
    registry.counter("fleet.store_dedup").inc(2)
    registry.counter("fleet.bucket_dedup").inc(1)
    registry.counter("campaign.reshards").inc(1)
    registry.counter("campaign.crashes").inc(2)
    registry.counter("dist.cov_bytes_delta").inc(100)
    registry.counter("dist.cov_bytes_bitmap").inc(4000)
    events.emit("run-end", metrics=registry.dump())
    events.close()
    fleet = telemetry_report.summarize(tdir)["fleet"]
    assert fleet["delta_frames"] == 10
    assert fleet["cov_bytes_saved"] == 3900
    assert fleet["delta_ratio"] == 40.0
    assert fleet["store_dedup_hits"] == 2
    assert fleet["bucket_dedup_rate"] == 0.5
    assert fleet["reshards"] == 1


# ---------------------------------------------------------------------------
# elastic resharding (the acceptance parity bar)
# ---------------------------------------------------------------------------

def _fingerprint(loop):
    cov, edge = loop.backend.coverage_state()
    return (cov.tobytes(), edge.tobytes(), loop._coverage(),
            [hex_digest(d) for d in loop.corpus],
            sorted(loop.crash_buckets), sorted(loop.crash_names),
            loop.stats.testcases)


def test_elastic_reshard_bit_identical(tmp_path):
    """A seeded devmangle campaign checkpointed at a batch boundary by
    the in-master policy hook and resumed under a DIFFERENT
    --mesh-devices count finishes with bit-identical coverage,
    crash-bucket and corpus state to the uninterrupted run (the
    test_resume/test_devmut shapes: compile-cache shared)."""
    from wtf_tpu.analysis.trace import build_tlv_campaign
    from wtf_tpu.fleet.elastic import ScheduledReshard, run_elastic

    BUILD = dict(n_lanes=8, limit=20_000, chunk_steps=128,
                 overlay_slots=16, mutator="devmangle", seed=0x55)
    runs = 8 * 5

    ref = build_tlv_campaign(**BUILD)
    ref.fuzz(runs)
    want = _fingerprint(ref)

    ckpt = tmp_path / "ckpt"

    def build_loop(mesh_devices):
        kwargs = dict(BUILD)
        if mesh_devices:
            kwargs["mesh_devices"] = mesh_devices
        return build_tlv_campaign(**kwargs)

    policy = ScheduledReshard({2: 8})
    loop = run_elastic(build_loop, runs, ckpt, policy=policy)
    assert policy.fired == [(2, 8)]
    assert loop.backend.mesh.size == 8  # really moved placements
    assert _fingerprint(loop) == want
    assert loop.registry.counter("campaign.reshards").value == 1


def test_reshard_refuses_indivisible_lanes(tmp_path):
    from wtf_tpu.fleet.elastic import validate_placement

    with pytest.raises(ValueError, match="not divisible"):
        validate_placement({"config": {"lanes": 8}}, 3)
    validate_placement({"config": {"lanes": 8}}, 4)  # fine
