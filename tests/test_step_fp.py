"""Device-executor differentials for SSE/SSE2 floating point (OPC_SSEFP).

Round-4 made the oracle's FP bit-exact against the live host CPU
(tests/test_ssefp.py); this file closes the loop for the DEVICE step
(VERDICT r4 item 2): the same op/value grids now assert that
interp/step.py produces the oracle's exact XMM/GPR/flag state — which
the hardware battery already pins to the metal.  Three-way, by
transitivity: hardware == oracle == device.

The reference executes all of this inside bochscpu's fast path
(SURVEY.md §2.6); with this file green, FP-touching lanes no longer
leave the device fast path either.
"""

import random
import struct

import pytest

from emurunner import DATA_BASE
from test_ssefp import F32_PAIRS, F64, _sse_snippet
from test_step import assert_matches_oracle

SD_OPS = ["addsd", "subsd", "mulsd", "divsd", "minsd", "maxsd"]
SS_OPS = ["addss", "subss", "mulss", "divss", "minss", "maxss"]
PS_OPS = ["addps", "mulps", "subps", "minps", "maxps", "divps"]


def _dev(snippet, regs):
    assert_matches_oracle(snippet + "\nhlt", regs=regs)


@pytest.mark.parametrize("op", SD_OPS + ["sqrtsd", "cmpeqsd", "cmpltsd",
                                         "cmpnlesd", "cmpunordsd"])
@pytest.mark.parametrize("a_name,b_name", [
    ("one", "two"), ("pi", "neg"), ("pzero", "nzero"), ("pinf", "ninf"),
    ("pinf", "pinf"), ("qnan", "one"), ("one", "qnan"), ("snan", "one"),
    ("one", "snan"), ("qnan", "snan"), ("denorm", "denorm"), ("big", "big"),
])
def test_sd_device_vs_oracle(op, a_name, b_name):
    kind = "cmp" if op.startswith("cmp") else (
        "unary" if op.startswith("sqrt") else None)
    _dev(_sse_snippet(op, kind),
         {"rax": F64[a_name], "rcx": F64[b_name]})


@pytest.mark.parametrize("op", SS_OPS + ["sqrtss"])
@pytest.mark.parametrize("a,b", [
    (0x3F800000, 0x40000000), (0x7FC00001, 0x3F800000),
    (0x7F800001, 0x3F800000), (0xFF800000, 0x7F800000),
    (0x80000000, 0x00000000), (0x00000001, 0x7F7FFFFF),
])
def test_ss_device_vs_oracle(op, a, b):
    kind = "unary" if op.startswith("sqrt") else None
    _dev(_sse_snippet(op, kind), {"rax": a, "rcx": b})


@pytest.mark.parametrize("op", PS_OPS + ["sqrtps", "cmpleps"])
@pytest.mark.parametrize("lo_a,hi_a,lo_b,hi_b", [
    ("one_two", "nan_inf", "zeros", "denorm_big"),
    ("snan_neg", "one_two", "one_two", "nan_inf"),
])
def test_ps_device_vs_oracle(op, lo_a, hi_a, lo_b, hi_b):
    kind = "cmp" if op.startswith("cmp") else (
        "unary" if op.startswith("sqrt") else None)
    _dev(_sse_snippet(op, kind, packed=True), {
        "rax": F32_PAIRS[lo_a], "rdx": F32_PAIRS[hi_a],
        "rcx": F32_PAIRS[lo_b], "rsi": F32_PAIRS[hi_b]})


@pytest.mark.parametrize("op", ["ucomisd", "comisd", "ucomiss", "comiss"])
@pytest.mark.parametrize("a_name,b_name", [
    ("one", "two"), ("two", "one"), ("one", "one"), ("qnan", "one"),
    ("one", "snan"), ("pzero", "nzero"), ("pinf", "big"), ("ninf", "pinf"),
])
def test_ucomi_device_vs_oracle(op, a_name, b_name):
    # the ss forms just compare the low 4 of the same f64 patterns —
    # payload reinterpretation is exactly what the bit-level path must get
    # right, and assert_matches_oracle checks rflags
    _dev(f"movq xmm0, rax\nmovq xmm1, rcx\n{op} xmm0, xmm1",
         {"rax": F64[a_name], "rcx": F64[b_name]})


@pytest.mark.parametrize("snippet_op", [
    "cvtsi2sd xmm0, rcx", "cvtsi2ss xmm0, rcx",
    "cvtsi2sd xmm0, ecx", "cvtsi2ss xmm0, ecx",
])
@pytest.mark.parametrize("ival", [
    0, 1, 2**63 - 1, 2**64 - 512, 0x8000000000000000,
    12345678901234567, 0xFFFFFFFF80000000,
])
def test_cvtsi2_device_vs_oracle(snippet_op, ival):
    _dev(f"pxor xmm0, xmm0\n{snippet_op}", {"rcx": ival})


@pytest.mark.parametrize("op", ["cvttsd2si rax, xmm1", "cvtsd2si rax, xmm1",
                                "cvttsd2si eax, xmm1", "cvtsd2si eax, xmm1",
                                "cvttss2si rax, xmm1", "cvtss2si eax, xmm1"])
@pytest.mark.parametrize("b_name", [
    "one", "half", "pi", "neg", "big", "qnan", "pinf", "nzero", "tiny",
])
def test_cvt2si_device_vs_oracle(op, b_name):
    _dev(f"movq xmm1, rcx\nxor eax, eax\n{op}", {"rcx": F64[b_name]})


@pytest.mark.parametrize("op", [
    "cvtss2sd xmm0, xmm1", "cvtsd2ss xmm0, xmm1", "cvtdq2ps xmm0, xmm1",
    "cvtps2dq xmm0, xmm1", "cvttps2dq xmm0, xmm1", "cvtdq2pd xmm0, xmm1",
    "cvtpd2dq xmm0, xmm1", "cvttpd2dq xmm0, xmm1", "cvtps2pd xmm0, xmm1",
    "cvtpd2ps xmm0, xmm1",
])
@pytest.mark.parametrize("bits_lo,bits_hi", [
    (0x3FF0000000000000, 0x40091EB851EB851F),
    (0x7FF800000000BEEF, 0xC024000000000000),
    (0x41DFFFFFFFC00000, 0x00000000499602D2),
    (0xFFFFFFFF7FFFFFFF, 0x8000000180000000),
])
def test_cvt_shapes_device_vs_oracle(op, bits_lo, bits_hi):
    _dev("movq xmm1, rax\nmovq xmm2, rdx\npunpcklqdq xmm1, xmm2\n"
         "pxor xmm0, xmm0\n" + op,
         {"rax": bits_lo, "rdx": bits_hi})


@pytest.mark.parametrize("op", [
    "shufps xmm0, xmm1, 0x1B", "shufps xmm0, xmm1, 0xE4",
    "shufpd xmm0, xmm1, 0x1", "shufpd xmm0, xmm1, 0x2",
    "unpcklps xmm0, xmm1", "unpckhps xmm0, xmm1",
    "unpcklpd xmm0, xmm1", "unpckhpd xmm0, xmm1",
])
def test_shuffle_device_vs_oracle(op):
    _dev("movq xmm0, rax\nmovq xmm2, rdx\npunpcklqdq xmm0, xmm2\n"
         "movq xmm1, rcx\nmovq xmm3, rsi\npunpcklqdq xmm1, xmm3\n" + op,
         {"rax": 0x1111111122222222, "rdx": 0x3333333344444444,
          "rcx": 0x5555555566666666, "rsi": 0x7777777788888888})


def test_ssefp_memory_operands_device():
    """Scalar + packed memory sources ride the l1 window with the oracle's
    exact read sizes (scalar elem / packed 16)."""
    data = struct.pack("<dd", 1.5, 2.25) + struct.pack("<4f", 1, 2, 3, 4)
    assert_matches_oracle(f"""
        mov rbx, {DATA_BASE}
        movsd xmm0, [rbx]
        addsd xmm0, [rbx+8]
        movups xmm1, [rbx+16]
        addps xmm1, [rbx+16]
        cvtsi2sd xmm2, dword ptr [rbx+16]
        ucomisd xmm0, [rbx+8]
        hlt""", data={DATA_BASE: data.ljust(0x1000, b"\x00")})


@pytest.mark.parametrize("op", [
    "vaddsd xmm0, xmm0, xmm1", "vmulsd xmm0, xmm0, xmm1",
    "vdivsd xmm0, xmm0, xmm1", "vsqrtsd xmm0, xmm0, xmm1",
    "vucomisd xmm0, xmm1", "vcvtsi2sd xmm0, xmm0, rcx",
])
@pytest.mark.parametrize("a_name,b_name", [("pi", "neg"), ("qnan", "one")])
def test_vex128_fp_device_vs_oracle(op, a_name, b_name):
    _dev(f"movq xmm0, rax\nmovq xmm1, rcx\n{op}",
         {"rax": F64[a_name], "rcx": F64[b_name]})


@pytest.mark.parametrize("op", ["addsd", "mulsd", "divsd", "minsd",
                                "cmplesd"])
def test_sd_random_battery_device(op):
    """Seeded random sweep per op (smaller than the hw battery: each case
    is a full device run).  Shapes cover NaN-payload and denormal space."""
    rng = random.Random(hash(op) & 0xFFFFFF)
    kind = "cmp" if op.startswith("cmp") else None
    snippet = _sse_snippet(op, kind)
    for _ in range(12):
        shape = rng.randrange(3)
        if shape == 0:
            a, b = rng.getrandbits(64), rng.getrandbits(64)
        elif shape == 1:
            a = 0x7FF0000000000000 | rng.getrandbits(52) | (
                rng.getrandbits(1) << 63)
            b = rng.getrandbits(64)
        else:
            a = rng.getrandbits(52) | (rng.getrandbits(1) << 63)
            b = a ^ rng.getrandbits(3)
        _dev(snippet, {"rax": a, "rcx": b})


@pytest.mark.parametrize("op", ["addps", "divps"])
def test_ps_random_battery_device(op):
    rng = random.Random(~hash(op) & 0xFFFFFF)
    snippet = _sse_snippet(op, None, packed=True)
    for _ in range(8):
        regs = {r: rng.getrandbits(64) for r in ("rax", "rdx", "rcx", "rsi")}
        _dev(snippet, regs)


def test_fp_lane_no_fallback():
    """An FP-heavy loop must complete with ZERO oracle fallbacks — the
    round-4 situation (every SSE-FP insn a per-lane host round trip) is
    the regression this guards against."""
    from test_step import make_runner

    snippet = f"""
        mov rbx, {DATA_BASE}
        movsd xmm0, [rbx]
        mov ecx, 50
    loop_top:
        addsd xmm0, [rbx+8]
        mulsd xmm0, [rbx+16]
        sqrtsd xmm1, xmm0
        cvttsd2si rax, xmm1
        dec ecx
        jnz loop_top
        movsd [rbx+24], xmm0
        hlt"""
    data = struct.pack("<ddd", 100.0, 3.5, 1.0625).ljust(0x1000, b"\x00")
    runner = make_runner(snippet, data={DATA_BASE: data}, n_lanes=4)
    runner.run()
    assert runner.stats["fallbacks"] == 0, (
        f"FP loop fell back to the oracle {runner.stats['fallbacks']} times")
