"""Megachunk window tests (wtf_tpu/fuzz/megachunk.py).

The acceptance contract (ISSUE 14): a devmangle campaign driven through
one-dispatch multi-batch windows is bit-identical to the batch-at-a-time
device loop at equal seeds — coverage/edge bytes, crash buckets, corpus
digests — for any window size, on a single device and on a mesh; the
PR-8 checkpoint/resume contract survives (kill at any batch boundary,
resume bit-identically); and the devmut seed stream is neither
double-generated nor skewed when generation moves in-graph
(bit-exactness vs hostref at any batch count).
"""

import jax
import numpy as np
import pytest

from wtf_tpu.analysis.trace import build_tlv_campaign
from wtf_tpu.resume import load_campaign, restore_campaign
from wtf_tpu.testing.faultinject import fuzz_until_killed
from wtf_tpu.utils.hashing import hex_digest

# test_devmut/test_resume shapes: compile-cache-shared across the suite
BUILD = dict(n_lanes=8, limit=20_000, chunk_steps=128, overlay_slots=16)


def _fingerprint(loop) -> dict:
    cov, edge = loop.backend.coverage_state()
    return {
        "cov": cov.tobytes(),
        "edge": edge.tobytes(),
        "cov_bits": loop._coverage(),
        "corpus_order": [hex_digest(d) for d in loop.corpus],
        "crashes": sorted(loop.crash_names),
        "buckets": sorted(loop.crash_buckets),
        "testcases": loop.stats.testcases,
        "timeouts": loop.stats.timeouts,
        "new_coverage": loop.stats.new_coverage,
    }


def _campaign(megachunk: int, runs: int, seed: int = 0x5EED, **kw):
    cfg = dict(BUILD)
    cfg.update(kw)
    loop = build_tlv_campaign(mutator="devmangle", seed=seed,
                              megachunk=megachunk, **cfg)
    loop.fuzz(runs)
    return loop


def test_megachunk_window_bit_identical_to_batch_at_a_time():
    """The tentpole parity bar: a B=4 window campaign is byte-identical
    to the B=1 (one-batch-per-dispatch) campaign AND to the legacy
    prelaunch loop at equal seeds — aggregate coverage/edge bitmap
    BYTES, corpus digests in order, crash names/buckets, and every
    counter.

    12 batches, NOT a cold-cache-only handful: the campaign must run
    long enough that new-coverage finds land in IN-GRAPH batches (the
    find-stop seam), because that is where the slab schedule can skew —
    the next window's first batch must sample the slab WITHOUT the
    final harvested batch's finds (the legacy prelaunch lag), which a
    4-batch run whose only find is host-serviced never exercises."""
    runs = BUILD["n_lanes"] * 12
    fp1 = _fingerprint(_campaign(1, runs))
    fp4 = _fingerprint(_campaign(4, runs))
    assert fp4 == fp1
    legacy = _fingerprint(_campaign(0, runs))
    assert legacy == fp1
    assert fp1["cov_bits"] > 0 and fp1["testcases"] == runs
    assert fp1["new_coverage"] > 1  # finds beyond the cold-start window


def test_megachunk_batches_accounting_and_host_spans():
    """A window advances batches_done by its COMPLETED batch count, the
    devmut stream cursor matches (no double-generate), and the device
    wait is fenced under execute/device (the host-share measurement's
    denominator)."""
    runs = BUILD["n_lanes"] * 3
    loop = _campaign(3, runs)
    assert loop.batches_done == loop.mutator._batch
    assert loop.stats.testcases == loop.batches_done * BUILD["n_lanes"]
    secs = loop.registry.spans.seconds("execute/device")
    assert secs > 0.0
    # megachunk consumed the whole campaign: the legacy per-batch device
    # generation span must never have fired
    assert loop.registry.spans.seconds("mutate/device") == 0.0


def test_megachunk_seed_stream_bit_exact_vs_hostref():
    """The no-skew satellite: batch k generated in-graph inside a window
    equals hostref.host_generate(slab, seed, k) byte-for-byte — the
    stream is keyed on the ABSOLUTE batch index, so moving generation
    in-graph cannot double-generate or shift it."""
    from wtf_tpu.devmut import hostref
    from wtf_tpu.devmut.engine import make_generate

    runs = BUILD["n_lanes"] * 3
    loop = _campaign(3, runs)
    mut = loop.mutator
    # regenerate an arbitrary executed batch index through the ENGINE at
    # the as-uploaded slab view and compare with the host reference
    k = 1
    up = mut.corpus.uploaded_state()
    seeds = hostref.lane_seeds(mut.seed, k, mut.n_lanes)
    import jax.numpy as jnp

    cum = np.cumsum(up["weight"], dtype=np.uint64).astype(np.uint32)
    # host reference over the same slab view
    ref_words, ref_lens = hostref.host_generate(
        up["data"], up["lens"], cum, seeds, rounds=mut.rounds)
    dev_words, dev_lens = make_generate(mut.rounds)(
        jnp.asarray(up["data"]), jnp.asarray(up["lens"]),
        jnp.asarray(cum), jnp.asarray(seeds))
    assert np.array_equal(np.asarray(jax.device_get(dev_words)),
                          ref_words)
    assert np.array_equal(np.asarray(jax.device_get(dev_lens)), ref_lens)


def test_megachunk_requires_device_engine_and_limit():
    """Config surface: megachunk without devmangle / without a limit
    fails at construction, not deep into a campaign."""
    import random

    from wtf_tpu.backend import create_backend
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop
    from wtf_tpu.fuzz.native_mutator import best_mangle_mutator
    from wtf_tpu.harness import demo_tlv

    rng = random.Random(7)
    backend = create_backend("tpu", demo_tlv.build_snapshot(),
                             n_lanes=2, limit=1000)
    backend.initialize()
    with pytest.raises(ValueError, match="devmangle"):
        FuzzLoop(backend, demo_tlv.TARGET,
                 best_mangle_mutator(rng, max_len=16), Corpus(rng=rng),
                 megachunk=4)
    from wtf_tpu.devmut.mutator import DevMangleMutator

    backend2 = create_backend("tpu", demo_tlv.build_snapshot(),
                              n_lanes=2, limit=0)
    backend2.initialize()
    demo_tlv.TARGET.init(backend2)
    with pytest.raises(ValueError, match="limit"):
        FuzzLoop(backend2, demo_tlv.TARGET,
                 DevMangleMutator(seed=1, max_len=64), Corpus(rng=rng),
                 megachunk=4)


@pytest.mark.slow
def test_megachunk_checkpoint_killpoint_sweep(tmp_path):
    """PR-8 crash-safety under megachunk windows: with a checkpoint at
    every batch boundary (the cadence caps each window to one batch, so
    every boundary is reachable), kill at EVERY interior boundary and
    resume — final state bit-identical to the uninterrupted windowed
    run."""
    batches = 4
    runs = BUILD["n_lanes"] * batches
    ref = _campaign(4, runs)
    ref_fp = _fingerprint(ref)
    assert ref_fp["cov_bits"] > 0

    for kill_at in range(1, batches):
        ckpt = tmp_path / f"kill{kill_at}"
        victim = build_tlv_campaign(mutator="devmangle", seed=0x5EED,
                                    megachunk=4, **BUILD)
        victim.checkpoint_dir, victim.checkpoint_every = ckpt, 1
        fuzz_until_killed(victim, runs, kill_at_batch=kill_at)

        resumed = build_tlv_campaign(mutator="devmangle", seed=0x5EED,
                                     megachunk=4, **BUILD)
        state, fell_back = load_campaign(ckpt)
        assert not fell_back
        assert restore_campaign(resumed, state, ckpt) == kill_at
        resumed.fuzz(runs)
        fp = _fingerprint(resumed)
        assert fp == ref_fp, f"kill at batch {kill_at}: state diverged"


def test_fused_window_bit_identical_to_ladder_window():
    """The PR-19 tentpole bar: the same equal-seed campaign through
    megachunk windows whose quiesce body is the Pallas fused kernel +
    bounded resume (fused_step=on) is byte-identical to the XLA-ladder
    window campaign — aggregate coverage/edge BYTES, corpus digests in
    order, crash names/buckets, every counter.  The engine split is
    checked too: the fused campaign actually dispatched the kernel
    (device.fused_window_rounds > 0), the ladder one never did."""
    runs = BUILD["n_lanes"] * 12
    ladder = _campaign(3, runs, fused_step="off")
    fused = _campaign(3, runs, fused_step="on")
    assert _fingerprint(fused) == _fingerprint(ladder)
    assert _fingerprint(ladder)["cov_bits"] > 0
    assert ladder.registry.counter(
        "device.fused_window_rounds").value == 0
    assert fused.registry.counter("device.fused_window_rounds").value > 0
    # donation bookkeeping: bytes-saved scales exactly with dispatches
    rounds = fused.registry.counter("device.fused_window_rounds").value
    saved = fused.registry.counter(
        "device.fused_window_bytes_saved").value
    assert saved == rounds * fused.backend._fused_alias_bytes()


def test_fused_window_mesh_parity():
    """Fused windows on the forced 8-device mesh: the shard_map window
    with the kernel inside — per-shard local trip counts folded by the
    lockstep psum — is bit-identical to the single-device fused window
    (and therefore to the ladder window)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs XLA_FLAGS=--xla_force_host_platform_"
                    "device_count=8 (make mesh-smoke environment)")
    runs = BUILD["n_lanes"] * 3
    fp_single = _fingerprint(_campaign(3, runs, fused_step="on"))
    fp_mesh = _fingerprint(_campaign(3, runs, fused_step="on",
                                     mesh_devices=8))
    assert fp_mesh == fp_single


@pytest.mark.slow
def test_fused_window_checkpoint_killpoint_sweep(tmp_path):
    """PR-8 crash-safety with the kernel inside the window: kill at
    EVERY interior batch boundary of a fused-window campaign and resume
    — final state bit-identical to the uninterrupted fused run (which
    its own parity test pins equal to the ladder run)."""
    batches = 4
    runs = BUILD["n_lanes"] * batches
    ref_fp = _fingerprint(_campaign(4, runs, fused_step="on"))
    assert ref_fp["cov_bits"] > 0

    for kill_at in range(1, batches):
        ckpt = tmp_path / f"kill{kill_at}"
        victim = build_tlv_campaign(mutator="devmangle", seed=0x5EED,
                                    megachunk=4, fused_step="on", **BUILD)
        victim.checkpoint_dir, victim.checkpoint_every = ckpt, 1
        fuzz_until_killed(victim, runs, kill_at_batch=kill_at)

        resumed = build_tlv_campaign(mutator="devmangle", seed=0x5EED,
                                     megachunk=4, fused_step="on",
                                     **BUILD)
        state, fell_back = load_campaign(ckpt)
        assert not fell_back
        assert restore_campaign(resumed, state, ckpt) == kill_at
        resumed.fuzz(runs)
        fp = _fingerprint(resumed)
        assert fp == ref_fp, f"kill at batch {kill_at}: state diverged"


def test_megachunk_mesh_parity():
    """Windows on a forced 8-device mesh (conftest forces the virtual
    mesh for the whole suite): the shard_map megachunk — whose
    loop-control scalars must be all-reduced so the shards' while_loops
    stay in lockstep — is bit-identical to the single-device one (and
    therefore to the legacy loop) at equal seeds."""
    if len(jax.devices()) < 8:
        pytest.skip("needs XLA_FLAGS=--xla_force_host_platform_"
                    "device_count=8 (make mesh-smoke environment)")
    runs = BUILD["n_lanes"] * 3
    fp_single = _fingerprint(_campaign(3, runs))
    fp_mesh = _fingerprint(_campaign(3, runs, mesh_devices=8))
    assert fp_mesh == fp_single
