"""fuzz/dirwatch.py + Corpus.load_dir seam tests (ISSUE 6 satellite).

These are the host-side seams the device corpus slab sits on: the
mid-campaign seed-injection watcher and the seed-directory replay
ordering.  Both have exact ordering contracts (biggest-first, the
reference master's server.h:399-414 policy) and determinism contracts
(pinned RNG -> replayable pick sequence) that were previously untested.
"""

import random

import pytest

from wtf_tpu.fuzz.corpus import Corpus, seed_paths
from wtf_tpu.fuzz.dirwatch import DirWatcher


def _write(d, name, data):
    p = d / name
    p.write_bytes(data)
    return p


class TestDirWatcher:
    def test_initial_contents_are_not_new(self, tmp_path):
        _write(tmp_path, "pre", b"x" * 10)
        watcher = DirWatcher(tmp_path)
        assert watcher.poll() == []

    def test_new_files_biggest_first(self, tmp_path):
        watcher = DirWatcher(tmp_path)
        _write(tmp_path, "small", b"a")
        _write(tmp_path, "big", b"b" * 100)
        _write(tmp_path, "mid", b"c" * 10)
        assert [p.name for p in watcher.poll()] == ["big", "mid", "small"]
        # already-reported files never re-appear
        assert watcher.poll() == []
        _write(tmp_path, "later", b"d" * 5)
        assert [p.name for p in watcher.poll()] == ["later"]

    def test_missing_directory_and_subdirs(self, tmp_path):
        watcher = DirWatcher(tmp_path / "absent")
        assert watcher.poll() == []
        watcher2 = DirWatcher(tmp_path)
        (tmp_path / "subdir").mkdir()
        _write(tmp_path, "f", b"data")
        assert [p.name for p in watcher2.poll()] == ["f"]


class TestCorpusLoadDir:
    def test_biggest_first_and_content_dedup(self, tmp_path):
        _write(tmp_path, "a-small", b"s")
        _write(tmp_path, "b-big", b"B" * 64)
        _write(tmp_path, "c-mid", b"m" * 8)
        _write(tmp_path, "d-dup-of-big", b"B" * 64)   # content twin
        corpus = Corpus.load_dir(tmp_path)
        # replay order is size-sorted biggest first, content-deduped
        assert list(corpus) == [b"B" * 64, b"m" * 8, b"s"]
        assert len(corpus) == 3

    def test_seed_paths_keep_dups_census(self, tmp_path):
        _write(tmp_path, "x", b"same")
        _write(tmp_path, "y", b"same")
        deduped = seed_paths([tmp_path])
        census = seed_paths([tmp_path], keep_dups=True)
        assert len(deduped) == 1
        assert len(census) == 2
        # digests agree between the two modes
        assert {d for _, d in census} == {d for _, d in deduped}

    def test_pick_sequence_deterministic_under_pinned_rng(self, tmp_path):
        """The device-corpus seeding path relies on this: load_dir with a
        pinned RNG must yield an identical corpus AND an identical pick
        stream across runs (mutation-stream reproducibility)."""
        for i in range(5):
            _write(tmp_path, f"seed{i}", bytes([i]) * (i + 1))
        runs = []
        for _ in range(2):
            corpus = Corpus.load_dir(tmp_path, rng=random.Random(0x5EED))
            runs.append([corpus.pick() for _ in range(16)])
        assert runs[0] == runs[1]
        assert len(set(runs[0])) > 1   # actually random over the set

    def test_load_dir_items_ordering_feeds_device_slab(self, tmp_path):
        """Iteration order (what DevMangleMutator.seed_from consumes) is
        the replay order — stable across identical directory contents,
        regardless of creation order."""
        _write(tmp_path, "za", b"1" * 3)
        _write(tmp_path, "ab", b"2" * 9)
        other = tmp_path / "other"
        other.mkdir()
        _write(other, "ab2", b"2" * 9)
        _write(other, "za2", b"1" * 3)
        c1 = Corpus.load_dir(tmp_path)
        c2 = Corpus.load_dir(other)
        assert list(c1) == list(c2) == [b"2" * 9, b"1" * 3]


def test_vanished_file_mid_scan_is_skipped(tmp_path, monkeypatch):
    """Files disappearing between iterdir and stat/read (atomic-rename
    temp files) must not abort the scan — both seams skip them."""
    from pathlib import Path

    _write(tmp_path, "stays", b"x" * 4)
    ghost = _write(tmp_path, "ghost", b"y" * 8)
    real_stat = Path.stat

    def flaky_stat(self, **kw):
        if self.name == "ghost":
            raise OSError("vanished")
        return real_stat(self, **kw)

    monkeypatch.setattr(Path, "stat", flaky_stat)
    watcher = DirWatcher(tmp_path / "nowhere")
    watcher.directory = tmp_path          # bypass ctor's initial scan
    watcher._seen = set()
    assert [p.name for p in watcher.poll()] == ["stays"]
    assert [p.name for p, _ in seed_paths([tmp_path])] == ["stays"]
    monkeypatch.undo()
    ghost.unlink()