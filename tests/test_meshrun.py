"""Mesh campaign driver (wtf_tpu/meshrun) on the conftest's 8 virtual
CPU devices.

The acceptance contract (ISSUE 7): a mesh is ONE logical backend —
identical seeds produce bit-identical merged coverage, crash sets and
devmut byte streams against the single-device run at equal execs; the
compiled chunk's only cross-device collective is the coverage
all-reduce; per-shard device counters sum to the merged view.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wtf_tpu.core.results import Crash, StatusCode
from wtf_tpu.harness import demo_tlv
from wtf_tpu.interp.runner import Runner, warm_decode_cache
from wtf_tpu.interp.step import make_run_chunk
from wtf_tpu.meshrun import (
    MeshRunner, make_mesh, make_mesh_chunk, make_mesh_merge, merge_coverage,
    replicate, shard_machine,
)

PAYLOAD = b"\x01\x02AB\x03\x08CCCCCCCC"
N_DEVICES = 8
N_LANES = 16

SMALL = dict(uop_capacity=1 << 10, overlay_slots=16, edge_bits=12,
             chunk_steps=8)


def _seed_lanes(runner) -> None:
    view = runner.view()
    for lane in range(runner.n_lanes):
        data = PAYLOAD[:4 + (lane % 3) * 5]
        view.virt_write(lane, demo_tlv.INPUT_GVA, data)
        view.r["gpr"][lane, 2] = np.uint64(len(data))
    runner.push(view)


def _runner(cls=Runner, **extra) -> Runner:
    snapshot = demo_tlv.build_snapshot()
    runner = cls(snapshot, n_lanes=N_LANES, **SMALL, **extra)
    warm_decode_cache(runner, demo_tlv.TARGET, PAYLOAD, limit=4096)
    _seed_lanes(runner)
    return runner


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N_DEVICES, "conftest should provision 8"
    return make_mesh(N_DEVICES)


def test_mesh_chunk_bit_parity_and_merged_bitmaps(mesh):
    """The shard_map chunk executor == the plain chunk executor on every
    machine leaf, and its on-chip merged cov/edge == the host union."""
    r1 = _runner()
    m_single = make_run_chunk(8, donate=False)(
        r1.cache.device(), r1.physmem.image, r1.machine, jnp.uint64(500))

    r2 = _runner()
    machine = shard_machine(r2.machine, mesh)
    tab = replicate(r2.cache.device(), mesh)
    image = replicate(r2.physmem.image, mesh)
    m_mesh, cov, edge = make_mesh_chunk(8, mesh, donate=False)(
        tab, image, machine, jnp.uint64(500))

    for name in m_single._fields:
        for la, lb in zip(jax.tree.leaves(getattr(m_single, name)),
                          jax.tree.leaves(getattr(m_mesh, name))):
            np.testing.assert_array_equal(
                np.asarray(la), np.asarray(lb),
                err_msg=f"machine leaf {name} diverges on the mesh")
    cov_host = np.bitwise_or.reduce(np.asarray(m_single.cov), axis=0)
    edge_host = np.bitwise_or.reduce(np.asarray(m_single.edge), axis=0)
    np.testing.assert_array_equal(np.asarray(cov), cov_host)
    np.testing.assert_array_equal(np.asarray(edge), edge_host)
    assert cov_host.sum() > 0  # something actually executed


def test_mesh_runner_full_run_parity(mesh):
    """MeshRunner.run() (host servicing, decode misses, breakpoints
    included) matches Runner.run() bit-for-bit, per-shard counters sum
    to the batch total, and the merged-coverage view needs no
    [lanes, words] gather."""
    r1 = _runner()
    r1.cache.set_breakpoint(demo_tlv.FINISH_GVA)
    statuses1 = r1.run(bp_handler=_stop_handler)

    r2 = _runner(cls=MeshRunner, mesh=mesh)
    r2.cache.set_breakpoint(demo_tlv.FINISH_GVA)
    statuses2 = r2.run(bp_handler=_stop_handler)
    np.testing.assert_array_equal(statuses1, statuses2)
    np.testing.assert_array_equal(np.asarray(r1.machine.icount),
                                  np.asarray(r2.machine.icount))

    # per-shard device counters sum to the merged device.* view
    ctr = r2.fold_device_counters()
    dump = r2.registry.counter("device.shard_instructions").dump()
    assert len(dump) == N_DEVICES
    assert sum(dump.values()) == int(
        ctr[:, 0].sum(dtype=np.uint64))
    assert r2.registry.counter("device.instructions").value == sum(
        dump.values())

    # the on-chip merged bitmap equals the host union of the lane planes
    merged = r2.merged_coverage()
    assert merged is not None
    np.testing.assert_array_equal(
        merged[0], np.bitwise_or.reduce(np.asarray(r2.machine.cov), axis=0))
    np.testing.assert_array_equal(
        merged[1], np.bitwise_or.reduce(np.asarray(r2.machine.edge), axis=0))


def _stop_handler(runner, view, lane):
    view.set_status(lane, StatusCode.OK)


def test_mesh_merge_matches_single_device(mesh):
    """make_mesh_merge == merge_coverage (union, per-lane credit,
    new-word report) on randomized bitmaps with a non-trivial aggregate
    and masked lanes — the reference set-union semantics survive
    sharding."""
    rng = np.random.default_rng(0xC07)
    cov = rng.integers(0, 1 << 32, (N_LANES, 24), dtype=np.uint32)
    edge = rng.integers(0, 1 << 32, (N_LANES, 40), dtype=np.uint32)
    # duplicate rows so prefix credit actually discriminates
    cov[3] = cov[1]
    edge[3] = edge[1]
    agg_cov = cov[5] & rng.integers(0, 1 << 32, 24, dtype=np.uint32)
    agg_edge = np.zeros(40, np.uint32)
    include = np.ones(N_LANES, bool)
    include[[2, 9]] = False

    want = jax.jit(merge_coverage)(agg_cov, agg_edge, cov, edge, include)
    got = make_mesh_merge(mesh)(
        jnp.asarray(agg_cov), jnp.asarray(agg_edge),
        shard_machine(jnp.asarray(cov), mesh),
        shard_machine(jnp.asarray(edge), mesh),
        shard_machine(jnp.asarray(include), mesh))
    for a, b, name in zip(got, want,
                          ("agg_cov", "agg_edge", "new_lane", "new_words")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"{name} diverges on mesh")
    # sanity: the mask and the prefix credit both did something
    new_lane = np.asarray(want[2])
    assert not new_lane[2] and not new_lane[9]
    assert new_lane[1] and not new_lane[3]


def _campaign(mesh_devices, seed=0x5EED, batches=2, mutator="devmangle"):
    from wtf_tpu.analysis.trace import build_tlv_campaign

    loop = build_tlv_campaign(n_lanes=8, mutator=mutator, limit=20_000,
                              seed=seed, chunk_steps=128, overlay_slots=16,
                              mesh_devices=mesh_devices)
    for _ in range(batches):
        loop.run_one_batch()
    return loop


def test_mesh_campaign_devmangle_parity():
    """Acceptance: `--mesh-devices 8 --mutator devmangle` == the
    single-device campaign at equal seeds/execs — bit-identical merged
    coverage, crash set, corpus, AND devmut byte streams (the in-HBM
    generator sharded per-shard against the same hostref lane_seeds)."""
    a = _campaign(None)
    b = _campaign(8)
    assert a.stats.testcases == b.stats.testcases == 16
    assert a.stats.new_coverage == b.stats.new_coverage
    assert a.crash_names == b.crash_names
    assert a.corpus.digests == b.corpus.digests
    np.testing.assert_array_equal(np.asarray(a.backend._agg_cov),
                                  np.asarray(b.backend._agg_cov))
    np.testing.assert_array_equal(np.asarray(a.backend._agg_edge),
                                  np.asarray(b.backend._agg_edge))
    # the device-resident testcase stream is bit-exact across shardings
    wa, la = a.mutator.current_batch()
    wb, lb = b.mutator.current_batch()
    np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    fa = a.mutator.fetch(range(8))
    fb = b.mutator.fetch(range(8))
    assert fa == fb
    # mesh telemetry gauges + per-shard counters sum to the merged view
    reg = b.registry
    assert reg.gauge("mesh.devices").value == 8
    assert reg.gauge("mesh.lanes_per_shard").value == 1
    by_shard = reg.counter("device.shard_instructions").dump()
    assert sum(by_shard.values()) == \
        reg.counter("device.instructions").value > 0
    assert reg.counter("device.instructions").value == \
        a.registry.counter("device.instructions").value


def test_cli_mesh_flag_plumbs_to_backend():
    """--mesh-devices parses on campaign/fuzz, flows through the tuning
    kwargs, and create_backend routes it to the MeshBackend (0 = every
    local device)."""
    from wtf_tpu.backend import create_backend
    from wtf_tpu.cli import _backend_tuning_kwargs, build_parser
    from wtf_tpu.meshrun.backend import MeshBackend

    args = build_parser().parse_args(
        ["campaign", "--name", "demo_tlv", "--mesh-devices", "8"])
    assert _backend_tuning_kwargs(args)["mesh_devices"] == 8
    args = build_parser().parse_args(["fuzz", "--name", "demo_tlv"])
    assert "mesh_devices" not in _backend_tuning_kwargs(args)

    backend = create_backend("tpu", demo_tlv.build_snapshot(),
                             n_lanes=8, mesh_devices=0)
    assert isinstance(backend, MeshBackend)
    emu = create_backend("emu", demo_tlv.build_snapshot(), mesh_devices=8)
    assert not isinstance(emu, MeshBackend)


def test_mesh_backend_rejects_indivisible_lanes():
    from wtf_tpu.backend import create_backend

    backend = create_backend("tpu", demo_tlv.build_snapshot(),
                             n_lanes=6, mesh_devices=4)
    with pytest.raises(ValueError, match="divide"):
        backend.initialize()


def test_mesh_lint_rules_fire_on_seeded_violations():
    """The mesh rule family's checks, seeded directly (the clean run on
    the real tree is `wtf-tpu lint` / the slow full-lint test): a
    gather-class collective over budget fires mesh.collectives, a
    shard-count-dependent program fires mesh.shard-unstable, and the
    normalizer strips exactly the device-list noise."""
    from wtf_tpu.analysis.rules import (
        check_mesh_collectives, check_shard_stability,
        count_collective_ops, load_budgets, normalize_partitioned_hlo,
    )

    budget = load_budgets()["mesh_chunk"]
    assert budget["all-reduce"] == 1 and budget["total"] == 1

    hlo = ('  %ar = u32[160,32]{1,0} all-reduce(u32[160,32]{1,0} %x), '
           'replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%or\n'
           '  %ag = u32[16,24]{1,0} all-gather(u32[2,24]{1,0} %m), '
           'replica_groups=[8,1]<=[8], dimensions={0}\n')
    counts = count_collective_ops(hlo)
    assert counts == {"all-reduce": 1, "all-gather": 1, "all-to-all": 0,
                      "collective-permute": 0, "collective-broadcast": 0,
                      "reduce-scatter": 0, "total": 2}
    findings = check_mesh_collectives(counts, budget, entry="seeded")
    rules = {(f.rule, f.primitive) for f in findings}
    assert ("mesh.collectives", "all-gather") in rules
    assert ("mesh.collectives", "total") in rules

    eight = ('%p = u32[2,16]{1,0} parameter(0), '
             'sharding={devices=[8,1]<=[8]}\n'
             '%ar = pred[] all-reduce(%q), replica_groups={{0,1,2,3,4,5,6,7}}')
    four = eight.replace("[8,1]<=[8]", "[4,1]<=[4]").replace(
        "{{0,1,2,3,4,5,6,7}}", "{{0,1,2,3}}")
    assert normalize_partitioned_hlo(eight) == normalize_partitioned_hlo(four)
    assert check_shard_stability(eight, four, entry="seeded") == []
    drifted = four.replace("u32[2,16]", "u32[4,16]")
    bad = check_shard_stability(eight, drifted, entry="seeded")
    assert [f.rule for f in bad] == ["mesh.shard-unstable"]


def test_telemetry_report_mesh_section(tmp_path):
    """tools/telemetry_report.py surfaces the per-shard counters and
    their agreement with the merged device view."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
    from telemetry_report import summarize

    events = tmp_path / "events.jsonl"
    metrics = {
        "campaign.testcases": 32,
        "device.instructions": 1792,
        "device.mem_faults": 0,
        "device.decode_misses": 32,
        "device.shard_instructions": {"0": 1000, "1": 792},
        "mesh.devices": 2,
        "mesh.lanes_per_shard": 8,
        "phase.seconds": {"execute": 1.0},
    }
    with events.open("w") as fh:
        fh.write(json.dumps({"ts": 1.0, "seq": 0, "type": "run-start"}) + "\n")
        fh.write(json.dumps({"ts": 2.0, "seq": 1, "type": "run-end",
                             "metrics": metrics}) + "\n")
    s = summarize(events)
    assert s["mesh"] == {
        "devices": 2, "lanes_per_shard": 8,
        "shard_instructions": {"0": 1000, "1": 792},
        "shard_instructions_sum": 1792, "merged_instructions": 1792,
    }


@pytest.mark.slow
def test_mesh_campaign_ramp_parity_slow():
    """The larger ramp: 64 lanes x 6 batches (384 execs) with crashes
    possible; mesh and single-device runs stay bit-identical on
    coverage, crash names and corpus over the longer horizon, and the
    fused Pallas ladder on the mesh agrees too."""
    from wtf_tpu.analysis.trace import build_tlv_campaign

    def run(mesh_devices, fused="off"):
        loop = build_tlv_campaign(n_lanes=64, mutator="devmangle",
                                  limit=20_000, seed=0xAB, chunk_steps=128,
                                  overlay_slots=16,
                                  mesh_devices=mesh_devices,
                                  fused_step=fused)
        for _ in range(6):
            loop.run_one_batch()
        return loop

    a = run(None)
    b = run(8)
    assert a.stats.testcases == b.stats.testcases == 384
    assert a.crash_names == b.crash_names
    assert a.corpus.digests == b.corpus.digests
    np.testing.assert_array_equal(np.asarray(a.backend._agg_cov),
                                  np.asarray(b.backend._agg_cov))
    np.testing.assert_array_equal(np.asarray(a.backend._agg_edge),
                                  np.asarray(b.backend._agg_edge))

    from wtf_tpu.interp.pstep import fused_available

    if fused_available():
        c = run(8, fused="on")
        assert c.stats.testcases == 384
        np.testing.assert_array_equal(np.asarray(a.backend._agg_cov),
                                      np.asarray(c.backend._agg_cov))
        assert c.registry.counter("device.fused_steps").value > 0
