"""SSE/SSE2 floating-point oracle vs the REAL host CPU (VERDICT r3 item 3).

Every case routes XMM state through GPRs (movq xmm<->gpr is in-subset), so
the GPR-protocol native harness (tests/nativeharness.py) gives bit-exact
hardware ground truth for the new OPC_SSEFP semantics — NaN payloads,
quieting, min/max second-operand rules, converts, the lot.
"""

import struct

import pytest

from emurunner import run_emu
from nativeharness import run_native
from wtf_tpu.core.cpustate import GPR_NAMES

# f64 bit patterns that probe every special-case rule
F64 = {
    "one": 0x3FF0000000000000,
    "two": 0x4000000000000000,
    "half": 0x3FE0000000000000,
    "neg": 0xC045000000000000,        # -42.0
    "pzero": 0x0000000000000000,
    "nzero": 0x8000000000000000,
    "pinf": 0x7FF0000000000000,
    "ninf": 0xFFF0000000000000,
    "qnan": 0x7FF8000000001234,       # QNaN w/ payload
    "snan": 0x7FF0000000000BAD,       # SNaN w/ payload
    "denorm": 0x0000000000000001,
    "big": 0x7FE123456789ABCD,
    "tiny": 0x0010000000000000,
    "pi": 0x400921FB54442D18,
}

# f32 patterns (packed low/high pairs ride in one u64)
F32_PAIRS = {
    "one_two": 0x400000003F800000,
    "nan_inf": 0x7F8000007FC00123,
    "snan_neg": 0xC2280000FF800001,
    "zeros": 0x8000000000000000,
    "denorm_big": 0x7F7FFFFF00000001,
}

_SD_OPS = [("addsd", None), ("subsd", None), ("mulsd", None),
           ("divsd", None), ("minsd", None), ("maxsd", None),
           ("sqrtsd", "unary"), ("cmpeqsd", "cmp"), ("cmpltsd", "cmp"),
           ("cmpnlesd", "cmp"), ("cmpunordsd", "cmp")]
_SS_OPS = [("addss", None), ("subss", None), ("mulss", None),
           ("divss", None), ("minss", None), ("maxss", None),
           ("sqrtss", "unary")]
_PS_OPS = [("addps", None), ("mulps", None), ("subps", None),
           ("minps", None), ("maxps", None), ("divps", None),
           ("sqrtps", "unary"), ("cmpleps", "cmp")]


def _sse_snippet(op, kind, packed=False):
    """Build xmm0 from rax(:rdx), xmm1 from rcx(:rsi), run `op`, pull the
    result back through rax(:rdx)."""
    build = ["movq xmm0, rax", "movq xmm1, rcx"]
    if packed:
        build += ["movq xmm2, rdx", "punpcklqdq xmm0, xmm2",
                  "movq xmm3, rsi", "punpcklqdq xmm1, xmm3"]
    if kind == "cmp":
        body = [f"{op} xmm0, xmm1"]
    elif kind == "unary":
        body = [f"{op} xmm0, xmm1"]
    else:
        body = [f"{op} xmm0, xmm1"]
    out = ["movq rax, xmm0"]
    if packed:
        out += ["psrldq xmm0, 8", "movq rdx, xmm0"]
    return "\n".join(build + body + out)


def _run_both(snippet, init_regs):
    init = [0] * 16
    for name, value in init_regs.items():
        init[GPR_NAMES.index(name)] = value
    hw_regs, hw_flags = run_native(snippet, init)
    regs = {n: v for n, v in zip(GPR_NAMES, init) if n != "rsp"}
    cpu = run_emu(snippet + "\nhlt", regs=regs)
    return hw_regs, hw_flags, cpu


@pytest.mark.parametrize("op,kind", _SD_OPS)
@pytest.mark.parametrize("a_name,b_name", [
    ("one", "two"), ("pi", "neg"), ("big", "tiny"), ("pzero", "nzero"),
    ("pinf", "ninf"), ("pinf", "pinf"), ("qnan", "one"), ("one", "qnan"),
    ("snan", "one"), ("one", "snan"), ("qnan", "snan"), ("denorm", "denorm"),
    ("nzero", "pzero"), ("big", "big"), ("neg", "pzero"),
])
def test_sd_vs_hardware(op, kind, a_name, b_name):
    snippet = _sse_snippet(op, kind)
    hw_regs, _, cpu = _run_both(
        snippet, {"rax": F64[a_name], "rcx": F64[b_name]})
    assert cpu.gpr[0] == hw_regs[0], (
        f"{op}({a_name},{b_name}): emu={cpu.gpr[0]:#018x} "
        f"hw={hw_regs[0]:#018x}")


@pytest.mark.parametrize("op,kind", _SS_OPS)
@pytest.mark.parametrize("a,b", [
    (0x3F800000, 0x40000000), (0x7FC00001, 0x3F800000),
    (0x7F800001, 0x3F800000), (0xFF800000, 0x7F800000),
    (0x80000000, 0x00000000), (0x00000001, 0x7F7FFFFF),
    (0x42280000, 0xC2280000),
])
def test_ss_vs_hardware(op, kind, a, b):
    snippet = _sse_snippet(op, kind)
    hw_regs, _, cpu = _run_both(snippet, {"rax": a, "rcx": b})
    assert cpu.gpr[0] == hw_regs[0], (
        f"{op}({a:#x},{b:#x}): emu={cpu.gpr[0]:#018x} hw={hw_regs[0]:#018x}")


@pytest.mark.parametrize("op,kind", _PS_OPS)
@pytest.mark.parametrize("lo_a,hi_a,lo_b,hi_b", [
    ("one_two", "nan_inf", "zeros", "denorm_big"),
    ("snan_neg", "one_two", "one_two", "nan_inf"),
    ("denorm_big", "zeros", "snan_neg", "one_two"),
])
def test_ps_vs_hardware(op, kind, lo_a, hi_a, lo_b, hi_b):
    snippet = _sse_snippet(op, kind, packed=True)
    hw_regs, _, cpu = _run_both(snippet, {
        "rax": F32_PAIRS[lo_a], "rdx": F32_PAIRS[hi_a],
        "rcx": F32_PAIRS[lo_b], "rsi": F32_PAIRS[hi_b]})
    for slot, reg in ((0, "rax"), (2, "rdx")):
        assert cpu.gpr[slot] == hw_regs[slot], (
            f"{op} {reg}: emu={cpu.gpr[slot]:#018x} hw={hw_regs[slot]:#018x}")


@pytest.mark.parametrize("op", ["ucomisd", "comisd"])
@pytest.mark.parametrize("a_name,b_name", [
    ("one", "two"), ("two", "one"), ("one", "one"), ("qnan", "one"),
    ("one", "snan"), ("pzero", "nzero"), ("pinf", "big"), ("ninf", "pinf"),
])
def test_ucomi_flags_vs_hardware(op, a_name, b_name):
    snippet = (f"movq xmm0, rax\nmovq xmm1, rcx\n{op} xmm0, xmm1")
    hw_regs, hw_flags, cpu = _run_both(
        snippet, {"rax": F64[a_name], "rcx": F64[b_name]})
    mask = 0x8D5  # OF|SF|ZF|AF|PF|CF
    assert cpu.rflags & mask == hw_flags & mask, (
        f"{op}({a_name},{b_name}): emu={cpu.rflags:#x} hw={hw_flags:#x}")


@pytest.mark.parametrize("snippet_op,rex", [
    ("cvtsi2sd xmm0, rcx", ""), ("cvtsi2ss xmm0, rcx", ""),
    ("cvtsi2sd xmm0, ecx", ""), ("cvtsi2ss xmm0, ecx", ""),
])
@pytest.mark.parametrize("ival", [
    0, 1, 2**32 - 1, 2**63 - 1, 2**64 - 512, 0x8000000000000000,
    12345678901234567, 0xFFFFFFFF80000000,
])
def test_cvtsi2_vs_hardware(snippet_op, rex, ival):
    snippet = f"pxor xmm0, xmm0\n{snippet_op}\nmovq rax, xmm0"
    hw_regs, _, cpu = _run_both(snippet, {"rcx": ival})
    assert cpu.gpr[0] == hw_regs[0], (
        f"{snippet_op} {ival:#x}: emu={cpu.gpr[0]:#018x} "
        f"hw={hw_regs[0]:#018x}")


@pytest.mark.parametrize("op", ["cvttsd2si rax, xmm1", "cvtsd2si rax, xmm1",
                                "cvttsd2si eax, xmm1", "cvtsd2si eax, xmm1"])
@pytest.mark.parametrize("b_name", [
    "one", "half", "pi", "neg", "big", "qnan", "pinf", "nzero", "tiny",
])
def test_cvt2si_vs_hardware(op, b_name):
    snippet = f"movq xmm1, rcx\nxor eax, eax\n{op}"
    hw_regs, _, cpu = _run_both(snippet, {"rcx": F64[b_name]})
    assert cpu.gpr[0] == hw_regs[0], (
        f"{op}({b_name}): emu={cpu.gpr[0]:#018x} hw={hw_regs[0]:#018x}")


@pytest.mark.parametrize("op", [
    "cvtss2sd xmm0, xmm1", "cvtsd2ss xmm0, xmm1", "cvtdq2ps xmm0, xmm1",
    "cvtps2dq xmm0, xmm1", "cvttps2dq xmm0, xmm1", "cvtdq2pd xmm0, xmm1",
    "cvtpd2dq xmm0, xmm1", "cvttpd2dq xmm0, xmm1", "cvtps2pd xmm0, xmm1",
    "cvtpd2ps xmm0, xmm1",
])
@pytest.mark.parametrize("bits_lo,bits_hi", [
    (0x3FF0000000000000, 0x40091EB851EB851F),
    (0x7FF800000000BEEF, 0xC024000000000000),
    (0x41DFFFFFFFC00000, 0x00000000499602D2),  # 2^31-ish boundaries
    (0xFFFFFFFF7FFFFFFF, 0x8000000180000000),
])
def test_cvt_shapes_vs_hardware(op, bits_lo, bits_hi):
    snippet = ("movq xmm1, rax\nmovq xmm2, rdx\npunpcklqdq xmm1, xmm2\n"
               "pxor xmm0, xmm0\n" + op +
               "\nmovq rax, xmm0\npsrldq xmm0, 8\nmovq rdx, xmm0")
    hw_regs, _, cpu = _run_both(snippet, {"rax": bits_lo, "rdx": bits_hi})
    for slot, reg in ((0, "rax"), (2, "rdx")):
        assert cpu.gpr[slot] == hw_regs[slot], (
            f"{op} {reg}: emu={cpu.gpr[slot]:#018x} hw={hw_regs[slot]:#018x}")


@pytest.mark.parametrize("op", [
    "shufps xmm0, xmm1, 0x1B", "shufps xmm0, xmm1, 0xE4",
    "shufpd xmm0, xmm1, 0x1", "unpcklps xmm0, xmm1",
    "unpckhps xmm0, xmm1", "unpcklpd xmm0, xmm1", "unpckhpd xmm0, xmm1",
    "andps xmm0, xmm1", "orps xmm0, xmm1", "andnps xmm0, xmm1",
    "andpd xmm0, xmm1", "orpd xmm0, xmm1",
    "psllq xmm0, 3", "psrlq xmm0, 17", "psllq xmm0, 63",
    "psrlq xmm0, 64", "psllq xmm0, 200",  # counts > 63 zero the register
])
def test_shuffle_bitwise_vs_hardware(op):
    snippet = ("movq xmm0, rax\nmovq xmm2, rdx\npunpcklqdq xmm0, xmm2\n"
               "movq xmm1, rcx\nmovq xmm3, rsi\npunpcklqdq xmm1, xmm3\n"
               + op + "\nmovq rax, xmm0\npsrldq xmm0, 8\nmovq rdx, xmm0")
    hw_regs, _, cpu = _run_both(snippet, {
        "rax": 0x1111111122222222, "rdx": 0x3333333344444444,
        "rcx": 0x5555555566666666, "rsi": 0x7777777788888888})
    for slot in (0, 2):
        assert cpu.gpr[slot] == hw_regs[slot], (
            f"{op}: emu={cpu.gpr[slot]:#018x} hw={hw_regs[slot]:#018x}")


def test_ssefp_memory_operand():
    """Scalar FP with a memory source reads exactly elem bytes through the
    guest page tables (oracle path; no hardware needed for the plumbing)."""
    from emurunner import DATA_BASE

    cpu = run_emu(
        f"""
        mov rbx, {DATA_BASE}
        movsd xmm0, [rbx]
        addsd xmm0, [rbx+8]
        movq rax, xmm0
        hlt
        """,
        data={DATA_BASE: struct.pack("<dd", 1.5, 2.25)})
    assert struct.unpack("<d", cpu.gpr[0].to_bytes(8, "little"))[0] == 3.75


# ---------------------------------------------------------------------------
# VEX.128 (AVX) forms: moves always; 3-operand ops when src1 == dst
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("op", [
    "vaddsd xmm0, xmm0, xmm1", "vmulsd xmm0, xmm0, xmm1",
    "vsubss xmm0, xmm0, xmm1", "vdivsd xmm0, xmm0, xmm1",
    "vminsd xmm0, xmm0, xmm1", "vsqrtsd xmm0, xmm0, xmm1",
    "vandps xmm0, xmm0, xmm1", "vxorps xmm0, xmm0, xmm1",
    "vpxor xmm0, xmm0, xmm1", "vucomisd xmm0, xmm1",
    "vmovsd xmm0, xmm0, xmm1", "vmovq rax, xmm1",
    "vcvtsi2sd xmm0, xmm0, rcx",
])
@pytest.mark.parametrize("a_name,b_name", [("pi", "neg"), ("qnan", "one")])
def test_vex128_vs_hardware(op, a_name, b_name):
    snippet = (f"movq xmm0, rax\nmovq xmm1, rcx\n{op}\n"
               "movq rax, xmm0")
    hw_regs, hw_flags, cpu = _run_both(
        snippet, {"rax": F64[a_name], "rcx": F64[b_name]})
    assert cpu.gpr[0] == hw_regs[0], (
        f"{op}: emu={cpu.gpr[0]:#018x} hw={hw_regs[0]:#018x}")
    if "ucomi" in op:
        mask = 0x8D5
        assert cpu.rflags & mask == hw_flags & mask


def test_vex128_memory_and_rejects():
    from asmhelper import assemble
    from wtf_tpu.cpu.decoder import decode
    from wtf_tpu.cpu.uops import OPC_INVALID, OPC_SSEFP, OPC_SSEMOV

    pad = b"\x90" * 12
    # loads/stores decode onto the legacy move semantics
    assert decode(assemble("vmovups xmm1, [rax]") + pad).opc == OPC_SSEMOV
    assert decode(assemble("vmovdqu [rax], xmm2") + pad).opc == OPC_SSEMOV
    assert decode(assemble("vmovaps xmm3, xmm4") + pad).opc == OPC_SSEMOV
    assert decode(assemble("vaddsd xmm1, xmm1, [rax]") + pad).opc == OPC_SSEFP
    # genuinely 3-operand (src1 != dst): outside this pipeline's model —
    # must stay INVALID, not silently execute with wrong semantics
    assert decode(assemble("vaddsd xmm1, xmm2, xmm3") + pad).opc == OPC_INVALID
    # 2-operand forms demand vvvv == 1111b like hardware: a vmovups with
    # a nonzero vvvv is not something an assembler emits; craft the bytes
    # (C5 f0 10 ca = vvvv=xmm1)
    assert decode(bytes([0xC5, 0x70, 0x10, 0xCA]) + pad).opc == OPC_INVALID


@pytest.mark.parametrize("op", ["addsd", "subsd", "mulsd", "divsd",
                                "minsd", "maxsd", "cmplesd"])
def test_sd_random_battery_vs_hardware(op):
    """Seeded random bit-pattern sweep per op — 60 pairs drawn from the
    full f64 space (incl. NaN payload and denormal regions) against the
    live host CPU."""
    import random

    rng = random.Random(hash(op) & 0xFFFFFFFF)
    kind = "cmp" if op.startswith("cmp") else None
    snippet = _sse_snippet(op, kind)
    for _ in range(60):
        shape = rng.randrange(4)
        if shape == 0:      # uniform bits
            a, b = rng.getrandbits(64), rng.getrandbits(64)
        elif shape == 1:    # NaN/inf region (exp all-ones)
            a = 0x7FF0000000000000 | (rng.getrandbits(52)) | (
                rng.getrandbits(1) << 63)
            b = rng.getrandbits(64)
        elif shape == 2:    # denormal region
            a = rng.getrandbits(52) | (rng.getrandbits(1) << 63)
            b = rng.getrandbits(52) | (rng.getrandbits(1) << 63)
        else:               # near-equal magnitudes (cancellation)
            a = rng.getrandbits(64)
            b = a ^ rng.getrandbits(3)
        hw_regs, _, cpu = _run_both(snippet, {"rax": a, "rcx": b})
        assert cpu.gpr[0] == hw_regs[0], (
            f"{op}({a:#018x},{b:#018x}): emu={cpu.gpr[0]:#018x} "
            f"hw={hw_regs[0]:#018x}")


@pytest.mark.parametrize("op", ["addps", "mulps", "divps", "minps"])
def test_ps_random_battery_vs_hardware(op):
    """Same sweep for packed single: 40 random 128-bit pairs per op."""
    import random

    rng = random.Random(~hash(op) & 0xFFFFFFFF)
    snippet = _sse_snippet(op, None, packed=True)
    for _ in range(40):
        regs = {r: rng.getrandbits(64) for r in ("rax", "rdx", "rcx", "rsi")}
        hw_regs, _, cpu = _run_both(snippet, regs)
        for slot in (0, 2):
            assert cpu.gpr[slot] == hw_regs[slot], (
                f"{op} {regs}: emu={cpu.gpr[slot]:#018x} "
                f"hw={hw_regs[slot]:#018x}")
