"""Unit tests for the u32 limb-arithmetic library (interp/limbs.py) and the
HLO guard for the ported step paths.

Two jobs:

1. Property-style corner grids: every limb helper checked against Python
   big-int ground truth at the places limb code breaks — carry-out chains,
   cross-limb shifts by 0/31/32/33/63(/64), widening multiply highs, flag
   bits at every operand width.

2. The no-u64 guard (ISSUE 2 acceptance): compile the ported functions —
   the limb library itself, the step's ALU/unary/addressing cores, the
   decode-cache hash probe — and assert the optimized HLO contains ZERO
   64-bit integer ops.  This is what keeps a future edit from silently
   reintroducing u64 (XLA would lower it to a u32 pair on TPU and Pallas
   would reject it outright) on the paths this PR ported.  Since ISSUE 5
   the contract lives in the analysis rule API (wtf_tpu/analysis/rules.py
   dtype family, enumerated from step.PORTED_LIMB_PATHS) — this file just
   runs the family, so the tests and `wtf-tpu lint` can never disagree.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from wtf_tpu.interp import limbs as L
from wtf_tpu.interp import step as S
from wtf_tpu.utils.hashing import mix64, splitmix64

MASK64 = (1 << 64) - 1

# corner values: limb boundaries, sign boundaries, all-ones, and a few
# irregular bit patterns
CORNERS = [
    0, 1, 2, 0xFF, 0x100, 0x7FFF, 0x8000, 0xFFFF, 0x10000,
    0x7FFFFFFF, 0x80000000, 0xFFFFFFFF, 0x100000000, 0x123456789,
    0x7FFFFFFFFFFFFFFF, 0x8000000000000000, 0xFFFFFFFFFFFFFFFF,
    0x1122334455667788, 0xFEDCBA9876543210, 0x00000001FFFFFFFF,
    0xFFFFFFFF00000000, 0x0F0F0F0F0F0F0F0F,
]
SHIFTS = [0, 1, 7, 31, 32, 33, 63, 64, 65, 127]


def _pairs(values):
    v = np.array(values, dtype=np.uint64)
    u = L.unpack_np(v)
    return jnp.asarray(u[:, 0]), jnp.asarray(u[:, 1])


def _ints(pair):
    lo = np.asarray(pair[0], dtype=np.uint64)
    hi = np.asarray(pair[1], dtype=np.uint64)
    return [int(l) | (int(h) << 32) for l, h in zip(lo.ravel(), hi.ravel())]


def _cross(xs, ys):
    """All (x, y) combinations as two flat lists."""
    ax = [x for x in xs for _ in ys]
    ay = [y for _ in xs for y in ys]
    return ax, ay


def test_pack_unpack_roundtrip_np():
    v = np.array(CORNERS, dtype=np.uint64)
    assert (L.pack_np(L.unpack_np(v)) == v).all()
    m = np.arange(32, dtype=np.uint64).reshape(2, 4, 4)
    assert (L.pack_np(L.unpack_np(m)) == m).all()


def test_pack_unpack_roundtrip_device():
    v = jnp.asarray(np.array(CORNERS, dtype=np.uint64))
    assert (L.pack_u64(L.unpack_u64(v)) == v).all()
    p = L.pair(v)
    assert (L.to_u64(p) == v).all()


def test_add_sub_carry_chains():
    ax, bx = _cross(CORNERS, CORNERS)
    a, b = _pairs(ax), _pairs(bx)
    for carry in (False, True):
        cin = jnp.full(len(ax), carry)
        s, cout = L.adc64(a, b, cin)
        d, bout = L.sbb64(a, b, cin)
        for i, (x, y) in enumerate(zip(ax, bx)):
            add = x + y + carry
            assert _ints(s)[i] == add & MASK64, f"adc {x:#x}+{y:#x}+{carry}"
            assert bool(np.asarray(cout)[i]) == (add > MASK64)
            sub = x - y - carry
            assert _ints(d)[i] == sub & MASK64, f"sbb {x:#x}-{y:#x}-{carry}"
            assert bool(np.asarray(bout)[i]) == (sub < 0)


def test_logic_neg_compare():
    ax, bx = _cross(CORNERS, CORNERS)
    a, b = _pairs(ax), _pairs(bx)
    assert _ints(L.and64(a, b)) == [x & y for x, y in zip(ax, bx)]
    assert _ints(L.or64(a, b)) == [x | y for x, y in zip(ax, bx)]
    assert _ints(L.xor64(a, b)) == [x ^ y for x, y in zip(ax, bx)]
    assert _ints(L.not64(a)) == [x ^ MASK64 for x in ax]
    assert _ints(L.neg64(a)) == [(-x) & MASK64 for x in ax]
    assert list(np.asarray(L.eq64(a, b))) == [x == y for x, y in zip(ax, bx)]
    assert list(np.asarray(L.ltu64(a, b))) == [x < y for x, y in zip(ax, bx)]
    assert list(np.asarray(L.leu64(a, b))) == [x <= y for x, y in zip(ax, bx)]
    assert list(np.asarray(L.is_zero64(a))) == [x == 0 for x in ax]


@pytest.mark.parametrize("op,ref", [
    ("shl64", lambda x, s: (x << s) & MASK64 if s < 64 else 0),
    ("shr64", lambda x, s: x >> s if s < 64 else 0),
    ("sar64", lambda x, s: (x - ((x >> 63) << 64)) >> min(s, 63) & MASK64),
])
def test_shifts_across_limb_boundary(op, ref):
    ax, sx = _cross(CORNERS, SHIFTS)
    a = _pairs(ax)
    s = jnp.asarray(np.array(sx, dtype=np.uint32))
    got = _ints(getattr(L, op)(a, s))
    for i, (x, sh) in enumerate(zip(ax, sx)):
        assert got[i] == ref(x, sh) & MASK64, f"{op}({x:#x}, {sh})"


def test_rotates():
    ax, sx = _cross(CORNERS, SHIFTS)
    a = _pairs(ax)
    s = jnp.asarray(np.array(sx, dtype=np.uint32))
    rol = _ints(L.rol64(a, s))
    ror = _ints(L.ror64(a, s))
    for i, (x, sh) in enumerate(zip(ax, sx)):
        k = sh % 64
        want_rol = ((x << k) | (x >> (64 - k))) & MASK64 if k else x
        want_ror = ((x >> k) | (x << (64 - k))) & MASK64 if k else x
        assert rol[i] == want_rol, f"rol64({x:#x}, {sh})"
        assert ror[i] == want_ror, f"ror64({x:#x}, {sh})"


def test_mul32_wide_highs():
    vals = [0, 1, 2, 0xFF, 0xFFFF, 0x10000, 0x10001, 0x7FFFFFFF,
            0x80000000, 0xFFFFFFFF, 0xDEADBEEF, 0x12345678]
    ax, bx = _cross(vals, vals)
    a = jnp.asarray(np.array(ax, dtype=np.uint32))
    b = jnp.asarray(np.array(bx, dtype=np.uint32))
    lo, hi = L.mul32_wide(a, b)
    for i, (x, y) in enumerate(zip(ax, bx)):
        p = x * y
        assert int(np.asarray(lo)[i]) == p & 0xFFFFFFFF
        assert int(np.asarray(hi)[i]) == p >> 32, f"mulhi {x:#x}*{y:#x}"


def test_umulhi_smulhi64():
    ax, bx = _cross(CORNERS, CORNERS[:14])
    a, b = _pairs(ax), _pairs(bx)
    uhi = _ints(L.umulhi64(a, b))
    shi = _ints(L.smulhi64(a, b))
    for i, (x, y) in enumerate(zip(ax, bx)):
        assert uhi[i] == (x * y) >> 64, f"umulhi64 {x:#x}*{y:#x}"
        sx = x - (1 << 64) if x >> 63 else x
        sy = y - (1 << 64) if y >> 63 else y
        assert shi[i] == ((sx * sy) >> 64) & MASK64, f"smulhi64 {x:#x}*{y:#x}"


def test_mul64_lo_and_splitmix():
    ax, bx = _cross(CORNERS, CORNERS[:12])
    a, b = _pairs(ax), _pairs(bx)
    got = _ints(L.mul64_lo(a, b))
    for i, (x, y) in enumerate(zip(ax, bx)):
        assert got[i] == (x * y) & MASK64, f"mul64_lo {x:#x}*{y:#x}"
    # splitmix64/mix64 must match the host reference bit-for-bit (the
    # decode-cache probe and edge hash depend on it)
    v = _pairs(CORNERS)
    assert _ints(L.splitmix64(v)) == [splitmix64(x) for x in CORNERS]
    assert _ints(L.mix64(v)) == [mix64(x) for x in CORNERS]


@pytest.mark.parametrize("nbytes", [1, 2, 4, 8])
def test_extend_mask_msb(nbytes):
    a = _pairs(CORNERS)
    n = jnp.full(len(CORNERS), nbytes, dtype=jnp.int32)
    bits = min(nbytes, 8) * 8
    m = (1 << bits) - 1
    assert _ints(L.zext(a, n)) == [x & m for x in CORNERS]
    want_sext = []
    for x in CORNERS:
        v = x & m
        if v >> (bits - 1):
            v |= MASK64 ^ m
        want_sext.append(v)
    assert _ints(L.sext(a, n)) == want_sext
    assert list(np.asarray(L.msb(a, n))) == [
        bool((x >> (bits - 1)) & 1) for x in CORNERS]


def _ref_flags_add(a, b, bits, carry):
    m = (1 << bits) - 1
    am, bm = a & m, b & m
    r = am + bm + carry
    rm = r & m
    return _mk_ref(cf=r > m, r=rm, bits=bits, af=(a ^ b ^ rm) & 0x10,
                   of=((am ^ rm) & (bm ^ rm)) >> (bits - 1) & 1)


def _ref_flags_sub(a, b, bits, borrow):
    m = (1 << bits) - 1
    am, bm = a & m, b & m
    rm = (am - bm - borrow) & m
    return _mk_ref(cf=am < bm + borrow, r=rm, bits=bits,
                   af=(a ^ b ^ rm) & 0x10,
                   of=((am ^ bm) & (am ^ rm)) >> (bits - 1) & 1)


def _mk_ref(cf, r, bits, af, of):
    pf = bin(r & 0xFF).count("1") % 2 == 0
    return ((L.CF if cf else 0) | (L.PF if pf else 0) | (L.AF if af else 0)
            | (L.ZF if r == 0 else 0)
            | (L.SF if (r >> (bits - 1)) & 1 else 0)
            | (L.OF if of else 0))


@pytest.mark.parametrize("nbytes", [1, 2, 4, 8])
def test_flag_bits_against_bigint(nbytes):
    bits = nbytes * 8
    m = (1 << bits) - 1
    ops = [v & m for v in CORNERS]
    ax, bx = _cross(ops, ops)
    n = jnp.full(len(ax), nbytes, dtype=jnp.int32)
    a, b = _pairs(ax), _pairs(bx)
    for carry in (False, True):
        cin = jnp.full(len(ax), carry)
        r_add = L.zext(L.adc64(a, b, cin)[0], n)
        fl_add = np.asarray(L.flags_add(a, b, r_add, n, cin))
        r_sub = L.zext(L.sbb64(a, b, cin)[0], n)
        fl_sub = np.asarray(L.flags_sub(a, b, r_sub, n, cin))
        for i, (x, y) in enumerate(zip(ax, bx)):
            assert int(fl_add[i]) == _ref_flags_add(x, y, bits, carry), (
                f"flags_add({x:#x}, {y:#x}, c={carry}, n={nbytes})")
            assert int(fl_sub[i]) == _ref_flags_sub(x, y, bits, carry), (
                f"flags_sub({x:#x}, {y:#x}, b={carry}, n={nbytes})")
    fl_logic = np.asarray(L.flags_logic(L.zext(L.and64(a, b), n), n))
    for i, (x, y) in enumerate(zip(ax, bx)):
        r = (x & y) & m
        assert int(fl_logic[i]) == _mk_ref(cf=False, r=r, bits=bits,
                                           af=0, of=0)


def test_eval_cond_table():
    # every flag combination over CF/PF/ZF/SF/OF x every condition code
    combos = []
    for mask in range(32):
        rf = ((mask & 1) * L.CF | ((mask >> 1) & 1) * L.PF
              | ((mask >> 2) & 1) * L.ZF | ((mask >> 3) & 1) * L.SF
              | ((mask >> 4) & 1) * L.OF)
        combos.append(rf)
    for cc in range(18):
        for rcx in (0, 1, 0xFFFFFFFF, 0x100000000, 0x1_0000_0001):
            rf = jnp.asarray(np.array(combos, dtype=np.uint32))
            rcx_l = _pairs([rcx] * len(combos))
            got = np.asarray(L.eval_cond(rf, rcx_l, jnp.int32(cc)))
            for i, flags in enumerate(combos):
                cf, pf = bool(flags & L.CF), bool(flags & L.PF)
                zf, sf = bool(flags & L.ZF), bool(flags & L.SF)
                of = bool(flags & L.OF)
                table = [of, not of, cf, not cf, zf, not zf,
                         cf or zf, not (cf or zf), sf, not sf, pf, not pf,
                         sf != of, sf == of, zf or (sf != of),
                         not zf and (sf == of)]
                if cc == 16:
                    want = rcx == 0
                elif cc == 17:
                    want = rcx & 0xFFFFFFFF == 0
                else:
                    want = table[cc]
                assert bool(got[i]) == want, f"cc={cc} flags={flags:#x}"


# ---------------------------------------------------------------------------
# the no-u64 guard for the ported step paths (one source of truth:
# wtf_tpu/analysis — ISSUE 5 satellite migrated the ad-hoc string greps)
# ---------------------------------------------------------------------------

def test_hlo_ported_paths_are_u64_free():
    """The zero-u64/s64 (and float-free) HLO pin over EVERY enumerated
    ported path — the limb library, the step ALU/unary/shift/mul/EA
    cores, the decode-cache probe, the Pallas-bound register-file writer,
    and the pack/unpack bitcast-only seam — via the analysis dtype rule
    family (what `wtf-tpu lint` runs; step.PORTED_LIMB_PATHS is the
    enumeration, so a newly ported path is covered by being exported)."""
    from wtf_tpu.analysis.rules import run_dtype_family

    findings = run_dtype_family()
    assert not findings, [str(f) for f in findings]


def test_limb_shift_mul_match_bigint_reference():
    """shift_limb / mul_limb against Python big-int recomputation of the
    x86 semantics at every width — the contract the deleted u64 SHIFT/MUL
    blocks embodied (results only; the flag images are pinned three-way by
    tests/test_step.py's hardware-differential corpus)."""
    from wtf_tpu.cpu import uops as U

    rng = np.random.default_rng(0x5417)
    k = 128
    a64 = rng.integers(0, 1 << 64, k, dtype=np.uint64)
    f64 = rng.integers(0, 1 << 64, k, dtype=np.uint64)
    cnt = rng.integers(0, 256, k, dtype=np.uint64)
    for nbytes in (1, 2, 4, 8):
        bits = nbytes * 8
        m = (1 << bits) - 1
        n = jnp.full(k, nbytes, dtype=jnp.int32)
        a = L.zext(L.pair(jnp.asarray(a64)), n)
        fill = L.zext(L.pair(jnp.asarray(f64)), n)
        cl = jnp.asarray(cnt, dtype=np.uint32)

        def run_shift(subval, sextv=0):
            r, _rf, writes = S.shift_limb(
                jnp.full(k, subval, jnp.int32), jnp.full(k, sextv, jnp.int32),
                a, fill, cl, cl, cl, jnp.full(k, True), n,
                jnp.uint32(0x246))
            return _ints(r), np.asarray(writes)

        cmask = 0x3F if nbytes == 8 else 0x1F
        got_shl, w_shl = run_shift(U.SH_SHL)
        got_shr, _ = run_shift(U.SH_SHR)
        got_sar, _ = run_shift(U.SH_SAR)
        got_rol, _ = run_shift(U.SH_ROL)
        got_rcl, _ = run_shift(U.SH_RCL)
        for i in range(k):
            av = int(a64[i]) & m
            c = int(cnt[i]) & cmask
            if c == 0:
                assert not w_shl[i]
                continue
            assert got_shl[i] == (av << c) & m if c < 64 else 0
            assert got_shr[i] == (av >> c) if c < 64 else 0
            sv = av - (1 << bits) if av >> (bits - 1) else av
            assert got_sar[i] == (sv >> min(c, 63)) & m
            rc = c % bits
            want_rol = av if rc == 0 else ((av << rc) | (av >> (bits - rc))) & m
            assert got_rol[i] == want_rol, f"rol n={nbytes} a={av:#x} c={c}"
            crc = c % (bits + 1)
            wide = (1 << bits) | av          # CF=1 : bits+1-bit value
            want_rcl = av if crc == 0 else (
                ((wide << crc) | (wide >> (bits + 1 - crc))) & m)
            assert got_rcl[i] == want_rcl, f"rcl n={nbytes} a={av:#x} c={c}"

        b = L.zext(L.pair(jnp.asarray(f64)), n)
        for subval, signed in ((U.MUL_WIDE_U, False), (U.MUL_WIDE_S, True),
                               (U.MUL_2OP, True)):
            r1, r2, _rf = S.mul_limb(
                jnp.full(k, subval, jnp.int32), jnp.zeros(k, jnp.int32),
                a, b, a, b, n, jnp.uint32(0x246))
            g1, g2 = _ints(r1), _ints(r2)
            for i in range(k):
                av, bv = int(a64[i]) & m, int(f64[i]) & m
                sa = av - (1 << bits) if signed and av >> (bits - 1) else av
                sb = bv - (1 << bits) if signed and bv >> (bits - 1) else bv
                prod = sa * sb
                if subval == U.MUL_2OP:
                    assert g1[i] == prod & m, f"imul2 n={nbytes}"
                elif nbytes == 1:
                    assert g1[i] == prod & 0xFFFF, f"mul8 {av:#x}*{bv:#x}"
                else:
                    assert g1[i] == prod & m
                    assert g2[i] == (prod >> bits) & m, (
                        f"mulhi n={nbytes} {av:#x}*{bv:#x} sub={subval}")


def test_limb_alu_matches_u64_reference():
    """alu_limb against a direct u64 recompute of the same semantics —
    the contract the deleted u64 ALU block used to embody."""
    rng = np.random.default_rng(0x11B5)
    k = 256
    a64 = rng.integers(0, 1 << 64, k, dtype=np.uint64)
    b64 = rng.integers(0, 1 << 64, k, dtype=np.uint64)
    a = L.pair(jnp.asarray(a64))
    b = L.pair(jnp.asarray(b64))
    for nbytes in (1, 2, 4, 8):
        m = (1 << (nbytes * 8)) - 1
        n = jnp.full(k, nbytes, dtype=jnp.int32)
        for subname, subval, ref in [
            ("add", 0, lambda x, y: (x + y) & m),
            ("or", 1, lambda x, y: (x | y) & m),
            ("adc", 2, lambda x, y: (x + y + 1) & m),
            ("sbb", 3, lambda x, y: (x - y - 1) & m),
            ("and", 4, lambda x, y: (x & y) & m),
            ("sub", 5, lambda x, y: (x - y) & m),
            ("xor", 6, lambda x, y: (x ^ y) & m),
            ("cmp", 7, lambda x, y: (x - y) & m),
        ]:
            sub = jnp.full(k, subval, dtype=jnp.int32)
            cin = jnp.full(k, True)
            am = L.zext(a, n)
            bm = L.zext(b, n)
            r, _rf, writes = S.alu_limb(sub, am, bm, cin, n, jnp.uint32(0x2))
            got = _ints(r)
            for i in range(k):
                assert got[i] == ref(int(a64[i]) & m, int(b64[i]) & m), (
                    f"{subname} n={nbytes} a={a64[i]:#x} b={b64[i]:#x}")
            assert bool(np.asarray(writes)[0]) == (subname != "cmp")


def test_const_shifts_and_small_add():
    a = _pairs(CORNERS)
    for k in (0, 1, 7, 31, 32, 33, 63):
        assert _ints(L.shl64_const(a, k)) == [
            (x << k) & MASK64 for x in CORNERS], f"shl64_const {k}"
        assert _ints(L.shr64_const(a, k)) == [
            x >> k for x in CORNERS], f"shr64_const {k}"
    for small in (0, 1, 0xFF, 0xFFFFFFFF):
        s = jnp.full(len(CORNERS), small, dtype=jnp.uint32)
        assert _ints(L.add64_u32(a, s)) == [
            (x + small) & MASK64 for x in CORNERS], f"add64_u32 {small:#x}"


def test_gpr_write_limb_matches_u64_reference():
    """The Pallas-bound limb register-file writer against the u64 scatter
    the step currently uses — same partial-write merge semantics."""
    from wtf_tpu.cpu import uops as U

    rng = np.random.default_rng(0x6B)
    file64 = jnp.asarray(rng.integers(0, 1 << 64, 16, dtype=np.uint64))
    gl = L.unpack_u64(file64)
    val64 = jnp.uint64(0x1122334455667788)
    val_l = L.pair(val64)
    for idx in (0, 3, 15, U.REG_AH_BASE, U.REG_AH_BASE + 3):
        for nbytes in (1, 2, 4, 8):
            for cond in (False, True):
                want = S._gpr_write(file64, jnp.bool_(cond), jnp.int32(idx),
                                    val64, jnp.int32(nbytes))
                got = S._gpr_write_l(gl, jnp.bool_(cond), jnp.int32(idx),
                                     val_l, jnp.int32(nbytes))
                assert (L.pack_u64(got) == want).all(), (
                    f"idx={idx} nbytes={nbytes} cond={cond}")
    # (the no-u64 HLO pin for _gpr_write_l rides the analysis dtype
    # family — "step.gpr_write_l" in step.PORTED_LIMB_PATHS)
