"""Stress/soak paths: overlay exhaustion, big writes, mixed deep+shallow
batches — the servicing edges a long campaign hits."""

import struct

import numpy as np
import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.core.results import Crash, Ok, OverlayFull, Timedout
from wtf_tpu.harness import demo_spin, demo_tlv


def test_overlay_overflow_host_write_surfaces():
    """A host write (testcase insertion path) that exceeds the lane's
    overlay slots must surface as OVERLAY_FULL, not silently truncate;
    sibling lanes are unaffected."""
    from wtf_tpu.core.results import StatusCode

    backend = create_backend("tpu", demo_tlv.build_snapshot(),
                             n_lanes=2, limit=100_000, overlay_slots=4)
    backend.initialize()
    runner = backend.runner
    view = runner.view()
    for i in range(5):  # 5 distinct stack pages > 4 slots
        view.virt_write(0, demo_tlv.STACK_TOP - 0x1000 * (i + 2), b"\xCC" * 8)
    runner.push(view)
    statuses = runner.statuses()
    assert statuses[0] == int(StatusCode.OVERLAY_FULL)
    assert statuses[1] == int(StatusCode.RUNNING)


def test_overlay_overflow_guest_store_is_distinct_result():
    """A lane whose guest stores need more pages than its overlay holds
    parks as OverlayFull — a framework resource limit, NOT a Crash
    (VERDICT r3 item 8) — and contributes no coverage (it ran on
    truncated memory); siblings run; rerun is deterministic."""
    backend = create_backend("tpu", demo_tlv.build_snapshot(),
                             n_lanes=2, limit=100_000, overlay_slots=2)
    backend.initialize()
    demo_tlv.TARGET.init(backend)
    # lane 0: input page + stack page + scratch store (type-2) = 3 pages
    # > 2 slots; lane 1: empty input touches input + stack only = 2 pages
    cases = [b"\x02\x08AAAAAAAA", b"\x01\x00"]
    results = backend.run_batch(cases, demo_tlv.TARGET)
    assert isinstance(results[0], OverlayFull), results[0]
    assert isinstance(results[1], Ok), results[1]
    assert not backend.lane_found_new_coverage(0)
    r1 = [str(r) for r in results]
    demo_tlv.TARGET.restore()
    backend.restore()
    r2 = [str(r) for r in backend.run_batch(cases, demo_tlv.TARGET)]
    assert r1 == r2


def test_overlay_full_requeues_in_fuzz_loop(tmp_path):
    """The campaign driver gives an overlay-exhausted testcase ONE honest
    re-run and never writes it under crashes/ (VERDICT r3 item 8 done
    criterion)."""
    from wtf_tpu.fuzz.corpus import Corpus
    from wtf_tpu.fuzz.loop import FuzzLoop

    class ReplayMutator:
        """Serves a fixed queue, then benign fillers."""

        def __init__(self, queue):
            self.queue = list(queue)

        def get_new_testcase(self, corpus):
            return self.queue.pop(0) if self.queue else b"\x01\x00"

        def on_new_coverage(self, data):
            pass

    overflowing = b"\x02\x08AAAAAAAA"
    backend = create_backend("tpu", demo_tlv.build_snapshot(),
                             n_lanes=2, limit=100_000, overlay_slots=2)
    backend.initialize()
    demo_tlv.TARGET.init(backend)
    crashes = tmp_path / "crashes"
    loop = FuzzLoop(backend, demo_tlv.TARGET, ReplayMutator([overflowing]),
                    Corpus(), crashes_dir=crashes)
    loop.run_one_batch()
    assert loop.stats.overlay_fulls == 1
    assert loop._requeue == [overflowing]        # queued for the re-run
    loop.run_one_batch()                         # serves the requeue first
    assert loop.stats.overlay_fulls == 2
    assert loop._requeue == []                   # second exhaustion: dropped
    assert loop.stats.crashes == 0
    assert list(crashes.iterdir()) == []         # nothing saved as a crash


def test_mixed_depth_batch():
    """Shallow, deep, and timing-out lanes in one batch resolve to the
    right per-lane results (the adaptive chunk loop must service the
    shallow lanes' breakpoints without stalling the deep ones)."""
    backend = create_backend("tpu", demo_spin.build_snapshot(),
                             n_lanes=4, limit=40_000, chunk_steps=64)
    backend.initialize()
    demo_spin.TARGET.init(backend)
    cases = [
        struct.pack("<I", 3),        # shallow ok
        struct.pack("<I", 2000),     # deep ok (~16k instr)
        struct.pack("<I", 1 << 24),  # exceeds the 40k limit
        b"",                         # len<4 -> immediate ok
    ]
    results = backend.run_batch(cases, demo_spin.TARGET)
    assert isinstance(results[0], Ok)
    assert isinstance(results[1], Ok)
    assert isinstance(results[2], Timedout)
    assert isinstance(results[3], Ok)
    icount = np.asarray(backend.runner.machine.icount)
    assert int(icount[2]) == 40_000  # instruction-precise timeout


def test_large_testcase_insertion():
    """A near-page-sized testcase crosses pages through insertion,
    parsing, and restore."""
    backend = create_backend("emu", demo_tlv.build_snapshot(), limit=200_000)
    backend.initialize()
    demo_tlv.TARGET.init(backend)
    # many type-1 records summing every payload byte
    record = b"\x01\x08" + bytes(range(8))
    big = record * 300  # 3000 bytes
    results = backend.run_batch([big], demo_tlv.TARGET)
    assert isinstance(results[0], Ok)
