"""Tests for CpuState: regs.json loading + sanitize rules."""

import json

from wtf_tpu.core import CpuState, load_cpu_state_json, sanitize_cpu_state
from wtf_tpu.core.cpustate import GPR_NAMES


def _sample_regs(tmp_path, **overrides):
    regs = {
        "rax": "0x1122334455667788",
        "rbx": "0x2",
        "rcx": "0x3",
        "rdx": "0x4",
        "rsi": "0x5",
        "rdi": "0x6",
        "rip": "0x7ff7b0001000",
        "rsp": "0x14ff20",
        "rbp": "0x14ff80",
        "r8": "0x8",
        "r9": "0x9",
        "r10": "0xa",
        "r11": "0xb",
        "r12": "0xc",
        "r13": "0xd",
        "r14": "0xe",
        "r15": "0xf",
        "rflags": "0x246",
        "tsc": "0x1234",
        "cr0": "0x80050031",
        "cr2": "0x0",
        "cr3": "0x6d4000",
        "cr4": "0x370678",
        "cr8": "0xf",
        "dr7": "0x400",
        "efer": "0xd01",
        "mxcsr": "0x1f80",
        "mxcsr_mask": "0x0",
        "fptw": "0x0",
        "fpst": ["0xInfinity"] * 8,
        "cs": {
            "present": True,
            "selector": "0x33",
            "base": "0x0",
            "limit": "0xffffffff",
            "attr": "0xaffb",
        },
        "fs": {
            "present": True,
            "selector": "0x53",
            "base": "0x12345000",
            "limit": "0x3c00",
            "attr": "0xf3",
        },
        "gdtr": {"base": "0xfffff8007b5fb000", "limit": "0x57"},
    }
    regs.update(overrides)
    path = tmp_path / "regs.json"
    path.write_text(json.dumps(regs))
    return path


def test_load_basic_registers(tmp_path):
    state = load_cpu_state_json(_sample_regs(tmp_path))
    assert state.rax == 0x1122334455667788
    assert state.rip == 0x7FF7B0001000
    assert state.rflags == 0x246
    assert state.cr3 == 0x6D4000
    assert state.efer == 0xD01
    assert state.long_mode()
    assert state.paging_enabled()


def test_load_segments_and_gdtr(tmp_path):
    state = load_cpu_state_json(_sample_regs(tmp_path))
    assert state.cs.selector == 0x33
    assert state.cs.present
    assert state.fs.base == 0x12345000
    assert state.gdtr.base == 0xFFFFF8007B5FB000
    assert state.gdtr.limit == 0x57


def test_fptw_windbg_workaround(tmp_path):
    # fptw==0 with all-Infinity x87 slots means windbg didn't capture the FPU:
    # the loader must force an empty tag word (ref utils.cc:156-191).
    state = load_cpu_state_json(_sample_regs(tmp_path))
    assert state.fptw == 0xFFFF
    assert state.fpst == [0] * 8


def test_sanitize_rules(tmp_path):
    state = load_cpu_state_json(_sample_regs(tmp_path))
    assert sanitize_cpu_state(state)
    # rip is user-mode -> cr8 forced to 0 (ref utils.cc:200-206)
    assert state.cr8 == 0
    # debug registers cleared (ref utils.cc:208-227)
    assert state.dr7 == 0
    # mxcsr_mask defaulted (ref utils.cc:244-252)
    assert state.mxcsr_mask == 0xFFBF


def test_sanitize_rejects_bad_segment(tmp_path):
    # limit[16:20] copy lives in attr bits 8..11; a mismatch is fatal
    # (ref utils.cc:229-242).
    path = _sample_regs(
        tmp_path,
        cs={
            "present": True,
            "selector": "0x33",
            "base": "0x0",
            "limit": "0xffffffff",
            "attr": "0x02fb",  # reserved nibble 0x2 != limit[16:20]==0xf
        },
    )
    state = load_cpu_state_json(path)
    assert not sanitize_cpu_state(state)


def test_gpr_roundtrip():
    state = CpuState()
    values = list(range(16))
    state.set_gpr_list(values)
    assert state.gpr_list() == values
    assert state.rsp == 4  # GPR_NAMES order is x86 encoding order
    assert GPR_NAMES[4] == "rsp"


def test_copy_is_deep():
    state = CpuState()
    clone = state.copy()
    clone.fpst[0] = 42
    clone.cs.selector = 0x10
    assert state.fpst[0] == 0
    assert state.cs.selector == 0
