"""Kernel-mode target + crash-detection harness tests.

VERDICT round-2 item 5's done criteria: an end-to-end fuzz finds the
kernel bug, and crash names distinguish read/write/exec.
"""

import random
import struct

import pytest

from wtf_tpu.backend import create_backend
from wtf_tpu.core import nt
from wtf_tpu.core.results import Crash, Ok
from wtf_tpu.fuzz.corpus import Corpus
from wtf_tpu.fuzz.loop import FuzzLoop
from wtf_tpu.fuzz.mutator import ByteMutator
from wtf_tpu.harness import crash_detection, demo_kernel as dk


BENIGN = b"\x01" + bytes([1, 2, 3, 4])
BUGCHECK = b"\x02" + struct.pack("<IQ", 0xDEADBEEF, 0x41) + b"pad"
OOB_WRITE = b"\x03" + b"A" * 200
WILD_JUMP = b"\x04" + struct.pack("<Q", 0xDEAD0000) + b"x"


def make_backend(name, **kw):
    backend = create_backend(name, dk.build_snapshot(), limit=100_000, **kw)
    backend.initialize()
    dk.TARGET.init(backend)
    return backend


def test_kernel_crash_classes_emu():
    backend = make_backend("emu")
    results = backend.run_batch(
        [BENIGN, BUGCHECK, OOB_WRITE, WILD_JUMP, b""], dk.TARGET)
    assert isinstance(results[0], Ok)
    assert results[1].name == "crash-bugcheck-0xdeadbeef-0x41"
    assert results[2].name == f"crash-write-{dk.KBUF_PAGE + 0x1000:#x}"
    assert results[3].name == "crash-execute-0xdead0000"
    assert isinstance(results[4], Ok)


def test_kernel_backends_agree():
    """syscall/swapgs/stack-switch/sysret + all crash classes must match
    between the device interpreter and the oracle, name for name."""
    cases = [BENIGN, BUGCHECK, OOB_WRITE, WILD_JUMP, b"", b"\x03\x41",
             b"\x02short", b"\x01" + bytes(range(250))]
    emu = make_backend("emu")
    tpu = make_backend("tpu", n_lanes=8)
    r_emu = emu.run_batch(cases, dk.TARGET)
    r_tpu = tpu.run_batch(cases, dk.TARGET)
    for i, (a, b) in enumerate(zip(r_emu, r_tpu)):
        assert type(a) is type(b), f"case {i}: emu={a} tpu={b}"
        if isinstance(a, Crash):
            assert a.name == b.name, f"case {i}: emu={a} tpu={b}"
    # the kernel path must run natively on device, not via oracle fallback
    assert tpu.runner.stats["fallbacks"] == 0


def test_kernel_determinism_across_restore():
    backend = make_backend("tpu", n_lanes=4)
    r1 = backend.run_batch([OOB_WRITE, BENIGN], dk.TARGET)
    dk.TARGET.restore()
    backend.restore()
    r2 = backend.run_batch([OOB_WRITE, BENIGN], dk.TARGET)
    assert r1[0].name == r2[0].name
    assert type(r1[1]) is type(r2[1])


# seed verified to reach the cmd-3 kernel OOB write within the cap
_FUZZ_SEED = {"emu": 21, "tpu": 21}


@pytest.mark.parametrize("backend_name", ["emu", "tpu"])
def test_kernel_fuzz_finds_bug(backend_name):
    backend = make_backend(backend_name, **(
        {"n_lanes": 16} if backend_name == "tpu" else {}))
    rng = random.Random(_FUZZ_SEED[backend_name])
    corpus = Corpus(rng=rng)
    corpus.add(b"\x01\x10\x20")
    corpus.add(b"\x03\x41")
    loop = FuzzLoop(backend, dk.TARGET, ByteMutator(rng, max_len=64),
                    corpus, batch_size=16 if backend_name == "tpu" else 8)
    stats = loop.fuzz(runs=30_000, stop_on_crash=True)
    assert stats.crashes >= 1, (
        f"no kernel crash after {stats.testcases} testcases "
        f"(corpus={len(corpus)})")
    assert any(n.startswith("crash-") for n in loop.crash_names)


# ---------------------------------------------------------------------------
# user-mode exception-dispatch hook (EXCEPTION_RECORD parsing)
# ---------------------------------------------------------------------------

def _dispatch_snapshot(record: bytes):
    from wtf_tpu.snapshot.loader import Snapshot
    from wtf_tpu.snapshot.synthetic import SyntheticSnapshotBuilder

    DISPATCH = 0x1500_0000
    RECORD = 0x1600_0000
    b = SyntheticSnapshotBuilder()
    b.write(DISPATCH, b"\x90\xf4")  # nop ; hlt (hook fires pre-execution)
    b.write(RECORD, record)
    b.map(0x7FFF0000, 0x2000)
    pages, cpu = b.build(rip=DISPATCH, rsp=0x7FFF1000)
    cpu.rcx = RECORD
    return Snapshot.from_pages(pages, cpu, symbols={
        crash_detection.SYM_DISPATCH_EXCEPTION: DISPATCH,
    })


def _record(code: int, params=()) -> bytes:
    raw = bytearray(nt.ExceptionRecord.SIZE)
    struct.pack_into("<II", raw, 0, code, 0)
    struct.pack_into("<QQ", raw, 8, 0, 0x1234_5678)
    struct.pack_into("<I", raw, 0x18, len(params))
    for i, p in enumerate(params):
        struct.pack_into("<Q", raw, 0x20 + i * 8, p)
    return bytes(raw)


@pytest.mark.parametrize("record,expect", [
    (_record(nt.EXCEPTION_ACCESS_VIOLATION, (1, 0xDEADBEEF)),
     "crash-write-0xdeadbeef"),
    (_record(nt.EXCEPTION_ACCESS_VIOLATION, (0, 0xCAFE)),
     "crash-read-0xcafe"),
    (_record(nt.EXCEPTION_ACCESS_VIOLATION, (8, 0x41414141)),
     "crash-execute-0x41414141"),
    (_record(nt.EXCEPTION_STACK_BUFFER_OVERRUN),
     "crash-stack-buffer-overrun-0x12345678"),
    (_record(nt.EXCEPTION_INT_DIVIDE_BY_ZERO),
     "crash-divide-by-zero-0x12345678"),
])
def test_exception_record_refinement(record, expect):
    backend = create_backend("emu", _dispatch_snapshot(record))
    backend.initialize()
    crash_detection.setup_usermode_crash_detection(backend)
    result = backend.run()
    assert isinstance(result, Crash), result
    assert result.name == expect


def test_exception_dispatch_filters_dbg_print():
    """DbgPrint/C++ exceptions are not crashes: the hook lets the guest's
    own dispatch run (here: falls through to the hlt)."""
    record = _record(nt.DBG_PRINTEXCEPTION_C)
    backend = create_backend("emu", _dispatch_snapshot(record))
    backend.initialize()
    crash_detection.setup_usermode_crash_detection(backend)
    result = backend.run()
    # passed through the hook; the stub guest then executes nop+hlt
    assert isinstance(result, Crash)
    assert result.name.startswith("crash-int-")
